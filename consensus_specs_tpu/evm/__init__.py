"""Minimal in-process EVM: enough of the Ethereum VM to EXECUTE the
vendored deposit-contract bytecode instead of trusting it.

Role analogue: the reference runs its compiled deposit contract under
web3/eth-tester for behavioral tests
(solidity_deposit_contract/web3_tester/tests/test_deposit.py:1-194); this
interpreter is that capability without the web3 stack — a stack machine
over the solc 0.6 opcode subset, word-addressed memory, a storage dict,
LOG collection, and the SHA-256 precompile (address 0x2) the deposit
contract's incremental merkle tree leans on.  Gas is not metered (the
tests assert behavior, not gas).

Differential harness: tests/test_deposit_contract_evm.py deploys the
artifact, drives deposit() sequences, and cross-checks logs +
get_deposit_root() against the transcribed twin and merkle_minimal.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .keccak import keccak256, selector

__all__ = ["Contract", "EvmRevert", "deploy", "keccak256", "selector",
           "encode_abi", "decode_abi"]

_U256 = (1 << 256) - 1
_SIGN_BIT = 1 << 255


class EvmRevert(Exception):
    """REVERT executed; .data carries the returned reason bytes."""

    def __init__(self, data: bytes):
        super().__init__(data.hex() or "reverted")
        self.data = data


@dataclass
class Log:
    topics: List[int]
    data: bytes


@dataclass
class _Ctx:
    code: bytes
    calldata: bytes
    value: int
    storage: Dict[int, int]
    static: bool = False
    logs: List[Log] = field(default_factory=list)


def _run(ctx: _Ctx) -> bytes:
    stack: List[int] = []
    mem = bytearray()
    code = ctx.code
    returndata = b""
    pc = 0

    def push(v: int) -> None:
        stack.append(v & _U256)

    def pop() -> int:
        return stack.pop()

    def mgrow(end: int) -> None:
        if end > len(mem):
            mem.extend(b"\x00" * (((end + 31) // 32) * 32 - len(mem)))

    def mload(off: int, n: int) -> bytes:
        mgrow(off + n)
        return bytes(mem[off:off + n])

    def mstore_bytes(off: int, data: bytes) -> None:
        if not data:
            return
        mgrow(off + len(data))
        mem[off:off + len(data)] = data

    steps = 0
    while pc < len(code):
        steps += 1
        if steps > 10_000_000:
            raise RuntimeError("EVM step limit exceeded")
        op = code[pc]
        pc += 1

        if 0x60 <= op <= 0x7F:  # PUSH1..PUSH32
            n = op - 0x5F
            push(int.from_bytes(code[pc:pc + n], "big"))
            pc += n
        elif 0x80 <= op <= 0x8F:  # DUP1..DUP16
            push(stack[-(op - 0x7F)])
        elif 0x90 <= op <= 0x9F:  # SWAP1..SWAP16
            i = op - 0x8F
            stack[-1], stack[-1 - i] = stack[-1 - i], stack[-1]
        elif op == 0x00:  # STOP
            return b""
        elif op == 0x01:
            push(pop() + pop())
        elif op == 0x02:
            push(pop() * pop())
        elif op == 0x03:
            a, b = pop(), pop()
            push(a - b)
        elif op == 0x04:
            a, b = pop(), pop()
            push(0 if b == 0 else a // b)
        elif op == 0x05:  # SDIV
            a, b = pop(), pop()
            sa = a - (1 << 256) if a & _SIGN_BIT else a
            sb = b - (1 << 256) if b & _SIGN_BIT else b
            push(0 if sb == 0 else abs(sa) // abs(sb) * (1 if (sa < 0) == (sb < 0) else -1))
        elif op == 0x06:
            a, b = pop(), pop()
            push(0 if b == 0 else a % b)
        elif op == 0x08:  # ADDMOD
            a, b, n = pop(), pop(), pop()
            push(0 if n == 0 else (a + b) % n)
        elif op == 0x09:  # MULMOD
            a, b, n = pop(), pop(), pop()
            push(0 if n == 0 else (a * b) % n)
        elif op == 0x0A:  # EXP
            a, b = pop(), pop()
            push(pow(a, b, 1 << 256))
        elif op == 0x0B:  # SIGNEXTEND
            k, v = pop(), pop()
            if k < 31:
                bit = 8 * (k + 1) - 1
                if v & (1 << bit):
                    v |= _U256 ^ ((1 << (bit + 1)) - 1)
                else:
                    v &= (1 << (bit + 1)) - 1
            push(v)
        elif op == 0x10:
            a, b = pop(), pop()
            push(1 if a < b else 0)
        elif op == 0x11:
            a, b = pop(), pop()
            push(1 if a > b else 0)
        elif op == 0x12:  # SLT
            a, b = pop(), pop()
            sa = a - (1 << 256) if a & _SIGN_BIT else a
            sb = b - (1 << 256) if b & _SIGN_BIT else b
            push(1 if sa < sb else 0)
        elif op == 0x13:  # SGT
            a, b = pop(), pop()
            sa = a - (1 << 256) if a & _SIGN_BIT else a
            sb = b - (1 << 256) if b & _SIGN_BIT else b
            push(1 if sa > sb else 0)
        elif op == 0x14:
            push(1 if pop() == pop() else 0)
        elif op == 0x15:
            push(1 if pop() == 0 else 0)
        elif op == 0x16:
            push(pop() & pop())
        elif op == 0x17:
            push(pop() | pop())
        elif op == 0x18:
            push(pop() ^ pop())
        elif op == 0x19:
            push(~pop())
        elif op == 0x1A:  # BYTE
            i, x = pop(), pop()
            push((x >> (8 * (31 - i))) & 0xFF if i < 32 else 0)
        elif op == 0x1B:  # SHL
            s, v = pop(), pop()
            push(0 if s >= 256 else v << s)
        elif op == 0x1C:  # SHR
            s, v = pop(), pop()
            push(0 if s >= 256 else v >> s)
        elif op == 0x1D:  # SAR
            s, v = pop(), pop()
            sv = v - (1 << 256) if v & _SIGN_BIT else v
            push((sv >> min(s, 255)))
        elif op == 0x20:  # SHA3 (keccak256)
            off, n = pop(), pop()
            push(int.from_bytes(keccak256(mload(off, n)), "big"))
        elif op == 0x30:  # ADDRESS
            push(0xDE9052717)
        elif op == 0x33:  # CALLER
            push(0xCA11E4)
        elif op == 0x34:  # CALLVALUE
            push(ctx.value)
        elif op == 0x35:  # CALLDATALOAD
            off = pop()
            chunk = ctx.calldata[off:off + 32]
            push(int.from_bytes(chunk.ljust(32, b"\x00"), "big"))
        elif op == 0x36:
            push(len(ctx.calldata))
        elif op == 0x37:  # CALLDATACOPY
            doff, soff, n = pop(), pop(), pop()
            chunk = ctx.calldata[soff:soff + n].ljust(n, b"\x00")
            mstore_bytes(doff, chunk)
        elif op == 0x38:
            push(len(code))
        elif op == 0x39:  # CODECOPY
            doff, soff, n = pop(), pop(), pop()
            chunk = code[soff:soff + n].ljust(n, b"\x00")
            mstore_bytes(doff, chunk)
        elif op == 0x3D:
            push(len(returndata))
        elif op == 0x3E:  # RETURNDATACOPY
            doff, soff, n = pop(), pop(), pop()
            if soff + n > len(returndata):  # hard EVM fault, not an assert:
                raise EvmRevert(b"returndata out of bounds")  # -O must not strip
            mstore_bytes(doff, returndata[soff:soff + n])
        elif op == 0x47:  # SELFBALANCE
            push(0)
        elif op == 0x50:
            pop()
        elif op == 0x51:
            push(int.from_bytes(mload(pop(), 32), "big"))
        elif op == 0x52:
            off, v = pop(), pop()
            mstore_bytes(off, v.to_bytes(32, "big"))
        elif op == 0x53:
            off, v = pop(), pop()
            mstore_bytes(off, bytes([v & 0xFF]))
        elif op == 0x54:
            push(ctx.storage.get(pop(), 0))
        elif op == 0x55:
            if ctx.static:
                raise EvmRevert(b"SSTORE in static context")
            k, v = pop(), pop()
            if v == 0:
                ctx.storage.pop(k, None)
            else:
                ctx.storage[k] = v
        elif op == 0x56:  # JUMP
            dest = pop()
            if dest >= len(code) or code[dest] != 0x5B:
                raise EvmRevert(b"bad jumpdest")
            pc = dest
        elif op == 0x57:  # JUMPI
            dest, cond = pop(), pop()
            if cond:
                if dest >= len(code) or code[dest] != 0x5B:
                    raise EvmRevert(b"bad jumpdest")
                pc = dest
        elif op == 0x58:
            push(pc - 1)
        elif op == 0x59:
            push(len(mem))
        elif op == 0x5A:  # GAS (not metered)
            push(10**12)
        elif op == 0x5B:  # JUMPDEST
            pass
        elif 0xA0 <= op <= 0xA4:  # LOG0..LOG4
            if ctx.static:
                raise EvmRevert(b"LOG in static context")
            off, n = pop(), pop()
            topics = [pop() for _ in range(op - 0xA0)]
            ctx.logs.append(Log(topics, mload(off, n)))
        elif op in (0xF1, 0xFA):  # CALL / STATICCALL (precompiles only)
            if op == 0xF1:
                _gas, addr, _value, in_off, in_n, out_off, out_n = (
                    pop(), pop(), pop(), pop(), pop(), pop(), pop())
            else:
                _gas, addr, in_off, in_n, out_off, out_n = (
                    pop(), pop(), pop(), pop(), pop(), pop())
            data = mload(in_off, in_n)
            if addr == 2:  # SHA-256 precompile
                returndata = hashlib.sha256(data).digest()
            elif addr == 4:  # identity
                returndata = data
            else:
                raise NotImplementedError(f"CALL to address {addr:#x}")
            mstore_bytes(out_off, returndata[:out_n])
            push(1)
        elif op == 0xF3:  # RETURN
            off, n = pop(), pop()
            return mload(off, n)
        elif op == 0xFD:  # REVERT
            off, n = pop(), pop()
            raise EvmRevert(mload(off, n))
        elif op == 0xFE:  # INVALID
            raise EvmRevert(b"invalid opcode")
        else:
            raise NotImplementedError(f"opcode {op:#04x} at {pc - 1}")
    return b""


# --------------------------------------------------------------------------
# ABI (the subset the deposit contract's interface needs)
# --------------------------------------------------------------------------

def encode_abi(types: List[str], args: List) -> bytes:
    """Head/tail ABI encoding for static words, bytes32 and dynamic bytes."""
    heads: List[Optional[bytes]] = []
    tails: List[bytes] = []
    for typ, arg in zip(types, args):
        if typ == "bytes":
            heads.append(None)  # placeholder: offset
            raw = bytes(arg)
            tails.append(len(raw).to_bytes(32, "big")
                         + raw.ljust(((len(raw) + 31) // 32) * 32, b"\x00"))
        elif typ == "bytes32":
            heads.append(bytes(arg).ljust(32, b"\x00"))
            tails.append(b"")
        elif typ in ("uint256", "uint64", "bool"):
            heads.append(int(arg).to_bytes(32, "big"))
            tails.append(b"")
        elif typ == "bytes4":
            heads.append(bytes(arg).ljust(32, b"\x00"))
            tails.append(b"")
        else:
            raise NotImplementedError(typ)
    head_size = 32 * len(heads)
    out = b""
    tail_off = head_size
    tail_blob = b""
    for head, tail in zip(heads, tails):
        if head is None:
            out += tail_off.to_bytes(32, "big")
            tail_blob += tail
            tail_off += len(tail)
        else:
            out += head
    return out + tail_blob


def decode_abi(types: List[str], data: bytes) -> List:
    out = []
    for i, typ in enumerate(types):
        word = data[32 * i:32 * i + 32]
        if typ == "bytes32":
            out.append(word)
        elif typ in ("uint256", "uint64"):
            out.append(int.from_bytes(word, "big"))
        elif typ == "bool":
            out.append(bool(int.from_bytes(word, "big")))
        elif typ == "bytes":
            off = int.from_bytes(word, "big")
            n = int.from_bytes(data[off:off + 32], "big")
            out.append(data[off + 32:off + 32 + n])
        else:
            raise NotImplementedError(typ)
    return out


# --------------------------------------------------------------------------
# contract object
# --------------------------------------------------------------------------

class Contract:
    """A deployed contract: runtime code + persistent storage + log sink."""

    def __init__(self, runtime: bytes, storage: Dict[int, int]):
        self.runtime = runtime
        self.storage = storage
        self.logs: List[Log] = []

    def call(self, signature: str, types: List[str], args: List,
             value: int = 0, static: bool = False) -> bytes:
        calldata = selector(signature) + encode_abi(types, args)
        # run against a storage snapshot: EVM revert semantics discard ALL
        # state effects of the failed call (logs are discarded implicitly —
        # ctx.logs only merges on success)
        working = dict(self.storage)
        ctx = _Ctx(code=self.runtime, calldata=calldata, value=value,
                   storage=working, static=static)
        ret = _run(ctx)
        self.storage = working
        self.logs.extend(ctx.logs)
        return ret


def deploy(deployment_bytecode: bytes) -> Contract:
    """Run the constructor; its RETURN is the runtime code, its SSTOREs
    persist into the contract's storage."""
    storage: Dict[int, int] = {}
    ctx = _Ctx(code=deployment_bytecode, calldata=b"", value=0,
               storage=storage)
    runtime = _run(ctx)
    assert runtime, "constructor returned no runtime code"
    return Contract(runtime, storage)
