"""Soak-endurance harness (ISSUE 9 tentpole, layer 3; ROADMAP item 5).

The chaos suite proves containment per fault; the benches prove speed per
run.  Neither watches the system *over time* — a breaker that recovers in
a 10-block test can still wedge open across epochs, a bounded cache can
still creep, and a regression between headline benches is invisible.
The soak run closes that gap: a long seeded random block/attestation
walk, epochs alternately faulted (seeded ``FaultPlan`` schedules over the
stf seams, error + corrupt kinds — crashes are chaos-suite territory:
native degradation is one-way by design and would fail the recovery
claim vacuously) and clean, with four endurance assertions:

* **breaker recovery** — the first faulted epoch deterministically trips
  the breaker (three consecutive injected errors); by the end of the
  walk's trailing clean epochs the breaker must be CLOSED again, through
  its own probe machinery (never re-armed by the harness);
* **root parity throughout** — every block's post-state root matches the
  literal spec replay, faults or no faults;
* **cache coherence** — a fault-free re-run of the whole walk over the
  SAME process-global caches takes the fast path on every block
  (``replayed_blocks == 0``): no fault in any epoch stranded a poisoned
  entry;
* **memory flatness** — after every epoch, each bounded cache/ring
  (attestation plans, geometry memos, verified triples, resident
  columns, sync seats, the flight-recorder ring, the causal-timeline
  ring) is sampled off the telemetry bus and must sit at or under its
  registered cap; AND the process RSS itself is sampled per epoch
  (ISSUE 11, the ROADMAP item-3 follow-up) — cap checks prove each
  *known* structure is bounded, the RSS series proves nothing UNKNOWN
  is growing either.  After a warmup quarter the walk's RSS must stay
  within a bounded-growth budget of its warmup level.

The run emits ``SOAK.json``: profile, per-epoch cache samples, the
engine/verify counters, the full telemetry snapshot, and the flight
recorder's last-N timeline — the artifact IS the post-mortem when an
assertion trips (written before the failure is raised).

Profiles: ``bounded`` (~2 min on the 1 vCPU host: phase0 + altair, 32
epochs each — long enough for finality to advance, FIFO memos to rotate,
and the plan cache to shed old epochs) is the ``make soak`` default;
``deep`` (96 epochs each) is the slow endurance tier (``make
soak-deep``).  Orthogonal to both, ``run_endurance`` loops the bounded
corpus under a WALL-CLOCK budget (``CSTPU_SOAK_MINUTES``, ``make
soak-endurance``) and asserts the same flatness envelope over the whole
multi-pass RSS series — the multi-hour flat-RSS lever.  An ambient
``CSTPU_FAULTS`` schedule stays armed during the walk's clean epochs
(extra chaos, same assertions) but is masked during the verification
re-run, which must be genuinely fault-free to prove coherence.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

PROFILES = {
    # ring_cap sizes the flight recorder to hold the WHOLE walk (still a
    # bound — flatness is asserted against it like every other cap); the
    # default 512-event ring is tuned for serving, not endurance reports
    "bounded": {"forks": ("phase0", "altair"), "epochs": 32,
                "ring_cap": 4096},
    "deep": {"forks": ("phase0", "altair"), "epochs": 96,
             "ring_cap": 16384},
}

# the seams soak schedules draw from: every stf site the chaos suite
# already proves containment for, minus nothing — kinds are restricted
# instead (error/corrupt only, see module docstring)
_SOAK_KINDS = ("error", "corrupt")


class SoakFailure(AssertionError):
    """An endurance assertion failed; SOAK.json carries the post-mortem."""


# RSS flatness budget: after the warmup quarter (caches filling, native
# pools spinning up), growth to the END of the walk must stay under
# max(_RSS_BUDGET_MB, _RSS_BUDGET_FRAC * warmup level) — loose enough
# that allocator noise and page-cache jitter never flake the gate,
# tight enough that a leaked per-epoch structure (the failure mode cap
# checks cannot see) trips it within one soak
_RSS_BUDGET_MB = 128.0
_RSS_BUDGET_FRAC = 0.25


def process_rss_mb() -> Optional[float]:
    """Current resident-set size of this process in MB — /proc-based on
    Linux (current residency, the flatness signal), falling back to
    ru_maxrss (peak; still monotone-growth-detecting) elsewhere; None
    when neither source works (the flatness assert then skips rather
    than flaking)."""
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        return rss_pages * os.sysconf("SC_PAGE_SIZE") / (1024.0 * 1024.0)
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    except Exception:
        return None


def rss_flatness(samples) -> Optional[dict]:
    """The bounded-growth verdict over a per-epoch RSS series: compares
    the end of the walk against the post-warmup level (first quarter,
    minimum one epoch) and returns {baseline_mb, final_mb, growth_mb,
    budget_mb, flat}; None when the series is too short or unsampled."""
    series = [s for s in samples if s is not None]
    if len(series) < 2:
        return None
    warmup = max(1, len(series) // 4)
    baseline = min(series[warmup - 1:warmup + 1])
    final = series[-1]
    budget = max(_RSS_BUDGET_MB, _RSS_BUDGET_FRAC * baseline)
    growth = final - baseline
    return {"baseline_mb": round(baseline, 1), "final_mb": round(final, 1),
            "growth_mb": round(growth, 1), "budget_mb": round(budget, 1),
            "warmup_epochs": warmup, "flat": growth <= budget}


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _stf_sites() -> List[str]:
    from consensus_specs_tpu import faults, stf  # noqa: F401  (registers sites)

    return sorted(n for n in faults.registry() if n.startswith("stf."))


def _build_corpus(fork: str, epochs: int):
    """(spec, pre_state, signed_blocks, per-block literal roots) for an
    ``epochs``-long full-block walk (the chaos corpus pattern, longer)."""
    from consensus_specs_tpu.query import coldstart
    from consensus_specs_tpu.testing.context import spec_state_test, with_phases
    from consensus_specs_tpu.testing.helpers.attestations import (
        next_slots_with_attestations,
    )
    from consensus_specs_tpu.testing.helpers.state import next_epoch

    out = {}

    @with_phases([fork])
    @spec_state_test
    def build(spec, state):
        next_epoch(spec, state)
        # ISSUE 16: the soak's pre-state rides the universal cold-start
        # seam — restored byte-identical from the snapshot artifact when
        # one matches (CSTPU_NO_CHECKPOINT_SYNC=1 forces the literal
        # build), built and snapshotted otherwise
        pre = coldstart.restore_or_build(
            spec, len(state.validators), state.copy, label="soak")
        _, signed, _ = next_slots_with_attestations(
            spec, pre.copy(), epochs * int(spec.SLOTS_PER_EPOCH),
            True, True)
        s = pre.copy()
        roots = []
        for sb in signed:
            spec.state_transition(s, sb, True)
            roots.append(bytes(s.hash_tree_root()))
        out["corpus"] = (spec, pre, signed, roots)
        yield None

    build(phase=fork)  # DEFAULT_BLS_ACTIVE: signatures are real
    return out["corpus"]


def _pipeline_inflight_cap() -> int:
    from consensus_specs_tpu.stf import pipeline

    return pipeline.window_depth() + 1


def bounded_cache_sizes() -> List[dict]:
    """(name, size, cap) of every bounded structure the telemetry bus
    reports — the memory-flatness sample."""
    import consensus_specs_tpu.node.admission  # noqa: F401  (registers provider)
    import consensus_specs_tpu.query  # noqa: F401  (registers provider)

    from . import snapshot

    providers = snapshot()["providers"]
    plan = providers.get("stf.plan_cache", {})
    verify = providers.get("stf.verify", {})
    columns = providers.get("stf.columns", {})
    sync = providers.get("stf.sync", {})
    ring = providers.get("flight_recorder", {})
    samples = [
        {"name": "stf.plan_cache.plan", "size": plan.get("plan_size", 0),
         "cap": plan.get("plan_cap", 0)},
        {"name": "stf.verify.memo", "size": verify.get("memo_size", 0),
         "cap": verify.get("memo_cap", 0)},
        {"name": "stf.columns.store", "size": columns.get("size", 0),
         "cap": columns.get("cap", 0)},
        # ISSUE 10 residency stores + the pipeline's in-flight queue:
        # bounded like everything else, flatness-asserted per epoch
        {"name": "stf.columns.balances",
         "size": columns.get("balance_size", 0),
         "cap": columns.get("balance_cap", 0)},
        {"name": "stf.columns.device_buffers",
         "size": columns.get("device_size", 0),
         "cap": columns.get("device_cap", 0)},
        {"name": "stf.pipeline.inflight",
         "size": providers.get("stf.pipeline", {}).get("depth", 0),
         # the engine's bound: window_depth + the current block's dispatch
         "cap": _pipeline_inflight_cap()},
        {"name": "stf.sync.rows_memo",
         "size": sync.get("rows_memo_size", 0), "cap": sync.get("cap", 0)},
        {"name": "flight_recorder.ring", "size": ring.get("events", 0),
         "cap": ring.get("cap", 0)},
        # the causal-timeline ring (ISSUE 11): bounded like the flight
        # recorder's, flatness-asserted the same way
        {"name": "timeline.ring",
         "size": providers.get("timeline", {}).get("events", 0),
         "cap": providers.get("timeline", {}).get("cap", 0)},
    ]
    # the node admission survival structures (ISSUE 13): orphan pool,
    # parked ring, dead-letter ring, seen-set, and the peer-score table
    # all carry caps on the bus — a soak (or the adversarial firehose)
    # proves they stay bounded over every epoch
    adm = providers.get("node.admission", {})
    for name, size_key, cap_key in (
            ("node.admission.orphans", "orphan_pool_depth",
             "orphan_pool_cap"),
            ("node.admission.parked", "parked_depth", "parked_cap"),
            ("node.admission.dead_letters", "dead_letter_depth",
             "dead_letter_cap"),
            ("node.admission.seen", "seen_size", "seen_cap"),
            ("node.admission.scores", "scores_size", "scores_cap"),
            ("node.admission.aggregation", "agg_depth", "agg_cap")):
        samples.append({"name": name, "size": adm.get(size_key, 0),
                        "cap": adm.get(cap_key, 0)})
    for key in ("ctx_size", "ctx_lookup_size", "plan_ctx_lookup_size",
                "active_size", "proposer_size"):
        samples.append({"name": f"stf.plan_cache.{key[:-5]}",
                        "size": plan.get(key, 0),
                        "cap": plan.get("geometry_cap", 0)})
    # the durable checkpoint store (ISSUE 14): checkpoints on disk are a
    # bounded ring like everything else — prune-on-finalization must
    # hold the depth at its cap over any number of epochs
    persist = providers.get("persist", {})
    samples.append({"name": "persist.checkpoints",
                    "size": persist.get("size", 0),
                    "cap": persist.get("cap", 0)})
    # the historical read path (ISSUE 16): the live query engine's
    # artifact index, proof cache, and resident-state set are bounded
    # LRUs on the bus — flatness-asserted like every other cache (when
    # no engine is live the gauges are absent and cap=0 skips the check)
    q = providers.get("query", {})
    for name, size_key, cap_key in (
            ("query.artifact_index", "artifact_index_size",
             "artifact_index_cap"),
            ("query.proof_cache", "proof_cache_size", "proof_cache_cap"),
            ("query.resident", "resident_size", "resident_cap")):
        samples.append({"name": name, "size": q.get(size_key, 0),
                        "cap": q.get(cap_key, 0)})
    return samples


def _epoch_plan(epoch_index: int, seed: int, sites: List[str],
                breaker_trip: bool):
    """The fault schedule of one faulted epoch: a deterministic breaker
    trip (three consecutive early errors) on the first, seeded random
    error/corrupt draws on the rest."""
    from consensus_specs_tpu import faults

    if breaker_trip:
        trip = [faults.Fault("stf.engine.operations", nth=n)
                for n in (1, 2, 3)]
        extra = faults.FaultPlan.seeded(
            seed + epoch_index, sites, n_faults=2, max_nth=6,
            kinds=_SOAK_KINDS).faults()
        return faults.FaultPlan(trip + extra)
    return faults.FaultPlan.seeded(
        seed + epoch_index, sites, n_faults=3, max_nth=8,
        kinds=_SOAK_KINDS)


def _fresh_engine_env() -> None:
    from consensus_specs_tpu import stf
    from consensus_specs_tpu.stf import attestations as stf_attestations
    from consensus_specs_tpu.stf import verify as stf_verify

    stf.reset_stats()
    stf_verify.reset_memo()
    stf_verify.reset_degraded()
    stf_attestations.reset_caches()


def _soak_fork(fork: str, epochs: int, seed: int, report: dict) -> dict:
    """One fork's endurance walk; returns the fork's report section and
    raises ``SoakFailure`` (after dumping) on any broken assertion."""
    from consensus_specs_tpu import faults, stf

    spec, pre, blocks, roots = _build_corpus(fork, epochs)
    sites = _stf_sites()
    spe = int(spec.SLOTS_PER_EPOCH)
    epoch_chunks = [blocks[i:i + spe] for i in range(0, len(blocks), spe)]
    # faulted prefix, clean tail: the LAST TWO epochs always run clean so
    # the breaker has >= 2*SLOTS_PER_EPOCH blocks to probe its way closed
    n_faulted = max(1, len(epoch_chunks) - 2)

    _fresh_engine_env()
    section: dict = {"fork": fork, "blocks": len(blocks),
                     "epochs": len(epoch_chunks), "faulted_epochs": n_faulted,
                     "fired": [], "cache_samples": []}
    s = pre.copy()
    applied = 0
    for e, chunk in enumerate(epoch_chunks):
        plan = (_epoch_plan(e, seed, sites, breaker_trip=(e == 0))
                if e < n_faulted else None)
        ctx = faults.inject(plan) if plan is not None else _ambient()
        with ctx:
            for sb in chunk:
                stf.apply_signed_blocks(spec, s, [sb], True)
                if bytes(s.hash_tree_root()) != roots[applied]:
                    _fail(report, section,
                          f"{fork}: root diverged from the literal replay "
                          f"at block {applied} (epoch {e})")
                applied += 1
        if plan is not None:
            section["fired"].extend(
                [site, hit, kind] for site, hit, kind in plan.fired)
        sample = {"epoch": e, "sizes": bounded_cache_sizes(),
                  "rss_mb": process_rss_mb(),
                  "breaker_state": stf.stats["breaker_state"]}
        section["cache_samples"].append(sample)
        for entry in sample["sizes"]:
            if entry["cap"] and entry["size"] > entry["cap"]:
                _fail(report, section,
                      f"{fork}: {entry['name']} grew past its cap after "
                      f"epoch {e}: {entry['size']} > {entry['cap']}")

    # RSS flatness (ISSUE 11): the per-epoch series must show bounded
    # growth past warmup — cap checks bound every KNOWN structure, this
    # catches a leak in anything the bus doesn't know about
    section["rss_flatness"] = rss_flatness(
        [s["rss_mb"] for s in section["cache_samples"]])
    if section["rss_flatness"] is not None \
            and not section["rss_flatness"]["flat"]:
        rf = section["rss_flatness"]
        _fail(report, section,
              f"{fork}: process RSS grew {rf['growth_mb']} MB past the "
              f"post-warmup level ({rf['baseline_mb']} MB), over the "
              f"{rf['budget_mb']} MB flatness budget")

    section["walk_stats"] = {
        **{k: stf.stats[k] for k in
           ("fast_blocks", "replayed_blocks", "breaker_trips",
            "breaker_probes", "breaker_skipped", "breaker_state")},
        "replay_reasons": dict(stf.stats["replay_reasons"]),
    }
    if stf.stats["breaker_state"] != "closed":
        _fail(report, section,
              f"{fork}: breaker still open after the clean tail "
              f"({stf.stats['breaker_trips']} trips, "
              f"{stf.stats['breaker_probes']} probes)")
    if n_faulted and not section["fired"]:
        _fail(report, section,
              f"{fork}: no scheduled fault ever fired — the walk "
              "exercised nothing")

    # cache coherence: fault-free re-run over the SAME caches (ambient
    # CSTPU_FAULTS masked by an empty plan) must be all-fast.  The
    # degraded mark is cleared the way an operator would after ambient
    # crash chaos — the claim under test is cache state, not the ladder
    from consensus_specs_tpu.stf import verify as stf_verify

    stf.reset_stats()
    stf_verify.reset_degraded()
    s2 = pre.copy()
    with faults.inject(faults.FaultPlan([])):
        for i, sb in enumerate(blocks):
            stf.apply_signed_blocks(spec, s2, [sb], True)
            if bytes(s2.hash_tree_root()) != roots[i]:
                _fail(report, section,
                      f"{fork}: re-run root diverged at block {i}")
    section["rerun_stats"] = {
        "fast_blocks": stf.stats["fast_blocks"],
        "replayed_blocks": stf.stats["replayed_blocks"],
        "replay_reasons": dict(stf.stats["replay_reasons"]),
    }
    if stf.stats["replayed_blocks"] != 0:
        _fail(report, section,
              f"{fork}: fault-free re-run replayed "
              f"{stf.stats['replayed_blocks']} blocks — a fault stranded "
              f"poisoned cache state: {stf.stats['replay_reasons']}")
    return section


def _ambient():
    """No-op context: the walk's clean epochs run under whatever ambient
    plan (CSTPU_FAULTS) is armed — soak under operator chaos is a
    supported mode."""
    import contextlib

    return contextlib.nullcontext()


def _fail(report: dict, section: dict, message: str) -> None:
    """Dump the post-mortem (SOAK.json + flight-recorder timeline), then
    raise — a failed soak carries its own flight data."""
    from . import recorder

    report["failure"] = message
    _finalize(report, section)
    _write(report)
    recorder.disable()
    raise SoakFailure(f"{message} (post-mortem: {report['out_path']})")


def _finalize(report: dict, *sections: dict) -> None:
    from . import recorder, snapshot

    for section in sections:
        if section is not None and section not in report["forks"]:
            report["forks"].append(section)
    report["snapshot"] = snapshot()
    report["timeline"] = recorder.timeline()


def _write(report: dict) -> None:
    path = report["out_path"]
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, default=str)
    # durable-io: SOAK.json is a human-readable run report, rewritten
    # per soak — not an integrity-checked artifact (no digest by design)
    os.replace(tmp, path)


def run_soak(profile: str = "bounded", seed: int = 90001,
             out_path: Optional[str] = None) -> Dict:
    """Run the soak-endurance walk and write the ``SOAK.json`` artifact.
    Returns the report dict; raises ``SoakFailure`` on any broken
    endurance assertion (the artifact is written first, either way)."""
    from consensus_specs_tpu.crypto import bls

    from . import recorder

    if profile not in PROFILES:
        raise ValueError(f"unknown soak profile {profile!r} "
                         f"(one of {sorted(PROFILES)})")
    cfg = PROFILES[profile]
    out_path = out_path or os.environ.get(
        "CSTPU_SOAK_OUT", os.path.join(_repo_root(), "SOAK.json"))
    report: Dict = {"profile": profile, "seed": seed, "config": dict(cfg),
                    "out_path": out_path, "forks": [], "failure": None}

    bls.use_fastest()
    prev_bls = bls.bls_active
    bls.bls_active = True
    was_recording = recorder.enabled()
    prev_cap = recorder.stats()["cap"]
    recorder.enable(cap=cfg["ring_cap"])
    recorder.reset()
    try:
        for fork in cfg["forks"]:
            report["forks"].append(
                _soak_fork(fork, cfg["epochs"], seed, report))
        _finalize(report)
        _write(report)
    finally:
        bls.bls_active = prev_bls
        # restore the PRE-RUN bound (an operator-configured ambient
        # recorder must not come back shrunk to the default)
        recorder.enable(cap=prev_cap)
        if not was_recording:
            recorder.disable()
    return report


def run_endurance(minutes: Optional[float] = None,
                  out_path: Optional[str] = None) -> Dict:
    """Wall-clock-budgeted endurance mode (``CSTPU_SOAK_MINUTES``,
    ``make soak-endurance``): build the bounded corpus once, then loop
    fault-free full passes over it until the budget expires, sampling
    every bounded cap and the process RSS after each epoch and asserting
    the SAME flatness envelope over the whole multi-pass series — the
    opt-in lever for ROADMAP item 3's remaining multi-hour flat-RSS
    claim.  At least one full pass always completes, however small the
    budget; a started pass always finishes (root parity is per block, so
    the series stays pass-aligned).  Clean passes run under whatever
    ambient ``CSTPU_FAULTS`` plan is armed, like the walk's clean
    epochs — containment keeps parity either way."""
    import time as _time

    from consensus_specs_tpu import stf
    from consensus_specs_tpu.crypto import bls

    from . import recorder

    if minutes is None:
        minutes = float(os.environ.get("CSTPU_SOAK_MINUTES", "0") or 0.0)
    if minutes <= 0:
        raise ValueError(
            "endurance soak needs a positive wall-clock budget "
            "(CSTPU_SOAK_MINUTES=<minutes> or minutes=...)")
    cfg = PROFILES["bounded"]
    out_path = out_path or os.environ.get(
        "CSTPU_SOAK_OUT", os.path.join(_repo_root(), "SOAK.json"))
    report: Dict = {"profile": "endurance",
                    "config": {**cfg, "minutes": minutes},
                    "out_path": out_path, "forks": [], "failure": None}

    bls.use_fastest()
    prev_bls = bls.bls_active
    bls.bls_active = True
    was_recording = recorder.enabled()
    prev_cap = recorder.stats()["cap"]
    recorder.enable(cap=cfg["ring_cap"])
    recorder.reset()
    section: Dict = {"mode": "endurance", "budget_minutes": minutes,
                     "passes": 0, "blocks_applied": 0, "cache_samples": []}
    try:
        corpora = {fork: _build_corpus(fork, cfg["epochs"])
                   for fork in cfg["forks"]}
        _fresh_engine_env()
        start = _time.monotonic()
        deadline = start + minutes * 60.0
        while section["passes"] == 0 or _time.monotonic() < deadline:
            for fork in cfg["forks"]:
                spec, pre, blocks, roots = corpora[fork]
                spe = int(spec.SLOTS_PER_EPOCH)
                s = pre.copy()
                applied = 0
                with _ambient():
                    for off in range(0, len(blocks), spe):
                        for sb in blocks[off:off + spe]:
                            stf.apply_signed_blocks(spec, s, [sb], True)
                            if bytes(s.hash_tree_root()) != roots[applied]:
                                _fail(report, section,
                                      f"{fork}: root diverged from the "
                                      f"literal replay at block {applied} "
                                      f"(pass {section['passes']})")
                            applied += 1
                            section["blocks_applied"] += 1
                        sample = {"pass": section["passes"], "fork": fork,
                                  "epoch": off // spe,
                                  "sizes": bounded_cache_sizes(),
                                  "rss_mb": process_rss_mb(),
                                  "breaker_state": stf.stats["breaker_state"]}
                        section["cache_samples"].append(sample)
                        for entry in sample["sizes"]:
                            if entry["cap"] and entry["size"] > entry["cap"]:
                                _fail(report, section,
                                      f"{fork}: {entry['name']} grew past "
                                      f"its cap in pass "
                                      f"{section['passes']}: "
                                      f"{entry['size']} > {entry['cap']}")
            section["passes"] += 1
        section["elapsed_s"] = round(_time.monotonic() - start, 1)
        section["walk_stats"] = {
            **{k: stf.stats[k] for k in
               ("fast_blocks", "replayed_blocks", "breaker_trips",
                "breaker_probes", "breaker_skipped", "breaker_state")},
            "replay_reasons": dict(stf.stats["replay_reasons"]),
        }
        # the endurance claim: over however many passes the budget
        # bought, RSS past warmup stays inside the same bounded-growth
        # envelope the per-walk soak asserts — a per-pass leak (the
        # failure mode a single bounded walk cannot see) compounds
        # across passes and trips this within a handful of them
        section["rss_flatness"] = rss_flatness(
            [smp["rss_mb"] for smp in section["cache_samples"]])
        if section["rss_flatness"] is not None \
                and not section["rss_flatness"]["flat"]:
            rf = section["rss_flatness"]
            _fail(report, section,
                  f"endurance: process RSS grew {rf['growth_mb']} MB past "
                  f"the post-warmup level ({rf['baseline_mb']} MB) across "
                  f"{section['passes']} passes, over the {rf['budget_mb']} "
                  f"MB flatness budget")
        _finalize(report, section)
        _write(report)
    finally:
        bls.bls_active = prev_bls
        recorder.enable(cap=prev_cap)
        if not was_recording:
            recorder.disable()
    return report


if __name__ == "__main__":  # pragma: no cover - operator entry point
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "endurance":
        run_endurance()
        print("endurance soak green: SOAK.json written")
    else:
        run_soak(profile=sys.argv[1] if len(sys.argv) > 1 else "bounded")
        print("soak green: SOAK.json written")
