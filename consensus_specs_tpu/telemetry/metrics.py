"""Thread-safe spans + counters: the measurement core of the telemetry
subsystem (ISSUE 9 tentpole, layer 1's substrate).

This is the engine room behind the legacy ``tracing`` facade — same API,
same report shape, two hardening changes the facade alone could not make:

* **thread safety** — the native pairing pool and the ``parallel/`` code
  paths can increment counters concurrently; the old bare ``defaultdict``
  increment raced (two threads could both read-modify-write the same
  slot and lose one increment).  All span/counter mutation now happens
  under one module lock, and the span nesting stack is *thread-local* so
  two threads timing unrelated work can never interleave their key paths;
* **re-entrant spec instrumentation** — ``instrument_spec`` marks its
  wrappers with a self-referencing attribute and checks IDENTITY, not a
  boolean flag.  A spec rebuild that rebinds ``process_*`` globals (the
  builder's kernel substitution, bench's ``__wrapped__`` unwrap idiom)
  silently dropped instrumentation before, and a stale copied flag
  (``functools.wraps`` copies ``__dict__``) made re-instrumentation skip
  the very functions that needed re-wrapping.  Now a function is only
  "already instrumented" if it IS a wrapper this module created, so
  calling ``instrument_spec`` again after any rebuild re-wraps exactly
  the fresh functions.

Disabled (the default), ``span``/``count`` cost one module-global load
and a truth check — nothing to measure in a phase breakdown.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict

from . import timeline

_enabled = False
_LOCK = threading.Lock()
_spans: Dict[str, list] = {}  # name -> [count, total_s]
_counters: Dict[str, int] = {}
_tls = threading.local()  # per-thread span nesting stack


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    with _LOCK:
        _spans.clear()
        _counters.clear()
    _tls.stack = []


def enabled() -> bool:
    return _enabled


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


@contextlib.contextmanager
def span(name: str):
    """Nested wall-time span; keys are '/'-joined paths.  Nesting is
    per-thread: concurrent spans from different threads each build their
    own path, and the aggregate mutation is lock-guarded.

    Every span site doubles as a causal-timeline emitter (ISSUE 11):
    with ``CSTPU_TIMELINE`` armed, the same begin/end lands as paired
    timeline events — existing ``tracing.span`` callsites feed the
    Chrome-trace export without touching a line of producer code.  The
    nesting stack builds the same '/'-joined key either way, so a span's
    exported name is identical whether the metrics layer is on or the
    timeline alone is.  Both layers disabled, the cost stays two
    module-global loads and a truth check."""
    tl = timeline.enabled()
    if not _enabled and not tl:
        yield
        return
    stack = _stack()
    stack.append(name)
    key = "/".join(stack)
    sid = timeline.begin(key) if tl else 0
    t0 = time.perf_counter() if _enabled else 0.0
    try:
        yield
    finally:
        if _enabled:
            dt = time.perf_counter() - t0
            with _LOCK:
                rec = _spans.get(key)
                if rec is None:
                    rec = _spans[key] = [0, 0.0]
                rec[0] += 1
                rec[1] += dt
        timeline.end(sid)
        stack.pop()


def count(name: str, n: int = 1) -> None:
    if _enabled:
        with _LOCK:
            _counters[name] = _counters.get(name, 0) + n


def report() -> dict:
    """{'spans': {path: {'count', 'total_s'}}, 'counters': {...}}"""
    with _LOCK:
        return {
            "spans": {
                k: {"count": v[0], "total_s": round(v[1], 6)}
                for k, v in sorted(_spans.items())
            },
            "counters": dict(sorted(_counters.items())),
        }


@contextlib.contextmanager
def xla_trace(log_dir: str):
    """Device-level XLA profiler trace (TensorBoard/XProf format)."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


# --- spec instrumentation ----------------------------------------------------

_INSTRUMENT_PREFIXES = ("process_", "state_transition", "verify_block_signature")


def _wrap(name: str, fn):
    def traced(*args, **kw):
        if not _enabled:
            return fn(*args, **kw)
        with span(name):
            return fn(*args, **kw)

    traced.__name__ = getattr(fn, "__name__", name)
    traced.__wrapped__ = fn
    return traced


def _is_own_wrapper(fn) -> bool:
    """True only for a wrapper THIS module created: the marker is a
    self-reference, so an attribute merely copied onto another function
    (``functools.wraps`` copies ``__dict__``) fails the identity check
    and the function gets (re-)wrapped honestly."""
    return getattr(fn, "_tracing_self", None) is fn


def instrument_spec(spec, prefixes=_INSTRUMENT_PREFIXES) -> int:
    """Wrap a compiled spec module's transition functions with spans.

    Idempotent AND re-entrant: returns the number of functions newly
    instrumented this call.  After a spec rebuild rebinds some globals
    (kernel substitution, ``__wrapped__`` unwrapping), calling this again
    re-wraps exactly the functions that lost their wrapper."""
    g = spec.__dict__
    n = 0
    for name, fn in list(g.items()):
        if not callable(fn) or not name.startswith(tuple(prefixes)):
            continue
        if _is_own_wrapper(fn):
            continue
        wrapped = _wrap(name, fn)
        wrapped._tracing_self = wrapped
        g[name] = wrapped
        n += 1
    return n
