"""Per-block flight recorder: a bounded ring buffer of structured events
(ISSUE 9 tentpole, layer 2).

The engines already *count* everything (breaker trips, replay reasons,
cache hits), but counters answer "how many", never "in what order" — and
a post-mortem is an ordering question: did the breaker open before or
after the native backend degraded?  Which block's rollback preceded the
cache-coherence miss?  The recorder keeps the last-N structured events:

    {"seq": 17, "t": 3.1415, "kind": "breaker_open", ...fields}

* ``record(kind, **fields)`` appends; DISABLED (the default) it costs one
  module-global load and a truth check — the hot path stays unmeasurable.
  Enabled, the append is lock-guarded (the native pool and ``parallel/``
  paths can record concurrently) and the ring is bounded: the oldest
  event falls off and ``dropped`` counts it, so a month-long soak holds
  exactly ``cap`` events;
* ``timeline()`` returns copies (callers can never mutate ring state);
* ``dump(reason)`` materializes the post-mortem: the reason, the
  timeline, and (optionally) a full ``telemetry.snapshot()``, written as
  JSON when given a path — failures carry their own flight data.

Producers emit through the module-level ``record``; the ring itself
(``_EVENTS``) is analyzer-registered (CC01 "flight-recorder ring") and
OB01 enforces that commit-class events are never recorded inside a still
open block transaction (a rolled-back block must not log a commit that
never happened).

Activation: ``enable()``/``disable()``, or ``CSTPU_FLIGHT_RECORDER=1``
at import; ``CSTPU_FLIGHT_RECORDER_CAP`` overrides the default 512-event
bound.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Deque, Optional

DEFAULT_CAP = 512

_LOCK = threading.Lock()
_ENABLED = False


def _env_cap() -> int:
    """The env-configured ring bound, validated like ``enable(cap=...)``
    would — a malformed or non-positive value falls back to the default
    instead of making the whole package unimportable (or silently
    zero-length, dropping every post-mortem event)."""
    raw = os.environ.get("CSTPU_FLIGHT_RECORDER_CAP", "")
    try:
        cap = int(raw)
    except ValueError:
        return DEFAULT_CAP
    return cap if cap >= 1 else DEFAULT_CAP


_CAP = _env_cap()
_EVENTS: Deque[dict] = collections.deque(maxlen=_CAP)
_SEQ = 0
_DROPPED = 0


def enabled() -> bool:
    return _ENABLED


def enable(cap: Optional[int] = None) -> None:
    """Switch recording on, optionally re-bounding the ring (a new cap
    drops the existing timeline — bounds are structural, not advisory)."""
    global _ENABLED, _CAP, _EVENTS
    with _LOCK:
        if cap is not None and int(cap) != _CAP:
            if cap < 1:
                raise ValueError(f"ring cap must be >= 1, got {cap}")
            _CAP = int(cap)
            _EVENTS = collections.deque(maxlen=_CAP)
        _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    """Drop the timeline and zero the counters (cap + enablement keep)."""
    global _SEQ, _DROPPED
    with _LOCK:
        _EVENTS.clear()
        _SEQ = 0
        _DROPPED = 0


def record(kind: str, **fields) -> None:
    """Append one structured event.  Near-zero cost when disabled; when
    enabled, fields must be JSON-able (ints/floats/strings/bools/small
    dicts) — the recorder never coerces, a dump would fail loudly."""
    if not _ENABLED:
        return
    global _SEQ, _DROPPED
    with _LOCK:
        _SEQ += 1
        if len(_EVENTS) == _CAP:
            _DROPPED += 1
        event = {"seq": _SEQ, "t": round(time.perf_counter(), 6),
                 "kind": kind}
        if fields:
            event.update(fields)
        _EVENTS.append(event)


def timeline() -> list:
    """The ring's events oldest-first, as copies."""
    with _LOCK:
        return [dict(e) for e in _EVENTS]


def stats() -> dict:
    """Ring health for the telemetry bus: enabled flag, bound, fill,
    total events seen, events shed past the bound."""
    with _LOCK:
        return {"enabled": _ENABLED, "cap": _CAP, "events": len(_EVENTS),
                "total": _SEQ, "dropped": _DROPPED}


def dump(reason: str, path: Optional[str] = None,
         with_snapshot: bool = True) -> dict:
    """The post-mortem payload: reason + last-N timeline (+ the full
    telemetry snapshot unless opted out), written as JSON when ``path``
    is given.  Safe to call with recording disabled (the timeline is
    whatever the ring holds)."""
    payload = {"reason": reason, "recorder": stats(), "events": timeline()}
    if with_snapshot:
        from . import registry

        payload["snapshot"] = registry.snapshot()
    if path:
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
        # durable-io: a human-readable post-mortem report, rewritten per
        # dump — not an integrity-checked artifact (no digest by design)
        os.replace(tmp, path)
    return payload


if os.environ.get("CSTPU_FLIGHT_RECORDER") == "1":
    _ENABLED = True
