"""Fixed-bucket (log2) latency histograms with p50/p90/p99 readouts
(ISSUE 11 tentpole, layer 2 of the observability stack).

The engine's phase attribution (``sig_verify_s``, ``attestation_apply_s``,
...) is sum-only: a regression that doubles the p99 while leaving the
median alone moves the total by noise-level amounts and hides.  This
module keeps a per-phase DISTRIBUTION at constant memory: 28 log2
buckets from ~1 µs to >64 s, one counter each, observed once per block
per phase (32 observations per epoch — the hot loops never touch it).

* ``observe(name, seconds)`` — one lock-guarded bucket increment (the
  metrics-lock discipline: producers on the dispatch worker and the host
  observe concurrently);
* ``snapshot()`` — per-name count / total / mean / max plus p50/p90/p99
  estimated from the buckets (linear interpolation inside the winning
  bucket; exact max tracked separately so the tail never under-reports
  past the bucket boundary), and the non-zero buckets keyed by their
  upper bound — rides the telemetry bus as the ``"histograms"`` provider;
* ``reset()`` — drops every histogram (``stf.engine.reset_stats`` calls
  it, so a bench pass's distributions describe exactly that pass).

The registry (``_HISTOGRAMS``) is analyzer-registered (CC01
"latency-histogram registry"): inserts happen only through ``observe``
here.
"""
from __future__ import annotations

import math
import threading
from typing import Dict

# bucket upper bounds: 2**e seconds for e in [_MIN_EXP, _MAX_EXP], plus
# one overflow bucket — ~1 µs resolution at the bottom, >64 s at the top
_MIN_EXP = -20
_MAX_EXP = 6
N_BUCKETS = _MAX_EXP - _MIN_EXP + 2

_LOCK = threading.Lock()
_HISTOGRAMS: Dict[str, "Histogram"] = {}


def _bucket_index(seconds: float) -> int:
    """Index of the half-open ``[2^(e-1), 2^e)`` bucket holding
    ``seconds`` (frexp yields the exponent directly, no float log)."""
    if seconds <= 0.0:
        return 0
    _, exp = math.frexp(seconds)  # seconds = m * 2**exp, 0.5 <= m < 1
    if exp < _MIN_EXP:
        return 0
    if exp > _MAX_EXP:
        return N_BUCKETS - 1
    return exp - _MIN_EXP


def _bucket_bounds(index: int):
    """(lower, upper) bound in seconds of bucket ``index`` (the overflow
    bucket's upper bound is reported as infinity)."""
    lo = 0.0 if index == 0 else 2.0 ** (index - 1 + _MIN_EXP)
    hi = math.inf if index == N_BUCKETS - 1 else 2.0 ** (index + _MIN_EXP)
    return lo, hi


class Histogram:
    """One phase's latency distribution at fixed memory."""

    __slots__ = ("name", "counts", "count", "total_s", "max_s")

    def __init__(self, name: str):
        self.name = name
        self.counts = [0] * N_BUCKETS
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0

    def observe(self, seconds: float) -> None:
        self.counts[_bucket_index(seconds)] += 1
        self.count += 1
        self.total_s += seconds
        if seconds > self.max_s:
            self.max_s = seconds

    def quantile(self, q: float) -> float:
        """The q-quantile estimated from the buckets: linear
        interpolation between the winning bucket's bounds (the overflow
        bucket reports the tracked exact max — the tail never caps at a
        boundary the data already passed)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        cum = 0
        for i, n in enumerate(self.counts):
            if n == 0:
                continue
            if cum + n >= rank:
                lo, hi = _bucket_bounds(i)
                if not math.isfinite(hi):
                    return self.max_s
                frac = (rank - cum) / n
                return min(lo + (hi - lo) * frac, self.max_s or hi)
            cum += n
        return self.max_s

    def snapshot(self) -> dict:
        buckets = {}
        for i, n in enumerate(self.counts):
            if n:
                _, hi = _bucket_bounds(i)
                label = "inf" if not math.isfinite(hi) else f"{hi:.9g}"
                buckets[label] = n
        return {
            "count": self.count,
            "total_s": round(self.total_s, 6),
            "mean_s": round(self.total_s / self.count, 6) if self.count else 0.0,
            "max_s": round(self.max_s, 6),
            "p50_s": round(self.quantile(0.50), 6),
            "p90_s": round(self.quantile(0.90), 6),
            "p99_s": round(self.quantile(0.99), 6),
            "buckets": buckets,
        }


def observe(name: str, seconds: float) -> None:
    """Record one observation into the named histogram (created on first
    use); one lock-guarded increment — safe from any thread."""
    with _LOCK:
        h = _HISTOGRAMS.get(name)
        if h is None:
            h = _HISTOGRAMS[name] = Histogram(name)
        h.observe(seconds)


def names() -> tuple:
    with _LOCK:
        return tuple(sorted(_HISTOGRAMS))


def reset() -> None:
    """Drop every histogram (bench passes and tests want per-run
    distributions; the registry repopulates on first observe)."""
    with _LOCK:
        _HISTOGRAMS.clear()


def snapshot() -> dict:
    """{name: {count, total_s, mean_s, max_s, p50_s, p90_s, p99_s,
    buckets}} over every live histogram (the bus provider)."""
    with _LOCK:
        items = sorted(_HISTOGRAMS.items())
        return {name: h.snapshot() for name, h in items}
