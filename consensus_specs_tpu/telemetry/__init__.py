"""Unified telemetry subsystem (ISSUE 9; SURVEY §5's wall-time
observability, grown from ``tracing.py`` into three cooperating layers):

* **metrics** — thread-safe spans/counters + re-entrant spec
  instrumentation (the legacy ``consensus_specs_tpu.tracing`` module is
  now a thin facade over this layer, byte-compatible for every existing
  callsite);
* **registry** — the metrics bus: every stats producer (stf engine,
  signature settlement, attestation plan cache, resident column store,
  fork-choice engine, faults harness, native counter exports, the
  recorder itself) registers a named snapshot provider, and
  ``snapshot()`` returns one schema-stable tree;
* **recorder** — the per-block flight recorder: a bounded ring of
  structured events (block fast/replayed + reason, breaker transitions,
  degradation, cache commit/rollback, plan/h2c hit deltas, fork-choice
  handler activity) that costs nothing disabled and ``dump()``s a JSON
  post-mortem on failure.

* **timeline + histograms** (ISSUE 11) — the causal trace timeline: a
  bounded ring of begin/end span events with thread identity and
  explicit causality links (block seq → dispatch → native verify →
  drain/commit), ``CSTPU_TIMELINE``-gated and exportable as Chrome
  trace-event JSON (``telemetry.timeline.dump_chrome_trace``); and
  fixed-bucket log2 latency histograms with p50/p90/p99 per phase
  (``telemetry.histogram``), both on the bus.

Layer 4, the soak-endurance harness, lives in ``telemetry.soak`` (run
via ``make soak``) and consumes the others: long seeded walks under
fault schedules with breaker-recovery/cache-coherence/memory-flatness
asserts and a ``SOAK.json`` timeline artifact.

Import contract: this package imports nothing from ``stf``/``forkchoice``
(producers import *us* and register providers at their import); the few
built-in providers below reach into other modules only through
``sys.modules`` probes or deliberately cheap imports, so ``snapshot()``
never drags a subsystem into the process as a side effect.
"""
from __future__ import annotations

import sys

from . import histogram, metrics, recorder, registry, timeline
from .recorder import record
from .registry import register_provider, snapshot

__all__ = [
    "histogram", "metrics", "recorder", "record", "register_provider",
    "registry", "snapshot", "timeline",
]


# -- built-in providers -------------------------------------------------------

def _tracing_provider() -> dict:
    """Spans + counters of the metrics layer (the legacy report shape)."""
    return metrics.report()


def _native_provider() -> dict:
    """Native BLS counter exports — the bounded hash_to_g2 cache that
    fronts the batch verifier's message hashing.  Probed via sys.modules
    so a snapshot never *loads* the native library as a side effect."""
    native = sys.modules.get("consensus_specs_tpu.crypto.bls.native")
    if native is None:
        return {"loaded": False}
    return {"loaded": True, "h2c": native.h2c_cache_stats()}


def _faults_provider() -> dict:
    """Fault-injection activity: whether a plan is armed, what fired."""
    from consensus_specs_tpu import faults

    plan = faults.active_plan()
    out = {"sites_registered": len(faults.registry()),
           "plan_active": plan is not None}
    if plan is not None:
        out["fired"] = [list(f) for f in plan.fired]
        out["hits"] = dict(plan.hits)
    return out


register_provider("tracing", _tracing_provider, replace=True)
register_provider("native.bls", _native_provider, replace=True)
register_provider("faults", _faults_provider, replace=True)
register_provider("flight_recorder", recorder.stats, replace=True)
# ISSUE 11: the causal-timeline ring's health and the per-phase latency
# distributions ride the same bus as every other producer
register_provider("timeline", timeline.stats, replace=True)
register_provider("histograms", histogram.snapshot, replace=True)
