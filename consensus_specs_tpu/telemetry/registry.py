"""Metrics registry/bus: one schema-stable snapshot over every stats
producer (ISSUE 9 tentpole, layer 1).

Before this module, phase timings and health counters lived in five
uncoordinated dicts — ``stf.engine.stats``, ``stf.verify.stats``, the
native ``h2c_cache_stats`` export, the fork-choice engine, the faults
harness — and anything that wanted "the system's state right now" had to
know every one of them.  Now each producer registers a named **snapshot
provider** (a zero-arg callable returning a JSON-able tree) at import
time, and ``snapshot()`` returns one tree over all of them:

    {"schema": 1, "providers": {"stf.engine": {...}, "tracing": {...}}}

Contracts:

* providers must return a FRESH JSON-able structure (``snapshot()``
  deep-copies defensively, so aliasing a live dict is survivable but
  wasteful);
* a provider that raises is isolated: its subtree becomes
  ``{"error": repr(exc)}`` and every other provider still reports —
  telemetry must never take down the thing it observes;
* names are dotted paths mirroring the owning module; duplicate
  registration raises unless ``replace=True`` (module-level
  registrations pass it so re-imports stay safe);
* registration/lookup is lock-guarded — providers register from module
  import while the native pool may be mid-snapshot elsewhere.

The registry itself is analyzer-registered (CC01 "telemetry provider
registry"): inserts happen only through ``register_provider`` here.
"""
from __future__ import annotations

import copy
import threading
from typing import Callable, Dict, Tuple

SCHEMA_VERSION = 1

_LOCK = threading.Lock()
_PROVIDERS: Dict[str, Callable[[], dict]] = {}


def register_provider(name: str, fn: Callable[[], dict],
                      replace: bool = False) -> None:
    """Register ``fn`` as the snapshot provider for ``name`` (a dotted
    path mirroring the owning module).  Duplicates raise unless
    ``replace=True``."""
    if not name or not callable(fn):
        raise ValueError(f"provider needs a name and a callable, got "
                         f"{name!r}/{fn!r}")
    with _LOCK:
        if name in _PROVIDERS and not replace:
            raise ValueError(f"duplicate telemetry provider {name!r}")
        _PROVIDERS[name] = fn


def unregister_provider(name: str) -> None:
    """Drop one provider (tests; a subsystem shutting down)."""
    with _LOCK:
        _PROVIDERS.pop(name, None)


def providers() -> Tuple[str, ...]:
    """Sorted names of every registered provider."""
    with _LOCK:
        return tuple(sorted(_PROVIDERS))


def reset() -> None:
    """Drop every provider (test isolation only — production providers
    re-register at module import)."""
    with _LOCK:
        _PROVIDERS.clear()


def snapshot() -> dict:
    """One schema-stable tree over every registered provider.  Provider
    order is sorted-by-name; a failing provider contributes an
    ``{"error": ...}`` subtree instead of killing the snapshot."""
    with _LOCK:
        items = sorted(_PROVIDERS.items())
    tree: dict = {}
    for name, fn in items:
        try:
            tree[name] = copy.deepcopy(fn())
        except Exception as exc:  # isolation: observation must not wound
            tree[name] = {"error": repr(exc)[:200]}
    return {"schema": SCHEMA_VERSION, "providers": tree}
