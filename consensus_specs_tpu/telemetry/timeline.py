"""Causal trace timeline: a bounded ring of wall-clock span EVENTS
(ISSUE 11 tentpole, layer 3 of the observability stack).

The flight recorder answers "what happened, in what order"; the metrics
layer answers "how much, in total".  Neither can show *where wall-clock
goes across threads* — the PR 10 pipeline runs block N's native pairing
on a dispatch thread while block N+1's host phases run on the main
thread, and proving (or debugging) that overlap needs begin/end events
with thread identity, not aggregate sums.  This module keeps them:

    {"ph": "B", "sid": 17, "name": "host/operations", "t": 3.14,
     "tid": 140244..., "tname": "MainThread", "link": 5, "slot": 34}
    {"ph": "E", "sid": 17, "t": 3.19, "status": "ok"}

* ``begin(name, link=..., **fields)`` / ``end(sid, status=...)`` append
  paired events; ``span(...)`` is the context-manager form (begin/end in
  a ``finally`` — the shape OB01's unclosed-span check enforces for raw
  ``begin`` callers).  DISABLED (the default) every entry point costs one
  module-global load and a truth check — the block path stays
  unmeasurable (pinned by the overhead microbench in
  tests/telemetry/test_timeline.py, the recorder's discipline).
* ``link`` is the explicit CAUSALITY edge: the engine allocates one id
  per block (``next_link()``) and threads it through host phases →
  pipeline dispatch → the worker's native-verify span → the await/drain,
  so a Perfetto load draws the block's flow across threads.  A drained
  speculation's events are marked ``status="cancelled"``
  (``cancel_link``) — the timeline never claims rolled-back work settled.
* the ring is bounded (``CSTPU_TIMELINE_CAP``, default 65536 events) and
  lock-guarded; eviction is counted in ``dropped`` like the recorder's.
* ``dump_chrome_trace(path)`` exports the Chrome trace-event JSON
  (Perfetto / chrome://tracing loadable): one "X" complete event per
  matched begin/end pair on its thread's track, flow arrows ("s"/"f")
  per causality link, thread-name metadata, instants for point events.

Activation: ``CSTPU_TIMELINE=1`` at import, or ``enable()``/``disable()``.
The clock is injectable (``set_clock``) so export tests are
deterministic.
"""
from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from typing import Deque, Optional

DEFAULT_CAP = 65536  # events; a span is two (begin + end)

_LOCK = threading.Lock()
_ENABLED = False
_clock = time.perf_counter


def _env_cap() -> int:
    """The env-configured ring bound, validated like the recorder's — a
    malformed or non-positive value falls back to the default instead of
    making the package unimportable."""
    raw = os.environ.get("CSTPU_TIMELINE_CAP", "")
    try:
        cap = int(raw)
    except ValueError:
        return DEFAULT_CAP
    return cap if cap >= 2 else DEFAULT_CAP


_CAP = _env_cap()
_EVENTS: Deque[dict] = collections.deque(maxlen=_CAP)
_SEQ = 0       # span ids (begin events)
_INSTANTS = 0  # point events (counted separately: not spans)
_LINKS = 0     # causality-link ids (one per block in the engine)
_DROPPED = 0


def enabled() -> bool:
    return _ENABLED


def enable(cap: Optional[int] = None) -> None:
    """Switch timeline recording on, optionally re-bounding the ring (a
    new cap drops the existing events — bounds are structural)."""
    global _ENABLED, _CAP, _EVENTS
    with _LOCK:
        if cap is not None and int(cap) != _CAP:
            if cap < 2:
                raise ValueError(f"timeline cap must be >= 2, got {cap}")
            _CAP = int(cap)
            _EVENTS = collections.deque(maxlen=_CAP)
        _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    """Drop the events and zero the counters (cap + enablement keep)."""
    global _SEQ, _INSTANTS, _LINKS, _DROPPED
    with _LOCK:
        _EVENTS.clear()
        _SEQ = 0
        _INSTANTS = 0
        _LINKS = 0
        _DROPPED = 0


def set_clock(fn=None) -> None:
    """Swap the timestamp source (tests: a deterministic fake clock);
    ``set_clock()`` restores ``time.perf_counter``."""
    global _clock
    _clock = fn if fn is not None else time.perf_counter


def next_link() -> int:
    """A fresh causality-link id (the engine allocates one per block and
    threads it through every span that belongs to that block's flow)."""
    global _LINKS
    with _LOCK:
        _LINKS += 1
        return _LINKS


def _append(event: dict) -> None:
    global _DROPPED
    if len(_EVENTS) == _CAP:
        _DROPPED += 1
    _EVENTS.append(event)


def begin(name: str, link: Optional[int] = None, **fields) -> int:
    """Open a span: returns its id (0 when disabled — ``end(0)`` is a
    no-op, so gated callers need no second check).  Raw ``begin`` callers
    outside telemetry/ must close the span in a ``finally`` (or hand the
    id to an owner object) — OB01's unclosed-span check enforces it."""
    if not _ENABLED:
        return 0
    global _SEQ
    t = _clock()
    thread = threading.current_thread()
    with _LOCK:
        _SEQ += 1
        sid = _SEQ
        event = {"ph": "B", "sid": sid, "name": name, "t": t,
                 "tid": thread.ident, "tname": thread.name}
        if link is not None:
            event["link"] = link
        if fields:
            event.update(fields)
        _append(event)
    return sid


def end(sid: int, status: str = "ok") -> None:
    """Close span ``sid`` (no-op for 0/None — the disabled-path id)."""
    if not _ENABLED or not sid:
        return
    t = _clock()
    with _LOCK:
        _append({"ph": "E", "sid": sid, "t": t,
                 "tid": threading.get_ident(), "status": status})


def instant(name: str, link: Optional[int] = None, **fields) -> None:
    """A point event (drain/commit markers) on the calling thread —
    counted separately from spans (no begin/end pair, no span id)."""
    if not _ENABLED:
        return
    global _INSTANTS
    t = _clock()
    thread = threading.current_thread()
    with _LOCK:
        _INSTANTS += 1
        event = {"ph": "i", "name": name, "t": t,
                 "tid": thread.ident, "tname": thread.name}
        if link is not None:
            event["link"] = link
        if fields:
            event.update(fields)
        _append(event)


@contextlib.contextmanager
def span(name: str, link: Optional[int] = None, **fields):
    """Context-manager span: begin/end with the end in a ``finally``, so
    every exit path (including exceptions) closes the span."""
    sid = begin(name, link=link, **fields)
    try:
        yield
    finally:
        end(sid)


def cancel_link(link: Optional[int]) -> None:
    """Mark every ring event carrying ``link`` as cancelled — the
    engine's unwind path calls this for a rolled-back block, so a
    Perfetto read never mistakes drained host work for settled work.
    One ring pass under the lock (failure paths only — the hot path
    never cancels)."""
    cancel_links((link,) if link is not None else ())


def cancel_links(links) -> None:
    """``cancel_link`` for a whole drained window in ONE ring pass — a
    deep-window drain marks every rolled-back speculation without
    re-scanning the ring (and re-blocking the dispatch worker's appends)
    per block."""
    if not _ENABLED:
        return
    wanted = {l for l in links if l is not None}
    if not wanted:
        return
    with _LOCK:
        for event in _EVENTS:
            if event.get("link") in wanted:
                event["status"] = "cancelled"


def events() -> list:
    """The ring's events oldest-first, as copies."""
    with _LOCK:
        return [dict(e) for e in _EVENTS]


def stats() -> dict:
    """Ring health for the telemetry bus (and the soak flatness sample):
    enabled flag, bound, fill, spans begun, instants, links issued,
    events shed."""
    with _LOCK:
        return {"enabled": _ENABLED, "cap": _CAP, "events": len(_EVENTS),
                "spans": _SEQ, "instants": _INSTANTS, "links": _LINKS,
                "dropped": _DROPPED}


# -- Chrome trace-event export -------------------------------------------------


def dump_chrome_trace(path: Optional[str] = None) -> dict:
    """The timeline as Chrome trace-event JSON (load in Perfetto or
    chrome://tracing): matched begin/end pairs become "X" complete events
    on their begin-thread's track, causality links become flow arrows
    ("s"/"f" with ``bp: "e"``), point events become instants, and every
    thread gets a name row.  Unclosed spans export with ``status:
    "open"`` and a duration up to the newest timestamp seen — a dump
    mid-flight still shows where time was going.  Timestamps are
    microseconds relative to the earliest ring event (Chrome's unit).
    Safe to call with recording disabled (exports whatever the ring
    holds); written atomically when ``path`` is given."""
    ring = events()
    meta_fields = ("ph", "sid", "name", "t", "tid", "tname", "status")
    spans_out, instants, opens = [], [], {}
    t_max = max((e["t"] for e in ring), default=0.0)
    for e in ring:
        if e["ph"] == "B":
            opens[e["sid"]] = e
        elif e["ph"] == "E":
            b = opens.pop(e["sid"], None)
            if b is not None:  # begin may have been evicted: skip orphan
                spans_out.append((b, e["t"],
                                  b.get("status", e.get("status", "ok"))))
        else:
            instants.append(e)
    for b in opens.values():
        spans_out.append((b, t_max, b.get("status", "open")))
    spans_out.sort(key=lambda s: (s[0]["t"], s[0]["sid"]))

    t0 = min((e["t"] for e in ring), default=0.0)

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 3)

    trace, links, thread_names = [], {}, {}
    for b, t_end, status in spans_out:
        args = {k: v for k, v in b.items() if k not in meta_fields}
        args["status"] = status
        trace.append({"name": b["name"], "cat": "cstpu", "ph": "X",
                      "ts": us(b["t"]),
                      "dur": max(0.0, round((t_end - b["t"]) * 1e6, 3)),
                      "pid": 0, "tid": b["tid"], "args": args})
        thread_names.setdefault(b["tid"], b.get("tname"))
        if "link" in b:
            links.setdefault(b["link"], []).append(b)
    for e in instants:
        args = {k: v for k, v in e.items() if k not in meta_fields}
        trace.append({"name": e["name"], "cat": "cstpu", "ph": "i",
                      "ts": us(e["t"]), "pid": 0, "tid": e["tid"],
                      "s": "t", "args": args})
        thread_names.setdefault(e["tid"], e.get("tname"))
        if "link" in e:
            links.setdefault(e["link"], []).append(e)
    # flow arrows: the link's first event starts the flow, every later
    # event on the SAME link receives it (bp="e": bind to enclosing slice)
    for link in sorted(links):
        chain = sorted(links[link], key=lambda e: (e["t"], e.get("sid", 0)))
        first = chain[0]
        trace.append({"name": "block-flow", "cat": "cstpu.flow", "ph": "s",
                      "id": int(link), "ts": us(first["t"]), "pid": 0,
                      "tid": first["tid"]})
        for e in chain[1:]:
            trace.append({"name": "block-flow", "cat": "cstpu.flow",
                          "ph": "f", "bp": "e", "id": int(link),
                          "ts": us(e["t"]), "pid": 0, "tid": e["tid"]})
    for tid in sorted(t for t in thread_names if t is not None):
        trace.append({"name": "thread_name", "ph": "M", "pid": 0,
                      "tid": tid,
                      "args": {"name": thread_names[tid] or f"thread-{tid}"}})
    payload = {"displayTimeUnit": "ms", "traceEvents": trace}
    if path:
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=1)
        # durable-io: a Chrome-trace JSON export for Perfetto, rewritten
        # per dump — a viewer input, not an integrity-checked artifact
        os.replace(tmp, path)
    return payload


if os.environ.get("CSTPU_TIMELINE") == "1":
    _ENABLED = True
