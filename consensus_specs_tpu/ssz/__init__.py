"""SSZ type system + persistent Merkle hashing (see types.py, node.py)."""
from .hashing import ZERO_HASHES, sha256, set_backend, get_backend_name, register_backend
from .impl import copy, hash_tree_root, serialize, uint_to_bytes
from .types import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Bytes1,
    Bytes4,
    Bytes8,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    SSZType,
    Union,
    Vector,
    View,
    bit,
    boolean,
    byte,
    uint,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
)
from .gindex import GeneralizedIndex, build_proof, get_generalized_index
