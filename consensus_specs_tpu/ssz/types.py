"""SSZ type system: basic types, containers, collections, unions.

Semantics follow the SSZ spec (reference: ssz/simple-serialize.md — type
system :40-103, serialization :105-208, merkleization :210-248) and the
reference's remerkleable-based view behavior (eth2spec/utils/ssz/ssz_typing.py
re-exports), re-implemented from scratch on the persistent node layer in
``node.py``:

  * views are mutable facades over immutable backings (copy-on-write)
  * ``copy()`` is O(1): a new view over the same backing
  * child mutation propagates dirtiness to ancestors; flushing happens
    lazily on ``get_backing()`` / ``hash_tree_root()``
  * uintN arithmetic is overflow-checked (spec rule: out-of-range uint64
    math makes a state transition invalid, phase0/beacon-chain.md:1238)

Python-value caches keep hot spec loops off the tree: packed basic
sequences (balances, inactivity scores) materialize as flat int lists with
chunk-granular dirty tracking, so an epoch's worth of balance updates
turns into one bulk subtree update + one layer-batched hash pass.
"""
from __future__ import annotations

import io
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from .node import (
    BranchNode,
    LeafNode,
    Node,
    get_subtree,
    merkle_root,
    pack_chunks,
    subtree_fill_to_contents,
    uint_to_leaf,
    with_updated_subtrees,
    zero_node,
)

OFFSET_BYTE_LENGTH = 4


def ceil_log2(x: int) -> int:
    if x < 1:
        return 0
    return (x - 1).bit_length()


# ---------------------------------------------------------------------------
# Base machinery
# ---------------------------------------------------------------------------


class SSZType:
    """Mixin namespace of the classmethod API every SSZ type implements."""

    @classmethod
    def _layout_key(cls) -> tuple:
        """Structural identity of the type (used to allow assigning
        layout-identical containers across fork namespaces, which the
        reference's fork-upgrade functions rely on)."""
        key = cls.__dict__.get("_layout_key_cache")
        if key is None:
            key = cls._compute_layout_key()
            cls._layout_key_cache = key
        return key

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        raise NotImplementedError

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        raise NotImplementedError

    @classmethod
    def type_byte_length(cls) -> int:
        raise NotImplementedError

    @classmethod
    def default(cls):
        raise NotImplementedError

    @classmethod
    def default_node(cls) -> Node:
        raise NotImplementedError

    @classmethod
    def decode_bytes(cls, data: bytes):
        raise NotImplementedError

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        raise NotImplementedError

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        raise NotImplementedError


class View(SSZType):
    """Mutable composite view over an immutable backing."""

    __slots__ = ("_backing", "_parent", "_pkey")

    def get_backing(self) -> Node:
        raise NotImplementedError

    def encode_bytes(self) -> bytes:
        raise NotImplementedError

    def hash_tree_root(self) -> bytes:
        return merkle_root(self.get_backing())

    def copy(self):
        return type(self).view_from_backing(self.get_backing())

    def _child_changed(self, key) -> None:
        raise NotImplementedError

    def _invalidate(self) -> None:
        p = self._parent
        if p is not None:
            p._child_changed(self._pkey)

    def __eq__(self, other):
        if isinstance(other, View):
            # layout (not type identity): fork-layered spec modules each
            # define their own classes, but identical layouts compare equal
            return (
                type(self)._layout_key() == type(other)._layout_key()
                and self.hash_tree_root() == other.hash_tree_root()
            )
        return NotImplemented

    def __ne__(self, other):
        r = self.__eq__(other)
        return r if r is NotImplemented else not r

    def __hash__(self):
        return int.from_bytes(self.hash_tree_root()[:8], "little")


# ---------------------------------------------------------------------------
# Basic types: uintN, boolean
# ---------------------------------------------------------------------------


class uint(int, SSZType):
    TYPE_BYTE_LENGTH = 0

    def __new__(cls, value=0):
        value = int(value)
        if not 0 <= value < (1 << (cls.TYPE_BYTE_LENGTH * 8)):
            raise ValueError(
                f"value {value} out of range for {cls.__name__}"
            )
        return super().__new__(cls, value)

    # -- checked arithmetic (overflow/underflow -> ValueError) --
    # Non-int operands return NotImplemented so Python falls back to the
    # other operand's handler (e.g. list repetition `[x] * uint64(n)`).

    def __add__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) + int(o))

    __radd__ = __add__

    def __sub__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) - int(o))

    def __rsub__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(o) - int(self))

    def __mul__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) * int(o))

    __rmul__ = __mul__

    def __floordiv__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) // int(o))

    def __rfloordiv__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(o) // int(self))

    def __mod__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) % int(o))

    def __rmod__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(o) % int(self))

    def __pow__(self, o, mod=None):
        if not isinstance(o, int):
            return NotImplemented
        if o < 0:
            raise ValueError("negative exponent on checked uint")
        return type(self)(pow(int(self), int(o), mod))

    def __lshift__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) << int(o))

    def __rshift__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) >> int(o))

    def __and__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) & int(o))

    __rand__ = __and__

    def __or__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) | int(o))

    __ror__ = __or__

    def __xor__(self, o):
        if not isinstance(o, int):
            return NotImplemented
        return type(self)(int(self) ^ int(o))

    __rxor__ = __xor__

    # -- SSZ API --
    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return cls.TYPE_BYTE_LENGTH

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return ("uint", cls.TYPE_BYTE_LENGTH)

    @classmethod
    def default(cls):
        return cls(0)

    @classmethod
    def default_node(cls) -> Node:
        return zero_node(0)

    def encode_bytes(self) -> bytes:
        return int(self).to_bytes(self.TYPE_BYTE_LENGTH, "little")

    @classmethod
    def decode_bytes(cls, data: bytes):
        assert len(data) == cls.TYPE_BYTE_LENGTH
        return cls(int.from_bytes(data, "little"))

    def get_backing(self) -> Node:
        return LeafNode(int(self).to_bytes(32, "little"))

    def hash_tree_root(self) -> bytes:
        return int(self).to_bytes(32, "little")

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        return cls(int.from_bytes(node._root[: cls.TYPE_BYTE_LENGTH], "little"))

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        return value if type(value) is cls else cls(value)


class uint8(uint):
    TYPE_BYTE_LENGTH = 1


class uint16(uint):
    TYPE_BYTE_LENGTH = 2


class uint32(uint):
    TYPE_BYTE_LENGTH = 4


class uint64(uint):
    TYPE_BYTE_LENGTH = 8


class uint128(uint):
    TYPE_BYTE_LENGTH = 16


class uint256(uint):
    TYPE_BYTE_LENGTH = 32


byte = uint8


class boolean(int, SSZType):
    TYPE_BYTE_LENGTH = 1

    def __new__(cls, value=0):
        value = int(value)
        if value not in (0, 1):
            raise ValueError(f"boolean must be 0 or 1, got {value}")
        return super().__new__(cls, value)

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return 1

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return ("bool",)

    @classmethod
    def default(cls):
        return cls(0)

    @classmethod
    def default_node(cls) -> Node:
        return zero_node(0)

    def encode_bytes(self) -> bytes:
        return b"\x01" if self else b"\x00"

    @classmethod
    def decode_bytes(cls, data: bytes):
        assert len(data) == 1 and data[0] in (0, 1)
        return cls(data[0])

    def get_backing(self) -> Node:
        return LeafNode(int(self).to_bytes(32, "little"))

    def hash_tree_root(self) -> bytes:
        return int(self).to_bytes(32, "little")

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        return cls(node._root[0])

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        return value if type(value) is cls else cls(value)


bit = boolean


def is_basic_type(t) -> bool:
    return isinstance(t, type) and issubclass(t, (uint, boolean))


# ---------------------------------------------------------------------------
# ByteVector / ByteList (immutable bytes subclasses)
# ---------------------------------------------------------------------------

_byte_vector_cache: Dict[int, type] = {}
_byte_list_cache: Dict[int, type] = {}


class ByteVector(bytes, SSZType):
    TYPE_BYTE_LENGTH = 0

    def __class_getitem__(cls, length: int) -> type:
        t = _byte_vector_cache.get(length)
        if t is None:
            t = type(f"ByteVector[{length}]", (ByteVector,), {"TYPE_BYTE_LENGTH": length})
            _byte_vector_cache[length] = t
        return t

    def __new__(cls, value: bytes = None):
        if cls.TYPE_BYTE_LENGTH == 0 and cls is ByteVector:
            raise TypeError("use ByteVector[N]")
        if value is None:
            value = b"\x00" * cls.TYPE_BYTE_LENGTH
        elif isinstance(value, str):
            value = bytes.fromhex(value[2:] if value.startswith("0x") else value)
        else:
            value = bytes(value)
        if len(value) != cls.TYPE_BYTE_LENGTH:
            raise ValueError(
                f"{cls.__name__} requires {cls.TYPE_BYTE_LENGTH} bytes, got {len(value)}"
            )
        return super().__new__(cls, value)

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return ("bytevector", cls.TYPE_BYTE_LENGTH)

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return cls.TYPE_BYTE_LENGTH

    @classmethod
    def default(cls):
        return cls(b"\x00" * cls.TYPE_BYTE_LENGTH)

    @classmethod
    def default_node(cls) -> Node:
        return zero_node(ceil_log2((cls.TYPE_BYTE_LENGTH + 31) // 32))

    def encode_bytes(self) -> bytes:
        return bytes(self)

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls(data)

    def get_backing(self) -> Node:
        chunks = pack_chunks(bytes(self))
        return subtree_fill_to_contents(chunks, ceil_log2(len(chunks)))

    def hash_tree_root(self) -> bytes:
        if self.TYPE_BYTE_LENGTH <= 32:
            return bytes(self) + b"\x00" * (32 - self.TYPE_BYTE_LENGTH)
        return merkle_root(self.get_backing())

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        n_chunks = (cls.TYPE_BYTE_LENGTH + 31) // 32
        depth = ceil_log2(n_chunks)
        data = b"".join(
            get_subtree(node, depth, i)._root for i in range(n_chunks)
        )
        return cls(data[: cls.TYPE_BYTE_LENGTH])

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        return value if type(value) is cls else cls(value)

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


class ByteList(bytes, SSZType):
    LIMIT = 0

    def __class_getitem__(cls, limit: int) -> type:
        t = _byte_list_cache.get(limit)
        if t is None:
            t = type(f"ByteList[{limit}]", (ByteList,), {"LIMIT": limit})
            _byte_list_cache[limit] = t
        return t

    def __new__(cls, value: bytes = b""):
        if isinstance(value, str):
            value = bytes.fromhex(value[2:] if value.startswith("0x") else value)
        if isinstance(value, (list, tuple)):
            value = bytes(value)
        value = bytes(value)
        if len(value) > cls.LIMIT:
            raise ValueError(f"{cls.__name__} max {cls.LIMIT} bytes, got {len(value)}")
        return super().__new__(cls, value)

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return ("bytelist", cls.LIMIT)

    @classmethod
    def default(cls):
        return cls(b"")

    @classmethod
    def contents_depth(cls) -> int:
        return ceil_log2((cls.LIMIT + 31) // 32)

    @classmethod
    def default_node(cls) -> Node:
        return BranchNode(zero_node(cls.contents_depth()), zero_node(0))

    def encode_bytes(self) -> bytes:
        return bytes(self)

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls(data)

    def get_backing(self) -> Node:
        chunks = pack_chunks(bytes(self))
        contents = subtree_fill_to_contents(chunks, self.contents_depth())
        return BranchNode(contents, uint_to_leaf(len(self)))

    def hash_tree_root(self) -> bytes:
        return merkle_root(self.get_backing())

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        assert isinstance(node, BranchNode)
        length = int.from_bytes(node.right._root[:8], "little")
        n_chunks = (length + 31) // 32
        depth = cls.contents_depth()
        data = b"".join(
            get_subtree(node.left, depth, i)._root for i in range(n_chunks)
        )
        return cls(data[:length])

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        return value if type(value) is cls else cls(value)

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


# Common aliases used across the spec types
Bytes1 = ByteVector[1]
Bytes4 = ByteVector[4]
Bytes8 = ByteVector[8]
Bytes20 = ByteVector[20]
Bytes32 = ByteVector[32]
Bytes48 = ByteVector[48]
Bytes96 = ByteVector[96]


# ---------------------------------------------------------------------------
# Bitvector / Bitlist
# ---------------------------------------------------------------------------


def _pack_bits(bits: Sequence[int]) -> bytes:
    n = len(bits)
    out = bytearray((n + 7) // 8)
    for i, b in enumerate(bits):
        if b:
            out[i >> 3] |= 1 << (i & 7)
    return bytes(out)


def _unpack_bits(data: bytes, n: int) -> list:
    return [bool((data[i >> 3] >> (i & 7)) & 1) for i in range(n)]


_bitvector_cache: Dict[int, type] = {}
_bitlist_cache: Dict[int, type] = {}


class _BitsBase(View):
    __slots__ = ("_bits",)
    LENGTH = 0  # Bitvector: exact length; Bitlist: limit

    def __init__(self, *args):
        self._parent = None
        self._pkey = None
        if len(args) == 1 and isinstance(args[0], (list, tuple, bytes, bytearray)) or (
            len(args) == 1 and hasattr(args[0], "__iter__") and not isinstance(args[0], int)
        ):
            bits = [bool(b) for b in args[0]]
        else:
            bits = [bool(b) for b in args]
        self._init_bits(bits)
        self._backing = None

    def _init_bits(self, bits):
        raise NotImplementedError

    def __len__(self):
        return len(self._bits)

    def __iter__(self):
        return iter(self._bits)

    def __getitem__(self, i):
        return self._bits[i]

    def __setitem__(self, i, v):
        if isinstance(i, slice):
            # length-preserving slice write (spec: justification bit rotation)
            new = [bool(b) for b in v]
            if len(self._bits[i]) != len(new):
                raise ValueError("bit slice assignment must preserve length")
            self._bits[i] = new
        else:
            self._bits[i] = bool(v)
        self._backing = None
        self._invalidate()

    def _child_changed(self, key):
        pass

    def __eq__(self, other):
        if isinstance(other, _BitsBase):
            return type(self)._layout_key() == type(other)._layout_key() and self._bits == other._bits
        if isinstance(other, (list, tuple)):
            return self._bits == [bool(b) for b in other]
        return NotImplemented

    __hash__ = View.__hash__

    def __repr__(self):
        return f"{type(self).__name__}({''.join('1' if b else '0' for b in self._bits)})"


class Bitvector(_BitsBase):
    __slots__ = ()

    def __class_getitem__(cls, length: int) -> type:
        t = _bitvector_cache.get(length)
        if t is None:
            t = type(f"Bitvector[{length}]", (Bitvector,), {"LENGTH": length, "__slots__": ()})
            _bitvector_cache[length] = t
        return t

    def _init_bits(self, bits):
        if not bits:
            bits = [False] * self.LENGTH
        if len(bits) != self.LENGTH:
            raise ValueError(f"Bitvector[{self.LENGTH}] got {len(bits)} bits")
        self._bits = bits

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return (cls.LENGTH + 7) // 8

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return ("bitvector", cls.LENGTH)

    @classmethod
    def chunk_depth(cls) -> int:
        return ceil_log2((cls.LENGTH + 255) // 256)

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def default_node(cls) -> Node:
        return zero_node(cls.chunk_depth())

    def encode_bytes(self) -> bytes:
        return _pack_bits(self._bits)

    @classmethod
    def decode_bytes(cls, data: bytes):
        assert len(data) == cls.type_byte_length()
        # verify padding bits are zero
        if cls.LENGTH % 8:
            assert data[-1] >> (cls.LENGTH % 8) == 0
        return cls(_unpack_bits(data, cls.LENGTH))

    def get_backing(self) -> Node:
        if self._backing is None:
            chunks = pack_chunks(_pack_bits(self._bits))
            self._backing = subtree_fill_to_contents(chunks, self.chunk_depth())
        return self._backing

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        n_chunks = (cls.LENGTH + 255) // 256
        depth = cls.chunk_depth()
        data = b"".join(get_subtree(node, depth, i)._root for i in range(n_chunks))
        v = cls(_unpack_bits(data, cls.LENGTH))
        v._parent = parent
        v._pkey = pkey
        v._backing = node
        return v

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        if isinstance(value, cls):
            v = cls(list(value._bits))
        else:
            v = cls(value)
        v._parent = parent
        v._pkey = pkey
        return v


class Bitlist(_BitsBase):
    __slots__ = ()

    def __class_getitem__(cls, limit: int) -> type:
        t = _bitlist_cache.get(limit)
        if t is None:
            t = type(f"Bitlist[{limit}]", (Bitlist,), {"LENGTH": limit, "__slots__": ()})
            _bitlist_cache[limit] = t
        return t

    def _init_bits(self, bits):
        if len(bits) > self.LENGTH:
            raise ValueError(f"Bitlist[{self.LENGTH}] got {len(bits)} bits")
        self._bits = bits

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return ("bitlist", cls.LENGTH)

    def append(self, v):
        if len(self._bits) >= self.LENGTH:
            raise ValueError("bitlist full")
        self._bits.append(bool(v))
        self._backing = None
        self._invalidate()

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def chunk_depth(cls) -> int:
        return ceil_log2((cls.LENGTH + 255) // 256)

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def default_node(cls) -> Node:
        return BranchNode(zero_node(cls.chunk_depth()), zero_node(0))

    def encode_bytes(self) -> bytes:
        n = len(self._bits)
        out = bytearray(_pack_bits(self._bits))
        # delimiter bit
        if n % 8 == 0:
            out.append(1)
        else:
            out[-1] |= 1 << (n % 8)
        return bytes(out)

    @classmethod
    def decode_bytes(cls, data: bytes):
        assert len(data) > 0 and data[-1] != 0, "invalid bitlist delimiter"
        last = data[-1]
        hi = last.bit_length() - 1  # delimiter position within last byte
        n = (len(data) - 1) * 8 + hi
        assert n <= cls.LENGTH
        bits = _unpack_bits(data, n)
        return cls(bits)

    def get_backing(self) -> Node:
        if self._backing is None:
            chunks = pack_chunks(_pack_bits(self._bits))
            contents = subtree_fill_to_contents(chunks, self.chunk_depth())
            self._backing = BranchNode(contents, uint_to_leaf(len(self._bits)))
        return self._backing

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        assert isinstance(node, BranchNode)
        n = int.from_bytes(node.right._root[:8], "little")
        n_chunks = (n + 255) // 256
        depth = cls.chunk_depth()
        data = b"".join(get_subtree(node.left, depth, i)._root for i in range(n_chunks))
        v = cls(_unpack_bits(data, n))
        v._parent = parent
        v._pkey = pkey
        v._backing = node
        return v

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        if isinstance(value, cls):
            v = cls(list(value._bits))
        else:
            v = cls(value)
        v._parent = parent
        v._pkey = pkey
        return v


# ---------------------------------------------------------------------------
# Container
# ---------------------------------------------------------------------------


class Container(View):
    __slots__ = ("_cache", "_dirty")

    _field_names: Tuple[str, ...] = ()
    _field_types: Tuple[type, ...] = ()
    _field_index: Dict[str, int] = {}
    _depth = 0
    _default_backing_cache: Optional[Node] = None

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        fields: Dict[str, type] = {}
        for base in reversed(cls.__mro__):
            anns = base.__dict__.get("__annotations__", {})
            for name, t in anns.items():
                if name.startswith("_"):
                    continue
                fields[name] = t
        cls._field_names = tuple(fields.keys())
        cls._field_types = tuple(fields.values())
        cls._field_index = {n: i for i, n in enumerate(cls._field_names)}
        cls._depth = ceil_log2(len(fields)) if fields else 0
        cls._default_backing_cache = None

    def __init__(self, **kwargs):
        object.__setattr__(self, "_backing", type(self).default_backing())
        object.__setattr__(self, "_cache", {})
        object.__setattr__(self, "_dirty", set())
        object.__setattr__(self, "_parent", None)
        object.__setattr__(self, "_pkey", None)
        for k, v in kwargs.items():
            if k not in type(self)._field_index:
                raise TypeError(f"{type(self).__name__} has no field {k}")
            setattr(self, k, v)

    @classmethod
    def default_backing(cls) -> Node:
        if cls._default_backing_cache is None:
            cls._default_backing_cache = subtree_fill_to_contents(
                [t.default_node() for t in cls._field_types], cls._depth
            )
        return cls._default_backing_cache

    @classmethod
    def default_node(cls) -> Node:
        return cls.default_backing()

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return all(t.is_fixed_byte_length() for t in cls._field_types)

    @classmethod
    def type_byte_length(cls) -> int:
        assert cls.is_fixed_byte_length()
        return sum(t.type_byte_length() for t in cls._field_types)

    # -- attribute protocol --

    def __getattr__(self, name: str):
        # only called when normal lookup fails (fields are not real attrs)
        idx = type(self)._field_index.get(name)
        if idx is None:
            raise AttributeError(f"{type(self).__name__} has no field {name}")
        cache = self._cache
        if name in cache:
            return cache[name]
        node = get_subtree(self._backing, type(self)._depth, idx)
        val = type(self)._field_types[idx].view_from_backing(node, self, name)
        cache[name] = val
        return val

    def __setattr__(self, name: str, value):
        idx = type(self)._field_index.get(name)
        if idx is None:
            object.__setattr__(self, name, value)
            return
        ftype = type(self)._field_types[idx]
        self._cache[name] = ftype.coerce_for_store(value, self, name)
        if name not in self._dirty:
            self._dirty.add(name)
            self._invalidate()

    def _child_changed(self, key):
        if key not in self._dirty:
            self._dirty.add(key)
            self._invalidate()

    # -- backing / serialization --

    def get_backing(self) -> Node:
        if self._dirty:
            cls = type(self)
            updates = []
            for name in self._dirty:
                idx = cls._field_index[name]
                updates.append((idx, _node_of(cls._field_types[idx], self._cache[name])))
            updates.sort(key=lambda kv: kv[0])
            object.__setattr__(
                self, "_backing", with_updated_subtrees(self._backing, cls._depth, updates)
            )
            self._dirty.clear()
        return self._backing

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        v = cls.__new__(cls)
        object.__setattr__(v, "_backing", node)
        object.__setattr__(v, "_cache", {})
        object.__setattr__(v, "_dirty", set())
        object.__setattr__(v, "_parent", parent)
        object.__setattr__(v, "_pkey", pkey)
        return v

    def set_backing(self, node: Node) -> None:
        """Swap this view's tree wholesale (state snapshot restore)."""
        object.__setattr__(self, "_backing", node)
        # detach handed-out child views: writes through them must not
        # re-dirty fields that no longer exist in this view's cache
        for child in self._cache.values():
            if isinstance(child, View):
                object.__setattr__(child, "_parent", None)
                object.__setattr__(child, "_pkey", None)
        self._cache.clear()
        self._dirty.clear()
        self._invalidate()

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return (
            "container",
            tuple(
                (n, t._layout_key())
                for n, t in zip(cls._field_names, cls._field_types)
            ),
        )

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        if isinstance(value, Container):
            # same class -> same layout a priori: the deep layout-key
            # tuple compare is only for cross-fork-namespace stores
            if type(value) is cls or value._layout_key() == cls._layout_key():
                return cls.view_from_backing(value.get_backing(), parent, pkey)
            # fork-extension reinterpretation (e.g. a bellatrix
            # ExecutionPayloadHeader stored into capella's, fork.md
            # upgrades): when the source's (name, layout) field list is a
            # strict prefix of the target's, rebuild the backing from the
            # source's field subtrees plus proper *default nodes* for the
            # appended fields — structurally correct for composite
            # additions, root-identical to zero-padding for basic ones.
            src = type(value)
            n_src = len(src._field_types)
            if n_src <= len(cls._field_types) and all(
                na == nb and ta._layout_key() == tb._layout_key()
                for (na, ta), (nb, tb) in zip(
                    zip(src._field_names, src._field_types),
                    zip(cls._field_names, cls._field_types),
                )
            ):
                backing = value.get_backing()
                nodes = [get_subtree(backing, src._depth, i) for i in range(n_src)]
                nodes += [t.default_node() for t in cls._field_types[n_src:]]
                rebuilt = subtree_fill_to_contents(nodes, cls._depth)
                return cls.view_from_backing(rebuilt, parent, pkey)
        raise TypeError(f"cannot store {type(value).__name__} as {cls.__name__}")

    def encode_bytes(self) -> bytes:
        cls = type(self)
        return _encode_ordered(
            [getattr(self, n) for n in cls._field_names], cls._field_types
        )

    @classmethod
    def decode_bytes(cls, data: bytes):
        values = _decode_ordered(data, cls._field_types)
        return cls(**dict(zip(cls._field_names, values)))

    def __repr__(self):
        cls = type(self)
        inner = ", ".join(f"{n}={getattr(self, n)!r}" for n in cls._field_names)
        return f"{cls.__name__}({inner})"


def _uniform_tree(leaf: Node, depth: int) -> Node:
    """Depth-`depth` tree whose leaves are all `leaf` (siblings shared)."""
    if leaf is zero_node(0):
        return zero_node(depth)
    cur = leaf
    for _ in range(depth):
        cur = BranchNode(cur, cur)
    return cur


def _node_of(ftype, value) -> Node:
    """Backing node of a stored field/element value."""
    if isinstance(value, View):
        return value.get_backing()
    if isinstance(value, (uint, boolean)):
        return LeafNode(int(value).to_bytes(32, "little"))
    if isinstance(value, (ByteVector, ByteList)):
        return value.get_backing()
    raise TypeError(f"cannot get node of {type(value).__name__}")


# ---------------------------------------------------------------------------
# Vector / List
# ---------------------------------------------------------------------------

_vector_cache: Dict[Tuple[type, int], type] = {}
_list_cache: Dict[Tuple[type, int], type] = {}


class _HomogeneousBase(View):
    """Shared machinery for Vector/List.

    Packed (basic-element) sequences materialize all values into a flat
    Python list with chunk-level dirty tracking; composite-element
    sequences cache per-index child views.
    """

    __slots__ = ("_cache", "_dirty", "_values", "_dirty_chunks", "_length")

    ELEM_TYPE: type = uint8
    # Vector: LENGTH = fixed length. List: LENGTH = limit.
    LENGTH = 0
    IS_LIST = False

    # -- class helpers --

    @classmethod
    def _is_packed(cls) -> bool:
        return is_basic_type(cls.ELEM_TYPE)

    @classmethod
    def _elems_per_chunk(cls) -> int:
        return 32 // cls.ELEM_TYPE.type_byte_length()

    @classmethod
    def _limit_chunks(cls) -> int:
        if cls._is_packed():
            return (cls.LENGTH * cls.ELEM_TYPE.type_byte_length() + 31) // 32
        return cls.LENGTH

    @classmethod
    def contents_depth(cls) -> int:
        return ceil_log2(cls._limit_chunks())

    # -- init --

    def _base_init(self, values: Iterable):
        object.__setattr__(self, "_parent", None)
        object.__setattr__(self, "_pkey", None)
        self._cache = {}
        self._dirty = set()
        self._dirty_chunks = set() if type(self)._is_packed() else None
        cls = type(self)
        vals = list(values)
        if cls.IS_LIST:
            if len(vals) > cls.LENGTH:
                raise ValueError(f"{cls.__name__}: {len(vals)} > limit {cls.LENGTH}")
        elif vals and len(vals) != cls.LENGTH:
            raise ValueError(f"{cls.__name__}: need {cls.LENGTH} elements")
        if not vals:
            # default-shaped: share the global zero backing, nothing dirty
            self._length = 0 if cls.IS_LIST else cls.LENGTH
            self._values = None
            self._backing = cls._empty_backing()
            return
        self._length = len(vals)
        self._backing = self._empty_backing()
        if cls._is_packed():
            et = cls.ELEM_TYPE
            self._values = [int(et(v)) if not isinstance(v, et) else int(v) for v in vals]
            self._dirty_chunks = True  # full rebuild pending
        else:
            et = cls.ELEM_TYPE
            self._values = None
            for i, v in enumerate(vals):
                self._cache[i] = et.coerce_for_store(v, self, i)
                self._dirty.add(i)

    @classmethod
    def _empty_backing(cls) -> Node:
        if cls.IS_LIST or cls._is_packed():
            # list slots are zero chunks until filled; packed contents are zero chunks
            contents = zero_node(cls.contents_depth())
        else:
            # Vector of composites: every element exists at its default value,
            # and element subtrees extend below the contents depth.  Identical
            # siblings share one node (persistent DAG), so this is O(depth).
            contents = _uniform_tree(cls.ELEM_TYPE.default_node(), cls.contents_depth())
        if cls.IS_LIST:
            return BranchNode(contents, zero_node(0))
        return contents

    def __init__(self, *args):
        if (
            len(args) == 1
            and not isinstance(args[0], (int, bytes, SSZType))
            and hasattr(args[0], "__iter__")
        ):
            values = args[0]
        else:
            values = args
        self._base_init(values)

    # -- python sequence protocol --

    def __len__(self):
        return self._length

    def __iter__(self):
        for i in range(self._length):
            yield self[i]

    def __contains__(self, item):
        return any(self[i] == item for i in range(self._length))

    def count(self, item) -> int:
        return sum(1 for i in range(self._length) if self[i] == item)

    def index(self, item) -> int:
        for i in range(self._length):
            if self[i] == item:
                return i
        raise ValueError(f"{item!r} not in sequence")

    def _materialize_values(self):
        """Packed path: decode all chunks into a flat int list."""
        if self._values is not None:
            return
        cls = type(self)
        per = cls._elems_per_chunk()
        n_chunks = (self._length + per - 1) // per
        contents = self._contents_node()
        depth = cls.contents_depth()
        data = b"".join(
            _collect_leaf_roots(contents, depth, n_chunks)
        )
        size = cls.ELEM_TYPE.type_byte_length()
        if size == 8:
            arr = np.frombuffer(data[: 8 * ((len(data)) // 8)], dtype="<u8")
            self._values = [int(x) for x in arr[: self._length]]
        elif size == 1:
            self._values = list(data[: self._length])
        else:
            self._values = [
                int.from_bytes(data[i * size : (i + 1) * size], "little")
                for i in range(self._length)
            ]
        self._dirty_chunks = set()

    def _contents_node(self) -> Node:
        if type(self).IS_LIST:
            assert isinstance(self._backing, BranchNode)
            return self._backing.left
        return self._backing

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self._length))]
        i = int(i)
        if i < 0:
            i += self._length
        if not 0 <= i < self._length:
            raise IndexError(f"index {i} out of range (len {self._length})")
        cls = type(self)
        if cls._is_packed():
            self._materialize_values()
            return cls.ELEM_TYPE(self._values[i])
        if i in self._cache:
            return self._cache[i]
        node = get_subtree(self._contents_node(), cls.contents_depth(), i)
        v = cls.ELEM_TYPE.view_from_backing(node, self, i)
        self._cache[i] = v
        return v

    def __setitem__(self, i, value):
        i = int(i)
        if i < 0:
            i += self._length
        if not 0 <= i < self._length:
            raise IndexError(f"index {i} out of range (len {self._length})")
        cls = type(self)
        if cls._is_packed():
            self._materialize_values()
            self._values[i] = int(cls.ELEM_TYPE(value))
            if self._dirty_chunks is not True:
                self._dirty_chunks.add(i // cls._elems_per_chunk())
        else:
            self._cache[i] = cls.ELEM_TYPE.coerce_for_store(value, self, i)
            self._dirty.add(i)
        self._invalidate()

    def _child_changed(self, key):
        if key not in self._dirty:
            self._dirty.add(key)
            self._invalidate()

    def __eq__(self, other):
        if isinstance(other, View):
            return (
                type(self)._layout_key() == type(other)._layout_key()
                and self.hash_tree_root() == other.hash_tree_root()
            )
        if isinstance(other, (list, tuple)):
            return self._length == len(other) and all(
                self[i] == other[i] for i in range(self._length)
            )
        return NotImplemented

    __hash__ = View.__hash__

    def __repr__(self):
        return f"{type(self).__name__}([{', '.join(repr(self[i]) for i in range(self._length))}])"

    # -- backing --

    def get_backing(self) -> Node:
        cls = type(self)
        contents = self._contents_node()
        depth = cls.contents_depth()
        changed = False
        if cls._is_packed():
            if self._dirty_chunks is True or (self._dirty_chunks and len(self._dirty_chunks) > 0):
                per = cls._elems_per_chunk()
                size = cls.ELEM_TYPE.type_byte_length()
                vals = self._values
                n_chunks = (self._length + per - 1) // per
                if self._dirty_chunks is True:
                    chunk_ids = range(n_chunks)
                else:
                    chunk_ids = sorted(self._dirty_chunks)
                updates = []
                for c in chunk_ids:
                    lo = c * per
                    hi = min(lo + per, self._length)
                    if size == 8:
                        raw = np.asarray(vals[lo:hi], dtype="<u8").tobytes()
                    elif size == 1:
                        raw = bytes(vals[lo:hi])
                    else:
                        raw = b"".join(
                            v.to_bytes(size, "little") for v in vals[lo:hi]
                        )
                    if len(raw) < 32:
                        raw = raw + b"\x00" * (32 - len(raw))
                    updates.append((c, LeafNode(raw)))
                if self._dirty_chunks is True:
                    # bulk rebuild: zero-out beyond n_chunks is implicit (fresh tree)
                    contents = subtree_fill_to_contents([u[1] for u in updates], depth)
                else:
                    contents = with_updated_subtrees(contents, depth, updates)
                self._dirty_chunks = set()
                changed = True
        else:
            if self._dirty:
                updates = sorted(
                    (i, _node_of(cls.ELEM_TYPE, self._cache[i])) for i in self._dirty
                )
                if len(updates) == self._length and updates[-1][0] == self._length - 1:
                    # bulk build (genesis registries): one bottom-up fill
                    contents = subtree_fill_to_contents([u[1] for u in updates], depth)
                else:
                    contents = with_updated_subtrees(contents, depth, updates)
                self._dirty.clear()
                changed = True
        if changed or (cls.IS_LIST and self._length_changed()):
            if cls.IS_LIST:
                self._backing = BranchNode(contents, uint_to_leaf(self._length))
            else:
                self._backing = contents
        return self._backing

    def _length_changed(self) -> bool:
        assert isinstance(self._backing, BranchNode)
        return int.from_bytes(self._backing.right._root[:8], "little") != self._length

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        v = cls.__new__(cls)
        object.__setattr__(v, "_parent", parent)
        object.__setattr__(v, "_pkey", pkey)
        v._cache = {}
        v._dirty = set()
        v._values = None
        v._dirty_chunks = set() if cls._is_packed() else None
        v._backing = node
        if cls.IS_LIST:
            assert isinstance(node, BranchNode)
            v._length = int.from_bytes(node.right._root[:8], "little")
        else:
            v._length = cls.LENGTH
        return v

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        if isinstance(value, _HomogeneousBase):
            if value._layout_key() != cls._layout_key():
                raise TypeError(f"cannot store {type(value).__name__} as {cls.__name__}")
            v = cls.view_from_backing(value.get_backing(), parent, pkey)
        else:
            v = cls(value)
            object.__setattr__(v, "_parent", parent)
            object.__setattr__(v, "_pkey", pkey)
        return v

    # -- serialization --

    def encode_bytes(self) -> bytes:
        cls = type(self)
        if cls._is_packed():
            self._materialize_values()
            size = cls.ELEM_TYPE.type_byte_length()
            if size == 8:
                return np.asarray(self._values, dtype="<u8").tobytes()
            if size == 1:
                return bytes(self._values)
            return b"".join(v.to_bytes(size, "little") for v in self._values)
        return _encode_ordered(
            [self[i] for i in range(self._length)],
            [cls.ELEM_TYPE] * self._length,
        )


class Vector(_HomogeneousBase):
    __slots__ = ()

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return ("vector", cls.ELEM_TYPE._layout_key(), cls.LENGTH)

    def __class_getitem__(cls, params) -> type:
        elem_type, length = params
        key = (elem_type, length)
        t = _vector_cache.get(key)
        if t is None:
            t = type(
                f"Vector[{elem_type.__name__},{length}]",
                (Vector,),
                {"ELEM_TYPE": elem_type, "LENGTH": length, "IS_LIST": False, "__slots__": ()},
            )
            _vector_cache[key] = t
        return t

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return cls.ELEM_TYPE.is_fixed_byte_length()

    @classmethod
    def type_byte_length(cls) -> int:
        return cls.ELEM_TYPE.type_byte_length() * cls.LENGTH

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def default_node(cls) -> Node:
        return cls._empty_backing()

    @classmethod
    def decode_bytes(cls, data: bytes):
        et = cls.ELEM_TYPE
        if et.is_fixed_byte_length():
            size = et.type_byte_length()
            assert len(data) == size * cls.LENGTH
            return cls([et.decode_bytes(data[i * size : (i + 1) * size]) for i in range(cls.LENGTH)])
        values = _decode_variable_list(data, et)
        assert len(values) == cls.LENGTH
        return cls(values)


class List(_HomogeneousBase):
    __slots__ = ()

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return ("list", cls.ELEM_TYPE._layout_key(), cls.LENGTH)

    def __class_getitem__(cls, params) -> type:
        elem_type, limit = params
        key = (elem_type, limit)
        t = _list_cache.get(key)
        if t is None:
            t = type(
                f"List[{elem_type.__name__},{limit}]",
                (List,),
                {"ELEM_TYPE": elem_type, "LENGTH": limit, "IS_LIST": True, "__slots__": ()},
            )
            _list_cache[key] = t
        return t

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def default(cls):
        return cls()

    @classmethod
    def default_node(cls) -> Node:
        return BranchNode(zero_node(cls.contents_depth()), zero_node(0))

    def append(self, value):
        cls = type(self)
        if self._length >= cls.LENGTH:
            raise ValueError(f"{cls.__name__} full (limit {cls.LENGTH})")
        i = self._length
        if cls._is_packed():
            self._materialize_values()
            self._values.append(int(cls.ELEM_TYPE(value)))
            if self._dirty_chunks is not True:
                self._dirty_chunks.add(i // cls._elems_per_chunk())
        else:
            self._cache[i] = cls.ELEM_TYPE.coerce_for_store(value, self, i)
            self._dirty.add(i)
        self._length = i + 1
        self._invalidate()

    def pop(self):
        cls = type(self)
        if self._length == 0:
            raise IndexError("pop from empty list")
        i = self._length - 1
        if cls._is_packed():
            self._materialize_values()
            self._values.pop()
            if self._dirty_chunks is not True:
                self._dirty_chunks.add(i // cls._elems_per_chunk())
            self._length = i
        else:
            # flush pending updates, then zero the vacated slot (unfilled list
            # slots are zero chunks, not default-element subtrees)
            self.get_backing()
            self._cache.pop(i, None)
            self._length = i
            contents = with_updated_subtrees(
                self._contents_node(), cls.contents_depth(), [(i, zero_node(0))]
            )
            self._backing = BranchNode(contents, uint_to_leaf(self._length))
        self._invalidate()

    @classmethod
    def decode_bytes(cls, data: bytes):
        et = cls.ELEM_TYPE
        if len(data) == 0:
            return cls()
        if et.is_fixed_byte_length():
            size = et.type_byte_length()
            assert len(data) % size == 0
            n = len(data) // size
            assert n <= cls.LENGTH
            return cls([et.decode_bytes(data[i * size : (i + 1) * size]) for i in range(n)])
        values = _decode_variable_list(data, et)
        assert len(values) <= cls.LENGTH
        return cls(values)


# ---------------------------------------------------------------------------
# Union
# ---------------------------------------------------------------------------

_union_cache: Dict[tuple, type] = {}


class Union(View):
    __slots__ = ("_selector", "_value")

    OPTIONS: Tuple[Optional[type], ...] = ()

    def __class_getitem__(cls, params) -> type:
        if not isinstance(params, tuple):
            params = (params,)
        t = _union_cache.get(params)
        if t is None:
            name = f"Union[{','.join('None' if p is None else p.__name__ for p in params)}]"
            t = type(name, (Union,), {"OPTIONS": params, "__slots__": ()})
            _union_cache[params] = t
        return t

    def __init__(self, selector: int = 0, value=None):
        object.__setattr__(self, "_parent", None)
        object.__setattr__(self, "_pkey", None)
        cls = type(self)
        assert 0 <= selector < len(cls.OPTIONS)
        opt = cls.OPTIONS[selector]
        if opt is None:
            assert value is None
        else:
            value = opt.coerce_for_store(value if value is not None else opt.default())
        self._selector = selector
        self._value = value
        self._backing = None

    @property
    def selector(self) -> int:
        return self._selector

    @property
    def value(self):
        return self._value

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def default(cls):
        return cls(0, None if cls.OPTIONS[0] is None else cls.OPTIONS[0].default())

    @classmethod
    def default_node(cls) -> Node:
        opt = cls.OPTIONS[0]
        val = zero_node(0) if opt is None else opt.default_node()
        return BranchNode(val, zero_node(0))

    @classmethod
    def _compute_layout_key(cls) -> tuple:
        return (
            "union",
            tuple(None if o is None else o._layout_key() for o in cls.OPTIONS),
        )

    def get_backing(self) -> Node:
        val_node = zero_node(0) if self._value is None else _node_of(None, self._value)
        return BranchNode(val_node, uint_to_leaf(self._selector))

    @classmethod
    def view_from_backing(cls, node: Node, parent=None, pkey=None):
        assert isinstance(node, BranchNode)
        sel = int.from_bytes(node.right._root[:8], "little")
        opt = cls.OPTIONS[sel]
        v = cls.__new__(cls)
        object.__setattr__(v, "_parent", parent)
        object.__setattr__(v, "_pkey", pkey)
        v._selector = sel
        v._value = None if opt is None else opt.view_from_backing(node.left, v, "value")
        v._backing = node
        return v

    @classmethod
    def coerce_for_store(cls, value, parent=None, pkey=None):
        if not (isinstance(value, Union) and value._layout_key() == cls._layout_key()):
            raise TypeError(f"cannot store {type(value).__name__} as {cls.__name__}")
        v = cls.view_from_backing(value.get_backing())
        object.__setattr__(v, "_parent", parent)
        object.__setattr__(v, "_pkey", pkey)
        return v

    def encode_bytes(self) -> bytes:
        body = b"" if self._value is None else self._value.encode_bytes()
        return bytes([self._selector]) + body

    @classmethod
    def decode_bytes(cls, data: bytes):
        sel = data[0]
        opt = cls.OPTIONS[sel]
        if opt is None:
            assert len(data) == 1
            return cls(sel, None)
        return cls(sel, opt.decode_bytes(data[1:]))

    def _child_changed(self, key):
        self._invalidate()

    def change(self, selector: int, value) -> None:
        """In-place re-tag (the sharding spec's ShardWork status flips:
        ``committee_work.status.change(selector=..., value=...)``)."""
        cls = type(self)
        assert 0 <= selector < len(cls.OPTIONS)
        opt = cls.OPTIONS[selector]
        if opt is None:
            assert value is None
            new_value = None
        else:
            new_value = opt.coerce_for_store(
                value if value is not None else opt.default(), self, "value"
            )
        self._selector = selector
        self._value = new_value
        self._invalidate()

    def __eq__(self, other):
        if isinstance(other, Union):
            return (
                type(self)._layout_key() == type(other)._layout_key()
                and self._selector == other._selector
                and self._value == other._value
            )
        return NotImplemented

    __hash__ = View.__hash__

    def __repr__(self):
        return f"{type(self).__name__}(selector={self._selector}, value={self._value!r})"


# ---------------------------------------------------------------------------
# Serialization helpers (offset scheme, ssz/simple-serialize.md:105-208)
# ---------------------------------------------------------------------------


def _encode_ordered(values, types) -> bytes:
    fixed_parts = []
    variable_parts = []
    for v, t in zip(values, types):
        if t.is_fixed_byte_length():
            fixed_parts.append(v.encode_bytes())
            variable_parts.append(b"")
        else:
            fixed_parts.append(None)
            variable_parts.append(v.encode_bytes())
    fixed_len = sum(
        len(p) if p is not None else OFFSET_BYTE_LENGTH for p in fixed_parts
    )
    out = io.BytesIO()
    offset = fixed_len
    for p, vp in zip(fixed_parts, variable_parts):
        if p is not None:
            out.write(p)
        else:
            out.write(offset.to_bytes(OFFSET_BYTE_LENGTH, "little"))
            offset += len(vp)
    for vp in variable_parts:
        out.write(vp)
    return out.getvalue()


def _decode_ordered(data: bytes, types) -> list:
    fixed_len = sum(
        t.type_byte_length() if t.is_fixed_byte_length() else OFFSET_BYTE_LENGTH
        for t in types
    )
    if len(data) < fixed_len:
        raise ValueError(f"SSZ: data shorter than fixed section ({len(data)} < {fixed_len})")
    # first pass: fixed parts + offsets
    pos = 0
    fixed_vals: list = []
    offsets: list = []
    for t in types:
        if t.is_fixed_byte_length():
            size = t.type_byte_length()
            fixed_vals.append(t.decode_bytes(data[pos : pos + size]))
            pos += size
        else:
            offsets.append((len(fixed_vals), int.from_bytes(data[pos : pos + 4], "little")))
            fixed_vals.append(None)
            pos += 4
    # validate offsets: first == end of fixed section, monotonic, within data
    for k, (_, off) in enumerate(offsets):
        if k == 0 and off != fixed_len:
            raise ValueError(f"SSZ: first offset {off} != fixed section length {fixed_len}")
        if k > 0 and off < offsets[k - 1][1]:
            raise ValueError("SSZ: offsets not monotonically increasing")
        if off > len(data):
            raise ValueError(f"SSZ: offset {off} beyond data length {len(data)}")
    if not offsets and len(data) != fixed_len:
        raise ValueError(f"SSZ: {len(data) - fixed_len} trailing bytes after fixed section")
    # second pass: slice variable parts
    for k, (idx, off) in enumerate(offsets):
        end = offsets[k + 1][1] if k + 1 < len(offsets) else len(data)
        t = types[idx]
        fixed_vals[idx] = t.decode_bytes(data[off:end])
    return fixed_vals


def _decode_variable_list(data: bytes, elem_type) -> list:
    first_offset = int.from_bytes(data[:4], "little")
    if first_offset % 4 != 0 or first_offset > len(data):
        raise ValueError("SSZ: invalid first offset in variable-size list")
    n = first_offset // 4
    offsets = [
        int.from_bytes(data[i * 4 : i * 4 + 4], "little") for i in range(n)
    ]
    for k in range(1, n):
        if offsets[k] < offsets[k - 1] or offsets[k] > len(data):
            raise ValueError("SSZ: invalid offsets in variable-size list")
    values = []
    for k in range(n):
        end = offsets[k + 1] if k + 1 < n else len(data)
        values.append(elem_type.decode_bytes(data[offsets[k] : end]))
    return values


def _collect_leaf_roots(node: Node, depth: int, count: int) -> list:
    """First `count` leaf chunk roots of a subtree, left to right (iterative)."""
    from .node import PackedLazySubtree

    out: list = []
    if count == 0:
        return out
    stack = [(node, depth)]
    while stack and len(out) < count:
        n, d = stack.pop()
        if d == 0:
            out.append(n._root if n._root is not None else merkle_root(n))
            continue
        if isinstance(n, PackedLazySubtree) and d == n._depth:
            # raw-bytes shortcut: the chunks ARE the stored buffer
            out.extend(n.leaf_roots(min(count - len(out), 1 << d)))
            continue
        assert isinstance(n, BranchNode)
        stack.append((n.right, d - 1))
        stack.append((n.left, d - 1))
    return out
