"""Bulk numpy <-> SSZ-backing transfers.

The TPU pipeline consumes whole-registry columns (effective balances,
participation flags, epochs) and produces whole-registry balance vectors.
Feeding those through the per-element view protocol costs O(n) Python
object churn per epoch; these helpers move data between numpy arrays and
the persistent Merkle backing directly at chunk granularity.

The reference has no analogue — its spec loops per validator (e.g.
process_rewards_and_penalties, phase0/beacon-chain.md:1439-1561); this
module is the seam that lets the compiled spec keep identical semantics
while the state transfer runs at memcpy speed.
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from .node import (
    BranchNode,
    Node,
    PackedLazySubtree,
    ZERO_HASHES,
    pack_chunks,
    subtree_fill_to_contents,
    uint_to_leaf,
    zero_node,
)
from .types import _collect_leaf_roots


def _packed_to_numpy(view, elem_bytes: int, np_dtype: str) -> np.ndarray:
    cls = type(view)
    node = view.get_backing()  # flush pending writes
    contents = node.left if cls.IS_LIST else node
    n = len(view)
    per_chunk = 32 // elem_bytes
    n_chunks = (n + per_chunk - 1) // per_chunk
    data = b"".join(_collect_leaf_roots(contents, cls.contents_depth(), n_chunks))
    return np.frombuffer(data, dtype=np_dtype)[:n]


def packed_lazy_contents(data: bytes, contents_depth: int) -> Node:
    """Contents node for a freshly bulk-written packed subtree: the dense
    power-of-two region is a ``PackedLazySubtree`` (eager level-loop root,
    children materialized only on demand), the zero spine above carries
    eagerly folded roots — a whole-column write costs one vectorized hash
    pass instead of ~n/32 leaf nodes plus a wave re-merkleization."""
    import hashlib

    n_chunks = (len(data) + 31) // 32
    if n_chunks == 0 or not any(data):
        return zero_node(contents_depth)
    dense_depth = (n_chunks - 1).bit_length()
    if dense_depth == 0:
        node: Node = pack_chunks(data)[0]
    else:
        node = PackedLazySubtree(data, dense_depth)
    root = node._root
    for d in range(dense_depth, contents_depth):
        parent = BranchNode(node, zero_node(d))
        root = parent._root = hashlib.sha256(root + ZERO_HASHES[d]).digest()
        node = parent
    return node


def _set_packed_from_numpy(view, arr: np.ndarray, lazy: bool = False) -> None:
    cls = type(view)
    if cls.IS_LIST:
        if len(arr) > cls.LENGTH:
            raise ValueError(f"{len(arr)} exceeds list limit {cls.LENGTH}")
    elif len(arr) != cls.LENGTH:
        raise ValueError(f"vector needs exactly {cls.LENGTH} elements")
    if lazy:
        contents = packed_lazy_contents(arr.tobytes(), cls.contents_depth())
    else:
        contents = subtree_fill_to_contents(
            pack_chunks(arr.tobytes()), cls.contents_depth()
        )
    backing = (
        BranchNode(contents, uint_to_leaf(len(arr))) if cls.IS_LIST else contents
    )
    # install fresh backing; drop any materialized value cache
    view._values = None
    view._dirty_chunks = set()
    view._backing = backing
    view._length = len(arr) if cls.IS_LIST else cls.LENGTH
    view._invalidate()  # parent (e.g. the BeaconState container) sees the change


def packed_uint64_to_numpy(view) -> np.ndarray:
    """List/Vector[uint64, N] -> int64 numpy array (values < 2^63 assumed,
    which Gwei balances satisfy by orders of magnitude)."""
    return _packed_to_numpy(view, 8, "<u8").astype(np.int64)


def set_packed_uint64_from_numpy(view, arr: np.ndarray) -> None:
    """Replace the full contents of a packed uint64 List/Vector in one
    bottom-up rebuild, preserving view/parent dirty-tracking semantics."""
    _set_packed_from_numpy(view, np.ascontiguousarray(arr, dtype="<u8"))


def packed_uint8_to_numpy(view) -> np.ndarray:
    """List/Vector[uint8, N] (e.g. altair participation flags) -> uint8."""
    return _packed_to_numpy(view, 1, np.uint8).copy()


def set_packed_uint8_from_numpy(view, arr: np.ndarray) -> None:
    """uint8 columns take the lazy-subtree write: participation flags are
    rewritten once per block and their subtree ROOT is always consumed by
    the next state-root check, while their chunk nodes are read back only
    on a resident-store miss — the eager-root/lazy-children split is
    exactly that access pattern.  (uint64 balance writes stay node-built:
    epoch processing rewrites them several times between root reads, so
    an eager root per write would hash MORE, not less.)"""
    _set_packed_from_numpy(
        view, np.ascontiguousarray(arr, dtype=np.uint8), lazy=True)


def bitlist_to_numpy(bits) -> np.ndarray:
    """Bool column of a ``Bitlist``/``Bitvector`` view (the per-bit view
    protocol costs a Python object per member; attestation batching reads
    whole aggregation-bit columns)."""
    inner = getattr(bits, "_bits", None)
    if inner is not None:  # the in-repo bit views hold a plain bool list
        return np.asarray(inner, dtype=bool)
    return np.fromiter(bits, dtype=bool, count=len(bits))


def composite_subtrees(view) -> list:
    """The backing subtree node of each element of a List/Vector of
    composites, left to right (no hashing is triggered)."""
    cls = type(view)
    node = view.get_backing()
    contents = node.left if cls.IS_LIST else node
    n = len(view)
    out: list = []
    if n == 0:
        return out
    stack = [(contents, cls.contents_depth())]
    while stack and len(out) < n:
        nd, d = stack.pop()
        if d == 0:
            out.append(nd)
            continue
        stack.append((nd.right, d - 1))
        stack.append((nd.left, d - 1))
    return out


def _field_path(field_index: int, depth: int):
    """Descent path (True=right) for a field at the given container depth."""
    return [bool((field_index >> (depth - 1 - b)) & 1) for b in range(depth)]


def _walk(node: Node, path) -> Node:
    for go_right in path:
        node = node.right if go_right else node.left
    return node


# --- validator registry columns ---------------------------------------------

# epoch-processing columns (phase0/beacon-chain.md Validator container; later
# forks may append fields — e.g. the early capella draft's
# fully_withdrawn_epoch — so paths are derived from the element class layout)
_V_FIELDS_U64 = (
    "effective_balance",
    "activation_eligibility_epoch",
    "activation_epoch",
    "exit_epoch",
    "withdrawable_epoch",
)


def validator_pubkeys(validators) -> list:
    """One walk over the registry subtrees -> every validator's pubkey as
    raw 48-byte strings.  The per-index view path
    (``state.validators[i].pubkey``) costs a tree descent + view
    materialization per read; attestation verification reads ~25k pubkeys
    per block, which makes this column the cheap representation."""
    et = type(validators).ELEM_TYPE
    path = _field_path(et._field_index["pubkey"], et._depth)
    out = []
    for sub in composite_subtrees(validators):
        node = _walk(sub, path)
        # Bytes48 backing: Branch(chunk0, chunk1) with 16 zero tail bytes
        out.append(node.left._root + node.right._root[:16])
    return out


class RootKeyedCache:
    """FIFO cache keyed by a view's tree root: any mutation produces a new
    root, so invalidation is automatic.  THE shared memoizer for derived
    registry representations (pubkey column here, numeric columns in
    ops/epoch_jax.registry_columns)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._store: Dict[bytes, object] = {}

    def get(self, view, build, on_insert=None):
        """Cached value for ``view``; on a miss, builds and inserts.
        ``on_insert(store, root)`` fires after a fresh insert — the stf
        cache transaction uses it to record the insert for rollback."""
        root = bytes(view.hash_tree_root())
        hit = self._store.get(root)
        if hit is None:
            if len(self._store) >= self.capacity:
                self._store.pop(next(iter(self._store)))
            hit = build(view)
            self._store[root] = hit
            if on_insert is not None:
                on_insert(self._store, root)
        return hit


# 2 entries cover the pre/post-epoch registries a transition touches
_PUBKEY_CACHE = RootKeyedCache(2)


def cached_validator_pubkeys(validators) -> list:
    return _PUBKEY_CACHE.get(validators, validator_pubkeys)


_PUBKEY_INDEX_CACHE = RootKeyedCache(2)


def cached_pubkey_index(validators) -> Dict[bytes, int]:
    """pubkey bytes -> FIRST validator index carrying it (list.index
    semantics, which is what the altair sync-committee reward loop's
    ``all_pubkeys.index(pubkey)`` resolves to on duplicate keys)."""

    def build(v):
        index_of: Dict[bytes, int] = {}
        for i, pk in enumerate(cached_validator_pubkeys(v)):
            index_of.setdefault(pk, i)
        return index_of

    return _PUBKEY_INDEX_CACHE.get(validators, build)


def validator_columns(validators) -> Dict[str, np.ndarray]:
    """One walk over the registry subtrees -> all epoch-processing columns.

    Field paths come from the element class's own layout (field count sets
    the tree depth).  Saturates epochs at int64 max (FAR_FUTURE_EPOCH =
    2^64-1 would wrap)."""
    et = type(validators).ELEM_TYPE
    depth = et._depth
    findex = et._field_index
    subs = composite_subtrees(validators)
    n = len(subs)
    cols: Dict[str, np.ndarray] = {}
    u64_paths = {
        name: _field_path(findex[name], depth) for name in _V_FIELDS_U64
    }
    slashed_path = _field_path(findex["slashed"], depth)
    raw = {name: bytearray() for name in u64_paths}
    slashed = np.zeros(n, dtype=bool)
    for i, sub in enumerate(subs):
        for name, path in u64_paths.items():
            raw[name] += _walk(sub, path)._root[:8]
        slashed[i] = _walk(sub, slashed_path)._root[0] != 0
    for name, buf in raw.items():
        u = np.frombuffer(bytes(buf), dtype="<u8")
        cols[name] = np.minimum(u, np.uint64(2**63 - 1)).astype(np.int64)
    cols["slashed"] = slashed
    return cols
