"""SHA-256 hashing backends for SSZ merkleization.

This is the hasher plugin seam: the reference hard-codes
``hashlib.sha256`` (eth2spec/utils/hash_function.py:8-9); here the merkle
layer-hash is pluggable so a whole tree layer can be hashed as one batch —
on host via hashlib, or on TPU via the packed-uint32 JAX kernel in
``consensus_specs_tpu.ops.sha256_jax``.

The batch API is ``hash_layer(blocks)``: ``blocks`` is a list of 64-byte
inputs (two concatenated 32-byte child roots); the result is the list of
32-byte parent digests.  Merkleization in ``node.py`` always funnels
through the active backend, so swapping backends changes performance only,
never bytes.
"""
from __future__ import annotations

import hashlib
from typing import Callable, Dict, List

# -- single-shot hash (used by spec `hash()` and small paths) ---------------


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


# -- batched layer hashing ---------------------------------------------------


def _hashlib_hash_layer(blocks: List[bytes]) -> List[bytes]:
    h = hashlib.sha256
    return [h(b).digest() for b in blocks]


_BACKENDS: Dict[str, Callable[[List[bytes]], List[bytes]]] = {
    "hashlib": _hashlib_hash_layer,
}

_active_name = "hashlib"
_active: Callable[[List[bytes]], List[bytes]] = _hashlib_hash_layer

# Batches smaller than this always use hashlib regardless of the active
# backend: device dispatch overhead dominates tiny layers.
MIN_DEVICE_BATCH = 256

# Trees smaller than this (total branch nodes) are hashed per-layer on
# host even when a device wave backend is active.
MIN_DEVICE_TREE = 4096


def register_backend(name: str, fn: Callable[[List[bytes]], List[bytes]]) -> None:
    _BACKENDS[name] = fn


# -- whole-tree wave hashing (optional backend capability) ------------------
#
# A wave backend runs an entire merkle wave schedule as ONE device
# program: ``fn(known, waves) -> digests`` where ``known`` is the list of
# already-rooted 32-byte child digests, ``waves`` is a list of
# (left_idx, right_idx) int32 index-array pairs into the digest pool
# (known rows first, then every prior wave's outputs), and the result is
# the concatenated 32-byte outputs of every wave.  This removes the
# per-tree-level host<->device round trip that dominates layered hashing
# on high-latency links.

_WAVE_BACKENDS: Dict[str, Callable] = {}


def register_wave_backend(name: str, fn: Callable) -> None:
    _WAVE_BACKENDS[name] = fn


def get_wave_hasher():
    """The active backend's whole-tree wave hasher, or None if the active
    backend hashes per-layer only (hashlib default)."""
    return _WAVE_BACKENDS.get(_active_name)


# Device backends register lazily on first request (importing them pulls
# in jax, which SSZ-only consumers must not pay for).
_LAZY_BACKENDS = {
    "jax": "consensus_specs_tpu.ops.sha256_jax",
    "pallas": "consensus_specs_tpu.ops.sha256_pallas",
}


def set_backend(name: str) -> None:
    global _active, _active_name
    if name not in _BACKENDS and name in _LAZY_BACKENDS:
        import importlib

        module = importlib.import_module(_LAZY_BACKENDS[name])
        register_backend(name, module.hash_layer)
        if hasattr(module, "hash_waves"):
            register_wave_backend(name, module.hash_waves)
    _active = _BACKENDS[name]
    _active_name = name


def get_backend_name() -> str:
    return _active_name


def hash_layer(blocks: List[bytes]) -> List[bytes]:
    """Hash a list of 64-byte blocks into 32-byte digests."""
    if not blocks:
        return []
    if _active is not _hashlib_hash_layer and len(blocks) < MIN_DEVICE_BATCH:
        return _hashlib_hash_layer(blocks)
    return _active(blocks)


# -- zero-subtree roots ------------------------------------------------------
# zerohashes[i] = root of a depth-i tree of zero chunks
# (reference: eth2spec/utils/merkle_minimal.py:7-9)

ZERO_HASHES: List[bytes] = [b"\x00" * 32]
for _ in range(64):
    ZERO_HASHES.append(sha256(ZERO_HASHES[-1] + ZERO_HASHES[-1]))
