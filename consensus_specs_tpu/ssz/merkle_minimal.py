"""Standalone reference merkleizer (behavioral twin of
eth2spec/utils/merkle_minimal.py:7-89) used as the correctness oracle for
the persistent-node merkleization and by deposit-proof helpers.
"""
from __future__ import annotations

from typing import List, Sequence

from .hashing import ZERO_HASHES, sha256


def calc_merkle_tree_from_leaves(values: Sequence[bytes], layer_count: int = 32) -> List[List[bytes]]:
    values = list(values)
    tree = [values[:]]
    for h in range(layer_count):
        if len(values) % 2 == 1:
            values.append(ZERO_HASHES[h])
        values = [sha256(values[i] + values[i + 1]) for i in range(0, len(values), 2)]
        tree.append(values[:])
    return tree


def get_merkle_root(values: Sequence[bytes], pad_to: int = 1) -> bytes:
    layer_count = (pad_to - 1).bit_length() if pad_to > 1 else 0
    if len(values) == 0:
        return ZERO_HASHES[layer_count]
    return calc_merkle_tree_from_leaves(values, layer_count)[-1][0]


def get_merkle_proof(tree: List[List[bytes]], item_index: int, tree_len: int = None) -> List[bytes]:
    proof = []
    for i in range(tree_len if tree_len is not None else len(tree)):
        subindex = (item_index // 2**i) ^ 1
        proof.append(tree[i][subindex] if subindex < len(tree[i]) else ZERO_HASHES[i])
    return proof


def merkleize_chunks(chunks: Sequence[bytes], limit: int = None) -> bytes:
    """Streaming merkleization per ssz/simple-serialize.md:210-248."""
    count = len(chunks)
    if limit is None:
        limit = count
    assert count <= limit, f"merkleize: {count} chunks exceeds limit {limit}"
    if limit == 0:
        return ZERO_HASHES[0]
    depth = (limit - 1).bit_length() if limit > 1 else 0
    if count == 0:
        return ZERO_HASHES[depth]
    layer = [bytes(c) for c in chunks]
    for h in range(depth):
        if len(layer) % 2 == 1:
            layer.append(ZERO_HASHES[h])
        layer = [sha256(layer[i] + layer[i + 1]) for i in range(0, len(layer), 2)]
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return sha256(root + length.to_bytes(32, "little"))


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return sha256(root + selector.to_bytes(32, "little"))
