"""SSZ facade: serialize / hash_tree_root / copy / uint_to_bytes.

Mirrors the reference seam eth2spec/utils/ssz/ssz_impl.py:8-25, which is
the interface the compiled spec modules call.  ``hash_tree_root`` routes
through the persistent node layer, whose layer hashing is backend-
pluggable (see hashing.py) — that is where the TPU batch path plugs in.
"""
from __future__ import annotations

from . import types as tp
from .node import merkle_root
from .types import Bytes32, View, boolean, uint


def serialize(obj) -> bytes:
    return obj.encode_bytes()


def hash_tree_root(obj) -> Bytes32:
    if isinstance(obj, (uint, boolean)):
        return Bytes32(int(obj).to_bytes(32, "little"))
    if isinstance(obj, (tp.ByteVector, tp.ByteList)):
        return Bytes32(obj.hash_tree_root())
    if isinstance(obj, View):
        return Bytes32(merkle_root(obj.get_backing()))
    raise TypeError(f"cannot hash_tree_root {type(obj).__name__}")


def copy(obj):
    if isinstance(obj, View):
        return obj.copy()
    return obj  # immutable value types


def uint_to_bytes(n: uint) -> bytes:
    """Serialize a uint to its type's byte length (little-endian).

    Reference: eth2spec custom `uint_to_bytes` (setup.py injects it from
    the uint type's byte length).
    """
    return int(n).to_bytes(type(n).TYPE_BYTE_LENGTH, "little")
