"""Persistent (structurally shared) binary Merkle tree nodes.

This is the remerkleable-equivalent backing layer (reference seam:
eth2spec/utils/ssz/ssz_impl.py:8-13 routes ``hash_tree_root`` through
``View.get_backing().merkle_root()``).  Key properties kept from the
reference design, because the test framework's zero-copy state cache
depends on them (reference: eth2spec/test/context.py:105-125):

  * nodes are immutable; updates copy the path from root to leaf
  * every node memoizes its Merkle root, so unchanged subtrees are never
    re-hashed (incremental ``hash_tree_root``)
  * zero-subtrees of every depth are globally shared singletons

TPU-first difference: root computation is *layer-batched*.  Instead of
recursive child-then-parent hashing, all unhashed nodes are collected and
hashed in ready-waves through ``hashing.hash_layer`` — one device dispatch
per tree level — so a dirty 400k-validator registry becomes a handful of
large SHA-256 batches instead of ~10^5 single hashes.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .hashing import (
    MIN_DEVICE_TREE,
    ZERO_HASHES,
    get_wave_hasher,
    hash_layer,
)


class Node:
    __slots__ = ("_root",)


class LeafNode(Node):
    __slots__ = ()

    def __init__(self, root: bytes):
        assert len(root) == 32
        self._root = root

    @property
    def root(self) -> bytes:
        return self._root

    def __repr__(self) -> str:
        return f"Leaf({self._root.hex()[:16]})"


class BranchNode(Node):
    __slots__ = ("left", "right")

    def __init__(self, left: Node, right: Node):
        self.left = left
        self.right = right
        self._root: Optional[bytes] = None

    def __repr__(self) -> str:
        return f"Branch(root={'?' if self._root is None else self._root.hex()[:16]})"


ZERO_LEAF = LeafNode(b"\x00" * 32)

# zero_node(d): root of a fully-zero subtree of depth d, globally shared.
_ZERO_NODES: List[Node] = [ZERO_LEAF]
for _d in range(1, 64):
    _b = BranchNode(_ZERO_NODES[-1], _ZERO_NODES[-1])
    _b._root = ZERO_HASHES[_d]
    _ZERO_NODES.append(_b)


def zero_node(depth: int) -> Node:
    return _ZERO_NODES[depth]


def merkle_root(node: Node) -> bytes:
    """Compute (and memoize) the root, hashing whole ready-waves at once."""
    if node._root is not None:
        return node._root
    # Collect every unhashed branch reachable from `node` (deduped: the tree
    # is a DAG under structural sharing).
    pending: List[BranchNode] = []
    seen = set()
    stack: List[Node] = [node]
    while stack:
        n = stack.pop()
        if n._root is not None or id(n) in seen:
            continue
        seen.add(id(n))
        pending.append(n)  # type: ignore[arg-type]
        if n.left._root is None:  # type: ignore[union-attr]
            stack.append(n.left)  # type: ignore[union-attr]
        if n.right._root is None:  # type: ignore[union-attr]
            stack.append(n.right)  # type: ignore[union-attr]
    # Topological ready-waves: a node is ready once both children have
    # roots or are scheduled in an earlier wave.
    waves: List[List[BranchNode]] = []
    scheduled = set()
    rest = pending
    while rest:
        ready: List[BranchNode] = []
        later: List[BranchNode] = []
        for n in rest:
            if ((n.left._root is not None or id(n.left) in scheduled)
                    and (n.right._root is not None or id(n.right) in scheduled)):
                ready.append(n)
            else:
                later.append(n)
        for n in ready:
            scheduled.add(id(n))
        waves.append(ready)
        rest = later

    wave_hasher = get_wave_hasher() if len(seen) >= MIN_DEVICE_TREE else None
    if wave_hasher is not None:
        _hash_waves_on_device(waves, wave_hasher)
    else:
        for wave in waves:
            digests = hash_layer([n.left._root + n.right._root for n in wave])
            for n, d in zip(wave, digests):
                n._root = d
    return node._root  # type: ignore[return-value]


def _hash_waves_on_device(waves: "List[List[BranchNode]]", wave_hasher) -> None:
    """Run the whole wave schedule as one device program: upload the
    deduped known child digests once, gather+compress every level inside
    a single dispatch, download all produced digests once (the per-level
    round trip is what dominates layered hashing over slow links)."""
    import numpy as np

    known: List[bytes] = []
    known_index = {}
    for wave in waves:
        for n in wave:
            for c in (n.left, n.right):
                if c._root is not None and id(c) not in known_index:
                    known_index[id(c)] = len(known)
                    known.append(c._root)
    out_index = {}
    pos = len(known)
    for wave in waves:
        for n in wave:
            out_index[id(n)] = pos
            pos += 1

    def cidx(c):
        return known_index[id(c)] if c._root is not None else out_index[id(c)]

    index_waves = [
        (np.array([cidx(n.left) for n in wave], dtype=np.int32),
         np.array([cidx(n.right) for n in wave], dtype=np.int32))
        for wave in waves
    ]
    digests = wave_hasher(known, index_waves)
    k = 0
    for wave in waves:
        for n in wave:
            n._root = digests[k]
            k += 1


def branch_with_root(left: Node, right: Node, root: bytes) -> BranchNode:
    """A ``BranchNode`` with its memoized root pre-installed — the
    deserialization constructor (persist/store.py's tree codec rebuilds
    checkpointed states from digest-verified artifacts; recomputing
    every root would re-pay the full-tree hash the memo exists to skip).
    Owner-side on purpose: installing ``_root`` anywhere else is a CC01
    violation, because only a verified byte stream may vouch for it."""
    node = BranchNode(left, right)
    node._root = root
    return node


def get_subtree(node: Node, depth: int, index: int) -> Node:
    """Descend `depth` levels; bit k of `index` (MSB first) picks the child."""
    for k in range(depth - 1, -1, -1):
        assert isinstance(node, BranchNode), "descended past a leaf"
        node = node.right if (index >> k) & 1 else node.left
    return node


def with_subtree(node: Node, depth: int, index: int, subtree: Node) -> Node:
    """Return a new tree with the subtree at (depth, index) replaced (path copy)."""
    if depth == 0:
        return subtree
    assert isinstance(node, BranchNode)
    bit = (index >> (depth - 1)) & 1
    if bit:
        return BranchNode(node.left, with_subtree(node.right, depth - 1, index, subtree))
    return BranchNode(with_subtree(node.left, depth - 1, index, subtree), node.right)


def with_updated_subtrees(
    node: Node, depth: int, updates: Sequence[Tuple[int, Node]]
) -> Node:
    """Bulk path-copy update: `updates` is a sorted list of (index, subtree).

    Untouched subtrees are returned by identity, preserving their memoized
    roots — this is what keeps epoch-boundary registry updates incremental.
    """
    if not updates:
        return node
    if depth == 0:
        assert len(updates) == 1
        return updates[0][1]
    half = 1 << (depth - 1)
    split = 0
    while split < len(updates) and updates[split][0] < half:
        split += 1
    left_updates = updates[:split]
    right_updates = [(i - half, n) for i, n in updates[split:]]
    if isinstance(node, BranchNode):
        left, right = node.left, node.right
    else:
        raise AssertionError("descended past a leaf")
    new_left = with_updated_subtrees(left, depth - 1, left_updates) if left_updates else left
    new_right = (
        with_updated_subtrees(right, depth - 1, right_updates) if right_updates else right
    )
    if new_left is left and new_right is right:
        return node
    return BranchNode(new_left, new_right)


class PackedLazySubtree(BranchNode):
    """A packed subtree held as raw bytes with an EAGER root and LAZY
    children — the tree half of the resident-column contract
    (stf/columns.py): per-block bulk writes of a whole packed column
    (participation flags are the canonical case) need the subtree's ROOT
    at the next state-root check, but its ~n/32 chunk nodes only if some
    later consumer actually descends — and the resident column store
    answers almost every read before the tree is touched.  The root comes
    from one vectorized hashlib level-loop over the raw bytes (~2x the
    node-layer wave hash, with zero node churn); ``left``/``right``
    materialize on first access (a per-element read, an SSZ encode, a
    path-copy write landing inside the subtree) and recursively stay
    lazy, so a single-leaf descent builds one path, not the whole tree.

    Instances are immutable like every node: ``_data`` is private bytes,
    children memoize, and the eager ``_root`` makes ``merkle_root`` a
    field read."""

    __slots__ = ("_data", "_depth", "_l", "_r")

    def __init__(self, data: bytes, depth: int, root: bytes = None):
        self._data = data
        self._depth = depth
        self._l = self._r = None
        self._root = root if root is not None else packed_subtree_root(
            data, depth)

    @property
    def left(self) -> Node:
        if self._l is None:
            self._l = self._child(0)
        return self._l

    @property
    def right(self) -> Node:
        if self._r is None:
            self._r = self._child(1)
        return self._r

    def _child(self, side: int) -> Node:
        d = self._depth - 1
        half = 32 << d  # bytes per half subtree
        data = self._data[side * half: (side + 1) * half]
        if not any(data):
            return zero_node(d)
        if d == 0:
            return LeafNode(data.ljust(32, b"\x00"))
        return PackedLazySubtree(data, d)

    def leaf_roots(self, count: int) -> List[bytes]:
        """First ``count`` chunk roots straight off the raw bytes — the
        bulk-unpack shortcut (ssz/types._collect_leaf_roots)."""
        data = self._data
        need = count * 32
        if len(data) < need:
            data = data.ljust(need, b"\x00")
        return [data[i: i + 32] for i in range(0, need, 32)]


def packed_subtree_root(data: bytes, depth: int) -> bytes:
    """Root of a depth-``depth`` subtree whose leading chunks are ``data``
    (zero chunks beyond): one hashlib level-loop over contiguous buffers,
    folding the all-zero tail with the shared zero hashes instead of
    hashing it."""
    from hashlib import sha256

    n_chunks = (len(data) + 31) // 32
    assert n_chunks <= (1 << depth)
    if n_chunks == 0 or not any(data):
        return ZERO_HASHES[depth]
    if len(data) % 32:
        data = data + b"\x00" * (32 - len(data) % 32)
    level = data
    for d in range(depth):
        if (len(level) // 32) & 1:
            level += ZERO_HASHES[d]
        level = b"".join(
            sha256(level[i: i + 64]).digest()
            for i in range(0, len(level), 64))
    return level


def subtree_fill_to_contents(nodes: Sequence[Node], depth: int) -> Node:
    """Build a depth-`depth` subtree whose first len(nodes) leaves are `nodes`,
    zero-padded on the right (shared zero subtrees)."""
    n = len(nodes)
    assert n <= (1 << depth)
    if n == 0:
        return zero_node(depth)
    if depth == 0:
        return nodes[0]
    layer: List[Node] = list(nodes)
    for d in range(depth):
        odd = len(layer) & 1
        pairs = len(layer) >> 1
        nxt: List[Node] = [BranchNode(layer[2 * i], layer[2 * i + 1]) for i in range(pairs)]
        if odd:
            nxt.append(BranchNode(layer[-1], zero_node(d)))
        layer = nxt
    assert len(layer) == 1
    return layer[0]


def pack_chunks(data: bytes) -> List[LeafNode]:
    """Split serialized bytes into zero-padded 32-byte chunk leaves."""
    if len(data) % 32:
        data = data + b"\x00" * (32 - len(data) % 32)
    return [LeafNode(data[i : i + 32]) for i in range(0, len(data), 32)]


def uint_to_leaf(value: int) -> LeafNode:
    return LeafNode(value.to_bytes(32, "little"))
