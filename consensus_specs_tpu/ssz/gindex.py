"""Generalized indices over SSZ types (reference: ssz/merkle-proofs.md:58-189).

Provides ``get_generalized_index(type, *path)`` used by the altair light
client sync protocol (FINALIZED_ROOT_INDEX / NEXT_SYNC_COMMITTEE_INDEX)
and merkle-proof test helpers, plus single-branch proof construction and
verification against a view's backing.
"""
from __future__ import annotations

from typing import List as PyList

from .node import BranchNode, Node, get_subtree, merkle_root
from .types import Bitlist, Bitvector, ByteList, ByteVector, Container, List, Vector, _HomogeneousBase, ceil_log2

GeneralizedIndex = int


def get_generalized_index(typ, *path) -> GeneralizedIndex:
    """Walk `path` (field names / element indices / '__len__') from `typ`."""
    gindex = 1
    for p in path:
        if p == "__len__":
            assert isinstance(typ, type) and issubclass(typ, (List, Bitlist, ByteList))
            gindex = gindex * 2 + 1
            typ = None
            continue
        if isinstance(typ, type) and issubclass(typ, Container):
            idx = typ._field_index[p]
            gindex = (gindex << typ._depth) | idx
            typ = typ._field_types[idx]
        elif isinstance(typ, type) and issubclass(typ, (List, Vector, Bitlist, Bitvector, ByteList, ByteVector)):
            i = int(p)
            if issubclass(typ, (List, Bitlist, ByteList)):
                gindex = gindex * 2  # contents side of the length mixin
            if issubclass(typ, _HomogeneousBase):
                depth = typ.contents_depth()
                if typ._is_packed():
                    per = typ._elems_per_chunk()
                    gindex = (gindex << depth) | (i // per)
                    typ = None
                else:
                    gindex = (gindex << depth) | i
                    typ = typ.ELEM_TYPE
            elif issubclass(typ, (Bitlist, Bitvector)):
                n_chunks_depth = ceil_log2((typ.LENGTH + 255) // 256)
                gindex = (gindex << n_chunks_depth) | (i // 256)
                typ = None
            else:  # ByteVector / ByteList
                byte_len = typ.TYPE_BYTE_LENGTH if issubclass(typ, ByteVector) else typ.LIMIT
                n_chunks_depth = ceil_log2((byte_len + 31) // 32)
                gindex = (gindex << n_chunks_depth) | (i // 32)
                typ = None
        else:
            raise TypeError(f"cannot index into {typ} with {p}")
    return gindex


def get_generalized_index_length(index: GeneralizedIndex) -> int:
    """Depth of a generalized index (ssz/merkle-proofs.md)."""
    return index.bit_length() - 1


def get_subtree_at_gindex(node: Node, gindex: GeneralizedIndex) -> Node:
    depth = gindex.bit_length() - 1
    return get_subtree(node, depth, gindex - (1 << depth))


def build_proof(node: Node, gindex: GeneralizedIndex) -> PyList[bytes]:
    """Sibling hashes along the branch, leaf-side first (matches
    is_valid_merkle_branch ordering, phase0/beacon-chain.md:742-753)."""
    depth = gindex.bit_length() - 1
    index = gindex - (1 << depth)
    proof: PyList[bytes] = []
    cur = node
    for k in range(depth - 1, -1, -1):
        assert isinstance(cur, BranchNode)
        bit = (index >> k) & 1
        sibling = cur.left if bit else cur.right
        proof.append(merkle_root(sibling))
        cur = cur.right if bit else cur.left
    return list(reversed(proof))


# --- multiproofs (ssz/merkle-proofs.md:249-326) -----------------------------


def generalized_index_sibling(index: GeneralizedIndex) -> GeneralizedIndex:
    return index ^ 1


def generalized_index_parent(index: GeneralizedIndex) -> GeneralizedIndex:
    return index // 2


def get_branch_indices(tree_index: GeneralizedIndex) -> PyList[GeneralizedIndex]:
    """Sister-node chain a single-leaf proof consists of."""
    o = [generalized_index_sibling(tree_index)]
    while o[-1] > 1:
        o.append(generalized_index_sibling(generalized_index_parent(o[-1])))
    return o[:-1]


def get_path_indices(tree_index: GeneralizedIndex) -> PyList[GeneralizedIndex]:
    """The leaf's own chain of ancestors up to (excluding) the root."""
    o = [tree_index]
    while o[-1] > 1:
        o.append(generalized_index_parent(o[-1]))
    return o[:-1]


def get_helper_indices(indices) -> PyList[GeneralizedIndex]:
    """Indices of all extra nodes a combined multiproof needs, in the
    canonical descending order."""
    all_helper_indices = set()
    all_path_indices = set()
    for index in indices:
        all_helper_indices.update(get_branch_indices(index))
        all_path_indices.update(get_path_indices(index))
    return sorted(all_helper_indices - all_path_indices, reverse=True)


def calculate_multi_merkle_root(leaves, proof, indices) -> bytes:
    """Root implied by ``leaves`` at ``indices`` plus the helper ``proof``
    nodes (in get_helper_indices order)."""
    from .hashing import sha256

    assert len(leaves) == len(indices)
    helper_indices = get_helper_indices(indices)
    assert len(proof) == len(helper_indices)
    objects = {
        **{index: bytes(node) for index, node in zip(indices, leaves)},
        **{index: bytes(node) for index, node in zip(helper_indices, proof)},
    }
    keys = sorted(objects.keys(), reverse=True)
    pos = 0
    while pos < len(keys):
        k = keys[pos]
        if k in objects and k ^ 1 in objects and k // 2 not in objects:
            objects[k // 2] = sha256(
                objects[(k | 1) ^ 1] + objects[k | 1]
            )
            keys.append(k // 2)
        pos += 1
    return objects[1]


def verify_merkle_multiproof(leaves, proof, indices, root: bytes) -> bool:
    return calculate_multi_merkle_root(leaves, proof, indices) == bytes(root)


def build_multiproof(node: Node, gindices) -> PyList[bytes]:
    """Helper-node roots for a combined proof of all ``gindices`` against
    a backing tree, in get_helper_indices order."""
    return [
        merkle_root(get_subtree_at_gindex(node, helper))
        for helper in get_helper_indices(gindices)
    ]
