"""Generalized indices over SSZ types (reference: ssz/merkle-proofs.md:58-189).

Provides ``get_generalized_index(type, *path)`` used by the altair light
client sync protocol (FINALIZED_ROOT_INDEX / NEXT_SYNC_COMMITTEE_INDEX)
and merkle-proof test helpers, plus single-branch proof construction and
verification against a view's backing.
"""
from __future__ import annotations

from typing import List as PyList

from .node import BranchNode, Node, get_subtree, merkle_root
from .types import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Union,
    Vector,
    _HomogeneousBase,
    ceil_log2,
    is_basic_type,
)

GeneralizedIndex = int


def get_generalized_index(typ, *path) -> GeneralizedIndex:
    """Walk `path` (field names / element indices / '__len__') from `typ`."""
    gindex = 1
    for p in path:
        if p == "__len__":
            assert isinstance(typ, type) and issubclass(typ, (List, Bitlist, ByteList))
            gindex = gindex * 2 + 1
            typ = None
            continue
        if isinstance(typ, type) and issubclass(typ, Container):
            idx = typ._field_index[p]
            gindex = (gindex << typ._depth) | idx
            typ = typ._field_types[idx]
        elif isinstance(typ, type) and issubclass(typ, (List, Vector, Bitlist, Bitvector, ByteList, ByteVector)):
            i = int(p)
            if issubclass(typ, (List, Bitlist, ByteList)):
                gindex = gindex * 2  # contents side of the length mixin
            if issubclass(typ, _HomogeneousBase):
                depth = typ.contents_depth()
                if typ._is_packed():
                    per = typ._elems_per_chunk()
                    gindex = (gindex << depth) | (i // per)
                    typ = None
                else:
                    gindex = (gindex << depth) | i
                    typ = typ.ELEM_TYPE
            elif issubclass(typ, (Bitlist, Bitvector)):
                n_chunks_depth = ceil_log2((typ.LENGTH + 255) // 256)
                gindex = (gindex << n_chunks_depth) | (i // 256)
                typ = None
            else:  # ByteVector / ByteList
                byte_len = typ.TYPE_BYTE_LENGTH if issubclass(typ, ByteVector) else typ.LIMIT
                n_chunks_depth = ceil_log2((byte_len + 31) // 32)
                gindex = (gindex << n_chunks_depth) | (i // 32)
                typ = None
        else:
            raise TypeError(f"cannot index into {typ} with {p}")
    return gindex


def get_generalized_index_length(index: GeneralizedIndex) -> int:
    """Depth of a generalized index (ssz/merkle-proofs.md)."""
    return index.bit_length() - 1


def get_subtree_at_gindex(node: Node, gindex: GeneralizedIndex) -> Node:
    depth = gindex.bit_length() - 1
    return get_subtree(node, depth, gindex - (1 << depth))


def build_proof(node: Node, gindex: GeneralizedIndex) -> PyList[bytes]:
    """Sibling hashes along the branch, leaf-side first (matches
    is_valid_merkle_branch ordering, phase0/beacon-chain.md:742-753)."""
    depth = gindex.bit_length() - 1
    index = gindex - (1 << depth)
    proof: PyList[bytes] = []
    cur = node
    for k in range(depth - 1, -1, -1):
        assert isinstance(cur, BranchNode)
        bit = (index >> k) & 1
        sibling = cur.left if bit else cur.right
        proof.append(merkle_root(sibling))
        cur = cur.right if bit else cur.left
    return list(reversed(proof))
