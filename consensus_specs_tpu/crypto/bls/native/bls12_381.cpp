// Native BLS12-381 backend: the "fast host path" of the BLS selector
// (role analogous to the reference's milagro/Rust backend selectable in
// eth2spec/utils/bls.py:8-30; implementation is from scratch).
//
// Design: 6x64-bit Montgomery Fp, Karatsuba Fp2/Fp6/Fp12 towers mirroring
// the formulas of the pure-Python oracle (crypto/bls/fields.py), affine
// Miller loop on the twist with sparse line evaluation, final exponentiation
// via Frobenius easy part + plain hard-part exponent.  All constants come
// from the generated bls_constants.h, each validated against the Python
// oracle at generation time.  Differential tests in
// tests/crypto/test_native_bls.py pin every exported function to the oracle.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 bls12_381.cpp -o _bls.so

#include <stdint.h>
#include <stddef.h>
#include <stdlib.h>
#include <string.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "bls_constants.h"

typedef unsigned __int128 u128;

// ===========================================================================
// Thread pool: fork-join parallel_for over an index space
// ===========================================================================
// Sized once at init from hardware_concurrency, overridable with the
// CSTPU_BLS_THREADS environment variable (the operator knob documented in
// docs/architecture.md; 1 disables threading entirely).  Workers pull
// indices from a shared atomic counter, so ragged per-index costs (lane
// chunks, hash_to_curve misses) self-balance.  The calling thread
// participates — T==1 degenerates to the plain serial loop with no thread
// creation at all, which keeps the 1-vCPU bench host honest.  Nested
// calls (a worker's body reaching another parallel_for, e.g. the per-group
// G1 fold dispatching Pippenger whose window passes also fan out) run
// serial on the worker — the outer region already owns every core, so a
// second fan-out would only oversubscribe T× with no extra parallelism.
static unsigned BLS_THREADS = 1;
static thread_local bool IN_PARALLEL_REGION = false;

template <class Fn>
static void parallel_for(size_t n, const Fn &fn) {
    unsigned T = BLS_THREADS;
    if (T <= 1 || n <= 1 || IN_PARALLEL_REGION) {
        for (size_t i = 0; i < n; i++) fn(i);
        return;
    }
    unsigned workers = (unsigned)std::min<size_t>(T, n);
    std::atomic<size_t> next{0};
    auto run = [&]() {
        IN_PARALLEL_REGION = true;
        for (size_t i; (i = next.fetch_add(1)) < n;) fn(i);
        IN_PARALLEL_REGION = false;
    };
    std::vector<std::thread> pool;
    pool.reserve(workers - 1);
    for (unsigned t = 0; t + 1 < workers; t++) pool.emplace_back(run);
    run();
    for (auto &th : pool) th.join();
}

static double monotonic_seconds() {
    return std::chrono::duration<double>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
}

// ===========================================================================
// Fp: integers mod p in Montgomery form (R = 2^384)
// ===========================================================================

struct fp {
    uint64_t l[6];
};

static inline bool fp_is_zero_raw(const fp &a) {
    return (a.l[0] | a.l[1] | a.l[2] | a.l[3] | a.l[4] | a.l[5]) == 0;
}

static inline int limbs_cmp(const uint64_t a[6], const uint64_t b[6]) {
    for (int i = 5; i >= 0; i--) {
        if (a[i] < b[i]) return -1;
        if (a[i] > b[i]) return 1;
    }
    return 0;
}

static inline void limbs_sub(uint64_t r[6], const uint64_t a[6], const uint64_t b[6]) {
    u128 borrow = 0;
    for (int i = 0; i < 6; i++) {
        u128 cur = (u128)a[i] - b[i] - (uint64_t)borrow;
        r[i] = (uint64_t)cur;
        borrow = (cur >> 64) & 1;  // 1 when borrowed
    }
}

static inline void fp_add(fp &r, const fp &a, const fp &b) {
    u128 carry = 0;
    uint64_t t[6];
    for (int i = 0; i < 6; i++) {
        u128 cur = (u128)a.l[i] + b.l[i] + (uint64_t)carry;
        t[i] = (uint64_t)cur;
        carry = cur >> 64;
    }
    // 2p < 2^384, so no carry out; reduce once if >= p
    if (limbs_cmp(t, P_LIMBS) >= 0) limbs_sub(r.l, t, P_LIMBS);
    else memcpy(r.l, t, sizeof(t));
}

static inline void fp_sub(fp &r, const fp &a, const fp &b) {
    u128 borrow = 0;
    uint64_t t[6];
    for (int i = 0; i < 6; i++) {
        u128 cur = (u128)a.l[i] - b.l[i] - (uint64_t)borrow;
        t[i] = (uint64_t)cur;
        borrow = (cur >> 64) & 1;
    }
    if (borrow) {
        u128 carry = 0;
        for (int i = 0; i < 6; i++) {
            u128 cur = (u128)t[i] + P_LIMBS[i] + (uint64_t)carry;
            t[i] = (uint64_t)cur;
            carry = cur >> 64;
        }
    }
    memcpy(r.l, t, sizeof(t));
}

static inline void fp_neg(fp &r, const fp &a) {
    if (fp_is_zero_raw(a)) {
        memset(r.l, 0, sizeof(r.l));
        return;
    }
    limbs_sub(r.l, P_LIMBS, a.l);
}

// CIOS Montgomery multiplication: r = a*b*R^-1 mod p
static void fp_mul(fp &r, const fp &a, const fp &b) {
    uint64_t t[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    for (int i = 0; i < 6; i++) {
        u128 carry = 0;
        uint64_t bi = b.l[i];
        for (int j = 0; j < 6; j++) {
            u128 cur = (u128)a.l[j] * bi + t[j] + (uint64_t)carry;
            t[j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        u128 cur = (u128)t[6] + (uint64_t)carry;
        t[6] = (uint64_t)cur;
        t[7] = (uint64_t)(cur >> 64);

        uint64_t m = t[0] * P_INV_NEG;
        u128 cur0 = (u128)m * P_LIMBS[0] + t[0];
        carry = cur0 >> 64;
        for (int j = 1; j < 6; j++) {
            u128 c2 = (u128)m * P_LIMBS[j] + t[j] + (uint64_t)carry;
            t[j - 1] = (uint64_t)c2;
            carry = c2 >> 64;
        }
        u128 c3 = (u128)t[6] + (uint64_t)carry;
        t[5] = (uint64_t)c3;
        t[6] = t[7] + (uint64_t)(c3 >> 64);
        t[7] = 0;
    }
    if (limbs_cmp(t, P_LIMBS) >= 0) limbs_sub(r.l, t, P_LIMBS);
    else memcpy(r.l, t, sizeof(fp));
}

static inline void fp_sqr(fp &r, const fp &a) { fp_mul(r, a, a); }

static const fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static fp FP_ONE;   // R mod p (Montgomery 1), set in init
static fp FP_R2;    // 2^768 mod p
static fp FP_RMODP_MONT;  // mont(R mod p): for 2^384 shifts in reductions

static inline void fp_to_mont(fp &r, const fp &raw) { fp_mul(r, raw, FP_R2); }

static inline void fp_from_mont(fp &r, const fp &a) {
    fp one_raw = {{1, 0, 0, 0, 0, 0}};
    fp_mul(r, a, one_raw);
}

static inline bool fp_eq(const fp &a, const fp &b) {
    return memcmp(a.l, b.l, sizeof(a.l)) == 0;
}

// Binary extended GCD inversion.  Treats the Montgomery representative aR
// as a plain integer: egcd gives (aR)^-1 = a^-1 R^-1; two Montgomery
// multiplications by R^2 then lift to a^-1 R (Montgomery form of a^-1).
static void fp_inv(fp &r, const fp &a) {
    if (fp_is_zero_raw(a)) {  // 0 has no inverse; define inv(0)=0 (never hit on valid input)
        r = FP_ZERO;
        return;
    }
    // HAC 14.61 structure: invariants x1*aR = u (mod p), x2*aR = v (mod p);
    // gcd(aR, p) = 1 so u == v > 1 can never occur and the loop terminates
    // with u == 1 (answer x1) or v == 1 (answer x2).
    uint64_t u[6], v[6], x1[6], x2[6];
    memcpy(u, a.l, sizeof(u));
    memcpy(v, P_LIMBS, sizeof(v));
    memset(x1, 0, sizeof(x1));
    memset(x2, 0, sizeof(x2));
    x1[0] = 1;

    auto is_zero = [](const uint64_t x[6]) {
        return (x[0] | x[1] | x[2] | x[3] | x[4] | x[5]) == 0;
    };
    auto shr1 = [](uint64_t x[6], uint64_t top) {
        for (int i = 0; i < 5; i++) x[i] = (x[i] >> 1) | (x[i + 1] << 63);
        x[5] = (x[5] >> 1) | (top << 63);
    };
    auto half_mod = [&](uint64_t x[6]) {
        if (x[0] & 1) {
            // x = (x + p) / 2, with the carry bit out of 384 feeding the shift
            u128 carry = 0;
            for (int i = 0; i < 6; i++) {
                u128 cur = (u128)x[i] + P_LIMBS[i] + (uint64_t)carry;
                x[i] = (uint64_t)cur;
                carry = cur >> 64;
            }
            shr1(x, (uint64_t)carry);
        } else {
            shr1(x, 0);
        }
    };
    auto sub_mod = [&](uint64_t x[6], const uint64_t y[6]) {
        // x = (x - y) mod p
        u128 borrow = 0;
        for (int i = 0; i < 6; i++) {
            u128 cur = (u128)x[i] - y[i] - (uint64_t)borrow;
            x[i] = (uint64_t)cur;
            borrow = (cur >> 64) & 1;
        }
        if (borrow) {
            u128 carry = 0;
            for (int i = 0; i < 6; i++) {
                u128 cur = (u128)x[i] + P_LIMBS[i] + (uint64_t)carry;
                x[i] = (uint64_t)cur;
                carry = cur >> 64;
            }
        }
    };

    auto is_one = [](const uint64_t x[6]) {
        return x[0] == 1 && (x[1] | x[2] | x[3] | x[4] | x[5]) == 0;
    };

    while (!is_one(u) && !is_one(v)) {
        while (!(u[0] & 1)) {
            shr1(u, 0);
            half_mod(x1);
        }
        while (!(v[0] & 1)) {
            shr1(v, 0);
            half_mod(x2);
        }
        if (limbs_cmp(u, v) >= 0) {
            limbs_sub(u, u, v);
            sub_mod(x1, x2);
        } else {
            limbs_sub(v, v, u);
            sub_mod(x2, x1);
        }
    }
    // answer = (aR)^-1 mod p = a^-1 R^-1
    fp e;
    memcpy(e.l, is_one(u) ? x1 : x2, sizeof(e.l));
    fp_mul(e, e, FP_R2);  // a^-1 (canonical)
    fp_mul(r, e, FP_R2);  // a^-1 R (Montgomery)
}

// generic square-and-multiply by a big-endian byte exponent
template <typename T>
static T pow_be(const T &base, const uint8_t *exp, size_t n, const T &one) {
    T result = one;
    for (size_t i = 0; i < n; i++) {
        uint8_t byte = exp[i];
        for (int b = 7; b >= 0; b--) {
            result = result.square();
            if ((byte >> b) & 1) result = result * base;
        }
    }
    return result;
}

struct Fp {
    fp v;
    Fp() : v(FP_ZERO) {}
    explicit Fp(const fp &x) : v(x) {}
    Fp operator+(const Fp &o) const { Fp r; fp_add(r.v, v, o.v); return r; }
    Fp operator-(const Fp &o) const { Fp r; fp_sub(r.v, v, o.v); return r; }
    Fp operator*(const Fp &o) const { Fp r; fp_mul(r.v, v, o.v); return r; }
    Fp operator-() const { Fp r; fp_neg(r.v, v); return r; }
    Fp square() const { Fp r; fp_sqr(r.v, v); return r; }
    Fp inv() const { Fp r; fp_inv(r.v, v); return r; }
    bool is_zero() const { return fp_is_zero_raw(v); }
    bool operator==(const Fp &o) const { return fp_eq(v, o.v); }
    bool operator!=(const Fp &o) const { return !fp_eq(v, o.v); }
    static Fp one() { return Fp(FP_ONE); }
    static Fp zero() { return Fp(FP_ZERO); }
};

static Fp fp_from_limbs(const uint64_t raw[6]) {
    fp x;
    memcpy(x.l, raw, sizeof(x.l));
    Fp r;
    fp_to_mont(r.v, x);
    return r;
}

// canonical (non-Montgomery) little-endian limbs
static void fp_canonical(uint64_t out[6], const Fp &a) {
    fp c;
    fp_from_mont(c, a.v);
    memcpy(out, c.l, sizeof(c.l));
}

static bool fp_sgn_lex(const Fp &y) {  // y > (p-1)/2
    uint64_t c[6];
    fp_canonical(c, y);
    return limbs_cmp(c, HALF_P) > 0;
}

static int fp_parity(const Fp &a) {
    uint64_t c[6];
    fp_canonical(c, a);
    return (int)(c[0] & 1);
}

// ===========================================================================
// Fp2 = Fp[u]/(u^2+1)
// ===========================================================================

struct Fp2 {
    Fp c0, c1;
    Fp2() {}
    Fp2(const Fp &a, const Fp &b) : c0(a), c1(b) {}
    Fp2 operator+(const Fp2 &o) const { return Fp2(c0 + o.c0, c1 + o.c1); }
    Fp2 operator-(const Fp2 &o) const { return Fp2(c0 - o.c0, c1 - o.c1); }
    Fp2 operator-() const { return Fp2(-c0, -c1); }
    Fp2 operator*(const Fp2 &o) const {
        // karatsuba, mirrors fields.py Fq2.__mul__
        Fp t0 = c0 * o.c0;
        Fp t1 = c1 * o.c1;
        Fp cross = (c0 + c1) * (o.c0 + o.c1);
        return Fp2(t0 - t1, cross - t0 - t1);
    }
    Fp2 square() const {
        // (a0+a1)(a0-a1) + 2 a0 a1 u
        Fp t0 = (c0 + c1) * (c0 - c1);
        Fp t1 = c0 * c1;
        return Fp2(t0, t1 + t1);
    }
    Fp2 mul_by_xi() const {  // * (1 + u)
        return Fp2(c0 - c1, c0 + c1);
    }
    Fp2 conjugate() const { return Fp2(c0, -c1); }
    Fp2 inv() const {
        Fp norm = c0.square() + c1.square();
        Fp ninv = norm.inv();
        return Fp2(c0 * ninv, -(c1 * ninv));
    }
    Fp2 scale(const Fp &s) const { return Fp2(c0 * s, c1 * s); }
    bool is_zero() const { return c0.is_zero() && c1.is_zero(); }
    bool operator==(const Fp2 &o) const { return c0 == o.c0 && c1 == o.c1; }
    bool operator!=(const Fp2 &o) const { return !(*this == o); }
    static Fp2 one() { return Fp2(Fp::one(), Fp::zero()); }
    static Fp2 zero() { return Fp2(Fp::zero(), Fp::zero()); }
};

static Fp2 fp2_from_limbs(const uint64_t c0[6], const uint64_t c1[6]) {
    return Fp2(fp_from_limbs(c0), fp_from_limbs(c1));
}

static int fp2_sgn0(const Fp2 &a) {  // RFC 9380 sgn0, m=2
    int sign_0 = fp_parity(a.c0);
    int zero_0 = a.c0.is_zero() ? 1 : 0;
    int sign_1 = fp_parity(a.c1);
    return sign_0 | (zero_0 & sign_1);
}

static Fp2 FQ2_SQRT_ADJ[4];

static bool fp2_sqrt(Fp2 &out, const Fp2 &a) {
    Fp2 c = pow_be(a, EXP_FQ2_SQRT, EXP_FQ2_SQRT_LEN, Fp2::one());
    for (int i = 0; i < 4; i++) {
        Fp2 cand = c * FQ2_SQRT_ADJ[i];
        if (cand.square() == a) {
            out = cand;
            return true;
        }
    }
    return false;
}

static bool fp_sqrt(Fp &out, const Fp &a) {
    Fp c = pow_be(a, EXP_FP_SQRT, EXP_FP_SQRT_LEN, Fp::one());
    if (c.square() == a) {
        out = c;
        return true;
    }
    return false;
}

// ===========================================================================
// Fp6 = Fp2[v]/(v^3 - xi), Fp12 = Fp6[w]/(w^2 - v)     (xi = 1 + u)
// ===========================================================================

struct Fp6 {
    Fp2 c0, c1, c2;
    Fp6() {}
    Fp6(const Fp2 &a, const Fp2 &b, const Fp2 &c) : c0(a), c1(b), c2(c) {}
    Fp6 operator+(const Fp6 &o) const { return Fp6(c0 + o.c0, c1 + o.c1, c2 + o.c2); }
    Fp6 operator-(const Fp6 &o) const { return Fp6(c0 - o.c0, c1 - o.c1, c2 - o.c2); }
    Fp6 operator-() const { return Fp6(-c0, -c1, -c2); }
    Fp6 operator*(const Fp6 &o) const {
        // mirrors fields.py Fq6.__mul__
        Fp2 t0 = c0 * o.c0;
        Fp2 t1 = c1 * o.c1;
        Fp2 t2 = c2 * o.c2;
        Fp2 r0 = ((c1 + c2) * (o.c1 + o.c2) - t1 - t2).mul_by_xi() + t0;
        Fp2 r1 = (c0 + c1) * (o.c0 + o.c1) - t0 - t1 + t2.mul_by_xi();
        Fp2 r2 = (c0 + c2) * (o.c0 + o.c2) - t0 - t2 + t1;
        return Fp6(r0, r1, r2);
    }
    Fp6 square() const { return (*this) * (*this); }
    Fp6 mul_by_v() const { return Fp6(c2.mul_by_xi(), c0, c1); }
    Fp6 inv() const {
        Fp2 t0 = c0.square() - (c1 * c2).mul_by_xi();
        Fp2 t1 = c2.square().mul_by_xi() - c0 * c1;
        Fp2 t2 = c1.square() - c0 * c2;
        Fp2 factor = (c0 * t0 + (c2 * t1).mul_by_xi() + (c1 * t2).mul_by_xi()).inv();
        return Fp6(t0 * factor, t1 * factor, t2 * factor);
    }
    bool is_zero() const { return c0.is_zero() && c1.is_zero() && c2.is_zero(); }
    bool operator==(const Fp6 &o) const { return c0 == o.c0 && c1 == o.c1 && c2 == o.c2; }
    static Fp6 one() { return Fp6(Fp2::one(), Fp2::zero(), Fp2::zero()); }
    static Fp6 zero() { return Fp6(Fp2::zero(), Fp2::zero(), Fp2::zero()); }
};

struct Fp12 {
    Fp6 c0, c1;
    Fp12() {}
    Fp12(const Fp6 &a, const Fp6 &b) : c0(a), c1(b) {}
    Fp12 operator*(const Fp12 &o) const {
        Fp6 t0 = c0 * o.c0;
        Fp6 t1 = c1 * o.c1;
        Fp6 r0 = t0 + t1.mul_by_v();
        Fp6 r1 = (c0 + c1) * (o.c0 + o.c1) - t0 - t1;
        return Fp12(r0, r1);
    }
    Fp12 square() const {
        // mirrors fields.py Fq12.square
        Fp6 t0 = c0 * c1;
        Fp6 r0 = (c0 + c1) * (c0 + c1.mul_by_v()) - t0 - t0.mul_by_v();
        return Fp12(r0, t0 + t0);
    }
    Fp12 conjugate() const { return Fp12(c0, -c1); }
    Fp12 inv() const {
        Fp6 factor = (c0.square() - c1.square().mul_by_v()).inv();
        return Fp12(c0 * factor, -(c1 * factor));
    }
    bool operator==(const Fp12 &o) const { return c0 == o.c0 && c1 == o.c1; }
    static Fp12 one() { return Fp12(Fp6::one(), Fp6::zero()); }
};

// Frobenius p^2: coefficient at w^k scales by FROB2_G[k] (an Fp element).
// Basis order: c0.(c0,c1,c2) sit at w^0,w^2,w^4; c1.(c0,c1,c2) at w^1,w^3,w^5.
static Fp FROB2_COEF[6];

static Fp12 frobenius_p2(const Fp12 &f) {
    return Fp12(
        Fp6(f.c0.c0.scale(FROB2_COEF[0]),
            f.c0.c1.scale(FROB2_COEF[2]),
            f.c0.c2.scale(FROB2_COEF[4])),
        Fp6(f.c1.c0.scale(FROB2_COEF[1]),
            f.c1.c1.scale(FROB2_COEF[3]),
            f.c1.c2.scale(FROB2_COEF[5])));
}

// Frobenius p^1: conjugate each Fp2 coefficient (c^p = conj(c)), scale the
// w^k coefficient by FROB1_G[k] = xi^(k(p-1)/6) (an Fp2 element).
static Fp2 FROB1_COEF[6];

static Fp12 frobenius_p1(const Fp12 &f) {
    return Fp12(
        Fp6(f.c0.c0.conjugate() * FROB1_COEF[0],
            f.c0.c1.conjugate() * FROB1_COEF[2],
            f.c0.c2.conjugate() * FROB1_COEF[4]),
        Fp6(f.c1.c0.conjugate() * FROB1_COEF[1],
            f.c1.c1.conjugate() * FROB1_COEF[3],
            f.c1.c2.conjugate() * FROB1_COEF[5]));
}

// Granger-Scott squaring for elements of the cyclotomic subgroup (where
// conjugate == inverse).  9 Fp2 squarings vs 18 Fp2 mul-equivalents for a
// generic Fp12 square — the workhorse of the fast final exponentiation.
static Fp12 cyclotomic_square(const Fp12 &x) {
    const Fp2 &x00 = x.c0.c0, &x01 = x.c0.c1, &x02 = x.c0.c2;
    const Fp2 &x10 = x.c1.c0, &x11 = x.c1.c1, &x12 = x.c1.c2;
    Fp2 t0 = x11.square();
    Fp2 t1 = x00.square();
    Fp2 t6 = (x11 + x00).square() - t0 - t1;  // 2 x11 x00
    Fp2 t2 = x02.square();
    Fp2 t3 = x10.square();
    Fp2 t7 = (x02 + x10).square() - t2 - t3;  // 2 x02 x10
    Fp2 t4 = x12.square();
    Fp2 t5 = x01.square();
    Fp2 t8 = ((x12 + x01).square() - t4 - t5).mul_by_xi();  // 2 xi x12 x01
    t0 = t0.mul_by_xi() + t1;
    t2 = t2.mul_by_xi() + t3;
    t4 = t4.mul_by_xi() + t5;
    Fp2 z00 = t0 - x00; z00 = z00 + z00 + t0;
    Fp2 z01 = t2 - x01; z01 = z01 + z01 + t2;
    Fp2 z02 = t4 - x02; z02 = z02 + z02 + t4;
    Fp2 z10 = t8 + x10; z10 = z10 + z10 + t8;
    Fp2 z11 = t6 + x11; z11 = z11 + z11 + t6;
    Fp2 z12 = t7 + x12; z12 = z12 + z12 + t7;
    return Fp12(Fp6(z00, z01, z02), Fp6(z10, z11, z12));
}

// f^x for the (negative) BLS parameter x: cyclotomic square-and-multiply by
// |x| = ATE_LOOP (64 bits, weight 6), then conjugate for the sign.
static Fp12 cyc_exp_x(const Fp12 &f) {
    Fp12 r = f;  // top bit of ATE_LOOP is bit 63, always set
    for (int i = 62; i >= 0; i--) {
        r = cyclotomic_square(r);
        if ((ATE_LOOP >> i) & 1) r = r * f;
    }
    return r.conjugate();
}

// ===========================================================================
// Curve points (Jacobian), generic over the coordinate field
// ===========================================================================

template <class F>
struct Pt {
    F x, y, z;
    bool is_inf() const { return z.is_zero(); }
    static Pt infinity() { return Pt{F::one(), F::one(), F::zero()}; }

    Pt dbl() const {
        if (is_inf()) return *this;
        // dbl-2009-l, mirrors curve.py Point.double
        F A = x.square();
        F B = y.square();
        F C = B.square();
        F D = (x + B).square() - A - C;
        D = D + D;
        F E = A + A + A;
        F Fv = E.square();
        F X3 = Fv - D - D;
        F eightC = C + C;
        eightC = eightC + eightC;
        eightC = eightC + eightC;
        F Y3 = E * (D - X3) - eightC;
        F Z3 = y * z;
        Z3 = Z3 + Z3;
        return Pt{X3, Y3, Z3};
    }

    Pt add(const Pt &o) const {
        if (is_inf()) return o;
        if (o.is_inf()) return *this;
        // add-2007-bl, mirrors curve.py Point.__add__
        F Z1Z1 = z.square();
        F Z2Z2 = o.z.square();
        F U1 = x * Z2Z2;
        F U2 = o.x * Z1Z1;
        F S1 = y * o.z * Z2Z2;
        F S2 = o.y * z * Z1Z1;
        if (U1 == U2) {
            if (S1 == S2) return dbl();
            return infinity();
        }
        F H = U2 - U1;
        F I = (H + H).square();
        F J = H * I;
        F rr = S2 - S1;
        rr = rr + rr;
        F V = U1 * I;
        F X3 = rr.square() - J - V - V;
        F S1J = S1 * J;
        F Y3 = rr * (V - X3) - S1J - S1J;
        F Z3 = ((z + o.z).square() - Z1Z1 - Z2Z2) * H;
        return Pt{X3, Y3, Z3};
    }

    Pt neg() const { return Pt{x, -y, z}; }

    // Mixed addition with an affine point (implicit Z2 = 1), madd-2007-bl:
    // 7M + 4S vs the 11M + 5S of the general add — the workhorse of the
    // Pippenger bucket phase where every input point is affine.
    Pt add_affine(const F &ox, const F &oy) const {
        if (is_inf()) return Pt{ox, oy, F::one()};
        F Z1Z1 = z.square();
        F U2 = ox * Z1Z1;
        F S2 = oy * z * Z1Z1;
        if (x == U2) {
            if (y == S2) return dbl();
            return infinity();
        }
        F H = U2 - x;
        F HH = H.square();
        F I = HH + HH;
        I = I + I;
        F J = H * I;
        F rr = S2 - y;
        rr = rr + rr;
        F V = x * I;
        F X3 = rr.square() - J - V - V;
        F YJ = y * J;
        F Y3 = rr * (V - X3) - YJ - YJ;
        F Z3 = (z + H).square() - Z1Z1 - HH;
        return Pt{X3, Y3, Z3};
    }

    Pt mul_be(const uint8_t *k, size_t n) const {
        Pt result = infinity();
        for (size_t i = 0; i < n; i++) {
            uint8_t byte = k[i];
            for (int b = 7; b >= 0; b--) {
                result = result.dbl();
                if ((byte >> b) & 1) result = result.add(*this);
            }
        }
        return result;
    }

    // affine (x, y); only valid when not infinity
    void to_affine(F &ax, F &ay) const {
        F zinv = z.inv();
        F zinv2 = zinv.square();
        ax = x * zinv2;
        ay = y * zinv2 * zinv;
    }
};

typedef Pt<Fp> G1;
typedef Pt<Fp2> G2;

static G1 G1_GEN;
static G2 G2_GEN;
static Fp B1;     // 4
static Fp2 B2;    // 4(1+u)

// --- psi endomorphism on the twist (untwist-Frobenius-twist) ---------------
// psi(x, y) = (PSI_CX·conj(x), PSI_CY·conj(y)); on Jacobian coordinates the
// conjugation distributes (conj is a field automorphism), so
// psi(X, Y, Z) = (PSI_CX·conj(X), PSI_CY·conj(Y), conj(Z)).
// Constants generated + oracle-validated in tools/gen_bls_native_constants.py.

static Fp2 PSI_CX_C, PSI_CY_C;
static Fp PSI2_CX_Q;

static G2 g2_psi(const G2 &p) {
    return G2{p.x.conjugate() * PSI_CX_C, p.y.conjugate() * PSI_CY_C,
              p.z.conjugate()};
}

static G2 g2_psi2(const G2 &p) {  // psi∘psi: (PSI2_CX·x, -y) on affine
    return G2{p.x.scale(PSI2_CX_Q), -p.y, p.z};
}

template <class P>
static P mul_u64(const P &pt, uint64_t k) {
    P r = P::infinity();
    for (int i = 63; i >= 0; i--) {
        r = r.dbl();
        if ((k >> i) & 1) r = r.add(pt);
    }
    return r;
}

// [x]P for the (negative) BLS parameter x: |x| = ATE_LOOP, then negate.
static G2 g2_mul_x(const G2 &p) { return mul_u64(p, ATE_LOOP).neg(); }

// Jacobian equality without normalizing: cross-multiplied coordinates.
template <class P>
static bool jac_eq(const P &a, const P &b) {
    if (a.is_inf() || b.is_inf()) return a.is_inf() && b.is_inf();
    auto z1z1 = a.z.square();
    auto z2z2 = b.z.square();
    if (!(a.x * z2z2 == b.x * z1z1)) return false;
    return a.y * z2z2 * b.z == b.y * z1z1 * a.z;
}

// Budroni-Pintore fast cofactor clearing:
//   [x^2-x-1]P + [x-1]psi(P) + psi^2(2P)
// RFC 9380 G.3 defines h_eff so this equals [h_eff]P exactly (equality
// machine-checked against the oracle curve at constant-generation time).
// Two 64-bit scalar mults instead of one 636-bit one.
static G2 g2_clear_cofactor(const G2 &p) {
    G2 t1 = g2_mul_x(p);          // [x]P
    G2 t2 = g2_psi(p);            // psi(P)
    G2 t3 = g2_psi2(p.dbl());     // psi^2(2P)
    t3 = t3.add(t2.neg());        // psi^2(2P) - psi(P)
    t2 = g2_mul_x(t1.add(t2));    // [x^2]P + [x]psi(P)
    t3 = t3.add(t2);
    t3 = t3.add(t1.neg());
    return t3.add(p.neg());       // ... - [x]P - P
}

// Scott's fast G2 membership test: on the r-order subgroup psi acts as
// multiplication by p ≡ x (mod r), and for BLS12-381 no other E2(Fp2)
// points satisfy psi(P) == [x]P.  One 64-bit mult instead of a 255-bit one.
static bool g2_in_subgroup_fast(const G2 &p) {
    if (p.is_inf()) return true;
    return jac_eq(g2_psi(p), g2_mul_x(p));
}

// --- fast G1 membership (Scott, eprint 2021/1130) --------------------------
// The GLV endomorphism sigma(x, y) = (beta*x, y) (beta a primitive cube
// root of unity in Fp — the same constant psi^2 scales the twist's x by)
// acts on the r-order subgroup as multiplication by an eigenvalue lambda
// with lambda^2 + lambda + 1 = 0 (mod r); the two eigenvalues are -z^2 and
// z^2 - 1 (r = z^4 - z^2 + 1).  For BLS12-381 no other E(Fp) point
// satisfies sigma(P) == [-z^2]P, so the check needs two 64-bit scalar
// mults instead of the generic 255-bit [r]P == inf.  Registry pubkeys are
// decompressed + membership-checked once per validator (native.py affine
// cache), which made this the dominant cold cost of the block engine.
//
// Orientation is self-established at init: whichever of {beta, beta^2}
// satisfies sigma(G1_GEN) == [-z^2]G1_GEN is the eigenvalue -z^2 pairing
// (an endomorphism relation that holds on the prime-order generator holds
// on the whole subgroup).  If neither matches — foreign constants — the
// generic [r]P check stays in force.
static Fp G1_ENDO_BETA;
static bool G1_FAST_CHECK_OK = false;

static bool g1_in_subgroup_fast(const G1 &p) {
    if (p.is_inf()) return true;
    G1 sigma{p.x * G1_ENDO_BETA, p.y, p.z};
    G1 z2p = mul_u64(mul_u64(p, ATE_LOOP), ATE_LOOP);  // [z^2]P: signs cancel
    return jac_eq(sigma, z2p.neg());
}

static bool g1_on_curve(const Fp &x, const Fp &y) {
    return y.square() == x.square() * x + B1;
}

static bool g2_on_curve(const Fp2 &x, const Fp2 &y) {
    return y.square() == x.square() * x + B2;
}

template <class P>
static bool in_subgroup(const P &pt) {
    return pt.mul_be(CURVE_ORDER_R, CURVE_ORDER_R_LEN).is_inf();
}

// ===========================================================================
// Serialization (ZCash compressed format, mirrors curve.py)
// ===========================================================================

static void fp_to_bytes48(uint8_t out[48], const Fp &a) {
    uint64_t c[6];
    fp_canonical(c, a);
    for (int i = 0; i < 6; i++) {
        uint64_t limb = c[5 - i];
        for (int b = 0; b < 8; b++) out[i * 8 + b] = (uint8_t)(limb >> (56 - 8 * b));
    }
}

// returns false if value >= p
static bool fp_from_bytes48(Fp &out, const uint8_t in[48]) {
    fp raw;
    for (int i = 0; i < 6; i++) {
        uint64_t limb = 0;
        for (int b = 0; b < 8; b++) limb = (limb << 8) | in[i * 8 + b];
        raw.l[5 - i] = limb;
    }
    if (limbs_cmp(raw.l, P_LIMBS) >= 0) return false;
    fp_to_mont(out.v, raw);
    return true;
}

static void g1_serialize(uint8_t out[48], const G1 &pt) {
    if (pt.is_inf()) {
        memset(out, 0, 48);
        out[0] = 0xC0;
        return;
    }
    Fp x, y;
    pt.to_affine(x, y);
    fp_to_bytes48(out, x);
    out[0] |= 0x80 | (fp_sgn_lex(y) ? 0x20 : 0);
}

static void g2_serialize(uint8_t out[96], const G2 &pt) {
    if (pt.is_inf()) {
        memset(out, 0, 96);
        out[0] = 0xC0;
        return;
    }
    Fp2 x, y;
    pt.to_affine(x, y);
    fp_to_bytes48(out, x.c1);
    fp_to_bytes48(out + 48, x.c0);
    bool s = y.c1.is_zero() ? fp_sgn_lex(y.c0) : fp_sgn_lex(y.c1);
    out[0] |= 0x80 | (s ? 0x20 : 0);
}

// 0 = ok, nonzero = malformed.  Subgroup check NOT included.
static int g1_deserialize(G1 &out, const uint8_t in[48]) {
    int c_flag = (in[0] >> 7) & 1;
    int i_flag = (in[0] >> 6) & 1;
    int s_flag = (in[0] >> 5) & 1;
    if (!c_flag) return 1;
    if (i_flag) {
        if (in[0] & 0x3F) return 2;
        for (int i = 1; i < 48; i++)
            if (in[i]) return 2;
        out = G1::infinity();
        return 0;
    }
    uint8_t buf[48];
    memcpy(buf, in, 48);
    buf[0] &= 0x1F;
    Fp x;
    if (!fp_from_bytes48(x, buf)) return 3;
    Fp y2 = x.square() * x + B1;
    Fp y;
    if (!fp_sqrt(y, y2)) return 4;
    if (fp_sgn_lex(y) != (bool)s_flag) y = -y;
    out = G1{x, y, Fp::one()};
    return 0;
}

static int g2_deserialize(G2 &out, const uint8_t in[96]) {
    int c_flag = (in[0] >> 7) & 1;
    int i_flag = (in[0] >> 6) & 1;
    int s_flag = (in[0] >> 5) & 1;
    if (!c_flag) return 1;
    if (i_flag) {
        if (in[0] & 0x3F) return 2;
        for (int i = 1; i < 96; i++)
            if (in[i]) return 2;
        out = G2::infinity();
        return 0;
    }
    uint8_t buf[48];
    memcpy(buf, in, 48);
    buf[0] &= 0x1F;
    Fp x1, x0;
    if (!fp_from_bytes48(x1, buf)) return 3;
    if (!fp_from_bytes48(x0, in + 48)) return 3;
    Fp2 x(x0, x1);
    Fp2 y2 = x.square() * x + B2;
    Fp2 y;
    if (!fp2_sqrt(y, y2)) return 4;
    bool cur = y.c1.is_zero() ? fp_sgn_lex(y.c0) : fp_sgn_lex(y.c1);
    if (cur != (bool)s_flag) y = -y;
    out = G2{x, y, Fp2::one()};
    return 0;
}

// ===========================================================================
// SHA-256 (from generated round constants) + expand_message_xmd
// ===========================================================================

struct Sha256 {
    uint32_t h[8];
    uint64_t len;
    uint8_t buf[64];
    size_t buflen;

    Sha256() {
        memcpy(h, SHA_H0, sizeof(h));
        len = 0;
        buflen = 0;
    }
    static inline uint32_t ror(uint32_t v, int r) { return (v >> r) | (v << (32 - r)); }

    void block(const uint8_t *p) {
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = ((uint32_t)p[4 * i] << 24) | ((uint32_t)p[4 * i + 1] << 16) |
                   ((uint32_t)p[4 * i + 2] << 8) | p[4 * i + 3];
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = ror(w[i - 15], 7) ^ ror(w[i - 15], 18) ^ (w[i - 15] >> 3);
            uint32_t s1 = ror(w[i - 2], 17) ^ ror(w[i - 2], 19) ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
        uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t s1 = ror(e, 6) ^ ror(e, 11) ^ ror(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + s1 + ch + SHA_K[i] + w[i];
            uint32_t s0 = ror(a, 2) ^ ror(a, 13) ^ ror(a, 22);
            uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = s0 + maj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const uint8_t *p, size_t n) {
        len += n;
        if (buflen) {
            while (n && buflen < 64) {
                buf[buflen++] = *p++;
                n--;
            }
            if (buflen == 64) {
                block(buf);
                buflen = 0;
            }
        }
        while (n >= 64) {
            block(p);
            p += 64;
            n -= 64;
        }
        while (n) {
            buf[buflen++] = *p++;
            n--;
        }
    }

    void final(uint8_t out[32]) {
        uint64_t bits = len * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t zero = 0;
        while (buflen != 56) update(&zero, 1);
        uint8_t lb[8];
        for (int i = 0; i < 8; i++) lb[i] = (uint8_t)(bits >> (56 - 8 * i));
        update(lb, 8);
        for (int i = 0; i < 8; i++)
            for (int b = 0; b < 4; b++) out[4 * i + b] = (uint8_t)(h[i] >> (24 - 8 * b));
    }
};

// RFC 9380 §5.3.1 (SHA-256: b=32, s=64)
static void expand_message_xmd(uint8_t *out, size_t len_in_bytes,
                               const uint8_t *msg, size_t msg_len,
                               const uint8_t *dst, size_t dst_len) {
    if (dst_len > 255) dst_len = 255;  // callers reject earlier; never overflow
    size_t ell = (len_in_bytes + 31) / 32;
    uint8_t dst_prime[256];
    memcpy(dst_prime, dst, dst_len);
    dst_prime[dst_len] = (uint8_t)dst_len;
    size_t dpl = dst_len + 1;

    uint8_t b0[32];
    {
        Sha256 s;
        uint8_t zpad[64] = {0};
        s.update(zpad, 64);
        s.update(msg, msg_len);
        uint8_t lib[3] = {(uint8_t)(len_in_bytes >> 8), (uint8_t)len_in_bytes, 0};
        s.update(lib, 3);
        s.update(dst_prime, dpl);
        s.final(b0);
    }
    uint8_t bi[32];
    {
        Sha256 s;
        s.update(b0, 32);
        uint8_t one = 1;
        s.update(&one, 1);
        s.update(dst_prime, dpl);
        s.final(bi);
    }
    size_t off = 0;
    for (size_t i = 1;; i++) {
        size_t take = len_in_bytes - off < 32 ? len_in_bytes - off : 32;
        memcpy(out + off, bi, take);
        off += take;
        if (i >= ell) break;
        uint8_t tmp[32];
        for (int j = 0; j < 32; j++) tmp[j] = b0[j] ^ bi[j];
        Sha256 s;
        s.update(tmp, 32);
        uint8_t idx = (uint8_t)(i + 1);
        s.update(&idx, 1);
        s.update(dst_prime, dpl);
        s.final(bi);
    }
}

// reduce a 64-byte big-endian integer mod p (Montgomery form out)
static Fp fp_from_bytes64_reduce(const uint8_t in[64]) {
    // n = hi(16B) * 2^384 + lo(48B)
    fp lo_raw, hi_raw;
    memset(hi_raw.l, 0, sizeof(hi_raw.l));
    // hi bytes in[0..15] are big-endian: in[15-k] is the k-th least
    // significant byte, landing in limb k/8 at bit offset 8*(k%8)
    for (int k = 0; k < 16; k++)
        hi_raw.l[k / 8] |= (uint64_t)in[15 - k] << (8 * (k % 8));
    for (int i = 0; i < 6; i++) {
        uint64_t limb = 0;
        for (int b = 0; b < 8; b++) limb = (limb << 8) | in[16 + i * 8 + b];
        lo_raw.l[5 - i] = limb;
    }
    Fp lo, hi;
    fp_to_mont(lo.v, lo_raw);  // valid for raw < 2^384 even if >= p
    fp_to_mont(hi.v, hi_raw);
    Fp shift(FP_RMODP_MONT);  // mont(2^384 mod p)
    return hi * shift + lo;
}

// ===========================================================================
// hash_to_curve G2 (RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO)
// ===========================================================================

static Fp2 SSWU_A_C, SSWU_B_C, SSWU_Z_C;
static std::vector<Fp2> ISO_K1_C, ISO_K2_C, ISO_K3_C, ISO_K4_C;

static void hash_to_field_fq2(Fp2 out[2], const uint8_t *msg, size_t msg_len,
                              const uint8_t *dst, size_t dst_len) {
    uint8_t uniform[256];  // count=2, m=2, L=64
    expand_message_xmd(uniform, 256, msg, msg_len, dst, dst_len);
    for (int i = 0; i < 2; i++) {
        Fp e0 = fp_from_bytes64_reduce(uniform + 128 * i);
        Fp e1 = fp_from_bytes64_reduce(uniform + 128 * i + 64);
        out[i] = Fp2(e0, e1);
    }
}

// simplified SWU onto E2' (mirrors hash_to_curve.py _sswu)
static void sswu(Fp2 &x, Fp2 &y, const Fp2 &u) {
    Fp2 z_u2 = SSWU_Z_C * u.square();
    Fp2 tv = z_u2.square() + z_u2;
    Fp2 x1;
    if (tv.is_zero()) {
        x1 = SSWU_B_C * (SSWU_Z_C * SSWU_A_C).inv();
    } else {
        x1 = (-SSWU_B_C) * SSWU_A_C.inv() * (Fp2::one() + tv.inv());
    }
    Fp2 gx1 = x1.square() * x1 + SSWU_A_C * x1 + SSWU_B_C;
    Fp2 y1;
    if (fp2_sqrt(y1, gx1)) {
        x = x1;
        y = y1;
    } else {
        Fp2 x2 = z_u2 * x1;
        Fp2 gx2 = x2.square() * x2 + SSWU_A_C * x2 + SSWU_B_C;
        Fp2 y2;
        fp2_sqrt(y2, gx2);  // must succeed
        x = x2;
        y = y2;
    }
    if (fp2_sgn0(u) != fp2_sgn0(y)) y = -y;
}

static Fp2 horner(const std::vector<Fp2> &k, const Fp2 &x) {
    Fp2 acc = k.back();
    for (int i = (int)k.size() - 2; i >= 0; i--) acc = acc * x + k[i];
    return acc;
}

static void iso_map(Fp2 &xo, Fp2 &yo, const Fp2 &x, const Fp2 &y) {
    Fp2 xn = horner(ISO_K1_C, x);
    Fp2 xd = horner(ISO_K2_C, x);
    Fp2 yn = horner(ISO_K3_C, x);
    Fp2 yd = horner(ISO_K4_C, x);
    xo = xn * xd.inv();
    yo = y * yn * yd.inv();
}

static G2 hash_to_g2(const uint8_t *msg, size_t msg_len,
                     const uint8_t *dst, size_t dst_len) {
    Fp2 u[2];
    hash_to_field_fq2(u, msg, msg_len, dst, dst_len);
    G2 q[2];
    for (int i = 0; i < 2; i++) {
        Fp2 xp, yp, xe, ye;
        sswu(xp, yp, u[i]);
        iso_map(xe, ye, xp, yp);
        q[i] = G2{xe, ye, Fp2::one()};
    }
    G2 r = q[0].add(q[1]);
    (void)H_EFF_G2;  // retained in the header as documentation of h_eff
    return g2_clear_cofactor(r);
}

static const uint8_t DST_POP[] = "BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_";
static const size_t DST_POP_LEN = sizeof(DST_POP) - 1;

// --- bounded hash_to_g2 result cache (DST_POP messages only) ---------------
// hash_to_g2 is the single most expensive per-lane cost of the batch
// verifier (two Fp2 square-root exponentiations + the cofactor clearing),
// and the SAME signing roots recur across calls: epoch replays re-verify
// re-carried aggregates, and the bisection descent re-hashes every message
// of every sub-batch it probes.  Keyed on raw message bytes — the batch
// path always hashes under the fixed proof-of-possession DST, so the DST
// is not part of the key (the arbitrary-DST diagnostic export bypasses the
// cache entirely).  FIFO-bounded; mutex-guarded because ctypes drops the
// GIL and the h2c phase itself runs on the pool.
static const size_t H2C_CACHE_MAX = 1 << 13;  // 8192 messages ~ 2.5 MB

static std::mutex H2C_MU;
static std::unordered_map<std::string, G2> H2C_MAP;
static std::deque<std::string> H2C_FIFO;
static uint64_t H2C_HITS = 0, H2C_MISSES = 0;

static bool h2c_cache_get(const std::string &key, G2 &out) {
    std::lock_guard<std::mutex> lk(H2C_MU);
    auto it = H2C_MAP.find(key);
    if (it == H2C_MAP.end()) {
        H2C_MISSES++;
        return false;
    }
    H2C_HITS++;
    out = it->second;
    return true;
}

static void h2c_cache_put(const std::string &key, const G2 &val) {
    std::lock_guard<std::mutex> lk(H2C_MU);
    if (H2C_MAP.count(key)) return;  // another thread won the miss race
    while (H2C_MAP.size() >= H2C_CACHE_MAX) {
        H2C_MAP.erase(H2C_FIFO.front());
        H2C_FIFO.pop_front();
    }
    H2C_FIFO.push_back(key);
    H2C_MAP[key] = val;
}

// hash_to_g2 under the fixed POP DST, cache-fronted
static G2 hash_to_g2_pop_cached(const uint8_t *msg, size_t msg_len) {
    std::string key((const char *)msg, msg_len);
    G2 h;
    if (h2c_cache_get(key, h)) return h;
    h = hash_to_g2(msg, msg_len, DST_POP, DST_POP_LEN);
    h2c_cache_put(key, h);
    return h;
}

// ===========================================================================
// Pairing
// ===========================================================================

// Line through points on the twist, evaluated at the untwisted G1 point and
// folded into a sparse Fp12: with untwist (x,y) -> (x/w^2, y/w^3) and the
// whole line scaled by xi (an Fp2 constant the final exponentiation kills):
//   l = (-xi*yP)  +  (yT - lambda*xT) * v*w  +  (lambda*xP) * v^2*w
// where lambda is the slope in Fp2.  Basis: Fp12 c0=(w^0,w^2,w^4), c1=(w^1,w^3,w^5).
//
// f * (A + B·vw + C·v²w) without materializing the sparse Fp12 (which
// would be Fp12(Fp6(A,0,0), Fp6(0,B,C)) in that basis): the generic
// product pays 18 Fp2 muls; exploiting the line's three-of-six sparsity
// pattern brings it to 14 (t0 = a0·(A,0,0) is 3 scalings, t1 = a1·(0,B,C)
// is a 5-mul sparse Fp6 product, and the Karatsuba cross term runs the
// full 6).  Verified against the generic operator* by the pairing
// differential tests (GT pinned byte-for-byte against the pure-Python
// oracle) — every exported verification path funnels through here.
static Fp12 fp12_mul_line(const Fp12 &f, const Fp2 &A, const Fp2 &B,
                          const Fp2 &C) {
    const Fp6 &a0 = f.c0, &a1 = f.c1;
    Fp6 t0(a0.c0 * A, a0.c1 * A, a0.c2 * A);
    Fp2 m1 = a1.c1 * B;
    Fp2 m2 = a1.c2 * C;
    Fp6 t1(((a1.c1 + a1.c2) * (B + C) - m1 - m2).mul_by_xi(),
           (a1.c0 + a1.c1) * B - m1 + m2.mul_by_xi(),
           (a1.c0 + a1.c2) * C - m2 + m1);
    Fp6 s = a0 + a1;
    Fp2 u0 = s.c0 * A;
    Fp2 u1 = s.c1 * B;
    Fp2 u2 = s.c2 * C;
    Fp6 cross(((s.c1 + s.c2) * (B + C) - u1 - u2).mul_by_xi() + u0,
              (s.c0 + s.c1) * (A + B) - u0 - u1 + u2.mul_by_xi(),
              (s.c0 + s.c2) * (A + C) - u0 - u2 + u1);
    return Fp12(t0 + t1.mul_by_v(), cross - t0 - t1);
}

// f_{|x|,Q}(P) conjugated (BLS parameter is negative), mirrors pairing.py
// miller_loop but runs on the twist with affine steps.
static Fp12 miller_loop(const G1 &p, const G2 &q) {
    if (p.is_inf() || q.is_inf()) return Fp12::one();
    Fp xP, yP;
    p.to_affine(xP, yP);
    Fp2 xQ, yQ;
    q.to_affine(xQ, yQ);

    Fp negyP = -yP;
    Fp2 A(negyP, negyP);  // -xi*yP = -(yP + yP*u)

    Fp2 xT = xQ, yT = yQ;
    Fp12 f = Fp12::one();

    for (int i = 62; i >= 0; i--) {
        // doubling step: lambda = 3 xT^2 / (2 yT)
        Fp2 xT2 = xT.square();
        Fp2 lam = (xT2 + xT2 + xT2) * (yT + yT).inv();
        Fp2 B = yT - lam * xT;
        Fp2 C = lam.scale(xP);
        f = fp12_mul_line(f.square(), A, B, C);
        Fp2 x3 = lam.square() - xT - xT;
        yT = lam * (xT - x3) - yT;
        xT = x3;

        if ((ATE_LOOP >> i) & 1) {
            // addition step: lambda = (yQ - yT) / (xQ - xT)
            Fp2 lam2 = (yQ - yT) * (xQ - xT).inv();
            Fp2 B2c = yQ - lam2 * xQ;
            Fp2 C2 = lam2.scale(xP);
            f = fp12_mul_line(f, A, B2c, C2);
            Fp2 x3a = lam2.square() - xT - xQ;
            yT = lam2 * (xT - x3a) - yT;
            xT = x3a;
        }
    }
    return f.conjugate();
}

// Multi-pairing: prod_i f_{|x|,Q_i}(P_i) with ONE shared squaring chain.
// Because squaring distributes over the product —
//   prod_i (f_i^2 · line_i) = (prod_i f_i)^2 · prod_i line_i
// — a product of k Miller loops costs 63 Fp12 squarings TOTAL instead of
// 63k, and the per-step slope denominators (2y_T, x_Q - x_T) of all lanes
// are inverted together with Montgomery's batch-inversion trick (one Fp2
// inversion per step instead of k).  All k lanes share the identical
// doubling/addition schedule (the ATE bits), so the lockstep is exact.
// This is what makes the batch verifier's pairing product cheap; the
// math per lane is unchanged from miller_loop (differentially pinned).
static void fp2_batch_inverse(std::vector<Fp2> &vals) {
    size_t n = vals.size();
    if (n == 0) return;
    std::vector<Fp2> prefix(n);
    Fp2 acc = Fp2::one();
    for (size_t i = 0; i < n; i++) {
        prefix[i] = acc;
        acc = acc * vals[i];
    }
    Fp2 inv = acc.inv();
    for (size_t i = n; i-- > 0;) {
        Fp2 orig = vals[i];
        vals[i] = inv * prefix[i];
        inv = inv * orig;
    }
}

struct MillerLane {
    Fp xP;
    Fp2 A;        // -xi*yP folded constant of the line
    Fp2 xQ, yQ;   // affine twist point
    Fp2 xT, yT;   // running point
};

// One squaring chain over lanes [lo, hi): the shared-squaring product of
// that lane slice, conjugated for the negative BLS parameter.  All local
// state — safe to run one range per thread.
static Fp12 miller_lanes_range(const std::vector<MillerLane> &all,
                               size_t lo, size_t hi) {
    std::vector<MillerLane> lanes(all.begin() + lo, all.begin() + hi);
    size_t k = lanes.size();
    Fp12 f = Fp12::one();
    if (k == 0) return f;
    std::vector<Fp2> dens(k);

    for (int i = 62; i >= 0; i--) {
        // doubling step, all lanes: lambda = 3 xT^2 / (2 yT)
        for (size_t j = 0; j < k; j++) dens[j] = lanes[j].yT + lanes[j].yT;
        fp2_batch_inverse(dens);
        f = f.square();
        for (size_t j = 0; j < k; j++) {
            MillerLane &ln = lanes[j];
            Fp2 xT2 = ln.xT.square();
            Fp2 lam = (xT2 + xT2 + xT2) * dens[j];
            Fp2 B = ln.yT - lam * ln.xT;
            Fp2 C = lam.scale(ln.xP);
            f = fp12_mul_line(f, ln.A, B, C);
            Fp2 x3 = lam.square() - ln.xT - ln.xT;
            ln.yT = lam * (ln.xT - x3) - ln.yT;
            ln.xT = x3;
        }
        if ((ATE_LOOP >> i) & 1) {
            // addition step, all lanes: lambda = (yQ - yT) / (xQ - xT)
            for (size_t j = 0; j < k; j++)
                dens[j] = lanes[j].xQ - lanes[j].xT;
            fp2_batch_inverse(dens);
            for (size_t j = 0; j < k; j++) {
                MillerLane &ln = lanes[j];
                Fp2 lam = (ln.yQ - ln.yT) * dens[j];
                Fp2 B = ln.yQ - lam * ln.xQ;
                Fp2 C = lam.scale(ln.xP);
                f = fp12_mul_line(f, ln.A, B, C);
                Fp2 x3 = lam.square() - ln.xT - ln.xQ;
                ln.yT = lam * (ln.xT - x3) - ln.yT;
                ln.xT = x3;
            }
        }
    }
    return f.conjugate();
}

// Lane-parallel multi-pairing: lanes split into contiguous chunks, each
// chunk runs its own shared-squaring Miller chain on a pool thread, and
// the partial Fp12 products multiply in FIXED chunk-index order before the
// single shared final exponentiation.  Exactness: squaring distributes
// over products, so prod_c miller_lanes_range(chunk_c) equals the one-chain
// product over all lanes regardless of where the chunk boundaries fall —
// the result is bit-identical for every thread count (conjugation is the
// p^6 Frobenius, a ring automorphism, so per-chunk conjugates compose).
// Each extra chunk re-pays the 63 Fp12 squarings one chain shares, so
// chunks stay >= ~6 lanes: below that the squaring overhead eats the
// parallel win.
static const size_t MILLER_MIN_LANES_PER_CHUNK = 6;

static Fp12 miller_loop_product(const std::vector<G1> &ps,
                                const std::vector<G2> &qs) {
    std::vector<MillerLane> lanes;
    lanes.reserve(ps.size());
    for (size_t i = 0; i < ps.size(); i++) {
        if (ps[i].is_inf() || qs[i].is_inf()) continue;  // contributes 1
        MillerLane ln;
        Fp yP;
        ps[i].to_affine(ln.xP, yP);
        Fp negyP = -yP;
        ln.A = Fp2(negyP, negyP);
        qs[i].to_affine(ln.xQ, ln.yQ);
        ln.xT = ln.xQ;
        ln.yT = ln.yQ;
        lanes.push_back(ln);
    }
    size_t k = lanes.size();
    if (k == 0) return Fp12::one();
    size_t max_chunks = k / MILLER_MIN_LANES_PER_CHUNK;
    if (max_chunks == 0) max_chunks = 1;
    size_t n_chunks = std::min<size_t>(BLS_THREADS, max_chunks);
    if (n_chunks <= 1) return miller_lanes_range(lanes, 0, k);
    std::vector<Fp12> partial(n_chunks);
    parallel_for(n_chunks, [&](size_t c) {
        size_t lo = c * k / n_chunks;
        size_t hi = (c + 1) * k / n_chunks;
        partial[c] = miller_lanes_range(lanes, lo, hi);
    });
    Fp12 f = partial[0];
    for (size_t c = 1; c < n_chunks; c++) f = f * partial[c];
    return f;
}

// Exact final exponentiation f^((p^6-1)(p^2+1)·d), d = (p^4-p^2+1)/r.
// Kept for the bls_pairing diagnostic export, whose GT output is pinned
// byte-for-byte against the pure-Python oracle.
static Fp12 final_exponentiation(const Fp12 &f) {
    Fp12 t = f.conjugate() * f.inv();    // f^(p^6 - 1)
    t = frobenius_p2(t) * t;             // ^(p^2 + 1)
    return pow_be(t, EXP_HARD, EXP_HARD_LEN, Fp12::one());
}

// Fast final exponentiation for VERIFICATION: computes f^(3·full_exp) via
// the Hayashida-Hayasaka-Teruya decomposition
//   3·(p^4-p^2+1)/r = (x-1)^2 (x+p) (x^2+p^2-1) + 3
// (identity machine-checked in tools/gen_bls_native_constants.py).  The
// extra factor 3 is coprime to the GT order r, so f^(3d) == 1 iff
// f^d == 1 — exactly what every pairing-equation check needs.  All inputs
// to the hard part lie in the cyclotomic subgroup, where conjugation is
// inversion and Granger-Scott squaring applies.
static Fp12 final_exp_fast(const Fp12 &f) {
    Fp12 m = f.conjugate() * f.inv();    // easy: f^(p^6 - 1)
    m = frobenius_p2(m) * m;             // ^(p^2 + 1)
    Fp12 t = cyc_exp_x(m) * m.conjugate();   // m^(x-1)
    Fp12 a = cyc_exp_x(t) * t.conjugate();   // m^((x-1)^2)
    Fp12 b = cyc_exp_x(a) * frobenius_p1(a); // a^(x+p)
    Fp12 c = cyc_exp_x(cyc_exp_x(b)) * frobenius_p2(b) * b.conjugate();  // b^(x^2+p^2-1)
    return c * cyclotomic_square(m) * m;     // · m^3
}

// is f == 1 up to the final exponentiation?  The single exit point for
// every verification path.
static bool pairing_product_is_one(const Fp12 &f) {
    return final_exp_fast(f) == Fp12::one();
}

// ===========================================================================
// init
// ===========================================================================

static void bls_init_impl();

// thread-safe one-time init (C++ guarantees a single racing-free run of the
// function-local static initializer; ctypes calls drop the GIL)
static void bls_init() {
    static const bool done = []() {
        bls_init_impl();
        return true;
    }();
    (void)done;
}

static void bls_init_impl() {
    memcpy(FP_R2.l, R2_MONT, sizeof(FP_R2.l));
    memcpy(FP_ONE.l, R_MONT, sizeof(FP_ONE.l));
    {
        fp r_raw;
        memcpy(r_raw.l, R_MONT, sizeof(r_raw.l));
        fp_to_mont(FP_RMODP_MONT, r_raw);
    }
    G1_GEN = G1{fp_from_limbs(G1_GEN_X), fp_from_limbs(G1_GEN_Y), Fp::one()};
    G2_GEN = G2{fp2_from_limbs(G2_GEN_X_C0, G2_GEN_X_C1),
                fp2_from_limbs(G2_GEN_Y_C0, G2_GEN_Y_C1), Fp2::one()};
    B1 = fp_from_limbs(B_G1);
    B2 = fp2_from_limbs(B_G2_C0, B_G2_C1);
    SSWU_A_C = fp2_from_limbs(SSWU_A_C0, SSWU_A_C1);
    SSWU_B_C = fp2_from_limbs(SSWU_B_C0, SSWU_B_C1);
    SSWU_Z_C = fp2_from_limbs(SSWU_Z_C0, SSWU_Z_C1);
    FQ2_SQRT_ADJ[0] = fp2_from_limbs(FQ2_SQRT_ADJ0_C0, FQ2_SQRT_ADJ0_C1);
    FQ2_SQRT_ADJ[1] = fp2_from_limbs(FQ2_SQRT_ADJ1_C0, FQ2_SQRT_ADJ1_C1);
    FQ2_SQRT_ADJ[2] = fp2_from_limbs(FQ2_SQRT_ADJ2_C0, FQ2_SQRT_ADJ2_C1);
    FQ2_SQRT_ADJ[3] = fp2_from_limbs(FQ2_SQRT_ADJ3_C0, FQ2_SQRT_ADJ3_C1);
    ISO_K1_C = {fp2_from_limbs(ISO_K1_0_C0, ISO_K1_0_C1), fp2_from_limbs(ISO_K1_1_C0, ISO_K1_1_C1),
                fp2_from_limbs(ISO_K1_2_C0, ISO_K1_2_C1), fp2_from_limbs(ISO_K1_3_C0, ISO_K1_3_C1)};
    ISO_K2_C = {fp2_from_limbs(ISO_K2_0_C0, ISO_K2_0_C1), fp2_from_limbs(ISO_K2_1_C0, ISO_K2_1_C1),
                fp2_from_limbs(ISO_K2_2_C0, ISO_K2_2_C1)};
    ISO_K3_C = {fp2_from_limbs(ISO_K3_0_C0, ISO_K3_0_C1), fp2_from_limbs(ISO_K3_1_C0, ISO_K3_1_C1),
                fp2_from_limbs(ISO_K3_2_C0, ISO_K3_2_C1), fp2_from_limbs(ISO_K3_3_C0, ISO_K3_3_C1)};
    ISO_K4_C = {fp2_from_limbs(ISO_K4_0_C0, ISO_K4_0_C1), fp2_from_limbs(ISO_K4_1_C0, ISO_K4_1_C1),
                fp2_from_limbs(ISO_K4_2_C0, ISO_K4_2_C1), fp2_from_limbs(ISO_K4_3_C0, ISO_K4_3_C1)};
    FROB2_COEF[0] = fp_from_limbs(FROB2_G0);
    FROB2_COEF[1] = fp_from_limbs(FROB2_G1);
    FROB2_COEF[2] = fp_from_limbs(FROB2_G2);
    FROB2_COEF[3] = fp_from_limbs(FROB2_G3);
    FROB2_COEF[4] = fp_from_limbs(FROB2_G4);
    FROB2_COEF[5] = fp_from_limbs(FROB2_G5);
    FROB1_COEF[0] = fp2_from_limbs(FROB1_G0_C0, FROB1_G0_C1);
    FROB1_COEF[1] = fp2_from_limbs(FROB1_G1_C0, FROB1_G1_C1);
    FROB1_COEF[2] = fp2_from_limbs(FROB1_G2_C0, FROB1_G2_C1);
    FROB1_COEF[3] = fp2_from_limbs(FROB1_G3_C0, FROB1_G3_C1);
    FROB1_COEF[4] = fp2_from_limbs(FROB1_G4_C0, FROB1_G4_C1);
    FROB1_COEF[5] = fp2_from_limbs(FROB1_G5_C0, FROB1_G5_C1);
    PSI_CX_C = fp2_from_limbs(PSI_CX_C0, PSI_CX_C1);
    PSI_CY_C = fp2_from_limbs(PSI_CY_C0, PSI_CY_C1);
    PSI2_CX_Q = fp_from_limbs(PSI2_CX);
    // orient the G1 endomorphism: whichever cube root of unity pairs with
    // eigenvalue -z^2 on the generator serves the fast membership check
    {
        G1 z2g = mul_u64(mul_u64(G1_GEN, ATE_LOOP), ATE_LOOP).neg();
        Fp beta = PSI2_CX_Q;
        for (int attempt = 0; attempt < 2; attempt++) {
            G1 sigma{G1_GEN.x * beta, G1_GEN.y, G1_GEN.z};
            if (jac_eq(sigma, z2g)) {
                G1_ENDO_BETA = beta;
                G1_FAST_CHECK_OK = true;
                break;
            }
            beta = beta.square();  // the other primitive cube root
        }
    }
    // thread budget for the batch verifier's parallel phases: hardware
    // concurrency, clamped by the CSTPU_BLS_THREADS operator knob (1
    // disables threading; results are bit-identical at every setting)
    {
        unsigned hw = std::thread::hardware_concurrency();
        if (hw == 0) hw = 1;
        const char *env = getenv("CSTPU_BLS_THREADS");
        if (env && *env) {
            long v = strtol(env, nullptr, 10);
            if (v >= 1 && v <= 1024) hw = (unsigned)v;
        }
        BLS_THREADS = hw;
    }
}

// ===========================================================================
// helpers for the ciphersuite
// ===========================================================================

// deserialize + subgroup-check; rc: 0 ok, nonzero bad
// ===========================================================================
// Pippenger multi-scalar multiplication over G1
// ===========================================================================
// The KZG commitment core: blob_to_kzg is a FIELD_ELEMENTS_PER_BLOB-point
// G1 MSM over the Lagrange trusted setup (reference capability:
// specs/eip4844/beacon-chain.md:112-120 `g1_lincomb`).  Bucketed windows
// with mixed affine additions; window width tuned for the n*(adds) +
// windows*2^c aggregation tradeoff.

static inline unsigned scalar_window(const uint8_t *s32, unsigned lo,
                                     unsigned width) {
    // bits [lo, lo+width) of a 256-bit big-endian scalar, LSB-first order
    unsigned v = 0;
    for (unsigned b = 0; b < width && lo + b < 256; b++) {
        unsigned bit = lo + b;
        v |= (unsigned)((s32[31 - bit / 8] >> (bit % 8)) & 1u) << b;
    }
    return v;
}

// bits [lo, lo+width) of a big-endian scalar of `stride` bytes
static inline unsigned scalar_window_s(const uint8_t *s, size_t stride,
                                       unsigned lo, unsigned width) {
    unsigned v = 0;
    for (unsigned b = 0; b < width && lo + b < 8 * stride; b++) {
        unsigned bit = lo + b;
        v |= (unsigned)((s[stride - 1 - bit / 8] >> (bit % 8)) & 1u) << b;
    }
    return v;
}

// Variable-base Pippenger MSM, generic over the coordinate field: the
// bucketed window machinery behind bls_g1_msm reused verbatim for G2 (the
// batch verifier's signature fold) by instantiating over Fp2.  `bits`
// bounds the scalar width so 128-bit RLC scalars pay ceil(128/c) windows
// instead of ceil(255/c); `stride` is the byte width of each big-endian
// scalar.  Window bucket passes are independent, so they fan out across
// the thread pool; the inter-window doubling chain that combines the
// window sums is inherently serial and stays on the caller.
template <class F>
static Pt<F> msm_pippenger_bits(const std::vector<F> &xs,
                                const std::vector<F> &ys,
                                const uint8_t *scalars, size_t stride,
                                unsigned bits, size_t n) {
    typedef Pt<F> P;
    if (n == 0) return P::infinity();
    // argmin over window width of the field-mul count:
    //   windows * (n mixed adds @ ~11M + 2*2^c bucket-agg adds @ ~16M)
    // ceil(bits/c) windows cover the scalar exactly (a biased form would
    // over-count an always-empty top window whenever c divides bits)
    unsigned c = 2;
    double best = 1e300;
    for (unsigned t = 2; t <= 16; t++) {
        double cost = ((bits + t - 1) / t)
                      * (n * 11.0 + (double)(size_t(1) << t) * 32.0);
        if (cost < best) { best = cost; c = t; }
    }
    unsigned n_windows = (bits + c - 1) / c;
    std::vector<P> window_sums(n_windows);
    parallel_for(n_windows, [&](size_t w) {
        std::vector<P> buckets(size_t(1) << c, P::infinity());
        unsigned lo = (unsigned)w * c;
        for (size_t i = 0; i < n; i++) {
            unsigned digit = scalar_window_s(scalars + stride * i, stride,
                                             lo, c);
            if (digit)
                buckets[digit - 1] = buckets[digit - 1].add_affine(xs[i],
                                                                   ys[i]);
        }
        // sum_d (d+1)*buckets[d] via suffix running sums
        P running = P::infinity();
        P window_sum = P::infinity();
        for (size_t d = buckets.size(); d-- > 0;) {
            if (!buckets[d].is_inf()) running = running.add(buckets[d]);
            window_sum = window_sum.add(running);
        }
        window_sums[w] = window_sum;
    });
    P acc = P::infinity();
    for (int w = (int)n_windows - 1; w >= 0; w--) {
        if (w != (int)n_windows - 1)
            for (unsigned d = 0; d < c; d++) acc = acc.dbl();
        acc = acc.add(window_sums[w]);
    }
    return acc;
}

static G1 g1_msm_pippenger(const std::vector<Fp> &xs, const std::vector<Fp> &ys,
                           const uint8_t *scalars32, size_t n) {
    return msm_pippenger_bits<Fp>(xs, ys, scalars32, 32, 255, n);
}

// Single-point short-scalar multiplication, 4-bit fixed windows off a
// 15-entry table: the singleton-group case of the batch verifier's G1
// fold (nothing for Pippenger buckets to share at n == 1, but the window
// table still beats plain double-and-add on 128-bit RLC scalars).
template <class P>
static P mul_window4(const P &pt, const uint8_t *k, size_t nbytes) {
    P table[15];
    table[0] = pt;
    for (int j = 1; j < 15; j++) table[j] = table[j - 1].add(pt);
    P r = P::infinity();
    for (size_t i = 0; i < nbytes; i++) {
        for (int half = 0; half < 2; half++) {
            unsigned d = half ? (k[i] & 0xF) : (k[i] >> 4);
            if (!r.is_inf())
                for (int s = 0; s < 4; s++) r = r.dbl();
            if (d) r = r.add(table[d - 1]);
        }
    }
    return r;
}

// Fixed-base variant: KZG commits always against the SAME trusted setup, so
// the per-point window shifts [2^(w*c)]P_i can be precomputed once.  The MSM
// then becomes a single bucket pass over n*n_windows (point, digit) pairs —
// no inter-window doublings of the accumulator at all.
static const unsigned MSM_FIXED_C = 12;  // argmin of adds: 22*(n·11) + 2^13·16

static void g1_batch_to_affine(const std::vector<G1> &pts,
                               std::vector<Fp> &xs, std::vector<Fp> &ys) {
    // Montgomery batch inversion of every z (callers guarantee no infinity)
    size_t n = pts.size();
    xs.resize(n);
    ys.resize(n);
    std::vector<Fp> prefix(n + 1);
    prefix[0] = Fp::one();
    for (size_t i = 0; i < n; i++) prefix[i + 1] = prefix[i] * pts[i].z;
    Fp inv = prefix[n].inv();
    for (size_t i = n; i-- > 0;) {
        Fp zi = inv * prefix[i];
        inv = inv * pts[i].z;
        Fp zi2 = zi.square();
        xs[i] = pts[i].x * zi2;
        ys[i] = pts[i].y * zi2 * zi;
    }
}

static bool g1_in_subgroup(const G1 &p) {
    return G1_FAST_CHECK_OK ? g1_in_subgroup_fast(p) : in_subgroup(p);
}

static int load_pubkey(G1 &out, const uint8_t pk[48]) {
    int rc = g1_deserialize(out, pk);
    if (rc) return rc;
    if (!out.is_inf() && !g1_in_subgroup(out)) return 5;
    return 0;
}

static int load_signature(G2 &out, const uint8_t sig[96]) {
    int rc = g2_deserialize(out, sig);
    if (rc) return rc;
    if (!g2_in_subgroup_fast(out)) return 5;
    return 0;
}

// ===========================================================================
// exported C ABI (all return 1=true/ok, 0=false/error unless noted)
// ===========================================================================

extern "C" {

int bls_sk_to_pk(const uint8_t sk[32], uint8_t out[48]) {
    bls_init();
    G1 pk = G1_GEN.mul_be(sk, 32);
    g1_serialize(out, pk);
    return 1;
}

int bls_sign(const uint8_t sk[32], const uint8_t *msg, size_t msg_len, uint8_t out[96]) {
    bls_init();
    G2 h = hash_to_g2(msg, msg_len, DST_POP, DST_POP_LEN);
    G2 sig = h.mul_be(sk, 32);
    g2_serialize(out, sig);
    return 1;
}

int bls_key_validate(const uint8_t pk[48]) {
    bls_init();
    G1 pt;
    if (load_pubkey(pt, pk)) return 0;
    return pt.is_inf() ? 0 : 1;
}

int bls_verify(const uint8_t pk[48], const uint8_t *msg, size_t msg_len,
               const uint8_t sig[96]) {
    bls_init();
    G1 pkpt;
    G2 sigpt;
    if (load_pubkey(pkpt, pk)) return 0;
    if (pkpt.is_inf()) return 0;
    if (load_signature(sigpt, sig)) return 0;
    G2 h = hash_to_g2(msg, msg_len, DST_POP, DST_POP_LEN);
    Fp12 f = miller_loop_product({pkpt, G1_GEN.neg()}, {h, sigpt});
    return pairing_product_is_one(f) ? 1 : 0;
}

int bls_aggregate(const uint8_t *sigs, size_t n, uint8_t out[96]) {
    bls_init();
    if (n == 0) return 0;
    G2 acc = G2::infinity();
    for (size_t i = 0; i < n; i++) {
        G2 s;
        if (load_signature(s, sigs + 96 * i)) return 0;
        acc = acc.add(s);
    }
    g2_serialize(out, acc);
    return 1;
}

int bls_aggregate_pks(const uint8_t *pks, size_t n, uint8_t out[48]) {
    bls_init();
    if (n == 0) return 0;
    G1 acc = G1::infinity();
    for (size_t i = 0; i < n; i++) {
        G1 p;
        if (load_pubkey(p, pks + 48 * i)) return 0;
        if (p.is_inf()) return 0;
        acc = acc.add(p);
    }
    g1_serialize(out, acc);
    return 1;
}

int bls_fast_aggregate_verify(const uint8_t *pks, size_t n, const uint8_t *msg,
                              size_t msg_len, const uint8_t sig[96]) {
    bls_init();
    if (n == 0) return 0;
    G2 sigpt;
    if (load_signature(sigpt, sig)) return 0;
    G1 agg = G1::infinity();
    for (size_t i = 0; i < n; i++) {
        G1 p;
        if (load_pubkey(p, pks + 48 * i)) return 0;
        if (p.is_inf()) return 0;
        agg = agg.add(p);
    }
    G2 h = hash_to_g2(msg, msg_len, DST_POP, DST_POP_LEN);
    Fp12 f = miller_loop_product({agg, G1_GEN.neg()}, {h, sigpt});
    return pairing_product_is_one(f) ? 1 : 0;
}

// Validated decompression: pk -> canonical affine x||y (48+48 bytes BE).
// rc 1 on success; 0 for malformed/out-of-subgroup/infinity keys.
int bls_decompress_pubkey(const uint8_t pk[48], uint8_t out_xy[96]) {
    bls_init();
    G1 pt;
    if (load_pubkey(pt, pk)) return 0;
    if (pt.is_inf()) return 0;
    Fp x, y;
    pt.to_affine(x, y);
    fp_to_bytes48(out_xy, x);
    fp_to_bytes48(out_xy + 48, y);
    return 1;
}

// Batched validated decompression over the thread pool: n compressed
// pubkeys -> n affine x||y rows + per-key ok flags (0 marks malformed/
// out-of-subgroup/infinity; its row is left untouched).  One native call
// instead of n ctypes round-trips — the registry affine-matrix cold
// build is the consumer (each key's sqrt + subgroup check is independent
// work, so the shared-counter parallel_for self-balances).  Always
// returns 1; validity is per-key in out_ok.
int bls_decompress_pubkeys(const uint8_t *pks, size_t n, uint8_t *out_xys,
                           uint8_t *out_ok) {
    bls_init();
    parallel_for(n, [&](size_t i) {
        G1 pt;
        if (load_pubkey(pt, pks + 48 * i) || pt.is_inf()) {
            out_ok[i] = 0;
            return;
        }
        Fp x, y;
        pt.to_affine(x, y);
        fp_to_bytes48(out_xys + 96 * i, x);
        fp_to_bytes48(out_xys + 96 * i + 48, y);
        out_ok[i] = 1;
    });
    return 1;
}

// FastAggregateVerify over pre-decompressed affine pubkeys (from
// bls_decompress_pubkey, cached by the caller): no square roots, no
// subgroup checks — the decompression already established both.
int bls_fast_aggregate_verify_affine(const uint8_t *xys, size_t n,
                                     const uint8_t *msg, size_t msg_len,
                                     const uint8_t sig[96]) {
    bls_init();
    if (n == 0) return 0;
    G2 sigpt;
    if (load_signature(sigpt, sig)) return 0;
    G1 agg = G1::infinity();
    for (size_t i = 0; i < n; i++) {
        Fp x, y;
        if (!fp_from_bytes48(x, xys + 96 * i)) return 0;
        if (!fp_from_bytes48(y, xys + 96 * i + 48)) return 0;
        agg = agg.add(G1{x, y, Fp::one()});
    }
    G2 h = hash_to_g2(msg, msg_len, DST_POP, DST_POP_LEN);
    Fp12 f = miller_loop_product({agg, G1_GEN.neg()}, {h, sigpt});
    return pairing_product_is_one(f) ? 1 : 0;
}

// msgs: concatenated message bytes; msg_lens[i] the length of message i
int bls_aggregate_verify(const uint8_t *pks, size_t n, const uint8_t *msgs,
                         const size_t *msg_lens, const uint8_t sig[96]) {
    bls_init();
    if (n == 0) return 0;
    G2 sigpt;
    if (load_signature(sigpt, sig)) return 0;
    std::vector<G1> ps;
    std::vector<G2> qs;
    size_t off = 0;
    for (size_t i = 0; i < n; i++) {
        G1 p;
        if (load_pubkey(p, pks + 48 * i)) return 0;
        if (p.is_inf()) return 0;
        ps.push_back(p);
        qs.push_back(hash_to_g2(msgs + off, msg_lens[i], DST_POP, DST_POP_LEN));
        off += msg_lens[i];
    }
    ps.push_back(G1_GEN.neg());
    qs.push_back(sigpt);
    Fp12 f = miller_loop_product(ps, qs);
    return pairing_product_is_one(f) ? 1 : 0;
}

// Batched FastAggregateVerify: k aggregate checks collapsed into ONE final
// exponentiation via a random linear combination (the standard batch
// verification of Bellare-Garay-Rabin applied to pairing equations):
//
//   each item i asserts   e(agg_i, H(m_i)) · e(-g1, sig_i) = 1
//   batch asserts         prod_i [ e([r_i]agg_i, H(m_i)) ] · e(-g1, sum_i [r_i]sig_i) = 1
//
// with independent 128-bit scalars r_i drawn from a SHA-256 counter DRBG
// over the caller's seed.  If every item verifies the batch always passes;
// if any item fails, the batch passes with probability <= 2^-128 over the
// seed.  Per item: one Miller loop + one hash-to-curve + two short scalar
// mults — the k-1 saved final exponentiations are the whole win.
// Role analogue: the reference's milagro slot makes per-signature pairing
// cheap enough for CI (eth2spec/utils/bls.py:8-30); this makes the mainnet
// workload cheap the algorithmic way instead.
static void rlc_scalar(uint8_t out16[16], const uint8_t seed[32], uint64_t i) {
    Sha256 s;
    s.update(seed, 32);
    uint8_t ctr[8];
    for (int b = 0; b < 8; b++) ctr[b] = (uint8_t)(i >> (8 * b));
    s.update(ctr, 8);
    uint8_t d[32];
    s.final(d);
    memcpy(out16, d, 16);
}

// Affine-pubkey variant (coordinates from bls_decompress_pubkey, already
// validated + subgroup-checked by the caller's cache).  xys holds the
// members of every item back to back; pk_counts[i] says how many belong to
// item i.  Returns 1 iff every item's aggregate signature verifies.
//
// The interior is built around three foldings (ISSUE 7 tentpole):
//
//   1. the G2 signature fold sum_i [r_i]sig_i runs as ONE variable-base
//      Pippenger MSM (128-bit windows) instead of k serial double-and-add
//      chains;
//   2. lanes whose messages are byte-identical share one hash_to_g2 (a
//      bounded cache fronts even that) and fold their RLC-scaled G1
//      points into a single Miller lane via e([r1]P1 + [r2]P2, Q) — the
//      group's fold is itself a bucketed MSM when more than one lane
//      lands in it, a 4-bit-window mult when only one does;
//   3. the multi-pairing's Miller loop runs lane-parallel on the thread
//      pool (miller_loop_product: chunked partial products, fixed merge
//      order, one shared final exponentiation).
//
// Bilinearity makes the folded product equal the unfolded one exactly, so
// the BGR98 soundness argument (<= 2^-128 over the seed) is untouched,
// and the caller's bisection-on-failure contract (BDLO12-style forgery
// identification in stf/verify.py) keeps working: a sub-batch call simply
// re-folds within the subset it was handed.
//
// `phases`, when non-null, receives wall seconds of the four interior
// phases: [hash_to_g2, msm, miller+final-exp, marshal].
static int batch_fast_aggregate_verify_impl(
    size_t k, const uint8_t *xys, const size_t *pk_counts,
    const uint8_t *msgs, const size_t *msg_lens,
    const uint8_t *sigs, const uint8_t seed[32], double *phases) {
    bls_init();
    if (phases) phases[0] = phases[1] = phases[2] = phases[3] = 0.0;
    if (k == 0) return 1;  // vacuous batch
    double t0 = monotonic_seconds();

    // -- marshal: per-item signature load + member aggregation (parallel;
    // the G2 deserialization pays an Fp2 square root per signature)
    std::vector<size_t> pk_offs(k + 1, 0), msg_offs(k + 1, 0);
    for (size_t i = 0; i < k; i++) {
        pk_offs[i + 1] = pk_offs[i] + pk_counts[i];
        msg_offs[i + 1] = msg_offs[i] + msg_lens[i];
    }
    std::vector<G2> sigpts(k);
    std::vector<G1> aggs(k);
    std::atomic<int> bad{0};
    parallel_for(k, [&](size_t i) {
        if (pk_counts[i] == 0) { bad.store(1); return; }
        if (load_signature(sigpts[i], sigs + 96 * i)) { bad.store(1); return; }
        G1 agg = G1::infinity();
        for (size_t j = 0; j < pk_counts[i]; j++) {
            Fp x, y;
            if (!fp_from_bytes48(x, xys + 96 * (pk_offs[i] + j))) {
                bad.store(1);
                return;
            }
            if (!fp_from_bytes48(y, xys + 96 * (pk_offs[i] + j) + 48)) {
                bad.store(1);
                return;
            }
            agg = agg.add_affine(x, y);
        }
        aggs[i] = agg;
    });
    if (bad.load()) return 0;

    std::vector<uint8_t> rlc(16 * k);
    for (size_t i = 0; i < k; i++)
        rlc_scalar(&rlc[16 * i], seed, (uint64_t)i);

    // -- same-message lane folding: group items by message bytes
    struct MsgGroup {
        size_t off, len;
        std::vector<size_t> items;
        G2 h;
        G1 folded;
    };
    std::vector<MsgGroup> groups;
    {
        std::unordered_map<std::string, size_t> index;
        for (size_t i = 0; i < k; i++) {
            std::string key((const char *)(msgs + msg_offs[i]), msg_lens[i]);
            auto it = index.find(key);
            if (it == index.end()) {
                index.emplace(std::move(key), groups.size());
                groups.push_back(MsgGroup{msg_offs[i], msg_lens[i], {i},
                                          G2::infinity(), G1::infinity()});
            } else {
                groups[it->second].items.push_back(i);
            }
        }
    }
    double t1 = monotonic_seconds();

    // -- hash_to_g2: once per UNIQUE message, cache-fronted, parallel
    parallel_for(groups.size(), [&](size_t g) {
        groups[g].h = hash_to_g2_pop_cached(msgs + groups[g].off,
                                            groups[g].len);
    });
    double t2 = monotonic_seconds();

    // -- msm: the G2 signature fold as one bucketed pass, then the G1
    // fold of every message group (MSM for multi-lane groups, windowed
    // mult for singletons).  Infinity points contribute the identity and
    // are skipped — batch affine normalization requires z != 0.
    G2 sig_sum;
    {
        std::vector<Fp2> sx, sy;
        std::vector<uint8_t> ss;
        sx.reserve(k);
        sy.reserve(k);
        ss.reserve(16 * k);
        for (size_t i = 0; i < k; i++) {
            if (sigpts[i].is_inf()) continue;  // deserialized affine: z == 1
            sx.push_back(sigpts[i].x);
            sy.push_back(sigpts[i].y);
            ss.insert(ss.end(), &rlc[16 * i], &rlc[16 * i] + 16);
        }
        sig_sum = msm_pippenger_bits<Fp2>(sx, sy, ss.data(), 16, 128,
                                          sx.size());
    }
    // one batched affine normalization of every non-infinity aggregate
    std::vector<Fp> ax(k), ay(k);
    std::vector<char> a_inf(k, 0);
    {
        std::vector<G1> live;
        std::vector<size_t> live_idx;
        live.reserve(k);
        for (size_t i = 0; i < k; i++) {
            if (aggs[i].is_inf()) a_inf[i] = 1;
            else { live.push_back(aggs[i]); live_idx.push_back(i); }
        }
        std::vector<Fp> lx, ly;
        g1_batch_to_affine(live, lx, ly);
        for (size_t j = 0; j < live_idx.size(); j++) {
            ax[live_idx[j]] = lx[j];
            ay[live_idx[j]] = ly[j];
        }
    }
    parallel_for(groups.size(), [&](size_t g) {
        MsgGroup &grp = groups[g];
        std::vector<Fp> gx, gy;
        std::vector<uint8_t> gs;
        for (size_t i : grp.items) {
            if (a_inf[i]) continue;
            gx.push_back(ax[i]);
            gy.push_back(ay[i]);
            gs.insert(gs.end(), &rlc[16 * i], &rlc[16 * i] + 16);
        }
        if (gx.empty())
            grp.folded = G1::infinity();
        else if (gx.size() == 1)
            grp.folded = mul_window4(G1{gx[0], gy[0], Fp::one()},
                                     gs.data(), 16);
        else
            grp.folded = msm_pippenger_bits<Fp>(gx, gy, gs.data(), 16, 128,
                                                gx.size());
    });
    double t3 = monotonic_seconds();

    // -- the whole batch is ONE multi-pairing: one lane per unique
    // message plus the folded-signature lane, chunk-parallel Miller,
    // shared final exponentiation
    std::vector<G1> ps;
    std::vector<G2> qs;
    ps.reserve(groups.size() + 1);
    qs.reserve(groups.size() + 1);
    for (MsgGroup &grp : groups) {
        ps.push_back(grp.folded);
        qs.push_back(grp.h);
    }
    ps.push_back(G1_GEN.neg());
    qs.push_back(sig_sum);
    Fp12 f = miller_loop_product(ps, qs);
    int ok = pairing_product_is_one(f) ? 1 : 0;
    double t4 = monotonic_seconds();
    if (phases) {
        phases[0] = t2 - t1;  // hash_to_g2
        phases[1] = t3 - t2;  // msm (G2 fold + per-group G1 folds)
        phases[2] = t4 - t3;  // miller + final exponentiation
        phases[3] = t1 - t0;  // marshal (sig loads, member sums, grouping)
    }
    return ok;
}

int bls_batch_fast_aggregate_verify_affine(
    size_t k, const uint8_t *xys, const size_t *pk_counts,
    const uint8_t *msgs, const size_t *msg_lens,
    const uint8_t *sigs, const uint8_t seed[32]) {
    return batch_fast_aggregate_verify_impl(k, xys, pk_counts, msgs,
                                            msg_lens, sigs, seed, nullptr);
}

// Timed variant: identical verdict, plus the per-phase wall-second
// breakdown [hash_to_g2, msm, miller, marshal] the engine's verify stats
// attribute regressions with.
int bls_batch_fast_aggregate_verify_affine_timed(
    size_t k, const uint8_t *xys, const size_t *pk_counts,
    const uint8_t *msgs, const size_t *msg_lens,
    const uint8_t *sigs, const uint8_t seed[32], double phases_out[4]) {
    return batch_fast_aggregate_verify_impl(k, xys, pk_counts, msgs,
                                            msg_lens, sigs, seed,
                                            phases_out);
}

// G2 MSM: n compressed G2 points (96 bytes each, fully validated incl.
// the psi-based subgroup check), n scalars as 32-byte big-endian integers
// (caller reduces mod r).  out = compressed sum_i [s_i]Q_i.  rc 1 on
// success, 0 when any point is malformed or outside the r-order subgroup.
// Infinity points are legal and contribute the identity.  This is the
// differential pin for the bucketed G2 machinery the batch verifier's
// signature fold runs on.
int bls_g2_msm(const uint8_t *points, const uint8_t *scalars32, size_t n,
               uint8_t out[96]) {
    bls_init();
    std::vector<Fp2> xs, ys;
    std::vector<uint8_t> ss;
    xs.reserve(n);
    ys.reserve(n);
    ss.reserve(32 * n);
    for (size_t i = 0; i < n; i++) {
        G2 q;
        if (load_signature(q, points + 96 * i)) return 0;
        if (q.is_inf()) continue;
        xs.push_back(q.x);  // deserialized affine: z == 1
        ys.push_back(q.y);
        ss.insert(ss.end(), scalars32 + 32 * i, scalars32 + 32 * i + 32);
    }
    G2 r = msm_pippenger_bits<Fp2>(xs, ys, ss.data(), 32, 255, xs.size());
    g2_serialize(out, r);
    return 1;
}

// hash_to_g2 cache telemetry + measurement control (bench cold-start
// symmetry: an A/B leg that should pay its own hashing must not inherit
// the other leg's warm cache)
int bls_h2c_cache_stats(uint64_t out[3]) {
    std::lock_guard<std::mutex> lk(H2C_MU);
    out[0] = H2C_HITS;
    out[1] = H2C_MISSES;
    out[2] = (uint64_t)H2C_MAP.size();
    return 1;
}

int bls_h2c_cache_clear(void) {
    std::lock_guard<std::mutex> lk(H2C_MU);
    H2C_MAP.clear();
    H2C_FIFO.clear();
    H2C_HITS = 0;
    H2C_MISSES = 0;
    return 1;
}

// G1 MSM: n points as canonical affine x||y (96 bytes each, e.g. a KZG
// trusted setup), n scalars as 32-byte big-endian integers (caller reduces
// mod r).  out = compressed sum_i [s_i]P_i.  rc 1 on success; 0 when any
// coordinate is non-canonical or any point is off-curve.  Subgroup
// membership is the CALLER's invariant (setup points are multiples of the
// generator by construction).
int bls_g1_msm(const uint8_t *xys, const uint8_t *scalars32, size_t n,
               uint8_t out[48]) {
    bls_init();
    std::vector<Fp> xs(n), ys(n);
    for (size_t i = 0; i < n; i++) {
        if (!fp_from_bytes48(xs[i], xys + 96 * i)) return 0;
        if (!fp_from_bytes48(ys[i], xys + 96 * i + 48)) return 0;
        if (!g1_on_curve(xs[i], ys[i])) return 0;
    }
    G1 r = g1_msm_pippenger(xs, ys, scalars32, n);
    g1_serialize(out, r);
    return 1;
}

// Fixed-base MSM precomputation: expand n affine points into the shifted
// window table [[2^(w*c)]P_i for w in 0..n_windows), laid out window-major
// (all points of window 0, then window 1, ...).  Entries are RAW MONTGOMERY
// limb pairs (2 x 48 bytes, machine byte order) — the table is an opaque
// in-process cache handed straight back to bls_g1_msm_fixed, so skipping
// canonical encode/decode saves a to/from-Montgomery multiply per coordinate
// per bucket add.  out_table must hold n * n_windows * 96 bytes.  rc =
// n_windows on success, 0 on bad input.
// Window count of the fixed-base layout — Python sizes the table buffer
// from THIS export so the two sides can never drift.
int bls_g1_msm_fixed_windows(void) { return (int)((255 + MSM_FIXED_C - 1) / MSM_FIXED_C); }

int bls_g1_msm_precompute(const uint8_t *xys, size_t n, uint8_t *out_table) {
    bls_init();
    const unsigned c = MSM_FIXED_C;
    const unsigned n_windows = (255 + c - 1) / c;
    if (n == 0) return (int)n_windows;
    std::vector<G1> shifted(n * n_windows);
    for (size_t i = 0; i < n; i++) {
        Fp x, y;
        if (!fp_from_bytes48(x, xys + 96 * i)) return 0;
        if (!fp_from_bytes48(y, xys + 96 * i + 48)) return 0;
        if (!g1_on_curve(x, y)) return 0;
        G1 p{x, y, Fp::one()};
        for (unsigned w = 0; w < n_windows; w++) {
            shifted[w * n + i] = p;
            if (w + 1 < n_windows)
                for (unsigned d = 0; d < c; d++) p = p.dbl();
        }
    }
    std::vector<Fp> xs, ys;
    g1_batch_to_affine(shifted, xs, ys);
    for (size_t j = 0; j < shifted.size(); j++) {
        memcpy(out_table + 96 * j, xs[j].v.l, 48);
        memcpy(out_table + 96 * j + 48, ys[j].v.l, 48);
    }
    return (int)n_windows;
}

// Fixed-base MSM over a precomputed shifted-window table: ONE bucket pass —
// digit d of scalar i's window w sends table entry (w, i) to bucket d, with
// no accumulator doubling chain.  Bucket accumulation is fully batch-affine:
// entries are grouped by digit (counting sort), then each group collapses by
// pairwise tree reduction where every round's slope denominators share ONE
// modular inversion (Montgomery batching) — an affine add costs ~6 field
// muls against the ~11 of a mixed Jacobian add, and the tree shape keeps
// same-bucket streaks (constant blobs) at log depth instead of a serial
// chain.
int bls_g1_msm_fixed(const uint8_t *table, size_t n, const uint8_t *scalars32,
                     uint8_t out[48]) {
    bls_init();
    const unsigned c = MSM_FIXED_C;
    const unsigned n_windows = (255 + c - 1) / c;
    const size_t n_groups = (size_t(1) << c) - 1;

    // Cheap sanity probe of the opaque table: entries are raw Montgomery
    // limb pairs, so a table persisted by an incompatible build (different
    // limb layout / byte order) or a torn write decodes to coordinates off
    // the curve.  Checking the first entry costs two 48-byte copies and
    // one curve evaluation — the documented "corrupted MSM table" failure
    // mode in native.py G1MSMFixed is only real because of this check.
    if (n > 0) {
        Fp x0, y0;
        memcpy(x0.v.l, table, 48);
        memcpy(y0.v.l, table + 48, 48);
        if (!g1_on_curve(x0, y0)) return 0;
    }

    // digit extraction + counting sort by bucket
    std::vector<uint16_t> digits((size_t)n_windows * n);
    std::vector<uint32_t> count(n_groups, 0);
    for (unsigned w = 0; w < n_windows; w++)
        for (size_t i = 0; i < n; i++) {
            unsigned d = scalar_window(scalars32 + 32 * i, w * c, c);
            digits[(size_t)w * n + i] = (uint16_t)d;
            if (d) count[d - 1]++;
        }
    std::vector<size_t> start(n_groups + 1, 0);
    for (size_t g = 0; g < n_groups; g++) start[g + 1] = start[g] + count[g];
    std::vector<Fp> wx(start[n_groups]), wy(start[n_groups]);
    {
        std::vector<size_t> cursor(start.begin(), start.end() - 1);
        for (unsigned w = 0; w < n_windows; w++) {
            const uint8_t *win = table + (size_t)96 * w * n;
            for (size_t i = 0; i < n; i++) {
                unsigned d = digits[(size_t)w * n + i];
                if (!d) continue;
                size_t k = cursor[d - 1]++;
                memcpy(wx[k].v.l, win + 96 * i, 48);
                memcpy(wy[k].v.l, win + 96 * i + 48, 48);
            }
        }
    }
    std::vector<size_t> gsize(count.begin(), count.end());

    // pairwise tree reduction, one shared inversion per round.  In-place is
    // safe: op j of a group writes slot (start + write-cursor <= j) after
    // every read of that slot (pair reads are at 2j, 2j+1 >= j+1 for later
    // ops; processing is in-order).
    struct Op {
        size_t grp, a, b;
        bool dbl, cancel;
    };
    std::vector<Op> ops;
    std::vector<Fp> denoms, pref, dinv;
    for (;;) {
        ops.clear();
        denoms.clear();
        for (size_t g = 0; g < n_groups; g++) {
            size_t s = gsize[g];
            if (s < 2) continue;
            for (size_t j = 0; j + 1 < s; j += 2) {
                size_t a = start[g] + j, b = a + 1;
                Op op{g, a, b, false, false};
                if (wx[a] == wx[b]) {
                    if (wy[a] == wy[b]) {
                        op.dbl = true;
                        denoms.push_back(wy[a] + wy[a]);  // y != 0: odd order
                    } else {
                        op.cancel = true;  // P + (-P): drop both
                        denoms.push_back(Fp::one());
                    }
                } else {
                    denoms.push_back(wx[b] - wx[a]);
                }
                ops.push_back(op);
            }
        }
        if (ops.empty()) break;
        size_t m = denoms.size();
        pref.assign(m + 1, Fp::one());
        for (size_t i = 0; i < m; i++) pref[i + 1] = pref[i] * denoms[i];
        Fp inv = pref[m].inv();
        dinv.resize(m);
        for (size_t i = m; i-- > 0;) {
            dinv[i] = inv * pref[i];
            inv = inv * denoms[i];
        }
        size_t gi = 0, wcur = 0;
        bool have_group = false;
        auto close_group = [&](size_t g) {
            // odd leftover slides down next to the written results
            if (gsize[g] & 1) {
                size_t last = start[g] + gsize[g] - 1;
                size_t dst = start[g] + wcur;
                if (dst != last) {
                    wx[dst] = wx[last];
                    wy[dst] = wy[last];
                }
                wcur++;
            }
            gsize[g] = wcur;
        };
        for (size_t i = 0; i < m; i++) {
            const Op &op = ops[i];
            if (!have_group || op.grp != gi) {
                if (have_group) close_group(gi);
                gi = op.grp;
                wcur = 0;
                have_group = true;
            }
            if (op.cancel) continue;
            Fp ax = wx[op.a], ay = wy[op.a];
            Fp l;
            if (op.dbl) {
                Fp x2 = ax.square();
                l = (x2 + x2 + x2) * dinv[i];
            } else {
                l = (wy[op.b] - ay) * dinv[i];
            }
            Fp x3 = l.square() - ax - wx[op.b];
            Fp y3 = l * (ax - x3) - ay;
            size_t dst = start[gi] + wcur++;
            wx[dst] = x3;
            wy[dst] = y3;
        }
        if (have_group) close_group(gi);
    }

    // suffix running sums: acc = sum_d (d+1) * bucket_d
    G1 running = G1::infinity();
    G1 acc = G1::infinity();
    for (size_t d = n_groups; d-- > 0;) {
        if (gsize[d]) running = running.add_affine(wx[start[d]], wy[start[d]]);
        acc = acc.add(running);
    }
    g1_serialize(out, acc);
    return 1;
}

// test/diagnostic exports ---------------------------------------------------

int bls_hash_to_g2(const uint8_t *msg, size_t msg_len, const uint8_t *dst,
                   size_t dst_len, uint8_t out[96]) {
    bls_init();
    if (dst_len > 255) return 0;  // RFC 9380: DST must be <= 255 bytes
    G2 h = hash_to_g2(msg, msg_len, dst, dst_len);
    g2_serialize(out, h);
    return 1;
}

int bls_initialize() {
    bls_init();
    return 1;
}

// e(P, Q) -> 12 canonical 48-byte big-endian Fp values, order:
// (c0|c1) x (c0,c1,c2 of Fp6) x (c0,c1 of Fp2)
int bls_pairing(const uint8_t p48[48], const uint8_t q96[96], uint8_t out[576]) {
    bls_init();
    G1 p;
    G2 q;
    if (load_pubkey(p, p48)) return 0;
    if (load_signature(q, q96)) return 0;
    Fp12 f = final_exponentiation(miller_loop(p, q));
    const Fp2 *coeffs[6] = {&f.c0.c0, &f.c0.c1, &f.c0.c2, &f.c1.c0, &f.c1.c1, &f.c1.c2};
    for (int i = 0; i < 6; i++) {
        fp_to_bytes48(out + 96 * i, coeffs[i]->c0);
        fp_to_bytes48(out + 96 * i + 48, coeffs[i]->c1);
    }
    return 1;
}

int bls_sha256(const uint8_t *msg, size_t n, uint8_t out[32]) {
    Sha256 s;
    s.update(msg, n);
    s.final(out);
    return 1;
}

}  // extern "C"
