"""ctypes loader + API for the native C++ BLS12-381 backend.

Fills the reference's "fast host BLS" slot (eth2spec/utils/bls.py:8-30
selects a Rust milagro binding for CI speed); here the fast path is a
from-scratch C++ implementation compiled on first use with g++ and cached
next to the source, keyed by a content hash so edits rebuild automatically.

Exposes the same API surface as crypto/bls/ciphersuite.py so the selector
in crypto/bls/__init__.py can register it verbatim.  Raises ImportError on
any build/load failure — callers fall back to the pure-Python oracle.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import time
from typing import Sequence

_HERE = os.path.join(os.path.dirname(__file__), "native")
_SOURCES = ("bls12_381.cpp", "bls_constants.h")

G2_POINT_AT_INFINITY = bytes([0xC0]) + b"\x00" * 95

# subgroup order (for secret-key range checks, mirrors ciphersuite._sk_to_int)
_R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001


def _source_digest() -> str:
    h = hashlib.sha256()
    for name in _SOURCES:
        with open(os.path.join(_HERE, name), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _build() -> str:
    digest = _source_digest()
    so_path = os.path.join(_HERE, f"_bls_{digest}.so")
    if os.path.exists(so_path):
        return so_path
    # stale artifacts from older sources
    for f in os.listdir(_HERE):
        if f.startswith("_bls_") and f.endswith(".so"):
            try:
                os.unlink(os.path.join(_HERE, f))
            except OSError:
                pass
    src = os.path.join(_HERE, "bls12_381.cpp")
    with tempfile.NamedTemporaryFile(suffix=".so", dir=_HERE, delete=False) as tmp:
        tmp_path = tmp.name
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-fno-exceptions", "-fno-rtti", "-pthread",
        src, "-o", tmp_path,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as exc:
        os.unlink(tmp_path)
        raise ImportError(f"native BLS build failed to launch: {exc}") from exc
    if proc.returncode != 0:
        os.unlink(tmp_path)
        raise ImportError(f"native BLS build failed:\n{proc.stderr[-2000:]}")
    # durable-io: the .so is a compiler OUTPUT promoted whole — the
    # envelope cannot wrap a dlopen target, and staleness is already
    # governed by the source-digest in its filename
    os.replace(tmp_path, so_path)  # atomic: concurrent builders converge
    return so_path


def _load() -> ctypes.CDLL:
    lib = ctypes.CDLL(_build())
    u8p = ctypes.POINTER(ctypes.c_uint8)

    def sig(name, *argtypes):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = list(argtypes)
        return fn

    sz = ctypes.c_size_t
    sig("bls_sk_to_pk", u8p, u8p)
    sig("bls_sign", u8p, u8p, sz, u8p)
    sig("bls_key_validate", u8p)
    sig("bls_verify", u8p, u8p, sz, u8p)
    sig("bls_aggregate", u8p, sz, u8p)
    sig("bls_aggregate_pks", u8p, sz, u8p)
    sig("bls_fast_aggregate_verify", u8p, sz, u8p, sz, u8p)
    sig("bls_decompress_pubkey", u8p, u8p)
    sig("bls_decompress_pubkeys", u8p, sz, u8p, u8p)
    sig("bls_fast_aggregate_verify_affine", u8p, sz, u8p, sz, u8p)
    sig("bls_aggregate_verify", u8p, sz, u8p, ctypes.POINTER(sz), u8p)
    sig("bls_batch_fast_aggregate_verify_affine",
        sz, u8p, ctypes.POINTER(sz), u8p, ctypes.POINTER(sz), u8p, u8p)
    sig("bls_batch_fast_aggregate_verify_affine_timed",
        sz, u8p, ctypes.POINTER(sz), u8p, ctypes.POINTER(sz), u8p, u8p,
        ctypes.POINTER(ctypes.c_double))
    sig("bls_g1_msm", u8p, u8p, sz, u8p)
    sig("bls_g2_msm", u8p, u8p, sz, u8p)
    sig("bls_h2c_cache_stats", ctypes.POINTER(ctypes.c_uint64))
    sig("bls_h2c_cache_clear")
    sig("bls_g1_msm_precompute", u8p, sz, u8p)
    sig("bls_g1_msm_fixed", u8p, sz, u8p, u8p)
    sig("bls_g1_msm_fixed_windows")
    sig("bls_hash_to_g2", u8p, sz, u8p, sz, u8p)
    sig("bls_pairing", u8p, u8p, u8p)
    sig("bls_sha256", u8p, sz, u8p)
    sig("bls_initialize")
    return lib


try:
    _lib = _load()
    _lib.bls_initialize()  # under the import lock: constants ready before any
    # ctypes call can release the GIL mid-init
except ImportError:
    raise
except Exception as exc:  # missing sources, read-only tree, dlopen failure...
    raise ImportError(f"native BLS unavailable: {exc}") from exc


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else \
        ctypes.cast(ctypes.c_char_p(b"\x00"), ctypes.POINTER(ctypes.c_uint8))


def _sk_to_bytes(sk) -> bytes:
    v = int(sk) if isinstance(sk, int) else int.from_bytes(bytes(sk), "big")
    if not 0 < v < _R:
        raise ValueError("secret key out of range")
    return v.to_bytes(32, "big")


def SkToPk(sk) -> bytes:
    out = (ctypes.c_uint8 * 48)()
    _lib.bls_sk_to_pk(_buf(_sk_to_bytes(sk)), out)
    return bytes(out)


def Sign(sk, message: bytes) -> bytes:
    msg = bytes(message)
    out = (ctypes.c_uint8 * 96)()
    _lib.bls_sign(_buf(_sk_to_bytes(sk)), _buf(msg), len(msg), out)
    return bytes(out)


def KeyValidate(pubkey: bytes) -> bool:
    pk = bytes(pubkey)
    if len(pk) != 48:
        return False
    return bool(_lib.bls_key_validate(_buf(pk)))


def Verify(pubkey: bytes, message: bytes, signature: bytes) -> bool:
    pk, msg, sig = bytes(pubkey), bytes(message), bytes(signature)
    if len(pk) != 48 or len(sig) != 96:
        return False
    return bool(_lib.bls_verify(_buf(pk), _buf(msg), len(msg), _buf(sig)))


def Aggregate(signatures: Sequence[bytes]) -> bytes:
    sigs = [bytes(s) for s in signatures]
    if len(sigs) == 0:
        raise ValueError("cannot aggregate zero signatures")
    if any(len(s) != 96 for s in sigs):
        raise ValueError("malformed signature length")
    flat = b"".join(sigs)
    out = (ctypes.c_uint8 * 96)()
    if not _lib.bls_aggregate(_buf(flat), len(sigs), out):
        # reproduce the oracle's exact exception type (DeserializationError
        # vs ValueError) so backend choice never changes caller behavior
        from . import ciphersuite as _py

        return _py.Aggregate(sigs)
    return bytes(out)


def AggregatePKs(pubkeys: Sequence[bytes]) -> bytes:
    pks = [bytes(p) for p in pubkeys]
    if len(pks) == 0:
        raise ValueError("cannot aggregate zero pubkeys")
    if any(len(p) != 48 for p in pks):
        raise ValueError("malformed pubkey length")
    flat = b"".join(pks)
    out = (ctypes.c_uint8 * 48)()
    if not _lib.bls_aggregate_pks(_buf(flat), len(pks), out):
        from . import ciphersuite as _py

        return _py.AggregatePKs(pks)
    return bytes(out)


# Validated + decompressed pubkeys (canonical affine x||y): the same
# validator keys recur in every attestation, so later aggregates skip both
# the subgroup scalar mult and the decompression square root (same idea as
# the oracle's lru_cache on pubkey_to_point, curve.py:269-276).
_AFFINE_PKS: dict = {}
_AFFINE_PKS_MAX = 1 << 20


def _affine_of(pk: bytes):
    """96-byte affine coordinates for a validated pubkey, or None if the
    key is malformed/out-of-subgroup/infinity."""
    cached = _AFFINE_PKS.get(pk)
    if cached is not None:
        return cached
    out = (ctypes.c_uint8 * 96)()
    if not _lib.bls_decompress_pubkey(_buf(pk), out):
        return None
    xy = bytes(out)
    if len(_AFFINE_PKS) < _AFFINE_PKS_MAX:
        _AFFINE_PKS[pk] = xy
    return xy


def FastAggregateVerify(pubkeys: Sequence[bytes], message: bytes, signature: bytes) -> bool:
    pks = [bytes(p) for p in pubkeys]
    sig = bytes(signature)
    if len(pks) == 0 or len(sig) != 96 or any(len(p) != 48 for p in pks):
        return False
    msg = bytes(message)
    affines = []
    for p in pks:
        xy = _affine_of(p)
        if xy is None:
            return False  # invalid pubkey: the aggregate cannot verify
        affines.append(xy)
    flat = b"".join(affines)
    return bool(
        _lib.bls_fast_aggregate_verify_affine(
            _buf(flat), len(pks), _buf(msg), len(msg), _buf(sig)
        )
    )


def _batch_call_flat(counts, flat: bytes, msgs, sigs, seed, stats=None) -> bool:
    """The ONE marshal + seed-handling path under both batch entry points:
    packs the flat-affine buffers, draws the RLC seed (``os.urandom`` once
    per batch unless a deterministic seed is supplied), and dispatches the
    timed native call.  ``stats``, when given, is a mutable mapping whose
    ``hash_to_g2_s``/``msm_s``/``miller_s``/``marshal_s`` keys accumulate
    the native per-phase breakdown plus this function's own Python-side
    marshalling time."""
    k = len(counts)
    if seed is None:
        seed = os.urandom(32)
    elif len(seed) != 32:
        # the C DRBG unconditionally reads 32 bytes; fail fast rather than
        # hand it a short buffer
        raise ValueError(f"seed must be exactly 32 bytes, got {len(seed)}")
    t0 = time.perf_counter()
    args = (
        k,
        _buf(flat),
        (ctypes.c_size_t * k)(*counts),
        _buf(b"".join(msgs)),
        (ctypes.c_size_t * k)(*[len(m) for m in msgs]),
        _buf(b"".join(sigs)),
        _buf(seed),
    )
    py_marshal = time.perf_counter() - t0
    phases = (ctypes.c_double * 4)()
    ok = bool(_lib.bls_batch_fast_aggregate_verify_affine_timed(
        *args, phases))
    if stats is not None:
        stats["hash_to_g2_s"] += phases[0]
        stats["msm_s"] += phases[1]
        stats["miller_s"] += phases[2]
        stats["marshal_s"] += phases[3] + py_marshal
    return ok


def BatchFastAggregateVerify(items, seed: bytes = None, stats=None) -> bool:
    """Batched FastAggregateVerify: ``items`` is a sequence of
    ``(pubkeys, message, signature)`` triples; True iff EVERY item verifies.

    One random-linear-combination pairing product with a single shared
    final exponentiation (C side: bls_batch_fast_aggregate_verify_affine,
    MSM-folded interior).  Soundness 2^-128 per batch over the RLC seed
    (os.urandom unless a deterministic ``seed`` is supplied for test
    replay).  This is the capability the reference's milagro slot exists
    for — BLS cheap enough for the mainnet workload (reference seam:
    eth2spec/utils/bls.py:67-74).  The compressed-key path only resolves
    keys through the affine cache; marshal + seed handling are the same
    ``_batch_call_flat`` the preflattened entry point uses.
    """
    triples = list(items)
    if not triples:
        return True
    counts, affines, msgs, sigs = [], [], [], []
    for pubkeys, message, signature in triples:
        pks = [bytes(p) for p in pubkeys]
        sig = bytes(signature)
        if len(pks) == 0 or len(sig) != 96 or any(len(p) != 48 for p in pks):
            return False
        for p in pks:
            xy = _affine_of(p)
            if xy is None:
                return False  # invalid member pubkey: that item cannot verify
            affines.append(xy)
        counts.append(len(pks))
        msgs.append(bytes(message))
        sigs.append(sig)
    return _batch_call_flat(counts, b"".join(affines), msgs, sigs, seed,
                            stats=stats)


def pubkey_affine(pubkey: bytes):
    """Validated 96-byte affine x||y for a compressed pubkey, or None when
    malformed / off-subgroup / infinity (cached; the block-transition
    engine gathers these into per-registry coordinate matrices so batch
    entries skip the per-member dict walk)."""
    return _affine_of(bytes(pubkey))


def pubkey_affine_batch(pubkeys):
    """``pubkey_affine`` for a whole key set in ONE native call: the
    sqrt + subgroup check of every uncached key fans across the native
    thread pool instead of paying a ctypes round-trip each (the registry
    affine-matrix cold build decompresses ~8k unique keys).  Returns
    {pubkey: 96-byte affine or None}, and seeds the per-key cache."""
    pubkeys = {bytes(pk) for pk in pubkeys}
    out = {}
    fresh = []
    for pk in pubkeys:
        cached = _AFFINE_PKS.get(pk)
        if cached is not None:
            out[pk] = cached
        elif len(pk) != 48:
            out[pk] = None
        else:
            fresh.append(pk)
    if fresh:
        flat = b"".join(fresh)
        xys = (ctypes.c_uint8 * (96 * len(fresh)))()
        ok = (ctypes.c_uint8 * len(fresh))()
        _lib.bls_decompress_pubkeys(_buf(flat), len(fresh), xys, ok)
        raw = bytes(xys)
        for i, pk in enumerate(fresh):
            if ok[i]:
                xy = raw[96 * i: 96 * (i + 1)]
                out[pk] = xy
                if len(_AFFINE_PKS) < _AFFINE_PKS_MAX:
                    _AFFINE_PKS[pk] = xy
            else:
                out[pk] = None
    return out


def clear_affine_cache() -> None:
    """Drop the decompressed-pubkey cache.  Measurement control: an A/B
    bench leg that should pay its own cold decompression+membership cost
    must not inherit the other leg's warm cache."""
    _AFFINE_PKS.clear()


def BatchFastAggregateVerifyFlat(counts: Sequence[int], flat_affines: bytes,
                                 messages: Sequence[bytes],
                                 signatures: Sequence[bytes],
                                 seed: bytes = None, stats=None) -> bool:
    """Preflattened BatchFastAggregateVerify: the member pubkeys of every
    item arrive as one contiguous affine-coordinate buffer (96-byte x||y
    each, item i owning ``counts[i]`` consecutive entries) instead of
    per-member compressed keys.  Coordinates must come from
    ``pubkey_affine`` (validated + subgroup-checked); the C side trusts
    them, exactly as it trusts the ``_affine_of`` cache in the compressed
    path.  Same RLC multi-pairing and soundness as
    ``BatchFastAggregateVerify``; ``stats`` forwards to the shared
    ``_batch_call_flat`` per-phase accumulator."""
    counts = [int(c) for c in counts]
    k = len(counts)
    if k == 0:
        return True
    sigs = [bytes(s) for s in signatures]
    msgs = [bytes(m) for m in messages]
    if len(sigs) != k or len(msgs) != k:
        raise ValueError(f"{k} counts vs {len(msgs)} messages / {len(sigs)} signatures")
    if any(c <= 0 for c in counts) or any(len(s) != 96 for s in sigs):
        return False
    flat = bytes(flat_affines)
    if len(flat) != 96 * sum(counts):
        raise ValueError("affine buffer size inconsistent with counts")
    return _batch_call_flat(counts, flat, msgs, sigs, seed, stats=stats)


def AggregateVerify(pubkeys: Sequence[bytes], messages: Sequence[bytes], signature: bytes) -> bool:
    pks = [bytes(p) for p in pubkeys]
    msgs = [bytes(m) for m in messages]
    sig = bytes(signature)
    if len(pks) != len(msgs) or len(pks) == 0:
        return False
    if len(sig) != 96 or any(len(p) != 48 for p in pks):
        return False
    flat_pks = b"".join(pks)
    flat_msgs = b"".join(msgs)
    lens = (ctypes.c_size_t * len(msgs))(*[len(m) for m in msgs])
    return bool(
        _lib.bls_aggregate_verify(_buf(flat_pks), len(pks), _buf(flat_msgs), lens, _buf(sig))
    )


def G1MSM(points_xy: bytes, scalars_be: bytes) -> bytes:
    """Pippenger multi-scalar multiplication over G1 (the KZG commitment
    core — reference capability: specs/eip4844/beacon-chain.md:112-120
    ``g1_lincomb``).  ``points_xy`` holds n canonical affine x||y pairs
    (96 bytes each; subgroup membership is the caller's invariant),
    ``scalars_be`` n 32-byte big-endian scalars already reduced mod r.
    Returns the compressed 48-byte sum; raises ValueError on malformed
    coordinates or off-curve points."""
    if len(points_xy) % 96 or len(scalars_be) % 32:
        raise ValueError("points must be 96-byte x||y, scalars 32-byte BE")
    n = len(points_xy) // 96
    if n != len(scalars_be) // 32:
        raise ValueError(f"{n} points vs {len(scalars_be) // 32} scalars")
    out = (ctypes.c_uint8 * 48)()
    if not _lib.bls_g1_msm(_buf(points_xy), _buf(scalars_be), n, out):
        raise ValueError("malformed or off-curve MSM input point")
    return bytes(out)


def G2MSM(points: bytes, scalars_be: bytes) -> bytes:
    """Variable-base Pippenger multi-scalar multiplication over G2 — the
    bucketed machinery behind the batch verifier's signature fold
    (``sum_i [r_i]sig_i`` in one pass instead of k serial double-and-add
    chains), exported for differential pinning.  ``points`` holds n
    compressed G2 points (96 bytes each, fully validated including the
    psi-based subgroup check; infinity entries contribute the identity),
    ``scalars_be`` n 32-byte big-endian scalars already reduced mod r.
    Returns the compressed 96-byte sum; raises ValueError on malformed or
    off-subgroup points."""
    if len(points) % 96 or len(scalars_be) % 32:
        raise ValueError("points must be 96-byte compressed G2, scalars 32-byte BE")
    n = len(points) // 96
    if n != len(scalars_be) // 32:
        raise ValueError(f"{n} points vs {len(scalars_be) // 32} scalars")
    out = (ctypes.c_uint8 * 96)()
    if not _lib.bls_g2_msm(_buf(points), _buf(scalars_be), n, out):
        raise ValueError("malformed or off-subgroup G2 MSM input point")
    return bytes(out)


def h2c_cache_stats() -> dict:
    """Hit/miss/size counters of the native bounded hash_to_g2 cache that
    fronts the batch verifier's per-message hashing."""
    out = (ctypes.c_uint64 * 3)()
    _lib.bls_h2c_cache_stats(out)
    return {"hits": int(out[0]), "misses": int(out[1]), "size": int(out[2])}


def clear_h2c_cache() -> None:
    """Drop the native hash_to_g2 cache (and its counters).  Measurement
    control, like ``clear_affine_cache``: a bench leg that should pay its
    own message hashing must not inherit a warm cache."""
    _lib.bls_h2c_cache_clear()


# window count of the C side's fixed-base layout, read from the library so
# the table buffer Python allocates can never drift from what C writes
_MSM_FIXED_WINDOWS = _lib.bls_g1_msm_fixed_windows()


def G1MSMPrecompute(points_xy: bytes) -> bytes:
    """One-time fixed-base expansion of n affine points into the shifted
    window table consumed by G1MSMFixed (window-major, 96 bytes/entry)."""
    if len(points_xy) % 96:
        raise ValueError("points must be 96-byte x||y")
    n = len(points_xy) // 96
    table = (ctypes.c_uint8 * (96 * n * _MSM_FIXED_WINDOWS))()
    rc = _lib.bls_g1_msm_precompute(_buf(points_xy), n, table)
    if rc != _MSM_FIXED_WINDOWS:
        raise ValueError("malformed or off-curve MSM input point")
    return bytes(table)


def G1MSMFixed(table: bytes, n: int, scalars_be: bytes) -> bytes:
    """Fixed-base MSM against a G1MSMPrecompute table: one bucket pass, no
    inter-window doubling chain (~1.8x the on-the-fly Pippenger at blob
    scale, on top of the table's one-time cost).  The C side sanity-checks
    the first table entry against the curve, so a table from an
    incompatible build (or a torn write that survived the disk cache's
    digest) raises the ValueError below instead of returning garbage."""
    if len(scalars_be) != 32 * n or len(table) != 96 * n * _MSM_FIXED_WINDOWS:
        raise ValueError("table/scalar sizes inconsistent with n")
    out = (ctypes.c_uint8 * 48)()
    if not _lib.bls_g1_msm_fixed(_buf(table), n, _buf(scalars_be), out):
        raise ValueError("corrupted MSM table")
    return bytes(out)


# --- diagnostics / test hooks ----------------------------------------------

def hash_to_g2_compressed(message: bytes, dst: bytes) -> bytes:
    msg, d = bytes(message), bytes(dst)
    out = (ctypes.c_uint8 * 96)()
    if not _lib.bls_hash_to_g2(_buf(msg), len(msg), _buf(d), len(d), out):
        raise ValueError("DST must be <= 255 bytes")
    return bytes(out)


def pairing_bytes(p_g1: bytes, q_g2: bytes) -> bytes:
    """e(P, Q) as 12 canonical big-endian 48-byte Fp values (test hook)."""
    out = (ctypes.c_uint8 * 576)()
    if not _lib.bls_pairing(_buf(bytes(p_g1)), _buf(bytes(q_g2)), out):
        raise ValueError("invalid pairing input")
    return bytes(out)


def sha256(data: bytes) -> bytes:
    out = (ctypes.c_uint8 * 32)()
    _lib.bls_sha256(_buf(bytes(data)), len(bytes(data)), out)
    return bytes(out)
