"""BLS12-381 field towers: Fq, Fq2 = Fq[u]/(u^2+1), Fq6 = Fq2[v]/(v^3 - xi),
Fq12 = Fq6[w]/(w^2 - v), with xi = u + 1.

From-scratch implementation (the reference delegates to py_ecc; see
eth2spec/utils/bls.py:1-2).  Plain-int arithmetic with Karatsuba Fq2
multiplication — this is the host correctness oracle; the batched TPU
path in ops/ mirrors these formulas on uint32 limb lanes.
"""
from __future__ import annotations

# BLS12-381 parameters
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001  # subgroup order
X_PARAM = -0xD201000000010000  # BLS parameter x (negative)
H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551  # noqa: E501 (RFC 9380 G2 h_eff)


class Fq:
    __slots__ = ("n",)

    def __init__(self, n: int):
        self.n = n % P

    def __add__(self, o):
        return Fq(self.n + o.n)

    def __sub__(self, o):
        return Fq(self.n - o.n)

    def __mul__(self, o):
        return Fq(self.n * o.n)

    def __neg__(self):
        return Fq(-self.n)

    def square(self):
        return Fq(self.n * self.n)

    def inv(self):
        return Fq(pow(self.n, P - 2, P))

    def pow(self, e: int):
        return Fq(pow(self.n, e, P))

    def is_zero(self) -> bool:
        return self.n == 0

    def __eq__(self, o):
        return isinstance(o, Fq) and self.n == o.n

    def __hash__(self):
        return hash(self.n)

    def __repr__(self):
        return f"Fq(0x{self.n:x})"

    def sqrt(self):
        """Square root for p ≡ 3 (mod 4); None if not a square."""
        c = pow(self.n, (P + 1) // 4, P)
        if c * c % P == self.n:
            return Fq(c)
        return None

    def sgn0(self) -> int:
        return self.n & 1

    @staticmethod
    def zero():
        return Fq(0)

    @staticmethod
    def one():
        return Fq(1)


class Fq2:
    """c0 + c1*u with u^2 = -1.  Coefficients stored as raw ints mod P."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % P
        self.c1 = c1 % P

    def __add__(self, o):
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self):
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, o):
        a0, a1, b0, b1 = self.c0, self.c1, o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        # karatsuba: c1 = (a0+a1)(b0+b1) - t0 - t1
        return Fq2(t0 - t1, (a0 + a1) * (b0 + b1) - t0 - t1)

    def square(self):
        a0, a1 = self.c0, self.c1
        # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
        return Fq2((a0 + a1) * (a0 - a1), 2 * a0 * a1)

    def mul_by_xi(self):
        """Multiply by xi = 1 + u."""
        return Fq2(self.c0 - self.c1, self.c0 + self.c1)

    def conjugate(self):
        return Fq2(self.c0, -self.c1)

    def inv(self):
        a0, a1 = self.c0, self.c1
        norm = (a0 * a0 + a1 * a1) % P
        ninv = pow(norm, P - 2, P)
        return Fq2(a0 * ninv, -a1 * ninv)

    def pow(self, e: int):
        result = FQ2_ONE
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def __eq__(self, o):
        return isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash((self.c0, self.c1))

    def __repr__(self):
        return f"Fq2(0x{self.c0:x}, 0x{self.c1:x})"

    def sgn0(self) -> int:
        """RFC 9380 sgn0 for m=2 (sign of the 'least' non-zero coeff)."""
        sign_0 = self.c0 & 1
        zero_0 = self.c0 == 0
        sign_1 = self.c1 & 1
        return sign_0 | (zero_0 & sign_1)

    def sqrt(self):
        """Square root in Fq2 (q = p^2 ≡ 9 mod 16); None if not a square.

        RFC 9380 §I.3: candidate c = a^((q+7)/16); the true root (if any)
        is c times one of {1, sqrt(-1), sqrt(sqrt(-1)), sqrt(-sqrt(-1))}.
        """
        c = self.pow(_SQRT_EXP)
        for zeta in _SQRT_ADJUSTMENTS:
            cand = c * zeta
            if cand.square() == self:
                return cand
        return None

    @staticmethod
    def zero():
        return FQ2_ZERO

    @staticmethod
    def one():
        return FQ2_ONE


FQ2_ZERO = Fq2(0, 0)
FQ2_ONE = Fq2(1, 0)
FQ2_U = Fq2(0, 1)

_SQRT_EXP = (P * P + 7) // 16

# 8th roots of unity needed by Fq2.sqrt: 1, u (= sqrt(-1)), sqrt(u), sqrt(-u).
# sqrt(u) = a(1+u) with a^2 = 1/2, or a(1-u) with a^2 = -1/2, whichever exists.
def _compute_sqrt_u() -> Fq2:
    a = Fq(pow(2, P - 2, P)).sqrt()  # sqrt(1/2)
    if a is not None:
        cand = Fq2(a.n, a.n)
    else:
        a = Fq((P - pow(2, P - 2, P)) % P).sqrt()  # sqrt(-1/2)
        assert a is not None
        cand = Fq2(a.n, (-a.n) % P)
    assert cand.square() == FQ2_U
    return cand


_SQRT_U = _compute_sqrt_u()
_SQRT_NEG_U = _SQRT_U * FQ2_U  # (sqrt(u)*u)^2 = -u
_SQRT_ADJUSTMENTS = (FQ2_ONE, FQ2_U, _SQRT_U, _SQRT_NEG_U)


class Fq6:
    """c0 + c1*v + c2*v^2 over Fq2, v^3 = xi = 1+u."""

    __slots__ = ("c0", "c1", "c2")

    def __init__(self, c0: Fq2, c1: Fq2, c2: Fq2):
        self.c0 = c0
        self.c1 = c1
        self.c2 = c2

    def __add__(self, o):
        return Fq6(self.c0 + o.c0, self.c1 + o.c1, self.c2 + o.c2)

    def __sub__(self, o):
        return Fq6(self.c0 - o.c0, self.c1 - o.c1, self.c2 - o.c2)

    def __neg__(self):
        return Fq6(-self.c0, -self.c1, -self.c2)

    def __mul__(self, o):
        a0, a1, a2 = self.c0, self.c1, self.c2
        b0, b1, b2 = o.c0, o.c1, o.c2
        t0 = a0 * b0
        t1 = a1 * b1
        t2 = a2 * b2
        c0 = ((a1 + a2) * (b1 + b2) - t1 - t2).mul_by_xi() + t0
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1 + t2.mul_by_xi()
        c2 = (a0 + a2) * (b0 + b2) - t0 - t2 + t1
        return Fq6(c0, c1, c2)

    def square(self):
        return self * self

    def mul_by_v(self):
        """Multiply by v: (c0,c1,c2) -> (xi*c2, c0, c1)."""
        return Fq6(self.c2.mul_by_xi(), self.c0, self.c1)

    def inv(self):
        a0, a1, a2 = self.c0, self.c1, self.c2
        t0 = a0.square() - (a1 * a2).mul_by_xi()
        t1 = a2.square().mul_by_xi() - a0 * a1
        t2 = a1.square() - a0 * a2
        factor = (a0 * t0 + (a2 * t1).mul_by_xi() + (a1 * t2).mul_by_xi()).inv()
        return Fq6(t0 * factor, t1 * factor, t2 * factor)

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero() and self.c2.is_zero()

    def __eq__(self, o):
        return (
            isinstance(o, Fq6)
            and self.c0 == o.c0
            and self.c1 == o.c1
            and self.c2 == o.c2
        )

    def __hash__(self):
        return hash((self.c0, self.c1, self.c2))

    @staticmethod
    def zero():
        return FQ6_ZERO

    @staticmethod
    def one():
        return FQ6_ONE


FQ6_ZERO = Fq6(FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = Fq6(FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


class Fq12:
    """c0 + c1*w over Fq6, w^2 = v."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: Fq6, c1: Fq6):
        self.c0 = c0
        self.c1 = c1

    def __add__(self, o):
        return Fq12(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o):
        return Fq12(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self):
        return Fq12(-self.c0, -self.c1)

    def __mul__(self, o):
        a0, a1 = self.c0, self.c1
        b0, b1 = o.c0, o.c1
        t0 = a0 * b0
        t1 = a1 * b1
        c0 = t0 + t1.mul_by_v()
        c1 = (a0 + a1) * (b0 + b1) - t0 - t1
        return Fq12(c0, c1)

    def square(self):
        a0, a1 = self.c0, self.c1
        t0 = a0 * a1
        c0 = (a0 + a1) * (a0 + a1.mul_by_v()) - t0 - t0.mul_by_v()
        return Fq12(c0, t0 + t0)

    def conjugate(self):
        """f^(p^6): w -> -w."""
        return Fq12(self.c0, -self.c1)

    def inv(self):
        a0, a1 = self.c0, self.c1
        factor = (a0.square() - a1.square().mul_by_v()).inv()
        return Fq12(a0 * factor, -a1 * factor)

    def pow(self, e: int):
        if e < 0:
            return self.inv().pow(-e)
        result = FQ12_ONE
        base = self
        while e > 0:
            if e & 1:
                result = result * base
            base = base.square()
            e >>= 1
        return result

    def is_zero(self) -> bool:
        return self.c0.is_zero() and self.c1.is_zero()

    def __eq__(self, o):
        return isinstance(o, Fq12) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash((self.c0, self.c1))

    @staticmethod
    def zero():
        return FQ12_ZERO

    @staticmethod
    def one():
        return FQ12_ONE


FQ12_ZERO = Fq12(FQ6_ZERO, FQ6_ZERO)
FQ12_ONE = Fq12(FQ6_ONE, FQ6_ZERO)


def fq12_from_fq2(x: Fq2) -> Fq12:
    """Embed Fq2 scalar into Fq12 (as c0 of c0 of c0... careful: Fq2 sits at
    the bottom of the tower, so the embedding is (x, 0, 0) + 0*w)."""
    return Fq12(Fq6(x, FQ2_ZERO, FQ2_ZERO), FQ6_ZERO)


def fq12_from_fq(x: int) -> Fq12:
    return fq12_from_fq2(Fq2(x, 0))


# w and its inverse powers, used by the G2 untwist map
# w^2 = v, so as an Fq12 element w = (0, 1·1) i.e. c1 = Fq6.one()
FQ12_W = Fq12(FQ6_ZERO, FQ6_ONE)
FQ12_W2 = FQ12_W.square()           # = v embedded
FQ12_W3 = FQ12_W2 * FQ12_W
FQ12_W2_INV = FQ12_W2.inv()
FQ12_W3_INV = FQ12_W3.inv()
