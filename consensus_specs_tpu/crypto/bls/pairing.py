"""Optimal ate pairing on BLS12-381.

Implementation strategy: untwist G2 points into E(Fq12) and run the
Miller loop with generic affine chord-tangent line functions — the
least-fragile formulation (no sparse-line index bookkeeping), at oracle
speed.  Pairing-product form `prod e(Pi, Qi) == 1` shares one final
exponentiation across all pairs, which is what Verify/FastAggregateVerify
need (reference behavior: eth2spec/utils/bls.py:47-74 via py_ecc).
"""
from __future__ import annotations

from typing import Iterable, Optional, Tuple

from .fields import (
    FQ12_ONE,
    FQ12_W2_INV,
    FQ12_W3_INV,
    Fq,
    Fq2,
    Fq12,
    P,
    R,
    X_PARAM,
    fq12_from_fq,
    fq12_from_fq2,
)

_ATE_LOOP = -X_PARAM  # 0xd201000000010000 (|x|; x itself is negative)
_ATE_BITS = bin(_ATE_LOOP)[3:]  # skip leading '0b1'

# hard part exponent of the final exponentiation: (p^4 - p^2 + 1) / r
_HARD_EXP = (P**4 - P**2 + 1) // R


AffFq12 = Tuple[Fq12, Fq12]


def _untwist(q_affine: Tuple[Fq2, Fq2]) -> AffFq12:
    """E'(Fq2) -> E(Fq12): (x, y) -> (x / w^2, y / w^3)."""
    x, y = q_affine
    return (fq12_from_fq2(x) * FQ12_W2_INV, fq12_from_fq2(y) * FQ12_W3_INV)


def _embed_g1(p_affine: Tuple[Fq, Fq]) -> AffFq12:
    x, y = p_affine
    return (fq12_from_fq(x.n), fq12_from_fq(y.n))


def _line(p1: AffFq12, p2: AffFq12, t: AffFq12) -> Fq12:
    """Evaluate the line through p1,p2 (tangent if equal) at t."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        m = (y2 - y1) * (x2 - x1).inv()
        return m * (xt - x1) - (yt - y1)
    if y1 == y2:
        three = fq12_from_fq(3)
        two = fq12_from_fq(2)
        m = three * x1.square() * (two * y1).inv()
        return m * (xt - x1) - (yt - y1)
    return xt - x1


def _add_aff(p1: AffFq12, p2: AffFq12) -> Optional[AffFq12]:
    """Affine addition in E(Fq12); None = infinity."""
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return _double_aff(p1)
        return None
    m = (y2 - y1) * (x2 - x1).inv()
    x3 = m.square() - x1 - x2
    y3 = m * (x1 - x3) - y1
    return (x3, y3)


def _double_aff(p: AffFq12) -> AffFq12:
    x, y = p
    m = fq12_from_fq(3) * x.square() * (fq12_from_fq(2) * y).inv()
    x3 = m.square() - x - x
    y3 = m * (x - x3) - y
    return (x3, y3)


def miller_loop(p: Point, q: Point) -> Fq12:
    """f_{|x|,Q}(P), conjugated for the negative BLS parameter."""
    if p.is_infinity() or q.is_infinity():
        return FQ12_ONE
    p12 = _embed_g1(p.to_affine())
    q12 = _untwist(q.to_affine())
    t = q12
    f = FQ12_ONE
    for bit in _ATE_BITS:
        f = f.square() * _line(t, t, p12)
        t = _double_aff(t)
        if bit == "1":
            f = f * _line(t, q12, p12)
            t = _add_aff(t, q12)
    # x < 0: conjugate (inverse up to factors killed by the final exponentiation)
    return f.conjugate()


def final_exponentiation(f: Fq12) -> Fq12:
    easy = f.conjugate() * f.inv()          # f^(p^6 - 1)
    easy = easy.pow(P * P) * easy           # ^(p^2 + 1)
    return easy.pow(_HARD_EXP)


def pairing(p: Point, q: Point) -> Fq12:
    """e(P, Q) with P in G1, Q in G2."""
    return final_exponentiation(miller_loop(p, q))


def pairings_are_identity(pairs: Iterable[Tuple[Point, Point]]) -> bool:
    """prod e(Pi, Qi) == 1, sharing a single final exponentiation."""
    f = FQ12_ONE
    for p, q in pairs:
        f = f * miller_loop(p, q)
    return final_exponentiation(f) == FQ12_ONE
