"""BLS signatures over BLS12-381, G2 proof-of-possession scheme
(BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_), matching the behavior the
spec requires of its BLS backend (reference: eth2spec/utils/bls.py wraps
py_ecc's G2ProofOfPossession; IETF bls-signature draft semantics).

All functions take/return the spec's byte encodings (48-byte pubkeys,
96-byte signatures); points are validated (on-curve + subgroup) on
deserialization, with failures surfacing as False from the Verify
family — the wrapper layer in __init__.py enforces that contract.
"""
from __future__ import annotations

from typing import Sequence

from .curve import (
    DeserializationError,
    g1_generator,
    g1_infinity,
    g1_to_bytes,
    g2_infinity,
    g2_to_bytes,
    pubkey_to_point,
    signature_to_point,
)
from .fields import R
from .hash_to_curve import DST_G2_POP, hash_to_g2

G2_POINT_AT_INFINITY = bytes([0xC0]) + b"\x00" * 95


def _sk_to_int(sk) -> int:
    if isinstance(sk, int):
        v = int(sk)
    else:
        v = int.from_bytes(bytes(sk), "big")
    if not 0 < v < R:
        raise ValueError("secret key out of range")
    return v


def SkToPk(sk) -> bytes:
    return g1_to_bytes(g1_generator().mul(_sk_to_int(sk)))


def Sign(sk, message: bytes) -> bytes:
    return g2_to_bytes(hash_to_g2(bytes(message), DST_G2_POP).mul(_sk_to_int(sk)))


def KeyValidate(pubkey: bytes) -> bool:
    try:
        pt = pubkey_to_point(bytes(pubkey))
    except DeserializationError:
        return False
    return not pt.is_infinity()


def Verify(pubkey: bytes, message: bytes, signature: bytes) -> bool:
    from .pairing import pairings_are_identity

    try:
        pk = pubkey_to_point(bytes(pubkey))
        sig = signature_to_point(bytes(signature))
    except DeserializationError:
        return False
    if pk.is_infinity():
        return False
    h = hash_to_g2(bytes(message), DST_G2_POP)
    return pairings_are_identity([(pk, h), (-g1_generator(), sig)])


def Aggregate(signatures: Sequence[bytes]) -> bytes:
    if len(signatures) == 0:
        raise ValueError("cannot aggregate zero signatures")
    acc = g2_infinity()
    for s in signatures:
        acc = acc + signature_to_point(bytes(s))
    return g2_to_bytes(acc)


def AggregatePKs(pubkeys: Sequence[bytes]) -> bytes:
    if len(pubkeys) == 0:
        raise ValueError("cannot aggregate zero pubkeys")
    acc = g1_infinity()
    for p in pubkeys:
        pt = pubkey_to_point(bytes(p))
        if pt.is_infinity():
            raise ValueError("identity pubkey in aggregate")
        acc = acc + pt
    return g1_to_bytes(acc)


def AggregateVerify(pubkeys: Sequence[bytes], messages: Sequence[bytes], signature: bytes) -> bool:
    from .pairing import pairings_are_identity

    if len(pubkeys) != len(messages) or len(pubkeys) == 0:
        return False
    try:
        sig = signature_to_point(bytes(signature))
        pairs = []
        for pk_bytes, msg in zip(pubkeys, messages):
            pk = pubkey_to_point(bytes(pk_bytes))
            if pk.is_infinity():
                return False
            pairs.append((pk, hash_to_g2(bytes(msg), DST_G2_POP)))
    except DeserializationError:
        return False
    pairs.append((-g1_generator(), sig))
    return pairings_are_identity(pairs)


def FastAggregateVerify(pubkeys: Sequence[bytes], message: bytes, signature: bytes) -> bool:
    from .pairing import pairings_are_identity

    if len(pubkeys) == 0:
        return False
    try:
        sig = signature_to_point(bytes(signature))
        agg = g1_infinity()
        for pk_bytes in pubkeys:
            pk = pubkey_to_point(bytes(pk_bytes))
            if pk.is_infinity():
                return False
            agg = agg + pk
    except DeserializationError:
        return False
    h = hash_to_g2(bytes(message), DST_G2_POP)
    return pairings_are_identity([(agg, h), (-g1_generator(), sig)])
