"""BLS backend selector — the plugin seam the spec modules import.

Behavioral twin of the reference's eth2spec/utils/bls.py:
  * module-global backend rebinding (use_python / use_jax), mirroring
    use_py_ecc/use_milagro (bls.py:17-30)
  * ``bls_active`` kill-switch + ``only_with_bls`` decorator returning
    stub values when off (bls.py:6, 33-44) — tests run 100x faster
  * Verify-family wrappers catch every exception and return False
    (bls.py:47-74): malformed inputs are invalid, never fatal

Backends:
  * "python": the from-scratch pure-Python oracle in this package
  * "native": from-scratch C++ (crypto/bls/native/), the fast host path —
    the role the reference fills with its Rust milagro binding
  * "jax":    batched TPU pipeline (ops/bls_jax) — registered lazily
"""
from __future__ import annotations

from types import SimpleNamespace

from . import ciphersuite as _py_backend

G2_POINT_AT_INFINITY = _py_backend.G2_POINT_AT_INFINITY

STUB_SIGNATURE = b"\x11" * 96
STUB_PUBKEY = b"\x22" * 48
STUB_COORDINATES = G2_POINT_AT_INFINITY

bls_active = True

_backends = {"python": _py_backend}
_backend_name = "python"
bls = _py_backend


def register_backend(name: str, module) -> None:
    _backends[name] = module


def use_backend(name: str) -> None:
    global bls, _backend_name
    if name == "jax" and "jax" not in _backends:
        from consensus_specs_tpu.ops import bls_jax

        register_backend("jax", bls_jax.backend())
    if name == "native" and "native" not in _backends:
        from . import native

        register_backend("native", native)
    bls = _backends[name]
    _backend_name = name


def use_python() -> None:
    use_backend("python")


def use_native() -> None:
    use_backend("native")


def use_jax() -> None:
    use_backend("jax")


def use_fastest() -> None:
    """Prefer the native C++ backend, falling back to the Python oracle
    (mirrors the reference's bls_active default of the fastest available
    backend for CI; eth2spec/utils/bls.py:8-30)."""
    try:
        use_backend("native")
    except ImportError:
        use_backend("python")


def backend_name() -> str:
    return _backend_name


def only_with_bls(alt_return=None):
    """Decorator: skip the wrapped function when BLS is disabled
    (reference: eth2spec/utils/bls.py:33-44)."""

    def decorator(fn):
        def wrapper(*args, **kwargs):
            if not bls_active:
                return alt_return
            return fn(*args, **kwargs)

        wrapper.__name__ = fn.__name__
        return wrapper

    return decorator


@only_with_bls(alt_return=True)
def Verify(PK, message, signature):
    from consensus_specs_tpu import tracing

    tracing.count("bls.verify")
    try:
        return bls.Verify(PK, message, signature)
    except Exception:
        return False


@only_with_bls(alt_return=True)
def AggregateVerify(pubkeys, messages, signature):
    try:
        return bls.AggregateVerify(pubkeys, messages, signature)
    except Exception:
        return False


@only_with_bls(alt_return=True)
def FastAggregateVerify(pubkeys, message, signature):
    from consensus_specs_tpu import tracing

    tracing.count("bls.fast_aggregate_verify")
    tracing.count("bls.fast_aggregate_verify.pubkeys", len(pubkeys))
    stack = _deferred_stack.get()
    if stack:
        stack[-1].entries.append(
            (tuple(bytes(p) for p in pubkeys), bytes(message), bytes(signature))
        )
        return True  # optimistic; settled at scope exit
    try:
        return bls.FastAggregateVerify(pubkeys, message, signature)
    except Exception:
        return False


# --- deferred (batched) verification ----------------------------------------
# The sanctioned sundry-layer substitution for the block-processing hot path
# (SURVEY §7; reference analogue setup.py:488-492): every FastAggregateVerify
# issued inside the scope is collected and settled in ONE batched pairing
# product with a single shared final exponentiation.

# per-context scope stack: a ContextVar (not a module list) so concurrent
# block processing in threads or asyncio tasks cannot interleave entries
# across unrelated deferred scopes
import contextvars as _contextvars

_deferred_stack: "_contextvars.ContextVar[tuple]" = _contextvars.ContextVar(
    "bls_deferred_stack", default=())


def _batch_verify(entries) -> bool:
    """True iff every (pubkeys, message, signature) entry verifies."""
    if not entries:
        return True
    backend_batch = getattr(bls, "BatchFastAggregateVerify", None)
    if backend_batch is not None:
        try:
            return bool(backend_batch(entries))
        except Exception:
            return False
    for pks, msg, sig in entries:  # backends without a batch API
        try:
            if not bls.FastAggregateVerify(pks, msg, sig):
                return False
        except Exception:
            return False
    return True


def _first_invalid(entries):
    """Index of the FIRST failing entry, or None if all verify.

    Bisects with sub-batch calls: O(log n) batched verifications instead of
    n sequential ones, and always lands on the leftmost failure so deferred
    semantics report the same culprit the sequential path would have."""
    if _batch_verify(entries):
        return None
    lo, hi = 0, len(entries)
    # invariant: entries[:lo] all verify; at least one failure in [lo, hi)
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if _batch_verify(entries[lo:mid]):
            lo = mid
        else:
            hi = mid
    return lo


class deferred_fast_aggregate_verify:
    """Context manager: FastAggregateVerify calls inside the scope return
    True optimistically and are settled as one batch on exit.

    Failure semantics mirror the sequential path:
      * all signatures valid -> scope exits cleanly (and any structural
        exception raised inside propagates unchanged);
      * some signature invalid -> AssertionError naming the first failing
        check in call order — the same check the sequential path would have
        tripped on — even if a later operation raised first while running
        optimistically.
    """

    def __enter__(self):
        self.entries = []
        self._token = _deferred_stack.set(_deferred_stack.get() + (self,))
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = _deferred_stack.get()
        assert stack and stack[-1] is self, "deferred verification scopes must nest"
        _deferred_stack.reset(self._token)
        if not bls_active or not self.entries:
            return False
        first_bad = _first_invalid(self.entries)
        if first_bad is not None:
            raise AssertionError(
                f"deferred signature verification failed: batch entry "
                f"{first_bad} of {len(self.entries)} is invalid"
            ) from exc
        return False


@only_with_bls(alt_return=STUB_SIGNATURE)
def Aggregate(signatures):
    return bls.Aggregate(signatures)


@only_with_bls(alt_return=STUB_SIGNATURE)
def Sign(SK, message):
    return bls.Sign(SK, message)


@only_with_bls(alt_return=True)
def KeyValidate(pubkey):
    return bls.KeyValidate(pubkey)


def AggregatePKs(pubkeys):
    # NOT bls_active-gated: aggregation is deterministic state content
    # (sync-committee aggregate pubkeys live in the state), so it must
    # compute even when signature *verification* is stubbed off — the
    # reference's AggregatePKs is likewise ungated (utils/bls.py).
    return bls.AggregatePKs(pubkeys)


@only_with_bls(alt_return=STUB_PUBKEY)
def SkToPk(SK):
    return bls.SkToPk(SK)


@only_with_bls(alt_return=None)
def Pairing(p_g1, q_g2):
    """e(P, Q) as a comparable GT element (the sharding spec's degree-proof
    check compares two pairings; reference analogue: py_ecc pairing via
    the bls wrapper).  Accepts 48-byte G1 / 96-byte G2 encodings or curve
    Points.  With BLS disabled both sides stub to None and compare equal."""
    from .curve import Point, g1_from_bytes, g2_from_bytes
    from .pairing import pairing

    p = p_g1 if isinstance(p_g1, Point) else g1_from_bytes(bytes(p_g1))
    q = q_g2 if isinstance(q_g2, Point) else g2_from_bytes(bytes(q_g2))
    return pairing(p, q)
