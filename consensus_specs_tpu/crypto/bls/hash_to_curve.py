"""Hash-to-curve for BLS12-381 G2: BLS12381G2_XMD:SHA-256_SSWU_RO
(RFC 9380 / draft-irtf-cfrg-hash-to-curve), the suite the spec's BLS
ciphersuite requires (reference: via py_ecc's hash_to_G2; DST in
eth2spec/utils/bls.py usage of the G2 proof-of-possession scheme).

Pipeline: expand_message_xmd(SHA-256) -> hash_to_field(Fq2, count=2)
-> simplified-SWU on the 3-isogenous curve E2' -> iso_map -> add ->
clear_cofactor(h_eff).  Every stage is internally validated: SSWU output
must lie on E2', the isogeny image on E2, and the cleared point in the
r-subgroup — a wrong constant fails loudly rather than silently.
"""
from __future__ import annotations

import hashlib
from typing import Tuple

from .curve import B_G2, Point
from .fields import FQ2_ONE, Fq2, H_EFF_G2, P

# -- expand_message_xmd (RFC 9380 §5.3.1) -----------------------------------

_B_IN_BYTES = 32  # SHA-256 output
_S_IN_BYTES = 64  # SHA-256 block size


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    assert len(dst) <= 255
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    assert ell <= 255
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * _S_IN_BYTES
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b_0 = hashlib.sha256(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b_vals = [hashlib.sha256(b_0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        tmp = bytes(a ^ b for a, b in zip(b_0, b_vals[-1]))
        b_vals.append(hashlib.sha256(tmp + bytes([i]) + dst_prime).digest())
    return b"".join(b_vals)[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes) -> list:
    """RFC 9380 §5.2 with m=2, L=64."""
    L = 64
    len_in_bytes = count * 2 * L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coeffs = []
        for j in range(2):
            offset = L * (j + i * 2)
            coeffs.append(int.from_bytes(uniform[offset : offset + L], "big") % P)
        out.append(Fq2(coeffs[0], coeffs[1]))
    return out


# -- simplified SWU on E2': y^2 = x^3 + A'x + B' (RFC 9380 §6.6.2) ----------

_A_PRIME = Fq2(0, 240)
_B_PRIME = Fq2(1012, 1012)
_Z = Fq2(-2 % P, -1 % P)  # -(2 + u)


def _sswu(u: Fq2) -> Tuple[Fq2, Fq2]:
    """Map a field element to a point on the isogenous curve E2'."""
    z_u2 = _Z * u.square()
    tv = z_u2.square() + z_u2
    if tv.is_zero():
        x1 = _B_PRIME * (_Z * _A_PRIME).inv()
    else:
        x1 = (-_B_PRIME) * _A_PRIME.inv() * (FQ2_ONE + tv.inv())
    gx1 = x1.square() * x1 + _A_PRIME * x1 + _B_PRIME
    y1 = gx1.sqrt()
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = z_u2 * x1
        gx2 = x2.square() * x2 + _A_PRIME * x2 + _B_PRIME
        y2 = gx2.sqrt()
        assert y2 is not None, "SSWU: neither gx1 nor gx2 is square (impossible)"
        x, y = x2, y2
    if u.sgn0() != y.sgn0():
        y = -y
    # internal validation: on E2'
    assert y.square() == x.square() * x + _A_PRIME * x + _B_PRIME
    return (x, y)


# -- 3-isogeny E2' -> E2 (RFC 9380 Appendix E.3) ----------------------------

_K1 = (
    Fq2(
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
    ),
    Fq2(
        0,
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A,
    ),
    Fq2(
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D,
    ),
    Fq2(
        0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
        0,
    ),
)
_K2 = (
    Fq2(
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63,
    ),
    Fq2(
        0xC,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F,
    ),
    Fq2(1, 0),
)
_K3 = (
    Fq2(
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
    ),
    Fq2(
        0,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE,
    ),
    Fq2(
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F,
    ),
    Fq2(
        0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
        0,
    ),
)
_K4 = (
    Fq2(
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
    ),
    Fq2(
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3,
    ),
    Fq2(
        0x12,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99,
    ),
    Fq2(1, 0),
)


def _horner(coeffs, x: Fq2) -> Fq2:
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = acc * x + c
    return acc


def _iso_map(x: Fq2, y: Fq2) -> Tuple[Fq2, Fq2]:
    x_num = _horner(_K1, x)
    x_den = _horner(_K2, x)
    y_num = _horner(_K3, x)
    y_den = _horner(_K4, x)
    xo = x_num * x_den.inv()
    yo = y * y_num * y_den.inv()
    # internal validation: image lies on E2
    assert yo.square() == xo.square() * xo + B_G2, "isogeny image off-curve"
    return (xo, yo)


# -- full hash_to_G2 ---------------------------------------------------------

DST_G2_POP = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"


def hash_to_g2(msg: bytes, dst: bytes = DST_G2_POP) -> Point:
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    p0 = _iso_map(*_sswu(u0))
    p1 = _iso_map(*_sswu(u1))
    q0 = Point(p0[0], p0[1], FQ2_ONE, B_G2)
    q1 = Point(p1[0], p1[1], FQ2_ONE, B_G2)
    r = (q0 + q1).mul(H_EFF_G2)
    assert r.in_subgroup(), "cofactor clearing failed"
    return r
