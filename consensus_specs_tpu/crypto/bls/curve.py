"""BLS12-381 curve groups G1 (over Fq) and G2 (over Fq2).

Short-Weierstrass y^2 = x^3 + b with a = 0; Jacobian coordinates for
inversion-free adds/doubles; ZCash-format compressed serialization
(48-byte G1 pubkeys / 96-byte G2 signatures as used by the spec's
BLSPubkey/BLSSignature types, phase0/beacon-chain.md:152-170).
"""
from __future__ import annotations

from functools import lru_cache
from typing import Optional, Tuple

from .fields import Fq, Fq2, FQ2_ONE, P, R

# curve coefficients
B_G1 = Fq(4)
B_G2 = Fq2(4, 4)  # 4 * (1 + u)

# generators
G1_X = Fq(0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB)
G1_Y = Fq(0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1)
G2_X = Fq2(
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = Fq2(
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)


class Point:
    """Jacobian point (X, Y, Z); Z == 0 means infinity.  Generic over the
    coordinate field (Fq for G1, Fq2 for G2)."""

    __slots__ = ("x", "y", "z", "b")

    def __init__(self, x, y, z, b):
        self.x = x
        self.y = y
        self.z = z
        self.b = b

    @staticmethod
    def infinity(field_one, b) -> "Point":
        zero = field_one - field_one
        return Point(field_one, field_one, zero, b)

    def is_infinity(self) -> bool:
        return self.z.is_zero()

    def double(self) -> "Point":
        if self.is_infinity():
            return self
        X, Y, Z = self.x, self.y, self.z
        A = X.square()
        Bv = Y.square()
        C = Bv.square()
        D = ((X + Bv).square() - A - C)
        D = D + D
        E = A + A + A
        F = E.square()
        X3 = F - D - D
        eight_c = C + C
        eight_c = eight_c + eight_c
        eight_c = eight_c + eight_c
        Y3 = E * (D - X3) - eight_c
        Z3 = Y * Z
        Z3 = Z3 + Z3
        return Point(X3, Y3, Z3, self.b)

    def __add__(self, other: "Point") -> "Point":
        if self.is_infinity():
            return other
        if other.is_infinity():
            return self
        X1, Y1, Z1 = self.x, self.y, self.z
        X2, Y2, Z2 = other.x, other.y, other.z
        Z1Z1 = Z1.square()
        Z2Z2 = Z2.square()
        U1 = X1 * Z2Z2
        U2 = X2 * Z1Z1
        S1 = Y1 * Z2 * Z2Z2
        S2 = Y2 * Z1 * Z1Z1
        if U1 == U2:
            if S1 == S2:
                return self.double()
            return Point.infinity(_one_like(X1), self.b)
        H = U2 - U1
        I = (H + H).square()
        J = H * I
        rr = S2 - S1
        rr = rr + rr
        V = U1 * I
        X3 = rr.square() - J - V - V
        S1J = S1 * J
        Y3 = rr * (V - X3) - S1J - S1J
        Z3 = ((Z1 + Z2).square() - Z1Z1 - Z2Z2) * H
        return Point(X3, Y3, Z3, self.b)

    def __neg__(self) -> "Point":
        return Point(self.x, -self.y, self.z, self.b)

    def __sub__(self, other: "Point") -> "Point":
        return self + (-other)

    def mul(self, k: int) -> "Point":
        if k < 0:
            return (-self).mul(-k)
        result = Point.infinity(_one_like(self.x), self.b)
        addend = self
        while k:
            if k & 1:
                result = result + addend
            addend = addend.double()
            k >>= 1
        return result

    def to_affine(self) -> Optional[Tuple]:
        """(x, y) or None for infinity."""
        if self.is_infinity():
            return None
        zinv = self.z.inv()
        zinv2 = zinv.square()
        return (self.x * zinv2, self.y * zinv2 * zinv)

    def __eq__(self, other):
        if not isinstance(other, Point):
            return NotImplemented
        if self.is_infinity() or other.is_infinity():
            return self.is_infinity() and other.is_infinity()
        # X1 Z2^2 == X2 Z1^2 and Y1 Z2^3 == Y2 Z1^3
        Z1Z1 = self.z.square()
        Z2Z2 = other.z.square()
        return (
            self.x * Z2Z2 == other.x * Z1Z1
            and self.y * Z2Z2 * other.z == other.y * Z1Z1 * self.z
        )

    def __hash__(self):
        aff = self.to_affine()
        return hash(aff and (aff[0], aff[1]))

    def on_curve(self) -> bool:
        if self.is_infinity():
            return True
        x, y = self.to_affine()
        b = self.b
        return y.square() == x * x * x + b

    def in_subgroup(self) -> bool:
        return self.mul(R).is_infinity()


def _one_like(v):
    return Fq(1) if isinstance(v, Fq) else FQ2_ONE


def g1_generator() -> Point:
    return Point(G1_X, G1_Y, Fq(1), B_G1)


def g2_generator() -> Point:
    return Point(G2_X, G2_Y, FQ2_ONE, B_G2)


def g1_infinity() -> Point:
    return Point.infinity(Fq(1), B_G1)


def g2_infinity() -> Point:
    return Point.infinity(FQ2_ONE, B_G2)


# ---------------------------------------------------------------------------
# ZCash compressed serialization
# flags in the top 3 bits of the first byte:
#   bit7 C_flag (always 1: compressed), bit6 I_flag (infinity),
#   bit5 S_flag (sign: y > (p-1)/2 lexicographically)
# ---------------------------------------------------------------------------

_HALF_P = (P - 1) // 2


def g1_to_bytes(pt: Point) -> bytes:
    if pt.is_infinity():
        return bytes([0xC0]) + b"\x00" * 47
    x, y = pt.to_affine()
    flags = 0x80 | (0x20 if y.n > _HALF_P else 0)
    raw = x.n.to_bytes(48, "big")
    return bytes([raw[0] | flags]) + raw[1:]


def g2_to_bytes(pt: Point) -> bytes:
    if pt.is_infinity():
        return bytes([0xC0]) + b"\x00" * 95
    (x, y) = pt.to_affine()
    # sign from y.c1, falling back to y.c0 when c1 == 0
    if y.c1 != 0:
        s = y.c1 > _HALF_P
    else:
        s = y.c0 > _HALF_P
    flags = 0x80 | (0x20 if s else 0)
    raw1 = x.c1.to_bytes(48, "big")
    raw0 = x.c0.to_bytes(48, "big")
    return bytes([raw1[0] | flags]) + raw1[1:] + raw0


class DeserializationError(Exception):
    pass


def g1_from_bytes(data: bytes) -> Point:
    """Decompress + validate on-curve (subgroup check is separate)."""
    if len(data) != 48:
        raise DeserializationError("G1 point must be 48 bytes")
    c_flag = (data[0] >> 7) & 1
    i_flag = (data[0] >> 6) & 1
    s_flag = (data[0] >> 5) & 1
    if c_flag != 1:
        raise DeserializationError("uncompressed G1 not supported")
    if i_flag:
        if any(data[1:]) or (data[0] & 0x3F):
            raise DeserializationError("malformed infinity encoding")
        return g1_infinity()
    xn = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if xn >= P:
        raise DeserializationError("x >= p")
    x = Fq(xn)
    y2 = x * x * x + B_G1
    y = y2.sqrt()
    if y is None:
        raise DeserializationError("x not on curve")
    if (y.n > _HALF_P) != bool(s_flag):
        y = -y
    return Point(x, y, Fq(1), B_G1)


def g2_from_bytes(data: bytes) -> Point:
    if len(data) != 96:
        raise DeserializationError("G2 point must be 96 bytes")
    c_flag = (data[0] >> 7) & 1
    i_flag = (data[0] >> 6) & 1
    s_flag = (data[0] >> 5) & 1
    if c_flag != 1:
        raise DeserializationError("uncompressed G2 not supported")
    if i_flag:
        if any(data[1:]) or (data[0] & 0x3F):
            raise DeserializationError("malformed infinity encoding")
        return g2_infinity()
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:96], "big")
    if x0 >= P or x1 >= P:
        raise DeserializationError("x >= p")
    x = Fq2(x0, x1)
    y2 = x * x * x + B_G2
    y = y2.sqrt()
    if y is None:
        raise DeserializationError("x not on curve")
    if y.c1 != 0:
        cur_sign = y.c1 > _HALF_P
    else:
        cur_sign = y.c0 > _HALF_P
    if cur_sign != bool(s_flag):
        y = -y
    return Point(x, y, FQ2_ONE, B_G2)


@lru_cache(maxsize=4096)
def pubkey_to_point(pubkey: bytes) -> Point:
    """Deserialize + subgroup-check a 48-byte pubkey (cached: the same
    validator pubkeys recur across every attestation)."""
    pt = g1_from_bytes(bytes(pubkey))
    if not pt.is_infinity() and not pt.in_subgroup():
        raise DeserializationError("pubkey not in subgroup")
    return pt


@lru_cache(maxsize=4096)
def signature_to_point(sig: bytes) -> Point:
    pt = g2_from_bytes(bytes(sig))
    if not pt.is_infinity() and not pt.in_subgroup():
        raise DeserializationError("signature not in subgroup")
    return pt
