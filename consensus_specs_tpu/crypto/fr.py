"""BLS12-381 scalar field Fr and its FFT machinery — the polynomial
substrate for KZG commitments (eip4844) and DAS erasure coding.

From-scratch host oracle (reference capability: the field/FFT math the
eip4844/das specs import from research code).  r - 1 = 2^32 * odd, so
radix-2 FFTs exist for every power-of-two size up to 2^32.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

# subgroup order of BLS12-381 (the "BLS_MODULUS" of eip4844)
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# 7 generates the multiplicative group of Fr (smallest generator)
_GENERATOR = 7
_TWO_ADICITY = 32
assert (R - 1) % (1 << _TWO_ADICITY) == 0

# primitive 2^32-th root of unity
_ROOT_2_32 = pow(_GENERATOR, (R - 1) >> _TWO_ADICITY, R)


def root_of_unity(order: int) -> int:
    """Primitive ``order``-th root of unity (order a power of two)."""
    assert order & (order - 1) == 0 and 0 < order <= (1 << _TWO_ADICITY)
    return pow(_ROOT_2_32, (1 << _TWO_ADICITY) // order, R)


def fft(values: Sequence[int], inv: bool = False) -> List[int]:
    """Radix-2 NTT over Fr; ``inv`` gives the inverse transform."""
    n = len(values)
    assert n & (n - 1) == 0
    if n == 1:
        return [values[0] % R]
    w = root_of_unity(n)
    if inv:
        w = pow(w, R - 2, R)
    out = _fft_core([v % R for v in values], w)
    if inv:
        n_inv = pow(n, R - 2, R)
        out = [v * n_inv % R for v in out]
    return out


def _fft_core(values: List[int], w: int) -> List[int]:
    n = len(values)
    if n == 1:
        return values
    even = _fft_core(values[0::2], w * w % R)
    odd = _fft_core(values[1::2], w * w % R)
    out = [0] * n
    wk = 1
    for k in range(n // 2):
        t = wk * odd[k] % R
        out[k] = (even[k] + t) % R
        out[k + n // 2] = (even[k] - t) % R
        wk = wk * w % R
    return out


def ifft(values: Sequence[int]) -> List[int]:
    return fft(values, inv=True)


def reverse_bit_order(i: int, order: int) -> int:
    """Bit-reversal permutation index (das-core.md reverse_bit_order)."""
    assert order & (order - 1) == 0
    bits = order.bit_length() - 1
    return int(format(i, f"0{bits}b")[::-1], 2) if bits else 0


def reverse_bit_order_list(elements: Sequence) -> list:
    order = len(elements)
    return [elements[reverse_bit_order(i, order)] for i in range(order)]


# --- polynomial helpers ------------------------------------------------------


def poly_mul(a: Sequence[int], b: Sequence[int]) -> List[int]:
    out = [0] * (len(a) + len(b) - 1)
    for i, ai in enumerate(a):
        if ai == 0:
            continue
        for j, bj in enumerate(b):
            out[i + j] = (out[i + j] + ai * bj) % R
    return out


def poly_eval(poly: Sequence[int], x: int) -> int:
    acc = 0
    for c in reversed(poly):
        acc = (acc * x + c) % R
    return acc


def zero_poly(missing_positions: Sequence[int], order: int) -> List[int]:
    """Vanishing polynomial with roots at w^p for the given positions."""
    w = root_of_unity(order)
    poly = [1]
    for p in missing_positions:
        poly = poly_mul(poly, [(-pow(w, p, R)) % R, 1])
    return poly


def recover_polynomial(samples: Sequence[Optional[int]]) -> List[int]:
    """Erasure recovery: given evaluations of a degree < n/2 polynomial on
    the order-n domain with at most n/2 erased (None) positions, recover
    ALL n evaluations (standard zero-poly method: E = D*Z on the domain,
    deconvolve on a coset).
    """
    n = len(samples)
    assert n & (n - 1) == 0
    missing = [i for i, s in enumerate(samples) if s is None]
    if not missing:
        return [s % R for s in samples]
    assert len(missing) <= n // 2, "too many erasures"

    z = zero_poly(missing, n) + [0] * (n - len(missing) - 1)
    z_evals = fft(z)
    # E(w^i) = D(w^i) * Z(w^i); erased positions contribute 0 = anything*0
    e_evals = [
        (0 if s is None else s) * z_evals[i] % R
        for i, s in enumerate(samples)
    ]
    e_poly = ifft(e_evals)

    # deconvolve on the coset k*w^i where Z never vanishes
    k = 31337 % R
    k_pows = [pow(k, i, R) for i in range(n)]
    e_coset = fft([c * k_pows[i] % R for i, c in enumerate(e_poly)])
    z_coset = fft([c * k_pows[i] % R for i, c in enumerate(z)])
    d_coset = [
        e * pow(zc, R - 2, R) % R for e, zc in zip(e_coset, z_coset)
    ]
    d_poly = ifft(d_coset)
    k_inv = pow(k, R - 2, R)
    d_poly = [c * pow(k_inv, i, R) % R for i, c in enumerate(d_poly)]
    recovered = fft(d_poly)

    for i, s in enumerate(samples):
        if s is not None:
            assert recovered[i] == s % R, "recovery inconsistent with inputs"
    return recovered
