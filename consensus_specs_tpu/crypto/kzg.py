"""KZG polynomial commitments over BLS12-381 G1 — the eip4844 crypto core
(reference capability: specs/eip4844/beacon-chain.md KZG core + the
trusted-setup preset entries KZG_SETUP_G2/KZG_SETUP_LAGRANGE).

The INSECURE deterministic trusted setup mirrors the spec's "minimal
insecure variant may be used during testing": powers of a fixed secret.
Commitment computation is a G1 multi-scalar multiplication; the host path
here is the correctness oracle, the batched device MSM lives in
ops/kzg_jax.py and is differentially tested against this module.
"""
from __future__ import annotations

from functools import lru_cache
from typing import List, Sequence

from .bls.curve import Point, g1_from_bytes, g1_generator, g1_infinity, g1_to_bytes
from .bls.fields import P as _FQ_P
from .fr import R, ifft, root_of_unity

# the spec's insecure testing secret must only ever appear in presets
INSECURE_SECRET = 1337


@lru_cache(maxsize=4)
def setup_monomial(n: int, secret: int = INSECURE_SECRET) -> List[Point]:
    """[G, sG, s^2 G, ...] — monomial-basis setup."""
    out = []
    acc = 1
    g = g1_generator()
    for _ in range(n):
        out.append(g.mul(acc))
        acc = acc * secret % R
    return out


@lru_cache(maxsize=4)
def setup_g2_monomial(n: int, secret: int = INSECURE_SECRET) -> List[Point]:
    """[H, sH, s^2 H, ...] — G2-side setup (degree proofs, sharding)."""
    from .bls.curve import g2_generator

    out = []
    acc = 1
    h = g2_generator()
    for _ in range(n):
        out.append(h.mul(acc))
        acc = acc * secret % R
    return out


@lru_cache(maxsize=4)
def setup_lagrange(n: int, secret: int = INSECURE_SECRET) -> List[Point]:
    """Lagrange-basis setup over the order-n root-of-unity domain:
    L_i(s) * G, computed as the inverse NTT of the monomial setup's
    scalars (host: scalars first, then single scalar-mults)."""
    # L_i(s) over the domain: ifft of [1, s, s^2, ...] as evaluations?
    # Direct route: L_i(s) = prod_{j!=i} (s - w^j)/(w^i - w^j); for the
    # roots-of-unity domain this reduces to w^i (s^n - 1) / (n (s - w^i)).
    w = root_of_unity(n)
    s_pow_n_minus_1 = (pow(secret, n, R) - 1) % R
    n_inv = pow(n, R - 2, R)
    g = g1_generator()
    out = []
    wi = 1
    for _ in range(n):
        denom_inv = pow((secret - wi) % R, R - 2, R)
        li = wi * s_pow_n_minus_1 % R * n_inv % R * denom_inv % R
        out.append(g.mul(li))
        wi = wi * w % R
    return out


def g1_lincomb(points: Sequence[Point], scalars: Sequence[int]) -> Point:
    """Multi-scalar multiplication (host oracle; naive double-and-add)."""
    acc = g1_infinity()
    for p, s in zip(points, scalars):
        s %= R
        if s:
            acc = acc + p.mul(s)
    return acc


def g1_msm_pippenger(points: Sequence[Point], scalars: Sequence[int],
                     window_bits: int = 8) -> Point:
    """Bucketed MSM — ~10x the naive oracle at blob size (4096 points).
    Differentially tested against g1_lincomb."""
    n_windows = (255 + window_bits - 1) // window_bits
    n_buckets = 1 << window_bits
    scalars = [s % R for s in scalars]
    acc = g1_infinity()
    for w in range(n_windows - 1, -1, -1):
        if w != n_windows - 1:
            for _ in range(window_bits):
                acc = acc.double()
        buckets = [None] * n_buckets
        shift = w * window_bits
        for p, s in zip(points, scalars):
            digit = (s >> shift) & (n_buckets - 1)
            if digit:
                buckets[digit] = p if buckets[digit] is None else buckets[digit] + p
        # bucket aggregation: sum_i i * bucket[i] via suffix running sums
        running = g1_infinity()
        window_sum = g1_infinity()
        for i in range(n_buckets - 1, 0, -1):
            if buckets[i] is not None:
                running = running + buckets[i]
            window_sum = window_sum + running
        acc = acc + window_sum
    return acc


_UNSET = object()
_NATIVE = _UNSET


def _native_mod():
    """The C++ BLS backend module, or None when unavailable (its fast G1
    arithmetic hosts the Pippenger MSM entry point bls_g1_msm)."""
    global _NATIVE
    if _NATIVE is _UNSET:
        try:
            from .bls import native as n

            _NATIVE = n
        except ImportError:
            _NATIVE = None
    return _NATIVE


# Affine x||y serialization of a point list, cached by list identity: the
# lru_cached setups are stable objects, and batch inversion (one modular
# inverse + 3n mults) keeps a cache miss cheap.  Strong refs keep ids valid.
_AFFINE_CACHE: dict = {}
_AFFINE_CACHE_MAX = 8


def _points_affine_bytes(points: Sequence[Point]) -> bytes:
    key = id(points)
    hit = _AFFINE_CACHE.get(key)
    if hit is not None and hit[0] is points:
        return hit[1]
    n = len(points)
    zs = [p.z.n for p in points]
    prefix = [1] * (n + 1)
    for i, z in enumerate(zs):
        prefix[i + 1] = prefix[i] * z % _FQ_P
    inv = pow(prefix[n], _FQ_P - 2, _FQ_P)
    zinvs = [0] * n
    for i in range(n - 1, -1, -1):
        zinvs[i] = inv * prefix[i] % _FQ_P
        inv = inv * zs[i] % _FQ_P
    parts = []
    for p, zi in zip(points, zinvs):
        zi2 = zi * zi % _FQ_P
        x = p.x.n * zi2 % _FQ_P
        y = p.y.n * zi2 % _FQ_P * zi % _FQ_P
        parts.append(x.to_bytes(48, "big") + y.to_bytes(48, "big"))
    data = b"".join(parts)
    if len(_AFFINE_CACHE) >= _AFFINE_CACHE_MAX:
        _AFFINE_CACHE.clear()
    _AFFINE_CACHE[key] = (points, data)
    return data


# fixed-base tables (blob commitments always hit the same setup): id-keyed
# like _AFFINE_CACHE; one table is ~8.6 MB at blob scale, so keep few
_FIXED_TABLES: dict = {}
_FIXED_TABLES_MAX = 2


_MSM_ABI_TAG = None


def _msm_abi_tag(nat) -> str:
    """ABI fingerprint of the persisted table format: byte order, pointer
    width, and — the real behavioral probe — a digest of the serialized
    window table of the G1 generator.  Entries are raw Montgomery limbs in
    machine byte order, so any change to limb size, limb order, or the
    Montgomery representation on the build host changes this tag and the
    stale table becomes a cache miss instead of garbage input."""
    global _MSM_ABI_TAG
    if _MSM_ABI_TAG is None:
        import ctypes
        import hashlib
        import sys

        gen = g1_generator()
        gen_xy = (gen.x.n.to_bytes(48, "big") + gen.y.n.to_bytes(48, "big"))
        h = hashlib.sha256()
        h.update(sys.byteorder.encode())
        h.update(bytes([ctypes.sizeof(ctypes.c_void_p)]))
        h.update(nat.G1MSMPrecompute(gen_xy))
        _MSM_ABI_TAG = h.hexdigest()[:8]
    return _MSM_ABI_TAG


def _fixed_table_path(nat, flat: bytes) -> str:
    import hashlib
    import os

    here = os.path.join(os.path.dirname(os.path.abspath(nat.__file__)),
                        "native")
    key = (nat._source_digest()[:8] + "_" + _msm_abi_tag(nat) + "_"
           + hashlib.sha256(flat).hexdigest()[:16])
    return os.path.join(here, f"_msmtab_{key}.bin")


_MSM_TABLE_KIND = "msm-fixed-table"


def _load_or_build_fixed_table(nat, flat: bytes) -> bytes:
    """Disk-cached shifted-window table: the ~1-5 s expansion of a blob
    setup otherwise recurs in every process.  Keyed by (native source
    digest, ABI tag, points digest) in the PATH — the entries are raw
    Montgomery limbs, valid only for the exact library build *and host
    ABI* — and persisted through ``persist/atomic.py`` (ISSUE 14: this
    cache pioneered the torn-write-safe discipline in PR 5; it now rides
    the one shared implementation): unique-temp + ``os.replace`` writes,
    trailing SHA-256, and the ABI tag bound INSIDE the envelope too, so
    even a renamed foreign table degrades to a miss.

    Failure containment: a truncated, damaged, or stale-tagged file
    fails verification and is REGENERATED in place; a reader can never
    observe a half-written table (the C side's on-curve entry-0 check
    stays as the tamper backstop behind both)."""
    from consensus_specs_tpu.persist import atomic

    path = _fixed_table_path(nat, flat)
    tag = _msm_abi_tag(nat)
    expect = 96 * (len(flat) // 96) * nat._MSM_FIXED_WINDOWS
    try:
        return atomic.read_artifact(path, _MSM_TABLE_KIND, tag,
                                    expected_payload_len=expect)
    except atomic.ArtifactError:
        pass  # missing / truncated / damaged / stale: rebuild below
    table = nat.G1MSMPrecompute(flat)
    try:
        atomic.write_artifact(path, table, _MSM_TABLE_KIND, tag)
    except OSError:
        pass  # read-only tree: rebuild per process
    return table


def g1_msm_native(points: Sequence[Point], scalars: Sequence[int],
                  fixed_base: bool = False):
    """Compressed-MSM fast path through the C++ Pippenger (bls_g1_msm) —
    ~20x the Python bucket MSM at blob scale.  With ``fixed_base`` the
    shifted-window table is precomputed once per point list and each call
    is a single bucket pass (bls_g1_msm_fixed) — the shape KZG wants, since
    every commitment targets the same trusted setup.  Returns compressed
    bytes, or None when the native backend is absent or an input point is
    at infinity (not representable in affine form).  Differentially pinned
    to g1_msm_pippenger/g1_lincomb in tests/crypto/test_kzg.py."""
    nat = _native_mod()
    if nat is None or any(p.is_infinity() for p in points):
        return None
    sc = b"".join((s % R).to_bytes(32, "big") for s in scalars)
    if fixed_base and len(points) == len(scalars):
        key = id(points)
        hit = _FIXED_TABLES.get(key)
        if hit is None or hit[0] is not points:
            table = _load_or_build_fixed_table(
                nat, _points_affine_bytes(points))
            if len(_FIXED_TABLES) >= _FIXED_TABLES_MAX:
                _FIXED_TABLES.clear()
            _FIXED_TABLES[key] = (points, table)
        else:
            table = hit[1]
        return nat.G1MSMFixed(table, len(points), sc)
    flat = _points_affine_bytes(points)[: 96 * len(scalars)]
    return nat.G1MSM(flat, sc)


def blob_to_kzg(blob: Sequence[int], lagrange_setup: Sequence[Point]) -> bytes:
    """Commit to a blob of field elements given in evaluation form."""
    assert len(blob) <= len(lagrange_setup)
    for v in blob:
        assert 0 <= v < R
    if len(blob) == len(lagrange_setup):
        # full-width commitment (the spec's shape): fixed-base tables hit
        # across blobs because the lru_cached setup is a stable object
        nat = g1_msm_native(lagrange_setup, blob, fixed_base=True)
        if nat is not None:
            return nat
    if len(blob) >= 64:  # bucketed MSM wins well before blob scale
        # pass the UNSLICED setup (g1_msm_native truncates the serialized
        # bytes itself) so the id-keyed affine cache hits across calls
        nat = g1_msm_native(lagrange_setup, blob)
        if nat is not None:
            return nat
        return g1_to_bytes(g1_msm_pippenger(lagrange_setup[: len(blob)], blob))
    return g1_to_bytes(g1_lincomb(lagrange_setup[: len(blob)], blob))


def commitment_to_point(commitment: bytes) -> Point:
    return g1_from_bytes(bytes(commitment))


def evaluate_blob_poly(blob: Sequence[int], x: int) -> int:
    """Evaluate the polynomial interpolating the blob (evaluation form on
    the root-of-unity domain) at an arbitrary x (barycentric form)."""
    n = len(blob)
    w = root_of_unity(n)
    if pow(x, n, R) == 1:  # x on the domain: direct read-off
        wi = 1
        for i in range(n):
            if wi == x % R:
                return blob[i] % R
            wi = wi * w % R
    num = (pow(x, n, R) - 1) * pow(n, R - 2, R) % R
    acc = 0
    wi = 1
    for i in range(n):
        acc = (acc + blob[i] * wi % R * pow((x - wi) % R, R - 2, R)) % R
        wi = wi * w % R
    return acc * num % R


def verify_commitment_matches_poly(commitment: bytes, blob: Sequence[int],
                                   secret: int = INSECURE_SECRET) -> bool:
    """Test-only oracle check: C == P(s)*G for the insecure setup."""
    expected = g1_generator().mul(evaluate_blob_poly(blob, secret))
    return bytes(commitment) == g1_to_bytes(expected)
