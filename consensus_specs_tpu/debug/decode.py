"""YAML/JSON structure -> SSZ value (inverse of encode.py; reference
capability: eth2spec/debug/decode.py)."""
from __future__ import annotations

from consensus_specs_tpu.ssz.impl import hash_tree_root
from consensus_specs_tpu.ssz.types import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Union,
    Vector,
    boolean,
    uint,
)


def decode(data, typ):
    if issubclass(typ, (uint, boolean)):
        return typ(int(data))
    if issubclass(typ, (ByteVector, ByteList)):
        return typ(bytes.fromhex(data[2:]))
    if issubclass(typ, (Bitlist, Bitvector)):
        # encode() emits the serialized bit form
        return typ.decode_bytes(bytes.fromhex(data[2:]))
    if issubclass(typ, (List, Vector)):
        elem = typ.ELEM_TYPE
        return typ([decode(v, elem) for v in data])
    if issubclass(typ, Container):
        kwargs = {}
        for name, ftyp in zip(typ._field_names, typ._field_types):
            kwargs[name] = decode(data[name], ftyp)
            htr_key = name + "_hash_tree_root"
            if htr_key in data:
                assert data[htr_key][2:] == hash_tree_root(kwargs[name]).hex()
        out = typ(**kwargs)
        if "hash_tree_root" in data:
            assert data["hash_tree_root"][2:] == hash_tree_root(out).hex()
        return out
    if issubclass(typ, Union):
        selector = int(data["selector"])
        opt = typ.OPTIONS[selector]
        if opt is None:
            assert data["value"] is None
            return typ(selector=selector, value=None)
        return typ(selector=selector, value=decode(data["value"], opt))
    raise TypeError(f"cannot decode into {typ!r}")
