"""Debug/vector tooling: YAML-shaped encoding and SSZ fuzzing.

Mirrors the capability of the reference's eth2spec/debug package
(encode.py, decode.py, random_value.py) on this framework's type system;
powers the generators' ``data`` parts and the ssz_static fuzz suites.
"""
