"""Random SSZ object generation — the fuzz engine behind ssz_static vectors
(reference capability: eth2spec/debug/random_value.py, six modes).

Modes and their vector-suite meanings:
  random     random content and random lengths
  zero       all-zero values, minimal lengths
  max        all-max values, count-1 lengths
  nil        empty collections
  one        single-element collections, random content
  lengthy    max-length collections, random content
``chaos`` re-rolls the mode per object, mixing shapes within one value.
"""
from __future__ import annotations

from enum import Enum
from random import Random
from typing import Type

from consensus_specs_tpu.ssz.types import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Union,
    Vector,
    boolean,
    uint,
)

UINT_BYTE_SIZES = (1, 2, 4, 8, 16, 32)

random_mode_names = ("random", "zero", "max", "nil", "one", "lengthy")


class RandomizationMode(Enum):
    mode_random = 0
    mode_zero = 1
    mode_max = 2
    mode_nil_count = 3
    mode_one_count = 4
    mode_max_count = 5

    def to_name(self) -> str:
        return random_mode_names[self.value]

    def is_changing(self) -> bool:
        """Modes whose output varies run-to-run (drives case counts)."""
        return self.value in (0, 4, 5)


def _rand_bytes(rng: Random, n: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(n))


def get_random_ssz_object(
    rng: Random,
    typ: Type,
    max_bytes_length: int,
    max_list_length: int,
    mode: RandomizationMode,
    chaos: bool = False,
):
    if chaos:
        mode = rng.choice(list(RandomizationMode))
    M = RandomizationMode

    if issubclass(typ, ByteList):
        limit = typ.LIMIT
        if mode == M.mode_nil_count:
            return typ(b"")
        if mode == M.mode_max_count:
            return typ(_rand_bytes(rng, min(max_bytes_length, limit)))
        if mode == M.mode_one_count:
            return typ(_rand_bytes(rng, min(1, limit)))
        if mode == M.mode_zero:
            return typ(b"\x00" * min(1, limit))
        if mode == M.mode_max:
            return typ(b"\xff" * min(1, limit))
        return typ(_rand_bytes(rng, rng.randint(0, min(max_bytes_length, limit))))

    if issubclass(typ, ByteVector):
        n = typ.type_byte_length()
        if mode == M.mode_zero:
            return typ(b"\x00" * n)
        if mode == M.mode_max:
            return typ(b"\xff" * n)
        return typ(_rand_bytes(rng, n))

    if issubclass(typ, boolean):
        if mode == M.mode_zero:
            return typ(False)
        if mode == M.mode_max:
            return typ(True)
        return typ(rng.choice((True, False)))

    if issubclass(typ, uint):
        size = typ.type_byte_length()
        assert size in UINT_BYTE_SIZES
        if mode == M.mode_zero:
            return typ(0)
        if mode == M.mode_max:
            return typ(256**size - 1)
        return typ(rng.randint(0, 256**size - 1))

    if issubclass(typ, Bitvector):
        n = typ.LENGTH
        if mode == M.mode_zero:
            return typ([False] * n)
        if mode == M.mode_max:
            return typ([True] * n)
        return typ([rng.choice((True, False)) for _ in range(n)])

    if issubclass(typ, Bitlist):
        limit = typ.LENGTH
        length = rng.randint(0, min(limit, max_list_length))
        if mode == M.mode_one_count:
            length = 1
        elif mode == M.mode_max_count:
            length = max_list_length
        elif mode == M.mode_nil_count:
            length = 0
        length = min(length, limit)
        if mode == M.mode_zero:
            return typ([False] * length)
        if mode == M.mode_max:
            return typ([True] * length)
        return typ([rng.choice((True, False)) for _ in range(length)])

    if issubclass(typ, Vector):
        return typ([
            get_random_ssz_object(
                rng, typ.ELEM_TYPE, max_bytes_length, max_list_length, mode, chaos
            )
            for _ in range(typ.LENGTH)
        ])

    if issubclass(typ, List):
        limit = typ.LENGTH
        length = rng.randint(0, min(limit, max_list_length))
        if mode == M.mode_one_count:
            length = 1
        elif mode == M.mode_max_count:
            length = max_list_length
        elif mode == M.mode_nil_count:
            length = 0
        length = min(length, limit)
        return typ([
            get_random_ssz_object(
                rng, typ.ELEM_TYPE, max_bytes_length, max_list_length, mode, chaos
            )
            for _ in range(length)
        ])

    if issubclass(typ, Container):
        return typ(**{
            name: get_random_ssz_object(
                rng, ftyp, max_bytes_length, max_list_length, mode, chaos
            )
            for name, ftyp in zip(typ._field_names, typ._field_types)
        })

    if issubclass(typ, Union):
        options = typ.OPTIONS
        if mode == M.mode_zero:
            selector = 0
        elif mode == M.mode_max:
            selector = len(options) - 1
        else:
            selector = rng.randrange(len(options))
        opt = options[selector]
        value = None if opt is None else get_random_ssz_object(
            rng, opt, max_bytes_length, max_list_length, mode, chaos
        )
        return typ(selector=selector, value=value)

    raise TypeError(f"cannot randomize {typ!r}")
