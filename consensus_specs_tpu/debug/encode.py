"""SSZ value -> YAML/JSON-encodable structure (reference capability:
eth2spec/debug/encode.py; adapted to this framework's view classes).

Encoding contract (identical observable output to the reference, so
generated vectors' yaml parts are cross-client comparable):
  * uints <= 8 bytes -> int; larger uints -> decimal string
  * boolean -> bool
  * Bitlist/Bitvector -> '0x' + serialized hex
  * byte types -> '0x' hex
  * sequences -> list of encoded elements
  * containers -> {field: encoded}, optionally with hash_tree_root keys
  * unions -> {'selector': int, 'value': encoded | None}
"""
from __future__ import annotations

from consensus_specs_tpu.ssz.impl import hash_tree_root, serialize
from consensus_specs_tpu.ssz.types import (
    Bitlist,
    Bitvector,
    Container,
    List,
    Union,
    Vector,
    boolean,
    uint,
)


def encode(value, include_hash_tree_roots: bool = False):
    if isinstance(value, uint):
        if type(value).type_byte_length() > 8:
            return str(int(value))
        return int(value)
    if isinstance(value, boolean):
        return bool(value)
    if isinstance(value, (Bitlist, Bitvector)):
        return "0x" + serialize(value).hex()
    if isinstance(value, bytes):  # ByteVector / ByteList / raw bytes
        return "0x" + bytes(value).hex()
    if isinstance(value, (List, Vector)) or isinstance(value, list):
        return [encode(v, include_hash_tree_roots) for v in value]
    if isinstance(value, Container):
        out = {}
        for name in type(value)._field_names:
            field = getattr(value, name)
            out[name] = encode(field, include_hash_tree_roots)
            if include_hash_tree_roots:
                out[name + "_hash_tree_root"] = "0x" + hash_tree_root(field).hex()
        if include_hash_tree_roots:
            out["hash_tree_root"] = "0x" + hash_tree_root(value).hex()
        return out
    if isinstance(value, Union):
        inner = value.value
        return {
            "selector": int(value.selector),
            "value": None if inner is None else encode(inner, include_hash_tree_roots),
        }
    raise TypeError(f"cannot encode {type(value).__name__}: {value!r}")
