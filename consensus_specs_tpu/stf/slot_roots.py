"""Slot advancement with cheap per-slot state roots.

``process_slots`` (specs/src/phase0.py:785-806, textually identical in
every later fork) computes ``hash_tree_root(state)`` once per slot.  The
persistent node layer already makes that incremental — unchanged subtrees
keep their memoized roots — so the replica below is byte-identical to the
spec loop while routing the one genuinely expensive case through the
resident merkle path: a freshly bulk-written packed balances subtree
(epoch kernels and state loaders rewrite the whole vector through
``ssz/bulk.py``, leaving an unhashed power-of-two subtree of ~n/4 chunks).
When the resident-merkle policy engages (``CSTPU_RESIDENT_MERKLE``, auto =
accelerator backends only — ops/merkle_resident.py:resident_device), that
subtree is reduced on device as one jit dispatch and the 32-byte root is
memoized into the host backing (``memoize_packed_u64_contents_root``), so
empty-slot advancement after an epoch transition stops paying the full
host re-merkleization of the balances vector.  On host backends the
wave-batched hashlib path (ssz/hashing.hash_layer) keeps the same
incremental shape.

Differentially pinned to ``spec.process_slots`` by
tests/spec/phase0/sanity/test_stf_engine_differential.py.
"""
from __future__ import annotations

from consensus_specs_tpu import faults, tracing

# fault probe (tests/chaos/): fires at each slot advance, so an error
# lands with some slots already processed — the engine rollback must
# restore the whole multi-slot advance
_SITE_PROCESS = faults.site("stf.slot_roots.process")


def state_root(spec, state):
    """``hash_tree_root(state)``, with dirty bulk-written balance subtrees
    routed through the device-resident reduction when the policy engages."""
    _maybe_resident_balances_root(state)
    return spec.hash_tree_root(state)


def _maybe_resident_balances_root(state) -> None:
    from consensus_specs_tpu.ops import merkle_resident

    balances = getattr(state, "balances", None)
    if balances is None or len(balances) < merkle_resident.RESIDENT_MIN:
        return
    backing = balances.get_backing()
    if backing.left._root is not None:
        return  # contents subtree already hashed: incremental path is free
    device = merkle_resident.resident_device()
    if device is None:
        return
    try:
        from . import columns

        resident = merkle_resident.ResidentPackedU64List(
            type(balances).LENGTH, device=device)
        # resident-column read (ISSUE 10): after the epoch transition's
        # flush this is the identity fast path — no tree walk before the
        # device upload
        resident.upload(columns.balance_column(state).astype("u8"))
        merkle_resident.memoize_packed_u64_contents_root(
            balances, resident.contents_subtree_root())
        tracing.count("stf.resident_slot_root")
    except Exception:  # device flake: the host path is always correct
        tracing.count("stf.resident_slot_root_failed")


def process_slots(spec, state, slot) -> None:
    """Spec-identical ``process_slots`` (same asserts, same mutations, the
    spec module's own ``process_epoch``) with per-slot roots through
    ``state_root`` above."""
    assert state.slot < slot
    while state.slot < slot:
        _SITE_PROCESS()
        _process_slot(spec, state)
        # Process epoch on the start slot of the next epoch
        if (state.slot + 1) % spec.SLOTS_PER_EPOCH == 0:
            spec.process_epoch(state)
        state.slot = spec.Slot(state.slot + 1)


def _process_slot(spec, state) -> None:
    # Cache state root (phase0.py:796-806 verbatim behind state_root)
    previous_state_root = state_root(spec, state)
    state.state_roots[state.slot % spec.SLOTS_PER_HISTORICAL_ROOT] = previous_state_root
    # Cache latest block header state root
    if state.latest_block_header.state_root == spec.Bytes32():
        state.latest_block_header.state_root = previous_state_root
    # Cache block root
    previous_block_root = spec.hash_tree_root(state.latest_block_header)
    state.block_roots[state.slot % spec.SLOTS_PER_HISTORICAL_ROOT] = previous_block_root
