"""Cross-block overlapped signature verification (ISSUE 10 tentpole).

The serial engine strictly alternates host work and native work: block
N's pairing batch settles on the (GIL-releasing, internally thread-
pooled) native backend while the host waits, then the host runs block
N+1's phases while the native pool idles — at 400k validators roughly
half of every block's wall time each way.  This module overlaps them:
the engine dispatches block N's *materialized* signature batch here and
keeps going; while the batch runs on the dispatch thread, the host
executes block N+1's phases (slot_roots merkle, attestation plan
resolution, participation/balance apply).  Block N's verdict is awaited
only when block N+1's host phases are done — by which point it is
usually already settled, so the await is (near) free and the native
seconds disappear behind host seconds (``overlap_s``).

Shape and bounds:

* **one dispatch worker** — batches execute on a single daemon thread,
  so the in-flight queue is bounded at ``window_depth() + 1`` (the
  speculated blocks' batches plus the current block's, newer ones
  queuing behind the oldest) and every ``stf.verify`` counter keeps a
  single writer per key (no locks on the hot path).  The native call
  parallelizes internally; a second dispatch thread would only contend
  the pool.  The window defaults to depth 2 — one extra block of host
  slack absorbs the per-block jitter a depth-1 window leaks as await
  time (``CSTPU_PIPELINE_DEPTH`` overrides).
* **speculation never leaks** — the engine holds each block's cache
  transaction open until its verdict lands (stf/staging.py), and the
  verified-triple memo commit stays deferred through the transaction,
  so a speculated batch that fails (or a fault anywhere in the window)
  drains the pipeline, rolls both blocks' inserts back, and replays the
  failing block through the literal spec — the same bisection naming
  the same original entry (stf/engine.py owns that orchestration).
* **opt-out** — ``CSTPU_PIPELINE=0`` restores the serial engine path;
  results are byte-identical either way (pinned by
  tests/test_stf_pipeline.py and the differential suites' ON/OFF
  exception-parity battery).

Fault seams (tests/chaos/): ``stf.pipeline.dispatch`` fires on the host
before a batch is submitted (a dying dispatch must fail into the
block's own rollback), ``stf.pipeline.drain`` fires on the host at
await time (a dying drain must resolve like a failed verdict — rollback
and literal replay, caches coherent).
"""
from __future__ import annotations

import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence

from consensus_specs_tpu import faults, telemetry, tracing
from consensus_specs_tpu.telemetry import timeline

from . import verify

_SITE_DISPATCH = faults.site("stf.pipeline.dispatch")
_SITE_DRAIN = faults.site("stf.pipeline.drain")

_EXECUTOR: Optional[ThreadPoolExecutor] = None

# the bounded in-flight queue: handles dispatched but not yet drained,
# in dispatch order (FIFO; depth <= 2 by the engine's speculation
# window).  Registered with CC01 — only this module may mutate it.
_INFLIGHT: List["SigBatchHandle"] = []

stats = {
    "dispatched": 0,
    "drained": 0,
    "cancelled": 0,     # handles discarded unconsumed by a pipeline drain
    "drains": 0,        # pipeline drain events (failure/ineligible-block)
    "drain_reasons": {},  # reason -> count (the recorder holds the order)
    "depth_max": 0,
    "overlap_s": 0.0,   # native seconds hidden behind host work
    "await_s": 0.0,     # native seconds the host actually waited for
    "worker_s": 0.0,    # total batch wall seconds on the dispatch thread
}


def reset_stats() -> None:
    for k in stats:
        if isinstance(stats[k], dict):
            stats[k] = {}
        else:
            stats[k] = 0.0 if isinstance(stats[k], float) else 0


def enabled() -> bool:
    """The pipeline gate: on by default, ``CSTPU_PIPELINE=0`` opts out
    (read per call so tests can flip it without re-importing)."""
    return os.environ.get("CSTPU_PIPELINE", "1") != "0"


def window_depth() -> int:
    """How many blocks may hold an outstanding verdict at once (the
    speculation window).  Depth 2 (the default) banks one extra block of
    host work as slack, absorbing per-block jitter where batch and host
    times cross over; depth 1 is the minimal overlap.
    ``CSTPU_PIPELINE_DEPTH`` overrides (clamped to >= 1); in-flight
    handles are bounded at depth + 1 (the current block's dispatch joins
    momentarily before the oldest verdict is awaited)."""
    try:
        depth = int(os.environ.get("CSTPU_PIPELINE_DEPTH", "2"))
    except ValueError:
        depth = 2
    return max(1, depth)


def _executor() -> ThreadPoolExecutor:
    global _EXECUTOR
    if _EXECUTOR is None:
        _EXECUTOR = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cstpu-sigpipe")
    return _EXECUTOR


class SigBatchHandle:
    """One in-flight signature batch: the future plus enough accounting
    to attribute its wall time as overlapped or awaited."""

    __slots__ = ("future", "entries", "link", "t_dispatch", "worker_span",
                 "_done")

    def __init__(self, entries, link=None):
        self.entries = entries
        self.link = link  # the block's causality-link id (timeline)
        self.t_dispatch = time.perf_counter()
        self.worker_span = [0.0, 0.0]  # [start, end], written by the worker
        self._done = False
        self.future = _executor().submit(self._run)

    def _run(self):
        span = self.worker_span
        span[0] = time.perf_counter()
        # the worker's span carries the dispatching block's link, so the
        # Chrome-trace export draws the cross-thread edge host phases →
        # native verify (the PR 10 overlap made visible)
        sid = timeline.begin("native/verify", link=self.link,
                             entries=len(self.entries))
        try:
            return verify.first_invalid(self.entries)
        finally:
            timeline.end(sid)
            span[1] = time.perf_counter()


def dispatch(entries: Sequence[verify.SigEntry],
             link=None) -> SigBatchHandle:
    """Submit a materialized batch to the dispatch worker.  Entries must
    be fully materialized (affine buffers built) — the worker touches
    pure data plus the native call, never the geometry caches.  The
    sig-batch tracing counts land HERE (host side; ``verify.settle``
    emits them on the serial path), keeping the worker tracing-free and
    the counters alive pipeline ON or OFF.  ``link`` is the dispatching
    block's timeline causality id (None with the timeline off)."""
    _SITE_DISPATCH()
    tracing.count("stf.sig_batch")
    tracing.count("stf.sig_batch.entries", len(entries))
    handle = SigBatchHandle(list(entries), link=link)
    _INFLIGHT.append(handle)
    stats["dispatched"] += 1
    stats["depth_max"] = max(stats["depth_max"], len(_INFLIGHT))
    return handle


def wait(handle: SigBatchHandle) -> Optional[int]:
    """Block until ``handle``'s batch settles; returns the first-invalid
    index (None = all verified) or re-raises the worker's exception
    (InjectedFault and friends resolve on the host, into the engine's
    replay contract).  The drain probe fires BEFORE the verdict is
    consumed, so an injected drain failure leaves an unconsumed verdict
    for the registry-coherence contract to clean up."""
    _SITE_DRAIN()
    t0 = time.perf_counter()
    try:
        result = handle.future.result()
    finally:
        _consume(handle, time.perf_counter() - t0)
    return result


def _consume(handle: SigBatchHandle, awaited_s: float) -> None:
    if handle._done:
        return
    handle._done = True
    if handle in _INFLIGHT:
        _INFLIGHT.remove(handle)
    worker_s = max(0.0, handle.worker_span[1] - handle.worker_span[0])
    stats["drained"] += 1
    stats["await_s"] += awaited_s
    stats["worker_s"] += worker_s
    stats["overlap_s"] += max(0.0, worker_s - awaited_s)


def discard(handle: Optional[SigBatchHandle]) -> None:
    """Drain one handle without consuming its verdict (the block it
    belongs to is being rolled back): await completion — a native call
    cannot be interrupted mid-pairing — and swallow the outcome.  Only
    WORKER failures are swallowed (Exception); a host-side interrupt
    raised while waiting (KeyboardInterrupt/SystemExit) propagates."""
    if handle is None or handle._done:
        return
    t0 = time.perf_counter()
    # a queued, not-yet-started batch is cancelled for free (failure
    # recovery must not serialize behind seconds of doomed pairing work);
    # a running one is awaited — native calls can't be interrupted
    if not handle.future.cancel():
        try:
            handle.future.result()
        except Exception:
            pass  # the block is rolling back either way
    _consume(handle, time.perf_counter() - t0)
    stats["cancelled"] += 1


def note_drain(reason: str) -> None:
    """Count one pipeline drain event, attributed per reason (the
    flight recorder's ``pipeline_drain`` events hold the ordering)."""
    stats["drains"] += 1
    reasons = stats["drain_reasons"]
    reasons[reason] = reasons.get(reason, 0) + 1


def _telemetry_provider() -> dict:
    total = stats["worker_s"]
    return {
        **{k: v for k, v in stats.items() if k != "drain_reasons"},
        "drain_reasons": dict(stats["drain_reasons"]),
        "depth": len(_INFLIGHT),
        "overlap_ratio": (round(stats["overlap_s"] / total, 3)
                          if total > 0 else None),
        "enabled": enabled(),
    }


telemetry.register_provider("stf.pipeline", _telemetry_provider,
                            replace=True)
