"""Whole-block attestation resolution for the batched transition engine.

The spec's ``process_attestation`` resolves each aggregate's committee with
``get_beacon_committee`` (a Python list comprehension over the shuffled
permutation) and its attesters with a per-bit Python loop, then gathers
per-member pubkeys one view access at a time — ~25k Python object hops per
full mainnet block.  This module resolves the WHOLE block at once:

* committees come straight off the cached whole-epoch shuffle permutation
  (``ops/shuffle.py``) as numpy gathers — ``active[perm[start:end]]`` with
  the spec's exact ``compute_committee`` slice arithmetic
  (``ops/shuffle.committee_bounds``);
* attester sets are one boolean mask + sort per attestation, with
  per-attestation participation counts reduced in bulk by
  ``ops/segment.segment_sum`` (the same primitive the fork-choice batch
  path uses for vote deltas);
* member pubkeys are rows of a registry-keyed affine-coordinate matrix
  (decompressed once per validator through the native cache), so batch
  entries are contiguous buffer slices instead of per-member dict walks.

Every structural rule of ``process_attestation`` is checked here in spec
order; any violation raises ``FastPathViolation`` and the engine replays
the block through the literal spec path, which re-raises the spec's exact
exception (stf/engine.py).
"""
from __future__ import annotations

from typing import List, NamedTuple

import numpy as np

from consensus_specs_tpu import faults, telemetry
from consensus_specs_tpu.ops.segment import segment_sum
from consensus_specs_tpu.ops.shuffle import committee_bounds, compute_shuffle_permutation
from consensus_specs_tpu.ssz import bulk

from . import staging


class FastPathViolation(Exception):
    """A block failed a fast-path check (or needs a capability the fast
    path lacks): the engine rolls back and replays through the literal
    spec, which raises the spec's own exception."""


# fault probes (tests/chaos/): whole-block resolution and the affine
# gather feed the signature batch — both must fail into the replay
# contract without poisoning a memo; the plan memo is probed on the
# value it is about to insert, so a corrupted plan is both consumed by
# the faulted block (bad members -> failed batch or root mismatch) and
# popped again by the cache transaction when that block rolls back
_SITE_RESOLVE = faults.site("stf.attestations.resolve")
_SITE_AFFINE_ROWS = faults.site("stf.attestations.affine_rows")
_SITE_PLAN_MEMO = faults.site("stf.attestations.plan_memo")

# plan-cache effectiveness counters (ISSUE 9): the e2e speed story leans
# on re-carried aggregates hitting the plan memo, so the hit/miss split
# is first-class telemetry — bench embeds the ratio and the trend gate
# refuses a run whose ratio silently collapsed
stats = {"plan_hits": 0, "plan_misses": 0}


def reset_stats() -> None:
    """Zero the plan-cache counters (``reset_caches`` calls this too, so
    a cold-start-controlled bench pass reports its own ratio)."""
    for k in stats:
        stats[k] = 0


# -- per-epoch committee geometry --------------------------------------------

_ACTIVE_CACHE: dict = {}
_CTX_CACHE: dict = {}
_CTX_LOOKUP: dict = {}
_CACHE_MAX = 8


def _fifo_put(cache: dict, key, value, cap: int = _CACHE_MAX):
    """FIFO insert, recorded with the block's cache transaction (if one
    is active) so a failed block's inserts roll back — the transactional
    half of the rollback contract (stf/staging.py)."""
    if len(cache) >= cap:
        cache.pop(next(iter(cache)))
    cache[key] = value
    staging.note_insert(cache, key)
    return value


def active_indices(spec, state, epoch: int) -> np.ndarray:
    """Ascending active-validator index array for ``epoch`` (the numpy
    form of ``get_active_validator_indices``), registry-root-cached."""
    from consensus_specs_tpu.ops.epoch_jax import active_mask, registry_columns

    key = (bytes(state.validators.hash_tree_root()), int(epoch))
    hit = _ACTIVE_CACHE.get(key)
    if hit is not None:
        return hit
    return _fifo_put(_ACTIVE_CACHE, key, np.nonzero(
        active_mask(registry_columns(state), int(epoch)))[0])


class _CommitteeContext:
    """Numpy view of one epoch's committees: active-validator array, the
    cached shuffle permutation, and all committee slice bounds."""

    def __init__(self, spec, state, epoch: int, seed: bytes):
        self.active = active_indices(spec, state, epoch)
        self.slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
        n = len(self.active)
        # get_committee_count_per_slot (beacon-chain.md:931-940) off the
        # active COUNT — the spec call would materialize the 400k-element
        # active index list just to len() it
        self.committees_per_slot = max(1, min(
            int(spec.MAX_COMMITTEES_PER_SLOT),
            n // self.slots_per_epoch // int(spec.TARGET_COMMITTEE_SIZE)))
        count = self.committees_per_slot * self.slots_per_epoch
        self.bounds = committee_bounds(n, count)
        self.perm = compute_shuffle_permutation(
            seed, n, int(spec.SHUFFLE_ROUND_COUNT))

    def committee(self, slot: int, index: int) -> np.ndarray:
        g = (slot % self.slots_per_epoch) * self.committees_per_slot + index
        lo, hi = self.bounds[g], self.bounds[g + 1]
        return self.active[self.perm[lo:hi]]


def _spec_geometry_key(spec) -> tuple:
    """The spec constants the committee computation reads — every memo
    key below must bind them (CC02): two spec builds sharing registry and
    randao roots but differing in preset geometry must never share a
    context."""
    return (int(spec.SLOTS_PER_EPOCH), int(spec.MAX_COMMITTEES_PER_SLOT),
            int(spec.TARGET_COMMITTEE_SIZE), int(spec.SHUFFLE_ROUND_COUNT))


def _ctx_lookup_key(spec, state, epoch: int) -> tuple:
    """The memoized-root lookup key of one epoch's committee geometry —
    also the context half of every attestation-plan key (below): two
    states sharing it resolve every committee identically."""
    return (
        bytes(state.validators.hash_tree_root()),
        bytes(state.randao_mixes.hash_tree_root()),
        int(epoch),
        _spec_geometry_key(spec),
    )


def committee_context(spec, state, epoch: int) -> _CommitteeContext:
    """Cached committee geometry.  The context itself is keyed on registry
    root + attester seed (the full input set of the spec's committee
    computation); a lookup layer keyed on the memoized registry/randao
    roots makes the per-attestation hit path a dict probe instead of a
    ``get_seed`` hash chain."""
    lookup_key = _ctx_lookup_key(spec, state, epoch)
    ctx = _CTX_LOOKUP.get(lookup_key)
    if ctx is not None:
        return ctx
    seed = bytes(spec.get_seed(
        state, spec.Epoch(epoch), spec.DOMAIN_BEACON_ATTESTER))
    key = (lookup_key[0], int(epoch), seed, _spec_geometry_key(spec))
    ctx = _CTX_CACHE.get(key)
    if ctx is None:
        ctx = _fifo_put(
            _CTX_CACHE, key, _CommitteeContext(spec, state, int(epoch), seed))
    return _fifo_put(_CTX_LOOKUP, lookup_key, ctx)


# -- proposer index off the numpy active set ---------------------------------

_PROPOSER_CACHE: dict = {}


def beacon_proposer_index(spec, state):
    """``get_beacon_proposer_index`` (beacon-chain.md:954-961) evaluated
    against the numpy active array: same seed, same scalar shuffled-index
    walk, same effective-balance rejection sampling — without building the
    spec's 400k-element ``ValidatorIndex`` list per epoch."""
    from consensus_specs_tpu.ops.epoch_jax import registry_columns

    epoch = spec.get_current_epoch(state)
    seed = bytes(spec.hash(
        spec.get_seed(state, epoch, spec.DOMAIN_BEACON_PROPOSER)
        + spec.uint_to_bytes(spec.uint64(state.slot))))
    key = (bytes(state.validators.hash_tree_root()), seed,
           _spec_geometry_key(spec), int(spec.MAX_EFFECTIVE_BALANCE))
    hit = _PROPOSER_CACHE.get(key)
    if hit is not None:
        return hit
    active = active_indices(spec, state, int(epoch))
    eff = registry_columns(state)["effective_balance"]
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    total = spec.uint64(len(active))
    # compute_proposer_index (beacon-chain.md:886-902) verbatim over the
    # numpy candidates; compute_shuffled_index is the spec's own (LRU'd)
    assert total > 0
    i = spec.uint64(0)
    while True:
        shuffled = spec.compute_shuffled_index(
            spec.uint64(int(i) % int(total)), total, seed)
        candidate = int(active[int(shuffled)])
        random_byte = spec.hash(
            seed + spec.uint_to_bytes(spec.uint64(int(i) // 32)))[int(i) % 32]
        if int(eff[candidate]) * 255 >= max_eb * random_byte:
            return _fifo_put(_PROPOSER_CACHE, key, spec.ValidatorIndex(candidate))
        i = spec.uint64(int(i) + 1)


# -- registry affine-coordinate matrix ---------------------------------------

_AFFINE_MATRIX_CACHE = bulk.RootKeyedCache(2)

_ZERO_ROW = b"\x00" * 96


def _new_affine_matrix(validators):
    """Eager whole-registry affine matrix: decompress each UNIQUE pubkey
    once through the batched native entry (one thread-pooled call, not a
    ctypes round-trip per key), then one C-speed join over the column.
    Rows whose pubkey cannot decompress are zero-marked, not fatal — the
    spec only fails when such a validator actually attests."""
    from consensus_specs_tpu.crypto.bls import native

    column = bulk.cached_validator_pubkeys(validators)
    affine_of = native.pubkey_affine_batch(set(column))
    invalid_pks = {pk for pk, xy in affine_of.items() if xy is None}
    for pk in invalid_pks:
        affine_of[pk] = _ZERO_ROW
    n = len(column)
    mat = np.frombuffer(
        b"".join(map(affine_of.__getitem__, column)), dtype=np.uint8
    ).reshape(n, 96)
    invalid = None
    if invalid_pks:
        invalid = np.fromiter(
            (pk in invalid_pks for pk in column), dtype=bool, count=n)
    return {"mat": mat, "invalid": invalid, "root": bytes(validators.hash_tree_root())}


def affine_matrix(validators) -> dict:
    """Registry-root-cached affine coordinate matrix + invalid-row mask.
    A build triggered mid-block is recorded with the cache transaction
    like every other fast-path insert (the value is pure in the registry
    root, so the rollback only costs a rebuild)."""
    return _AFFINE_MATRIX_CACHE.get(validators, _new_affine_matrix,
                                    on_insert=staging.note_insert)


def reset_caches() -> None:
    """Drop every derived-geometry cache (committee contexts, active sets,
    proposer walks, attestation plans, affine matrices, sync-committee
    seat rows, resident columns) plus the native decompression cache —
    bench cold-start control and test isolation."""
    from . import columns, sync

    from consensus_specs_tpu.ops import epoch_jax

    _ACTIVE_CACHE.clear()
    _CTX_CACHE.clear()
    _CTX_LOOKUP.clear()
    _PROPOSER_CACHE.clear()
    _PLAN_CACHE.clear()
    _PLAN_CTX_LOOKUP.clear()
    _AFFINE_MATRIX_CACHE._store.clear()
    reset_stats()
    sync.reset_caches()
    columns.reset_caches()
    epoch_jax.reset_caches()  # matching-scan memo: same cold-start control
    try:
        from consensus_specs_tpu.crypto.bls import native

        native.clear_affine_cache()
        native.clear_h2c_cache()  # same cold-start control for hashing
    except ImportError:
        pass


def affine_rows(validators, indices: np.ndarray) -> bytes:
    """Contiguous affine x||y coordinates for ``indices`` (ascending
    member order of one batch entry)."""
    entry = affine_matrix(validators)
    if entry["invalid"] is not None and entry["invalid"][indices].any():
        # an unverifiable member pubkey: the spec's FastAggregateVerify
        # returns False and process_attestation asserts — replay path
        raise FastPathViolation("invalid registry pubkey among attesters")
    # probed on the outgoing buffer: a corrupted coordinate fails the
    # batch, bisects to this entry, and the block replays literally
    return _SITE_AFFINE_ROWS(entry["mat"][indices].tobytes())


# -- whole-block resolution: the epoch-scoped attestation plan ---------------

# plan memo: (plan ctx key, attestation-data root, aggregation-bits
# root) -> AttestationPlan.  The corpus a live node sees re-carries
# aggregates heavily (every attestation rides in the next two blocks;
# gossip re-delivery does the same), so most of a block's resolutions are
# repeats of work an earlier block already did — committee gather, bits
# unpack, attester sort.  Both root halves are memoized SSZ roots, so the
# key is content-addressed: distinct decoded copies of the same aggregate
# hit the same plan.  The ctx half is the committee computation's TRUE
# input set — (registry root, epoch, attester seed, geometry) — NOT the
# full randao_mixes root: the current epoch's mix changes every block
# (process_randao), while the seed reads a mix pinned epochs ago, so
# seed-keying is what makes plans live across the blocks that re-carry
# them (and across the epoch boundary's pending-attestation scans).
# Capacity covers two full mainnet epochs of unique aggregates
# (2 * 32 slots * 64 committees) with headroom.
_PLAN_CACHE: dict = {}
_PLAN_CACHE_MAX = 8192

# (ctx lookup key) -> (plan ctx key): maps the cheap memoized-root lookup
# identity onto the seed identity so repeat callers (the epoch kernel's
# per-pending scans) pay a dict probe, not a get_seed hash chain
_PLAN_CTX_LOOKUP: dict = {}


def plan_ctx_key(spec, state, epoch: int) -> tuple:
    """The plan key's context half for one (state, epoch): registry root +
    epoch + attester seed + geometry constants (CC02-covered through
    ``_ctx_lookup_key``'s transparency)."""
    lk = _ctx_lookup_key(spec, state, epoch)
    pk = _PLAN_CTX_LOOKUP.get(lk)
    if pk is None:
        seed = bytes(spec.get_seed(
            state, spec.Epoch(epoch), spec.DOMAIN_BEACON_ATTESTER))
        pk = (lk[0], int(epoch), seed, lk[3])
        _fifo_put(_PLAN_CTX_LOOKUP, lk, pk)
    return pk


def cached_plan_attesters(plan_ctx: tuple, data, bits):
    """The owner-side read seam for state-resident pending attestations
    (``ops/epoch_jax.attesting_indices``): the epoch transition's
    per-pending scans resolve the very aggregates the block path already
    planned, so a probe on the content-addressed key replaces the
    committee gather + bits unpack.  ``plan_ctx`` is ``plan_ctx_key``
    computed ONCE per scan — recomputing it per pending would re-pay the
    two state-field view constructions 14k times per epoch.  Returns the
    SORTED attester array on a hit (callers are set-semantics scatters),
    or None."""
    plan = _PLAN_CACHE.get((plan_ctx,
                            bytes(data.hash_tree_root()),
                            bytes(bits.hash_tree_root())))
    return plan.attesters if plan is not None else None


class AttestationPlan(NamedTuple):
    """One aggregate's resolved application plan: everything about the
    attestation that is pure in (committee geometry, data, bits) — the
    per-block work left is the state-slot window checks, the justified-
    checkpoint compare, and the state writes themselves."""

    attesters: np.ndarray  # sorted attesting validator indices (readonly)
    data_root: bytes       # hash_tree_root(att.data) — signing-root input
    target_epoch: int      # int(data.target.epoch) — the apply loops'
    #                        current/previous discriminator, off the plan
    #                        instead of a per-attestation SSZ field chain


def resolve_block_attestations(spec, state) -> "_BlockResolver":
    return _BlockResolver(spec, state)


class _BlockResolver:
    """Resolves every attestation of one block against a fixed pre-ops
    state snapshot of the committee geometry."""

    def __init__(self, spec, state):
        self.spec = spec
        self.state = state
        self.previous_epoch = int(spec.get_previous_epoch(state))
        self.current_epoch = int(spec.get_current_epoch(state))
        self.state_slot = int(state.slot)
        self.min_delay = int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
        self.slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
        # the two plan ctx keys a block can touch, computed once per
        # block instead of per attestation (memoized-root reads + tuple
        # build were the hit path's dominant cost)
        self._ctx_keys: dict = {}

    def _ctx_key(self, target_epoch: int) -> tuple:
        key = self._ctx_keys.get(target_epoch)
        if key is None:
            key = self._ctx_keys[target_epoch] = plan_ctx_key(
                self.spec, self.state, target_epoch)
        return key

    def resolve(self, attestations) -> List[AttestationPlan]:
        """One ``AttestationPlan`` per attestation, after the spec's
        structural asserts (process_attestation, beacon-chain.md:1686-1714)
        — target epoch window, slot inclusion window, committee index
        range, and bit-count/committee-size match.  State-dependent checks
        (epoch window, inclusion window) re-run per block; data-pure checks
        and the committee gather + bits unpack + attester sort are served
        from the plan memo (a hit proves they passed when the plan was
        built — any fast-path ordering difference is unobservable because
        EVERY violation routes to the same literal replay, which raises
        the spec's own exception at the spec's own point)."""
        spec, state = self.spec, self.state
        plans: List = [None] * len(attestations)
        cold = []
        for i, att in enumerate(attestations):
            _SITE_RESOLVE()
            data = att.data
            target_epoch = int(data.target.epoch)
            slot = int(data.slot)
            if target_epoch not in (self.previous_epoch, self.current_epoch):
                raise FastPathViolation("target epoch outside window")
            if not (slot + self.min_delay <= self.state_slot
                    <= slot + self.slots_per_epoch):
                raise FastPathViolation("inclusion window")
            plan_key = (self._ctx_key(target_epoch),
                        bytes(data.hash_tree_root()),
                        bytes(att.aggregation_bits.hash_tree_root()))
            plan = _PLAN_CACHE.get(plan_key)
            if plan is None:
                cold.append((i, att, plan_key, target_epoch))
            else:
                plans[i] = plan
        stats["plan_hits"] += len(attestations) - len(cold)
        stats["plan_misses"] += len(cold)
        if cold:
            self._resolve_cold(cold, plans)
        return plans

    def _resolve_cold(self, cold, plans) -> None:
        """Batched first-sight resolution: per-item structural checks +
        committee gathers, then ONE concatenated mask/segment-count/argsort
        pass over the whole cold set (the per-item ``np.sort``/``np.split``
        walk this replaces was the cold path's Python floor).  Committee
        members are unique by construction (permutation slices), so the
        per-segment sorted gather IS the spec's ``sorted(set(...))``."""
        spec, state = self.spec, self.state
        comms, bit_arrays = [], []
        for i, att, plan_key, target_epoch in cold:
            data = att.data
            slot = int(data.slot)
            if target_epoch != slot // self.slots_per_epoch:
                raise FastPathViolation("target epoch != epoch of slot")
            ctx = committee_context(spec, state, target_epoch)
            if int(data.index) >= ctx.committees_per_slot:
                raise FastPathViolation("committee index out of range")
            committee = ctx.committee(slot, int(data.index))
            bits = bulk.bitlist_to_numpy(att.aggregation_bits)
            if len(bits) != len(committee):
                raise FastPathViolation("aggregation bits != committee size")
            comms.append(committee)
            bit_arrays.append(bits)
        k = len(cold)
        lens = np.fromiter((len(b) for b in bit_arrays), np.int64, k)
        item_ids = np.repeat(np.arange(k, dtype=np.int64), lens)
        all_bits = np.concatenate(bit_arrays)
        counts = segment_sum(all_bits.astype(np.int64), item_ids, k)
        if not counts.all():
            raise FastPathViolation("empty attesting set")
        selected = np.concatenate(comms)[all_bits]
        # one argsort for the whole block: stable sort on (item, value)
        order = np.lexsort((selected, item_ids[all_bits]))
        parts = np.split(selected[order], np.cumsum(counts)[:-1])
        for (i, att, plan_key, target_epoch), attesters in zip(cold, parts):
            # probed on the attester set about to enter the memo: a
            # corrupted plan is consumed by THIS block (wrong members ->
            # failed batch or root mismatch -> replay) and the poisoned
            # insert pops with the block's cache transaction
            attesters = _SITE_PLAN_MEMO(attesters)
            attesters.setflags(write=False)
            plan = AttestationPlan(attesters, plan_key[1], target_epoch)
            plans[i] = plan
            _fifo_put(_PLAN_CACHE, plan_key, plan, cap=_PLAN_CACHE_MAX)


# -- telemetry ----------------------------------------------------------------


def _telemetry_provider() -> dict:
    """Plan-cache effectiveness + the sizes of every geometry memo this
    module owns (all FIFO-bounded; the soak harness asserts the sizes
    never exceed the caps)."""
    return {
        "plan_hits": stats["plan_hits"],
        "plan_misses": stats["plan_misses"],
        "plan_size": len(_PLAN_CACHE),
        "plan_cap": _PLAN_CACHE_MAX,
        "ctx_size": len(_CTX_CACHE),
        "ctx_lookup_size": len(_CTX_LOOKUP),
        "plan_ctx_lookup_size": len(_PLAN_CTX_LOOKUP),
        "active_size": len(_ACTIVE_CACHE),
        "proposer_size": len(_PROPOSER_CACHE),
        "geometry_cap": _CACHE_MAX,
    }


telemetry.register_provider("stf.plan_cache", _telemetry_provider,
                            replace=True)
