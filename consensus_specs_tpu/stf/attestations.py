"""Whole-block attestation resolution for the batched transition engine.

The spec's ``process_attestation`` resolves each aggregate's committee with
``get_beacon_committee`` (a Python list comprehension over the shuffled
permutation) and its attesters with a per-bit Python loop, then gathers
per-member pubkeys one view access at a time — ~25k Python object hops per
full mainnet block.  This module resolves the WHOLE block at once:

* committees come straight off the cached whole-epoch shuffle permutation
  (``ops/shuffle.py``) as numpy gathers — ``active[perm[start:end]]`` with
  the spec's exact ``compute_committee`` slice arithmetic
  (``ops/shuffle.committee_bounds``);
* attester sets are one boolean mask + sort per attestation, with
  per-attestation participation counts reduced in bulk by
  ``ops/segment.segment_sum`` (the same primitive the fork-choice batch
  path uses for vote deltas);
* member pubkeys are rows of a registry-keyed affine-coordinate matrix
  (decompressed once per validator through the native cache), so batch
  entries are contiguous buffer slices instead of per-member dict walks.

Every structural rule of ``process_attestation`` is checked here in spec
order; any violation raises ``FastPathViolation`` and the engine replays
the block through the literal spec path, which re-raises the spec's exact
exception (stf/engine.py).
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np

from consensus_specs_tpu import faults
from consensus_specs_tpu.ops.segment import segment_sum
from consensus_specs_tpu.ops.shuffle import committee_bounds, compute_shuffle_permutation
from consensus_specs_tpu.ssz import bulk

from . import staging


class FastPathViolation(Exception):
    """A block failed a fast-path check (or needs a capability the fast
    path lacks): the engine rolls back and replays through the literal
    spec, which raises the spec's own exception."""


# fault probes (tests/chaos/): whole-block resolution and the affine
# gather feed the signature batch — both must fail into the replay
# contract without poisoning a memo
_SITE_RESOLVE = faults.site("stf.attestations.resolve")
_SITE_AFFINE_ROWS = faults.site("stf.attestations.affine_rows")


# -- per-epoch committee geometry --------------------------------------------

_ACTIVE_CACHE: dict = {}
_CTX_CACHE: dict = {}
_CTX_LOOKUP: dict = {}
_CACHE_MAX = 8


def _fifo_put(cache: dict, key, value, cap: int = _CACHE_MAX):
    """FIFO insert, recorded with the block's cache transaction (if one
    is active) so a failed block's inserts roll back — the transactional
    half of the rollback contract (stf/staging.py)."""
    if len(cache) >= cap:
        cache.pop(next(iter(cache)))
    cache[key] = value
    staging.note_insert(cache, key)
    return value


def active_indices(spec, state, epoch: int) -> np.ndarray:
    """Ascending active-validator index array for ``epoch`` (the numpy
    form of ``get_active_validator_indices``), registry-root-cached."""
    from consensus_specs_tpu.ops.epoch_jax import active_mask, registry_columns

    key = (bytes(state.validators.hash_tree_root()), int(epoch))
    hit = _ACTIVE_CACHE.get(key)
    if hit is not None:
        return hit
    return _fifo_put(_ACTIVE_CACHE, key, np.nonzero(
        active_mask(registry_columns(state), int(epoch)))[0])


class _CommitteeContext:
    """Numpy view of one epoch's committees: active-validator array, the
    cached shuffle permutation, and all committee slice bounds."""

    def __init__(self, spec, state, epoch: int, seed: bytes):
        self.active = active_indices(spec, state, epoch)
        self.slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
        n = len(self.active)
        # get_committee_count_per_slot (beacon-chain.md:931-940) off the
        # active COUNT — the spec call would materialize the 400k-element
        # active index list just to len() it
        self.committees_per_slot = max(1, min(
            int(spec.MAX_COMMITTEES_PER_SLOT),
            n // self.slots_per_epoch // int(spec.TARGET_COMMITTEE_SIZE)))
        count = self.committees_per_slot * self.slots_per_epoch
        self.bounds = committee_bounds(n, count)
        self.perm = compute_shuffle_permutation(
            seed, n, int(spec.SHUFFLE_ROUND_COUNT))

    def committee(self, slot: int, index: int) -> np.ndarray:
        g = (slot % self.slots_per_epoch) * self.committees_per_slot + index
        lo, hi = self.bounds[g], self.bounds[g + 1]
        return self.active[self.perm[lo:hi]]


def _spec_geometry_key(spec) -> tuple:
    """The spec constants the committee computation reads — every memo
    key below must bind them (CC02): two spec builds sharing registry and
    randao roots but differing in preset geometry must never share a
    context."""
    return (int(spec.SLOTS_PER_EPOCH), int(spec.MAX_COMMITTEES_PER_SLOT),
            int(spec.TARGET_COMMITTEE_SIZE), int(spec.SHUFFLE_ROUND_COUNT))


def committee_context(spec, state, epoch: int) -> _CommitteeContext:
    """Cached committee geometry.  The context itself is keyed on registry
    root + attester seed (the full input set of the spec's committee
    computation); a lookup layer keyed on the memoized registry/randao
    roots makes the per-attestation hit path a dict probe instead of a
    ``get_seed`` hash chain."""
    lookup_key = (
        bytes(state.validators.hash_tree_root()),
        bytes(state.randao_mixes.hash_tree_root()),
        int(epoch),
        _spec_geometry_key(spec),
    )
    ctx = _CTX_LOOKUP.get(lookup_key)
    if ctx is not None:
        return ctx
    seed = bytes(spec.get_seed(
        state, spec.Epoch(epoch), spec.DOMAIN_BEACON_ATTESTER))
    key = (lookup_key[0], int(epoch), seed, _spec_geometry_key(spec))
    ctx = _CTX_CACHE.get(key)
    if ctx is None:
        ctx = _fifo_put(
            _CTX_CACHE, key, _CommitteeContext(spec, state, int(epoch), seed))
    return _fifo_put(_CTX_LOOKUP, lookup_key, ctx)


# -- proposer index off the numpy active set ---------------------------------

_PROPOSER_CACHE: dict = {}


def beacon_proposer_index(spec, state):
    """``get_beacon_proposer_index`` (beacon-chain.md:954-961) evaluated
    against the numpy active array: same seed, same scalar shuffled-index
    walk, same effective-balance rejection sampling — without building the
    spec's 400k-element ``ValidatorIndex`` list per epoch."""
    from consensus_specs_tpu.ops.epoch_jax import registry_columns

    epoch = spec.get_current_epoch(state)
    seed = bytes(spec.hash(
        spec.get_seed(state, epoch, spec.DOMAIN_BEACON_PROPOSER)
        + spec.uint_to_bytes(spec.uint64(state.slot))))
    key = (bytes(state.validators.hash_tree_root()), seed,
           _spec_geometry_key(spec), int(spec.MAX_EFFECTIVE_BALANCE))
    hit = _PROPOSER_CACHE.get(key)
    if hit is not None:
        return hit
    active = active_indices(spec, state, int(epoch))
    eff = registry_columns(state)["effective_balance"]
    max_eb = int(spec.MAX_EFFECTIVE_BALANCE)
    total = spec.uint64(len(active))
    # compute_proposer_index (beacon-chain.md:886-902) verbatim over the
    # numpy candidates; compute_shuffled_index is the spec's own (LRU'd)
    assert total > 0
    i = spec.uint64(0)
    while True:
        shuffled = spec.compute_shuffled_index(
            spec.uint64(int(i) % int(total)), total, seed)
        candidate = int(active[int(shuffled)])
        random_byte = spec.hash(
            seed + spec.uint_to_bytes(spec.uint64(int(i) // 32)))[int(i) % 32]
        if int(eff[candidate]) * 255 >= max_eb * random_byte:
            return _fifo_put(_PROPOSER_CACHE, key, spec.ValidatorIndex(candidate))
        i = spec.uint64(int(i) + 1)


# -- registry affine-coordinate matrix ---------------------------------------

_AFFINE_MATRIX_CACHE = bulk.RootKeyedCache(2)

_ZERO_ROW = b"\x00" * 96


def _new_affine_matrix(validators):
    """Eager whole-registry affine matrix: decompress each UNIQUE pubkey
    once (native cache), then one C-speed join over the column.  Rows whose
    pubkey cannot decompress are zero-marked, not fatal — the spec only
    fails when such a validator actually attests."""
    from consensus_specs_tpu.crypto.bls import native

    column = bulk.cached_validator_pubkeys(validators)
    affine_of = {pk: native.pubkey_affine(pk) for pk in set(column)}
    invalid_pks = {pk for pk, xy in affine_of.items() if xy is None}
    for pk in invalid_pks:
        affine_of[pk] = _ZERO_ROW
    n = len(column)
    mat = np.frombuffer(
        b"".join(map(affine_of.__getitem__, column)), dtype=np.uint8
    ).reshape(n, 96)
    invalid = None
    if invalid_pks:
        invalid = np.fromiter(
            (pk in invalid_pks for pk in column), dtype=bool, count=n)
    return {"mat": mat, "invalid": invalid, "root": bytes(validators.hash_tree_root())}


def affine_matrix(validators) -> dict:
    """Registry-root-cached affine coordinate matrix + invalid-row mask.
    A build triggered mid-block is recorded with the cache transaction
    like every other fast-path insert (the value is pure in the registry
    root, so the rollback only costs a rebuild)."""
    return _AFFINE_MATRIX_CACHE.get(validators, _new_affine_matrix,
                                    on_insert=staging.note_insert)


def reset_caches() -> None:
    """Drop every derived-geometry cache (committee contexts, active sets,
    proposer walks, affine matrices, sync-committee seat rows) plus the
    native decompression cache — bench cold-start control and test
    isolation."""
    from . import sync

    _ACTIVE_CACHE.clear()
    _CTX_CACHE.clear()
    _CTX_LOOKUP.clear()
    _PROPOSER_CACHE.clear()
    _AFFINE_MATRIX_CACHE._store.clear()
    sync.reset_caches()
    try:
        from consensus_specs_tpu.crypto.bls import native

        native.clear_affine_cache()
        native.clear_h2c_cache()  # same cold-start control for hashing
    except ImportError:
        pass


def affine_rows(validators, indices: np.ndarray) -> bytes:
    """Contiguous affine x||y coordinates for ``indices`` (ascending
    member order of one batch entry)."""
    entry = affine_matrix(validators)
    if entry["invalid"] is not None and entry["invalid"][indices].any():
        # an unverifiable member pubkey: the spec's FastAggregateVerify
        # returns False and process_attestation asserts — replay path
        raise FastPathViolation("invalid registry pubkey among attesters")
    # probed on the outgoing buffer: a corrupted coordinate fails the
    # batch, bisects to this entry, and the block replays literally
    return _SITE_AFFINE_ROWS(entry["mat"][indices].tobytes())


# -- whole-block resolution ---------------------------------------------------

def resolve_block_attestations(spec, state) -> "_BlockResolver":
    return _BlockResolver(spec, state)


class _BlockResolver:
    """Resolves every attestation of one block against a fixed pre-ops
    state snapshot of the committee geometry."""

    def __init__(self, spec, state):
        self.spec = spec
        self.state = state
        self.previous_epoch = int(spec.get_previous_epoch(state))
        self.current_epoch = int(spec.get_current_epoch(state))
        self.state_slot = int(state.slot)
        self.min_delay = int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
        self.slots_per_epoch = int(spec.SLOTS_PER_EPOCH)

    def resolve(self, attestations) -> List[Tuple[np.ndarray, np.ndarray]]:
        """[(committee, bits)] per attestation, after the spec's structural
        asserts (process_attestation, beacon-chain.md:1686-1714) — target
        epoch window, slot inclusion window, committee index range, and
        bit-count/committee-size match — evaluated in spec order."""
        spec, state = self.spec, self.state
        out = []
        for att in attestations:
            _SITE_RESOLVE()
            data = att.data
            target_epoch = int(data.target.epoch)
            slot = int(data.slot)
            if target_epoch not in (self.previous_epoch, self.current_epoch):
                raise FastPathViolation("target epoch outside window")
            if target_epoch != slot // self.slots_per_epoch:
                raise FastPathViolation("target epoch != epoch of slot")
            if not (slot + self.min_delay <= self.state_slot
                    <= slot + self.slots_per_epoch):
                raise FastPathViolation("inclusion window")
            ctx = committee_context(spec, state, target_epoch)
            if int(data.index) >= ctx.committees_per_slot:
                raise FastPathViolation("committee index out of range")
            committee = ctx.committee(slot, int(data.index))
            bits = bulk.bitlist_to_numpy(att.aggregation_bits)
            if len(bits) != len(committee):
                raise FastPathViolation("aggregation bits != committee size")
            out.append((committee, bits))
        return out


def attesting_index_sets(resolved) -> List[np.ndarray]:
    """Sorted attesting-index arrays for a block's resolved attestations.

    One concatenated mask selects every attester in the block; per-item
    participation counts are one ``segment_sum`` over the item axis (the
    indexed-attestation emptiness rule — is_valid_indexed_attestation's
    ``len(indices) == 0`` reject — checked for all items in bulk).
    Committee members are unique by construction (permutation slices), so
    the sorted gather IS the spec's ``sorted(set(...))``."""
    if not resolved:
        return []
    k = len(resolved)
    lens = np.fromiter((len(bits) for _, bits in resolved), np.int64, k)
    item_ids = np.repeat(np.arange(k, dtype=np.int64), lens)
    all_bits = np.concatenate([bits for _, bits in resolved])
    counts = segment_sum(all_bits.astype(np.int64), item_ids, k)
    if not counts.all():
        raise FastPathViolation("empty attesting set")
    members = np.concatenate([committee for committee, _ in resolved])
    selected = members[all_bits]
    offsets = np.cumsum(counts)[:-1]
    return [np.sort(part) for part in np.split(selected, offsets)]
