"""Block-scoped transactional cache commits for the stf fast path.

The fast path populates process-global memos mid-block (committee
contexts, proposer walks, sync seat rows, affine matrices, the
verified-triple memo).  Before this module, an insert landed the moment
it was computed — so a fault between the insert and the block settling
could strand an entry whose value a corruption fault had just poisoned,
and every later block would consume it (the engine would silently replay
forever, or worse).  The chaos suite (tests/chaos/) makes that scenario
a tested path; this module makes it impossible:

* **visible inserts with an undo log** — caches the block itself re-reads
  (committee contexts are probed per attestation) insert immediately, but
  the owning module records each (cache, key) with ``note_insert``;
  if the block fails, ``rollback`` pops exactly those entries, so a
  failed block leaves every memo as it found it;
* **deferred commits** — inserts nothing re-reads within the block (the
  verified-triple memo keys) are staged with ``defer`` and applied only
  after the block fully settles — including the post-state root check —
  so a triple can never enter the memo on the strength of a block that
  then failed.

The engine opens one transaction per block (``block_transaction`` in
the synchronous path); with no transaction active (literal replays,
direct helper use, tests poking the memos), ``note_insert`` is a no-op
and ``defer`` runs the commit immediately — the memos behave exactly as
before PR 5.

**Overlapped pipeline (ISSUE 10):** the cross-block pipeline keeps block
N's transaction OPEN (verdict outstanding) while block N+1's host phases
run under their own transaction.  The explicit split API —
``begin_block`` / ``deactivate`` / ``commit_block`` / ``rollback_block``
— supports that: ``begin_block`` makes a fresh transaction current,
``deactivate`` detaches it (it stays open, later inserts route to the
successor's transaction), and settlement happens through
``commit_block``/``rollback_block`` on the detached handle.
``commit_block`` runs the deferred queue with NO transaction current, so
a deferred commit's own cache inserts can never leak into the
*successor's* undo log.  ``block_transaction`` is the same machinery as
a context manager.
"""
from __future__ import annotations

import contextlib
from typing import Optional

from consensus_specs_tpu.telemetry import recorder

_TXN: Optional["CacheTransaction"] = None


class CacheTransaction:
    """Undo log for visible inserts + queue of deferred commits, scoped to
    one block of ``apply_signed_blocks``."""

    __slots__ = ("_undo", "_deferred")

    def __init__(self):
        self._undo = []      # (cache_dict, key): pop on rollback
        self._deferred = []  # (fn, args): run on commit

    def note_insert(self, cache: dict, key) -> None:
        self._undo.append((cache, key))

    def defer(self, fn, *args) -> None:
        self._deferred.append((fn, args))

    def commit(self) -> None:
        """Apply deferred commits; on any failure mid-commit, undo the
        block's visible inserts too and re-raise (already-applied deferred
        entries are content-addressed facts — safe to keep)."""
        n_deferred, n_visible = len(self._deferred), len(self._undo)
        try:
            while self._deferred:
                fn, args = self._deferred.pop(0)
                fn(*args)
        except BaseException:
            self.rollback()
            raise
        self._undo.clear()
        # the event fires only after every deferred commit landed — a
        # torn commit takes the rollback branch and logs honestly
        recorder.record("cache_commit", deferred=n_deferred,
                        visible=n_visible)

    def rollback(self) -> None:
        """Pop every visible insert this block made (newest first) and
        drop the deferred queue: the memos read as if the block never
        ran.  Removal-only, so concurrent FIFO evictions stay safe."""
        n_undo, n_deferred = len(self._undo), len(self._deferred)
        while self._undo:
            cache, key = self._undo.pop()
            cache.pop(key, None)
        self._deferred.clear()
        recorder.record("cache_rollback", undone=n_undo,
                        deferred_dropped=n_deferred)


def current() -> Optional[CacheTransaction]:
    return _TXN


def note_insert(cache: dict, key) -> None:
    """Record a visible insert with the active transaction (no-op when
    none is active — non-engine callers keep the old immediate
    semantics)."""
    txn = _TXN
    if txn is not None:
        txn.note_insert(cache, key)


def defer(fn, *args) -> None:
    """Stage a commit for block settlement, or run it now when no
    transaction is active."""
    txn = _TXN
    if txn is not None:
        txn.defer(fn, *args)
    else:
        fn(*args)


def begin_block() -> CacheTransaction:
    """Open a fresh block transaction and make it current.  The caller
    owns settlement: ``deactivate`` when the block's host phases are done
    (the transaction stays open for the pipeline's speculation window),
    then ``commit_block`` or ``rollback_block``.  Must not be called with
    a transaction already current (the engine guards; re-entrant callers
    use ``block_transaction``)."""
    global _TXN
    assert _TXN is None, "begin_block with a transaction already current"
    txn = _TXN = CacheTransaction()
    return txn


def deactivate(txn: CacheTransaction) -> None:
    """Detach ``txn`` from the current slot (it stays open — its undo log
    and deferred queue settle later via commit_block/rollback_block)."""
    global _TXN
    if _TXN is txn:
        _TXN = None


def commit_block(txn: CacheTransaction) -> None:
    """Settle a (possibly detached) block transaction.  Runs with NO
    transaction current: a deferred commit's own inserts apply
    immediately instead of leaking into whatever successor transaction
    happens to be current (the pipeline's overlap window)."""
    global _TXN
    outer = _TXN
    _TXN = None
    try:
        txn.commit()
    finally:
        _TXN = outer if outer is not txn else None


def rollback_block(txn: CacheTransaction) -> None:
    """Roll back a (possibly detached) block transaction; pops exactly
    the entries that block inserted, drops its deferred queue."""
    global _TXN
    txn.rollback()
    if _TXN is txn:
        _TXN = None


@contextlib.contextmanager
def block_transaction():
    """One block's cache transaction: commit on clean exit, roll back on
    any exception (then re-raise into the engine's replay contract).
    Re-entrant use joins the outer transaction."""
    global _TXN
    if _TXN is not None:
        yield _TXN
        return
    txn = begin_block()
    try:
        yield txn
    except BaseException:
        rollback_block(txn)
        raise
    else:
        commit_block(txn)
    finally:
        deactivate(txn)
