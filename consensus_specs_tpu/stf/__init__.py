"""Batched block-path transition engine.

``apply_signed_blocks(spec, state, signed_blocks)`` replays signed blocks
with one BLS multi-pairing per block (cross-block triple dedup), whole-
block vectorized attestation application, and resident-routed per-slot
roots — differentially pinned to byte-identical post-states and identical
failure behavior vs the literal ``spec.state_transition``.

Layers (see docs/architecture.md, "The block path"):

* ``attestations`` — committee/attester resolution off the cached shuffle
  permutation, bulk counts via ``ops/segment.py``, registry affine matrix;
* ``verify``       — per-block signature batch: preflattened
  ``BatchFastAggregateVerify`` entries, verified-triple memo, bisection;
* ``sync``         — altair-lineage sync aggregates: seat rows memoized
  per sync period, the signature folded into the block batch, rewards as
  net per-validator deltas;
* ``slot_roots``   — spec-identical ``process_slots`` with dirty bulk
  subtrees routed through the resident merkle path;
* ``columns``      — root-keyed resident validator-state columns
  (participation, balances, registry-derived device buffers) serving
  dict probes where the tree hands out chunk walks;
* ``pipeline``     — cross-block overlapped verification: block N's
  native pairing batch runs on a dispatch worker while block N+1's
  host phases execute (``CSTPU_PIPELINE=0`` opts out);
* ``engine``       — the optimistic fast path + exact-spec replay
  fallback that makes failure behavior literally the spec's
  (fork families: phase0, and altair/bellatrix with the execution
  payload run literally inside the snapshot region), plus the
  speculation window's LIFO drain orchestration.
"""
from .attestations import FastPathViolation
from .engine import apply_signed_blocks, reset_stats, stats

__all__ = ["apply_signed_blocks", "FastPathViolation", "reset_stats", "stats"]
