"""Sync-aggregate fast path for the batched block-transition engine.

Altair's ``process_sync_aggregate`` (altair/beacon-chain.md:487-525) costs
the sequential path three separate walks per block: a 512-member
FastAggregateVerify pairing, an O(registry) pubkey scan to map committee
seats to validator indices, and ~1500 single-seat balance writes (one
``increase_balance`` per participant plus one per-participant proposer
increment).  This module folds all three into the engine's existing
batched shapes:

* the aggregate signature becomes ONE more entry in the block's
  ``BatchFastAggregateVerify`` multi-pairing (stf/verify.py) — message =
  previous-slot block root under ``DOMAIN_SYNC_COMMITTEE``, members
  resolved to rows of the registry affine matrix through a per-period
  memo (below), deduped through the verified-triple memo and covered by
  the same bisection-to-first-failure;
* seat-to-validator resolution is memoized per sync-committee period:
  ``sync_committee_rows`` maps the current committee's pubkeys to
  registry row indices once per (registry, committee) version, with the
  spec's exact first-occurrence (``list.index``) semantics;
* rewards apply as net per-validator deltas — participant/proposer
  reward math in exact integer arithmetic, per-seat occurrences
  aggregated with ``np.add.at`` — touching each affected balance leaf
  once instead of per seat.

Net-delta application is only order-equivalent to the spec's sequential
``increase_balance``/``decrease_balance`` walk while no balance can hit
the ``decrease_balance`` floor or the uint64 ceiling mid-sequence; both
are checked conservatively and any doubt raises ``FastPathViolation``,
handing the block to the literal replay (stf/engine.py's rollback
contract).  Differentially pinned by
tests/spec/altair/sanity/test_stf_engine_differential.py.
"""
from __future__ import annotations

import numpy as np

from consensus_specs_tpu import faults, telemetry, tracing

from .attestations import (
    FastPathViolation,
    _fifo_put,
    affine_rows,
    beacon_proposer_index,
)

# fault probes (tests/chaos/): the seat memo build (a corrupted value
# here must roll back with the block, never serve a later one) and the
# mid-walk reward application (partial balance writes must restore)
_SITE_ROWS_MEMO = faults.site("stf.sync.rows_memo")
_SITE_REWARDS = faults.site("stf.sync.rewards")

# -- per-period seat-to-registry-row memo -------------------------------------

_SYNC_ROWS_CACHE: dict = {}
_CACHE_MAX = 4


def sync_committee_rows(spec, state) -> np.ndarray:
    """Registry row indices of the CURRENT sync committee, in seat order
    with duplicate pubkeys preserved (the numpy form of the spec's
    ``all_pubkeys.index(pubkey)`` per seat — first occurrence wins).
    Memoized per (registry, committee) version: one resolution per sync
    period unless the registry changes under it."""
    key = (bytes(state.validators.hash_tree_root()),
           bytes(state.current_sync_committee.hash_tree_root()))
    hit = _SYNC_ROWS_CACHE.get(key)
    if hit is not None:
        return hit
    from consensus_specs_tpu.ssz import bulk

    index_of = bulk.cached_pubkey_index(state.validators)
    pubkeys = state.current_sync_committee.pubkeys
    try:
        rows = np.fromiter((index_of[bytes(pk)] for pk in pubkeys),
                           dtype=np.int64, count=len(pubkeys))
    except KeyError:
        # the spec's list.index scan raises on a committee pubkey missing
        # from the registry — replay path surfaces its exact ValueError
        raise FastPathViolation("sync committee pubkey not in registry")
    # probed before the insert: a corrupted seat map fails the block (bad
    # signature members / bad rewards -> root mismatch) and the cache
    # transaction pops the poisoned entry with the rollback
    rows = _SITE_ROWS_MEMO(rows)
    rows.setflags(write=False)
    return _fifo_put(_SYNC_ROWS_CACHE, key, rows, cap=_CACHE_MAX)


def reset_caches() -> None:
    """Drop the seat-resolution memo (bench cold-start control and test
    isolation)."""
    _SYNC_ROWS_CACHE.clear()


def _telemetry_provider() -> dict:
    return {"rows_memo_size": len(_SYNC_ROWS_CACHE), "cap": _CACHE_MAX}


telemetry.register_provider("stf.sync", _telemetry_provider, replace=True)


# -- process_sync_aggregate, engine shape -------------------------------------


def _u64(value: int) -> int:
    """The spec's reward math runs in checked uint64 (``Gwei``/``uint64``
    products raise on overflow); mirror the bound so the engine never
    accepts arithmetic the spec would reject."""
    if value >= 1 << 64:
        raise FastPathViolation("uint64 overflow in sync reward math")
    return value


def process_sync_aggregate(spec, state, sync_aggregate, collect, bls_on) -> None:
    """``process_sync_aggregate`` (altair/beacon-chain.md:487-525) with the
    signature deferred into the block batch and rewards as net deltas."""
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.ssz import bulk

    rows = sync_committee_rows(spec, state)
    bits = bulk.bitlist_to_numpy(sync_aggregate.sync_committee_bits)
    if len(bits) != len(rows):
        raise FastPathViolation("sync bits != committee size")
    participant_rows = rows[bits]

    if bls_on:
        signature = bytes(sync_aggregate.sync_committee_signature)
        if len(participant_rows) == 0:
            # eth_fast_aggregate_verify's one non-pairing acceptance: the
            # empty participation set with the infinity signature
            if signature != bls.G2_POINT_AT_INFINITY:
                raise FastPathViolation("empty sync set, non-infinity sig")
        else:
            previous_slot = max(int(state.slot), 1) - 1
            domain = spec.get_domain(
                state, spec.DOMAIN_SYNC_COMMITTEE,
                spec.compute_epoch_at_slot(spec.Slot(previous_slot)))
            signing_root = spec.compute_signing_root(
                spec.get_block_root_at_slot(state, spec.Slot(previous_slot)),
                domain)
            registry_root = bytes(state.validators.hash_tree_root())
            validators = state.validators
            collect(registry_root + participant_rows.tobytes(),
                    len(participant_rows),
                    lambda r=participant_rows: affine_rows(validators, r),
                    bytes(signing_root), signature)
    tracing.count("stf.sync_aggregate")

    # participant/proposer reward derivation (spec lines verbatim, in
    # checked integer arithmetic)
    ebi = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    weight_denominator = int(spec.WEIGHT_DENOMINATOR)
    proposer_weight = int(spec.PROPOSER_WEIGHT)
    total_active_increments = int(spec.get_total_active_balance(state)) // ebi
    total_base_rewards = _u64(
        int(spec.get_base_reward_per_increment(state)) * total_active_increments)
    max_participant_rewards = (
        _u64(total_base_rewards * int(spec.SYNC_REWARD_WEIGHT))
        // weight_denominator // int(spec.SLOTS_PER_EPOCH))
    participant_reward = max_participant_rewards // int(spec.SYNC_COMMITTEE_SIZE)
    proposer_reward = (_u64(participant_reward * proposer_weight)
                       // (weight_denominator - proposer_weight))

    _apply_rewards(spec, state, rows, bits, participant_reward, proposer_reward)


def _apply_rewards(spec, state, rows, bits, participant_reward: int,
                   proposer_reward: int) -> None:
    """Net-delta equivalent of the spec's per-seat reward walk: each seat
    contributes +participant_reward (bit set) or -participant_reward (bit
    clear) to its validator, and each set bit adds proposer_reward to the
    proposer.  Equivalence to the sequential fold needs no balance to
    floor at zero or overflow mid-walk; both are bounded conservatively
    (credits-only upper prefix, debits-only lower prefix) and violations
    replay through the literal spec."""
    uniq, inv = np.unique(rows, return_inverse=True)
    credit = np.zeros(len(uniq), dtype=np.uint64)
    np.add.at(credit, inv[bits], np.uint64(participant_reward))
    debit = np.zeros(len(uniq), dtype=np.uint64)
    np.add.at(debit, inv[~bits], np.uint64(participant_reward))

    deltas = {int(i): (int(c), int(d))
              for i, c, d in zip(uniq, credit, debit)}
    n_participants = int(np.count_nonzero(bits))
    if n_participants and proposer_reward:
        proposer = int(beacon_proposer_index(spec, state))
        c, d = deltas.get(proposer, (0, 0))
        deltas[proposer] = (c + n_participants * proposer_reward, d)

    balances = state.balances
    for index, (c, d) in deltas.items():
        _SITE_REWARDS()  # mid-walk: some balances written, some pending
        b = int(balances[index])
        if b + c >= 1 << 64:
            raise FastPathViolation("sync reward overflows a balance")
        if d > b:
            raise FastPathViolation("sync penalty floors a balance")
        balances[index] = spec.Gwei(b + c - d)
