"""Resident validator-state columns (ROADMAP item 3; completed ISSUE 10).

The altair fast path and the epoch kernels both consume whole-registry
columns (participation flags, effective balances, balances) that the SSZ
tree only hands out one chunk walk at a time: before this module, EVERY
block's attestation scatter re-unpacked both participation columns from
the tree (``bulk.packed_uint8_to_numpy`` — a ~n/32-chunk walk each), and
every epoch-transition phase re-unpacked them again, so a 32-block epoch
paid ~70 full-column tree walks for data that changed only incrementally.
ISSUE 10 finishes the arc: the balance column rides the same store (with
an identity fast path for freshly flushed, still-unhashed subtrees), and
registry/balance-derived *device* inputs of the epoch kernels upload once
per column version through ``device_buffer`` instead of re-staging per
jit call.

This module keeps those columns *resident*:

* **host residency** — a content-addressed store keyed by the column's
  memoized SSZ tree root.  A flush registers the freshly written array
  under the column's new root, so the next reader (the following block's
  mirror read, or any epoch-transition phase) gets the SAME array back as
  a dict probe instead of a tree walk.  Root keying makes staleness
  impossible: any tree write the store did not see (a deposit appending a
  participation entry, the literal replay rewriting a column) produces a
  new root and the next read rebuilds honestly.  Cached arrays are
  READONLY; mutating readers take ``staged_view`` (an explicit copy — the
  numpy mirror demoted to a staged view per the HD01 contract).
* **device residency** — ``device_column`` uploads a column to the JAX
  backend once per root (partitioned over the ``parallel/mesh.py`` axis
  when the backend has multiple devices, replicated otherwise) and serves
  the same buffer to every later device consumer of that version — the
  altair epoch kernel reads participation flags without re-staging them,
  the way ``ops/merkle_resident.py`` keeps balance leaves resident for
  the fused root reduction.

Insertions ride the block cache transaction (``staging.note_insert``)
like every other fast-path memo: a failed block's flush is popped with
the rollback, so the store can never serve a column version whose block
was rolled back (chaos-pinned via the engine's mirror probes).
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from consensus_specs_tpu import telemetry

from . import staging

# column tree root -> {"host": readonly ndarray, "device": jax array|None}
_COLUMN_STORE: Dict[bytes, dict] = {}
_COLUMN_STORE_MAX = 8

# balances root -> readonly int64 ndarray (ISSUE 10: the balance half of
# the residency arc — every epoch phase that read the packed vector paid
# a ~n/4-chunk tree walk per phase before this)
_BALANCE_STORE: Dict[bytes, "np.ndarray"] = {}
_BALANCE_STORE_MAX = 4

# identity fast path for freshly flushed balances: (backing node, col).
# A flush leaves the subtree unhashed — keying by root there would FORCE
# the very re-merkleization the lazy write avoids — but the backing node
# object is identity-stable until the next mutation, so the next reader
# (the following epoch phase, or slot_roots' resident upload) matches on
# identity and skips both the hash and the walk.  A rolled-back block
# orphans the node; the identity probe then just misses, honestly.
_BALANCE_PENDING = None

# (content root, tag, ...) -> device array: once-per-version uploads of
# registry/balance-derived kernel inputs (effective balance, eligibility,
# active/slashed masks), replacing the per-epoch-kernel-call re-staging
# ROADMAP item 3 named.  FIFO-bounded; root keying makes stale service
# impossible, exactly like the host stores.
_DEVICE_BUFFERS: Dict[tuple, object] = {}
_DEVICE_BUFFERS_MAX = 24

# residency effectiveness (ISSUE 9/10): a hit is a dict probe, a miss is
# a tree walk (host) or an upload (device) — the ratios are the module's
# whole value story
stats = {"hits": 0, "misses": 0,
         "balance_hits": 0, "balance_misses": 0,
         "device_hits": 0, "device_misses": 0}


def reset_stats() -> None:
    for k in stats:
        stats[k] = 0


def _bounded_put(cache: dict, cap: int, key, value):
    """THE FIFO store insert (evict oldest, insert, record with the
    block's cache transaction) — one definition for every bounded store
    here, so the eviction/transaction interplay can't drift per store."""
    if len(cache) >= cap:
        cache.pop(next(iter(cache)))
    cache[key] = value
    staging.note_insert(cache, key)
    return value


def _store_put(root: bytes, host: np.ndarray) -> dict:
    return _bounded_put(_COLUMN_STORE, _COLUMN_STORE_MAX, root,
                        {"host": host, "device": None})


def _participation_view(state, current: bool):
    return (state.current_epoch_participation if current
            else state.previous_epoch_participation)


def _entry_for(view) -> dict:
    """The store entry of a packed-uint8 column view, keyed by its
    memoized tree root (cheap after any state-root computation; a fresh
    write pays one subtree hash that the block's own state-root check
    would have paid anyway)."""
    from consensus_specs_tpu.ssz import bulk

    root = bytes(view.hash_tree_root())
    entry = _COLUMN_STORE.get(root)
    if entry is None:
        stats["misses"] += 1
        host = bulk.packed_uint8_to_numpy(view)
        host.setflags(write=False)
        entry = _store_put(root, host)
    else:
        stats["hits"] += 1
    return entry


def participation_column(state, current: bool) -> np.ndarray:
    """READONLY resident numpy column of one epoch's participation flags.
    Mutating consumers must copy (``staged_view``); read-only consumers
    (the epoch phases) use it directly."""
    return _entry_for(_participation_view(state, current))["host"]


def staged_view(state, current: bool) -> np.ndarray:
    """A mutable staged view (copy) of one participation column — the
    engine's per-block scatter target.  Hand it back via ``flush`` so the
    next reader hits residency instead of re-walking the tree."""
    return participation_column(state, current).copy()


def flush(state, current: bool, col: np.ndarray) -> None:
    """Write a staged column back into the state tree as ONE packed write
    and register the array under the column's new root — the resident
    half of the mirror-flush contract."""
    from consensus_specs_tpu.ssz import bulk

    view = _participation_view(state, current)
    bulk.set_packed_uint8_from_numpy(view, col)
    col.setflags(write=False)
    _store_put(bytes(view.hash_tree_root()), col)


# -- resident balance column (ISSUE 10) ---------------------------------------


def balance_column(state) -> np.ndarray:
    """READONLY resident int64 numpy column of ``state.balances``.

    Lookup order: the identity fast path (a column this module just
    flushed, subtree still unhashed), then the root-keyed store (cheap
    once any state-root computation memoized the subtree), then an
    honest tree walk.  The walk result is registered only when the root
    is already memoized — keying an unhashed subtree would force a
    re-merkleization the lazy write exists to avoid.  Mutating consumers
    take ``staged_balances`` (copy) and hand it back via
    ``flush_balances`` (HD01 contract)."""
    from consensus_specs_tpu.ssz import bulk

    view = state.balances
    backing = view.get_backing()
    pend = _BALANCE_PENDING
    if pend is not None and pend[0] is backing:
        stats["balance_hits"] += 1
        return pend[1]
    root = backing._root  # memoized by any prior root computation
    if root is not None:
        hit = _BALANCE_STORE.get(bytes(root))
        if hit is not None:
            stats["balance_hits"] += 1
            return hit
    stats["balance_misses"] += 1
    col = bulk.packed_uint64_to_numpy(view)
    col.setflags(write=False)
    if root is not None:
        _bounded_put(_BALANCE_STORE, _BALANCE_STORE_MAX, bytes(root), col)
    return col


def staged_balances(state) -> np.ndarray:
    """A mutable staged view (copy) of the resident balance column — the
    epoch phases' write target.  Hand it back via ``flush_balances``."""
    return balance_column(state).copy()


def flush_balances(state, col: np.ndarray) -> None:
    """Write a staged balance column back into the state tree as ONE
    packed rebuild and stage it on the identity fast path, so the next
    reader (the following epoch phase, the resident-merkle upload) gets
    the SAME array back without hashing or re-walking the subtree."""
    from consensus_specs_tpu.ssz import bulk

    global _BALANCE_PENDING
    bulk.set_packed_uint64_from_numpy(state.balances, col)
    if col.dtype != np.int64:
        col = col.astype(np.int64)
    col.setflags(write=False)
    _BALANCE_PENDING = (state.balances.get_backing(), col)


# -- resident device buffers (ISSUE 10) ----------------------------------------


def device_buffer(key: tuple, build_host, device=None):
    """The device twin of the host stores: a content-keyed once-per-
    version upload.  ``key`` must lead with the owning view's memoized
    tree root (staleness-impossible, like every store here) and bind
    every derivation parameter (tag, epoch, padding); the upload target
    is bound here.  ``build_host()`` produces the host array only on a
    miss — by the caller contract its output is pure in ``key`` (the
    RootKeyedCache build-function shape), so it is not key material.
    ``device`` pins the upload target (the epoch kernels' backend
    choice); None takes the mesh-aware default."""
    key = key + (str(device),)
    # build_host is the miss-path constructor, pure in key (caller
    # contract above) — not key material
    hit = _DEVICE_BUFFERS.get(key)  # noqa: CC02
    if hit is not None:
        stats["device_hits"] += 1
        return hit
    stats["device_misses"] += 1
    host = build_host()
    if device is not None:
        import jax

        buf = jax.device_put(host, device)
    else:
        buf = _device_put(host)
    return _bounded_put(_DEVICE_BUFFERS, _DEVICE_BUFFERS_MAX, key, buf)


def device_column(state, current: bool):
    """The resident column as a device array, uploaded once per column
    version and shared by every later consumer of that root (the altair
    epoch kernel's participation input)."""
    entry = _entry_for(_participation_view(state, current))
    if entry["device"] is None:
        entry["device"] = _device_put(entry["host"])
    return entry["device"]


def _device_put(host: np.ndarray):
    """Upload a column, partitioned over the mesh's validator axis when
    the backend has more than one device (and the length divides evenly —
    ragged columns replicate; the epoch kernels reduce over the full axis
    either way), single-device otherwise."""
    import jax

    devices = jax.devices()
    if len(devices) > 1 and len(host) % len(devices) == 0:
        from jax.sharding import NamedSharding, PartitionSpec

        from consensus_specs_tpu.parallel.mesh import default_mesh

        sharding = NamedSharding(default_mesh(), PartitionSpec("v"))
        return jax.device_put(host, sharding)
    return jax.device_put(host, devices[0])


def reset_caches() -> None:
    """Drop every resident column and device buffer (bench cold-start
    control and test isolation)."""
    global _BALANCE_PENDING
    _COLUMN_STORE.clear()
    _BALANCE_STORE.clear()
    _BALANCE_PENDING = None
    _DEVICE_BUFFERS.clear()
    reset_stats()


def _telemetry_provider() -> dict:
    return {**stats,
            "size": len(_COLUMN_STORE), "cap": _COLUMN_STORE_MAX,
            "balance_size": len(_BALANCE_STORE),
            "balance_cap": _BALANCE_STORE_MAX,
            "device_size": len(_DEVICE_BUFFERS),
            "device_cap": _DEVICE_BUFFERS_MAX}


telemetry.register_provider("stf.columns", _telemetry_provider, replace=True)
