"""Resident validator-state columns (ROADMAP item 3).

The altair fast path and the epoch kernels both consume whole-registry
columns (participation flags, effective balances) that the SSZ tree only
hands out one chunk walk at a time: before this module, EVERY block's
attestation scatter re-unpacked both participation columns from the tree
(``bulk.packed_uint8_to_numpy`` — a ~n/32-chunk walk each), and every
epoch-transition phase re-unpacked them again, so a 32-block epoch paid
~70 full-column tree walks for data that changed only incrementally.

This module keeps those columns *resident*:

* **host residency** — a content-addressed store keyed by the column's
  memoized SSZ tree root.  A flush registers the freshly written array
  under the column's new root, so the next reader (the following block's
  mirror read, or any epoch-transition phase) gets the SAME array back as
  a dict probe instead of a tree walk.  Root keying makes staleness
  impossible: any tree write the store did not see (a deposit appending a
  participation entry, the literal replay rewriting a column) produces a
  new root and the next read rebuilds honestly.  Cached arrays are
  READONLY; mutating readers take ``staged_view`` (an explicit copy — the
  numpy mirror demoted to a staged view per the HD01 contract).
* **device residency** — ``device_column`` uploads a column to the JAX
  backend once per root (partitioned over the ``parallel/mesh.py`` axis
  when the backend has multiple devices, replicated otherwise) and serves
  the same buffer to every later device consumer of that version — the
  altair epoch kernel reads participation flags without re-staging them,
  the way ``ops/merkle_resident.py`` keeps balance leaves resident for
  the fused root reduction.

Insertions ride the block cache transaction (``staging.note_insert``)
like every other fast-path memo: a failed block's flush is popped with
the rollback, so the store can never serve a column version whose block
was rolled back (chaos-pinned via the engine's mirror probes).
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from consensus_specs_tpu import telemetry

from . import staging

# column tree root -> {"host": readonly ndarray, "device": jax array|None}
_COLUMN_STORE: Dict[bytes, dict] = {}
_COLUMN_STORE_MAX = 8

# residency effectiveness (ISSUE 9): a hit is a dict probe, a miss is a
# ~n/32-chunk tree walk — the ratio is the module's whole value story
stats = {"hits": 0, "misses": 0}


def reset_stats() -> None:
    for k in stats:
        stats[k] = 0


def _store_put(root: bytes, host: np.ndarray) -> dict:
    if len(_COLUMN_STORE) >= _COLUMN_STORE_MAX:
        _COLUMN_STORE.pop(next(iter(_COLUMN_STORE)))
    entry = _COLUMN_STORE[root] = {"host": host, "device": None}
    staging.note_insert(_COLUMN_STORE, root)
    return entry


def _participation_view(state, current: bool):
    return (state.current_epoch_participation if current
            else state.previous_epoch_participation)


def _entry_for(view) -> dict:
    """The store entry of a packed-uint8 column view, keyed by its
    memoized tree root (cheap after any state-root computation; a fresh
    write pays one subtree hash that the block's own state-root check
    would have paid anyway)."""
    from consensus_specs_tpu.ssz import bulk

    root = bytes(view.hash_tree_root())
    entry = _COLUMN_STORE.get(root)
    if entry is None:
        stats["misses"] += 1
        host = bulk.packed_uint8_to_numpy(view)
        host.setflags(write=False)
        entry = _store_put(root, host)
    else:
        stats["hits"] += 1
    return entry


def participation_column(state, current: bool) -> np.ndarray:
    """READONLY resident numpy column of one epoch's participation flags.
    Mutating consumers must copy (``staged_view``); read-only consumers
    (the epoch phases) use it directly."""
    return _entry_for(_participation_view(state, current))["host"]


def staged_view(state, current: bool) -> np.ndarray:
    """A mutable staged view (copy) of one participation column — the
    engine's per-block scatter target.  Hand it back via ``flush`` so the
    next reader hits residency instead of re-walking the tree."""
    return participation_column(state, current).copy()


def flush(state, current: bool, col: np.ndarray) -> None:
    """Write a staged column back into the state tree as ONE packed write
    and register the array under the column's new root — the resident
    half of the mirror-flush contract."""
    from consensus_specs_tpu.ssz import bulk

    view = _participation_view(state, current)
    bulk.set_packed_uint8_from_numpy(view, col)
    col.setflags(write=False)
    _store_put(bytes(view.hash_tree_root()), col)


def device_column(state, current: bool):
    """The resident column as a device array, uploaded once per column
    version and shared by every later consumer of that root (the altair
    epoch kernel's participation input)."""
    entry = _entry_for(_participation_view(state, current))
    if entry["device"] is None:
        entry["device"] = _device_put(entry["host"])
    return entry["device"]


def _device_put(host: np.ndarray):
    """Upload a column, partitioned over the mesh's validator axis when
    the backend has more than one device (and the length divides evenly —
    ragged columns replicate; the epoch kernels reduce over the full axis
    either way), single-device otherwise."""
    import jax

    devices = jax.devices()
    if len(devices) > 1 and len(host) % len(devices) == 0:
        from jax.sharding import NamedSharding, PartitionSpec

        from consensus_specs_tpu.parallel.mesh import default_mesh

        sharding = NamedSharding(default_mesh(), PartitionSpec("v"))
        return jax.device_put(host, sharding)
    return jax.device_put(host, devices[0])


def reset_caches() -> None:
    """Drop every resident column (bench cold-start control and test
    isolation)."""
    _COLUMN_STORE.clear()
    reset_stats()


def _telemetry_provider() -> dict:
    return {"hits": stats["hits"], "misses": stats["misses"],
            "size": len(_COLUMN_STORE), "cap": _COLUMN_STORE_MAX}


telemetry.register_provider("stf.columns", _telemetry_provider, replace=True)
