"""Per-block signature settlement for the batched block-transition engine.

Every signature a block carries — proposer, RANDAO reveal, and each
aggregate attestation — asserts one pairing equation; the engine collects
them all and settles the block in ONE ``BatchFastAggregateVerify``
multi-pairing (crypto/bls/native.py: one random-linear-combination
pairing product, one shared final exponentiation).  Two accelerations on
top of the facade's deferred scope (crypto/bls/__init__.py):

* **preflattened members** — entries carry the member pubkeys as rows of
  the registry's affine-coordinate matrix (``stf/attestations.py``), so
  the native call skips the per-member ``bytes()`` + cache-dict walk the
  compressed path pays (~0.1 s/block at mainnet scale);
* **verified-triple memo** — verification is a pure function of
  ``(members, message, signature)``, so a triple that already settled in
  an earlier batch is dropped from later ones.  Mainnet blocks re-carry
  the previous slots' aggregates (the bench corpus includes every
  attestation in two consecutive blocks; gossip re-delivery does the same
  to a live node), making this worth ~2x pairing work across an epoch.

On batch failure ``first_invalid`` bisects with sub-batch calls —
O(log n) multi-pairings — to the leftmost failing entry; the engine then
rolls the block back and replays it through the literal spec path so the
offending signature raises exactly the spec's exception at exactly the
spec's point in processing (stf/engine.py).
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence, Tuple

from consensus_specs_tpu import faults, telemetry, tracing
from consensus_specs_tpu.telemetry import recorder, timeline

from . import staging

# (count, flat affine members, message, signature): one pairing equation
SigEntry = Tuple[int, bytes, bytes, bytes]

_VERIFIED_MEMO: dict = {}
_VERIFIED_MEMO_MAX = 1 << 16

# fault probes (tests/chaos/): the native multi-pairing call, the
# MSM-folded interior it dispatches (probed separately so a crashed MSM is
# proven to ride the same degradation ladder as any other native death),
# the bisection walk, and the memo commit are the settlement path's
# fragile seams — each must fail into the engine's replay contract, never
# into a poisoned memo
_SITE_NATIVE_CALL = faults.site("stf.verify.native_call")
_SITE_MSM = faults.site("stf.verify.msm")
_SITE_BISECT = faults.site("stf.verify.bisect")
_SITE_MEMO_COMMIT = faults.site("stf.verify.memo_commit")

# degradation ladder: a native call that DIES (OSError/ctypes failure,
# not a clean False) marks the backend degraded — this batch settles
# through the pure-Python oracle, and the engine gates every later block
# to the literal replay until an operator resets
_NATIVE_DEGRADED = False
_DEGRADED_WARNED = False

stats = {
    "batches": 0,
    "entries": 0,
    "memo_hits": 0,
    "speculative_hits": 0,
    "bisections": 0,
    "memo_evictions": 0,
    "native_degraded": 0,
    "memo_cap": _VERIFIED_MEMO_MAX,
    # sig_verify_s split into attributable sub-phases: the native batch
    # call reports its interior (message hashing, the dual MSM folds, the
    # chunked Miller product + shared final exp) and the marshal covers
    # both C-side deserialization and the Python buffer packing, so a
    # pairing regression names its component instead of moving one opaque
    # number (ISSUE 7 satellite)
    "hash_to_g2_s": 0.0,
    "msm_s": 0.0,
    "miller_s": 0.0,
    "marshal_s": 0.0,
}


def reset_stats() -> None:
    """Zero the settlement counters (``memo_cap`` is a constant readout,
    not a counter — it survives the reset; so does the degraded flag,
    which is operational state, reset via ``reset_degraded``)."""
    for k in stats:
        stats[k] = 0.0 if isinstance(stats[k], float) else 0
    stats["memo_cap"] = _VERIFIED_MEMO_MAX
    stats["native_degraded"] = int(_NATIVE_DEGRADED)


def native_degraded() -> bool:
    """True once a native batch call has crashed this process: the engine
    must stop routing blocks through the native fast path."""
    return _NATIVE_DEGRADED


def reset_degraded() -> None:
    """Clear the degraded mark (tests; an operator restoring the backend)."""
    global _NATIVE_DEGRADED, _DEGRADED_WARNED
    _NATIVE_DEGRADED = False
    _DEGRADED_WARNED = False
    stats["native_degraded"] = 0


def _degrade(exc: BaseException) -> None:
    """One-way degradation mark with a one-time traced warning: the run
    survives (pure-Python settles the in-flight batch, the engine falls
    back to literal replays) instead of dying mid-block."""
    global _NATIVE_DEGRADED, _DEGRADED_WARNED
    _NATIVE_DEGRADED = True
    stats["native_degraded"] = 1
    tracing.count("stf.native_degraded")
    recorder.record("native_degraded", error=f"{type(exc).__name__}: {exc}"[:200])
    if not _DEGRADED_WARNED:
        _DEGRADED_WARNED = True
        import warnings

        warnings.warn(
            f"native BLS batch backend crashed ({type(exc).__name__}: {exc}); "
            "degraded to pure-Python verification — fast path disabled until "
            "verify.reset_degraded()", RuntimeWarning)


def triple_key(members_id: bytes, message: bytes, signature: bytes) -> bytes:
    """Content address of one pairing equation.  ``members_id`` must bind
    the member set exactly (the engine uses registry root + the sorted
    attester-index buffer, or the raw pubkey for single-signer checks)."""
    return hashlib.sha256(members_id + message + signature).digest()


def is_verified(key: bytes) -> bool:
    """True when this triple already settled in an earlier successful
    batch — the caller may skip building (and verifying) the entry."""
    if key in _VERIFIED_MEMO:
        stats["memo_hits"] += 1
        return True
    return False


def note_speculative_hit() -> None:
    """Count a dedup hit against a PENDING (dispatched, unverdicted)
    batch's key set — the pipeline's speculative twin of a memo hit.
    Sound because the consuming block only survives if the providing
    block's batch verifies and commits: any failure drains the pipeline
    and replays both blocks literally (stf/engine.py).  Counted into
    ``memo_hits`` so the dedup ratio keeps one meaning pipeline ON or
    OFF, and separately so the overlap story is attributable."""
    stats["memo_hits"] += 1
    stats["speculative_hits"] += 1


def _verify_batch(entries: Sequence[SigEntry], seed: bytes = None) -> bool:
    """One RLC multi-pairing over ``entries`` (True iff every item holds).

    Containment: an ``InjectedFault`` (generic mid-phase error) propagates
    into the engine's replay contract; any OTHER exception out of the
    native call is a backend crash — the process marks itself degraded and
    this batch settles through the pure-Python oracle instead of dying."""
    if not entries:
        return True
    if _NATIVE_DEGRADED:
        # never re-enter a crashed backend — the bisection calls land
        # here too, so a mid-block crash stops touching native at once
        return _verify_batch_python(entries)
    from consensus_specs_tpu.crypto.bls import native

    counts, flats, msgs, sigs = zip(*entries)
    try:
        _SITE_NATIVE_CALL()
        # the MSM-folded interior is probed as its own seam: a crash here
        # is indistinguishable from the bucketed fold dying inside the
        # native call, and must degrade through the same ladder
        _SITE_MSM()
        return native.BatchFastAggregateVerifyFlat(
            counts, b"".join(flats), msgs, sigs, seed=seed, stats=stats)
    except faults.InjectedFault:
        raise
    except Exception as exc:
        _degrade(exc)
        return _verify_batch_python(entries)


def _verify_batch_python(entries: Sequence[SigEntry]) -> bool:
    """Pure-Python settlement of a batch (degraded mode): each entry's
    affine members compress back to ZCash form and verify through the
    oracle ``FastAggregateVerify`` — slow, but the node stays alive and
    byte-exact while the native backend is gone."""
    from consensus_specs_tpu.crypto.bls import ciphersuite
    from consensus_specs_tpu.crypto.bls.curve import _HALF_P

    for count, flat, message, signature in entries:
        pks = []
        for i in range(count):
            xy = flat[96 * i: 96 * (i + 1)]
            x, y = int.from_bytes(xy[:48], "big"), int.from_bytes(xy[48:], "big")
            raw = bytearray(x.to_bytes(48, "big"))
            raw[0] |= 0x80 | (0x20 if y > _HALF_P else 0)
            pks.append(bytes(raw))
        # noqa-justified: this IS the no-native fallback — there is no
        # batch backend left to route through while degraded
        if not ciphersuite.FastAggregateVerify(pks, message, signature):  # noqa: ST01
            return False
    return True


def first_invalid(entries: Sequence[SigEntry], seed: bytes = None) -> Optional[int]:
    """Index of the FIRST failing entry, or None if the batch verifies.

    Mirrors the facade's deferred-scope bisection
    (crypto/bls/__init__.py:_first_invalid): O(log n) sub-batch
    multi-pairings, always landing on the leftmost failure so the engine's
    spec replay trips on the same signature the sequential path would
    have.

    Threading: with the overlapped pipeline ON this runs on the single
    ``stf/pipeline.py`` dispatch thread — entries are materialized (pure
    data), and the batch/entry/bisection/timing counters it touches have
    that thread as their only writer (memo hits/evictions stay
    host-side), so the stats dict needs no lock."""
    stats["batches"] += 1
    stats["entries"] += len(entries)
    if _verify_batch(entries, seed=seed):
        return None
    stats["bisections"] += 1
    lo, hi = 0, len(entries)
    # invariant: entries[:lo] all verify; at least one failure in [lo, hi)
    while hi - lo > 1:
        _SITE_BISECT()
        mid = (lo + hi) // 2
        if _verify_batch(entries[lo:mid], seed=seed):
            lo = mid
        else:
            hi = mid
    return lo


def settle(entries: List[SigEntry], keys: List[bytes],
           seed: bytes = None, link=None) -> Optional[int]:
    """Settle a block's collected signature checks; None on success, else
    the index (in call order) of the first invalid entry.

    The engine drops already-verified triples before building entries
    (``is_verified``); on success the settled triples join the memo —
    through the block's cache transaction when one is active, so the
    commit lands only after the WHOLE block settles (including the
    post-state root check), never on the strength of a block that then
    rolled back.  ``link`` is the block's timeline causality id: the
    serial path's native multi-pairing gets the same ``native/verify``
    span the pipelined worker emits, so traces read identically pipeline
    ON or OFF."""
    if not entries:
        return None
    tracing.count("stf.sig_batch")
    tracing.count("stf.sig_batch.entries", len(entries))
    with timeline.span("native/verify", link=link, entries=len(entries)):
        bad = first_invalid(entries, seed=seed)
    if bad is not None:
        return bad
    staging.defer(_commit_keys, keys)
    return None


def stage_commit(keys: List[bytes]) -> None:
    """Stage a batch's triple keys for settlement WITHOUT settling the
    batch — the overlapped pipeline's half of ``settle``: the engine
    dispatches the multi-pairing asynchronously (stf/pipeline.py) and
    stages the commit through the block's open cache transaction, so the
    keys join the memo only at ``commit_block`` — after the verdict — and
    a rolled-back speculation drops them with its transaction."""
    if keys:
        staging.defer(_commit_keys, keys)


def _commit_keys(keys: Sequence[bytes]) -> None:
    """Insert a settled block's triples (the deferred half of ``settle``;
    runs at block commit, or immediately when no transaction is active)."""
    _SITE_MEMO_COMMIT()
    for k in keys:
        _memo_put(k)


def _memo_put(key: bytes) -> None:
    """Insert one settled triple, bounding the memo at
    ``_VERIFIED_MEMO_MAX`` with FIFO eviction (dicts iterate in insertion
    order) — a long multi-epoch replay sheds its oldest triples instead of
    growing without limit, and the blocks re-carrying recent aggregates
    still hit.  Evictions are counted in ``stats`` next to the cap."""
    if key in _VERIFIED_MEMO:
        return
    while len(_VERIFIED_MEMO) >= _VERIFIED_MEMO_MAX:
        _VERIFIED_MEMO.pop(next(iter(_VERIFIED_MEMO)))
        stats["memo_evictions"] += 1
    _VERIFIED_MEMO[key] = True


def reset_memo() -> None:
    """Drop the verified-triple memo (tests; the memo is content-addressed
    so staleness is impossible, but deterministic timing runs want a cold
    start)."""
    _VERIFIED_MEMO.clear()


def _telemetry_provider() -> dict:
    """Settlement counters + the memo's live fill (the stats dict already
    carries the cap; size rides alongside so the soak harness can assert
    the bound holds)."""
    return {**stats, "memo_size": len(_VERIFIED_MEMO)}


telemetry.register_provider("stf.verify", _telemetry_provider, replace=True)
