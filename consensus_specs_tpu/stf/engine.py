"""Batched block-transition engine: ``apply_signed_blocks``.

Replays a sequence of signed blocks through the spec state transition with
three fused optimizations (docs/architecture.md, "The block path"):

1. **one BLS batch per block** — the proposer signature, the RANDAO
   reveal, and every aggregate attestation settle in a single
   ``BatchFastAggregateVerify`` multi-pairing (stf/verify.py), with
   cross-block dedup of already-verified triples;
2. **vectorized attestation application** — committees and attester sets
   resolve off the cached whole-epoch shuffle permutation as numpy
   gathers, participation counts reduce through ``ops/segment.py``
   (stf/attestations.py), and only the spec-mandated tree writes
   (pending-attestation appends) touch the state;
3. **cheap per-slot roots** — ``process_slots`` runs with dirty packed
   balance subtrees routed through the resident merkle path
   (stf/slot_roots.py).

Failure contract — differential-exact by construction: the fast path is
optimistic; on ANY trouble (a structural check, a failed signature batch,
a fork or backend the fast path does not cover) the block's pre-state is
restored from its O(1) backing snapshot and the block replays through the
literal ``spec.state_transition``, which raises the spec's exact exception
type/message at the spec's exact point and leaves the state exactly as
poisoned as the sequential path would have.  Valid blocks therefore land
byte-identical post-states, and invalid blocks are indistinguishable from
the spec path (pinned by
tests/spec/phase0/sanity/test_stf_engine_differential.py).

Cross-block overlapped pipeline (ISSUE 10): with ``CSTPU_PIPELINE`` on
(the default), a multi-block call overlaps block N's native signature
batch with the next block(s)' host phases — the batch is dispatched
through ``stf/pipeline.py`` and its verdict awaited only after
``pipeline.window_depth()`` successors' host work (default 2; the extra
block of slack absorbs per-block jitter).  The rollback
contract makes the speculation safe by construction: each block's cache
transaction stays open (and its verified-triple commit deferred) until
its verdict lands, so a failed verdict, a breaker trip, native
degradation, or any fault in the window drains the pipeline — the
successor's inserts and state writes unwind first (LIFO), the failing
block restores its own backing snapshot, and the literal replay raises
the spec's exception with the existing bisection naming the original
entry.  Results are byte-identical pipeline ON or OFF (the ON/OFF
exception-parity battery in both differential suites pins it).
"""
from __future__ import annotations

import hashlib
import sys
import time

from consensus_specs_tpu import faults, telemetry, tracing
from consensus_specs_tpu.telemetry import histogram, recorder, timeline

from . import columns, pipeline, slot_roots, staging, sync, verify
from .attestations import (
    FastPathViolation,
    affine_rows,
    beacon_proposer_index,
    resolve_block_attestations,
)

# the fork families the fast path covers: phase0's pending-attestation
# shape, and the altair lineage (participation flags + sync aggregates;
# bellatrix adds the execution payload, run literally in the snapshot
# region).  capella+ (withdrawals, bls_to_execution_changes) replay
# through the literal spec until the engine grows those operations.
FAST_FORKS = ("phase0", "altair", "bellatrix")

# circuit breaker: after BREAKER_THRESHOLD CONSECUTIVE unexpected
# fast-path errors (not FastPathViolations — those are the contract
# working) the engine stops attempting the fast path and replays every
# block literally; while open, every BREAKER_PROBE_INTERVAL-th block is
# a recovery probe (i.e. INTERVAL-1 literal replays between probes), and
# a successful probe closes the breaker.  Failure containment for a systematically broken fast path
# (poisoned build, sick native library): correctness never depended on
# the fast path, but paying a doomed attempt + rollback per block would
# double the work exactly when the node is least healthy.
BREAKER_THRESHOLD = 3
BREAKER_PROBE_INTERVAL = 8

_breaker = {"consecutive_errors": 0, "open": False, "since_skipped": 0}

# fault probes (tests/chaos/): each fast-path phase fails into the
# rollback contract; the gate and the post-settlement cache commit are
# probed as well so degraded-availability and torn-commit scenarios are
# tested paths
_SITE_HEADER = faults.site("stf.engine.header")
_SITE_RANDAO = faults.site("stf.engine.randao")
_SITE_OPERATIONS = faults.site("stf.engine.operations")
_SITE_STATE_ROOT = faults.site("stf.engine.state_root")
_SITE_NATIVE_GATE = faults.site("stf.engine.native_gate")
_SITE_CACHE_COMMIT = faults.site("stf.engine.cache_commit")
_SITE_MIRROR_READ = faults.site("stf.engine.mirror_read")
_SITE_MIRROR_FLUSH = faults.site("stf.engine.mirror_flush")

stats = {
    "fast_blocks": 0,
    "replayed_blocks": 0,
    "fast_path_errors": 0,
    "breaker_trips": 0,
    "breaker_probes": 0,
    "breaker_skipped": 0,
    "breaker_state": "closed",
    "replay_reasons": {},
    "sig_verify_s": 0.0,
    "attestation_apply_s": 0.0,
    # attestation_apply_s attributed (ISSUE 8): plan resolution (memo
    # probes + cold committee gathers), the state-application loop, and
    # the participation mirror flush — a regression names its phase
    # instead of moving one opaque number
    "resolve_s": 0.0,
    "apply_s": 0.0,
    "mirror_flush_s": 0.0,
    "sync_apply_s": 0.0,
    "slot_roots_s": 0.0,
    "other_s": 0.0,
}


def reset_stats() -> None:
    """Zero ALL engine counters — the per-block phase/fallback dict here,
    the signature-settlement counters in stf/verify.py, and the pipeline's
    overlap accounting (one call, so bench rows can't accidentally report
    cumulative halves) — and re-arm the circuit breaker (counters and
    live state reset together, so a bench leg can't inherit the previous
    leg's open breaker)."""
    for k in stats:
        if isinstance(stats[k], float):
            stats[k] = 0.0
        elif isinstance(stats[k], dict):
            stats[k] = {}
        elif isinstance(stats[k], int):
            stats[k] = 0
    _breaker.update(consecutive_errors=0, open=False, since_skipped=0)
    stats["breaker_state"] = "closed"
    verify.reset_stats()
    pipeline.reset_stats()
    # per-phase latency distributions reset with the counters they
    # attribute, so a bench pass's p50/p99 describe exactly that pass
    histogram.reset()


def _count_reason(reason: str) -> None:
    reasons = stats["replay_reasons"]
    reasons[reason] = reasons.get(reason, 0) + 1


def _native_available() -> bool:
    try:
        from consensus_specs_tpu.crypto.bls import native  # noqa: F401
        return True
    except ImportError:
        return False


def _fast_path_ready(spec) -> bool:
    """The gate: covered fork family, native backend importable AND not
    degraded (a crashed backend demotes every block to the literal
    replay — see stf/verify._degrade)."""
    ok = (getattr(spec, "fork", None) in FAST_FORKS
          and _native_available() and not verify.native_degraded())
    return bool(_SITE_NATIVE_GATE(ok))


# -- circuit breaker -----------------------------------------------------------

def _breaker_note_success() -> None:
    _breaker["consecutive_errors"] = 0
    if _breaker["open"]:
        _breaker["open"] = False
        _breaker["since_skipped"] = 0
        stats["breaker_state"] = "closed"
        tracing.count("stf.breaker_closed")
        recorder.record("breaker_close")


def _breaker_note_error() -> None:
    _breaker["consecutive_errors"] += 1
    if _breaker["open"]:
        # a failed recovery probe: stay open, restart the skip countdown
        _breaker["since_skipped"] = 0
        recorder.record("breaker_probe_failed")
        return
    if _breaker["consecutive_errors"] >= BREAKER_THRESHOLD:
        _breaker["open"] = True
        _breaker["since_skipped"] = 0
        stats["breaker_trips"] += 1
        stats["breaker_state"] = "open"
        tracing.count("stf.breaker_tripped")
        recorder.record("breaker_open",
                        consecutive_errors=_breaker["consecutive_errors"])


def _breaker_allows_attempt() -> bool:
    """False while the breaker is open and this block is not a probe."""
    if not _breaker["open"]:
        return True
    _breaker["since_skipped"] += 1
    if _breaker["since_skipped"] % BREAKER_PROBE_INTERVAL == 0:
        stats["breaker_probes"] += 1
        tracing.count("stf.breaker_probe")
        recorder.record("breaker_probe")
        return True
    return False


def apply_signed_blocks(spec, state, signed_blocks, validate_result: bool = True):
    """Apply ``signed_blocks`` to ``state`` in place, semantically
    identical to ``for sb in signed_blocks: spec.state_transition(state,
    sb, validate_result)`` — same post-states on success, same exception
    and partial state on the first invalid block.

    With the overlapped pipeline enabled (``CSTPU_PIPELINE`` != 0, the
    default) and no cache transaction already open (a re-entrant call
    joins the caller's block and must stay synchronous), blocks run
    through the speculative cross-block path; otherwise the serial
    one-block-at-a-time path.  Both land byte-identical results."""
    if pipeline.enabled() and staging.current() is None:
        return _apply_pipelined(spec, state, signed_blocks, validate_result)
    for signed_block in signed_blocks:
        _apply_one(spec, state, signed_block, validate_result)
    return state


def _replay_breaker_open(spec, state, signed_block, validate_result: bool,
                         rec: bool) -> None:
    """The open-breaker skip: accounting + literal replay, shared by the
    serial and pipelined paths so their stats can never drift."""
    stats["replayed_blocks"] += 1
    stats["breaker_skipped"] += 1
    _count_reason("breaker_open")
    tracing.count("stf.replayed_block")
    if rec:
        recorder.record("block_replayed",
                        slot=int(signed_block.message.slot),
                        reason="breaker_open")
    spec.state_transition(state, signed_block, validate_result)


def _apply_one(spec, state, signed_block, validate_result: bool) -> None:
    # flight-recorder + timeline gates hoisted once per block: per-event
    # field computation (slot reads, stats deltas, link ids) is paid only
    # while an observer is armed
    rec = recorder.enabled()
    link = timeline.next_link() if timeline.enabled() else None
    if not _breaker_allows_attempt():
        _replay_breaker_open(spec, state, signed_block, validate_result, rec)
        return
    pre_backing = state.get_backing()
    snap = _block_snapshot() if rec else None
    try:
        if not _fast_path_ready(spec):
            # uncovered forks keep their own kernel substitutions + the
            # facade's deferred per-block batch
            raise FastPathViolation(
                "fast path covers phase0/altair/bellatrix + native BLS")
        with staging.block_transaction():
            _fast_transition(spec, state, signed_block, validate_result,
                             link=link)
            # the commit itself is a probed seam: a torn commit rolls the
            # staged entries back and the block replays literally
            _SITE_CACHE_COMMIT()
        stats["fast_blocks"] += 1
        _breaker_note_success()
        tracing.count("stf.fast_block")
        if rec:
            # after the transaction settled (OB01 discipline: a rolled
            # back block must never log a fast application)
            recorder.record("block_fast",
                            slot=int(signed_block.message.slot),
                            **_block_delta(snap))
    except Exception as exc:
        state.set_backing(pre_backing)
        # the fast-path spans describe work that just rolled back: mark
        # the block's flow cancelled before the literal replay re-does it
        timeline.cancel_link(link)
        _replay_literal(spec, state, signed_block, validate_result, exc, rec)


# phase attribution captured per block by the flight recorder (deltas of
# the cumulative stats above, plus the plan/h2c cache movement)
_PHASE_KEYS = ("slot_roots_s", "sig_verify_s", "attestation_apply_s",
               "sync_apply_s", "other_s")


def _h2c_stats():
    """The native hash_to_g2 cache counters, via sys.modules so a block
    applied without the native backend never imports it as a side effect."""
    native = sys.modules.get("consensus_specs_tpu.crypto.bls.native")
    if native is None:
        return None
    try:
        return native.h2c_cache_stats()
    except Exception:  # counter read must never fail a block
        return None


def _block_snapshot() -> dict:
    """Pre-block counter snapshot (recorder-enabled path only)."""
    from . import attestations

    snap = {k: stats[k] for k in _PHASE_KEYS}
    snap["plan_hits"] = attestations.stats["plan_hits"]
    snap["plan_misses"] = attestations.stats["plan_misses"]
    h2c = _h2c_stats()
    if h2c is not None:
        snap["h2c_hits"] = h2c["hits"]
        snap["h2c_misses"] = h2c["misses"]
    return snap


def _block_delta(snap: dict) -> dict:
    """This block's phase timings and cache movement, as deltas of the
    cumulative counters against the pre-block snapshot."""
    from . import attestations

    out = {k: round(stats[k] - snap[k], 6) for k in _PHASE_KEYS}
    out["plan_hits"] = attestations.stats["plan_hits"] - snap["plan_hits"]
    out["plan_misses"] = (attestations.stats["plan_misses"]
                          - snap["plan_misses"])
    if "h2c_hits" in snap:
        h2c = _h2c_stats()
        if h2c is not None:
            out["h2c_hits"] = h2c["hits"] - snap["h2c_hits"]
            out["h2c_misses"] = h2c["misses"] - snap["h2c_misses"]
    return out


def _collect_block(spec, state, signed_block, validate_result: bool,
                   spec_keys, link=None) -> tuple:
    """One block's host phases: slot advancement, header/RANDAO/eth1,
    operations with the vectorized attestation path, sync aggregate —
    every state mutation of the fast path, with the block's signature
    checks collected (not settled) as materialized batch entries.
    Returns ``(entries, keys, t_host_done)``; both settlement styles
    (serial ``_fast_transition``, pipelined ``_begin_block``) build on
    it.  ``spec_keys`` is the pending predecessor's dispatched key set —
    triples it is already verifying are skipped speculatively
    (verify.note_speculative_hit; safe because any predecessor failure
    drains this block too).  ``link`` is the block's timeline causality
    id: every host-phase span carries it, so the Chrome-trace export can
    chain this block's flow across threads (None with the timeline
    off)."""
    from consensus_specs_tpu.crypto import bls

    block = signed_block.message
    altair_lineage = spec.fork != "phase0"
    t0 = time.perf_counter()
    with timeline.span("host/slot_roots", link=link, slot=int(block.slot)):
        slot_roots.process_slots(spec, state, block.slot)
    t1 = time.perf_counter()
    stats["slot_roots_s"] += t1 - t0
    histogram.observe("slot_roots", t1 - t0)

    bls_on = bls.bls_active
    entries, keys = [], []

    def collect(members_id, count, flat, message, signature):
        key = verify.triple_key(members_id, message, signature)
        if verify.is_verified(key):
            return
        if spec_keys is not None and key in spec_keys:
            verify.note_speculative_hit()
            return
        entries.append((count, flat(), message, signature))
        keys.append(key)

    if validate_result and bls_on:
        _proposer_entry(spec, state, signed_block, collect)
    t2 = time.perf_counter()

    # process_block shape of the block's own fork (phase0.py:1149-1154,
    # altair.py:405-410, bellatrix.py:242-249): header/RANDAO/attestations/
    # sync aggregate run the vectorized or collect-don't-verify variants
    # below; the remaining operations are the spec's own functions
    with timeline.span("host/operations", link=link):
        _header(spec, state, block)
        if spec.fork == "bellatrix" and spec.is_execution_enabled(state, block.body):
            # [New in Bellatrix] — literal, inside the snapshot-protected
            # region: payload checks raise straight into the replay contract
            spec.process_execution_payload(
                state, block.body.execution_payload, spec.EXECUTION_ENGINE)
        _randao_collect(spec, state, block.body, collect, bls_on)
        spec.process_eth1_data(state, block.body)
        t3 = time.perf_counter()
        # _attestations times itself into attestation_apply_s; the remaining
        # operations (slashings, deposits, exits) belong to other_s so a
        # regression in e.g. process_deposit localizes honestly
        apply_before = stats["attestation_apply_s"]
        _operations(spec, state, block.body, collect, bls_on, altair_lineage)
        t4 = time.perf_counter()
    non_attestation_ops = (t4 - t3) - (stats["attestation_apply_s"] - apply_before)
    if altair_lineage:
        with timeline.span("host/sync_aggregate", link=link):
            sync.process_sync_aggregate(
                spec, state, block.body.sync_aggregate, collect, bls_on)
    t4s = time.perf_counter()
    stats["sync_apply_s"] += t4s - t4
    if altair_lineage:
        histogram.observe("sync_apply", t4s - t4)
    stats["sig_verify_s"] += t2 - t1
    stats["other_s"] += (t3 - t2) + non_attestation_ops
    return entries, keys, t4s


def _fast_transition(spec, state, signed_block, validate_result: bool,
                     link=None) -> None:
    """Serial settlement (pipeline OFF / re-entrant calls): host phases,
    then the one synchronous multi-pairing, then the post-state root."""
    entries, keys, t4s = _collect_block(
        spec, state, signed_block, validate_result, None, link=link)
    bad = verify.settle(entries, keys, link=link)
    if bad is not None:
        raise FastPathViolation(f"invalid signature (batch entry {bad})")
    t5 = time.perf_counter()
    histogram.observe("sig_verify", t5 - t4s)
    if validate_result:
        with timeline.span("host/state_root", link=link):
            computed = _SITE_STATE_ROOT(
                bytes(slot_roots.state_root(spec, state)))
        if bytes(signed_block.message.state_root) != computed:
            raise FastPathViolation("state root mismatch")
    t6 = time.perf_counter()
    stats["sig_verify_s"] += t5 - t4s
    stats["other_s"] += t6 - t5


# -- cross-block overlapped pipeline ------------------------------------------


class _Speculation:
    """One block whose host phases are applied and whose signature batch
    is in flight: everything needed to settle it (commit + memo keys) or
    unwind it (open transaction + backing snapshot + literal replay)."""

    __slots__ = ("signed_block", "slot", "index", "pre_backing", "txn",
                 "handle", "keys_set", "rec_delta", "link")

    def __init__(self, signed_block, pre_backing, txn, handle, keys_set,
                 link=None):
        self.signed_block = signed_block
        self.slot = int(signed_block.message.slot)
        self.index = -1  # position in the call's block list (set by the loop)
        self.pre_backing = pre_backing
        self.txn = txn
        self.handle = handle
        self.keys_set = keys_set
        self.rec_delta = None
        self.link = link  # timeline causality id (None with timeline off)


def _begin_block(spec, state, signed_block, validate_result: bool,
                 spec_keys, rec: bool, link=None) -> _Speculation:
    """Apply one block's host phases under a fresh (open) cache
    transaction and dispatch its signature batch; the post-state root is
    checked here (its inputs are complete — only the verdict is
    outstanding).  On any exception the partial work is fully unwound —
    own batch discarded, transaction rolled back, backing restored —
    before the exception propagates into the caller's replay handling."""
    pre_backing = state.get_backing()
    snap = _block_snapshot() if rec else None
    txn = staging.begin_block()
    handle = None
    try:
        entries, keys, t4s = _collect_block(
            spec, state, signed_block, validate_result, spec_keys, link=link)
        if entries:
            handle = pipeline.dispatch(entries, link=link)
            # the memo commit stays deferred through the block's own
            # transaction: it runs only at commit_block, after the
            # verdict — speculated verification never leaks into a
            # rolled-back block (EF01/OB01 discipline)
            verify.stage_commit(keys)
        t5 = time.perf_counter()
        stats["sig_verify_s"] += t5 - t4s
        if validate_result:
            with timeline.span("host/state_root", link=link):
                computed = _SITE_STATE_ROOT(
                    bytes(slot_roots.state_root(spec, state)))
            if bytes(signed_block.message.state_root) != computed:
                raise FastPathViolation("state root mismatch")
            stats["other_s"] += time.perf_counter() - t5
    except BaseException:
        pipeline.discard(handle)
        staging.rollback_block(txn)
        state.set_backing(pre_backing)
        timeline.cancel_link(link)
        raise
    finally:
        staging.deactivate(txn)
    pend = _Speculation(signed_block, pre_backing, txn, handle,
                        frozenset(keys) if keys else frozenset(),
                        link=link)
    if rec:
        # host-phase attribution captured NOW (the block's own work);
        # the settlement await is added at finish so the recorded block
        # never charges the successor's host phases to this block
        pend.rec_delta = _block_delta(snap)
    return pend


def _finish_speculation(pend: _Speculation, rec: bool):
    """Await ``pend``'s verdict and settle its transaction.  Returns None
    on success (fast-block bookkeeping done) or the exception that must
    drive the literal replay — the CALLER unwinds state, successor first
    (LIFO), because blocks may already be speculated on top."""
    a0 = pipeline.stats["await_s"]
    try:
        with timeline.span("host/await_verdict", link=pend.link):
            bad = (pipeline.wait(pend.handle)
                   if pend.handle is not None else None)
    except Exception as exc:
        return exc
    finally:
        awaited = pipeline.stats["await_s"] - a0
        stats["sig_verify_s"] += awaited
        histogram.observe("pipeline_await", awaited)
        if pend.handle is not None:
            # the sig_verify DISTRIBUTION keeps one meaning pipeline ON
            # or OFF: the batch's true wall time on the native backend
            # (the worker span), not the non-overlapped remainder the
            # cumulative sig_verify_s counter attributes
            ws = pend.handle.worker_span
            histogram.observe("sig_verify", max(0.0, ws[1] - ws[0]))
        if pend.rec_delta is not None:
            pend.rec_delta["sig_verify_s"] = round(
                pend.rec_delta["sig_verify_s"] + awaited, 6)
    if bad is not None:
        return FastPathViolation(f"invalid signature (batch entry {bad})")
    try:
        # the commit itself is a probed seam (same as the serial path): a
        # torn commit rolls the staged entries back and the block replays
        _SITE_CACHE_COMMIT()
        staging.commit_block(pend.txn)
    except Exception as exc:
        return exc
    stats["fast_blocks"] += 1
    _breaker_note_success()
    tracing.count("stf.fast_block")
    timeline.instant("commit", link=pend.link, slot=pend.slot)
    if rec and pend.rec_delta is not None:
        recorder.record("block_fast", slot=pend.slot, **pend.rec_delta)
    return None


def _account_failure(exc: BaseException) -> None:
    """The serial except-branch bookkeeping, shared with the pipeline."""
    if not isinstance(exc, FastPathViolation):
        stats["fast_path_errors"] += 1
        _breaker_note_error()
    _count_reason(type(exc).__name__)
    stats["replayed_blocks"] += 1
    tracing.count("stf.replayed_block")


def _replay_literal(spec, state, signed_block, validate_result: bool,
                    exc: BaseException, rec: bool) -> None:
    """Account a fast-path failure and replay the block through the
    literal spec (raising the spec's own exception, or succeeding)."""
    _account_failure(exc)
    if rec:
        recorder.record("block_replayed",
                        slot=int(signed_block.message.slot),
                        reason=type(exc).__name__,
                        detail=str(exc)[:160])
    spec.state_transition(state, signed_block, validate_result)


def _unwind_pending(state, pend: _Speculation) -> None:
    """Roll a failed pending block back: any still-unconsumed batch
    drained and discarded (a drain-seam fault can leave one), the open
    transaction popped, and the backing restored to its pre-block
    snapshot (also erasing any successor host mutations stacked on top —
    the caller unwound the successor's own transaction first)."""
    pipeline.discard(pend.handle)
    staging.rollback_block(pend.txn)
    state.set_backing(pend.pre_backing)


def _apply_pipelined(spec, state, signed_blocks, validate_result: bool):
    """The overlapped engine loop: begin block i (host phases + async
    dispatch), then settle the window down to ``pipeline.window_depth()``
    outstanding verdicts — so speculated blocks' native pairings run
    concurrently with up to ``depth`` later blocks' host work (the extra
    slack absorbs per-block jitter a one-deep window leaks as await
    time).  Any failure drains LIFO — newer speculations unwound first,
    then the failing block restores its snapshot and replays literally —
    and the loop resumes at the block after the failure, so recovery
    re-runs everything whose host phases rode the dead state.  The
    pipeline always drains before returning (no verdict outlives a
    call)."""
    blocks = list(signed_blocks)
    window = []  # oldest-first _Speculations with verdicts outstanding
    depth = pipeline.window_depth()

    def settle(target_len: int, drain_reason, rec: bool):
        """Settle the window (oldest first) down to ``target_len``.
        Returns None when every settled block committed, else the index
        to resume the main loop at (the failed block replayed literally
        — raising the spec's exception unless the replay recovered —
        and every NEWER speculation unwound LIFO first, its host phases
        having ridden a state that no longer exists)."""
        if drain_reason is not None and window:
            pipeline.note_drain(drain_reason)
            timeline.instant("pipeline_drain", link=window[0].link,
                             reason=drain_reason)
            if rec:
                recorder.record("pipeline_drain", reason=drain_reason,
                                slot=window[0].slot)
        while len(window) > target_len:
            pend = window[0]
            fail = _finish_speculation(pend, rec)
            if fail is None:
                window.pop(0)
                continue
            if drain_reason is None:
                pipeline.note_drain("verdict_failed")
                timeline.instant("pipeline_drain", link=pend.link,
                                 reason="verdict_failed")
                if rec:
                    recorder.record("pipeline_drain",
                                    reason="verdict_failed",
                                    slot=pend.slot)
            for newer in reversed(window[1:]):
                pipeline.discard(newer.handle)
                staging.rollback_block(newer.txn)
            # one ring pass marks the WHOLE drained window cancelled —
            # the failing block included (_unwind_pending no longer
            # rescans for it)
            timeline.cancel_links([n.link for n in window])
            del window[:]
            _unwind_pending(state, pend)
            _replay_literal(spec, state, pend.signed_block,
                            validate_result, fail, rec)
            return pend.index + 1
        return None

    i = 0

    def settle_then_replay(reason: str, exc, rec: bool):
        """The shared ineligible/failed-block shape: settle the whole
        window (drain-tagged when one was open), then — unless a window
        failure rewound the loop — replay the current block literally.
        Returns the next loop index."""
        resume = settle(0, reason if window else None, rec)
        if resume is not None:
            return resume
        _replay_literal(spec, state, blocks[i], validate_result, exc, rec)
        return i + 1

    while True:
        if i >= len(blocks):
            if not window:
                break
            resume = settle(0, None, recorder.enabled())
            if resume is not None:
                i = resume
            continue
        signed_block = blocks[i]
        rec = recorder.enabled()
        if not _breaker_allows_attempt():
            resume = settle(0, "breaker_open" if window else None, rec)
            if resume is not None:
                i = resume
                continue
            _replay_breaker_open(spec, state, signed_block, validate_result,
                                 rec)
            i += 1
            continue
        try:
            ready = _fast_path_ready(spec)
        except Exception as exc_gate:
            # the availability gate is a probed seam: a dying gate must
            # resolve like any fast-path error (serial-path parity)
            i = settle_then_replay("gate_failed", exc_gate, rec)
            continue
        if not ready:
            i = settle_then_replay(
                "fast_path_unready",
                FastPathViolation(
                    "fast path covers phase0/altair/bellatrix + native BLS"),
                rec)
            continue
        spec_keys = (frozenset().union(*(p.keys_set for p in window))
                     if window else None)
        link = timeline.next_link() if timeline.enabled() else None
        try:
            cur = _begin_block(spec, state, signed_block, validate_result,
                               spec_keys, rec, link=link)
        except Exception as exc_begin:
            # the partial current block is already unwound; settle its
            # predecessors first (sequential order), then replay it
            i = settle_then_replay("begin_failed", exc_begin, rec)
            continue
        cur.index = i
        window.append(cur)
        i += 1
        resume = settle(depth, None, rec)
        if resume is not None:
            i = resume
    return state


def _proposer_entry(spec, state, signed_block, collect) -> None:
    """verify_block_signature (phase0.py:777-780) as one batch entry."""
    block = signed_block.message
    proposer = state.validators[block.proposer_index]
    signing_root = spec.compute_signing_root(
        block, spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER))
    pk = bytes(proposer.pubkey)
    collect(pk, 1, lambda: _single_affine(pk),
            bytes(signing_root), bytes(signed_block.signature))


def _single_affine(pubkey: bytes) -> bytes:
    from consensus_specs_tpu.crypto.bls import native

    xy = native.pubkey_affine(pubkey)
    if xy is None:
        raise FastPathViolation("unverifiable pubkey")
    return xy


def _header(spec, state, block) -> None:
    """process_block_header (phase0.py:1156-1176) with the proposer check
    against the numpy-active fast proposer walk."""
    assert block.slot == state.slot
    assert block.slot > state.latest_block_header.slot
    assert block.proposer_index == beacon_proposer_index(spec, state)
    assert block.parent_root == spec.hash_tree_root(state.latest_block_header)
    state.latest_block_header = spec.BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=spec.Bytes32(),  # Overwritten in the next process_slot call
        body_root=spec.hash_tree_root(block.body),
    )
    proposer = state.validators[block.proposer_index]
    assert not proposer.slashed
    # probed AFTER the header write: a fault here proves the rollback
    # restores the mutated latest_block_header
    _SITE_HEADER()


def _randao_collect(spec, state, body, collect, bls_on) -> None:
    """process_randao (phase0.py:1179-1187) with the reveal's pairing
    check deferred into the block batch."""
    epoch = spec.get_current_epoch(state)
    proposer = state.validators[beacon_proposer_index(spec, state)]
    if bls_on:
        signing_root = spec.compute_signing_root(
            epoch, spec.get_domain(state, spec.DOMAIN_RANDAO))
        pk = bytes(proposer.pubkey)
        collect(pk, 1, lambda: _single_affine(pk),
                bytes(signing_root), bytes(body.randao_reveal))
    mix = spec.xor(spec.get_randao_mix(state, epoch),
                   spec.hash(body.randao_reveal))
    state.randao_mixes[epoch % spec.EPOCHS_PER_HISTORICAL_VECTOR] = mix
    _SITE_RANDAO()  # post-mix-write: rollback must restore randao_mixes


def _operations(spec, state, body, collect, bls_on, altair_lineage) -> None:
    """process_operations (phase0.py:1196-1208; altair inherits the same
    dispatch shape) with the attestation loop replaced by the whole-block
    vectorized path of the block's fork family."""
    assert len(body.deposits) == min(
        spec.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index)

    for operation in body.proposer_slashings:
        spec.process_proposer_slashing(state, operation)
    for operation in body.attester_slashings:
        spec.process_attester_slashing(state, operation)
    _SITE_OPERATIONS()  # mid-operations: slashings applied, rest pending
    _attestations(spec, state, body.attestations, collect, bls_on,
                  altair_lineage)
    for operation in body.deposits:
        spec.process_deposit(state, operation)
    for operation in body.voluntary_exits:
        spec.process_voluntary_exit(state, operation)


def _attestations(spec, state, attestations, collect, bls_on,
                  altair_lineage) -> None:
    """The block's process_attestation loop, vectorized: one resolution
    pass, one bulk attester-set reduction, then the fork family's state
    application — pending-attestation appends (phase0.py:1249-1275) or
    participation-flag scatter (altair.py:413-446) — and one signature
    entry per aggregate."""
    if len(attestations) == 0:
        return
    t0 = time.perf_counter()
    try:
        if altair_lineage:
            _attestations_inner_altair(spec, state, attestations, collect,
                                       bls_on)
        else:
            _attestations_inner(spec, state, attestations, collect, bls_on)
    finally:
        dt = time.perf_counter() - t0
        stats["attestation_apply_s"] += dt
        histogram.observe("attestation_apply", dt)


def _attester_domains(spec, state, resolver) -> dict:
    """The (at most two) beacon-attester domains a block's attestations
    can sign under, computed once per block.  ``compute_signing_root`` of
    an attestation then reduces to one sha256 of (data root || domain) —
    the SigningData container's own merkleization shape — instead of a
    per-attestation container build."""
    return {
        epoch: bytes(spec.get_domain(
            state, spec.DOMAIN_BEACON_ATTESTER, spec.Epoch(epoch)))
        for epoch in {resolver.previous_epoch, resolver.current_epoch}
    }


def _attestations_inner(spec, state, attestations, collect, bls_on) -> None:
    t0 = time.perf_counter()
    resolver = resolve_block_attestations(spec, state)
    plans = resolver.resolve(attestations)
    t1 = time.perf_counter()
    stats["resolve_s"] += t1 - t0
    tracing.count("stf.attestations", len(plans))

    # identical for every attestation in the block: state.slot is fixed and
    # process_block_header already pinned it to the block's proposer.
    # Every loop-invariant view is hoisted — at 122 aggregates/block the
    # per-attestation SSZ field chains were a measurable apply_s share
    proposer_index = beacon_proposer_index(spec, state)
    current_epoch = resolver.current_epoch
    validators = state.validators
    registry_root = bytes(validators.hash_tree_root())
    domains = _attester_domains(spec, state, resolver) if bls_on else None
    state_slot = state.slot
    cur_justified = state.current_justified_checkpoint
    prev_justified = state.previous_justified_checkpoint
    cur_pendings = state.current_epoch_attestations
    prev_pendings = state.previous_epoch_attestations
    PendingAttestation = spec.PendingAttestation

    for att, plan in zip(attestations, plans):
        data = att.data
        pending = PendingAttestation(
            data=data,
            aggregation_bits=att.aggregation_bits,
            inclusion_delay=state_slot - data.slot,
            proposer_index=proposer_index,
        )
        if plan.target_epoch == current_epoch:
            if data.source != cur_justified:
                raise FastPathViolation("source != current justified")
            cur_pendings.append(pending)
        else:
            if data.source != prev_justified:
                raise FastPathViolation("source != previous justified")
            prev_pendings.append(pending)
        if bls_on:
            attesters = plan.attesters
            signing_root = hashlib.sha256(
                plan.data_root + domains[plan.target_epoch]).digest()
            collect(registry_root + attesters.tobytes(), len(attesters),
                    lambda a=attesters: affine_rows(validators, a),
                    signing_root, bytes(att.signature))
    stats["apply_s"] += time.perf_counter() - t1


class _FlagMaskContext:
    """Per-block context for ``get_attestation_participation_flag_indices``
    (altair.py:303-330) as a bit mask, with the spec's ``assert
    is_matching_source`` mapped to the replay contract.  Everything
    loop-invariant — the justified checkpoints, the spec constants, and
    the (at most two) target-epoch block roots and (typically two)
    per-slot head roots — is computed once per block instead of once per
    attestation; the matching-target/head short-circuits and the
    ``get_block_root*`` raise points are preserved (memoized lookups
    raise at the same first-use point the spec's per-attestation call
    would, and a successful lookup would have re-succeeded identically)."""

    __slots__ = ("spec", "state", "state_slot", "cur_justified",
                 "prev_justified", "sqrt_spe", "spe", "min_delay",
                 "src_bit", "tgt_bit", "head_bit", "_target_roots",
                 "_head_roots")

    def __init__(self, spec, state, resolver):
        self.spec = spec
        self.state = state
        self.state_slot = resolver.state_slot
        self.cur_justified = state.current_justified_checkpoint
        self.prev_justified = state.previous_justified_checkpoint
        self.sqrt_spe = int(spec.integer_squareroot(spec.SLOTS_PER_EPOCH))
        self.spe = int(spec.SLOTS_PER_EPOCH)
        self.min_delay = int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
        self.src_bit = 1 << int(spec.TIMELY_SOURCE_FLAG_INDEX)
        self.tgt_bit = 1 << int(spec.TIMELY_TARGET_FLAG_INDEX)
        self.head_bit = 1 << int(spec.TIMELY_HEAD_FLAG_INDEX)
        self._target_roots: dict = {}
        self._head_roots: dict = {}

    def mask(self, data, target_epoch: int, is_current: bool) -> int:
        justified = self.cur_justified if is_current else self.prev_justified
        if data.source != justified:
            raise FastPathViolation("source != justified checkpoint")
        slot = int(data.slot)
        inclusion_delay = self.state_slot - slot
        target_root = self._target_roots.get(target_epoch)
        if target_root is None:
            target_root = self._target_roots[target_epoch] = bytes(
                self.spec.get_block_root(self.state, data.target.epoch))
        is_matching_target = bytes(data.target.root) == target_root
        if is_matching_target:
            head_root = self._head_roots.get(slot)
            if head_root is None:
                head_root = self._head_roots[slot] = bytes(
                    self.spec.get_block_root_at_slot(self.state, data.slot))
            is_matching_head = bytes(data.beacon_block_root) == head_root
        else:
            is_matching_head = False
        mask = 0
        if inclusion_delay <= self.sqrt_spe:
            mask |= self.src_bit
        if is_matching_target and inclusion_delay <= self.spe:
            mask |= self.tgt_bit
        if is_matching_head and inclusion_delay == self.min_delay:
            mask |= self.head_bit
        return mask


def _attestations_inner_altair(spec, state, attestations, collect, bls_on) -> None:
    """The altair-lineage process_attestation loop (altair.py:413-446),
    vectorized: the same plan-cached whole-block resolution as phase0,
    then per attestation a participation-flag OR-scatter on a staged view
    of the resident epoch participation column (stf/columns.py — a dict
    probe after the first block, not a tree walk), the proposer-reward
    numerator as one masked increment sum per newly-set flag, and one
    signature entry per aggregate.  Staged views flush as ONE packed
    write per dirtied column (re-registered under the column's new root,
    so the NEXT block's read hits residency) and the proposer reward
    lands as one balance write (per-attestation floor division preserved
    — the spec divides before each increase)."""
    import numpy as np

    from consensus_specs_tpu.ops.epoch_jax import registry_columns

    t_res0 = time.perf_counter()
    resolver = resolve_block_attestations(spec, state)
    plans = resolver.resolve(attestations)
    t_res1 = time.perf_counter()
    stats["resolve_s"] += t_res1 - t_res0
    tracing.count("stf.attestations", len(plans))

    proposer_index = beacon_proposer_index(spec, state)
    current_epoch = resolver.current_epoch
    validators = state.validators
    registry_root = bytes(validators.hash_tree_root())
    domains = _attester_domains(spec, state, resolver) if bls_on else None

    # participation mirrors: staged views of the resident columns, read
    # lazily once per block, written back once per dirtied column after
    # the loop (deposits append only later in process_operations, so the
    # column length is stable here).  ``resident`` keeps the store's
    # readonly original so a column whose bits were all already set (a
    # block of re-carried aggregates) skips the flush AND the subtree
    # re-hash its packed write would force.
    staged, resident = {}, {}

    def column_for(is_current):
        col = staged.get(is_current)
        if col is None:
            resident[is_current] = columns.participation_column(
                state, is_current)
            # probed between read and use: a corrupted mirror must be
            # caught by the post-state root check, never flushed silently
            col = staged[is_current] = _SITE_MIRROR_READ(
                columns.staged_view(state, is_current))
        return col

    # exact get_base_reward column: effective // increment * per-increment
    # (both constant within a block — effective balances only move at the
    # epoch boundary)
    increments = (registry_columns(state)["effective_balance"]
                  // int(spec.EFFECTIVE_BALANCE_INCREMENT))
    per_increment = int(spec.get_base_reward_per_increment(state))
    weights = [int(w) for w in spec.PARTICIPATION_FLAG_WEIGHTS]
    weight_denominator = int(spec.WEIGHT_DENOMINATOR)
    proposer_weight = int(spec.PROPOSER_WEIGHT)
    denominator = ((weight_denominator - proposer_weight)
                   * weight_denominator // proposer_weight)
    proposer_reward_total = 0
    flag_ctx = _FlagMaskContext(spec, state, resolver)

    for att, plan in zip(attestations, plans):
        data = att.data
        attesters = plan.attesters
        is_current = plan.target_epoch == current_epoch
        mask = flag_ctx.mask(data, plan.target_epoch, is_current)
        column = column_for(is_current)
        held = column[attesters]
        numerator = 0
        for flag_index, weight in enumerate(weights):
            bit = 1 << flag_index
            if not mask & bit:
                continue
            newly = attesters[(held & bit) == 0]
            if len(newly):
                numerator += int(
                    np.sum(increments[newly], dtype=np.uint64)) * weight
        column[attesters] = held | np.uint8(mask)
        # the spec floors the division per attestation, then increases the
        # proposer balance; summing the floored rewards is exact
        proposer_reward_total += numerator * per_increment // denominator
        if bls_on:
            signing_root = hashlib.sha256(
                plan.data_root + domains[plan.target_epoch]).digest()
            collect(registry_root + attesters.tobytes(), len(attesters),
                    lambda a=attesters: affine_rows(validators, a),
                    signing_root, bytes(att.signature))
    t_apply = time.perf_counter()
    stats["apply_s"] += t_apply - t_res1

    _SITE_MIRROR_FLUSH()  # pre-flush: mirrors dirty, state still clean
    for is_current, col in staged.items():
        if not np.array_equal(col, resident[is_current]):
            columns.flush(state, is_current, col)
    if proposer_reward_total:
        # Gwei() raises on uint64 overflow exactly where the spec's
        # sequential += would have (increments are non-negative)
        state.balances[proposer_index] = spec.Gwei(
            int(state.balances[proposer_index]) + proposer_reward_total)
    dt_flush = time.perf_counter() - t_apply
    stats["mirror_flush_s"] += dt_flush
    histogram.observe("mirror_flush", dt_flush)


# -- telemetry ----------------------------------------------------------------


def _telemetry_provider() -> dict:
    """The engine's cumulative counters + the breaker's live internals
    (consecutive-error count and skip countdown — the two numbers that
    predict the NEXT transition, which the state string alone hides)."""
    return {
        **{k: v for k, v in stats.items() if k != "replay_reasons"},
        "replay_reasons": dict(stats["replay_reasons"]),
        "breaker": dict(_breaker),
    }


telemetry.register_provider("stf.engine", _telemetry_provider, replace=True)
