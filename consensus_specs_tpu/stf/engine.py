"""Batched block-transition engine: ``apply_signed_blocks``.

Replays a sequence of signed blocks through the spec state transition with
three fused optimizations (docs/architecture.md, "The block path"):

1. **one BLS batch per block** — the proposer signature, the RANDAO
   reveal, and every aggregate attestation settle in a single
   ``BatchFastAggregateVerify`` multi-pairing (stf/verify.py), with
   cross-block dedup of already-verified triples;
2. **vectorized attestation application** — committees and attester sets
   resolve off the cached whole-epoch shuffle permutation as numpy
   gathers, participation counts reduce through ``ops/segment.py``
   (stf/attestations.py), and only the spec-mandated tree writes
   (pending-attestation appends) touch the state;
3. **cheap per-slot roots** — ``process_slots`` runs with dirty packed
   balance subtrees routed through the resident merkle path
   (stf/slot_roots.py).

Failure contract — differential-exact by construction: the fast path is
optimistic; on ANY trouble (a structural check, a failed signature batch,
a fork or backend the fast path does not cover) the block's pre-state is
restored from its O(1) backing snapshot and the block replays through the
literal ``spec.state_transition``, which raises the spec's exact exception
type/message at the spec's exact point and leaves the state exactly as
poisoned as the sequential path would have.  Valid blocks therefore land
byte-identical post-states, and invalid blocks are indistinguishable from
the spec path (pinned by
tests/spec/phase0/sanity/test_stf_engine_differential.py).
"""
from __future__ import annotations

import time

from consensus_specs_tpu import tracing

from . import slot_roots, verify
from .attestations import (
    FastPathViolation,
    affine_rows,
    attesting_index_sets,
    beacon_proposer_index,
    resolve_block_attestations,
)

stats = {
    "fast_blocks": 0,
    "replayed_blocks": 0,
    "fast_path_errors": 0,
    "sig_verify_s": 0.0,
    "attestation_apply_s": 0.0,
    "slot_roots_s": 0.0,
    "other_s": 0.0,
}


def reset_stats() -> None:
    """Zero ALL engine counters — the per-block phase/fallback dict here
    and the signature-settlement counters in stf/verify.py (one call, so
    bench rows can't accidentally report cumulative halves)."""
    for k in stats:
        stats[k] = 0.0 if isinstance(stats[k], float) else 0
    for k in verify.stats:
        verify.stats[k] = 0


def _native_available() -> bool:
    try:
        from consensus_specs_tpu.crypto.bls import native  # noqa: F401
        return True
    except ImportError:
        return False


def apply_signed_blocks(spec, state, signed_blocks, validate_result: bool = True):
    """Apply ``signed_blocks`` to ``state`` in place, semantically
    identical to ``for sb in signed_blocks: spec.state_transition(state,
    sb, validate_result)`` — same post-states on success, same exception
    and partial state on the first invalid block."""
    for signed_block in signed_blocks:
        _apply_one(spec, state, signed_block, validate_result)
    return state


def _apply_one(spec, state, signed_block, validate_result: bool) -> None:
    pre_backing = state.get_backing()
    try:
        if getattr(spec, "fork", None) != "phase0" or not _native_available():
            # later forks keep their own kernel substitutions + the
            # facade's deferred per-block batch; the fast path below is
            # the phase0 shape (ROADMAP follow-up: altair lineage)
            raise FastPathViolation("fast path covers phase0 + native BLS")
        _fast_transition(spec, state, signed_block, validate_result)
        stats["fast_blocks"] += 1
        tracing.count("stf.fast_block")
    except Exception as exc:
        if not isinstance(exc, FastPathViolation):
            stats["fast_path_errors"] += 1
        stats["replayed_blocks"] += 1
        tracing.count("stf.replayed_block")
        state.set_backing(pre_backing)
        spec.state_transition(state, signed_block, validate_result)


def _fast_transition(spec, state, signed_block, validate_result: bool) -> None:
    from consensus_specs_tpu.crypto import bls

    block = signed_block.message
    t0 = time.perf_counter()
    slot_roots.process_slots(spec, state, block.slot)
    t1 = time.perf_counter()
    stats["slot_roots_s"] += t1 - t0

    bls_on = bls.bls_active
    entries, keys = [], []

    def collect(members_id, count, flat, message, signature):
        key = verify.triple_key(members_id, message, signature)
        if verify.is_verified(key):
            return
        entries.append((count, flat(), message, signature))
        keys.append(key)

    if validate_result and bls_on:
        _proposer_entry(spec, state, signed_block, collect)
    t2 = time.perf_counter()

    # process_block, phase0 shape (phase0.py:1149-1154): header/RANDAO/
    # attestations run the vectorized or collect-don't-verify variants
    # below; the remaining operations are the spec's own functions
    _header(spec, state, block)
    _randao_collect(spec, state, block.body, collect, bls_on)
    spec.process_eth1_data(state, block.body)
    t3 = time.perf_counter()
    # _attestations times itself into attestation_apply_s; the remaining
    # operations (slashings, deposits, exits) belong to other_s so a
    # regression in e.g. process_deposit localizes honestly
    apply_before = stats["attestation_apply_s"]
    _operations(spec, state, block.body, collect, bls_on)
    t4 = time.perf_counter()
    non_attestation_ops = (t4 - t3) - (stats["attestation_apply_s"] - apply_before)

    bad = verify.settle(entries, keys)
    if bad is not None:
        raise FastPathViolation(f"invalid signature (batch entry {bad})")
    t5 = time.perf_counter()
    if validate_result:
        if bytes(block.state_root) != bytes(slot_roots.state_root(spec, state)):
            raise FastPathViolation("state root mismatch")
    t6 = time.perf_counter()
    stats["sig_verify_s"] += (t2 - t1) + (t5 - t4)
    stats["other_s"] += (t3 - t2) + non_attestation_ops + (t6 - t5)


def _proposer_entry(spec, state, signed_block, collect) -> None:
    """verify_block_signature (phase0.py:777-780) as one batch entry."""
    block = signed_block.message
    proposer = state.validators[block.proposer_index]
    signing_root = spec.compute_signing_root(
        block, spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER))
    pk = bytes(proposer.pubkey)
    collect(pk, 1, lambda: _single_affine(pk),
            bytes(signing_root), bytes(signed_block.signature))


def _single_affine(pubkey: bytes) -> bytes:
    from consensus_specs_tpu.crypto.bls import native

    xy = native.pubkey_affine(pubkey)
    if xy is None:
        raise FastPathViolation("unverifiable pubkey")
    return xy


def _header(spec, state, block) -> None:
    """process_block_header (phase0.py:1156-1176) with the proposer check
    against the numpy-active fast proposer walk."""
    assert block.slot == state.slot
    assert block.slot > state.latest_block_header.slot
    assert block.proposer_index == beacon_proposer_index(spec, state)
    assert block.parent_root == spec.hash_tree_root(state.latest_block_header)
    state.latest_block_header = spec.BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=spec.Bytes32(),  # Overwritten in the next process_slot call
        body_root=spec.hash_tree_root(block.body),
    )
    proposer = state.validators[block.proposer_index]
    assert not proposer.slashed


def _randao_collect(spec, state, body, collect, bls_on) -> None:
    """process_randao (phase0.py:1179-1187) with the reveal's pairing
    check deferred into the block batch."""
    epoch = spec.get_current_epoch(state)
    proposer = state.validators[beacon_proposer_index(spec, state)]
    if bls_on:
        signing_root = spec.compute_signing_root(
            epoch, spec.get_domain(state, spec.DOMAIN_RANDAO))
        pk = bytes(proposer.pubkey)
        collect(pk, 1, lambda: _single_affine(pk),
                bytes(signing_root), bytes(body.randao_reveal))
    mix = spec.xor(spec.get_randao_mix(state, epoch),
                   spec.hash(body.randao_reveal))
    state.randao_mixes[epoch % spec.EPOCHS_PER_HISTORICAL_VECTOR] = mix


def _operations(spec, state, body, collect, bls_on) -> None:
    """process_operations (phase0.py:1196-1208) with the attestation loop
    replaced by the whole-block vectorized path."""
    assert len(body.deposits) == min(
        spec.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index)

    for operation in body.proposer_slashings:
        spec.process_proposer_slashing(state, operation)
    for operation in body.attester_slashings:
        spec.process_attester_slashing(state, operation)
    _attestations(spec, state, body.attestations, collect, bls_on)
    for operation in body.deposits:
        spec.process_deposit(state, operation)
    for operation in body.voluntary_exits:
        spec.process_voluntary_exit(state, operation)


def _attestations(spec, state, attestations, collect, bls_on) -> None:
    """The block's process_attestation loop (phase0.py:1249-1275),
    vectorized: one resolution pass, one bulk attester-set reduction, then
    the spec-mandated pending-attestation appends and one signature entry
    per aggregate."""
    if len(attestations) == 0:
        return
    t0 = time.perf_counter()
    try:
        _attestations_inner(spec, state, attestations, collect, bls_on)
    finally:
        stats["attestation_apply_s"] += time.perf_counter() - t0


def _attestations_inner(spec, state, attestations, collect, bls_on) -> None:
    resolver = resolve_block_attestations(spec, state)
    resolved = resolver.resolve(attestations)
    index_sets = attesting_index_sets(resolved)
    tracing.count("stf.attestations", len(index_sets))

    # identical for every attestation in the block: state.slot is fixed and
    # process_block_header already pinned it to the block's proposer
    proposer_index = beacon_proposer_index(spec, state)
    current_epoch = resolver.current_epoch
    validators = state.validators
    registry_root = bytes(validators.hash_tree_root())

    for att, attesters in zip(attestations, index_sets):
        data = att.data
        pending = spec.PendingAttestation(
            data=data,
            aggregation_bits=att.aggregation_bits,
            inclusion_delay=state.slot - data.slot,
            proposer_index=proposer_index,
        )
        if int(data.target.epoch) == current_epoch:
            if data.source != state.current_justified_checkpoint:
                raise FastPathViolation("source != current justified")
            state.current_epoch_attestations.append(pending)
        else:
            if data.source != state.previous_justified_checkpoint:
                raise FastPathViolation("source != previous justified")
            state.previous_epoch_attestations.append(pending)
        if bls_on:
            signing_root = spec.compute_signing_root(
                data, spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER,
                                      data.target.epoch))
            collect(registry_root + attesters.tobytes(), len(attesters),
                    lambda a=attesters: affine_rows(validators, a),
                    bytes(signing_root), bytes(att.signature))
