"""Mesh construction helpers.

One logical axis ``"v"`` (validator / lane axis) is enough for the
protocol's compute: every hot kernel is data-parallel over validators or
chunk lanes with only scalar reductions crossing shards.  A second axis
can be layered for multi-host (DCN) topologies, keeping reductions
within a host's ICI domain first.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def build_mesh(n_devices: Optional[int] = None, axis: str = "v",
               devices: Optional[Sequence] = None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        assert len(devices) >= n_devices, (
            f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def default_mesh() -> Mesh:
    return build_mesh()
