"""Full sharded SSZ merkleization of packed uint64 data over a device
mesh (SURVEY §2.7 tensor-parallel merkle lanes, completed: per-shard
SUBTREE ROOTS, not just one hashed layer).

Layout: chunk lanes shard across devices; every device reduces its own
subtree bottom-up with the batched SHA-256 kernel (zero cross-device
traffic), producing one 32-byte subtree root per device.  The tiny top of
the tree — log2(n_dev) levels plus the zero-capped limit levels and the
SSZ length mixin — folds on the host, bit-identical to
``List[uint64, limit].hash_tree_root()`` (differential test:
tests/test_merkle_sharded.py).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_specs_tpu.ops.sha256_jax import sha256_block64
from consensus_specs_tpu.ssz.hashing import sha256
from consensus_specs_tpu.ssz.node import ZERO_HASHES

jax.config.update("jax_enable_x64", True)


def _bswap32(x):
    x = ((x & jnp.uint32(0x00FF00FF)) << 8) | ((x >> 8) & jnp.uint32(0x00FF00FF))
    return ((x << 16) | (x >> 16)).astype(jnp.uint32)


def _local_subtree_root(balances):
    """[local_n] int64 lanes -> [8] uint32 words: the shard's subtree root.
    local_n must be a power-of-two multiple of 8 (whole 64-byte blocks)."""
    lanes = balances.astype(jnp.uint64)
    lo = (lanes & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = (lanes >> jnp.uint64(32)).astype(jnp.uint32)
    words = jnp.stack([_bswap32(lo), _bswap32(hi)], axis=-1).reshape(-1)
    digests = sha256_block64(words.reshape(-1, 16))  # chunk-pair layer
    while digests.shape[0] > 1:
        digests = sha256_block64(digests.reshape(-1, 16))
    return digests[0]


_SUBTREE_FN_CACHE: dict = {}


def make_sharded_subtree_roots(mesh: Mesh, axis: str = "v"):
    """jitted fn: sharded [n] balances -> [n_dev, 8] per-shard subtree
    roots (still device-resident; axis-sharded input, replicated output).
    Cached per (mesh, axis) so repeated roots reuse the compiled kernel."""
    from jax.experimental.shard_map import shard_map

    key = (mesh, axis)
    fn = _SUBTREE_FN_CACHE.get(key)
    if fn is None:
        fn = jax.jit(shard_map(
            lambda b: _local_subtree_root(b)[None, :],
            mesh=mesh,
            in_specs=P(axis),
            out_specs=P(axis),
        ))
        if len(_SUBTREE_FN_CACHE) > 8:
            _SUBTREE_FN_CACHE.clear()
        _SUBTREE_FN_CACHE[key] = fn
    return fn


def _words_to_bytes(words: np.ndarray) -> bytes:
    return b"".join(int(w).to_bytes(4, "big") for w in words)


def sharded_uint64_list_root(mesh: Mesh, arr: np.ndarray, limit: int,
                             axis: str = "v") -> bytes:
    """hash_tree_root of ``List[uint64, limit](arr)`` with the heavy
    subtree hashed across the mesh.

    The data pads with zero lanes to (n_dev * pow2 * 8); zero-padding is
    exactly SSZ's virtual zero-extension, so no correction is needed."""
    n_dev = mesh.devices.size
    assert n_dev & (n_dev - 1) == 0, (
        "sharded merkleization needs a power-of-two device count; the "
        "pairwise host fold and the SSZ tree depth both assume it")
    n = len(arr)
    # chunks per shard must be a power of two for clean pairwise reduction
    per_shard = 8
    while per_shard * n_dev < max(n, 1):
        per_shard *= 2
    n_pad = per_shard * n_dev
    limit_chunks = (limit * 8 + 31) // 32
    if limit_chunks < n_pad // 4:
        # list too small to fill even one padded shard each: the sharded
        # reduction would hash past the limit depth — host path is right
        from consensus_specs_tpu.ssz.types import List, uint64

        return bytes(List[uint64, limit]([int(x) for x in arr]).hash_tree_root())
    padded = np.zeros(n_pad, dtype=np.int64)
    padded[:n] = arr

    sharding = NamedSharding(mesh, P(axis))
    roots = np.asarray(
        make_sharded_subtree_roots(mesh, axis)(
            jax.device_put(padded, sharding))
    )

    # top of the tree on host: log2(n_dev) levels over the shard roots
    level = [_words_to_bytes(roots[i]) for i in range(n_dev)]
    while len(level) > 1:
        level = [
            sha256(level[i] + level[i + 1]) for i in range(0, len(level), 2)
        ]
    node = level[0]

    # extend with zero-subtrees to the limit depth, then mix in the length
    chunks_hashed = n_pad // 4
    depth = (chunks_hashed - 1).bit_length()
    limit_chunks = (limit * 8 + 31) // 32
    limit_depth = max((limit_chunks - 1).bit_length(), 0)
    for d in range(depth, limit_depth):
        node = sha256(node + ZERO_HASHES[d])
    return sha256(node + len(arr).to_bytes(8, "little") + b"\x00" * 24)
