"""Sharded BLS batch verification: the pairing-product check split over a
device mesh along the batch axis.

The block-processing workload is B independent aggregate checks (SURVEY
§2.7: "#1 TPU target"; reference workload phase0/beacon-chain.md:1807-1833
— one FastAggregateVerify per attestation).  Each item's Miller loop +
final exponentiation is a self-contained limb program with NO cross-item
data flow, so the scale-out seam is pure data parallelism: shard the [K,
B, ...] limb tensors on B, run the whole pipeline per shard, gather the
[B] verdict bits.  The only collective is the implicit output gather —
exactly the shape that rides ICI for free.

Bit-exactness vs the host oracle is pinned by tests/test_sharded_lanes.py
and executed in the driver's multichip dryrun (__graft_entry__).
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, PartitionSpec as P

from consensus_specs_tpu.ops.bls_jax import pairing
from consensus_specs_tpu.ops.jax_compat import shard_map

# compiled per (mesh, axis): jit keys on callable identity, so a fresh
# wrapper per call would recompile the Miller-loop pipeline every time
_SHARDED_CHECK_CACHE: dict = {}
_SHARDED_PARTIALS_CACHE: dict = {}


def make_sharded_pairs_check(mesh: Mesh, axis: str = "v"):
    """Compile prod_k e(P_k, Q_k) == 1 per item, batch axis sharded.

    Returns fn(px, py, qx, qy) -> bool [B]; px, py are [K, B, 16] and
    qx, qy [K, B, 2, 16] Montgomery limb tensors (bls_jax marshalling),
    B divisible by the mesh size.
    """
    key = (mesh, axis)
    fn = _SHARDED_CHECK_CACHE.get(key)
    if fn is not None:
        return fn

    def body(px, py, qx, qy):
        f = pairing._miller_product(px, py, qx, qy)
        return pairing.final_exp_is_one_traced(f)

    fn = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(None, axis), P(None, axis),
                      P(None, axis), P(None, axis)),
            out_specs=P(axis),
            # the Miller loop's fori_loop carries have no replication
            # rule; every in/out spec is explicit so nothing rides on the
            # checker
            check_rep=False,
        )
    )
    _SHARDED_CHECK_CACHE[key] = fn
    return fn


def sharded_batch_fast_aggregate_verify(
    mesh: Mesh,
    pubkeys_lists: Sequence[Sequence[bytes]],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> List[bool]:
    """FastAggregateVerify for B items with the pairing batch sharded over
    the mesh.  Host marshalling is the SAME code path as the single-device
    backend (bls_jax.marshal_fast_aggregate_items); infinity-carrying
    items (no affine limb form) drop to the host oracle per the bls_jax
    policy; the rest are padded with a copy of the first item up to a
    mesh-size multiple and decided in one sharded device program."""
    from consensus_specs_tpu.crypto.bls.pairing import pairings_are_identity
    from consensus_specs_tpu.ops.bls_jax import (
        _g1_coords,
        _g2_coords,
        limbs,
        marshal_fast_aggregate_items,
    )

    results, todo = marshal_fast_aggregate_items(
        pubkeys_lists, messages, signatures)
    clean = []
    for b, pairs in todo:
        if any(p.is_infinity() or q.is_infinity() for p, q in pairs):
            results[b] = bool(pairings_are_identity(pairs))
        else:
            clean.append((b, pairs))
    if not clean:
        return results

    D = int(np.prod(mesh.devices.shape))
    n = len(clean)
    padded = [pairs for _, pairs in clean]
    while len(padded) % D:
        padded.append(padded[0])
    # K comes from the marshalled pairs themselves (FastAggregateVerify
    # always yields 2 — e(pk_agg, H(m)) · e(-G1, sig) — but the device
    # program is shaped by whatever the marshaller produced, not by a
    # hardcoded constant that could silently drift from it)
    K = len(padded[0])
    assert all(len(ps) == K for ps in padded), (
        "sharded pairing batch requires a uniform pair count per item; got "
        f"{sorted({len(ps) for ps in padded})}")
    Bp = len(padded)
    px = np.zeros((K, Bp, limbs.N_LIMBS), dtype=np.int64)
    py = np.zeros_like(px)
    qx = np.zeros((K, Bp, 2, limbs.N_LIMBS), dtype=np.int64)
    qy = np.zeros_like(qx)
    for b, ps in enumerate(padded):
        for k, (p, q) in enumerate(ps):
            px[k, b], py[k, b] = _g1_coords(p)
            qx[k, b], qy[k, b] = _g2_coords(q)
    check = make_sharded_pairs_check(mesh)
    verdicts = np.asarray(check(px, py, qx, qy))  # host-sync: per-block verdicts readback
    for (b, _), v in zip(clean, verdicts[:n]):
        results[b] = bool(v)
    return results


# ---------------------------------------------------------------------------
# Pairing-lane chunks: ONE product, its lanes split over the mesh
# ---------------------------------------------------------------------------
# The batch verifier's MSM-folded interior reduces a whole block to a
# SINGLE multi-pairing — one lane per unique message plus the folded
# signature lane — so the multi-chip seam is no longer B independent
# checks but the lanes of one product.  Mirror of the native kernel's
# chunk-parallel miller_loop_product: each device runs the shared-squaring
# Miller chain of its contiguous lane chunk, the partial Fp12 products
# multiply in FIXED chunk-index order, and ONE final exponentiation
# decides the whole product.  Squaring distributes over products, so the
# chunked result is bit-identical to the one-chain product wherever the
# chunk boundaries fall.


def make_sharded_lane_partials(mesh: Mesh, axis: str = "v"):
    """Compile the per-chunk partial Miller product, chunk axis sharded.

    Returns fn(px, py, qx, qy) -> f [D, 6, 2, 16]: px, py are [C, D, 16]
    and qx, qy [C, D, 2, 16] Montgomery limb tensors where chunk d owns C
    lanes; D divisible by the mesh size.  f[d] is the conjugated Miller
    value of chunk d's lane product (conjugation is the p^6 Frobenius, a
    ring automorphism, so per-chunk conjugates compose under the merge
    multiply)."""
    key = (mesh, axis)
    fn = _SHARDED_PARTIALS_CACHE.get(key)
    if fn is not None:
        return fn

    fn = jax.jit(
        shard_map(
            pairing._miller_product,
            mesh=mesh,
            in_specs=(P(None, axis), P(None, axis),
                      P(None, axis), P(None, axis)),
            out_specs=P(axis),
            # same fori_loop-carry caveat as make_sharded_pairs_check
            check_rep=False,
        )
    )
    _SHARDED_PARTIALS_CACHE[key] = fn
    return fn


def sharded_pairing_lanes_check(mesh: Mesh, pairs) -> bool:
    """prod_i e(P_i, Q_i) == 1, the lanes of ONE pairing product split
    into contiguous chunks over the mesh.

    ``pairs`` is a sequence of (G1 Point, G2 Point) lanes — the shape the
    folded batch verifier emits (unique-message lanes + the signature
    lane).  Infinity lanes contribute the identity and are dropped on the
    host.  Ragged lane counts are padded up to a chunks-times-lanes
    rectangle with self-canceling lanes (m-1 copies of e(G, H) and one
    e([-(m-1)]G, H): their product is exactly 1, so the verdict is
    untouched no matter which chunks the pads land in)."""
    from consensus_specs_tpu.crypto.bls.curve import (
        g1_generator,
        g2_generator,
    )
    from consensus_specs_tpu.ops.bls_jax import _g1_coords, _g2_coords, limbs

    lanes = [(p, q) for p, q in pairs
             if not (p.is_infinity() or q.is_infinity())]
    if not lanes:
        return True  # empty product
    D = int(np.prod(mesh.devices.shape))
    C = -(-len(lanes) // D)  # lanes per chunk
    m = C * D - len(lanes)
    if m == 1:
        # a single non-trivial lane cannot be the identity; widen the
        # chunks so the pad group has >= 2 lanes to cancel within
        C += 1
        m += D
    if m:
        G, H = g1_generator(), g2_generator()
        lanes += [(G, H)] * (m - 1) + [(-G.mul(m - 1), H)]
    px = np.zeros((C, D, limbs.N_LIMBS), dtype=np.int64)
    py = np.zeros_like(px)
    qx = np.zeros((C, D, 2, limbs.N_LIMBS), dtype=np.int64)
    qy = np.zeros_like(qx)
    for l, (p, q) in enumerate(lanes):
        d, c = divmod(l, C)  # chunk d owns lanes [d*C, (d+1)*C)
        px[c, d], py[c, d] = _g1_coords(p)
        qx[c, d], qy[c, d] = _g2_coords(q)
    partials = make_sharded_lane_partials(mesh)(px, py, qx, qy)
    # fixed chunk-index merge order, then the single shared final exp
    f = partials[0]
    for d in range(1, D):
        f = pairing._mul12(f, partials[d])
    return bool(pairing.final_exp_is_one(f[None])[0])
