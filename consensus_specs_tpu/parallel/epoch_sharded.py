"""Sharded epoch step: attestation deltas + balance update + merkle lanes
over a device mesh.

The validator axis shards across devices (``axis "v"``); the only
cross-shard traffic is:

  * psum of the three component attesting balances (scalars),
  * all_gather of (proposer-index, credit) pairs for the inclusion-delay
    proposer rewards — proposers live on arbitrary shards,
  * the SHA-256 chunk lanes hash locally (tensor-parallel) and the layer
    digests stay sharded for the next tree level.

Collectives ride ICI on a real pod; the same code runs on the test
harness's 8-device virtual CPU mesh (tests/conftest.py) and via the
driver's ``dryrun_multichip``.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_specs_tpu.ops.jax_compat import shard_map
from consensus_specs_tpu.ops.sha256_jax import sha256_block64

jax.config.update("jax_enable_x64", True)


def _local_deltas(eff, eligible, src, tgt, head, delay, att_bal, scalars):
    """Per-shard deltas given globally-reduced attesting balances.
    ``att_bal`` is [3] (source, target, head)."""
    (total_balance, sqrt_total, finality_delay, brf, brpe, prq, ipq,
     min_leak, ebi) = [scalars[i] for i in range(9)]

    base_reward = eff * brf // sqrt_total // brpe
    proposer_reward = base_reward // prq
    is_leak = finality_delay > min_leak

    rewards = jnp.zeros_like(eff)
    penalties = jnp.zeros_like(eff)
    total_incr = total_balance // ebi
    for k, part in enumerate((src, tgt, head)):
        att_incr = jnp.maximum(att_bal[k], ebi) // ebi
        comp_reward = jnp.where(
            is_leak, base_reward, base_reward * att_incr // total_incr)
        rewards = rewards + jnp.where(eligible & part, comp_reward, 0)
        penalties = penalties + jnp.where(eligible & ~part, base_reward, 0)

    max_attester_reward = base_reward - proposer_reward
    rewards = rewards + jnp.where(src, max_attester_reward // delay, 0)

    leak_base = brpe * base_reward - proposer_reward
    leak_extra = eff * finality_delay // ipq
    penalties = penalties + jnp.where(
        is_leak & eligible, leak_base + jnp.where(~tgt, leak_extra, 0), 0)

    return rewards, penalties, jnp.where(src, proposer_reward, 0)


def make_sharded_epoch_step(mesh: Mesh, axis: str = "v"):
    """Build the jitted, mesh-sharded epoch step.

    Step signature (all arrays sharded over ``axis`` except scalars):
      (balances, eff, eligible, src, tgt, head, delay, proposer, scalars)
        -> (new_balances, layer_digests)
    """
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P(axis), P(axis),
                  P(axis), P(axis), P()),
        out_specs=(P(axis), P(axis)),
    )
    def step(balances, eff, eligible, src, tgt, head, delay, proposer, scalars):
        # ---- global attesting balances: local partial sums -> psum ----
        local_bal = jnp.stack([
            jnp.sum(jnp.where(src, eff, 0)),
            jnp.sum(jnp.where(tgt, eff, 0)),
            jnp.sum(jnp.where(head, eff, 0)),
        ])
        att_bal = jax.lax.psum(local_bal, axis_name=axis)

        rewards, penalties, prop_credit = _local_deltas(
            eff, eligible, src, tgt, head, delay, att_bal=att_bal, scalars=scalars)

        # ---- proposer rewards: gather (global index, credit) pairs ----
        shard_idx = jax.lax.axis_index(axis)
        local_n = eff.shape[0]
        global_idx_base = shard_idx * local_n
        all_prop = jax.lax.all_gather(proposer, axis_name=axis)       # [D, n]
        all_credit = jax.lax.all_gather(prop_credit, axis_name=axis)  # [D, n]
        flat_prop = all_prop.reshape(-1)
        flat_credit = all_credit.reshape(-1)
        in_shard = (flat_prop >= global_idx_base) & (flat_prop < global_idx_base + local_n)
        local_slot = jnp.where(in_shard, flat_prop - global_idx_base, 0)
        rewards = rewards.at[local_slot].add(jnp.where(in_shard, flat_credit, 0))

        # ---- apply balance update (spec: increase/decrease_balance) ----
        new_balances = balances + rewards
        new_balances = jnp.where(
            penalties > new_balances, 0, new_balances - penalties)

        # ---- merkleize the local balance lanes (packed uint64 chunks) ----
        # 4 balances per 32-byte chunk; pairs of chunks -> 64-byte blocks.
        # Each device hashes its own lanes; digests stay sharded.
        lanes = new_balances.astype(jnp.uint64)
        assert local_n % 8 == 0, (
            "per-shard lane count must be a multiple of 8 (whole 64-byte "
            "merkle blocks); use shard_delta_inputs to pad")
        n_blocks = local_n // 8
        lo = (lanes & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (lanes >> jnp.uint64(32)).astype(jnp.uint32)
        # little-endian uint64 serialization -> big-endian word view
        words = jnp.stack([_bswap32(lo), _bswap32(hi)], axis=-1).reshape(-1)
        words = words[: n_blocks * 16].reshape(n_blocks, 16)
        digests = sha256_block64(words)  # [n_blocks, 8] uint32

        return new_balances, digests.reshape(-1)

    return jax.jit(step)


def _bswap32(x):
    x = ((x & jnp.uint32(0x00FF00FF)) << 8) | ((x >> 8) & jnp.uint32(0x00FF00FF))
    return ((x << 16) | (x >> 16)).astype(jnp.uint32)


def shard_delta_inputs(mesh: Mesh, inp, balances: np.ndarray, axis: str = "v"):
    """Pad arrays to a multiple of the mesh size and device_put with the
    sharding the step expects.  Returns (args tuple, original n)."""
    n_dev = mesh.devices.size
    n = inp.effective_balance.shape[0]
    # lanes must be a multiple of 8*n_dev so each shard hashes whole blocks
    mult = 8 * n_dev
    n_pad = ((n + mult - 1) // mult) * mult

    def pad(a, fill=0):
        if n_pad == a.shape[0]:
            return a
        return np.concatenate([a, np.full(n_pad - a.shape[0], fill, dtype=a.dtype)])

    sharding = NamedSharding(mesh, P(axis))
    rep = NamedSharding(mesh, P())

    scalars = np.array([
        inp.total_balance, inp.sqrt_total, inp.finality_delay,
        inp.base_reward_factor, inp.base_rewards_per_epoch,
        inp.proposer_reward_quotient, inp.inactivity_penalty_quotient,
        inp.min_epochs_to_inactivity_penalty, inp.effective_balance_increment,
    ], dtype=np.int64)

    args = (
        jax.device_put(pad(balances.astype(np.int64)), sharding),
        jax.device_put(pad(inp.effective_balance), sharding),
        jax.device_put(pad(inp.eligible), sharding),
        jax.device_put(pad(inp.source_part), sharding),
        jax.device_put(pad(inp.target_part), sharding),
        jax.device_put(pad(inp.head_part), sharding),
        jax.device_put(pad(inp.incl_delay, fill=1), sharding),
        jax.device_put(pad(inp.incl_proposer), sharding),
        jax.device_put(scalars, rep),
    )
    return args, n
