"""Distributed execution: device meshes, sharded kernels, collectives.

The reference has no intra-process parallelism (SURVEY §2.7) — its
networking layer is wire-format documentation only.  Here the
data-parallel axes the protocol actually exposes (validator registry,
pubkey sets, merkle chunk lanes) are sharded over a
``jax.sharding.Mesh`` with XLA collectives (psum / all_gather) riding
ICI; multi-host scale-out uses the same code over a DCN-spanning mesh
via ``jax.distributed``.
"""
from .mesh import build_mesh, default_mesh

__all__ = ["build_mesh", "default_mesh"]
