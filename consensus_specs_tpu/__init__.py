"""tpu-consensus-specs: a TPU-native framework with the capabilities of the
Ethereum consensus-specs executable pyspec.

Layer map (mirrors SURVEY.md):
  ssz/        SSZ type system + persistent-Merkle-tree hashing (remerkleable-equivalent,
              reference seam: tests/core/pyspec/eth2spec/utils/ssz/ssz_impl.py:8-25)
  crypto/     BLS12-381 (pure-Python oracle, reference seam: eth2spec/utils/bls.py)
              and SHA-256 backends
  ops/        JAX/XLA/Pallas kernels: layer-batched SHA-256 merkleization,
              vmapped BLS field arithmetic, sharded G1 MSM
  parallel/   jax.sharding Mesh / shard_map utilities (ICI collectives)
  specs/      executable fork specs phase0 -> altair -> bellatrix -> capella (+eip4844)
  config/     presets (mainnet/minimal) + runtime configs
  test_infra/ decorator DSL + helper library (reference: eth2spec/test/context.py)
  gen/        cross-client test-vector generators (reference: eth2spec/gen_helpers)
"""

__version__ = "0.1.0"


# NOTE: no eager imports here — pure-SSZ consumers must not pay the jax
# import cost.  Kernel modules call _jaxcache.configure() after importing
# jax themselves.
