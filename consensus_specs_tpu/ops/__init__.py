"""Batched/vectorized compute kernels.

Host (numpy) and device (JAX/Pallas) implementations of the hot
operations the sequential spec calls into: swap-or-not shuffling,
layer-batched SHA-256 merkleization, batched BLS verification, and
vectorized epoch processing.  Everything here is semantics-preserving:
each kernel has a scalar spec twin and a differential test.
"""
