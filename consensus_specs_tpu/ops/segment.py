"""Segment-sum primitive for per-node vote aggregation.

The batched fork-choice engine reduces hundreds of thousands of
``(validator_index, target_node, effective_balance)`` vote rows into one
weight delta per proto-array node.  That reduction is a segment sum over
the node axis — the same shape as the participation scatters in
``ops/epoch_jax.py`` (``np.add.at`` over dense arrays) and
``jax.ops.segment_sum`` on device.

The host path is the default: vote batches are memory-light (int64
triples) and arrive host-side, so a single ``np.add.at`` dispatch wins on
this tunnel for the same reason the epoch pipeline runs on the host XLA
backend (docs/architecture.md).  ``CSTPU_SEGMENT_BACKEND=jax`` flips the
reduction onto the accelerator unchanged; the differential test
(tests/spec/phase0/fork_choice/test_engine_differential.py) pins the two
backends element-identical.
"""
from __future__ import annotations

import os

import numpy as np


def segment_sum(values: np.ndarray, segment_ids: np.ndarray,
                num_segments: int, backend: str | None = None) -> np.ndarray:
    """``out[s] = sum(values[segment_ids == s])`` as int64 [num_segments].

    ``segment_ids`` must be in ``[0, num_segments)``; callers filter
    negative ids (the proto-array's "no node" sentinel) beforehand.
    """
    if backend is None:
        backend = os.environ.get("CSTPU_SEGMENT_BACKEND", "numpy")
    values = np.asarray(values, dtype=np.int64)
    segment_ids = np.asarray(segment_ids, dtype=np.int64)
    if backend == "jax":
        import jax
        import jax.numpy as jnp

        from consensus_specs_tpu import _jaxcache

        jax.config.update("jax_enable_x64", True)
        _jaxcache.configure()
        # host-sync: staged view — callers consume segment counts on host
        return np.asarray(jax.ops.segment_sum(
            jnp.asarray(values), jnp.asarray(segment_ids),
            num_segments=num_segments))
    out = np.zeros(num_segments, dtype=np.int64)
    np.add.at(out, segment_ids, values)
    return out
