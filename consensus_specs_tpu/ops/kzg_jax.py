"""Batched G1 scalar multiplication for KZG commitments (BASELINE config
5; reference analogue: the G1 MSM inside eip4844's blob_to_kzg,
specs/eip4844/beacon-chain.md:112-120).

Device layout: N lanes of (affine point, 255-bit scalar); a lax.scan over
bit-planes runs the double-and-add for ALL lanes at once on the Montgomery
limb representation from ops/bls_jax.  The per-lane products return to the
host, which finishes the (tiny) N-way sum on the oracle curve — the
O(N * 255) field work is the device's, the O(N) tail is not worth a
collective.  Multi-chip: shard the lane axis with shard_map (the scan body
is purely elementwise over lanes, so sharding is trivial).

Degenerate add cases (equal-x, infinity) are resolved branchlessly with
canonical-equality selects, so structured scalars cannot corrupt lanes.
Differential test vs the host oracle: tests/crypto/test_kzg.py.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from consensus_specs_tpu.ops.jax_compat import shard_map

from consensus_specs_tpu.crypto.bls.curve import Point, g1_infinity
from consensus_specs_tpu.crypto.fr import R as FR_ORDER

from .bls_jax import limbs

_N_BITS = 255


def _sel(mask, a, b):
    """mask [...] selecting between limb arrays [..., 16]."""
    return jnp.where(mask[..., None], a, b)


def _is_zero(a):
    return limbs.is_zero_canonical(limbs.canonical(a))


def _eq(a, b):
    return limbs.eq_canonical(limbs.canonical(a), limbs.canonical(b))


def _dbl(X, Y, Z):
    """Jacobian doubling (dbl-2009-l), lazy adds + renorm; Z=0 stays 0."""
    mul, rn = limbs.mul, limbs.renorm
    A = mul(X, X)
    B = mul(Y, Y)
    C = mul(B, B)
    D = rn(2 * (mul(rn(X + B), rn(X + B)) - A - C))
    E = rn(3 * A)
    F = mul(E, E)
    X3 = rn(F - 2 * D)
    Y3 = rn(mul(E, rn(D - X3)) - 8 * C)
    Z3 = rn(2 * mul(Y, Z))
    return X3, Y3, Z3


def _madd(X1, Y1, Z1, x2, y2):
    """Mixed add (madd-2007-bl) of jacobian (X1,Y1,Z1) + affine (x2,y2),
    with branchless handling of P1 = inf, equal-x double, and inverse."""
    mul, rn = limbs.mul, limbs.renorm
    Z1Z1 = mul(Z1, Z1)
    U2 = mul(x2, Z1Z1)
    S2 = mul(mul(y2, Z1), Z1Z1)
    H = rn(U2 - X1)
    HH = mul(H, H)
    I = rn(4 * HH)
    J = mul(H, I)
    r = rn(2 * (S2 - Y1))
    V = mul(X1, I)
    rr = mul(r, r)
    X3 = rn(rr - J - 2 * V)
    Y3 = rn(mul(r, rn(V - X3)) - 2 * mul(Y1, J))
    Z3 = rn(mul(rn(Z1 + H), rn(Z1 + H)) - Z1Z1 - HH)

    p1_inf = _is_zero(Z1)
    h_zero = _is_zero(H)
    r_zero = _is_zero(r)
    # equal-x, equal-y: the true result is double(P1)
    dX, dY, dZ = _dbl(X1, Y1, Z1)
    # equal-x, opposite-y: infinity (Z=0)
    zero = jnp.zeros_like(Z3)

    X3 = _sel(h_zero & r_zero, dX, _sel(h_zero & ~r_zero, X3, X3))
    Y3 = _sel(h_zero & r_zero, dY, Y3)
    Z3 = _sel(h_zero & r_zero, dZ, _sel(h_zero & ~r_zero, zero, Z3))

    one = jnp.broadcast_to(jnp.asarray(limbs.MONT_ONE_LIMBS), x2.shape)
    X3 = _sel(p1_inf, x2, X3)
    Y3 = _sel(p1_inf, y2, Y3)
    Z3 = _sel(p1_inf, one, Z3)
    return X3, Y3, Z3


# Device choice: the scan is int64 limb arithmetic — TPU hardware emulates
# int64 on 32-bit lanes and the axon-tunneled chip faults on the 4096-lane
# scan, so the host CPU XLA backend is the default.  CSTPU_KZG_BACKEND=tpu
# opts into the accelerator (appropriate on non-tunneled TPU VMs with an
# int32-limb rework).
import os as _os


def _msm_device():
    want = _os.environ.get("CSTPU_KZG_BACKEND", "cpu")
    try:
        return jax.local_devices(backend=want)[0]
    except RuntimeError:
        return None


@jax.jit
def _msm_lanes(px, py, bits):
    """Per-lane scalar multiplication.

    px, py: [N, 16] affine Montgomery limbs; bits: [255, N] int32
    (MSB-first).  Returns jacobian [N, 16] triples."""
    # derive the carry from the inputs (px * 0, not jnp.zeros): under
    # shard_map the scan carry must share the inputs' varying-axes type
    X = px * 0
    Y = px * 0 + jnp.asarray(limbs.MONT_ONE_LIMBS)
    Z = px * 0  # infinity

    def step(carry, bit_row):
        X, Y, Z = carry
        X, Y, Z = _dbl(X, Y, Z)
        aX, aY, aZ = _madd(X, Y, Z, px, py)
        m = bit_row > 0
        return (_sel(m, aX, X), _sel(m, aY, Y), _sel(m, aZ, Z)), None

    (X, Y, Z), _ = jax.lax.scan(step, (X, Y, Z), bits)
    return limbs.canonical(X), limbs.canonical(Y), limbs.canonical(Z)


def _to_bits(scalars: Sequence[int]) -> np.ndarray:
    out = np.zeros((_N_BITS, len(scalars)), dtype=np.int32)
    for lane, s in enumerate(scalars):
        s %= FR_ORDER
        for b in range(_N_BITS):
            out[_N_BITS - 1 - b, lane] = (s >> b) & 1
    return out


def _points_to_limbs(points: Sequence[Point]) -> tuple:
    px = np.zeros((len(points), limbs.N_LIMBS), dtype=np.int64)
    py = np.zeros_like(px)
    for i, p in enumerate(points):
        x, y = p.to_affine()
        px[i] = limbs.host_to_mont(x.n)
        py[i] = limbs.host_to_mont(y.n)
    return px, py


def _limbs_to_points(X: np.ndarray, Y: np.ndarray, Z: np.ndarray) -> List[Point]:
    """Jacobian Montgomery limb triples -> host curve points (shared by the
    single-device and mesh-sharded lanes)."""
    from consensus_specs_tpu.crypto.bls.curve import B_G1
    from consensus_specs_tpu.crypto.bls.fields import Fq

    out = []
    for i in range(X.shape[0]):
        z = limbs.host_from_mont(Z[i])
        if z == 0:
            out.append(g1_infinity())
            continue
        out.append(Point(
            Fq(limbs.host_from_mont(X[i])),
            Fq(limbs.host_from_mont(Y[i])),
            Fq(z),
            B_G1,
        ))
    return out


def batch_scalar_mul(points: Sequence[Point], scalars: Sequence[int]) -> List[Point]:
    """[k_i * P_i] for all lanes in one device dispatch."""
    assert len(points) == len(scalars)
    px, py = _points_to_limbs(points)
    bits = _to_bits(scalars)
    dev = _msm_device()
    put = (lambda a: jax.device_put(a, dev)) if dev is not None else jnp.asarray
    X, Y, Z = (np.asarray(a) for a in _msm_lanes(put(px), put(py), put(bits)))
    return _limbs_to_points(X, Y, Z)


def msm(points: Sequence[Point], scalars: Sequence[int]) -> Point:
    """sum_i k_i * P_i: device per-lane products, host tail sum."""
    acc = g1_infinity()
    for p in batch_scalar_mul(points, scalars):
        acc = acc + p
    return acc


# --- mesh-sharded lane (the TP axis of SURVEY §2.7: one large MSM split
# over cores) ----------------------------------------------------------------


# jitted shard_map wrappers cached per (mesh, axis): jit keys on callable
# identity, so rebuilding the wrapper per call would recompile the 255-step
# scan every time
_SHARDED_MSM_CACHE: dict = {}


def _sharded_msm_fn(mesh, axis: str):
    key = (mesh, axis)
    fn = _SHARDED_MSM_CACHE.get(key)
    if fn is None:
        from jax.sharding import PartitionSpec as P

        fn = jax.jit(shard_map(
            _msm_lanes,
            mesh=mesh,
            in_specs=(P(axis), P(axis), P(None, axis)),
            out_specs=(P(axis), P(axis), P(axis)),
        ))
        _SHARDED_MSM_CACHE[key] = fn
    return fn


def sharded_batch_scalar_mul(mesh, points: Sequence[Point],
                             scalars: Sequence[int],
                             axis: str = "v") -> List[Point]:
    """[k_i * P_i] with the lane axis sharded over a device mesh.

    The scan body is purely elementwise over lanes, so the shard_map needs
    no collectives — each device runs its lanes' double-and-add chains;
    the host gathers and tail-sums.  Lane count must divide by the mesh
    size.  Bit-exact vs batch_scalar_mul/host (tests/test_sharded_lanes.py;
    executed in the driver's multichip dryrun)."""
    assert len(points) == len(scalars)
    D = int(np.prod(mesh.devices.shape))
    assert len(points) % D == 0, f"{len(points)} lanes over {D} devices"
    px, py = _points_to_limbs(points)
    bits = _to_bits(scalars)
    X, Y, Z = (np.asarray(a) for a in _sharded_msm_fn(mesh, axis)(px, py, bits))
    return _limbs_to_points(X, Y, Z)


def sharded_msm(mesh, points: Sequence[Point], scalars: Sequence[int]) -> Point:
    """Mesh-sharded MSM: per-device lane products + host tail sum."""
    acc = g1_infinity()
    for p in sharded_batch_scalar_mul(mesh, points, scalars):
        acc = acc + p
    return acc
