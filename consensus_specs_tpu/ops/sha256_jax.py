"""Batched SHA-256 for merkle layer hashing, in JAX.

Each merkle parent is SHA-256 over exactly 64 bytes (two child roots) —
one message block plus one constant padding block (reference semantics:
eth2spec/utils/hash_function.py:8; merkleize rules
ssz/simple-serialize.md:210-248).  The kernel runs the 64-round
compression across all lanes of a layer at once: bitwise rotes/adds in
int32 lanes map directly onto the TPU VPU, and XLA fuses the whole
round chain into a few kernels.  Lanes are padded to the next power of
two to bound recompilation.

This module is also the building block for the sharded merkleization
path in ``parallel/`` (layer split across devices, no collectives
needed until the subtree roots merge).
"""
from __future__ import annotations

from typing import List

import numpy as np

import jax
import jax.numpy as jnp

# SHA-256 round constants
_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)

# Message schedule of the constant second (padding) block for a 64-byte
# message: 0x80, zeros, 64-bit bit-length (512).
_PAD_BLOCK = np.zeros(16, dtype=np.uint32)
_PAD_BLOCK[0] = 0x80000000
_PAD_BLOCK[15] = 512


def _rotr(x, n):
    return (x >> n) | (x << (32 - n))


def _compress(state, w):
    """One SHA-256 compression over a [N,16] uint32 block batch.
    ``state`` is a tuple of 8 [N] uint32 vectors.

    Rounds run under ``lax.fori_loop`` — one compiled body instead of a
    64×-unrolled graph (compile time matters: the dryrun and tests
    compile on CPU; runtime stays lane-vectorized either way).
    """
    n = w.shape[0]
    k = jnp.asarray(_K, dtype=jnp.uint32)

    # message schedule: extend [N,16] -> [N,64]
    ws0 = jnp.concatenate([w, jnp.zeros((n, 48), dtype=jnp.uint32)], axis=1)

    def sched_body(i, ws):
        w15 = ws[:, i - 15]
        w2 = ws[:, i - 2]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> 10)
        return ws.at[:, i].set(ws[:, i - 16] + s0 + ws[:, i - 7] + s1)

    ws = jax.lax.fori_loop(16, 64, sched_body, ws0)

    def round_body(i, carry):
        a, b, c, d, e, f, g, h = carry
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        temp1 = h + S1 + ch + k[i] + ws[:, i]
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        temp2 = S0 + maj
        return (temp1 + temp2, a, b, c, d + temp1, e, f, g)

    out = jax.lax.fori_loop(0, 64, round_body, state)
    return tuple(x + y for x, y in zip(state, out))


def sha256_block64(blocks: jnp.ndarray) -> jnp.ndarray:
    """SHA-256 of N 64-byte messages given as [N, 16] big-endian uint32.
    Returns [N, 8] uint32 digests."""
    n = blocks.shape[0]
    # the `+ blocks[:, 0] * 0` ties the init state to the input so its
    # sharding axes (vma) match the loop carry under shard_map
    zero = blocks[:, 0] * 0
    init = tuple(jnp.full((n,), _H0[i], dtype=jnp.uint32) + zero for i in range(8))
    mid = _compress(init, blocks)
    pad = (jnp.broadcast_to(jnp.asarray(_PAD_BLOCK, dtype=jnp.uint32), (n, 16))
           + zero[:, None])
    out = _compress(mid, pad)
    return jnp.stack(out, axis=1)


# jax.jit caches one executable per input shape on this single callable
_jit_block64 = jax.jit(sha256_block64)


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1


def _next_pow4(n: int) -> int:
    p = _next_pow2(n)
    return p if (p.bit_length() - 1) % 2 == 0 else p * 2


def hash_blocks_u32(words: np.ndarray) -> np.ndarray:
    """Hash [N,16] big-endian uint32 words to [N,8] digests (numpy in/out)."""
    n = words.shape[0]
    n_pad = _next_pow2(n)  # pad lanes to powers of two to bound recompiles
    if n_pad != n:
        words = np.vstack([words, np.zeros((n_pad - n, 16), dtype=np.uint32)])
    out = np.asarray(_jit_block64(jnp.asarray(words)))  # host-sync: digest batch returns to the byte pipeline
    return out[:n]


def hash_layer_via(hash_words, blocks: List[bytes]) -> List[bytes]:
    """Shared byte<->uint32 packing for layer-hash backends: `hash_words`
    maps [N,16] big-endian uint32 words to [N,8] digests (numpy in/out)."""
    n = len(blocks)
    if n == 0:
        return []
    words = np.frombuffer(b"".join(blocks), dtype=">u4").reshape(n, 16).astype(np.uint32)
    out = hash_words(words)
    flat = out.astype(">u4").tobytes()
    return [flat[i * 32:(i + 1) * 32] for i in range(n)]


def hash_layer(blocks: List[bytes]) -> List[bytes]:
    """Backend for ssz.hashing: list of 64-byte inputs -> 32-byte digests."""
    return hash_layer_via(hash_blocks_u32, blocks)


# -- whole-wave-schedule hashing (single device program) --------------------
#
# Per-layer dispatch pays one host<->device round trip per tree level —
# ruinous when the link is a tunnel and latency/bandwidth dominate.  The
# TPU-native shape for a full merkle (sub)tree is ONE program: upload the
# known child digests once, run every wave as a gather + compress stage
# inside a single jit (the level loop is unrolled at trace time — wave
# sizes are static), download every produced digest once.


def _run_waves(known, lefts, rights):
    """known: [K,8] u32 digest pool seed.  lefts/rights: per-wave int32
    index arrays into the pool (known rows, then each prior wave's rows).
    One preallocated pool buffer; each wave writes its digests in place
    (XLA turns the dynamic_update_slice chain into in-place updates).
    Returns all wave outputs concatenated [sum(n_k), 8]."""
    total = known.shape[0] + sum(left.shape[0] for left in lefts)
    pool = jnp.zeros((total, 8), dtype=jnp.uint32)
    pool = jax.lax.dynamic_update_slice(pool, known, (0, 0))
    offset = known.shape[0]
    outs = []
    for left, right in zip(lefts, rights):
        blocks = jnp.concatenate([pool[left], pool[right]], axis=1)  # [n,16]
        digest = sha256_block64(blocks)
        outs.append(digest)
        pool = jax.lax.dynamic_update_slice(pool, digest, (offset, 0))
        offset += left.shape[0]
    return jnp.concatenate(outs, axis=0)


_jit_run_waves = jax.jit(_run_waves)


def hash_waves_u32(known: np.ndarray, waves) -> np.ndarray:
    """Run a whole wave schedule on device in one dispatch.

    ``known``: [K,8] big-endian-word digests (the already-rooted children).
    ``waves``: list of (left_idx, right_idx) int32 numpy arrays indexing
    the pool, where pool rows are ``known`` rows followed by every prior
    wave's outputs in schedule order.  Returns all outputs concatenated.

    jax.jit caches one executable per (K, wave-size...) signature; the
    byte-level wrapper pads both to powers of two so differently-sized
    dirty subtrees bucket into a bounded set of compiled shapes.
    """
    lefts = tuple(jnp.asarray(w[0]) for w in waves)
    rights = tuple(jnp.asarray(w[1]) for w in waves)
    out = _jit_run_waves(jnp.asarray(known), lefts, rights)
    return np.asarray(out)  # host-sync: wave digests return to the byte pipeline


def hash_waves(known: List[bytes], waves) -> List[bytes]:
    """Byte-level wrapper: ``known`` is 32-byte digests; ``waves`` is
    (left_idx, right_idx) pairs indexing [known | outputs-so-far].
    Returns the concatenated 32-byte outputs of every wave.

    The known pool and the first wave are padded to powers of FOUR, later
    waves follow a monotone halving envelope, and the wave count is padded
    to a multiple of four with dummy single-lane waves (padding lanes hash
    row 0 and are discarded) — so the jit signature, and therefore the
    compile count, is a small bounded set per tree magnitude rather than
    one executable per exact dirty pattern."""
    k = len(known)
    k_pad = _next_pow4(max(k, 1))
    words = np.zeros((k_pad, 8), dtype=np.uint32)
    if k:
        words[:k] = np.frombuffer(b"".join(known), dtype=">u4").reshape(k, 8)

    sizes = [len(w[0]) for w in waves]
    # Monotone halving envelope: wave k is padded to
    # max(pow2(size_k), previous_pad // 2).  Merkle wave schedules are
    # (near-)halving ladders, so the whole padded-size tuple — and hence
    # the jit signature — is determined by (first-wave pow2, wave count):
    # arbitrary dirty patterns of similar magnitude share one executable
    # instead of recompiling per exact shape.
    padded = []
    for s in sizes:
        if padded:
            p = max(_next_pow2(max(s, 1)), padded[-1] // 2)
        else:
            p = _next_pow4(max(s, 1))
        padded.append(p)
    # padded pool row of each unpadded output position: known padding sits
    # at rows k..k_pad-1, wave k's rows start where wave k-1's padded rows end
    trans = np.empty(max(sum(sizes), 1), dtype=np.int64)
    base, up = k_pad, 0
    for size, psize in zip(sizes, padded):
        trans[up:up + size] = base + np.arange(size)
        up += size
        base += psize

    padded_waves = []
    for (left, right), size, psize in zip(waves, sizes, padded):
        lp = np.zeros(psize, dtype=np.int32)
        rp = np.zeros(psize, dtype=np.int32)
        for src, dst in ((left, lp), (right, rp)):
            src = np.asarray(src, dtype=np.int64)
            dst[:size] = np.where(src < k, src, trans[np.maximum(src - k, 0)])
        padded_waves.append((lp, rp))
    # dummy single-lane waves pad the count to a multiple of 4 (their
    # outputs land after every real wave's rows and are never extracted)
    dummy = (np.zeros(1, dtype=np.int32), np.zeros(1, dtype=np.int32))
    while len(padded_waves) % 4:
        padded_waves.append(dummy)
        padded.append(1)

    out = hash_waves_u32(words, padded_waves)
    flat = out.astype(">u4").tobytes()
    result = []
    base = 0
    for size, psize in zip(sizes, padded):
        result.extend(flat[(base + i) * 32:(base + i + 1) * 32]
                      for i in range(size))
        base += psize
    return result
