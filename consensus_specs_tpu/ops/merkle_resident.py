"""Device-resident merkleization of hot SSZ subtrees.

The round-2 measurement showed the device hasher losing to hashlib 8.5x —
not on compute, but because every dirty-subtree pass shipped chunk data
through the ~6 MB/s tunnel.  The TPU-native fix is residency: the packed
leaf data of a hot subtree (balances is the canonical case — every epoch
rewrites all of it) lives on the device across calls.  Mutations are
expressed as device ops on the resident buffers, the whole subtree
reduction runs as ONE jit dispatch, and only the 32-byte root crosses the
link.  The host keeps the rest of the state tree and folds the subtree
root into the state root with a handful of hashlib hashes.

Reference seams: eth2spec/utils/ssz/ssz_impl.py:12-13 (hash_tree_root =
backing.merkle_root()); merkleization rules ssz/simple-serialize.md:210-248
(pack / merkleize / mix_in_length).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from consensus_specs_tpu.ssz.node import (
    BranchNode,
    LeafNode,
    Node,
    ZERO_HASHES,
    merkle_root,
    uint_to_leaf,
)

from .sha256_jax import sha256_block64


def _byteswap32(x: jnp.ndarray) -> jnp.ndarray:
    """uint32 little-endian value -> big-endian word (SHA-256 reads bytes)."""
    return ((x >> 24) | ((x >> 8) & 0x0000FF00)
            | ((x << 8) & 0x00FF0000) | (x << 24))


def _reduce_to_root(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """Full merkle reduction of a packed uint64 leaf array, on device.

    ``lo``/``hi`` are the 32-bit halves of the (LE) uint64 values, length a
    multiple of 4 and a power of two in chunks.  Returns the [8] uint32
    (big-endian word) root of the 2^k-chunk subtree.
    """
    # chunk words: per value the LE bytes are lo,hi; as BE words that is
    # byteswap(lo), byteswap(hi); 4 values -> 8 words -> one 32-byte chunk
    words = jnp.stack([_byteswap32(lo), _byteswap32(hi)], axis=1).reshape(-1, 8)
    level = words
    while level.shape[0] > 1:
        level = sha256_block64(level.reshape(level.shape[0] // 2, 16))
    return level[0]


_jit_reduce = jax.jit(_reduce_to_root)


def _add_u64(lo, hi, dlo, dhi):
    """(lo,hi) += (dlo,dhi) with carry, element-wise on uint32 halves."""
    new_lo = lo + dlo
    carry = (new_lo < lo).astype(jnp.uint32)
    return new_lo, hi + dhi + carry


_jit_add = jax.jit(_add_u64)


class ResidentPackedU64List:
    """A packed ``List[uint64, limit]`` whose leaves live on the device.

    upload() once; mutate via apply_add()/set_values() (device ops); root()
    runs the reduction on device and downloads 32 bytes.  ``root()`` output
    is bit-identical to ``hash_tree_root`` of the equivalent SSZ list.
    """

    def __init__(self, limit: int, device=None):
        assert limit % 4 == 0
        self.limit = limit
        self.chunk_limit = limit // 4
        self.contents_depth = max((self.chunk_limit - 1).bit_length(), 0)
        self.device = device if device is not None else jax.devices()[0]
        self.length = 0
        self._lo: Optional[jnp.ndarray] = None
        self._hi: Optional[jnp.ndarray] = None

    # -- data movement -------------------------------------------------------

    def upload(self, values: np.ndarray) -> None:
        """One-time (or rare) bulk upload of the full value array."""
        values = np.ascontiguousarray(values, dtype="<u8")
        self.length = len(values)
        n_chunks = max((self.length + 3) // 4, 1)
        n_pad = 1 << (n_chunks - 1).bit_length() if n_chunks > 1 else 1
        padded = np.zeros(n_pad * 4, dtype="<u8")
        padded[: self.length] = values
        as_u32 = padded.view("<u4").reshape(-1, 2)
        self._lo = jax.device_put(
            jnp.asarray(as_u32[:, 0].copy()), self.device)
        self._hi = jax.device_put(
            jnp.asarray(as_u32[:, 1].copy()), self.device)

    def to_numpy(self) -> np.ndarray:
        """Download the current values (verification/debug path)."""
        lo = np.asarray(self._lo)[: self.length].astype(np.uint64)
        hi = np.asarray(self._hi)[: self.length].astype(np.uint64)
        return lo | (hi << np.uint64(32))

    # -- device-side mutation ------------------------------------------------

    def apply_add(self, delta) -> None:
        """Add ``delta`` (scalar or per-element array, may be negative) to
        every live element, entirely on device.  A jnp array delta (the
        epoch-kernel-output case) never leaves the device; a scalar ships
        only its two u32 halves; a numpy vector is the one case that pays
        an upload."""
        assert self._lo is not None, "upload() before apply_add()"
        dlo = jnp.zeros_like(self._lo)
        dhi = jnp.zeros_like(self._hi)
        if isinstance(delta, jnp.ndarray):
            # >> 32 must be an arithmetic shift so negative deltas carry a
            # sign-extended high half; only int64 guarantees that here
            assert delta.dtype == jnp.int64, (
                f"jnp delta must be int64, got {delta.dtype}")
            dlo = dlo.at[: self.length].set(delta.astype(jnp.uint32))
            dhi = dhi.at[: self.length].set((delta >> 32).astype(jnp.uint32))
        elif np.isscalar(delta):
            half = np.array([delta], dtype=np.int64).view("<u4")
            dlo = dlo.at[: self.length].set(np.uint32(half[0]))
            dhi = dhi.at[: self.length].set(np.uint32(half[1]))
        else:
            halves = np.ascontiguousarray(
                np.asarray(delta, dtype=np.int64)).view("<u4").reshape(-1, 2)
            dlo = dlo.at[: self.length].set(jnp.asarray(halves[:, 0].copy()))
            dhi = dhi.at[: self.length].set(jnp.asarray(halves[:, 1].copy()))
        self._lo, self._hi = _jit_add(self._lo, self._hi, dlo, dhi)

    # -- roots ---------------------------------------------------------------

    def contents_subtree_root(self) -> bytes:
        """Root of the real-data subtree (padded to its power of two)."""
        assert self._lo is not None, "upload() before reading roots"
        # host-sync: staged view — the resident tree's single root readback
        out = np.asarray(_jit_reduce(self._lo, self._hi))
        return out.astype(">u4").tobytes()

    def as_backing_node(self) -> Node:
        """The list's backing as a fixed-root node pair (contents, length)
        — spliceable into a host-side container backing."""
        import hashlib

        node_root = self.contents_subtree_root()
        n_chunks_padded = max(len(self._lo) // 4, 1)
        level = (n_chunks_padded - 1).bit_length()
        for d in range(level, self.contents_depth):
            node_root = hashlib.sha256(node_root + ZERO_HASHES[d]).digest()
        return BranchNode(LeafNode(node_root), uint_to_leaf(self.length))

    def root(self) -> bytes:
        """Full SSZ ``hash_tree_root`` of the list (zero-hash fold up to
        the virtual depth, then mix in the length)."""
        return merkle_root(self.as_backing_node())


# ---------------------------------------------------------------------------
# Shipping-path integration: "residency composes"
# ---------------------------------------------------------------------------
# The epoch transition's process_rewards_and_penalties rewrites the WHOLE
# balances vector.  The fused program below runs the deltas kernel, the
# clipped balance update AND the full merkle reduction of the new vector as
# ONE jit dispatch — the kernel's output is consumed by the hasher on
# device, never shipped back up for hashing.  The spec substitution
# (specs/builder.py _install_phase0_epoch_kernel) then memoizes the
# device-computed subtree root into the freshly written host backing via
# memoize_packed_u64_contents_root(), so the next hash_tree_root(state) —
# the per-slot state-root cache of process_slots included — skips the
# balances subtree entirely.  Reference seam unchanged:
# eth2spec/utils/ssz/ssz_impl.py:8-13.

RESIDENT_MIN = 16_384  # below this, host hashing of the subtree is trivial


def resident_device():
    """Device for the fused epoch+merkle program, or None to stay on the
    host path.  Policy (CSTPU_RESIDENT_MERKLE): '0' = off, '1' = force on
    the default backend, 'auto' (default) = engage only when the default
    JAX backend is an accelerator.  Measured basis for 'auto'
    (BENCH_DETAILS hash_tree_root_state): the XLA SHA-256 reduction beats
    hashlib on the TPU but loses ~4x on the host CPU backend."""
    import os

    mode = os.environ.get("CSTPU_RESIDENT_MERKLE", "auto")
    if mode == "0":
        return None
    try:
        dev = jax.devices()[0]
    except Exception:
        return None
    if mode == "1":
        return dev
    return dev if dev.platform != "cpu" else None


def _fused_epoch_balances(balances, eff, eligible, source_part, target_part,
                          head_part, incl_delay, incl_proposer, scalars):
    from .epoch_jax import _deltas_kernel

    rewards, penalties = _deltas_kernel(
        eff, eligible, source_part, target_part, head_part,
        incl_delay, incl_proposer, scalars)
    increased = balances + rewards
    new_bal = jnp.where(penalties > increased, 0, increased - penalties)
    # padded lanes carry balance 0 and zero deltas, so the zero-padded
    # chunk tail the SSZ merkleization demands is preserved
    lo = new_bal.astype(jnp.uint32)
    hi = (new_bal >> 32).astype(jnp.uint32)
    return new_bal, _reduce_to_root(lo, hi)


_jit_fused = jax.jit(_fused_epoch_balances)


def fused_epoch_balance_update(inp, balances: np.ndarray, device,
                               device_cache: tuple = None):
    """DeltaInputs + current balances -> (new balances [n] int64 numpy,
    padded-subtree root bytes).  One device program; the root reduction
    reads the kernel's output vector in place.  ``device_cache`` (from
    ``epoch_jax.delta_device_cache``) serves the registry-derived inputs
    as resident device buffers — uploaded once per registry version
    (stf/columns.device_buffer), not per epoch call."""
    n = balances.shape[0]
    n_pad = max(4, 1 << (n - 1).bit_length() if n > 1 else 1)

    def pad(a, fill=0):
        if n_pad == n:
            return a
        return np.concatenate([a, np.full(n_pad - n, fill, dtype=a.dtype)])

    from .epoch_jax import delta_scalars

    scalars = delta_scalars(inp)

    put = lambda a: jax.device_put(a, device)  # noqa: E731
    if device_cache is not None:
        from consensus_specs_tpu.stf import columns

        # backend identity bound by device_buffer (appends str(device));
        # these keys deliberately match attestation_deltas' so the two
        # paths share uploads on the same backend
        root, prev_epoch = device_cache
        eff_dev = columns.device_buffer(
            (root, "eff_pad", n_pad),
            lambda: pad(inp.effective_balance), device=device)
        elig_dev = columns.device_buffer(
            (root, "eligible_pad", prev_epoch, n_pad),
            lambda: pad(inp.eligible.astype(bool)), device=device)
    else:
        eff_dev = put(pad(inp.effective_balance))
        elig_dev = put(pad(inp.eligible.astype(bool)))
    new_bal, root_words = _jit_fused(
        put(pad(balances.astype(np.int64))),
        eff_dev,
        elig_dev,
        put(pad(inp.source_part.astype(bool))),
        put(pad(inp.target_part.astype(bool))),
        put(pad(inp.head_part.astype(bool))),
        put(pad(inp.incl_delay, fill=1)),
        put(pad(inp.incl_proposer)),
        put(scalars),
    )
    stats["fused_epoch_updates"] += 1
    # host-sync: staged view — fused-update outputs (new balances + root)
    # pulled once per epoch; ROADMAP item 3 keeps balances resident
    return (np.asarray(new_bal)[:n],
            np.asarray(root_words).astype(">u4").tobytes())


def memoize_packed_u64_contents_root(view, padded_root: bytes) -> None:
    """Install a device-computed subtree root into a packed uint64 List
    view freshly rewritten by bulk.set_packed_uint64_from_numpy: fold the
    padded-power-of-two root up to the list's virtual contents depth with
    shared zero hashes (a handful of host hashes) and memoize it on the
    still-unhashed contents node.  hash_tree_root output is bit-identical
    to the host path — pinned by tests/test_merkle_resident.py."""
    import hashlib

    cls = type(view)
    backing = view.get_backing()
    contents = backing.left
    if contents._root is not None:
        return  # already hashed (nothing to save)
    n = len(view)
    n_chunks = max((n + 3) // 4, 1)
    n_chunks_pad = 1 << (n_chunks - 1).bit_length() if n_chunks > 1 else 1
    root = padded_root
    for d in range((n_chunks_pad - 1).bit_length(), cls.contents_depth()):
        root = hashlib.sha256(root + ZERO_HASHES[d]).digest()
    contents._root = root
    stats["roots_memoized"] += 1


# engagement counters (bench/tests introspection)
stats = {"fused_epoch_updates": 0, "roots_memoized": 0}


def replace_field_subtree(backing: Node, field_index: int, depth: int,
                          new_node: Node) -> Node:
    """Rebuild the spine of a container backing with one field's subtree
    replaced (everything else structurally shared)."""
    if depth == 0:
        return new_node
    bit = (field_index >> (depth - 1)) & 1
    assert isinstance(backing, BranchNode)
    if bit:
        return BranchNode(backing.left, replace_field_subtree(
            backing.right, field_index, depth - 1, new_node))
    return BranchNode(replace_field_subtree(
        backing.left, field_index, depth - 1, new_node), backing.right)
