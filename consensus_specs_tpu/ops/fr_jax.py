"""Fr (BLS12-381 scalar field) NTT on device limbs, shardable across a
mesh along the chunk axis.

This is the SP/CP axis of SURVEY §2.7: the DAS erasure-coding FFT
(das/das-core.md:90-128) runs over polynomial chunks; sharding splits the
chunk axis across devices with a four-step (Bailey) decomposition —
local M-point NTTs per device, a twiddle stage, then the cross-device
D-point combine over an ``all_gather`` collective (ICI traffic only).

Field arithmetic mirrors the lazy-reduction Montgomery-limb design of
``ops/bls_jax/limbs.py`` (26-bit int64 limb lanes, only ``mul`` reduces),
specialized to the 255-bit scalar modulus: 10 limbs, R = 2^260.
Differential oracle: ``crypto/fr.py`` (host python-int NTT) — parity is
bit-exact, tests/test_fr_jax.py.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from consensus_specs_tpu import _jaxcache
from consensus_specs_tpu.crypto.fr import R as FR_MOD
from consensus_specs_tpu.crypto.fr import root_of_unity

jax.config.update("jax_enable_x64", True)
_jaxcache.configure()

N_LIMBS = 10
LIMB_BITS = 26
_B = LIMB_BITS
_MASK = (1 << LIMB_BITS) - 1
R_BITS = N_LIMBS * LIMB_BITS  # 260

R_INT = (1 << R_BITS) % FR_MOD
R2_INT = (R_INT * R_INT) % FR_MOD
N0INV_INT = (-pow(FR_MOD, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)


def int_to_limbs(x: int) -> np.ndarray:
    assert 0 <= x < (1 << R_BITS)
    out = np.zeros(N_LIMBS, dtype=np.int64)
    for i in range(N_LIMBS):
        out[i] = (x >> (LIMB_BITS * i)) & _MASK
    return out


def limbs_to_int(a) -> int:
    arr = np.asarray(a, dtype=object)
    return int(sum(int(arr[..., i]) << (LIMB_BITS * i) for i in range(N_LIMBS)))


_P_LIMBS = int_to_limbs(FR_MOD)
_P_LIMBS_J = jnp.asarray(_P_LIMBS)
_N0INV = np.int64(N0INV_INT)

# REDC static tables (same construction as bls_jax/limbs.py)
_P_SHIFTED = np.zeros((N_LIMBS, 2 * N_LIMBS), dtype=np.int64)
for _i in range(N_LIMBS):
    _P_SHIFTED[_i, _i:_i + N_LIMBS] = _P_LIMBS
_P_SHIFTED_J = jnp.asarray(_P_SHIFTED)
_E = np.zeros((2 * N_LIMBS + 1, 2 * N_LIMBS), dtype=np.int64)
for _i in range(2 * N_LIMBS):
    _E[_i, _i] = 1
_E_J = jnp.asarray(_E)
_CONV_IDX = np.zeros((N_LIMBS, 2 * N_LIMBS), dtype=np.int64)
for _r in range(N_LIMBS):
    for _c in range(2 * N_LIMBS):
        _CONV_IDX[_r, _c] = (_c - _r) % (2 * N_LIMBS)
_CONV_IDX_J = jnp.asarray(_CONV_IDX)


def mul(a, b):
    """Montgomery multiply-reduce over [..., N_LIMBS] int64 lanes."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    outer = a[..., :, None] * b[..., None, :]
    padded = jnp.concatenate(
        [outer, jnp.zeros(shape[:-1] + (N_LIMBS, N_LIMBS), jnp.int64)], axis=-1)
    idx = jnp.broadcast_to(_CONV_IDX_J, shape[:-1] + (N_LIMBS, 2 * N_LIMBS))
    rolled = jnp.take_along_axis(padded, idx, axis=-1)
    T = jnp.sum(rolled, axis=-2)
    for i in range(N_LIMBS):
        m = ((T[..., i] & _MASK) * _N0INV) & _MASK
        T = T + m[..., None] * _P_SHIFTED_J[i]
        carry = T[..., i] >> _B
        T = T + carry[..., None] * _E_J[i + 1]
    r = T[..., N_LIMBS:] + _P_LIMBS_J
    digits = []
    c = jnp.zeros_like(r[..., 0])
    for i in range(N_LIMBS):
        v = r[..., i] + c
        digits.append(v & _MASK)
        c = v >> _B
    return jnp.stack(digits, axis=-1)


def host_to_mont(x: int) -> np.ndarray:
    return int_to_limbs(x * R_INT % FR_MOD)


def host_from_mont(a) -> int:
    return limbs_to_int(np.asarray(a)) * pow(R_INT, -1, FR_MOD) % FR_MOD


def canonical_int(a) -> int:
    """Host: limb array (possibly lazy/Montgomery-reduced) -> canonical
    python int mod r, leaving Montgomery form."""
    return host_from_mont(a) % FR_MOD


# ---------------------------------------------------------------------------
# NTT
# ---------------------------------------------------------------------------


def _bit_reverse_perm(n: int) -> np.ndarray:
    bits = n.bit_length() - 1
    out = np.zeros(n, dtype=np.int64)
    for i in range(n):
        out[i] = int(format(i, f"0{bits}b")[::-1], 2) if bits else 0
    return out


def _ntt_host_precompute(n: int, w: int):
    """Index + twiddle schedule for the in-place iterative NTT."""
    perm = _bit_reverse_perm(n)
    schedule = []
    size = 2
    while size <= n:
        w_size = pow(w, n // size, FR_MOD)
        top = np.arange(n).reshape(n // size, size)[:, : size // 2].reshape(-1)
        bot = top + size // 2
        tw = np.stack([host_to_mont(pow(w_size, j, FR_MOD))
                       for j in range(size // 2)])
        tws = np.tile(tw, (n // size, 1))
        schedule.append((top, bot, tws))
        size *= 2
    return perm, schedule


def _ntt_apply(x, schedule):
    """Run the precomputed butterfly schedule over [n, N_LIMBS] limbs."""
    for top, bot, tws in schedule:
        t = mul(jnp.asarray(tws), x[jnp.asarray(bot)])
        e = x[jnp.asarray(top)]
        x = x.at[jnp.asarray(top)].set(e + t)
        x = x.at[jnp.asarray(bot)].set(e - t)
        # keep limbs in signed-lazy range; mul renormalizes next stage
    return x


# Lazy-carry magnitude bound: _ntt_apply accumulates e±t without per-stage
# renormalization while mul drops its final carry, so worst-case entry
# magnitudes grow ~2 canonical units per stage.  REDC stays exact for
# inputs above -2^260; 2^14 stages of growth keeps the worst case inside
# that window with margin (the eip4844/DAS sizes are <= 2^12, verified
# bit-exact to 2^12 in tests).  Larger transforms would need renormalizing
# lanes every few stages.
MAX_NTT_SIZE = 1 << 14


def ntt_device(values: Sequence[int], inv: bool = False) -> List[int]:
    """Single-device NTT over Fr, bit-exact vs crypto.fr.fft."""
    n = len(values)
    assert n & (n - 1) == 0
    assert n <= MAX_NTT_SIZE, (
        f"transform size {n} exceeds the lazy-carry bound {MAX_NTT_SIZE}")
    w = root_of_unity(n)
    if inv:
        w = pow(w, FR_MOD - 2, FR_MOD)
    perm, schedule = _ntt_host_precompute(n, w)
    x = np.stack([host_to_mont(int(v) % FR_MOD) for v in values])[perm]
    out = np.asarray(_ntt_apply(jnp.asarray(x), schedule))
    res = [canonical_int(out[i]) for i in range(n)]
    if inv:
        n_inv = pow(n, FR_MOD - 2, FR_MOD)
        res = [v * n_inv % FR_MOD for v in res]
    return res


# ---------------------------------------------------------------------------
# sharded four-step NTT (chunk axis across the mesh)
# ---------------------------------------------------------------------------
#
# N = D*M with device d holding the strided residue class x[M*n1 + ...].
# Decompose n = D*n1 + n2 (n2 = device), k = M*k2 + k1:
#   Y[M*k2 + k1] = sum_{n2} w_D^{n2 k2} * ( w_N^{n2 k1} * Z[n2, k1] )
#   Z[n2, k1]   = M-point NTT over n1 of x[D*n1 + n2]     (local, per device)
# Stage 3 (the D-point combine over k2) runs after an all_gather of the
# twiddled Z rows — D is the mesh size, so this is a small ICI collective.


def sharded_ntt(values: Sequence[int], mesh, axis_name: str = None) -> List[int]:
    """NTT of ``values`` sharded over ``mesh``'s devices along the chunk
    axis; returns canonical ints, bit-exact vs crypto.fr.fft."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    if axis_name is None:
        axis_name = mesh.axis_names[0]

    n = len(values)
    d = mesh.devices.size
    assert n % d == 0 and n & (n - 1) == 0
    assert n <= MAX_NTT_SIZE, (
        f"transform size {n} exceeds the lazy-carry bound {MAX_NTT_SIZE}")
    m = n // d
    w_n = root_of_unity(n)
    w_d = pow(w_n, m, FR_MOD)

    perm, schedule = _ntt_host_precompute(m, pow(w_n, d, FR_MOD))

    # rows[n2] = bit-reversed x[D*n1 + n2]; the row axis is the sharded axis
    rows = np.zeros((d, m, N_LIMBS), dtype=np.int64)
    for n2 in range(d):
        strided = [host_to_mont(int(values[d * n1 + n2]) % FR_MOD)
                   for n1 in range(m)]
        rows[n2] = np.stack(strided)[perm]

    # twiddle tensor w_N^{n2*k1} and combine tensor w_D^{n2*k2}, per device
    tw = np.zeros((d, m, N_LIMBS), dtype=np.int64)
    comb = np.zeros((d, d, N_LIMBS), dtype=np.int64)
    for n2 in range(d):
        for k1 in range(m):
            tw[n2, k1] = host_to_mont(pow(w_n, n2 * k1, FR_MOD))
        for k2 in range(d):
            # device k2's combine row: w_D^{n2*k2} for every source n2
            comb[k2, n2] = host_to_mont(pow(w_d, n2 * k2, FR_MOD))

    def _shard_body(x_row, tw_row, comb_row):
        # x_row/tw_row: [1, m, NL]; comb_row: [1, d, NL]
        z = _ntt_apply(x_row[0], schedule)          # local M-point NTT
        z = mul(tw_row[0], z)                       # w_N^{n2 k1} twiddle
        allz = jax.lax.all_gather(z, axis_name)     # [d, m, NL] over ICI
        # this device's output row k2: sum_n2 w_D^{n2 k2} * allz[n2]
        acc = mul(comb_row[0][0], allz[0])
        for n2 in range(1, allz.shape[0]):
            acc = acc + mul(comb_row[0][n2], allz[n2])
        # renormalize the lazy sum so host decode sees digit-bounded limbs
        # (same signed-carry scheme as renorm in bls_jax/limbs.py)
        digits = []
        c = jnp.zeros_like(acc[..., 0])
        for i in range(N_LIMBS - 1):
            v = acc[..., i] + c
            digits.append(v & _MASK)
            c = v >> _B
        digits.append(acc[..., N_LIMBS - 1] + c)
        return jnp.stack(digits, axis=-1)[None]

    spec_sharded = NamedSharding(mesh, P(axis_name))
    fn = shard_map(
        _shard_body, mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(axis_name)),
        out_specs=P(axis_name))
    out_arr = jax.jit(fn)(
        jax.device_put(jnp.asarray(rows), spec_sharded),
        jax.device_put(jnp.asarray(tw), spec_sharded),
        jax.device_put(jnp.asarray(comb), spec_sharded))
    if jax.process_count() > 1:
        # the sharded output spans processes (a DCN mesh): gather the
        # small result rows instead of materializing non-addressable shards
        from jax.experimental import multihost_utils

        # host-sync: cross-process gather of the small NTT result rows
        out = np.asarray(multihost_utils.process_allgather(
            out_arr, tiled=True))
    else:
        out = np.asarray(out_arr)  # host-sync: NTT result rows return to the int pipeline

    result = [0] * n
    for k2 in range(d):
        for k1 in range(m):
            result[m * k2 + k1] = canonical_int(out[k2, k1])
    return result
