"""Version-compat shims for the jax API surface the package relies on.

jax < 0.5 ships ``shard_map`` under ``jax.experimental.shard_map``; newer
releases promote it to the jax root.  Every sharded module imports the
resolved symbol from here, so the fallback lives in exactly one place.
"""
from __future__ import annotations

import jax

try:  # jax < 0.5 ships shard_map under the experimental namespace
    from jax.experimental.shard_map import shard_map
except ImportError:  # promoted to the jax root in newer releases
    shard_map = jax.shard_map

__all__ = ["shard_map"]
