"""Vectorized swap-or-not shuffle (whole-permutation form).

The spec's ``compute_shuffled_index`` (reference:
specs/phase0/beacon-chain.md:760-781) maps ONE index through
``SHUFFLE_ROUND_COUNT`` rounds, costing 2 SHA-256 per round per index.
Committees need the image of *every* index, so the per-index form does
O(n · rounds) hashes with n-fold redundancy: within a round, indices
sharing ``position // 256`` share the source hash.

This module computes the full permutation in one pass: per round, one
pivot hash plus ``ceil(n/256)`` source hashes (hashlib, C speed), then a
numpy gather applies the round to all lanes at once.  For mainnet-scale
(400k validators, 90 rounds) that is ~140k hashes total instead of
~72M.  ``permutation[i] == compute_shuffled_index(i, n, seed)`` exactly;
differential-tested in tests/test_shuffle.py.
"""
from __future__ import annotations

import hashlib
from typing import Dict, Tuple

import numpy as np

_cache: Dict[Tuple[bytes, int, int], np.ndarray] = {}
_CACHE_MAX = 16


def compute_shuffle_permutation(seed: bytes, index_count: int, round_count: int) -> np.ndarray:
    """Return an int64 array p of length index_count with
    p[i] = compute_shuffled_index(i, index_count, seed)."""
    from consensus_specs_tpu import tracing

    key = (bytes(seed), int(index_count), int(round_count))
    hit = _cache.get(key)
    if hit is not None:
        tracing.count("shuffle.permutation_cache_hit")
        return hit
    tracing.count("shuffle.permutation_compute")
    n = int(index_count)
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    seed = bytes(seed)
    m = np.arange(n, dtype=np.int64)
    n_blocks = (n + 255) // 256
    block_ids = np.arange(n_blocks, dtype=np.int64)
    for rnd in range(round_count):
        rb = bytes([rnd])
        pivot = int.from_bytes(hashlib.sha256(seed + rb).digest()[:8], "little") % n
        flip = (pivot - m) % n
        position = np.maximum(m, flip)
        # one source hash per 256-index block; gather bits per lane
        src = np.frombuffer(
            b"".join(
                hashlib.sha256(seed + rb + int(b).to_bytes(4, "little")).digest()
                for b in block_ids
            ),
            dtype=np.uint8,
        ).reshape(n_blocks, 32)
        byte_vals = src[position // 256, (position % 256) // 8]
        bits = (byte_vals >> (position % 8).astype(np.uint8)) & 1
        m = np.where(bits.astype(bool), flip, m)
    if len(_cache) >= _CACHE_MAX:
        _cache.pop(next(iter(_cache)))
    m.setflags(write=False)  # shared across callers; mutation would corrupt committees
    _cache[key] = m
    return m


def committee_bounds(n_active: int, committees_per_epoch: int) -> np.ndarray:
    """Slice boundaries of every committee of an epoch over the shuffled
    permutation: ``bounds[g] : bounds[g + 1]`` is the permutation range of
    global committee index ``g`` (``(slot % SLOTS_PER_EPOCH) *
    committees_per_slot + index``), exactly the spec's ``compute_committee``
    start/end arithmetic (beacon-chain.md:944-950) evaluated for all
    committees at once."""
    g = np.arange(committees_per_epoch + 1, dtype=np.int64)
    return (n_active * g) // committees_per_epoch
