"""Pallas TPU kernel for batched SHA-256 merkle compression.

Layout is the TPU-native transpose of ops/sha256_jax.py: message words
live on SUBLANES (16 rows) and independent messages on LANES (128 per
program), so every round is a VPU-wide uint32 op with zero gathers.  The
grid walks lane-tiles of 128 messages; each program runs the full 64
unrolled rounds for its tile plus the padding-block compression (the
merkle case: one 64-byte message = two child roots).

On non-TPU backends the kernel runs in interpreter mode — bit-identical
but minutes-per-shape slow under this image's jax build, so the
differential tests (tests/test_sha256_pallas.py) auto-skip off-TPU and
opt in via CSTPU_PALLAS_TESTS=1.  Registered as the "pallas" hashing
backend: ``hashing.set_backend("pallas")``.
"""
from __future__ import annotations

from typing import List

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from consensus_specs_tpu import _jaxcache
from consensus_specs_tpu.ops.sha256_jax import (
    _H0,
    _K,
    _PAD_BLOCK,
    _next_pow2,
    hash_layer_via,
)

_jaxcache.configure()

_LANES = 128


def _ror(x, n):
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _compress_rows(state, w_rows):
    """One SHA-256 compression over 8 state rows given 16 message rows
    (each row shape [LANES], uint32).  Rounds fully unrolled."""
    a, b, c, d, e, f, g, h = state
    w = list(w_rows)
    for i in range(64):
        if i >= 16:
            s0 = _ror(w[i - 15], 7) ^ _ror(w[i - 15], 18) ^ (w[i - 15] >> jnp.uint32(3))
            s1 = _ror(w[i - 2], 17) ^ _ror(w[i - 2], 19) ^ (w[i - 2] >> jnp.uint32(10))
            w.append(w[i - 16] + s0 + w[i - 7] + s1)
        s1 = _ror(e, 6) ^ _ror(e, 11) ^ _ror(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + jnp.uint32(_K[i]) + w[i]
        s0 = _ror(a, 2) ^ _ror(a, 13) ^ _ror(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        a, b, c, d, e, f, g, h = t1 + t2, a, b, c, d + t1, e, f, g
    return tuple(x + y for x, y in zip(state, (a, b, c, d, e, f, g, h)))


def _kernel(in_ref, out_ref):
    w_rows = [in_ref[i, :] for i in range(16)]
    init = tuple(
        jnp.full((_LANES,), _H0[i], dtype=jnp.uint32) for i in range(8)
    )
    mid = _compress_rows(init, w_rows)
    pad_rows = [
        jnp.full((_LANES,), int(_PAD_BLOCK[i]), dtype=jnp.uint32)
        for i in range(16)
    ]
    out = _compress_rows(mid, pad_rows)
    for i in range(8):
        out_ref[i, :] = out[i]


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _block64_t_impl(words_t: jnp.ndarray) -> jnp.ndarray:
    """[16, N] big-endian uint32 message words -> [8, N] digests.
    N must be a multiple of 128."""
    n = words_t.shape[1]
    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((8, n), jnp.uint32),
        grid=(n // _LANES,),
        in_specs=[pl.BlockSpec((16, _LANES), lambda i: (0, i))],
        out_specs=pl.BlockSpec((8, _LANES), lambda i: (0, i)),
        interpret=_use_interpret(),
    )(words_t)


# On real TPUs the kernel compiles natively and the jit wrapper caches the
# executable per shape.  In interpreter mode (every other backend) jitting
# would lower the op-by-op emulation into an enormous XLA graph — minutes
# of compile for zero benefit — so the interpreter runs eagerly.
_block64_t_jit = jax.jit(_block64_t_impl)


def _block64_t(words_t):
    if _use_interpret():
        return _block64_t_impl(words_t)
    return _block64_t_jit(words_t)


def sha256_block64(blocks: np.ndarray) -> np.ndarray:
    """SHA-256 of N 64-byte messages given as [N, 16] big-endian uint32
    (numpy in/out); the merkle parent-digest primitive."""
    n = blocks.shape[0]
    # pad to a power-of-two multiple of the lane tile: bounded shape set
    # (each distinct shape pays a trace/compile)
    n_pad = max(_LANES, _next_pow2(n))
    words = np.zeros((n_pad, 16), dtype=np.uint32)
    words[:n] = blocks
    out = np.asarray(_block64_t(jnp.asarray(words.T)))
    return out.T[:n]


def hash_layer(blocks: List[bytes]) -> List[bytes]:
    """Hashing-backend entry: list of 64-byte blocks -> 32-byte digests."""
    return hash_layer_via(sha256_block64, blocks)
