"""Vectorized phase0 epoch rewards/penalties (attestation deltas) in JAX.

The spec computes ``get_attestation_deltas`` with nested Python loops —
O(validators × attestations) (reference: phase0/beacon-chain.md:1439-1561,
call stack SURVEY §3.2).  Here the irregular part (pending attestations →
per-validator participation flags) is flattened on host using the cached
committees, and the arithmetic — base rewards, three component deltas,
inclusion delay, inactivity leak — runs as one fused elementwise/scatter
kernel over dense arrays.  This is the natural TPU mapping: the validator
axis is the data-parallel axis (SURVEY §2.7), and the same kernel shards
over a device mesh by splitting that axis (see parallel/).

Exactness: all quantities fit comfortably in int64 for any realistic
state (effective balances ≤ 32 Gwei·1e9, registry ≤ ~2^22 today, total
balance ≤ 2^57); the differential test (tests/spec/phase0/test_epoch_kernel.py)
checks bit-equality against the sequential spec.
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)


class DeltaInputs(NamedTuple):
    """Dense per-validator inputs for the deltas kernel (all numpy)."""

    effective_balance: np.ndarray  # int64 [N] Gwei
    eligible: np.ndarray           # bool [N] active-prev or slashed-not-withdrawable
    source_part: np.ndarray        # bool [N] unslashed source attester
    target_part: np.ndarray        # bool [N] unslashed target attester
    head_part: np.ndarray          # bool [N] unslashed head attester
    incl_delay: np.ndarray         # int64 [N] min inclusion delay (source attesters)
    incl_proposer: np.ndarray      # int64 [N] proposer of that attestation
    total_balance: int             # total active balance (>= EBI)
    sqrt_total: int                # integer_squareroot(total_balance)
    finality_delay: int
    # preset constants
    base_reward_factor: int
    base_rewards_per_epoch: int
    proposer_reward_quotient: int
    inactivity_penalty_quotient: int
    min_epochs_to_inactivity_penalty: int
    effective_balance_increment: int


def extract_delta_inputs(spec, state) -> DeltaInputs:
    """Host-side flattening of state + pending attestations into arrays."""
    n = len(state.validators)
    prev_epoch = spec.get_previous_epoch(state)

    eff = np.zeros(n, dtype=np.int64)
    slashed = np.zeros(n, dtype=bool)
    active_prev = np.zeros(n, dtype=bool)
    withdrawable = np.zeros(n, dtype=np.float64)
    for i, v in enumerate(state.validators):
        eff[i] = int(v.effective_balance)
        slashed[i] = bool(v.slashed)
        active_prev[i] = spec.is_active_validator(v, prev_epoch)
        withdrawable[i] = float(int(v.withdrawable_epoch))

    eligible = active_prev | (slashed & (int(prev_epoch) + 1 < withdrawable))

    source_atts = list(spec.get_matching_source_attestations(state, prev_epoch))
    target_atts = list(spec.get_matching_target_attestations(state, prev_epoch))
    head_atts = list(spec.get_matching_head_attestations(state, prev_epoch))

    def participation(atts):
        mask = np.zeros(n, dtype=bool)
        for a in atts:
            idx = np.fromiter(
                spec.get_attesting_indices(state, a.data, a.aggregation_bits),
                dtype=np.int64,
            )
            mask[idx] = True
        return mask & ~slashed

    source_part = participation(source_atts)
    target_part = participation(target_atts)
    head_part = participation(head_atts)

    # min-inclusion-delay attestation per source attester: first minimal
    # element in list order (spec: Python min(), beacon-chain.md:1500-1505)
    incl_delay = np.full(n, np.iinfo(np.int64).max, dtype=np.int64)
    incl_proposer = np.zeros(n, dtype=np.int64)
    for a in source_atts:
        idx = np.fromiter(
            spec.get_attesting_indices(state, a.data, a.aggregation_bits),
            dtype=np.int64,
        )
        d = int(a.inclusion_delay)
        upd = d < incl_delay[idx]
        upd_idx = idx[upd]
        incl_delay[upd_idx] = d
        incl_proposer[upd_idx] = int(a.proposer_index)
    incl_delay[incl_delay == np.iinfo(np.int64).max] = 1  # unused lanes

    total_balance = int(spec.get_total_active_balance(state))
    sqrt_total = int(spec.integer_squareroot(spec.uint64(total_balance)))
    finality_delay = int(prev_epoch - state.finalized_checkpoint.epoch)

    return DeltaInputs(
        effective_balance=eff,
        eligible=eligible,
        source_part=source_part,
        target_part=target_part,
        head_part=head_part,
        incl_delay=incl_delay,
        incl_proposer=incl_proposer,
        total_balance=total_balance,
        sqrt_total=sqrt_total,
        finality_delay=finality_delay,
        base_reward_factor=int(spec.BASE_REWARD_FACTOR),
        base_rewards_per_epoch=int(spec.BASE_REWARDS_PER_EPOCH),
        proposer_reward_quotient=int(spec.PROPOSER_REWARD_QUOTIENT),
        inactivity_penalty_quotient=int(spec.INACTIVITY_PENALTY_QUOTIENT),
        min_epochs_to_inactivity_penalty=int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY),
        effective_balance_increment=int(spec.EFFECTIVE_BALANCE_INCREMENT),
    )


def _deltas_kernel(eff, eligible, source_part, target_part, head_part,
                   incl_delay, incl_proposer, scalars):
    """Pure-JAX deltas. ``scalars`` is an int64 vector:
    [total_balance, sqrt_total, finality_delay, BRF, BRPE, PRQ, IPQ,
     MIN_EPOCHS_LEAK, EBI]."""
    (total_balance, sqrt_total, finality_delay, brf, brpe, prq, ipq,
     min_leak, ebi) = [scalars[i] for i in range(9)]

    n = eff.shape[0]
    base_reward = eff * brf // sqrt_total // brpe
    proposer_reward = base_reward // prq
    is_leak = finality_delay > min_leak

    rewards = jnp.zeros(n, dtype=jnp.int64)
    penalties = jnp.zeros(n, dtype=jnp.int64)

    total_incr = total_balance // ebi
    for part in (source_part, target_part, head_part):
        attesting_balance = jnp.maximum(jnp.sum(jnp.where(part, eff, 0)), ebi)
        att_incr = attesting_balance // ebi
        full_reward = base_reward  # during leak: full compensation
        scaled_reward = base_reward * att_incr // total_incr
        comp_reward = jnp.where(is_leak, full_reward, scaled_reward)
        rewards = rewards + jnp.where(eligible & part, comp_reward, 0)
        penalties = penalties + jnp.where(eligible & ~part, base_reward, 0)

    # inclusion delay: attester reward plus scatter-add of proposer rewards
    max_attester_reward = base_reward - proposer_reward
    rewards = rewards + jnp.where(source_part, max_attester_reward // incl_delay, 0)
    prop_credit = jnp.where(source_part, proposer_reward, 0)
    rewards = rewards.at[incl_proposer].add(prop_credit)

    # inactivity leak
    leak_base = brpe * base_reward - proposer_reward
    leak_extra = eff * finality_delay // ipq
    penalties = penalties + jnp.where(
        is_leak & eligible, leak_base + jnp.where(~target_part, leak_extra, 0), 0)

    return rewards, penalties


def epoch_step(balances, eff, eligible, source_part, target_part, head_part,
               incl_delay, incl_proposer, scalars):
    """Single-device full epoch step: deltas -> balance update.

    This is the jittable "forward step" the graft entry exposes; the
    mesh-sharded variant lives in parallel/epoch_sharded.py.
    """
    rewards, penalties = _deltas_kernel(
        eff, eligible, source_part, target_part, head_part,
        incl_delay, incl_proposer, scalars)
    new_balances = balances + rewards
    return jnp.where(penalties > new_balances, 0, new_balances - penalties)


# single jitted callable; XLA caches per input shape
_jit_kernel = jax.jit(_deltas_kernel)


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1


def attestation_deltas(inp: DeltaInputs):
    """Compute (rewards, penalties) int64 arrays from DeltaInputs."""
    n = inp.effective_balance.shape[0]
    n_pad = _next_pow2(n)

    def pad(a, fill=0):
        if n_pad == n:
            return a
        return np.concatenate([a, np.full(n_pad - n, fill, dtype=a.dtype)])

    scalars = np.array([
        inp.total_balance, inp.sqrt_total, inp.finality_delay,
        inp.base_reward_factor, inp.base_rewards_per_epoch,
        inp.proposer_reward_quotient, inp.inactivity_penalty_quotient,
        inp.min_epochs_to_inactivity_penalty, inp.effective_balance_increment,
    ], dtype=np.int64)

    rewards, penalties = _jit_kernel(
        jnp.asarray(pad(inp.effective_balance)),
        jnp.asarray(pad(inp.eligible.astype(bool))),
        jnp.asarray(pad(inp.source_part.astype(bool))),
        jnp.asarray(pad(inp.target_part.astype(bool))),
        jnp.asarray(pad(inp.head_part.astype(bool))),
        jnp.asarray(pad(inp.incl_delay, fill=1)),
        jnp.asarray(pad(inp.incl_proposer)),
        jnp.asarray(scalars),
    )
    return np.asarray(rewards)[:n], np.asarray(penalties)[:n]


def attestation_deltas_for_state(spec, state):
    """End-to-end: state -> (rewards, penalties) numpy arrays."""
    return attestation_deltas(extract_delta_inputs(spec, state))
