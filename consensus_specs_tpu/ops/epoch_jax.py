"""Vectorized phase0 epoch rewards/penalties (attestation deltas) in JAX.

The spec computes ``get_attestation_deltas`` with nested Python loops —
O(validators × attestations) (reference: phase0/beacon-chain.md:1439-1561,
call stack SURVEY §3.2).  Here the irregular part (pending attestations →
per-validator participation flags) is flattened on host using the cached
committees, and the arithmetic — base rewards, three component deltas,
inclusion delay, inactivity leak — runs as one fused elementwise/scatter
kernel over dense arrays.  This is the natural TPU mapping: the validator
axis is the data-parallel axis (SURVEY §2.7), and the same kernel shards
over a device mesh by splitting that axis (see parallel/).

Exactness: all quantities fit comfortably in int64 for any realistic
state (effective balances ≤ 32 Gwei·1e9, registry ≤ ~2^22 today, total
balance ≤ 2^57); the differential test (tests/spec/phase0/test_epoch_kernel.py)
checks bit-equality against the sequential spec.
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from consensus_specs_tpu import _jaxcache

jax.config.update("jax_enable_x64", True)
_jaxcache.configure()


# --- registry columns (cached off the validators tree root) ----------------

# validator_columns saturates FAR_FUTURE_EPOCH (2^64-1) at int64 max; any
# comparison against FAR_FUTURE therefore tests >= _SAT
_SAT = 2**63 - 1

_COLS_CACHE = None  # RootKeyedCache(4), built lazily (bulk imports jax-free)


def registry_columns(state):
    """Cached numpy columns of the validator registry, keyed by the
    registry's tree root (mutation -> new root -> automatic refresh)."""
    from consensus_specs_tpu.ssz import bulk

    global _COLS_CACHE
    if _COLS_CACHE is None:
        _COLS_CACHE = bulk.RootKeyedCache(4)
    return _COLS_CACHE.get(state.validators, bulk.validator_columns)


def active_mask(cols, epoch: int) -> np.ndarray:
    """is_active_validator over columns: activation <= epoch < exit."""
    return (cols["activation_epoch"] <= epoch) & (epoch < cols["exit_epoch"])


class DeltaInputs(NamedTuple):
    """Dense per-validator inputs for the deltas kernel (all numpy)."""

    effective_balance: np.ndarray  # int64 [N] Gwei
    eligible: np.ndarray           # bool [N] active-prev or slashed-not-withdrawable
    source_part: np.ndarray        # bool [N] unslashed source attester
    target_part: np.ndarray        # bool [N] unslashed target attester
    head_part: np.ndarray          # bool [N] unslashed head attester
    incl_delay: np.ndarray         # int64 [N] min inclusion delay (source attesters)
    incl_proposer: np.ndarray      # int64 [N] proposer of that attestation
    total_balance: int             # total active balance (>= EBI)
    sqrt_total: int                # integer_squareroot(total_balance)
    finality_delay: int
    # preset constants
    base_reward_factor: int
    base_rewards_per_epoch: int
    proposer_reward_quotient: int
    inactivity_penalty_quotient: int
    min_epochs_to_inactivity_penalty: int
    effective_balance_increment: int


def attesting_indices(spec, state, data, bits, plan_ctx=None) -> np.ndarray:
    """``get_attesting_indices`` for a state-resident pending attestation
    as one numpy gather off the cached whole-epoch committee geometry
    (stf/attestations.committee_context) — the spec call materializes the
    committee as a Python list per attestation, which made the epoch's
    pending-attestation scans the block-path replay's second-largest cost.
    With ``plan_ctx`` (a per-SCAN ``{epoch: plan ctx key}`` memo — pass a
    fresh ``{}`` per scan) the attestation-plan memo is probed first
    (ISSUE 8): the pendings ARE the aggregates the block path already
    resolved, so the content-addressed hit replaces even the gather +
    bits unpack (callers are set-semantics scatters, so the plan's sorted
    order is equivalent).  ``data`` was validated at inclusion, so
    ``compute_epoch_at_slot(slot)`` indexes a real committee.
    Element-set equality with the spec call is pinned by
    tests/spec/phase0/test_epoch_kernel.py."""
    from consensus_specs_tpu.ssz import bulk
    from consensus_specs_tpu.stf.attestations import (
        cached_plan_attesters,
        committee_context,
        plan_ctx_key,
    )

    slot = int(data.slot)
    epoch = slot // int(spec.SLOTS_PER_EPOCH)
    if plan_ctx is not None:
        pk = plan_ctx.get(epoch)
        if pk is None:
            pk = plan_ctx[epoch] = plan_ctx_key(spec, state, epoch)
        planned = cached_plan_attesters(pk, data, bits)
        if planned is not None:
            return planned
    ctx = committee_context(spec, state, epoch)
    committee = ctx.committee(slot, int(data.index))
    return committee[bulk.bitlist_to_numpy(bits)]


def extract_delta_inputs(spec, state) -> DeltaInputs:
    """Host-side flattening of state + pending attestations into arrays.

    Registry columns come straight off the Merkle backing in one tree walk
    (ssz/bulk.py) — the per-validator view loop this replaces was the real
    end-to-end bottleneck at 400k validators."""
    n = len(state.validators)
    prev_epoch = int(spec.get_previous_epoch(state))

    cols = registry_columns(state)
    eff = cols["effective_balance"]
    slashed = cols["slashed"]
    # is_active_validator: activation_epoch <= epoch < exit_epoch
    active_prev = (cols["activation_epoch"] <= prev_epoch) & (
        prev_epoch < cols["exit_epoch"]
    )
    eligible = active_prev | (
        slashed & (prev_epoch + 1 < cols["withdrawable_epoch"])
    )

    # ONE fused pass over the epoch's pending attestations replaces the
    # spec's three get_matching_* scans + three participation scans + the
    # inclusion-delay walk (seven list traversals, each rebuilding the
    # same ``a.data`` views).  Semantics per scan are the spec's exactly:
    # every attestation of the epoch matches source (the matching_source
    # selector), target matches on ``get_block_root(state, epoch)``
    # (computed at the first attestation — the spec's listcomp evaluates
    # it per item, so first-use raises identically and an empty list
    # never evaluates it), head refines target on the per-slot block root
    # (memoized per slot), and min-inclusion-delay keeps the FIRST
    # minimal element in list order (strict <, beacon-chain.md:1500-1505).
    if prev_epoch == int(spec.get_current_epoch(state)):
        epoch_atts = state.current_epoch_attestations
    else:
        epoch_atts = state.previous_epoch_attestations
    plan_ctx: dict = {}   # per-epoch plan-key memo for attesting_indices
    head_roots: dict = {}  # slot -> block root (typically two slots/epoch)
    expected_target = None
    source_part = np.zeros(n, dtype=bool)
    target_part = np.zeros(n, dtype=bool)
    head_part = np.zeros(n, dtype=bool)
    incl_delay = np.full(n, np.iinfo(np.int64).max, dtype=np.int64)
    incl_proposer = np.zeros(n, dtype=np.int64)
    for a in epoch_atts:
        data = a.data
        idx = attesting_indices(
            spec, state, data, a.aggregation_bits, plan_ctx)
        source_part[idx] = True
        d = int(a.inclusion_delay)
        upd = d < incl_delay[idx]
        upd_idx = idx[upd]
        incl_delay[upd_idx] = d
        incl_proposer[upd_idx] = int(a.proposer_index)
        if expected_target is None:
            expected_target = bytes(
                spec.get_block_root(state, spec.Epoch(prev_epoch)))
        if bytes(data.target.root) == expected_target:
            target_part[idx] = True
            slot = int(data.slot)
            head_root = head_roots.get(slot)
            if head_root is None:
                head_root = head_roots[slot] = bytes(
                    spec.get_block_root_at_slot(state, data.slot))
            if bytes(data.beacon_block_root) == head_root:
                head_part[idx] = True
    source_part &= ~slashed
    target_part &= ~slashed
    head_part &= ~slashed
    incl_delay[incl_delay == np.iinfo(np.int64).max] = 1  # unused lanes

    total_balance = int(spec.get_total_active_balance(state))
    sqrt_total = int(spec.integer_squareroot(spec.uint64(total_balance)))
    finality_delay = int(prev_epoch - state.finalized_checkpoint.epoch)

    return DeltaInputs(
        effective_balance=eff,
        eligible=eligible,
        source_part=source_part,
        target_part=target_part,
        head_part=head_part,
        incl_delay=incl_delay,
        incl_proposer=incl_proposer,
        total_balance=total_balance,
        sqrt_total=sqrt_total,
        finality_delay=finality_delay,
        base_reward_factor=int(spec.BASE_REWARD_FACTOR),
        base_rewards_per_epoch=int(spec.BASE_REWARDS_PER_EPOCH),
        proposer_reward_quotient=int(spec.PROPOSER_REWARD_QUOTIENT),
        inactivity_penalty_quotient=int(spec.INACTIVITY_PENALTY_QUOTIENT),
        min_epochs_to_inactivity_penalty=int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY),
        effective_balance_increment=int(spec.EFFECTIVE_BALANCE_INCREMENT),
    )


def delta_scalars(inp: DeltaInputs) -> np.ndarray:
    """THE scalar vector layout _deltas_kernel unpacks positionally —
    single definition so every caller (attestation_deltas, the fused
    merkle-resident program, the graft entry) stays in lockstep."""
    return np.array([
        inp.total_balance, inp.sqrt_total, inp.finality_delay,
        inp.base_reward_factor, inp.base_rewards_per_epoch,
        inp.proposer_reward_quotient, inp.inactivity_penalty_quotient,
        inp.min_epochs_to_inactivity_penalty,
        inp.effective_balance_increment,
    ], dtype=np.int64)


def _deltas_kernel(eff, eligible, source_part, target_part, head_part,
                   incl_delay, incl_proposer, scalars):
    """Pure-JAX deltas. ``scalars`` is an int64 vector in the
    delta_scalars() order: [total_balance, sqrt_total, finality_delay,
    BRF, BRPE, PRQ, IPQ, MIN_EPOCHS_LEAK, EBI]."""
    (total_balance, sqrt_total, finality_delay, brf, brpe, prq, ipq,
     min_leak, ebi) = [scalars[i] for i in range(9)]

    n = eff.shape[0]
    base_reward = eff * brf // sqrt_total // brpe
    proposer_reward = base_reward // prq
    is_leak = finality_delay > min_leak

    rewards = jnp.zeros(n, dtype=jnp.int64)
    penalties = jnp.zeros(n, dtype=jnp.int64)

    total_incr = total_balance // ebi
    for part in (source_part, target_part, head_part):
        attesting_balance = jnp.maximum(jnp.sum(jnp.where(part, eff, 0)), ebi)
        att_incr = attesting_balance // ebi
        full_reward = base_reward  # during leak: full compensation
        scaled_reward = base_reward * att_incr // total_incr
        comp_reward = jnp.where(is_leak, full_reward, scaled_reward)
        rewards = rewards + jnp.where(eligible & part, comp_reward, 0)
        penalties = penalties + jnp.where(eligible & ~part, base_reward, 0)

    # inclusion delay: attester reward plus scatter-add of proposer rewards
    max_attester_reward = base_reward - proposer_reward
    rewards = rewards + jnp.where(source_part, max_attester_reward // incl_delay, 0)
    prop_credit = jnp.where(source_part, proposer_reward, 0)
    rewards = rewards.at[incl_proposer].add(prop_credit)

    # inactivity leak
    leak_base = brpe * base_reward - proposer_reward
    leak_extra = eff * finality_delay // ipq
    penalties = penalties + jnp.where(
        is_leak & eligible, leak_base + jnp.where(~target_part, leak_extra, 0), 0)

    return rewards, penalties


def epoch_step(balances, eff, eligible, source_part, target_part, head_part,
               incl_delay, incl_proposer, scalars):
    """Single-device full epoch step: deltas -> balance update.

    This is the jittable "forward step" the graft entry exposes; the
    mesh-sharded variant lives in parallel/epoch_sharded.py.
    """
    rewards, penalties = _deltas_kernel(
        eff, eligible, source_part, target_part, head_part,
        incl_delay, incl_proposer, scalars)
    new_balances = balances + rewards
    return jnp.where(penalties > new_balances, 0, new_balances - penalties)


# single jitted callable; XLA caches per input shape.
#
# Device choice: this kernel is memory-bound int64 elementwise work with
# integer divisions — on TPU hardware int64 is emulated on 32-bit lanes and
# the axon-tunneled transfer adds seconds of latency, so the host CPU XLA
# backend is strictly faster at any registry size.  The TPU pays off on the
# compute-dense batched pairing / SHA-256 pipelines instead (ops/bls_jax,
# ops/sha256_jax); the multi-chip story for the epoch pass is the sharded
# mesh variant in parallel/epoch_sharded.py.  CSTPU_EPOCH_BACKEND overrides.
import os as _os


def _kernel_device():
    want = _os.environ.get("CSTPU_EPOCH_BACKEND", "cpu")
    try:
        return jax.local_devices(backend=want)[0]
    except RuntimeError:
        return None


_jit_kernel = jax.jit(_deltas_kernel)


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length() if n > 1 else 1


def delta_device_cache(spec, state) -> tuple:
    """The device-residency key half for one epoch-kernel call: registry
    root + previous epoch — everything the registry-derived kernel
    inputs (padded effective balance, eligibility mask) are pure in.
    State-ful callers pass it to ``attestation_deltas`` /
    ``fused_epoch_balance_update`` so those uploads happen once per
    registry VERSION (stf/columns.device_buffer) instead of per call."""
    return (bytes(state.validators.hash_tree_root()),
            int(spec.get_previous_epoch(state)))


def attestation_deltas(inp: DeltaInputs, device_cache: tuple = None):
    """Compute (rewards, penalties) int64 arrays from DeltaInputs.

    With ``device_cache`` (from ``delta_device_cache``) the registry-
    derived inputs — effective balance and the eligibility mask — are
    served as resident device buffers keyed by registry root, retiring
    the per-call re-staging ROADMAP item 3 named; the per-epoch inputs
    (participation, inclusion) still upload per call, as they must."""
    n = inp.effective_balance.shape[0]
    n_pad = _next_pow2(n)

    def pad(a, fill=0):
        if n_pad == n:
            return a
        return np.concatenate([a, np.full(n_pad - n, fill, dtype=a.dtype)])

    scalars = delta_scalars(inp)

    dev = _kernel_device()
    put = (lambda a: jax.device_put(a, dev)) if dev is not None else jnp.asarray
    if device_cache is not None:
        from consensus_specs_tpu.stf import columns

        # backend identity is bound by device_buffer itself (it appends
        # str(device) to every key) — callers key only their derivation
        root, prev_epoch = device_cache
        eff_dev = columns.device_buffer(
            (root, "eff_pad", n_pad),
            lambda: pad(inp.effective_balance), device=dev)
        elig_dev = columns.device_buffer(
            (root, "eligible_pad", prev_epoch, n_pad),
            lambda: pad(inp.eligible.astype(bool)), device=dev)
    else:
        eff_dev = put(pad(inp.effective_balance))
        elig_dev = put(pad(inp.eligible.astype(bool)))
    rewards, penalties = _jit_kernel(
        eff_dev,
        elig_dev,
        put(pad(inp.source_part.astype(bool))),
        put(pad(inp.target_part.astype(bool))),
        put(pad(inp.head_part.astype(bool))),
        put(pad(inp.incl_delay, fill=1)),
        put(pad(inp.incl_proposer)),
        put(scalars),
    )
    # host-sync: staged view — the one pull-back of the epoch kernel's
    # outputs (the input side is resident now; the output side goes
    # device-resident with the fused merkle path)
    return np.asarray(rewards)[:n], np.asarray(penalties)[:n]


def attestation_deltas_for_state(spec, state):
    """End-to-end: state -> (rewards, penalties) numpy arrays."""
    return attestation_deltas(extract_delta_inputs(spec, state),
                              device_cache=delta_device_cache(spec, state))


# ---------------------------------------------------------------------------
# vectorized epoch-phase twins (installed by the spec builder as
# semantics-preserving substitutions; each keeps the sequential original
# reachable via __wrapped__, differential tests in tests/spec/phase0/)
# ---------------------------------------------------------------------------


# -- phase0 matching-attestation scans (ISSUE 10) -----------------------------

# one shared pass per (pendings version, roots version, slot, epoch)
# computing the matching-target AND matching-head sublists together —
# the spec's two per-pending listcomps re-walk every pending's ``a.data``
# view chain per call (and its sundry LRU keys on the FULL state root).
# Both key halves are memoized subtree roots, so a probe is cheap after
# any state-root computation; FIFO-bounded like every geometry memo.
_MATCHING_SCAN_CACHE: dict = {}
_MATCHING_SCAN_MAX = 4


def _matching_scan(spec, state, epoch: int) -> dict:
    prev_epoch = int(spec.get_previous_epoch(state))
    cur_epoch = int(spec.get_current_epoch(state))
    # get_matching_source_attestations' own precondition, verbatim
    assert int(epoch) in (prev_epoch, cur_epoch)
    atts = (state.current_epoch_attestations if int(epoch) == cur_epoch
            else state.previous_epoch_attestations)
    key = (bytes(atts.hash_tree_root()),
           bytes(state.block_roots.hash_tree_root()),
           int(state.slot), int(epoch))
    hit = _MATCHING_SCAN_CACHE.get(key)
    if hit is not None:
        return hit
    # expected target root evaluated at the FIRST pending (the spec's
    # listcomp evaluates get_block_root per item, so first-use raises
    # identically and an empty list never evaluates it); head roots
    # memoized per slot with the same first-use raise point
    expected_target = None
    head_roots: dict = {}
    target, head = [], []
    for a in atts:
        data = a.data
        if expected_target is None:
            expected_target = bytes(
                spec.get_block_root(state, spec.Epoch(int(epoch))))
        if bytes(data.target.root) != expected_target:
            continue
        target.append(a)
        slot = int(data.slot)
        head_root = head_roots.get(slot)
        if head_root is None:
            head_root = head_roots[slot] = bytes(
                spec.get_block_root_at_slot(state, data.slot))
        if bytes(data.beacon_block_root) == head_root:
            head.append(a)
    from consensus_specs_tpu.stf import staging

    if len(_MATCHING_SCAN_CACHE) >= _MATCHING_SCAN_MAX:
        _MATCHING_SCAN_CACHE.pop(next(iter(_MATCHING_SCAN_CACHE)))
    value = {"target": target, "head": head}
    _MATCHING_SCAN_CACHE[key] = value
    staging.note_insert(_MATCHING_SCAN_CACHE, key)
    return value


def matching_target_attestations(spec, state, epoch) -> list:
    """``get_matching_target_attestations`` off the shared scan — same
    elements, same order, same assert/raise points."""
    return _matching_scan(spec, state, int(epoch))["target"]


def matching_head_attestations(spec, state, epoch) -> list:
    """``get_matching_head_attestations`` off the shared scan."""
    return _matching_scan(spec, state, int(epoch))["head"]


def reset_caches() -> None:
    """Drop the matching-scan memo (cold-start control; the registry
    column cache is root-keyed and self-invalidating, so it stays)."""
    _MATCHING_SCAN_CACHE.clear()


def participation_mask(spec, state, attestations, n: int) -> np.ndarray:
    mask = np.zeros(n, dtype=bool)
    plan_ctx: dict = {}  # per-scan plan-key memo
    for a in attestations:
        mask[attesting_indices(
            spec, state, a.data, a.aggregation_bits, plan_ctx)] = True
    return mask


def attesting_balance(spec, state, attestations) -> int:
    """get_attesting_balance: combined effective balance of unslashed
    participants (floored at one increment, per get_total_balance)."""
    cols = registry_columns(state)
    mask = participation_mask(spec, state, attestations, len(cols["slashed"]))
    mask &= ~cols["slashed"]
    total = int(np.sum(np.where(mask, cols["effective_balance"], 0),
                       dtype=np.uint64))
    return max(int(spec.EFFECTIVE_BALANCE_INCREMENT), total)


def total_active_balance(spec, state) -> int:
    cols = registry_columns(state)
    act = active_mask(cols, int(spec.get_current_epoch(state)))
    total = int(np.sum(np.where(act, cols["effective_balance"], 0),
                       dtype=np.uint64))
    return max(int(spec.EFFECTIVE_BALANCE_INCREMENT), total)


def active_validator_indices(spec, state, epoch) -> list:
    cols = registry_columns(state)
    return [int(i) for i in np.nonzero(active_mask(cols, int(epoch)))[0]]


def effective_balance_updates(spec, state) -> None:
    """Hysteresis update; only validators whose effective balance actually
    moves touch the tree (typically a handful per epoch).  The balance
    read is a resident-column probe (the rewards phase just flushed it)."""
    from consensus_specs_tpu.stf import columns as stf_columns

    cols = registry_columns(state)
    bal = stf_columns.balance_column(state)
    eff = cols["effective_balance"]
    ebi = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    hyst = ebi // int(spec.HYSTERESIS_QUOTIENT)
    down = hyst * int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER)
    up = hyst * int(spec.HYSTERESIS_UPWARD_MULTIPLIER)
    new_eff = np.minimum(bal - bal % ebi, int(spec.MAX_EFFECTIVE_BALANCE))
    change = (bal + down < eff) | (eff + up < bal)
    for i in np.nonzero(change)[0]:
        state.validators[int(i)].effective_balance = int(new_eff[i])


def slashings_sweep(spec, state, multiplier: int) -> None:
    """process_slashings with the fork's proportional multiplier.  Reads
    the resident balance column; the sweep only copies and flushes when a
    validator is actually due (usually never)."""
    from consensus_specs_tpu.stf import columns as stf_columns

    epoch = int(spec.get_current_epoch(state))
    total = int(spec.get_total_active_balance(state))
    sum_slash = sum(int(x) for x in state.slashings)
    adjusted = min(sum_slash * multiplier, total)
    cols = registry_columns(state)
    window = epoch + int(spec.EPOCHS_PER_SLASHINGS_VECTOR) // 2
    mask = cols["slashed"] & (cols["withdrawable_epoch"] == window)
    if not mask.any():
        return
    # exact python big-int arithmetic on the (few) affected validators —
    # penalty_numerator can exceed int64 in small-preset edge states
    increment = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    bal = stf_columns.staged_balances(state)
    for i in np.nonzero(mask)[0]:
        eff_i = int(cols["effective_balance"][i])
        penalty = eff_i // increment * adjusted // total * increment
        b = int(bal[i])
        bal[i] = 0 if penalty > b else b - penalty
    stf_columns.flush_balances(state, bal)


def registry_updates(spec, state) -> None:
    """process_registry_updates: vectorized scans, per-index mutations only
    for the (few) affected validators, in spec iteration order."""
    cols = registry_columns(state)  # snapshot before any mutation
    cur = int(spec.get_current_epoch(state))
    eff = cols["effective_balance"]

    # activation-queue eligibility: aee == FAR_FUTURE and eff == MAX
    elig_queue = (cols["activation_eligibility_epoch"] >= _SAT) & (
        eff == int(spec.MAX_EFFECTIVE_BALANCE)
    )
    # ejections: active now and eff <= EJECTION_BALANCE
    eject = active_mask(cols, cur) & (eff <= int(spec.config.EJECTION_BALANCE))
    for i in np.nonzero(elig_queue | eject)[0]:
        index = int(i)
        if elig_queue[i]:
            state.validators[index].activation_eligibility_epoch = cur + 1
        if eject[i]:
            spec.initiate_validator_exit(state, index)

    # activation dequeue: aee <= finalized and activation == FAR_FUTURE,
    # ordered by (aee, index).  The spec builds the queue AFTER the first
    # loop, so freshly-queued validators carry aee = cur+1 — which is
    # admissible whenever finalized >= cur+1 (artificial but legal states;
    # caught by tests/spec/phase0/test_registry_vectorization.py).
    aee = np.where(elig_queue, cur + 1, cols["activation_eligibility_epoch"])
    finalized = int(state.finalized_checkpoint.epoch)
    elig_act = (aee <= finalized) & (cols["activation_epoch"] >= _SAT)
    idxs = np.nonzero(elig_act)[0]
    order = np.lexsort((idxs, aee[idxs]))
    churn = int(spec.get_validator_churn_limit(state))
    target_epoch = int(spec.compute_activation_exit_epoch(cur))
    for i in idxs[order][:churn]:
        state.validators[int(i)].activation_epoch = target_epoch
