"""Integer-path probe for device BLS: measures the Fq-multiply primitive
under different limb radices on the actual chip.

Round 2's device BLS lost 14-23x to the host C++ backend; the open
question (VERDICT item 8) was whether the chip's integer path can win at
all, and specifically whether "16-bit limb products accumulating in int32"
beat the current 26-bit-limbs-in-int64 design.  The arithmetic answer is
no as stated: a 16x16-bit product is itself 32 bits, so ANY accumulation
overflows int32.  The densest radix whose schoolbook accumulation fits
int32 is 13-bit limbs (products 26 bits, 30-term row sums < 2^31), at the
cost of (30/16)^2 = 3.5x more partial products than the int64 design.
This module implements that 13-bit/int32 variant of the Montgomery
multiply, correctness-checked against python ints, so the two radices can
be raced on real hardware (bench.py bls row / tools/limb_probe_bench.py).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from consensus_specs_tpu import _jaxcache

from .limbs import P_INT

_jaxcache.configure()

N_LIMBS32 = 30
LIMB_BITS32 = 13
_B = LIMB_BITS32
_MASK = (1 << _B) - 1
R_BITS32 = N_LIMBS32 * LIMB_BITS32  # 390
R_INT32 = (1 << R_BITS32) % P_INT
N0INV32 = (-pow(P_INT, -1, 1 << _B)) % (1 << _B)


def int_to_limbs32(x: int) -> np.ndarray:
    assert 0 <= x < (1 << R_BITS32)
    out = np.zeros(N_LIMBS32, dtype=np.int32)
    for i in range(N_LIMBS32):
        out[i] = (x >> (_B * i)) & _MASK
    return out


def limbs32_to_int(a) -> int:
    arr = np.asarray(a, dtype=object)
    return int(sum(int(arr[..., i]) << (_B * i) for i in range(N_LIMBS32)))


_P_LIMBS32 = int_to_limbs32(P_INT)
_P_LIMBS32_J = jnp.asarray(_P_LIMBS32, dtype=jnp.int32)


def mul32(a, b):
    """Montgomery multiply over [..., 30] int32 13-bit limbs.

    All intermediates fit int32: schoolbook row sums <= 30 * 2^26 < 2^31;
    REDC is interleaved with carry propagation per limb (the int32 budget
    forces a serial carry chain the int64 design avoids — that serial
    chain is the price of the narrow accumulator, and the measured reason
    this radix does not win).
    """
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape).astype(jnp.int32)
    b = jnp.broadcast_to(b, shape).astype(jnp.int32)

    n = N_LIMBS32
    # product limbs with immediate carry splitting: build the 2n-limb
    # convolution one diagonal at a time, keeping every digit < 2^13
    T = [jnp.zeros(shape[:-1], jnp.int32) for _ in range(2 * n + 2)]
    for k in range(2 * n - 1):
        lo = max(0, k - n + 1)
        hi = min(n, k + 1)
        acc = jnp.zeros(shape[:-1], jnp.int32)
        for i in range(lo, hi):
            acc = acc + a[..., i] * b[..., k - i]  # <= 30 * 2^26 < 2^31
        # split the diagonal sum into digits immediately
        T[k] = T[k] + (acc & _MASK)
        T[k + 1] = T[k + 1] + ((acc >> _B) & _MASK)
        T[k + 2] = T[k + 2] + (acc >> (2 * _B))
        # normalize T[k] (may have grown past 13 bits from the carry adds)
        c = T[k] >> _B
        T[k] = T[k] & _MASK
        T[k + 1] = T[k + 1] + c

    # REDC: clear limbs 0..n-1
    for i in range(n):
        m = (T[i] * np.int32(N0INV32)) & _MASK
        carry = jnp.zeros(shape[:-1], jnp.int32)
        for j in range(n):
            v = T[i + j] + m * jnp.int32(int(_P_LIMBS32[j])) + carry
            T[i + j] = v & _MASK
            carry = v >> _B
        j = i + n
        while_carry = carry
        # propagate the tail carry (bounded: few limbs)
        for j2 in range(j, 2 * n + 2):
            v = T[j2] + while_carry
            T[j2] = v & _MASK
            while_carry = v >> _B

    out = jnp.stack(T[n:2 * n], axis=-1)
    return out


_jit_mul32 = jax.jit(mul32)


def host_to_mont32(x: int) -> np.ndarray:
    return int_to_limbs32(x * R_INT32 % P_INT)


def host_from_mont32(a) -> int:
    return limbs32_to_int(np.asarray(a)) * pow(R_INT32, -1, P_INT) % P_INT
