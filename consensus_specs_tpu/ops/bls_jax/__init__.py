"""Batched TPU BLS backend — the "#1 TPU target" of SURVEY §2.7.

Splits BLS verification the TPU way:
  * host (this module): byte deserialization + subgroup checks (cached),
    pubkey aggregation, message hashing to G2 — tiny, irregular, branchy
    work that XLA has no business compiling;
  * device (pairing.py): the pairing-product check — thousands of
    Montgomery limb multiplies per verification, batched over B
    independent verifications as [K, B, ...] limb tensors so the MXU sees
    large regular contractions instead of one sequential bigint chain.

The batch APIs are the point: a block carries <= 128 attestations
(phase0/beacon-chain.md:1807-1833 FastAggregateVerify per attestation) and
a sync aggregate of 512 pubkeys (altair/beacon-chain.md:540-547);
``batch_fast_aggregate_verify`` decides ALL of them in one device call.

The ciphersuite-compatible scalar API (Verify/FastAggregateVerify/...)
lets ``bls.use_jax()`` register this module as a drop-in backend; Sign /
SkToPk / aggregation delegate to the fastest host backend (native C++,
falling back to the pure-Python oracle) since signing is not a batch
workload.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

# persistent XLA compilation cache: _jaxcache.configure() runs when
# limbs.py (imported below via `pairing`) first imports jax — the pairing
# kernels' minutes-long per-shape compiles depend on it

from consensus_specs_tpu.crypto.bls import ciphersuite as _py
from consensus_specs_tpu.crypto.bls.curve import (
    DeserializationError,
    Point,
    g1_generator,
    g1_infinity,
    pubkey_to_point,
    signature_to_point,
)
from consensus_specs_tpu.crypto.bls.hash_to_curve import DST_G2_POP, hash_to_g2

from . import limbs, pairing, tower  # noqa: F401  (tower re-exported for tests)

try:  # fast host path for hashing/signing/aggregation
    from consensus_specs_tpu.crypto.bls import native as _host
except ImportError:
    _host = None

G2_POINT_AT_INFINITY = _py.G2_POINT_AT_INFINITY

# host-side scalar delegates --------------------------------------------------

_delegate = _host if _host is not None else _py

Sign = _delegate.Sign
SkToPk = _delegate.SkToPk
KeyValidate = _delegate.KeyValidate
Aggregate = _delegate.Aggregate
AggregatePKs = _delegate.AggregatePKs


def _hash_to_g2_point(message: bytes) -> Point:
    """H(msg) as an oracle curve point, via the native C++ hasher when
    available (compressed-bytes round trip), else the Python pipeline."""
    if _host is not None:
        from consensus_specs_tpu.crypto.bls.curve import g2_from_bytes

        return g2_from_bytes(_host.hash_to_g2_compressed(message, DST_G2_POP))
    return hash_to_g2(bytes(message), DST_G2_POP)


# marshalling -----------------------------------------------------------------


def _g1_coords(pt: Point) -> Tuple[np.ndarray, np.ndarray]:
    x, y = pt.to_affine()
    return limbs.host_to_mont(x.n), limbs.host_to_mont(y.n)


def _g2_coords(pt: Point) -> Tuple[np.ndarray, np.ndarray]:
    x, y = pt.to_affine()
    return (
        np.stack([limbs.host_to_mont(x.c0), limbs.host_to_mont(x.c1)]),
        np.stack([limbs.host_to_mont(y.c0), limbs.host_to_mont(y.c1)]),
    )


_NEG_G1_GEN = -g1_generator()


def _check_pairs_batch(
    pairs_per_item: Sequence[Sequence[Tuple[Point, Point]]],
) -> np.ndarray:
    """prod e(P_k, Q_k) == 1 for each item; every item must carry the same
    number K of pairs (the verify family always yields K = 2)."""
    B = len(pairs_per_item)
    K = len(pairs_per_item[0])
    assert all(len(ps) == K for ps in pairs_per_item)
    px = np.zeros((K, B, limbs.N_LIMBS), dtype=np.int64)
    py = np.zeros((K, B, limbs.N_LIMBS), dtype=np.int64)
    qx = np.zeros((K, B, 2, limbs.N_LIMBS), dtype=np.int64)
    qy = np.zeros((K, B, 2, limbs.N_LIMBS), dtype=np.int64)
    infinity_mask = np.zeros((K, B), dtype=bool)
    for b, ps in enumerate(pairs_per_item):
        for k, (p, q) in enumerate(ps):
            if p.is_infinity() or q.is_infinity():
                infinity_mask[k, b] = True  # this item falls back below
                continue
            px[k, b], py[k, b] = _g1_coords(p)
            qx[k, b], qy[k, b] = _g2_coords(q)
    if infinity_mask.any():
        # rare path (infinity points, e.g. infinity signatures): only the
        # affected items drop to the host oracle; the rest stay batched so
        # one adversarial attestation can't stall the whole block
        from consensus_specs_tpu.crypto.bls.pairing import pairings_are_identity

        out = np.zeros(B, dtype=bool)
        dirty = infinity_mask.any(axis=0)
        clean = [b for b in range(B) if not dirty[b]]
        if clean:
            # pad the clean subset to a power-of-two bucket (repeat first
            # item) so this path reuses the standard compiled shapes
            bucket = 2
            while bucket < len(clean):
                bucket *= 2
            sel = clean + [clean[0]] * (bucket - len(clean))
            sub = np.asarray(pairing.pairs_product_is_one(
                px[:, sel], py[:, sel], qx[:, sel], qy[:, sel]))
            out[clean] = sub[: len(clean)]
        for b in range(B):
            if dirty[b]:
                out[b] = pairings_are_identity(pairs_per_item[b])
        return out
    return np.asarray(pairing.pairs_product_is_one(px, py, qx, qy))


# batch APIs ------------------------------------------------------------------


def marshal_fast_aggregate_items(
    pubkeys_lists: Sequence[Sequence[bytes]],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> Tuple[List[bool], List[Tuple[int, List[Tuple[Point, Point]]]]]:
    """Host-side per-item marshalling shared by the single-device and
    mesh-sharded (parallel/bls_sharded.py) batch verifiers: signature and
    pubkey decompression + subgroup checks (cached), pubkey aggregation,
    hash-to-curve.  Returns ``(results, todo)``: the B-long verdict list
    prefilled False (malformed/empty items stay False) and the pairing
    pairs for every structurally valid item."""
    B = len(pubkeys_lists)
    assert len(messages) == len(signatures) == B
    results: List[bool] = [False] * B
    todo: List[Tuple[int, List[Tuple[Point, Point]]]] = []
    for b in range(B):
        try:
            if len(pubkeys_lists[b]) == 0:
                continue
            sig = signature_to_point(bytes(signatures[b]))
            agg = g1_infinity()
            ok = True
            for pk_bytes in pubkeys_lists[b]:
                pk = pubkey_to_point(bytes(pk_bytes))
                if pk.is_infinity():
                    ok = False
                    break
                agg = agg + pk
            if not ok:
                continue
            h = _hash_to_g2_point(bytes(messages[b]))
            todo.append((b, [(agg, h), (_NEG_G1_GEN, sig)]))
        except (DeserializationError, ValueError):
            continue
    return results, todo


def batch_fast_aggregate_verify(
    pubkeys_lists: Sequence[Sequence[bytes]],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> List[bool]:
    """One device call deciding FastAggregateVerify for B items.

    Malformed/out-of-subgroup inputs, infinity pubkeys, and empty pubkey
    lists yield False for that item (never an exception), mirroring the
    selector's Verify-family contract (crypto/bls/__init__.py)."""
    results, todo = marshal_fast_aggregate_items(
        pubkeys_lists, messages, signatures)
    if todo:
        # pad to a power-of-two bucket (min 2): bounded set of compiled
        # batch shapes, shared across callers.  Pad with an infinity-free
        # item when one exists — duplicating a dirty (infinity-carrying)
        # item would multiply its slow host-oracle fallback by the pad count
        n = len(todo)
        bucket = 2
        while bucket < n:
            bucket *= 2
        padded = [pairs for _, pairs in todo]
        pad_src = next(
            (pairs for pairs in padded
             if not any(p.is_infinity() or q.is_infinity() for p, q in pairs)),
            padded[0],
        )
        padded.extend([pad_src] * (bucket - n))
        verdicts = _check_pairs_batch(padded)
        for (b, _), v in zip(todo, verdicts[:n]):
            results[b] = bool(v)
    return list(results)


def batch_verify(
    pubkeys: Sequence[bytes],
    messages: Sequence[bytes],
    signatures: Sequence[bytes],
) -> List[bool]:
    """One device call deciding single-pubkey Verify for B items."""
    return batch_fast_aggregate_verify(
        [[pk] for pk in pubkeys], messages, signatures
    )


# ciphersuite-compatible scalar API ------------------------------------------


def Verify(pubkey: bytes, message: bytes, signature: bytes) -> bool:
    return batch_verify([pubkey], [message], [signature])[0]


def FastAggregateVerify(
    pubkeys: Sequence[bytes], message: bytes, signature: bytes
) -> bool:
    return batch_fast_aggregate_verify([list(pubkeys)], [message], [signature])[0]


def AggregateVerify(
    pubkeys: Sequence[bytes], messages: Sequence[bytes], signature: bytes
) -> bool:
    """Distinct-message aggregate verification.  K varies with len(pubkeys),
    and each distinct K would trigger a fresh XLA compilation, so this
    rare, unbatchable path stays on the fastest host backend."""
    return _delegate.AggregateVerify(pubkeys, messages, signature)


def backend():
    """The module itself is the backend object the selector registers."""
    import sys

    return sys.modules[__name__]
