"""MXU probe for device Fq multiplication: int8 limb products as matmuls.

Round 3's LIMB_PROBE measured the VPU integer-emulation ceiling at ~78k
Fq muls/s (26-bit limbs in int64 lanes) and named the MXU int8 route as
"the only plausible route... not attempted".  This module is that attempt
(round-4 VERDICT item 3).

Design.  Radix 2^6, 64 limbs (384 bits >= 381): every normalized digit is
0..63 and every REDC input digit stays < 2^7, so all matmul INPUTS fit
signed int8 — the MXU's native integer format — while products accumulate
in int32 (64 * 2^12 = 2^18 per diagonal, far inside int32).

A Montgomery multiply t = a*b*R^-1 decomposes into three multiplies:

  1. t   = a (*) b         — per-lane convolution; both sides vary per
                             batch element, so the MXU's shared-operand
                             shape does not apply.  Phrased as an im2col
                             batched contraction einsum('ni,nik->nk').
  2. m   = t_low * N0INV   — multiplication by a CONSTANT (the inverse of
     (mod R)                 -p^-1 mod R): a fixed lower-triangular
                             Toeplitz matrix.  TRUE MXU MATMUL
                             [N,64] x [64,64] int8 -> int32.
  3. t  += m * P           — multiplication by the CONSTANT modulus:
                             fixed Toeplitz [N,64] x [64,129] int8 ->
                             int32.  TRUE MXU MATMUL.

So 2 of the 3 multiplies in REDC are perfectly MXU-shaped; the probe
measures whether that + the unavoidable per-lane conv beats the 78k/s
VPU ceiling.  Carry normalization between steps is lazy split-and-add
(3 passes bound digits back under 2^7), vectorized across lanes.

Correctness is pinned to python ints in tests/test_mxu_probe.py; the
hardware race lives in tools/limb_probe_bench.py --mxu and lands in
LIMB_PROBE.json next to the earlier radix measurements.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from consensus_specs_tpu import _jaxcache

from .limbs import P_INT

_jaxcache.configure()

B = 6                      # bits per limb
N = 64                     # limbs: 64 * 6 = 384 bits
MASK = (1 << B) - 1
R_BITS = N * B             # 384
R_INT = (1 << R_BITS) % P_INT
N0INV = (-pow(P_INT, -1, 1 << R_BITS)) % (1 << R_BITS)  # -p^-1 mod R


def int_to_digits(x: int, n: int = N) -> np.ndarray:
    assert 0 <= x < (1 << (B * n))
    return np.array([(x >> (B * i)) & MASK for i in range(n)], dtype=np.int32)


def digits_to_int(d) -> int:
    arr = np.asarray(d)
    return int(sum(int(arr[..., i]) << (B * i) for i in range(arr.shape[-1])))


def _toeplitz_for_constant(c_digits: np.ndarray, out_limbs: int) -> np.ndarray:
    """T with T[i, k] = c[k - i]: right-multiplying a digit row-vector by T
    is multiplication by the constant, unnormalized digits out."""
    n = len(c_digits)
    T = np.zeros((n, out_limbs), dtype=np.int8)
    for i in range(n):
        for j in range(n):
            if i + j < out_limbs:
                T[i, i + j] = c_digits[j]
    return T


_P_DIGITS = int_to_digits(P_INT)
_N0_DIGITS = int_to_digits(N0INV)
# m*P spills one limb past 2N? m < R, P < R: m*P < R^2 -> 2N limbs.
_T_P = jnp.asarray(_toeplitz_for_constant(_P_DIGITS, 2 * N), dtype=jnp.int8)
# m = (t_low * n0inv) mod R: only the low N output limbs matter.
_T_N0 = jnp.asarray(_toeplitz_for_constant(_N0_DIGITS, N), dtype=jnp.int8)


def _normalize(d, passes: int = 3, width: int | None = None):
    """Lazy carry normalization: split digits into (low, carry), add the
    carry one limb up.  Each pass shrinks digit magnitude ~2^B; ``passes``
    = 3 takes the conv-output bound 2^18 below 2^7 (int8-safe, possibly
    denormal by one bit — fine for matmul inputs, exact for comparisons
    after a full propagate)."""
    for _ in range(passes):
        lo = d & MASK
        carry = d >> B
        d = lo + jnp.pad(carry, [(0, 0)] * (d.ndim - 1) + [(1, 0)])[..., :d.shape[-1]]
    if width is not None:
        d = d[..., :width]
    return d


def _conv_ab(a, b):
    """Per-lane limb convolution c[n,k] = sum_i a[n,i] b[n,k-i] via im2col:
    gather shifted copies of b and contract over the limb axis.  The one
    multiply the MXU's shared-operand shape cannot absorb."""
    n = a.shape[-1]
    out = 2 * n
    idx_k = jnp.arange(out)[None, :]            # [1, out]
    idx_i = jnp.arange(n)[:, None]              # [n, 1]
    gather = idx_k - idx_i                      # [n, out]
    valid = (gather >= 0) & (gather < n)
    gather = jnp.where(valid, gather, 0)
    # advanced indexing on the last axis: b[..., gather] -> [batch, n, out]
    shifted = jnp.where(valid, b[..., gather], 0)
    return jnp.einsum("ni,nik->nk", a.astype(jnp.int32),
                      shifted.astype(jnp.int32))


def _propagate_exact(d):
    """Exact carry propagation over the limb axis (lax.scan): digits out
    are canonical 0..63 plus a final carry limb.  One 2N-step scan per
    multiply — the serial tail the MXU phrasing cannot remove."""
    d_t = jnp.moveaxis(d, -1, 0)                 # [limbs, batch...]

    def step(carry, limb):
        v = limb + carry
        return v >> B, v & MASK

    final, digits = jax.lax.scan(step, jnp.zeros_like(d_t[0]), d_t)
    out = jnp.moveaxis(digits, 0, -1)
    return out, final


def mxu_mont_mul(a, b):
    """Montgomery multiply over [..., 64] 6-bit digit arrays: returns
    canonical-digit a*b*R^-1 (value < 2p — same lazy convention as the
    other probe radices; canonicalized on download)."""
    a = a.astype(jnp.int8)
    b = b.astype(jnp.int8)
    # 1. per-lane product (im2col conv), normalize into int8 range
    t = _conv_ab(a, b)                           # [..., 128] int32
    t_norm = _normalize(t, passes=3)
    t_low = t_norm[..., :N].astype(jnp.int8)
    # 2. m = t_low * N0INV mod R — FIXED matmul on the MXU.
    # NOTE t_low's lazy digits may exceed canonical 0..63 by the deferred
    # carries; that is fine: m only needs to be ≡ t*n0inv mod R given the
    # digits PRESENTED, and step 3 uses the same presented digits.
    m = jax.lax.dot_general(
        t_low, _T_N0, (((t_low.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    m_digits, _ = _propagate_exact(m)            # exact mod R: drop carry
    m8 = m_digits.astype(jnp.int8)
    # 3. t + m*P — FIXED matmul on the MXU
    mp = jax.lax.dot_general(
        m8, _T_P, (((m8.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    full = t_norm + mp
    digits, _final = _propagate_exact(full)      # low half becomes zeros
    # With canonical-digit inputs in the < 2p class and R = 2^384 > 4p,
    # the result t/R < (4p^2 + Rp)/R < 2p < R: the scan's outgoing carry
    # is provably zero and the < 2p class is closed under chaining.
    return digits[..., N:]


_jit_mxu_mul = jax.jit(mxu_mont_mul)


def host_to_mont(x: int) -> np.ndarray:
    return int_to_digits(x * R_INT % P_INT)


def host_from_mont(d) -> int:
    return digits_to_int(np.asarray(d)) * pow(R_INT, -1, P_INT) % P_INT


def mxu_mul_ints(x: int, y: int) -> int:
    """End-to-end x*y mod p through the device path (test hook)."""
    a = jnp.asarray(host_to_mont(x)[None], dtype=jnp.int8)
    b = jnp.asarray(host_to_mont(y)[None], dtype=jnp.int8)
    out = np.asarray(_jit_mxu_mul(a, b))[0]  # host-sync: test hook pulls the single product back
    return host_from_mont(out) % P_INT
