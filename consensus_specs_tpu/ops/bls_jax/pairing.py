"""Batched optimal-ate pairing product check on BLS12-381, in JAX.

The verification primitive is ``pairs_product_is_one``: given K pairs
(P_i in G1, Q_i in G2) per batch item, decide prod_i e(P_i, Q_i) == 1 —
exactly the check BLS Verify / FastAggregateVerify / AggregateVerify
reduce to (crypto/bls/ciphersuite.py; reference behavior
eth2spec/utils/bls.py:47-74 via py_ecc).

Design (vs the affine/untwist oracle in crypto/bls/pairing.py):
  * Q stays on the twist E'(Fq2) in homogeneous projective (X, Y, Z), so
    the Miller loop is inversion-free.  Lines are evaluated in scaled
    form — Fq2 scalar factors are annihilated by the final exponentiation
    — giving sparse lines with w-slots {0, 3, 5}:
      tangent at T=(X,Y,Z), evaluated at P=(x_P, y_P):
        l = -y_P*(2YZ^2)*xi + (2Y^2*Z - 3X^3) w^3 + x_P*(3X^2*Z) w^5
      chord through T and affine Q=(x2,y2), theta = Y - y2*Z,
      lam = X - x2*Z:
        l = -y_P*lam*xi + (y2*lam - theta*x2) w^3 + x_P*theta w^5
    (Derivation: untwist is x -> x/w^2, y -> y/w^3 with w^-1 = xi^-1 v^2 w
    and w^-3 = xi^-1 v w; the line is scaled by 2YZ^2 resp. lam, and by
    xi.)
  * x = -0xd201000000010000 has only 5 set bits after the leading 1, so
    the loop is runs of pure doublings with 5 unrolled add-steps.  The
    doubling runs use ONE jitted ``lax.fori_loop`` kernel with a DYNAMIC
    trip count — a single compilation serves every run length, and the
    same trick serves all six ``g^|x|`` squaring chains of the final
    exponentiation.  Pieces are composed eagerly from Python; dispatch
    cost is microseconds against milliseconds of compute, and the
    compile-once property is what makes the whole pairing compile in
    seconds rather than minutes.  x < 0 via final conjugation, as in the
    oracle (crypto/bls/pairing.py:101).
  * Final exponentiation: easy part, then the Hayashida-Hayasaka-Teruya
    decomposition  3*hard = (x-1)^2 (x+p) (x^2+p^2-1) + 3.  Computing
    f^(3*hard) instead of f^hard is sound for the ==1 check because
    gcd(3, r) = 1 (cubing is a bijection on the order-r subgroup).  The
    integer identity is verified exactly in tests/test_bls_jax.py.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import limbs, tower

X_ABS = 0xD201000000010000
_BITS = [int(c) for c in bin(X_ABS)[3:]]  # 63 bits after the leading 1
# (run_of_doublings, then_add?) segments; |x| has 5 set bits after the lead
_SEGMENTS = []
_run = 0
for _b in _BITS:
    _run += 1
    if _b:
        _SEGMENTS.append((_run, True))
        _run = 0
if _run:
    _SEGMENTS.append((_run, False))
assert sum(n for n, _ in _SEGMENTS) == 63
assert sum(1 for _, add in _SEGMENTS if add) == 5

_MONT_ONE_FQ2 = np.zeros((2, limbs.N_LIMBS), dtype=np.int64)
_MONT_ONE_FQ2[0] = limbs.MONT_ONE_LIMBS


def _scale(a, s):
    """Fq2 [...,2,16] times Fq scalar [...,16] (both Montgomery)."""
    return limbs.mul(a, s[..., None, :])


def _dbl_step(X, Y, Z, px, py):
    """Projective doubling on the twist + scaled tangent line at P.
    Returns (X3, Y3, Z3, l0, l3, l5)."""
    sq, mul, xi = tower.fq2_square, tower.fq2_mul, tower.fq2_mul_by_xi
    rn = limbs.renorm
    XX = sq(X)
    YY = sq(Y)
    S = mul(Y, Z)
    W = XX + XX + XX                       # 3X^2
    B = mul(mul(X, Y), S)                  # XYS
    H = rn(sq(W) - 8 * B)                  # W^2 - 8B
    SS = sq(S)
    X3 = rn(2 * mul(H, S))
    Y3 = rn(mul(W, rn(4 * B - H)) - 8 * sq(mul(Y, S)))
    Z3 = rn(8 * mul(SS, S))
    beta = 2 * mul(S, Z)                   # 2YZ^2
    l0 = -_scale(xi(beta), py)
    l3 = rn(2 * mul(YY, Z) - 3 * mul(XX, X))
    l5 = _scale(rn(3 * mul(XX, Z)), px)
    return X3, Y3, Z3, l0, l3, l5


def _add_step(X, Y, Z, qx, qy, px, py):
    """Mixed addition T + Q (Q affine on the twist) + scaled chord line.
    Returns (X3, Y3, Z3, l0, l3, l5)."""
    sq, mul, xi = tower.fq2_square, tower.fq2_mul, tower.fq2_mul_by_xi
    rn = limbs.renorm
    theta = rn(Y - mul(qy, Z))
    lam = rn(X - mul(qx, Z))
    ll = sq(lam)
    lll = mul(ll, lam)
    llX = mul(ll, X)
    F = rn(mul(sq(theta), Z) + lll - 2 * llX)
    X3 = mul(lam, F)
    Y3 = rn(mul(theta, rn(llX - F)) - mul(lll, Y))
    Z3 = mul(lll, Z)
    l0 = -_scale(xi(lam), py)
    l3 = rn(mul(qy, lam) - mul(theta, qx))
    l5 = _scale(theta, px)
    return X3, Y3, Z3, l0, l3, l5


# ---------------------------------------------------------------------------
# jitted pieces (compiled once per (K, B) shape, composed eagerly)
# ---------------------------------------------------------------------------


@jax.jit
def _dbl_run(f, X, Y, Z, px, py, n):
    """n Miller doubling steps (f <- f^2 * prod_k line_k; T <- 2T) via a
    fori_loop with DYNAMIC n — one compilation serves all run lengths."""
    K = px.shape[0]

    def body(_, st):
        f, X, Y, Z = st
        X2, Y2, Z2, l0, l3, l5 = _dbl_step(X, Y, Z, px, py)
        f2 = tower.fq12_square(f)
        for k in range(K):
            f2 = tower.fq12_mul_line(f2, l0[k], l3[k], l5[k])
        return (f2, X2, Y2, Z2)

    return jax.lax.fori_loop(0, n, body, (f, X, Y, Z))


@jax.jit
def _add_apply(f, X, Y, Z, qx, qy, px, py):
    """One Miller add step for all K pairs."""
    K = px.shape[0]
    X, Y, Z, l0, l3, l5 = _add_step(X, Y, Z, qx, qy, px, py)
    for k in range(K):
        f = tower.fq12_mul_line(f, l0[k], l3[k], l5[k])
    return f, X, Y, Z


@jax.jit
def _sq_run(acc, n):
    """acc^(2^n) via fori_loop with dynamic n."""
    return jax.lax.fori_loop(
        0, n, lambda _, a: tower.fq12_square(a), acc)


_mul12 = jax.jit(tower.fq12_mul)
_conj12 = jax.jit(tower.fq12_conj)
_frob1_12 = jax.jit(tower.fq12_frob1)
_frob2_12 = jax.jit(tower.fq12_frob2)
_inv12 = jax.jit(tower.fq12_inv)


@jax.jit
def _is_one(res):
    return tower.fq12_eq(res, jnp.asarray(tower.FQ12_ONE_LIMBS))


def _miller_product(px, py, qx, qy):
    """Miller loop f_{|x|}(product of K pairs), conjugated for x < 0.

    px, py: [K, B, 16] Fq (Montgomery); qx, qy: [K, B, 2, 16] Fq2.
    Returns f: [B, 6, 2, 16].
    """
    X, Y = qx, qy
    # derive the loop carries from the inputs (qx * 0, not broadcast
    # constants): under shard_map the fori_loop carries must share the
    # inputs' varying-axes type; XLA folds the zero-adds either way
    Z = qx * 0 + jnp.asarray(_MONT_ONE_FQ2)
    vzero = (qx * 0)[0, :, 0, :]  # [B, 16] varying zeros
    f = vzero[:, None, None, :] + jnp.asarray(tower.FQ12_ONE_LIMBS)
    for n_dbl, has_add in _SEGMENTS:
        f, X, Y, Z = _dbl_run(f, X, Y, Z, px, py, n_dbl)
        if has_add:
            f, X, Y, Z = _add_apply(f, X, Y, Z, qx, qy, px, py)
    return _conj12(f)


def _exp_abs_x(g):
    """g^|x|: squaring runs (shared _sq_run kernel) + 5 unrolled muls."""
    acc = g
    for n_sq, has_mul in _SEGMENTS:
        acc = _sq_run(acc, n_sq)
        if has_mul:
            acc = _mul12(acc, g)
    return acc


def _exp_x(g):
    """g^x for the negative BLS parameter x; g must be in the cyclotomic
    subgroup (conjugate == inverse there)."""
    return _conj12(_exp_abs_x(g))


def final_exp_is_one_traced(f):
    """final_exponentiation(f) == 1 as a traced jnp bool array — usable
    inside jit/shard_map (the sharded verification lane in
    parallel/bls_sharded.py shards the batch axis of this whole pipeline)."""
    # easy part: f^((p^6-1)(p^2+1)) — lands in the cyclotomic subgroup
    easy = _mul12(_conj12(f), _inv12(f))
    easy = _mul12(_frob2_12(easy), easy)
    # hard part (times 3), HHT: (x-1)^2 (x+p) (x^2+p^2-1) + 3
    a1 = _mul12(_exp_x(easy), _conj12(easy))            # ^(x-1)
    a = _mul12(_exp_x(a1), _conj12(a1))                 # ^(x-1)^2
    b = _mul12(_exp_x(a), _frob1_12(a))                 # ^(x+p)
    c = _exp_abs_x(_exp_abs_x(b))                       # b^(x^2)
    d = _mul12(_mul12(c, _frob2_12(b)), _conj12(b))     # ^(x^2+p^2-1)
    f3 = _mul12(_mul12(_sq_run(easy, 1), easy), d)      # * f^3
    return _is_one(f3)


def final_exp_is_one(f):
    """final_exponentiation(f) == 1, via f^(3*(p^12-1)/r) == 1."""
    return np.asarray(final_exp_is_one_traced(f))  # host-sync: pairing verdict readback


def pairs_product_is_one(px, py, qx, qy) -> np.ndarray:
    """prod_i e(P_i, Q_i) == 1 per batch item.

    px, py: [K, B, 16]; qx, qy: [K, B, 2, 16] (Montgomery limbs).
    Returns bool [B].
    """
    f = _miller_product(
        jnp.asarray(px), jnp.asarray(py), jnp.asarray(qx), jnp.asarray(qy))
    return final_exp_is_one(f)
