"""Fq2 / Fq6 / Fq12 extension arithmetic on lazy-reduction limb lanes.

Representations (leading axes are free batch axes everywhere):
  * Fq2  = ``[..., 2, 16]``  (c0 + c1*u, u^2 = -1)
  * Fq6  = ``[..., 3, 2, 16]`` (over Fq2, v^3 = xi = 1+u) — used only for
    the tower inversion
  * Fq12 = ``[..., 6, 2, 16]`` — SIX Fq2 coefficients in the **w-power
    basis** {1, w, ..., w^5} with w^6 = xi.  This flat basis is isomorphic
    to the reference tower Fq6[w]/(w^2-v) via the slot permutation
    {1,v,v^2,w,vw,v^2w} = {w^0,w^2,w^4,w^1,w^3,w^5}; it lets a full Fq12
    multiplication run as ONE batched limb multiplication over 108 lanes
    (36 Fq2 products x Karatsuba 3) — lanes, not recursion.

Reduction discipline (see limbs.py): adds/subs/negs are single elementwise
ops on signed limbs; every public multiplying op here ends with
``limbs.renorm`` so its output has canonical digits, keeping all
accumulations inside the ``limbs.mul`` operand envelope.

Formulas mirror the pure-int oracle (crypto/bls/fields.py); differential
tests in tests/test_bls_jax.py check every op against it bit-for-bit.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from consensus_specs_tpu.crypto.bls import fields as _oracle
from . import limbs

# ---------------------------------------------------------------------------
# Fq2
# ---------------------------------------------------------------------------


def fq2_add(a, b):
    return a + b


def fq2_sub(a, b):
    return a - b


def fq2_neg(a):
    return -a


def fq2_mul_by_xi(a):
    """Multiply by xi = 1 + u: (c0 - c1, c0 + c1)."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([a0 - a1, a0 + a1], axis=-2)


def fq2_conj(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    return jnp.stack([a0, -a1], axis=-2)


def fq2_mul(a, b):
    """Karatsuba: 3 limb products batched into one call; renormed output."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    b0, b1 = b[..., 0, :], b[..., 1, :]
    lhs = jnp.stack([a0, a1, a0 + a1], axis=-2)
    rhs = jnp.stack([b0, b1, b0 + b1], axis=-2)
    t = limbs.mul(lhs, rhs)
    t0, t1, t2 = t[..., 0, :], t[..., 1, :], t[..., 2, :]
    return limbs.renorm(jnp.stack([t0 - t1, t2 - t0 - t1], axis=-2))


def fq2_square(a):
    """(a0+a1)(a0-a1) and 2*a0*a1 — 2 limb products in one call."""
    a0, a1 = a[..., 0, :], a[..., 1, :]
    lhs = jnp.stack([a0 + a1, a0], axis=-2)
    rhs = jnp.stack([a0 - a1, a1], axis=-2)
    t = limbs.mul(lhs, rhs)
    return limbs.renorm(jnp.stack([t[..., 0, :], 2 * t[..., 1, :]], axis=-2))


def fq2_inv(a):
    a0, a1 = a[..., 0, :], a[..., 1, :]
    sq = limbs.mul(jnp.stack([a0, a1], axis=-2), jnp.stack([a0, a1], axis=-2))
    norm = sq[..., 0, :] + sq[..., 1, :]
    ninv = limbs.inv(norm)
    pair = limbs.mul(jnp.stack([a0, a1], axis=-2), ninv[..., None, :])
    return limbs.renorm(
        jnp.stack([pair[..., 0, :], -pair[..., 1, :]], axis=-2))


def fq2_scale_fq(a, s):
    """Multiply an Fq2 by an Fq scalar (s: [..., 16])."""
    return limbs.mul(a, s[..., None, :])


def fq2_canonical(a):
    return limbs.canonical(a)


def fq2_eq(a, b):
    """Exact equality; canonicalizes both sides."""
    return jnp.all(limbs.canonical(a) == limbs.canonical(b), axis=(-1, -2))


# ---------------------------------------------------------------------------
# Fq6 (tower layout; used by the Fq12 inversion)
# ---------------------------------------------------------------------------


def _fq6_parts(a):
    return a[..., 0, :, :], a[..., 1, :, :], a[..., 2, :, :]


def fq6_mul(a, b):
    """Mirror of fields.py Fq6.__mul__ — 6 Fq2 products in one batch."""
    a0, a1, a2 = _fq6_parts(a)
    b0, b1, b2 = _fq6_parts(b)
    lhs = jnp.stack([a0, a1, a2, a1 + a2, a0 + a1, a0 + a2], axis=-3)
    rhs = jnp.stack([b0, b1, b2, b1 + b2, b0 + b1, b0 + b2], axis=-3)
    t = fq2_mul(lhs, rhs)
    t0, t1, t2 = t[..., 0, :, :], t[..., 1, :, :], t[..., 2, :, :]
    m12, m01, m02 = t[..., 3, :, :], t[..., 4, :, :], t[..., 5, :, :]
    c0 = fq2_mul_by_xi(m12 - t1 - t2) + t0
    c1 = m01 - t0 - t1 + fq2_mul_by_xi(t2)
    c2 = m02 - t0 - t2 + t1
    return limbs.renorm(jnp.stack([c0, c1, c2], axis=-3))


def fq6_square(a):
    return fq6_mul(a, a)


def fq6_mul_by_v(a):
    a0, a1, a2 = _fq6_parts(a)
    return jnp.stack([fq2_mul_by_xi(a2), a0, a1], axis=-3)


def fq6_inv(a):
    """Mirror of fields.py Fq6.inv."""
    a0, a1, a2 = _fq6_parts(a)
    t0 = fq2_square(a0) - fq2_mul_by_xi(fq2_mul(a1, a2))
    t1 = fq2_mul_by_xi(fq2_square(a2)) - fq2_mul(a0, a1)
    t2 = fq2_square(a1) - fq2_mul(a0, a2)
    den = (fq2_mul(a0, t0)
           + fq2_mul_by_xi(fq2_mul(a2, t1))
           + fq2_mul_by_xi(fq2_mul(a1, t2)))
    factor = fq2_inv(limbs.renorm(den))
    stack = jnp.stack([t0, t1, t2], axis=-3)
    return fq2_mul(stack, factor[..., None, :, :])


# ---------------------------------------------------------------------------
# Fq12 in the w-power basis
# ---------------------------------------------------------------------------

_I36 = np.repeat(np.arange(6), 6)
_J36 = np.tile(np.arange(6), 6)


def _accumulate(terms, pairs):
    """Sum sparse products into the 6 w-slots, folding w^6 = xi."""
    acc = [None] * 6
    for idx, (i, j) in enumerate(pairs):
        term = terms[..., idx, :, :]
        if i + j >= 6:
            term = fq2_mul_by_xi(term)
        k = (i + j) % 6
        acc[k] = term if acc[k] is None else acc[k] + term
    return limbs.renorm(jnp.stack(acc, axis=-3))


def fq12_mul(a, b):
    """Schoolbook over w-slots: c_k = sum_{i+j==k mod 6} xi^[i+j>=6] a_i b_j.
    All 36 Fq2 products (108 limb lanes) run in one batched call."""
    t = fq2_mul(a[..., _I36, :, :], b[..., _J36, :, :])
    return _accumulate(t, list(zip(_I36.tolist(), _J36.tolist())))


# slot interleave for rebuilding w-basis from tower halves: the
# concatenated [c0(3), c1(3)] layout maps back to w-slots via this gather
_INTERLEAVE = [0, 3, 1, 4, 2, 5]


def fq12_square(a):
    """Complex squaring via the tower split (mirror of fields.py
    Fq12.square): 2 Fq6 products = 12 Fq2 products — 3x fewer limb lanes
    than schoolbook fq12_mul(a, a)."""
    c0 = a[..., _TOWER_LO, :, :]
    c1 = a[..., _TOWER_HI, :, :]
    t0 = fq6_mul(c0, c1)
    m = fq6_mul(limbs.renorm(c0 + c1),
                limbs.renorm(c0 + fq6_mul_by_v(c1)))
    r0 = m - t0 - fq6_mul_by_v(t0)
    r1 = t0 + t0
    out = jnp.concatenate([r0, r1], axis=-3)
    return limbs.renorm(out[..., _INTERLEAVE, :, :])


_LINE_SLOTS = (0, 3, 5)
_LINE_PAIRS = [(i, j) for j in _LINE_SLOTS for i in range(6)]
_LINE_I = np.array([i for i, _ in _LINE_PAIRS])


def fq12_mul_line(f, l0, l3, l5):
    """Multiply f by a sparse line l = l0 + l3*w^3 + l5*w^5 (the Miller-loop
    line shape; see pairing.py) — 18 Fq2 products in one batch."""
    ls = {0: l0, 3: l3, 5: l5}
    lhs = f[..., _LINE_I, :, :]
    rhs = jnp.stack([ls[j] for _, j in _LINE_PAIRS], axis=-3)
    t = fq2_mul(lhs, rhs)
    return _accumulate(t, _LINE_PAIRS)


_CONJ_SIGN = np.ones((6, 1, 1), dtype=np.int64)
_CONJ_SIGN[1::2] = -1


def fq12_conj(a):
    """f^(p^6): negate odd w-powers."""
    return a * jnp.asarray(_CONJ_SIGN)


# tower <-> w-slot permutation: (c0.c0, c0.c1, c0.c2) = slots (0, 2, 4),
# (c1.c0, c1.c1, c1.c2) = slots (1, 3, 5)
_TOWER_LO = [0, 2, 4]
_TOWER_HI = [1, 3, 5]


def fq12_inv(a):
    """Tower inversion (mirror of fields.py Fq12.inv)."""
    c0 = a[..., _TOWER_LO, :, :]
    c1 = a[..., _TOWER_HI, :, :]
    factor = fq6_inv(
        limbs.renorm(fq6_square(c0) - fq6_mul_by_v(fq6_square(c1))))
    r0 = fq6_mul(c0, factor)
    r1 = -fq6_mul(c1, factor)
    out = jnp.zeros_like(a)
    out = out.at[..., _TOWER_LO, :, :].set(r0)
    out = out.at[..., _TOWER_HI, :, :].set(r1)
    return out


def fq12_canonical(a):
    return limbs.canonical(a)


def fq12_eq(a, b):
    return jnp.all(limbs.canonical(a) == limbs.canonical(b),
                   axis=(-1, -2, -3))


# ---------------------------------------------------------------------------
# Frobenius maps (coefficient tables computed from the oracle at import)
# ---------------------------------------------------------------------------


def _host_fq2(c0: int, c1: int) -> np.ndarray:
    return np.stack([limbs.host_to_mont(c0), limbs.host_to_mont(c1)])


def _frob_consts(power: int) -> np.ndarray:
    """gamma_k = xi^(k*(p^power - 1)/6) as Montgomery Fq2, k = 0..5."""
    xi = _oracle.Fq2(1, 1)
    e = (_oracle.P ** power - 1) // 6
    out = np.zeros((6, 2, limbs.N_LIMBS), dtype=np.int64)
    for k in range(6):
        g = xi.pow(k * e)
        out[k] = _host_fq2(g.c0, g.c1)
    return out


_FROB1_C = jnp.asarray(_frob_consts(1))
_FROB2_C = jnp.asarray(_frob_consts(2))


def fq12_frob1(a):
    """f^p: conjugate each Fq2 slot, multiply slot k by xi^(k(p-1)/6)."""
    return fq2_mul(fq2_conj(a), _FROB1_C)


def fq12_frob2(a):
    """f^(p^2): no conjugation (even power)."""
    return fq2_mul(a, _FROB2_C)


# ---------------------------------------------------------------------------
# Host conversion (tests + marshalling)
# ---------------------------------------------------------------------------

FQ12_ONE_LIMBS = np.zeros((6, 2, limbs.N_LIMBS), dtype=np.int64)
FQ12_ONE_LIMBS[0, 0] = limbs.MONT_ONE_LIMBS


def host_fq12_from_oracle(x) -> np.ndarray:
    """oracle Fq12 -> [6,2,16] Montgomery limb array (w-slot basis)."""
    slots = [x.c0.c0, x.c1.c0, x.c0.c1, x.c1.c1, x.c0.c2, x.c1.c2]
    out = np.zeros((6, 2, limbs.N_LIMBS), dtype=np.int64)
    for k, s in enumerate(slots):
        out[k] = _host_fq2(s.c0, s.c1)
    return out


def host_fq12_to_oracle(arr):
    """[6,2,16] limb array (any lazy representation) -> oracle Fq12."""
    arr = np.asarray(arr)
    vals = [[_host_from_any(arr[k, c]) for c in range(2)] for k in range(6)]
    f2 = [_oracle.Fq2(v[0], v[1]) for v in vals]
    return _oracle.Fq12(
        _oracle.Fq6(f2[0], f2[2], f2[4]),
        _oracle.Fq6(f2[1], f2[3], f2[5]),
    )


def _host_from_any(a) -> int:
    """Limb array in any lazy signed representation -> int residue,
    un-Montgomeryfied."""
    return (limbs.limbs_to_int(a) * pow(limbs.R_INT, -1, limbs.P_INT)) \
        % limbs.P_INT
