"""Fq (BLS12-381 base field) arithmetic on 26-bit limb lanes in JAX.

Representation: an Fq element is a ``[..., N_LIMBS]`` (= 16) **int64**
array of little-endian 26-bit limbs, value = sum(limb[i] << 26*i), held
in Montgomery form (a*R mod p, R = 2^416).

Lazy-reduction design (the TPU-native shape — lanes with headroom, not
carry chains):

  * ``add``/``sub``/``neg``/scalar doublings are ONE elementwise op each:
    limbs are signed and may grow/ go negative; nothing propagates.
  * Only ``mul`` reduces.  It accepts operands with limbs |a_i| <= 2^29
    (i.e. sums/differences of up to ~8 reduced values) and values
    |a| <= 36p, and returns a *reduced* element: canonical digits in
    [0, 2^26), value in (0, 3p).  Equality therefore requires
    ``canonical()`` first.

Overflow audit for ``mul`` (int64):
  schoolbook product limbs: <= 16 * 2^29 * 2^29 = 2^62;
  REDC adds m_i * p limbs (<= 16 * 2^52 = 2^56) and carries (< 2^37):
  total < 2^62.6 < 2^63.  REDC exactness needs |a*b| < R*p: worst
  (36p)^2 = 1296 p^2 << 2^416 p.  After REDC the value lies in (-p, 2p);
  the tail adds p and carry-propagates, giving (0, 3p) with canonical
  digits.

Differential tests vs python ints: tests/crypto/test_bls_jax.py.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from consensus_specs_tpu import _jaxcache

jax.config.update("jax_enable_x64", True)
_jaxcache.configure()

P_INT = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# 16 limbs (R = 2^416) rather than the minimal 15: the extra limb buys
# enough headroom that lazily-accumulated values (up to ~1000p) still
# satisfy the REDC exactness bound |a|*|b| < R*p with a wide margin.
N_LIMBS = 16
LIMB_BITS = 26
MASK = (1 << LIMB_BITS) - 1
R_BITS = N_LIMBS * LIMB_BITS  # 416

R_INT = (1 << R_BITS) % P_INT
R2_INT = (R_INT * R_INT) % P_INT
N0INV_INT = (-pow(P_INT, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)


def int_to_limbs(x: int) -> np.ndarray:
    """Host: python int in [0, 2^416) -> [N_LIMBS] int64 limb array
    (plain value, NOT Montgomery).  p itself is a valid input."""
    assert 0 <= x < (1 << R_BITS)
    out = np.zeros(N_LIMBS, dtype=np.int64)
    for i in range(N_LIMBS):
        out[i] = x & MASK
        x >>= LIMB_BITS
    return out


def limbs_to_int(a) -> int:
    """Host: limb array (any signed representation) -> python int value."""
    a = np.asarray(a, dtype=np.int64)
    return sum(int(a[i]) << (LIMB_BITS * i) for i in range(N_LIMBS))


P_LIMBS = int_to_limbs(P_INT)
R2_LIMBS = int_to_limbs(R2_INT)
ONE_LIMBS = int_to_limbs(1)
MONT_ONE_LIMBS = int_to_limbs(R_INT)

_P_LIMBS_J = jnp.asarray(P_LIMBS)
_R2_LIMBS_J = jnp.asarray(R2_LIMBS)
_ONE_LIMBS_J = jnp.asarray(ONE_LIMBS)
_N0INV = jnp.int64(N0INV_INT)
_MASK = jnp.int64(MASK)
_B = LIMB_BITS

# p shifted to offset i inside a 2*N_LIMBS-limb window, one constant per REDC step
_P_SHIFTED = np.zeros((N_LIMBS, 2 * N_LIMBS), dtype=np.int64)
for _i in range(N_LIMBS):
    _P_SHIFTED[_i, _i:_i + N_LIMBS] = P_LIMBS
_P_SHIFTED_J = jnp.asarray(_P_SHIFTED)

# one-hot unit vectors for carry injection
_E = np.eye(2 * N_LIMBS, dtype=np.int64)
_E_J = jnp.asarray(_E)

# gather indices for anti-diagonal (convolution) summation:
# padded outer row i rolled right by i, so column k holds a_i * b_{k-i}
_CONV_IDX = np.zeros((N_LIMBS, 2 * N_LIMBS), dtype=np.int32)
for _i in range(N_LIMBS):
    _CONV_IDX[_i] = (np.arange(2 * N_LIMBS) - _i) % (2 * N_LIMBS)
_CONV_IDX_J = jnp.asarray(_CONV_IDX)


# ---------------------------------------------------------------------------
# lazy elementwise ops
# ---------------------------------------------------------------------------


def add(a, b):
    return a + b


def sub(a, b):
    return a - b


def neg(a):
    return -a


def double(a):
    return a + a


def renorm(a):
    """Digit renormalization for lazily-accumulated elements: signed
    carry propagation with NO offset — the represented value is unchanged
    (and may be negative).  Limbs 0..N-2 become canonical in [0, 2^26);
    the top limb absorbs the remaining signed magnitude (tiny: |value| < 2^20*p
    implies |top| < 2^32).  Keeps schoolbook digit bounds without
    inflating values — ``mul`` accepts signed operands natively."""
    digits = []
    c = jnp.zeros_like(a[..., 0])
    for i in range(N_LIMBS - 1):
        v = a[..., i] + c
        digits.append(v & _MASK)
        c = v >> _B
    digits.append(a[..., N_LIMBS - 1] + c)
    return jnp.stack(digits, axis=-1)


# ---------------------------------------------------------------------------
# multiplication (the only reducing op)
# ---------------------------------------------------------------------------


def mul(a, b):
    """Montgomery multiply-reduce: a*b*R^-1 mod p, reduced output
    (canonical digits, value in (0, 3p)).  See module docstring bounds."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)

    # schoolbook product via padded outer rows + anti-diagonal gather-sum
    outer = a[..., :, None] * b[..., None, :]                  # [..., N, N]
    padded = jnp.concatenate(
        [outer, jnp.zeros(shape[:-1] + (N_LIMBS, N_LIMBS), jnp.int64)],
        axis=-1)                                               # [..., N, 2N]
    idx = jnp.broadcast_to(_CONV_IDX_J, shape[:-1] + (N_LIMBS, 2 * N_LIMBS))
    rolled = jnp.take_along_axis(padded, idx.astype(jnp.int64), axis=-1)
    T = jnp.sum(rolled, axis=-2)                               # [..., 2N]

    # REDC: clear limbs 0..N-1; static-shift constant adds, no scatters
    for i in range(N_LIMBS):
        m = ((T[..., i] & _MASK) * _N0INV) & _MASK
        T = T + m[..., None] * _P_SHIFTED_J[i]
        carry = T[..., i] >> _B                                # exact: T[i] ≡ 0
        T = T + carry[..., None] * _E_J[i + 1]

    r = T[..., N_LIMBS:]
    # make surely positive, then carry-propagate to canonical digits
    r = r + _P_LIMBS_J
    digits = []
    c = jnp.zeros_like(r[..., 0])
    for i in range(N_LIMBS):
        v = r[..., i] + c
        digits.append(v & _MASK)
        c = v >> _B
    return jnp.stack(digits, axis=-1)


def square(a):
    return mul(a, a)


def to_mont(a):
    return mul(a, _R2_LIMBS_J)


def from_mont(a):
    """Montgomery -> plain residue, canonical in [0, p)."""
    return cond_sub_p(cond_sub_p(mul(a, _ONE_LIMBS_J)))


# ---------------------------------------------------------------------------
# canonicalization + comparisons
# ---------------------------------------------------------------------------


def _geq_p(a):
    """a >= p for canonical-digit a (lexicographic from the top limb)."""
    gt = jnp.zeros(a.shape[:-1], dtype=bool)
    eq_ = jnp.ones(a.shape[:-1], dtype=bool)
    for i in range(N_LIMBS - 1, -1, -1):
        pi = _P_LIMBS_J[i]
        gt = gt | (eq_ & (a[..., i] > pi))
        eq_ = eq_ & (a[..., i] == pi)
    return gt | eq_


def cond_sub_p(a):
    """Subtract p once where a >= p (canonical digits in, canonical out)."""
    d = a - _P_LIMBS_J
    # re-propagate (digits may go negative limb-wise but value >= 0)
    digits = []
    c = jnp.zeros_like(a[..., 0])
    for i in range(N_LIMBS):
        v = d[..., i] + c
        digits.append(v & _MASK)
        c = v >> _B
    d = jnp.stack(digits, axis=-1)
    return jnp.where(_geq_p(a)[..., None], d, a)


def canonical(a):
    """Fully-reduced Montgomery representative in [0, p)."""
    r = mul(a, jnp.asarray(MONT_ONE_LIMBS))  # value in (0, 2p + eps)
    return cond_sub_p(cond_sub_p(r))


def eq_canonical(a, b):
    """Equality of canonical() outputs."""
    return jnp.all(a == b, axis=-1)


def is_zero_canonical(a):
    return jnp.all(a == 0, axis=-1)


# ---------------------------------------------------------------------------
# fixed-exponent powers
# ---------------------------------------------------------------------------


def _exp_bits(e: int) -> np.ndarray:
    return np.array([int(c) for c in bin(e)[2:]], dtype=np.int32)


_P_MINUS_2_BITS = jnp.asarray(_exp_bits(P_INT - 2))


def inv(a):
    """a^(p-2) via square-and-multiply scan over the fixed exponent."""
    def body(acc, bit):
        acc = mul(acc, acc)
        acc = jnp.where(bit > 0, mul(acc, a), acc)
        return acc, None

    # a * 0 (not a broadcast constant) so the carry inherits the input's
    # varying-axes type under shard_map; XLA folds the zero-add
    init = a * 0 + jnp.asarray(MONT_ONE_LIMBS)
    out, _ = jax.lax.scan(body, init, _P_MINUS_2_BITS)
    return out


# ---------------------------------------------------------------------------
# host conversions
# ---------------------------------------------------------------------------


def host_to_mont(x: int) -> np.ndarray:
    return int_to_limbs((x * R_INT) % P_INT)


def host_from_mont(a) -> int:
    return (limbs_to_int(a) * pow(R_INT, -1, P_INT)) % P_INT
