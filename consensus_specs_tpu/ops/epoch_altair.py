"""Vectorized altair+ epoch processing: participation-flag rewards,
inactivity scores, and flag rotation as whole-registry column math
(reference semantics: specs/altair/beacon-chain.md process_rewards_and_
penalties / process_inactivity_updates / process_participation_flag_updates;
the sequential forms loop per validator per flag).

Same architecture as the phase0 pipeline (ops/epoch_jax.py): columns come
off the Merkle backing in one walk (ssz/bulk.py), arithmetic is exact
int64 (bounds: eff <= 32e9 * weight(<=14) * increments(<2^26) << 2^63),
results are written back in one bottom-up rebuild.  The per-deltas-pair
floor-at-zero application order of the spec is replicated exactly.
Sequential originals stay on __wrapped__; differential tests:
tests/spec/altair/test_epoch_vectorization.py.
"""
from __future__ import annotations

import numpy as np

from consensus_specs_tpu.ops.epoch_jax import (
    active_mask,
    registry_columns,
)

# fork -> inactivity penalty quotient constant name (altair raised by
# bellatrix; later forks keep bellatrix's)
INACTIVITY_QUOTIENT = {
    "altair": "INACTIVITY_PENALTY_QUOTIENT_ALTAIR",
}


def _inactivity_quotient(spec) -> int:
    name = INACTIVITY_QUOTIENT.get(
        spec.fork, "INACTIVITY_PENALTY_QUOTIENT_BELLATRIX")
    return int(getattr(spec, name))


def _participation_columns(spec, state):
    """Both epoch participation columns as READONLY resident arrays
    (stf/columns.py): after the block path's last mirror flush these are
    dict probes, not tree walks — the epoch phases below read the same
    physical arrays the engine registered, so a 4-phase transition stops
    paying ~8 full-column unpacks."""
    from consensus_specs_tpu.stf import columns

    return (
        columns.participation_column(state, current=False),
        columns.participation_column(state, current=True),
    )


def _device_columns_policy() -> bool:
    """Whether the per-flag reward loop runs as the fused device program
    over the resident participation column.  ``CSTPU_DEVICE_COLUMNS=1``
    forces it on (``0`` off); the auto policy stays host-side — on the
    CPU XLA backend the dispatch overhead loses to numpy, the same
    measured-not-assumed call ``ops/merkle_resident.py`` makes for the
    balance reduction.  Either path produces bit-identical deltas (exact
    int64; differential: tests/test_device_columns.py)."""
    import os

    env = os.environ.get("CSTPU_DEVICE_COLUMNS")
    if env is not None:
        return env == "1"
    return False


def _flag_deltas_device(spec, state, cols, eligible, in_leak,
                        active_increments, base_reward_per_increment):
    """Fused device twin of the per-flag reward/penalty loop: ONE jit
    dispatch consuming the previous-epoch participation column as a
    device-resident buffer (``stf/columns.device_column`` — uploaded once
    per column VERSION and shared across epoch phases, pjit-partitioned
    over the mesh's validator axis on multi-device backends), instead of
    three host passes over a re-staged copy.  All arithmetic is the same
    exact int64 as the host loop (bounds in the module docstring)."""
    import jax.numpy as jnp

    from consensus_specs_tpu.stf import columns

    prev_epoch = int(spec.get_previous_epoch(state))
    flags_dev = columns.device_column(state, current=False)
    # registry-derived kernel inputs ride the root-keyed device-buffer
    # store (ISSUE 10): uploaded once per registry VERSION, not re-staged
    # per jit call — the registry half of the residency arc
    reg_root = bytes(state.validators.hash_tree_root())
    rewards, penalties = _ensure_jit()(
        flags_dev,
        columns.device_buffer(
            (reg_root, "active", prev_epoch),
            lambda: active_mask(cols, prev_epoch)),
        columns.device_buffer((reg_root, "slashed"),
                              lambda: cols["slashed"]),
        columns.device_buffer(
            (reg_root, "eff_i64"),
            lambda: np.asarray(cols["effective_balance"], dtype=np.int64)),
        jnp.asarray(eligible),
        jnp.asarray([int(w) for w in spec.PARTICIPATION_FLAG_WEIGHTS],
                    dtype=jnp.int64),
        jnp.asarray([
            int(spec.EFFECTIVE_BALANCE_INCREMENT),
            base_reward_per_increment,
            active_increments,
            int(spec.WEIGHT_DENOMINATOR),
            int(in_leak),
            int(spec.TIMELY_HEAD_FLAG_INDEX),
        ], dtype=jnp.int64),
    )
    # host-sync: staged view — the one pull-back of the fused flag
    # program's outputs; the balance fold below stays host-side
    rewards = np.asarray(rewards)
    penalties = np.asarray(penalties)
    return [(rewards[i], penalties[i]) for i in range(rewards.shape[0])]


def _flag_deltas_kernel(flags, active_prev, slashed, eff, eligible,
                        weights, scalars):
    import jax.numpy as jnp

    ebi, brpi, active_increments, weight_den, in_leak, head_index = (
        scalars[0], scalars[1], scalars[2], scalars[3], scalars[4],
        scalars[5])
    base_reward = (eff // ebi) * brpi
    rewards_out, penalties_out = [], []
    for flag_index in range(3):  # static unroll: one fused program
        participating = (active_prev
                         & (((flags >> flag_index) & 1) != 0)
                         & ~slashed)
        participating_increments = (
            jnp.sum(jnp.where(participating, eff, 0)) // ebi)
        weight = weights[flag_index]
        reward_numerator = base_reward * weight * participating_increments
        rewards_out.append(jnp.where(
            eligible & participating & (in_leak == 0),
            reward_numerator // (active_increments * weight_den),
            0))
        penalties_out.append(jnp.where(
            eligible & ~participating & (flag_index != head_index),
            base_reward * weight // weight_den,
            0))
    return jnp.stack(rewards_out), jnp.stack(penalties_out)


_jit_flag_deltas = None  # jitted lazily: this module must import jax-free


def _ensure_jit():
    global _jit_flag_deltas
    if _jit_flag_deltas is None:
        import jax

        from consensus_specs_tpu.ops import epoch_jax  # noqa: F401 - x64 config

        _jit_flag_deltas = jax.jit(_flag_deltas_kernel)
    return _jit_flag_deltas


def _eligible_mask(spec, state, cols):
    prev_epoch = int(spec.get_previous_epoch(state))
    return active_mask(cols, prev_epoch) | (
        cols["slashed"] & (prev_epoch + 1 < cols["withdrawable_epoch"])
    )


def _unslashed_participating_mask(spec, state, cols, flags, flag_index,
                                  epoch=None):
    if epoch is None:
        epoch = int(spec.get_previous_epoch(state))
    has_flag = (flags >> flag_index) & 1
    return active_mask(cols, epoch) & has_flag.astype(bool) & ~cols["slashed"]


def rewards_and_penalties(spec, state) -> None:
    """altair+ process_rewards_and_penalties over columns."""
    from consensus_specs_tpu.ssz import bulk

    if int(spec.get_current_epoch(state)) == int(spec.GENESIS_EPOCH):
        return

    cols = registry_columns(state)
    prev_flags, _ = _participation_columns(spec, state)
    eff = cols["effective_balance"]
    eligible = _eligible_mask(spec, state, cols)

    ebi = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    total_active = int(spec.get_total_active_balance(state))
    active_increments = total_active // ebi
    base_reward_per_increment = (
        ebi * int(spec.BASE_REWARD_FACTOR)
        // int(spec.integer_squareroot(spec.uint64(total_active)))
    )
    base_reward = (eff // ebi) * base_reward_per_increment
    weight_denominator = int(spec.WEIGHT_DENOMINATOR)
    in_leak = bool(spec.is_in_inactivity_leak(state))
    weights = [int(w) for w in spec.PARTICIPATION_FLAG_WEIGHTS]
    timely_head_index = int(spec.TIMELY_HEAD_FLAG_INDEX)
    timely_target_index = int(spec.TIMELY_TARGET_FLAG_INDEX)

    if _device_columns_policy():
        deltas = _flag_deltas_device(
            spec, state, cols, eligible, in_leak, active_increments,
            base_reward_per_increment)
    else:
        deltas = []
        for flag_index, weight in enumerate(weights):
            participating = _unslashed_participating_mask(
                spec, state, cols, prev_flags, flag_index)
            participating_increments = (
                int(np.sum(np.where(participating, eff, 0),
                           dtype=np.uint64)) // ebi
            )
            rewards = np.zeros_like(eff)
            penalties = np.zeros_like(eff)
            if not in_leak:
                reward_numerator = (base_reward * weight
                                    * participating_increments)
                rewards = np.where(
                    eligible & participating,
                    reward_numerator // (active_increments * weight_denominator),
                    0,
                )
            if flag_index != timely_head_index:
                penalties = np.where(
                    eligible & ~participating,
                    base_reward * weight // weight_denominator,
                    0,
                )
            deltas.append((rewards, penalties))

    # inactivity penalties (altair/beacon-chain.md get_inactivity_penalty_deltas)
    # raw uint64 view: scores can exceed int63, so guard on the unsigned max
    scores_u64 = np.asarray(
        bulk._packed_to_numpy(state.inactivity_scores, 8, "<u8"))
    target_participating = _unslashed_participating_mask(
        spec, state, cols, prev_flags, timely_target_index)
    quotient = int(spec.config.INACTIVITY_SCORE_BIAS) * _inactivity_quotient(spec)
    affected = eligible & ~target_participating
    if int(scores_u64.max(initial=0)) < (1 << 27):
        # eff <= 32e9 < 2^35, so eff*score < 2^62: exact in int64.  Scores
        # grow by BIAS(4)/epoch, so this branch covers any realistic state.
        scores = scores_u64.astype(np.int64)
        inact_pen = np.where(affected, eff * scores // quotient, 0)
    else:  # huge scores: exact big-int per affected lane.  The sequential
        # spec's uint64 numerator (eff * score) overflows at 2^64 and
        # raises; mirror that exactly so both pipelines agree bit-for-bit
        # on every representable state.
        inact_pen = np.zeros_like(eff)
        for i in np.nonzero(affected)[0]:
            numerator = int(eff[i]) * int(scores_u64[i])
            if numerator >= 1 << 64:
                raise ValueError(
                    f"value {numerator} out of range for uint64")
            inact_pen[i] = numerator // quotient
    deltas.append((np.zeros_like(eff), inact_pen))

    # the balance column rides the resident store (ISSUE 10): the read is
    # a dict probe after any earlier phase touched it, and the flush
    # stages the written array on the identity fast path so the NEXT
    # phase (slashings, effective-balance hysteresis, the resident-merkle
    # upload) skips the tree walk too
    from consensus_specs_tpu.stf import columns as stf_columns

    balances = stf_columns.balance_column(state)
    for rewards, penalties in deltas:
        balances = balances + rewards
        balances = np.where(penalties > balances, 0, balances - penalties)
    stf_columns.flush_balances(state, balances)


def justification_and_finalization(spec, state) -> None:
    """altair+ process_justification_and_finalization: target balances as
    column sums instead of python index sets."""
    if int(spec.get_current_epoch(state)) <= int(spec.GENESIS_EPOCH) + 1:
        return
    cols = registry_columns(state)
    prev_flags, cur_flags = _participation_columns(spec, state)
    eff = cols["effective_balance"]
    ebi = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    target = int(spec.TIMELY_TARGET_FLAG_INDEX)

    prev_mask = _unslashed_participating_mask(spec, state, cols, prev_flags, target)
    cur_mask = _unslashed_participating_mask(
        spec, state, cols, cur_flags, target,
        epoch=int(spec.get_current_epoch(state)))
    # get_total_balance floors at one increment
    prev_bal = max(ebi, int(np.sum(np.where(prev_mask, eff, 0), dtype=np.uint64)))
    cur_bal = max(ebi, int(np.sum(np.where(cur_mask, eff, 0), dtype=np.uint64)))
    spec.weigh_justification_and_finalization(
        state, spec.get_total_active_balance(state),
        spec.Gwei(prev_bal), spec.Gwei(cur_bal))


def inactivity_updates(spec, state) -> None:
    """altair+ process_inactivity_updates over columns."""
    from consensus_specs_tpu.ssz import bulk

    if int(spec.get_current_epoch(state)) == int(spec.GENESIS_EPOCH):
        return

    cols = registry_columns(state)
    prev_flags, _ = _participation_columns(spec, state)
    eligible = _eligible_mask(spec, state, cols)
    target_participating = _unslashed_participating_mask(
        spec, state, cols, prev_flags, int(spec.TIMELY_TARGET_FLAG_INDEX))

    # raw uint64 view: int64 wrap would corrupt huge scores silently
    scores = np.asarray(
        bulk._packed_to_numpy(state.inactivity_scores, 8, "<u8"))
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    recovery = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)

    # sequential parity: uint64 increment overflow raises in the spec
    if int(scores.max(initial=0)) + bias >= 1 << 64:
        raise ValueError("inactivity score increment out of range for uint64")

    # increase/decrease per participation
    scores = np.where(
        eligible & target_participating,
        scores - np.minimum(np.uint64(1), scores),
        np.where(eligible, scores + np.uint64(bias), scores),
    )
    if not spec.is_in_inactivity_leak(state):
        scores = np.where(
            eligible, scores - np.minimum(np.uint64(recovery), scores), scores)
    bulk.set_packed_uint64_from_numpy(state.inactivity_scores, scores)


def participation_flag_updates(spec, state) -> None:
    """altair+ process_participation_flag_updates: rotate current into
    previous and zero current — two bulk writes instead of an O(n) list
    comprehension of fresh flag objects, registered with the resident
    store so the next epoch's readers keep hitting."""
    from consensus_specs_tpu.stf import columns

    _, current = _participation_columns(spec, state)
    # the rotated array is the store's own readonly current column —
    # registering it under the previous column's new root just shares it
    columns.flush(state, current=False, col=current)
    columns.flush(state, current=True,
                  col=np.zeros(len(current), dtype=np.uint8))
