"""Vectorized altair+ epoch processing: participation-flag rewards,
inactivity scores, and flag rotation as whole-registry column math
(reference semantics: specs/altair/beacon-chain.md process_rewards_and_
penalties / process_inactivity_updates / process_participation_flag_updates;
the sequential forms loop per validator per flag).

Same architecture as the phase0 pipeline (ops/epoch_jax.py): columns come
off the Merkle backing in one walk (ssz/bulk.py), arithmetic is exact
int64 (bounds: eff <= 32e9 * weight(<=14) * increments(<2^26) << 2^63),
results are written back in one bottom-up rebuild.  The per-deltas-pair
floor-at-zero application order of the spec is replicated exactly.
Sequential originals stay on __wrapped__; differential tests:
tests/spec/altair/test_epoch_vectorization.py.
"""
from __future__ import annotations

import numpy as np

from consensus_specs_tpu.ops.epoch_jax import (
    active_mask,
    registry_columns,
)

# fork -> inactivity penalty quotient constant name (altair raised by
# bellatrix; later forks keep bellatrix's)
INACTIVITY_QUOTIENT = {
    "altair": "INACTIVITY_PENALTY_QUOTIENT_ALTAIR",
}


def _inactivity_quotient(spec) -> int:
    name = INACTIVITY_QUOTIENT.get(
        spec.fork, "INACTIVITY_PENALTY_QUOTIENT_BELLATRIX")
    return int(getattr(spec, name))


def _participation_columns(spec, state):
    from consensus_specs_tpu.ssz import bulk

    return (
        bulk.packed_uint8_to_numpy(state.previous_epoch_participation),
        bulk.packed_uint8_to_numpy(state.current_epoch_participation),
    )


def _eligible_mask(spec, state, cols):
    prev_epoch = int(spec.get_previous_epoch(state))
    return active_mask(cols, prev_epoch) | (
        cols["slashed"] & (prev_epoch + 1 < cols["withdrawable_epoch"])
    )


def _unslashed_participating_mask(spec, state, cols, flags, flag_index,
                                  epoch=None):
    if epoch is None:
        epoch = int(spec.get_previous_epoch(state))
    has_flag = (flags >> flag_index) & 1
    return active_mask(cols, epoch) & has_flag.astype(bool) & ~cols["slashed"]


def rewards_and_penalties(spec, state) -> None:
    """altair+ process_rewards_and_penalties over columns."""
    from consensus_specs_tpu.ssz import bulk

    if int(spec.get_current_epoch(state)) == int(spec.GENESIS_EPOCH):
        return

    cols = registry_columns(state)
    prev_flags, _ = _participation_columns(spec, state)
    eff = cols["effective_balance"]
    eligible = _eligible_mask(spec, state, cols)

    ebi = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    total_active = int(spec.get_total_active_balance(state))
    active_increments = total_active // ebi
    base_reward_per_increment = (
        ebi * int(spec.BASE_REWARD_FACTOR)
        // int(spec.integer_squareroot(spec.uint64(total_active)))
    )
    base_reward = (eff // ebi) * base_reward_per_increment
    weight_denominator = int(spec.WEIGHT_DENOMINATOR)
    in_leak = bool(spec.is_in_inactivity_leak(state))
    weights = [int(w) for w in spec.PARTICIPATION_FLAG_WEIGHTS]
    timely_head_index = int(spec.TIMELY_HEAD_FLAG_INDEX)
    timely_target_index = int(spec.TIMELY_TARGET_FLAG_INDEX)

    deltas = []
    for flag_index, weight in enumerate(weights):
        participating = _unslashed_participating_mask(
            spec, state, cols, prev_flags, flag_index)
        participating_increments = (
            int(np.sum(np.where(participating, eff, 0), dtype=np.uint64)) // ebi
        )
        rewards = np.zeros_like(eff)
        penalties = np.zeros_like(eff)
        if not in_leak:
            reward_numerator = base_reward * weight * participating_increments
            rewards = np.where(
                eligible & participating,
                reward_numerator // (active_increments * weight_denominator),
                0,
            )
        if flag_index != timely_head_index:
            penalties = np.where(
                eligible & ~participating,
                base_reward * weight // weight_denominator,
                0,
            )
        deltas.append((rewards, penalties))

    # inactivity penalties (altair/beacon-chain.md get_inactivity_penalty_deltas)
    # raw uint64 view: scores can exceed int63, so guard on the unsigned max
    scores_u64 = np.asarray(
        bulk._packed_to_numpy(state.inactivity_scores, 8, "<u8"))
    target_participating = _unslashed_participating_mask(
        spec, state, cols, prev_flags, timely_target_index)
    quotient = int(spec.config.INACTIVITY_SCORE_BIAS) * _inactivity_quotient(spec)
    affected = eligible & ~target_participating
    if int(scores_u64.max(initial=0)) < (1 << 27):
        # eff <= 32e9 < 2^35, so eff*score < 2^62: exact in int64.  Scores
        # grow by BIAS(4)/epoch, so this branch covers any realistic state.
        scores = scores_u64.astype(np.int64)
        inact_pen = np.where(affected, eff * scores // quotient, 0)
    else:  # huge scores: exact big-int per affected lane.  The sequential
        # spec's uint64 numerator (eff * score) overflows at 2^64 and
        # raises; mirror that exactly so both pipelines agree bit-for-bit
        # on every representable state.
        inact_pen = np.zeros_like(eff)
        for i in np.nonzero(affected)[0]:
            numerator = int(eff[i]) * int(scores_u64[i])
            if numerator >= 1 << 64:
                raise ValueError(
                    f"value {numerator} out of range for uint64")
            inact_pen[i] = numerator // quotient
    deltas.append((np.zeros_like(eff), inact_pen))

    balances = bulk.packed_uint64_to_numpy(state.balances)
    for rewards, penalties in deltas:
        balances = balances + rewards
        balances = np.where(penalties > balances, 0, balances - penalties)
    bulk.set_packed_uint64_from_numpy(state.balances, balances)


def justification_and_finalization(spec, state) -> None:
    """altair+ process_justification_and_finalization: target balances as
    column sums instead of python index sets."""
    if int(spec.get_current_epoch(state)) <= int(spec.GENESIS_EPOCH) + 1:
        return
    cols = registry_columns(state)
    prev_flags, cur_flags = _participation_columns(spec, state)
    eff = cols["effective_balance"]
    ebi = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    target = int(spec.TIMELY_TARGET_FLAG_INDEX)

    prev_mask = _unslashed_participating_mask(spec, state, cols, prev_flags, target)
    cur_mask = _unslashed_participating_mask(
        spec, state, cols, cur_flags, target,
        epoch=int(spec.get_current_epoch(state)))
    # get_total_balance floors at one increment
    prev_bal = max(ebi, int(np.sum(np.where(prev_mask, eff, 0), dtype=np.uint64)))
    cur_bal = max(ebi, int(np.sum(np.where(cur_mask, eff, 0), dtype=np.uint64)))
    spec.weigh_justification_and_finalization(
        state, spec.get_total_active_balance(state),
        spec.Gwei(prev_bal), spec.Gwei(cur_bal))


def inactivity_updates(spec, state) -> None:
    """altair+ process_inactivity_updates over columns."""
    from consensus_specs_tpu.ssz import bulk

    if int(spec.get_current_epoch(state)) == int(spec.GENESIS_EPOCH):
        return

    cols = registry_columns(state)
    prev_flags, _ = _participation_columns(spec, state)
    eligible = _eligible_mask(spec, state, cols)
    target_participating = _unslashed_participating_mask(
        spec, state, cols, prev_flags, int(spec.TIMELY_TARGET_FLAG_INDEX))

    # raw uint64 view: int64 wrap would corrupt huge scores silently
    scores = np.asarray(
        bulk._packed_to_numpy(state.inactivity_scores, 8, "<u8"))
    bias = int(spec.config.INACTIVITY_SCORE_BIAS)
    recovery = int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE)

    # sequential parity: uint64 increment overflow raises in the spec
    if int(scores.max(initial=0)) + bias >= 1 << 64:
        raise ValueError("inactivity score increment out of range for uint64")

    # increase/decrease per participation
    scores = np.where(
        eligible & target_participating,
        scores - np.minimum(np.uint64(1), scores),
        np.where(eligible, scores + np.uint64(bias), scores),
    )
    if not spec.is_in_inactivity_leak(state):
        scores = np.where(
            eligible, scores - np.minimum(np.uint64(recovery), scores), scores)
    bulk.set_packed_uint64_from_numpy(state.inactivity_scores, scores)


def participation_flag_updates(spec, state) -> None:
    """altair+ process_participation_flag_updates: rotate current into
    previous and zero current — two bulk writes instead of an O(n) list
    comprehension of fresh flag objects."""
    from consensus_specs_tpu.ssz import bulk

    _, current = _participation_columns(spec, state)
    bulk.set_packed_uint8_from_numpy(state.previous_epoch_participation, current)
    bulk.set_packed_uint8_from_numpy(
        state.current_epoch_participation,
        np.zeros(len(current), dtype=np.uint8),
    )
