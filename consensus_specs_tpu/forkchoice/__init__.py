"""Batched fork-choice engine: proto-array LMD-GHOST with vectorized
attestation ingestion, behaviorally pinned to the spec ``Store``.

Layers (see docs/architecture.md):

* ``proto_array``  — flat append-only block array, incremental subtree
  weights, O(blocks) ``find_head``;
* ``batch``        — spec-equivalent batched ``on_attestation`` with the
  latest-message fold vectorized over dense validator arrays;
* ``engine``       — the ``on_tick / on_block / on_attestations /
  get_head`` wrapper keeping a real spec ``Store`` and the proto-array
  in lockstep, with head caching and finalized-subtree pruning.
"""
from .engine import ForkChoiceEngine
from .proto_array import ProtoArray

__all__ = ["ForkChoiceEngine", "ProtoArray"]
