"""Flat proto-array LMD-GHOST.

The spec's ``get_head`` (specs/src/phase0.py:1490) re-walks the block
tree and re-sums every validator's latest message on every call —
O(blocks × validators) per head query.  Production clients replaced that
with a *proto-array*: blocks in an append-only flat array with parent
indices, per-node subtree weights maintained incrementally from vote
deltas, so a head query is one O(blocks) pass and ingesting a vote batch
is one segment-sum plus one reverse scan.

Behavioral pin: ``find_head`` reproduces the spec walk *exactly* —

* viability is evaluated at leaves only (``filter_block_tree`` checks the
  leaf state's justified/finalized checkpoints against the store's) and
  propagated to ancestors, not re-checked per node as some clients do;
* a vote for block X counts toward node R iff R is an ancestor-or-self of
  X (the ``get_ancestor(X, R.slot) == R`` condition collapses to subtree
  membership because slots strictly increase along a chain), which is
  exactly the incremental subtree-weight invariant;
* proposer boost is added to a child during the walk iff the child lies
  on the boost root's ancestor chain;
* ties break on the lexicographically larger root.

The node axis (blocks) stays in Python — it is small and append-only.
The validator axis (400k+) is the vectorized one: votes and balances are
dense int64 arrays and every delta reduction goes through
``ops/segment.py``.  Equivalence with the spec ``Store`` is pinned by
tests/spec/phase0/fork_choice/test_engine_differential.py.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from consensus_specs_tpu.ops.segment import segment_sum

Checkpoint = Tuple[int, bytes]  # (epoch, root) snapshot, hashable + comparable


class ProtoArray:
    """Append-only block array with incrementally maintained LMD weights."""

    def __init__(self) -> None:
        self.indices: Dict[bytes, int] = {}
        self.roots: List[object] = []        # node -> Root (spec object)
        self.parents: List[int] = []         # node -> parent index or -1
        self.slots: List[int] = []
        self.justified: List[Checkpoint] = []  # block state's checkpoints
        self.finalized: List[Checkpoint] = []
        self.children: List[List[int]] = []
        self.weights: List[int] = []         # attestation subtree weights
        # validator axis (dense, grown on demand)
        self.vote_node = np.empty(0, dtype=np.int64)   # -1 = no message
        self.vote_epoch = np.empty(0, dtype=np.int64)
        self.balances = np.empty(0, dtype=np.int64)

    def __len__(self) -> int:
        return len(self.roots)

    def __contains__(self, root) -> bool:
        return bytes(root) in self.indices

    def ensure_validators(self, n: int) -> None:
        if n <= len(self.vote_node):
            return
        grow = n - len(self.vote_node)
        self.vote_node = np.concatenate(
            [self.vote_node, np.full(grow, -1, dtype=np.int64)])
        self.vote_epoch = np.concatenate(
            [self.vote_epoch, np.zeros(grow, dtype=np.int64)])
        self.balances = np.concatenate(
            [self.balances, np.zeros(grow, dtype=np.int64)])

    # -- block axis ----------------------------------------------------------

    def insert(self, root, parent_root, slot: int,
               justified: Checkpoint, finalized: Checkpoint) -> int:
        """Append a block node; parents must be inserted before children
        (guaranteed by ``on_block``'s parent-known assert), so a child's
        index is always greater than its parent's."""
        key = bytes(root)
        if key in self.indices:
            return self.indices[key]
        idx = len(self.roots)
        self.indices[key] = idx
        self.roots.append(root)
        self.parents.append(self.indices.get(bytes(parent_root), -1))
        self.slots.append(int(slot))
        self.justified.append(justified)
        self.finalized.append(finalized)
        self.children.append([])
        self.weights.append(0)
        if self.parents[idx] != -1:
            self.children[self.parents[idx]].append(idx)
        return idx

    def node_index(self, root) -> int:
        """Index of ``root``, or -1 when unknown/pruned (a vote there can
        never influence a head walk rooted under the finalized block)."""
        return self.indices.get(bytes(root), -1)

    # -- weight maintenance --------------------------------------------------

    def _apply_deltas(self, deltas: np.ndarray) -> None:
        """One reverse scan: each node absorbs its delta and forwards it to
        its parent — children always have larger indices, so a single
        descending pass settles every subtree sum."""
        acc = deltas.astype(object)  # python ints: no int64 overflow window
        weights, parents = self.weights, self.parents
        for i in range(len(weights) - 1, -1, -1):
            d = acc[i]
            if d:
                weights[i] += d
                p = parents[i]
                if p != -1:
                    acc[p] += d
    # -- vote ingestion ------------------------------------------------------

    def apply_vote_changes(self, validators: np.ndarray,
                           new_nodes: np.ndarray,
                           new_epochs: np.ndarray) -> None:
        """Move each validator's latest message to ``new_nodes`` (index -1 =
        vote for an unknown/pruned block, tracked but weightless) and update
        subtree weights by the balance deltas (one segment-sum per side)."""
        if len(validators) == 0:
            return
        n_nodes = len(self.roots)
        deltas = np.zeros(n_nodes, dtype=np.int64)
        old_nodes = self.vote_node[validators]
        bal = self.balances[validators]
        rem = old_nodes >= 0
        if rem.any():
            deltas -= segment_sum(bal[rem], old_nodes[rem], n_nodes)
        add = new_nodes >= 0
        if add.any():
            deltas += segment_sum(bal[add], new_nodes[add], n_nodes)
        self.vote_node[validators] = new_nodes
        self.vote_epoch[validators] = new_epochs
        self._apply_deltas(deltas)

    def clear_votes(self, validators: np.ndarray) -> None:
        """Equivocation discard: remove the validators' weight and bar the
        slots from ever re-entering the walk (mirror of the spec excluding
        ``equivocating_indices`` from ``get_latest_attesting_balance``)."""
        if len(validators) == 0:
            return
        n_nodes = len(self.roots)
        old_nodes = self.vote_node[validators]
        bal = self.balances[validators]
        rem = old_nodes >= 0
        if rem.any():
            self._apply_deltas(-segment_sum(bal[rem], old_nodes[rem], n_nodes))
        self.vote_node[validators] = -1

    def set_balances(self, balances: np.ndarray) -> None:
        """Swap in the justified-checkpoint state's effective balances and
        rebuild every subtree weight from the standing votes (justified
        changes are rare — at most once per epoch)."""
        self.ensure_validators(len(balances))
        self.balances[:len(balances)] = balances
        self.balances[len(balances):] = 0
        n_nodes = len(self.roots)
        voted = self.vote_node >= 0
        own = segment_sum(self.balances[voted], self.vote_node[voted], n_nodes) \
            if voted.any() else np.zeros(n_nodes, dtype=np.int64)
        self.weights = [0] * n_nodes
        self._apply_deltas(own)

    # -- head selection ------------------------------------------------------

    def _viable(self, store_justified: Checkpoint,
                store_finalized: Checkpoint, genesis_epoch: int) -> List[bool]:
        """Spec ``filter_block_tree`` flags: a leaf is viable iff its block
        state agrees with the store's justified/finalized checkpoints (or
        those are still at genesis); an interior node is viable iff any
        descendant leaf is."""
        n = len(self.roots)
        viable = [False] * n
        check_j = store_justified[0] != genesis_epoch
        check_f = store_finalized[0] != genesis_epoch
        for i in range(n - 1, -1, -1):
            kids = self.children[i]
            if kids:
                viable[i] = any(viable[c] for c in kids)
            else:
                viable[i] = (
                    (not check_j or self.justified[i] == store_justified)
                    and (not check_f or self.finalized[i] == store_finalized))
        return viable

    def _boost_path(self, boost_root: bytes) -> set:
        """Indices on the proposer-boost root's ancestor chain (the nodes
        the spec credits the boost to during the walk)."""
        idx = self.indices.get(boost_root, -1)
        path = set()
        while idx != -1:
            path.add(idx)
            idx = self.parents[idx]
        return path

    def find_head(self, justified_root, store_justified: Checkpoint,
                  store_finalized: Checkpoint, genesis_epoch: int,
                  boost_root: Optional[bytes] = None,
                  boost_score: int = 0):
        """The spec head walk over the flat array: start at the justified
        root, repeatedly descend to the viable child maximizing
        ``(weight + boost, root)``; O(blocks) total."""
        start = self.indices.get(bytes(justified_root))
        assert start is not None, "justified root missing from proto-array"
        viable = self._viable(store_justified, store_finalized, genesis_epoch)
        boosted = self._boost_path(boost_root) if boost_root and boost_score \
            else set()
        head = start
        while True:
            best = -1
            best_key = None
            for c in self.children[head]:
                if not viable[c]:
                    continue
                score = self.weights[c] + (boost_score if c in boosted else 0)
                key = (score, bytes(self.roots[c]))
                if best == -1 or key > best_key:
                    best, best_key = c, key
            if best == -1:
                return self.roots[head]
            head = best

    # -- pruning -------------------------------------------------------------

    def prune(self, finalized_root) -> int:
        """Drop every node outside the finalized root's subtree and remap.
        Kept weights are untouched: a vote for a dropped node only ever
        contributed to dropped subtrees (the finalized root's own subtree
        never contains a dropped descendant).  Returns nodes dropped."""
        fin = self.indices.get(bytes(finalized_root))
        assert fin is not None, "finalized root missing from proto-array"
        if fin == 0 and self.parents[0] == -1:
            return 0
        n = len(self.roots)
        keep = [False] * n
        keep[fin] = True
        for i in range(fin + 1, n):
            p = self.parents[i]
            keep[i] = p != -1 and keep[p]
        remap = np.full(n, -1, dtype=np.int64)
        kept = [i for i in range(n) if keep[i]]
        for new, old in enumerate(kept):
            remap[old] = new
        self.roots = [self.roots[i] for i in kept]
        self.slots = [self.slots[i] for i in kept]
        self.justified = [self.justified[i] for i in kept]
        self.finalized = [self.finalized[i] for i in kept]
        self.weights = [self.weights[i] for i in kept]
        self.parents = [
            int(remap[self.parents[i]]) if self.parents[i] != -1 else -1
            for i in kept]
        self.children = [
            [int(remap[c]) for c in self.children[i] if remap[c] != -1]
            for i in kept]
        self.indices = {bytes(r): i for i, r in enumerate(self.roots)}
        voted = self.vote_node >= 0
        self.vote_node[voted] = remap[self.vote_node[voted]]
        return n - len(kept)
