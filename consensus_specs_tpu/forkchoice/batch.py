"""Vectorized attestation ingestion for the fork-choice engine.

The spec's ``on_attestation`` (specs/src/phase0.py:1644) handles one
attestation at a time: validate, materialize the target checkpoint state,
index the committee, then walk the attesting indices in a Python loop
updating ``store.latest_messages``.  A node serving heavy traffic sees
hundreds of thousands of (mostly unaggregated) attestations per slot;
here the whole batch is flattened into dense ``(validator_index,
target_epoch, attestation_id)`` arrays and the latest-message update
becomes one vectorized reduction.

Spec equivalence, by construction:

* every attestation passes the spec's own ``validate_on_attestation``
  (deduplicated by ``AttestationData`` identity — the checks depend only
  on the data and the store clock, which is constant within a batch), and
  target checkpoint states are materialized with the spec's own
  ``store_target_checkpoint_state``;
* signature validation goes through the spec's
  ``is_valid_indexed_attestation`` whenever BLS is active; with BLS off
  the structural residue (non-empty, sorted-unique indices — sorted and
  unique hold a priori for committee-selected indices) is applied
  vectorized;
* the sequential ``update_latest_messages`` fold — "last write wins only
  with a strictly larger target epoch" — resolves, per validator, to the
  *earliest batch entry carrying the maximum target epoch*, applied only
  when that epoch exceeds the stored one; the reduction computes exactly
  that via one lexsort.  Equivocating validators are skipped, as in the
  spec.

Batch semantics: validation of the WHOLE batch precedes any vote landing,
so an invalid attestation aborts the batch with no votes applied (target
checkpoint states materialized during validation remain, as they would
under the spec).  For single-attestation batches — how the differential
suites replay scenarios — this coincides exactly with the spec handler.

Exception safety (PR 5): this module STAGES, it never commits.  The
returned ``StagedVotes`` carries the winning messages fully materialized;
``commit_votes`` applies them to ``store.latest_messages`` in a loop with
no failure modes left in it.  The engine fires the
``forkchoice.batch.apply`` fault probe between staging and commit and
lands the store fold and the proto-array weight update together — a fault
anywhere in ingestion leaves both exactly as they were (tests/chaos/).
"""
from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from consensus_specs_tpu import tracing
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.telemetry import timeline


class StagedVotes(NamedTuple):
    """A validated, reduced, NOT-yet-applied batch of latest-message
    updates: ``block_roots[att_ids[k]]`` is the LMD vote of
    ``validators[k]``; ``messages`` holds the prebuilt
    ``(ValidatorIndex, LatestMessage)`` pairs ``commit_votes`` applies."""

    validators: np.ndarray
    epochs: np.ndarray
    att_ids: np.ndarray
    block_roots: List
    messages: List


def commit_votes(store, staged: StagedVotes) -> None:
    """Apply a staged batch to ``store.latest_messages`` (the spec's
    ``update_latest_messages`` fold, precomputed): plain dict writes of
    prebuilt objects — nothing here can raise halfway."""
    messages = store.latest_messages
    for vi, msg in staged.messages:
        messages[vi] = msg


def ingest_attestations(
        spec, store, attestations, is_from_block: bool = False
) -> Optional[StagedVotes]:
    """Spec-equivalent batched ``on_attestation`` over ``store``.

    Validates every attestation and reduces the batch to its winning
    messages, WITHOUT applying them.  Returns a ``StagedVotes`` (commit
    with ``commit_votes``), or None when nothing would change.
    """
    attestations = list(attestations)
    if not attestations:
        return None

    # one batch-level timeline span carrying the ingest volume: the
    # index/reduce/stage tracing spans below auto-emit as its children
    # when CSTPU_TIMELINE is armed (ISSUE 11)
    with timeline.span("fc/ingest", atts=len(attestations)):
        return _ingest_attestations(spec, store, attestations, is_from_block)


def _ingest_attestations(spec, store, attestations, is_from_block):
    """The ingest body (non-empty batch), under the caller's span."""
    # Validation + committee resolution, deduplicated by AttestationData
    # identity.  The dedup key is the data's immutable backing node:
    # unaggregated gossip shards one committee's data across hundreds of
    # single-bit attestations, and the shared node lets each of them skip
    # every SSZ field read (the dominant per-attestation cost) as well as
    # the revalidation the spec loop pays per attestation.  Validation of
    # the whole batch still precedes any vote application (the reduce /
    # commit phases below).
    #
    # The BLS-off residue is fully deferred: the loop only EXTENDS one
    # flat Python bit list (C-speed) and records per-attestation geometry;
    # bit->validator resolution then runs as ONE numpy pass over the whole
    # batch through a unique-committee indirection — at 100k unaggregated
    # attestations the per-attestation ``np.asarray`` + gather + append
    # walk this replaces was the batched path's Python floor.
    with tracing.span("forkchoice/ingest/index"):
        tstates = {}     # (target epoch, target root) -> checkpoint state
        committees = {}  # (target epoch, target root, slot, index) -> (ndarray, base)
        data_memo = {}   # id(data backing node) -> per-data tuple
        root_memo = {}   # data hash_tree_root -> per-data tuple
        comm_concat = []     # unique committee arrays, in first-sight order
        comm_concat_len = 0
        n_atts = len(attestations)
        att_epochs = np.empty(n_atts, dtype=np.int64)
        block_roots = []
        att_msgs = []
        LatestMessage = spec.LatestMessage
        verify_sigs = bls.bls_active
        if verify_sigs:
            parts_v = []
            att_counts = np.empty(n_atts, dtype=np.int64)
        else:
            flat_bits: list = []
            att_bases = np.empty(n_atts, dtype=np.int64)
            att_comm_lens = np.empty(n_atts, dtype=np.int64)
            att_comm_bases = np.empty(n_atts, dtype=np.int64)
        for a, att in enumerate(attestations):
            d = att.data
            node = d.get_backing()
            memo = data_memo.get(id(node))
            if memo is None:
                # identity missed: wire-DECODED gossip carries a distinct
                # backing per attestation even when the data is identical
                # (one committee's vote sharded across hundreds of
                # single-bit attestations), so fall through to a content
                # key — ~15 sha256 of a small fixed container, against
                # the full revalidation + committee re-resolution a miss
                # costs.  Sound for the same reason the identity dedup
                # is: validate_on_attestation depends only on the data
                # and the store clock, constant within a batch.  (The
                # node firehose exposed this: identity-only dedup never
                # fired on an SSZ-decoded corpus and throughput fell
                # ~6x vs the same corpus freshly built.)
                memo = root_memo.get(bytes(d.hash_tree_root()))
                if memo is not None:
                    data_memo[id(node)] = memo
            if memo is None:
                spec.validate_on_attestation(store, att, is_from_block)
                spec.store_target_checkpoint_state(store, d.target)
                tkey = (int(d.target.epoch), bytes(d.target.root))
                ckey = tkey + (int(d.slot), int(d.index))
                centry = committees.get(ckey)
                if centry is None:
                    target_state = tstates.get(tkey)
                    if target_state is None:
                        target_state = store.checkpoint_states[d.target]
                        tstates[tkey] = target_state
                    comm = np.fromiter(
                        spec.get_beacon_committee(target_state, d.slot, d.index),
                        dtype=np.int64)
                    centry = committees[ckey] = (comm, comm_concat_len)
                    if not verify_sigs:
                        # the concat/base bookkeeping feeds only the
                        # BLS-off bit-resolution gather below
                        comm_concat.append(comm)
                        comm_concat_len += len(comm)
                # the node rides in the memo value so its id can't be
                # recycled while the memo is alive; the prebuilt
                # LatestMessage (shared by every winner voting this data —
                # the fold only ever stores it) keeps the stage loop off
                # the SSZ view protocol entirely
                memo = (centry[0], centry[1], tkey, d.beacon_block_root,
                        LatestMessage(epoch=d.target.epoch,
                                      root=d.beacon_block_root), node)
                data_memo[id(node)] = memo
                root_memo[bytes(d.hash_tree_root())] = memo
            comm, comm_base, tkey, beacon_root, msg, _ = memo
            block_roots.append(beacon_root)
            att_msgs.append(msg)
            if verify_sigs:
                target_state = tstates[tkey]
                indexed = spec.get_indexed_attestation(target_state, att)
                assert spec.is_valid_indexed_attestation(target_state, indexed)
                idx = np.asarray(indexed.attesting_indices, dtype=np.int64)
                parts_v.append(idx)
                att_counts[a] = len(idx)
            else:
                bl = att.aggregation_bits
                bits = getattr(bl, "_bits", None)
                if bits is None:
                    bits = list(bl)
                if len(bits) < len(comm):
                    # the spec's bit indexing raises IndexError here
                    raise IndexError("aggregation bits shorter than committee")
                att_bases[a] = len(flat_bits)
                att_comm_lens[a] = len(comm)
                att_comm_bases[a] = comm_base
                flat_bits.extend(bits)
            att_epochs[a] = tkey[0]

    with tracing.span("forkchoice/ingest/reduce"):
        if verify_sigs:
            v = np.concatenate(parts_v)
            a = np.repeat(np.arange(n_atts, dtype=np.int64), att_counts)
        else:
            all_bits = np.asarray(flat_bits, dtype=bool)
            pos = np.nonzero(all_bits)[0]
            # position -> owning attestation (bases are sorted by build)
            a = np.searchsorted(att_bases, pos, side="right") - 1
            offset = pos - att_bases[a]
            # bits beyond the committee are ignored (the spec reads
            # bits[i] only for committee members)
            keep = offset < att_comm_lens[a]
            a, offset = a[keep], offset[keep]
            v = np.concatenate(comm_concat)[att_comm_bases[a] + offset]
            # residue of is_valid_indexed_attestation with BLS off: every
            # attestation must select at least one member
            att_counts = np.zeros(n_atts, dtype=np.int64)
            np.add.at(att_counts, a, 1)
            assert att_counts.all()
        e = att_epochs[a]
        if store.equivocating_indices:
            eq = np.fromiter(store.equivocating_indices, dtype=np.int64)
            live = ~np.isin(v, eq)
            v, e, a = v[live], e[live], a[live]
        if len(v) == 0:
            return None
        # per validator: earliest batch entry carrying the maximum epoch
        order = np.lexsort((a, -e, v))
        v_s = v[order]
        lead = np.ones(len(v_s), dtype=bool)
        lead[1:] = v_s[1:] != v_s[:-1]
        win = order[lead]
        wv, we, wa = v[win], e[win], a[win]
        # strictly-larger-epoch gate against the standing messages
        messages = store.latest_messages
        cur = np.fromiter(
            (int(messages[vi].epoch) if vi in messages else -1
             for vi in wv.tolist()),
            dtype=np.int64, count=len(wv))
        upd = we > cur
        if not upd.any():
            return None
        wv, we, wa = wv[upd], we[upd], wa[upd]

    with tracing.span("forkchoice/ingest/stage"):
        ValidatorIndex = spec.ValidatorIndex
        staged_messages = [(ValidatorIndex(vi), att_msgs[ai])
                           for vi, ai in zip(wv.tolist(), wa.tolist())]

    return StagedVotes(wv, we, wa, block_roots, staged_messages)
