"""Fork-choice engine: a real spec ``Store`` + a proto-array, kept in
lockstep behind an ``on_tick / on_block / on_attestations / get_head``
API.

The engine *wraps* a spec ``Store`` (never forks its semantics): block,
tick and slashing handling delegate to the spec handlers on that store,
attestation batches go through the vectorized path in ``batch.py`` (which
updates ``store.latest_messages`` with the spec's exact fold), and the
proto-array mirrors the store's block tree and votes so ``get_head`` is
one O(blocks) array walk instead of the spec's O(blocks × validators)
recursive re-walk.

Invariants (pinned by tests/spec/phase0/fork_choice/test_engine_differential.py):

* ``engine.get_head()`` is byte-identical to ``spec.get_head(store)`` at
  every point in any handler sequence, as are the justified/finalized
  checkpoints (read straight off the wrapped store);
* the wrapped store remains a spec-true ``Store`` — any spec function may
  be applied to it at any time.  The one liberty taken: the justified
  checkpoint's state is materialized eagerly (with the spec's own
  ``store_target_checkpoint_state``) when the justified checkpoint moves,
  where the spec materializes it lazily on the first matching
  attestation; head behavior is identical.
* the head is cached and invalidated on every write (any handler call);
* on finalization the proto-array prunes to the finalized subtree; votes
  for pruned branches keep their latest-message entries (as in the spec)
  but carry no weight — the spec walk, rooted under the finalized block,
  can never count them either.

Effective balances and the proposer-boost score are snapshots of the
justified checkpoint state, refreshed only when the justified checkpoint
moves (at most once per epoch), via the same cached registry columns the
epoch kernels use (``ops/epoch_jax.registry_columns``).
"""
from __future__ import annotations

import numpy as np

from consensus_specs_tpu import faults, telemetry, tracing
from consensus_specs_tpu.telemetry import recorder, timeline

from . import batch
from .proto_array import ProtoArray

_ZERO32 = b"\x00" * 32

# handler/cache activity counters (ISSUE 9): the engine's health was
# previously visible only through tracing spans; these feed the telemetry
# bus so a soak run can watch ingest volume, head-cache effectiveness,
# and prune/refresh cadence over time
stats = {
    "on_block": 0,
    "on_tick": 0,
    "on_attestations": 0,
    "attestations_ingested": 0,
    "on_attester_slashing": 0,
    "head_cache_hits": 0,
    "head_recomputes": 0,
    "prunes": 0,
    "justified_refreshes": 0,
}


def reset_stats() -> None:
    """Zero the handler counters (they are module-wide like the stf
    engine's — one process may run several engines, the counters read as
    node-level activity)."""
    for k in stats:
        stats[k] = 0


def _telemetry_provider() -> dict:
    return dict(stats)


telemetry.register_provider("forkchoice.engine", _telemetry_provider,
                            replace=True)

# fault probes (tests/chaos/): each fires BEFORE its handler's first
# mutation, so an injected failure leaves the wrapped store and the
# proto-array exactly as they were — head parity with the spec walk is
# asserted across the fault in the chaos suite
_SITE_ON_BLOCK = faults.site("forkchoice.on_block")
_SITE_BATCH_APPLY = faults.site("forkchoice.batch.apply")
_SITE_PRUNE = faults.site("forkchoice.prune")


def _cp(checkpoint) -> tuple:
    return (int(checkpoint.epoch), bytes(checkpoint.root))


class ForkChoiceEngine:
    """Proto-array LMD-GHOST over a wrapped spec ``Store``."""

    def __init__(self, spec, store, block_handler=None):
        self.spec = spec
        self.store = store
        # the on_block seam (ISSUE 12): a drop-in replacement for
        # ``spec.on_block(store, signed_block)`` with the SAME contract —
        # same store mutations on success, the spec's exact exception and
        # partial store on failure.  The node subsystem installs its
        # engine-backed handler here (node/service.py routes the state
        # transition through the batched stf engine); None keeps the
        # literal spec handler.
        self._block_handler = block_handler
        self.proto = ProtoArray()
        self._head = None
        self._justified_seen = None
        self._finalized_seen = _cp(store.finalized_checkpoint)
        self._proposer_score = 0
        self._equivocating_seen = set(store.equivocating_indices)
        for root, block in sorted(store.blocks.items(),
                                  key=lambda kv: int(kv[1].slot)):
            self._insert_block(root)
        # a warm store may already carry latest messages: seed the votes
        # BEFORE the checkpoint sync so the balance refresh's full weight
        # rebuild counts them (spec parity holds from the first get_head)
        if store.latest_messages:
            self.proto.ensure_validators(
                int(max(store.latest_messages)) + 1)
            for v, message in store.latest_messages.items():
                if v in store.equivocating_indices:
                    continue
                self.proto.vote_node[int(v)] = \
                    self.proto.node_index(message.root)
                self.proto.vote_epoch[int(v)] = int(message.epoch)
        self._sync_checkpoints()

    # -- store mirroring -----------------------------------------------------

    def _insert_block(self, root) -> None:
        block = self.store.blocks[root]
        state = self.store.block_states[root]
        self.proto.insert(
            root, block.parent_root, int(block.slot),
            _cp(state.current_justified_checkpoint),
            _cp(state.finalized_checkpoint))

    def _refresh_justified(self) -> None:
        """Justified checkpoint moved: snapshot its state's active
        effective balances + proposer-boost score, rebuild weights."""
        spec, store = self.spec, self.store
        jc = store.justified_checkpoint
        spec.store_target_checkpoint_state(store, jc)
        state = store.checkpoint_states[jc]
        from consensus_specs_tpu.ops.epoch_jax import active_mask, registry_columns

        cols = registry_columns(state)
        epoch = int(spec.get_current_epoch(state))
        active = active_mask(cols, epoch)
        balances = np.where(active, cols["effective_balance"], 0)
        self.proto.set_balances(balances)
        num = int(active.sum())
        if num == 0:
            self._proposer_score = 0
            return
        total = max(int(spec.EFFECTIVE_BALANCE_INCREMENT),
                    int(balances.sum(dtype=np.uint64)))
        avg = total // num
        committee_weight = (num // int(spec.SLOTS_PER_EPOCH)) * avg
        self._proposer_score = (
            committee_weight * int(spec.config.PROPOSER_SCORE_BOOST) // 100)

    def _sync_checkpoints(self) -> None:
        jc = _cp(self.store.justified_checkpoint)
        if jc != self._justified_seen:
            # seen-marker moves only after the refresh succeeds: a failure
            # mid-refresh must retry on the next handler call, not leave
            # stale balances behind a marker that says they're fresh.
            # Counter + event move with it — a failed refresh must not be
            # logged as if it happened (same placement as the prune below)
            self._refresh_justified()
            self._justified_seen = jc
            stats["justified_refreshes"] += 1
            recorder.record("fc_justified_refresh", epoch=jc[0])
        fc = _cp(self.store.finalized_checkpoint)
        if fc != self._finalized_seen:
            with tracing.span("forkchoice/prune"):
                # probe before the prune mutates the proto-array; the seen
                # marker moves only after success, so an injected failure
                # here retries the prune on the next handler call
                _SITE_PRUNE()
                self.proto.prune(self.store.finalized_checkpoint.root)
            self._finalized_seen = fc
            stats["prunes"] += 1
            recorder.record("fc_prune", epoch=fc[0])

    # -- handlers ------------------------------------------------------------

    def on_tick(self, time) -> None:
        stats["on_tick"] += 1
        with tracing.span("forkchoice/on_tick"):
            try:
                self.spec.on_tick(self.store, time)
                self._sync_checkpoints()
            finally:
                # invalidate even on a failure part-way: the spec handler
                # may already have moved the store under the cached head
                self._head = None

    def on_block(self, signed_block) -> None:
        stats["on_block"] += 1
        if recorder.enabled():
            recorder.record("fc_on_block",
                            slot=int(signed_block.message.slot))
        # the tracing span auto-emits a timeline event; the explicit span
        # adds the slot field so a Perfetto read can line the fork-choice
        # track up against the stf block flow (ISSUE 11)
        with timeline.span("fc/on_block",
                           slot=int(signed_block.message.slot)), \
                tracing.span("forkchoice/on_block"):
            _SITE_ON_BLOCK()  # pre-mutation: a fault leaves store + proto as-is
            try:
                (self._block_handler or self.spec.on_block)(
                    self.store, signed_block)
                self._insert_block(
                    self.spec.hash_tree_root(signed_block.message))
                self._sync_checkpoints()
            finally:
                self._head = None

    def on_attestations(self, attestations, is_from_block: bool = False) -> None:
        """Batched ``on_attestation``: the whole batch is validated AND
        staged before any vote lands (batch.py), then the store fold and
        the proto-array weight update commit together in a region with no
        failure modes — a fault anywhere up to the commit leaves no
        partially-applied vote deltas."""
        stats["on_attestations"] += 1
        stats["attestations_ingested"] += len(attestations)
        with timeline.span("fc/on_attestations", n=len(attestations)), \
                tracing.span("forkchoice/on_attestations"):
            try:
                staged = batch.ingest_attestations(
                    self.spec, self.store, attestations, is_from_block)
                if staged is not None:
                    self.proto.ensure_validators(
                        int(staged.validators.max()) + 1)
                    nodes = np.fromiter(
                        (self.proto.node_index(staged.block_roots[a])
                         for a in staged.att_ids.tolist()),
                        dtype=np.int64, count=len(staged.att_ids))
                    _SITE_BATCH_APPLY()  # last probed point before the commit
                    batch.commit_votes(self.store, staged)
                    with tracing.span("forkchoice/apply_votes"):
                        self.proto.apply_vote_changes(
                            staged.validators, nodes, staged.epochs)
            finally:
                self._head = None

    def on_attestation(self, attestation, is_from_block: bool = False) -> None:
        self.on_attestations([attestation], is_from_block=is_from_block)

    def on_attester_slashing(self, attester_slashing) -> None:
        stats["on_attester_slashing"] += 1
        with tracing.span("forkchoice/on_attester_slashing"):
            try:
                self.spec.on_attester_slashing(self.store, attester_slashing)
                fresh = self.store.equivocating_indices - self._equivocating_seen
                if fresh:
                    eq = np.fromiter((int(i) for i in fresh), dtype=np.int64)
                    self.proto.ensure_validators(int(eq.max()) + 1)
                    self.proto.clear_votes(eq)
                    # seen-marker moves only after the votes cleared, like
                    # the justified/prune markers: a failure here retries
                    self._equivocating_seen |= fresh
            finally:
                self._head = None

    # -- queries -------------------------------------------------------------

    def get_head(self):
        if self._head is not None:
            stats["head_cache_hits"] += 1
            return self._head
        stats["head_recomputes"] += 1
        with tracing.span("forkchoice/find_head"):
            store = self.store
            boost_root = bytes(store.proposer_boost_root)
            boost = self._proposer_score if boost_root != _ZERO32 else 0
            self._head = self.proto.find_head(
                store.justified_checkpoint.root,
                _cp(store.justified_checkpoint),
                _cp(store.finalized_checkpoint),
                int(self.spec.GENESIS_EPOCH),
                boost_root=boost_root if boost else None,
                boost_score=boost)
        return self._head

    @property
    def justified_checkpoint(self):
        return self.store.justified_checkpoint

    @property
    def finalized_checkpoint(self):
        return self.store.finalized_checkpoint
