"""Persistent XLA compilation cache setup, shared by the kernel modules.

The heavy kernels (batched pairing, epoch deltas) cost minutes of XLA
compile per shape; the persistent cache makes that once-per-machine.
Called only from modules that already import jax — pure-SSZ import paths
never pay the jax import cost.
"""
from __future__ import annotations

import os

_configured = False


def configure() -> None:
    global _configured
    if _configured:
        return
    _configured = True
    if "JAX_COMPILATION_CACHE_DIR" in os.environ:
        return
    import jax

    if jax.config.jax_compilation_cache_dir is not None:
        return
    cache_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".cache", "jax")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except OSError:  # read-only checkout: in-memory cache only
        pass
