"""Tracing + metrics layer (SURVEY §5: the wall-time observability the
reference lacks; essential here because performance is a deliverable).

Three cooperating pieces:

* **Spans** — nested wall-time measurements.  ``span(name)`` is a
  context manager; ``instrument_spec(spec)`` wraps every ``process_*``
  and ``state_transition`` function of a compiled spec module so a whole
  transition self-profiles per phase.  Disabled (default) the wrapper is
  a single attribute check.
* **Counters** — monotonically increasing named counters
  (``count(name)``), e.g. BLS verifications, cache hits.
* **XLA profiler** — ``xla_trace(dir)`` wraps ``jax.profiler.trace`` for
  device-level traces viewable in TensorBoard/XProf.

Snapshot everything with ``report()``; reset with ``reset()``.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict

_enabled = False
_spans: Dict[str, list] = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
_counters: Dict[str, int] = defaultdict(int)
_stack: list = []


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    _spans.clear()
    _counters.clear()
    _stack.clear()


def enabled() -> bool:
    return _enabled


@contextlib.contextmanager
def span(name: str):
    """Nested wall-time span; keys are '/'-joined paths."""
    if not _enabled:
        yield
        return
    _stack.append(name)
    key = "/".join(_stack)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        rec = _spans[key]
        rec[0] += 1
        rec[1] += dt
        _stack.pop()


def count(name: str, n: int = 1) -> None:
    if _enabled:
        _counters[name] += n


def report() -> dict:
    """{'spans': {path: {'count', 'total_s'}}, 'counters': {...}}"""
    return {
        "spans": {
            k: {"count": v[0], "total_s": round(v[1], 6)}
            for k, v in sorted(_spans.items())
        },
        "counters": dict(sorted(_counters.items())),
    }


@contextlib.contextmanager
def xla_trace(log_dir: str):
    """Device-level XLA profiler trace (TensorBoard/XProf format)."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


# --- spec instrumentation ----------------------------------------------------

_INSTRUMENT_PREFIXES = ("process_", "state_transition", "verify_block_signature")


def _wrap(name: str, fn):
    def traced(*args, **kw):
        if not _enabled:
            return fn(*args, **kw)
        with span(name):
            return fn(*args, **kw)

    traced.__name__ = getattr(fn, "__name__", name)
    traced.__wrapped__ = fn
    return traced


def instrument_spec(spec, prefixes=_INSTRUMENT_PREFIXES) -> int:
    """Wrap a compiled spec module's transition functions with spans.
    Idempotent; returns the number of functions (newly) instrumented."""
    g = spec.__dict__
    n = 0
    for name, fn in list(g.items()):
        if not callable(fn) or not name.startswith(tuple(prefixes)):
            continue
        if getattr(fn, "_tracing_instrumented", False):
            continue
        wrapped = _wrap(name, fn)
        wrapped._tracing_instrumented = True
        g[name] = wrapped
        n += 1
    return n
