"""Tracing + metrics facade (SURVEY §5: the wall-time observability the
reference lacks; essential here because performance is a deliverable).

As of ISSUE 9 the implementation lives in
``consensus_specs_tpu.telemetry.metrics`` — this module is the stable
legacy surface every existing callsite (and tests/test_tracing.py) keeps
using, byte-compatible:

* **Spans** — nested wall-time measurements.  ``span(name)`` is a
  context manager; ``instrument_spec(spec)`` wraps every ``process_*``
  and ``state_transition`` function of a compiled spec module so a whole
  transition self-profiles per phase.  Disabled (default) the wrapper is
  a single attribute check.  New underneath: mutation is lock-guarded
  (the native pool / ``parallel/`` paths increment concurrently), span
  nesting is per-thread, and ``instrument_spec`` is re-entrant after
  spec rebuilds (identity-marked wrappers, not copyable flags).
* **Counters** — monotonically increasing named counters
  (``count(name)``), e.g. BLS verifications, cache hits.
* **XLA profiler** — ``xla_trace(dir)`` wraps ``jax.profiler.trace`` for
  device-level traces viewable in TensorBoard/XProf.

Snapshot everything with ``report()``; reset with ``reset()``.  The
report also rides the telemetry bus as the ``"tracing"`` provider
(``telemetry.snapshot()``), next to every other stats producer.
"""
from __future__ import annotations

from consensus_specs_tpu.telemetry.metrics import (  # noqa: F401
    _INSTRUMENT_PREFIXES,
    count,
    disable,
    enable,
    enabled,
    instrument_spec,
    report,
    reset,
    span,
    xla_trace,
)

__all__ = [
    "count", "disable", "enable", "enabled", "instrument_spec", "report",
    "reset", "span", "xla_trace",
]
