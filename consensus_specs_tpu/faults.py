"""Deterministic fault injection: named probes + seeded fault plans.

The engines' whole correctness story rests on containment contracts —
"any trouble rolls the block back and replays the literal spec"
(stf/engine.py), "validation precedes any vote landing"
(forkchoice/batch.py).  Those contracts are only real if failure is a
first-class tested path, so every fragile seam registers a named **fault
site** at import time and probes it on the hot path:

    _SITE = faults.site("stf.verify.native_call")   # module scope
    ...
    _SITE()                    # probe: no-op unless a plan targets it
    value = _SITE(value)       # probe that can corrupt a flowing value

Disabled (the default), a probe is one module-global load and a None
check — nothing to measure in a phase breakdown.  A **FaultPlan** arms
sites with (fire-on-Nth-hit → action) rules:

* ``error``   — raise ``InjectedFault`` (a RuntimeError: the generic
  "something broke mid-phase" the rollback contract must contain);
* ``crash``   — raise ``InjectedBackendCrash`` (an OSError: a native
  backend dying under the caller, feeding the degradation ladder);
* ``corrupt`` — return a deterministically corrupted COPY of the probed
  value (bit flip / off-by-one), modeling poisoned buffers.  On a
  valueless probe it degenerates to ``error``.

Plans activate via ``with faults.inject(plan):`` (tests) or the
``CSTPU_FAULTS`` environment variable (bench/CI chaos runs), e.g.::

    CSTPU_FAULTS="stf.verify.native_call@2=error,stf.sync.rows_memo=corrupt"

Each directive is ``site[@nth][=kind][@procK]`` (nth defaults to 1, kind
to ``error``); ``@nth+`` makes the fault sticky (fires on every hit from
the Nth on).  The trailing ``@procK`` scopes the fault to ONE process of
the dist fabric (``proc0`` is the coordinator, ``proc1..N`` the
workers): the coordinator ships the whole plan to every worker via env,
and each process arms only the faults addressed to it.  With no fabric
active (no process scope set) the scope is ignored and the fault is
armed everywhere — existing plans behave identically.
``FaultPlan.seeded`` draws a reproducible random schedule over
a site subset — the chaos differential suite (tests/chaos/) replays
seeded block walks under such plans and asserts the containment
contracts hold byte-exactly.

Site names are unique by construction (``site()`` raises on a duplicate)
and the registry is closed over by tests/chaos/test_registry_complete.py:
a new site without a chaos case turns that gate red.
"""
from __future__ import annotations

import contextlib
import os
import random
import re
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Fault", "FaultPlan", "InjectedBackendCrash", "InjectedFault",
    "inject", "plan_from_env", "process_scope", "registry",
    "set_process_scope", "site",
]

KINDS = ("error", "crash", "corrupt")

# the only well-formed process scope: procK, K a decimal ordinal
# (proc0 = coordinator, proc1.. = dist workers)
_PROC_RE = re.compile(r"proc\d+")

# this process's identity within an active dist fabric (None outside
# one): the coordinator sets "proc0" while a fabric is alive, workers
# inherit theirs from CSTPU_DIST_PROC at spawn.  Scoped faults fire only
# in their addressed process WHEN a scope is set; with no fabric active
# the scope is ignored and scoped faults are armed everywhere.
_PROC_SCOPE: Optional[str] = None


class InjectedFault(RuntimeError):
    """A generic injected failure: the kind of mid-phase exception the
    engine's rollback contract must contain."""


class InjectedBackendCrash(OSError):
    """An injected native-backend crash (the ctypes layer dying under the
    caller): feeds the degradation ladder, not the generic error path."""


class Fault:
    """One armed rule: fire ``kind`` at ``site`` on the ``nth`` hit
    (1-based; ``sticky`` keeps firing from the nth hit on).  ``proc``
    scopes the rule to one process of the dist fabric (``"proc0"`` =
    coordinator, ``"proc1"``.. = workers); None fires in every
    process."""

    __slots__ = ("site", "nth", "kind", "sticky", "proc")

    def __init__(self, site: str, nth: int = 1, kind: str = "error",
                 sticky: bool = False, proc: Optional[str] = None):
        if nth < 1:
            raise ValueError(f"nth is 1-based, got {nth}")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r} (one of {KINDS})")
        if proc is not None and not _PROC_RE.fullmatch(proc):
            raise ValueError(
                f"malformed process scope {proc!r} (expected procK, e.g. "
                "proc0 for the coordinator, proc1.. for workers)")
        self.site, self.nth, self.kind, self.sticky = site, int(nth), kind, sticky
        self.proc = proc

    def __repr__(self):  # deterministic, used in test ids
        tail = "+" if self.sticky else ""
        scope = f"@{self.proc}" if self.proc else ""
        return f"{self.site}@{self.nth}{tail}={self.kind}{scope}"


class FaultPlan:
    """A deterministic schedule of faults over named sites.

    Tracks per-site hit counts and records every firing in ``fired`` as
    ``(site, hit_number, kind)`` so a chaos case can assert its plan
    actually exercised the seam it claims to."""

    def __init__(self, faults: Iterable[Fault] = ()):
        self._by_site: Dict[str, List[Fault]] = {}
        for f in faults:
            self._by_site.setdefault(f.site, []).append(f)
        self.hits: Dict[str, int] = {}
        self.fired: List[Tuple[str, int, str]] = []

    @classmethod
    def seeded(cls, seed: int, sites: Iterable[str], n_faults: int = 3,
               max_nth: int = 4, kinds: Iterable[str] = ("error",)) -> "FaultPlan":
        """Reproducible random schedule: ``n_faults`` draws of
        (site, nth ≤ max_nth, kind) over ``sites``."""
        rng = random.Random(seed)
        pool, kindpool = sorted(sites), list(kinds)
        return cls(Fault(rng.choice(pool), rng.randint(1, max_nth),
                         rng.choice(kindpool)) for _ in range(n_faults))

    def faults(self) -> List[Fault]:
        return [f for fs in self._by_site.values() for f in fs]

    def _hit(self, name: str, value):
        n = self.hits.get(name, 0) + 1
        self.hits[name] = n
        for f in self._by_site.get(name, ()):
            if (f.proc is not None and _PROC_SCOPE is not None
                    and f.proc != _PROC_SCOPE):
                continue  # addressed to another process of the fabric
            if n == f.nth or (f.sticky and n > f.nth):
                self.fired.append((name, n, f.kind))
                if f.kind == "error" or (f.kind == "corrupt" and value is None):
                    raise InjectedFault(f"injected fault at {name} (hit {n})")
                if f.kind == "crash":
                    raise InjectedBackendCrash(
                        f"injected backend crash at {name} (hit {n})")
                return _corrupt(value)
        return value


def _corrupt(value):
    """Deterministic type-directed corruption of a COPY (never mutates the
    probed object in place — in-place damage to a cached array would
    bypass the very undo logs the chaos suite exists to prove out)."""
    import numpy as np

    if isinstance(value, np.ndarray):
        out = value.copy()
        if out.size:
            if out.dtype == bool:
                out.flat[0] = not out.flat[0]
            else:
                out.flat[0] += 1
        return out
    if isinstance(value, (bytes, bytearray)):
        if not len(value):
            return value
        out = bytearray(value)
        out[0] ^= 0x01
        return bytes(out)
    if isinstance(value, bool):
        return not value
    if isinstance(value, int):
        return value + 1
    raise InjectedFault(f"no corruption rule for {type(value).__name__}")


# -- site registry -------------------------------------------------------------

_SITES: Dict[str, "Site"] = {}
_PLAN: Optional[FaultPlan] = None


class Site:
    """A registered probe point.  Calling it is the probe: near-zero-cost
    when no plan is active, else the plan decides (raise / corrupt /
    pass through)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __call__(self, value=None):
        plan = _PLAN
        if plan is None:
            return value
        return plan._hit(self.name, value)

    def __repr__(self):
        return f"<fault site {self.name}>"


def site(name: str) -> Site:
    """Register (at import time) and return the named probe.  Names are
    dotted paths mirroring the instrumented module; duplicates raise —
    uniqueness is part of the registry-completeness gate."""
    if name in _SITES:
        raise ValueError(f"duplicate fault site {name!r}")
    s = Site(name)
    _SITES[name] = s
    return s


def registry() -> Dict[str, Site]:
    """Snapshot of every registered site (name -> Site)."""
    return dict(_SITES)


# -- activation ----------------------------------------------------------------

@contextlib.contextmanager
def inject(plan: FaultPlan):
    """Arm ``plan`` for the dynamic extent of the block.  Nesting replaces
    the outer plan for the inner extent (the outer plan resumes after)."""
    global _PLAN
    outer = _PLAN
    _PLAN = plan
    try:
        yield plan
    finally:
        _PLAN = outer


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def set_process_scope(scope: Optional[str]) -> None:
    """Declare this process's identity within a dist fabric (``"proc0"``
    for the coordinator, ``"proc1"``.. for workers; None tears the scope
    back down when the fabric stops).  While a scope is set, faults
    carrying a different ``proc`` are skipped; unscoped faults fire as
    always."""
    global _PROC_SCOPE
    if scope is not None and not _PROC_RE.fullmatch(scope):
        raise ValueError(
            f"malformed process scope {scope!r} (expected procK)")
    _PROC_SCOPE = scope


def process_scope() -> Optional[str]:
    return _PROC_SCOPE


def assert_sites_registered(plan: Optional[FaultPlan] = None) -> None:
    """Fail fast on a schedule naming sites the registry doesn't know — a
    typo in ``CSTPU_FAULTS`` would otherwise silently disarm the whole
    chaos run and report a clean row that exercised nothing.  Call AFTER
    the instrumented modules are imported (bench does, before replaying);
    defaults to the active plan."""
    plan = plan if plan is not None else _PLAN
    if plan is None:
        return
    unknown = sorted({f.site for f in plan.faults()} - set(_SITES))
    if unknown:
        raise ValueError(
            f"fault schedule names unregistered sites: {unknown} "
            f"(registered: {sorted(_SITES)})")


def plan_from_env(value: str) -> FaultPlan:
    """Parse a ``CSTPU_FAULTS`` directive string (see module docstring).
    Grammar per directive: ``site[@nth][=kind][@procK]`` — the process
    scope, when present, is the LAST ``@`` segment and must match
    ``proc\\d+`` exactly; anything else starting with ``proc`` after an
    ``@`` is rejected loudly (a typo'd scope must never silently arm the
    fault everywhere)."""
    faults = []
    for raw in value.split(","):
        raw = raw.strip()
        if not raw:
            continue
        proc = None
        if "@" in raw:
            head, tail = raw.rsplit("@", 1)
            if _PROC_RE.fullmatch(tail):
                proc, raw = tail, head
            elif tail.startswith("proc"):
                raise ValueError(
                    f"malformed process scope in fault directive "
                    f"{raw!r}: {tail!r} (expected procK, K a decimal "
                    "ordinal — proc0 = coordinator, proc1.. = workers)")
        kind = "error"
        if "=" in raw:
            raw, kind = raw.rsplit("=", 1)
        nth, sticky = 1, False
        if "@" in raw:
            raw, nth_s = raw.rsplit("@", 1)
            if nth_s.endswith("+"):
                sticky, nth_s = True, nth_s[:-1]
            nth = int(nth_s)
        faults.append(Fault(raw, nth=nth, kind=kind, sticky=sticky,
                            proc=proc))
    return FaultPlan(faults)


def plan_to_env(plan: FaultPlan) -> str:
    """Serialize a plan back to the ``CSTPU_FAULTS`` grammar — the
    coordinator ships its ACTIVE plan to every worker this way, so a
    chaos schedule written for the fabric crosses the process boundary
    verbatim (each process re-parses and arms only the faults addressed
    to it)."""
    return ",".join(repr(f) for f in plan.faults())


_env = os.environ.get("CSTPU_FAULTS")
if _env:  # bench/CI chaos runs: arm the process-wide plan at import
    _PLAN = plan_from_env(_env)
del _env

_env = os.environ.get("CSTPU_DIST_PROC")
if _env:  # dist worker subprocess: scope set before any probe can fire
    set_process_scope(_env)
del _env
