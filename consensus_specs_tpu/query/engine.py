"""The historical query engine (ISSUE 16, leg b): the checkpoint store
as the node's READ path.

A ``QueryEngine`` serves the light-client-shaped workload — state
summaries, per-validator balance/status, latest-vote lookups,
single-validator Merkle proofs, and full state-at-root — off checkpoint
ARTIFACTS, not off the apply loop's fork-choice store.  The artifact is
opened once through ``CheckpointStore.map_payload`` (envelope verified,
mmap kept open), its sections are indexed by OFFSET (meta JSON, the
packed latest-message table for binary search, block frames, and the
``streamproof`` entry table over the tree streams), and from then on:

* proofs walk entry offsets and emit sibling roots straight off the
  map — the state is never materialized, and every proof is verified
  in-engine against the stored state root before it is served (a
  poisoned buffer — the ``query.proof`` chaos probe — surfaces as
  ``QueryError``, never as a wrong answer);
* chunk reads (balance, validator status, list lengths) descend to a
  single generalized index and touch a few pages;
* ``state_at_root`` materializes through the bounded resident set
  (``resident.ResidentStates``): cold states spill, misses re-fault
  off the artifact.

Trouble mid-query rides the PR 14 corruption ladder: a candidate that
fails envelope verification is counted/quarantined by the store; one
that fails SECTION parsing is handed back via
``CheckpointStore.discard_corrupt`` — either way the engine falls to
the next-newest candidate and the apply loop never notices.  Readers
touch only store artifacts and engine-owned caches — never the apply
writer's fork-choice structures (the TH01 role contract for
"query-reader" threads).
"""
from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import List, Optional

from consensus_specs_tpu import faults
from consensus_specs_tpu.persist.store import (
    CheckpointError,
    CheckpointStore,
    decode_tree,
)
from consensus_specs_tpu.ssz.gindex import get_generalized_index

from . import _set_live_engine, stats
from . import streamproof
from .resident import ResidentStates

_SITE_PROOF = faults.site("query.proof")

DEFAULT_MAX_ARTIFACTS = 2
DEFAULT_PROOF_CACHE_CAP = 256
DEFAULT_RESIDENT_CAP = 2


class QueryError(Exception):
    """A query that could not be answered CORRECTLY (verification
    failure, damaged section, injected fault).  Never a wrong answer:
    callers retry or degrade; the apply loop is unaffected."""


class _ArtifactIndex:
    """Offset index over one mapped checkpoint artifact."""

    __slots__ = ("path", "mapped", "meta", "eq_off", "n_eq", "lm_off",
                 "n_lm", "block_frames", "tree_off", "entries",
                 "tree_order", "tops", "head_state_root")

    def __init__(self, path, mapped):
        self.path = path
        self.mapped = mapped


def _u32(buf, off: int) -> int:
    return int.from_bytes(buf[off:off + 4], "little")


def _u64(buf, off: int) -> int:
    return int.from_bytes(buf[off:off + 8], "little")


def _parse_index(path: str, mapped) -> _ArtifactIndex:
    """Walk ``serialize_checkpoint``'s section layout recording offsets
    (nothing is decoded but the small meta JSON); raises
    ``CheckpointError`` on any structural surprise."""
    idx = _ArtifactIndex(path, mapped)
    buf, off, end = mapped.buf, mapped.start, mapped.stop
    try:
        n = _u32(buf, off)
        off += 4
        idx.meta = json.loads(bytes(buf[off:off + n]).decode())
        off += n
        idx.n_eq = _u32(buf, off)
        off += 4
        idx.eq_off = off
        off += 8 * idx.n_eq
        idx.n_lm = _u32(buf, off)
        off += 4
        idx.lm_off = off
        off += 48 * idx.n_lm
        window = [bytes.fromhex(h) for h in idx.meta["window"]]
        idx.block_frames = {}
        for root in window:
            n = _u32(buf, off)
            off += 4
            idx.block_frames[root] = (off, n)
            off += n
        if off > end:
            raise CheckpointError("checkpoint sections overrun the payload")
        idx.tree_off = off
        entries: List[Optional[tuple]] = []
        idx.tree_order = []
        idx.tops = {}
        for block_root in window:
            eid, off = streamproof.parse_tree(buf, off, entries)
            state_root = streamproof.entry_root(buf, entries, eid)
            idx.tree_order.append(state_root)
            idx.tops[state_root] = eid
        idx.entries = entries
        idx.head_state_root = bytes.fromhex(idx.meta["head_state_root"])
        if idx.head_state_root not in idx.tops:
            raise CheckpointError("head state missing from tree streams")
        if off != end:
            raise CheckpointError("trailing bytes after tree streams")
    except CheckpointError:
        raise
    except Exception as exc:
        raise CheckpointError(f"malformed checkpoint sections: {exc!r}")
    return idx


class QueryEngine:
    """Serving surface over a ``CheckpointStore``'s artifacts.  One lock
    guards the artifact index, the proof cache, and the resident set —
    queries from any number of reader threads serialize on it (the
    engine owns no thread; readers bring their own)."""

    def __init__(self, spec, store: CheckpointStore,
                 max_artifacts: int = DEFAULT_MAX_ARTIFACTS,
                 proof_cache_cap: int = DEFAULT_PROOF_CACHE_CAP,
                 resident_cap: int = DEFAULT_RESIDENT_CAP):
        self.spec = spec
        self._store = store
        self._lock = threading.RLock()
        self._artifacts: "OrderedDict[str, _ArtifactIndex]" = OrderedDict()
        self._max_artifacts = max(1, int(max_artifacts))
        self._proof_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._proof_cache_cap = max(1, int(proof_cache_cap))
        self._resident = ResidentStates(resident_cap)
        _set_live_engine(self)

    # -- telemetry -----------------------------------------------------------

    def cache_gauges(self) -> dict:
        with self._lock:
            return {
                "artifact_index_size": len(self._artifacts),
                "artifact_index_cap": self._max_artifacts,
                "proof_cache_size": len(self._proof_cache),
                "proof_cache_cap": self._proof_cache_cap,
                "resident_size": self._resident.size(),
                "resident_cap": self._resident.cap,
            }

    def reset(self) -> None:
        """Drop every cache (the registered CC01 invalidation): mapped
        artifacts close, proofs and resident states rebuild lazily."""
        with self._lock:
            for idx in self._artifacts.values():
                idx.mapped.close()
            self._artifacts.clear()
            self._proof_cache.clear()
            self._resident.clear()

    # -- artifact index ------------------------------------------------------

    def _current(self) -> Optional[_ArtifactIndex]:
        """The newest servable artifact: cached index, else map + parse,
        walking the candidate ladder on damage.  Caller holds the lock."""
        for path in self._store.candidates():
            idx = self._artifacts.get(path)
            if idx is not None:
                self._artifacts.move_to_end(path)
                return idx
            try:
                mapped = self._store.map_payload(path)
            except CheckpointError:
                # counted/quarantined by the store; next candidate
                stats["artifact_corrupt"] += 1
                continue
            try:
                idx = _parse_index(path, mapped)
            except Exception as exc:
                mapped.close()
                stats["artifact_corrupt"] += 1
                self._store.discard_corrupt(path, exc)
                continue
            stats["artifact_loads"] += 1
            self._artifacts[path] = idx
            while len(self._artifacts) > self._max_artifacts:
                _p, old = self._artifacts.popitem(last=False)
                old.mapped.close()
            return idx
        return None

    def _resolve(self, idx: _ArtifactIndex,
                 state_root: Optional[bytes]) -> tuple:
        sr = idx.head_state_root if state_root is None else bytes(state_root)
        eid = idx.tops.get(sr)
        return (sr, eid) if eid is not None else (sr, None)

    # -- queries -------------------------------------------------------------

    def summary(self) -> Optional[dict]:
        """Head/vote summary off the newest artifact's meta section."""
        with self._lock:
            idx = self._current()
            if idx is None:
                stats["queries_unserved"] += 1
                return None
            m = idx.meta
            stats["queries_served"] += 1
            return {
                "journal_pos": int(m["journal_pos"]),
                "head_block_root": m["window"][-1],
                "head_state_root": m["head_state_root"],
                "window_depth": len(m["window"]),
                "justified": list(m["justified"]),
                "finalized": list(m["finalized"]),
                "n_latest_messages": idx.n_lm,
                "n_equivocating": idx.n_eq,
                "time": int(m["time"]),
            }

    def historical_roots(self) -> List[bytes]:
        """State roots servable from the newest artifact (stream order,
        oldest first)."""
        with self._lock:
            idx = self._current()
            return list(idx.tree_order) if idx is not None else []

    def _chunk(self, idx, eid, gindex) -> bytes:
        return streamproof.node_root_at(
            idx.mapped.buf, idx.entries, eid, gindex)

    def _list_len(self, idx, eid, field: str) -> int:
        g = get_generalized_index(self.spec.BeaconState, field, "__len__")
        return _u64(self._chunk(idx, eid, g), 0)

    def balance_of(self, validator_index: int,
                   state_root: Optional[bytes] = None) -> Optional[int]:
        """One validator's balance: a single packed-chunk descent."""
        i = int(validator_index)
        with self._lock:
            idx = self._current()
            if idx is None:
                stats["queries_unserved"] += 1
                return None
            sr, eid = self._resolve(idx, state_root)
            if eid is None or i >= self._list_len(idx, eid, "balances"):
                stats["queries_unserved"] += 1
                return None
            g = get_generalized_index(self.spec.BeaconState, "balances", i)
            chunk = self._chunk(idx, eid, g)
            stats["queries_served"] += 1
            return int.from_bytes(chunk[(i % 4) * 8:(i % 4) * 8 + 8],
                                  "little")

    _STATUS_FIELDS = ("effective_balance", "activation_eligibility_epoch",
                      "activation_epoch", "exit_epoch", "withdrawable_epoch")

    def validator_status(self, validator_index: int,
                         state_root: Optional[bytes] = None) -> Optional[dict]:
        """One validator's lifecycle fields: a handful of chunk reads
        under the registry leaf — the state is never materialized."""
        i = int(validator_index)
        with self._lock:
            idx = self._current()
            if idx is None:
                stats["queries_unserved"] += 1
                return None
            sr, eid = self._resolve(idx, state_root)
            if eid is None or i >= self._list_len(idx, eid, "validators"):
                stats["queries_unserved"] += 1
                return None
            typ = self.spec.BeaconState
            out = {"index": i}
            for field in self._STATUS_FIELDS:
                g = get_generalized_index(typ, "validators", i, field)
                out[field] = _u64(self._chunk(idx, eid, g), 0)
            g = get_generalized_index(typ, "validators", i, "slashed")
            out["slashed"] = bool(self._chunk(idx, eid, g)[0])
            stats["queries_served"] += 1
            return out

    def proof_of_validator(self, validator_index: int,
                           state_root: Optional[bytes] = None) -> Optional[dict]:
        """A single-validator Merkle proof off the mmap'd tree stream,
        verified in-engine against the stored state root before it is
        served.  ``branch`` is leaf-side first (``is_valid_merkle_branch``
        / ``ssz.gindex.build_proof`` ordering)."""
        i = int(validator_index)
        with self._lock:
            idx = self._current()
            if idx is None:
                stats["queries_unserved"] += 1
                return None
            sr, eid = self._resolve(idx, state_root)
            if eid is None or i >= self._list_len(idx, eid, "validators"):
                stats["queries_unserved"] += 1
                return None
            g = get_generalized_index(self.spec.BeaconState, "validators", i)
            key = (idx.path, sr, g)
            cached = self._proof_cache.get(key)
            if cached is not None:
                self._proof_cache.move_to_end(key)
                stats["proof_cache_hits"] += 1
                leaf, branch = cached
            else:
                stats["proof_cache_misses"] += 1
                leaf, branch = streamproof.proof_at(
                    idx.mapped.buf, idx.entries, eid, g)
                self._proof_cache[key] = (leaf, branch)
                while len(self._proof_cache) > self._proof_cache_cap:
                    self._proof_cache.popitem(last=False)
            # the chaos probe models a poisoned serving buffer: the
            # in-engine verification below must catch it — a QueryError,
            # never a wrong proof
            leaf = _SITE_PROOF(leaf)
            if not streamproof.verify_proof(leaf, branch, g, sr):
                stats["faults_in"] += 1
                raise QueryError(
                    f"proof for validator {i} failed verification "
                    f"against state root {sr.hex()[:16]}")
            stats["proofs_served"] += 1
            stats["queries_served"] += 1
            return {"validator_index": i, "gindex": g, "leaf": leaf,
                    "branch": branch, "state_root": sr}

    def vote_of(self, validator_index: int) -> Optional[dict]:
        """The validator's latest message, by binary search over the
        packed (u64 index, u64 epoch, root) table on the map."""
        i = int(validator_index)
        with self._lock:
            idx = self._current()
            if idx is None:
                stats["queries_unserved"] += 1
                return None
            buf, base = idx.mapped.buf, idx.lm_off
            lo, hi = 0, idx.n_lm
            while lo < hi:
                mid = (lo + hi) // 2
                v = _u64(buf, base + 48 * mid)
                if v < i:
                    lo = mid + 1
                elif v > i:
                    hi = mid
                else:
                    off = base + 48 * mid
                    stats["queries_served"] += 1
                    return {"validator_index": i,
                            "epoch": _u64(buf, off + 8),
                            "root": bytes(buf[off + 16:off + 48])}
            stats["queries_served"] += 1
            return None

    def state_at_root(self, state_root: Optional[bytes] = None):
        """A materialized historical state, through the bounded resident
        set: a miss re-faults off the artifact (decode in stream order —
        REFs point backward across the window's trees)."""
        with self._lock:
            idx = self._current()
            if idx is None:
                stats["queries_unserved"] += 1
                return None
            sr, eid = self._resolve(idx, state_root)
            if eid is None:
                stats["queries_unserved"] += 1
                return None
            try:
                state = self._resident.get(
                    sr, lambda: self._materialize(idx, sr))
            except (CheckpointError, faults.InjectedFault,
                    faults.InjectedBackendCrash) as exc:
                # a failed refault (damage or the chaos probe) never
                # installed anything: the resident set is coherent and
                # the next query re-faults honestly
                stats["faults_in"] += 1
                raise QueryError(str(exc)) from exc
            stats["queries_served"] += 1
            return state

    def _materialize(self, idx: _ArtifactIndex, state_root: bytes):
        stats["state_materializations"] += 1
        nodes: List[Optional[object]] = []
        buf, off = idx.mapped.buf, idx.tree_off
        for root in idx.tree_order:
            backing, off = decode_tree(buf, off, nodes)
            if root == state_root:
                return self.spec.BeaconState.view_from_backing(backing)
        raise CheckpointError("state root missing from tree streams")
