"""Historical-state query subsystem (ISSUE 16; ROADMAP item 3).

The PR 14 checkpoint store was write-only: artifacts existed to survive
crashes.  This package turns it into the node's READ path and its
universal cold-start path:

* ``coldstart.restore_or_build`` — checkpoint-sync under every cold
  start: bench/soak/firehose state builds route through a snapshot
  artifact (root-deduped subtree decode, byte-identical post-state
  asserted once per artifact) instead of genesis replay
  (``CSTPU_NO_CHECKPOINT_SYNC=1`` forces the literal path);
* ``streamproof`` — an offset index over the ``encode_tree`` stream
  plus a Merkle-proof walker that emits sibling hashes along a
  generalized-index path straight off the mmap'd artifact, without
  materializing the state;
* ``engine.QueryEngine`` — state-at-root, per-validator balance/status,
  head/vote summaries, and single-validator proofs served off store
  artifacts, exposed on the ``Node`` beside the apply loop;
* ``resident`` — the bounded materialized-state cache: cold window
  states spill (the artifact is the source of truth) and re-fault
  lazily through the same read path, so soaks hold flat RSS;
* ``harness`` — the concurrent query-load harness ("query-reader"
  threads) running against the live firehose.

One module-wide ``stats`` dict feeds the ``query`` telemetry provider
(proof cache hits, faults-in, spill/refault counters, cold-start
counters); live cache size/cap gauges ride a weakref to the most recent
engine, the ``persist`` provider's spelling, so soak's cap-flatness
sweep picks every new cache up unchanged.
"""
from __future__ import annotations

import threading
import weakref
from typing import Optional

from consensus_specs_tpu import telemetry

stats = {
    "queries_served": 0,        # successfully answered engine queries
    "queries_unserved": 0,      # no artifact yet / exhausted candidates
    "proofs_served": 0,
    "proof_cache_hits": 0,
    "proof_cache_misses": 0,
    "artifact_loads": 0,        # artifact indexes parsed (mmap + section walk)
    "artifact_corrupt": 0,      # artifacts the ENGINE handed to the ladder
    "faults_in": 0,             # queries that absorbed an injected/IO fault
    "state_materializations": 0,  # full window decodes feeding the resident set
    "spills": 0,                # resident states dropped back to the store
    "refaults": 0,              # resident misses re-decoded off the artifact
    "coldstart_restores": 0,    # cold starts served from a snapshot artifact
    "coldstart_builds": 0,      # literal builds (miss, opt-out, or corrupt)
    "coldstart_writes": 0,      # snapshot artifacts written after a build
    "coldstart_corrupt": 0,     # snapshot artifacts quarantined at restore
}

# most recent engine, for the size/cap gauges (the persist provider's
# weakref idiom — a dead engine reports empty, never stale)
_LIVE_ENGINE: Optional[weakref.ref] = None
_LIVE_LOCK = threading.Lock()


def reset_stats() -> None:
    for k in stats:
        stats[k] = 0


def _set_live_engine(engine) -> None:
    global _LIVE_ENGINE
    with _LIVE_LOCK:
        _LIVE_ENGINE = weakref.ref(engine)


def _telemetry_provider() -> dict:
    out = dict(stats)
    with _LIVE_LOCK:
        live = _LIVE_ENGINE() if _LIVE_ENGINE is not None else None
    gauges = live.cache_gauges() if live is not None else {}
    out.update(gauges)
    return out


telemetry.register_provider("query", _telemetry_provider, replace=True)
