"""Merkle proofs straight off the ``encode_tree`` stream (ISSUE 16).

``persist/store.py`` serializes window states as root-deduped subtree
streams: LEAF/ZERO/PACKED/BRANCH records with every root written inline
and REF records pointing backward into the shared emission order.  That
layout means a proof does not need the state at all — one linear parse
turns the stream into an **entry table** of offsets (no node objects,
no root copies), and a generalized-index walk descends entry to entry
reading sibling roots out of the buffer, synthesizing the only roots
the stream omits (packed interiors, zero subtrees) from the raw bytes.
The buffer can be (and in the engine is) the live mmap of a verified
artifact: proving one validator out of a 400k registry touches a few
pages, never the registry.

Entry kinds (tuples, index-aligned with ``encode_tree``'s dedup order
so REFs resolve by table position):

* ``(LEAF, root_off)`` — 32 content bytes at ``root_off``;
* ``(ZERO, depth)`` — the shared zero subtree;
* ``(PACKED, depth, data_off, data_len, root_off)`` — a packed column's
  raw bytes; descent halves the byte range exactly like
  ``PackedLazySubtree._child`` and hashes sibling halves with
  ``packed_subtree_root``;
* ``(BRANCH, root_off, left_id, right_id)`` — root plus child entries.

Proof ordering matches ``ssz.gindex.build_proof`` byte for byte:
sibling hashes leaf-side first, verifiable with ``verify_proof`` (the
``is_valid_merkle_branch`` fold) against the stored root.
"""
from __future__ import annotations

from hashlib import sha256
from typing import List, Optional, Tuple

from consensus_specs_tpu.persist.store import CheckpointError
from consensus_specs_tpu.ssz.hashing import ZERO_HASHES
from consensus_specs_tpu.ssz.node import packed_subtree_root

_TAG_LEAF = 0x01
_TAG_ZERO = 0x02
_TAG_PACKED = 0x03
_TAG_BRANCH = 0x04
_TAG_REF = 0x05

LEAF = 0
ZERO = 1
PACKED = 2
BRANCH = 3


def parse_tree(buf, off: int, entries: List[Optional[tuple]]) -> Tuple[int, int]:
    """Parse one tree from ``buf`` at ``off`` into ``entries`` (the
    shared REF table, same emission order as ``encode_tree``'s index);
    returns ``(entry_id, next_off)``.  Structure only — no node objects,
    no root copies; a malformed stream raises ``CheckpointError`` (one
    more rung of the corruption ladder, never a crash)."""
    tag = buf[off]
    off += 1
    if tag == _TAG_REF:
        ref = int.from_bytes(buf[off:off + 4], "little")
        if ref >= len(entries) or entries[ref] is None:
            raise CheckpointError(f"forward tree ref {ref}")
        return ref, off + 4
    slot = len(entries)
    entries.append(None)
    if tag == _TAG_ZERO:
        entry = (ZERO, buf[off])
        off += 1
    elif tag == _TAG_LEAF:
        entry = (LEAF, off)
        off += 32
    elif tag == _TAG_PACKED:
        depth = buf[off]
        n = int.from_bytes(buf[off + 1:off + 9], "little")
        off += 9
        entry = (PACKED, depth, off, n, off + n)
        off += n + 32
    elif tag == _TAG_BRANCH:
        root_off = off
        off += 32
        left, off = parse_tree(buf, off, entries)
        right, off = parse_tree(buf, off, entries)
        entry = (BRANCH, root_off, left, right)
    else:
        raise CheckpointError(f"unknown tree tag {tag:#x} at {off - 1}")
    if off > len(buf):
        raise CheckpointError("tree stream truncated")
    entries[slot] = entry
    return slot, off


def entry_root(buf, entries: List[tuple], entry_id: int) -> bytes:
    """The 32-byte root of ``entry_id``, read (not computed) from the
    stream — integrity is the artifact digest's job."""
    e = entries[entry_id]
    kind = e[0]
    if kind == ZERO:
        return ZERO_HASHES[e[1]]
    if kind == LEAF:
        return bytes(buf[e[1]:e[1] + 32])
    if kind == PACKED:
        return bytes(buf[e[4]:e[4] + 32])
    return bytes(buf[e[1]:e[1] + 32])  # BRANCH


# -- descent cursors -----------------------------------------------------------
#
# Proofs walk VIRTUAL nodes: an entry, or a position inside a packed
# byte region, or a zero subtree — ('e', id) | ('p', depth, start, len)
# | ('z', depth).  Packed halving mirrors PackedLazySubtree._child.


def _children(buf, entries, cur):
    kind = cur[0]
    if kind == "e":
        e = entries[cur[1]]
        ek = e[0]
        if ek == BRANCH:
            return ("e", e[2]), ("e", e[3])
        if ek == ZERO:
            d = e[1] - 1
            return ("z", d), ("z", d)
        if ek == PACKED:
            return _packed_children(e[1], e[2], e[3])
        raise CheckpointError("proof path descends past a leaf")
    if kind == "z":
        d = cur[1] - 1
        if d < 0:
            raise CheckpointError("proof path descends past a leaf")
        return ("z", d), ("z", d)
    # packed region
    return _packed_children(cur[1], cur[2], cur[3])


def _packed_children(depth, start, length):
    d = depth - 1
    if d < 0:
        raise CheckpointError("proof path descends past a leaf")
    half = 32 << d
    left = ("p", d, start, min(length, half))
    right_len = length - half
    right = ("p", d, start + half, right_len) if right_len > 0 else ("z", d)
    return left, right


def _cursor_root(buf, cur) -> bytes:
    kind = cur[0]
    if kind == "z":
        return ZERO_HASHES[cur[1]]
    # packed region: synthesize the root from the raw bytes (the stream
    # only stores the region's TOP root); all-zero folds to ZERO_HASHES
    # inside packed_subtree_root
    _k, d, start, length = cur
    if length <= 0:
        return ZERO_HASHES[d]
    return packed_subtree_root(bytes(buf[start:start + length]), d)


def _resolve_root(buf, entries, cur) -> bytes:
    if cur[0] == "e":
        return entry_root(buf, entries, cur[1])
    return _cursor_root(buf, cur)


def node_root_at(buf, entries, root_id: int, gindex: int) -> bytes:
    """Root of the node addressed by ``gindex`` under entry ``root_id``.
    For chunk-level gindices this IS the chunk's 32 content bytes (a
    leaf's root is its content; a depth-0 packed slice pads raw data) —
    the balance/status read path."""
    depth = gindex.bit_length() - 1
    index = gindex - (1 << depth)
    cur = ("e", root_id)
    for k in range(depth - 1, -1, -1):
        left, right = _children(buf, entries, cur)
        cur = right if (index >> k) & 1 else left
    return _resolve_root(buf, entries, cur)


def proof_at(buf, entries, root_id: int, gindex: int) -> Tuple[bytes, List[bytes]]:
    """(leaf, branch) for ``gindex`` under entry ``root_id``: the
    addressed node's root plus sibling hashes leaf-side first — exactly
    ``ssz.gindex.build_proof`` over the materialized tree, generated off
    stream offsets instead."""
    depth = gindex.bit_length() - 1
    index = gindex - (1 << depth)
    branch: List[bytes] = []
    cur = ("e", root_id)
    for k in range(depth - 1, -1, -1):
        left, right = _children(buf, entries, cur)
        if (index >> k) & 1:
            branch.append(_resolve_root(buf, entries, left))
            cur = right
        else:
            branch.append(_resolve_root(buf, entries, right))
            cur = left
    return _resolve_root(buf, entries, cur), list(reversed(branch))


def verify_proof(leaf: bytes, branch, gindex: int, root: bytes) -> bool:
    """The ``is_valid_merkle_branch`` fold (leaf-side-first branch):
    True iff ``leaf`` at ``gindex`` plus ``branch`` hashes to ``root``."""
    depth = gindex.bit_length() - 1
    index = gindex - (1 << depth)
    if len(branch) != depth:
        return False
    value = bytes(leaf)
    for k, sib in enumerate(branch):
        sib = bytes(sib)
        if (index >> k) & 1:
            value = sha256(sib + value).digest()
        else:
            value = sha256(value + sib).digest()
    return value == bytes(root)
