"""Store-backed eviction for materialized states (ISSUE 16, leg c).

The resident set is the only place a materialized historical state
lives: a bounded LRU keyed by state root.  Spilling an entry is FREE —
the mmap'd artifact is the source of truth, so eviction just drops the
reference — and a miss re-faults lazily through the engine's decode
path (``persist.refault`` is the chaos probe on that seam).  A refault
is only admitted if the decoded state's root equals the requested key
(memoized from the stream — a field read), so an injected fault or a
rotten artifact can fail a query but can never leave the set
incoherent.

Not a lock owner: the engine calls every method under its own lock
(declared via ``lock_holders`` in the concurrency registry).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable

from consensus_specs_tpu import faults
from consensus_specs_tpu.persist.store import CheckpointError

from . import stats

_SITE_REFAULT = faults.site("persist.refault")


class ResidentStates:
    """Bounded root-keyed LRU of materialized window states."""

    def __init__(self, cap: int = 2):
        if cap < 1:
            raise ValueError(f"resident cap must be >= 1, got {cap}")
        self._cap = cap
        self._states: "OrderedDict[bytes, object]" = OrderedDict()

    @property
    def cap(self) -> int:
        return self._cap

    def size(self) -> int:
        return len(self._states)

    def roots(self):
        return list(self._states)

    def get(self, root: bytes, loader: Callable):
        """The resident state for ``root``, re-faulting through
        ``loader`` on a miss.  The entry lands only after the coherence
        check — a loader that raises (the refault probe, a damaged
        artifact) leaves the set exactly as it was."""
        root = bytes(root)
        state = self._states.get(root)
        if state is not None:
            self._states.move_to_end(root)
            return state
        _SITE_REFAULT()
        stats["refaults"] += 1
        state = loader()
        if bytes(state.hash_tree_root()) != root:
            raise CheckpointError(
                "refaulted state root mismatch: artifact served the "
                "wrong tree")
        self._states[root] = state
        while len(self._states) > self._cap:
            self._states.popitem(last=False)
            stats["spills"] += 1
        return state

    def clear(self) -> None:
        """Drop every resident state (the registered CC01 invalidation;
        entries rebuild lazily and honestly through ``get``)."""
        self._states.clear()
