"""Checkpoint-sync under every cold start (ISSUE 16, leg a).

``state_build_s`` is the tax this module retires: every bench row, soak
profile, and firehose scaffold used to rebuild its anchor state from
genesis, seconds-to-half-a-minute per process at mainnet registry
sizes.  ``restore_or_build`` is the seam those builds route through
now: the state snapshots to a root-deduped subtree artifact (the
checkpoint store's tree codec under the atomic envelope) on first
build, and every later cold start decodes it back in milliseconds —
byte-identical, asserted once per artifact by re-encoding the decoded
tree and comparing streams.

Trust ladder, matching the store's: a missing artifact is a plain miss
(build), a stale tag is a codec/shape miss (build, re-snapshot), and
damage — digest mismatch, malformed stream, root mismatch, the
``query.restore`` chaos probe firing — quarantines the artifact
(``<path>.corrupt``), counts ``coldstart_corrupt``, flight-records, and
falls back to the literal build.  No path serves a wrong state.

``CSTPU_NO_CHECKPOINT_SYNC=1`` forces the literal build path (the cold
bench baselines stay measurable); ``CSTPU_SNAPSHOT_DIR`` overrides the
artifact directory (defaults to ``<repo>/.bench_cache/state_snapshots``,
beside the bench corpus cache).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Callable, Optional

from consensus_specs_tpu import faults
from consensus_specs_tpu.persist import atomic
from consensus_specs_tpu.persist.store import (
    CheckpointError,
    decode_tree,
    encode_tree,
)
from consensus_specs_tpu.telemetry import recorder

from . import stats

SNAPSHOT_KIND = "state-snapshot"
# bump on any codec or meta change: an old snapshot degrades to a
# stale-tag miss (rebuild + rewrite), never a misparse
FORMAT_TAG = "snap-v1"

_SITE_RESTORE = faults.site("query.restore")

# artifact paths whose decoded state already passed the once-per-artifact
# byte-identity check in this process
_VERIFIED = set()
_VERIFIED_LOCK = threading.Lock()


def _default_dir() -> str:
    env = os.environ.get("CSTPU_SNAPSHOT_DIR")
    if env:
        return env
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, ".bench_cache", "state_snapshots")


def _spec_ident(spec) -> str:
    return (f"{getattr(spec, 'fork', 'unknown')}_"
            f"{getattr(spec, 'preset_name', 'unknown')}")


def snapshot_path(spec, n_validators: int, label: str = "state",
                  cache_dir: Optional[str] = None) -> str:
    return os.path.join(
        cache_dir or _default_dir(),
        f"snap_{label}_{_spec_ident(spec)}_{int(n_validators)}.bin")


def _tag(spec, n_validators: int, label: str) -> str:
    # the tag binds codec generation, fork×preset, registry size, and
    # the builder variant: any mismatch is STALE, not damage
    return f"{FORMAT_TAG}:{_spec_ident(spec)}:{int(n_validators)}:{label}"


def forget_verified() -> None:
    """Drop the once-per-artifact verification memo (tests/bench: a
    restore timed after this pays the honest cold-process cost,
    byte-identity check included)."""
    with _VERIFIED_LOCK:
        _VERIFIED.clear()


def _encode_payload(state) -> bytes:
    root = bytes(state.hash_tree_root())  # memoizes every subtree root
    meta = {
        "root": root.hex(),
        "slot": int(state.slot),
        "n_validators": len(state.validators),
    }
    out = bytearray()
    raw = json.dumps(meta, sort_keys=True).encode()
    out += len(raw).to_bytes(4, "little")
    out += raw
    encode_tree(state.get_backing(), out, {})
    return bytes(out)


def _decode_payload(spec, payload):
    """(state, meta, tree_off); raises ``CheckpointError`` on any
    structural surprise or root mismatch."""
    try:
        n = int.from_bytes(payload[:4], "little")
        meta = json.loads(bytes(payload[4:4 + n]).decode())
        tree_off = 4 + n
        backing, end = decode_tree(payload, tree_off, [])
        state = spec.BeaconState.view_from_backing(backing)
    except CheckpointError:
        raise
    except Exception as exc:
        raise CheckpointError(f"malformed snapshot payload: {exc!r}")
    if end != len(payload):
        raise CheckpointError("snapshot payload has trailing bytes")
    # the content address must agree with the rebuilt tree (roots are
    # memoized from the stream; the digest vouched for the bytes, this
    # vouches meta and tree belong together)
    if bytes(state.hash_tree_root()) != bytes.fromhex(meta["root"]):
        raise CheckpointError("snapshot state root mismatch")
    return state, meta, tree_off


def _assert_byte_identical(state, payload, tree_off: int) -> None:
    """The once-per-artifact post-state identity: re-encoding the
    decoded backing must reproduce the artifact's tree stream exactly
    (codec round-trip == byte-identical state)."""
    out = bytearray()
    encode_tree(state.get_backing(), out, {})
    if bytes(out) != bytes(payload[tree_off:]):
        raise CheckpointError("snapshot re-encode diverged from artifact")


def _discard(path: str, exc: Exception) -> None:
    stats["coldstart_corrupt"] += 1
    atomic.quarantine(path)
    recorder.record("snapshot_corrupt", path=os.path.basename(path),
                    detail=repr(exc)[:160])


def write_snapshot(spec, state, n_validators: Optional[int] = None,
                   label: str = "state",
                   cache_dir: Optional[str] = None) -> Optional[str]:
    """Snapshot ``state`` for later cold starts.  The payload is
    round-tripped (decode + re-encode + root check) BEFORE the write —
    an artifact only exists once it is proven byte-identical.  Best
    effort: a read-only tree returns None (the cold path still works)."""
    n = len(state.validators) if n_validators is None else int(n_validators)
    path = snapshot_path(spec, n, label, cache_dir)
    payload = _encode_payload(state)
    decoded, _meta, tree_off = _decode_payload(spec, payload)
    _assert_byte_identical(decoded, payload, tree_off)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        atomic.write_artifact(path, payload, SNAPSHOT_KIND,
                              _tag(spec, n, label))
    except OSError:
        return None
    stats["coldstart_writes"] += 1
    with _VERIFIED_LOCK:
        _VERIFIED.add(path)
    return path


def restore_or_build(spec, n_validators: int, build_fn: Callable,
                     label: str = "state",
                     cache_dir: Optional[str] = None):
    """The universal cold-start seam: restore the matching snapshot
    artifact if one exists (and verifies), else run ``build_fn`` and
    snapshot its result for the next process.  Honors
    ``CSTPU_NO_CHECKPOINT_SYNC=1`` (always build, never touch disk)."""
    if os.environ.get("CSTPU_NO_CHECKPOINT_SYNC") == "1":
        stats["coldstart_builds"] += 1
        return build_fn()
    n = int(n_validators)
    path = snapshot_path(spec, n, label, cache_dir)
    tag = _tag(spec, n, label)
    payload = None
    try:
        payload = atomic.read_artifact(path, SNAPSHOT_KIND, tag)
    except atomic.ArtifactMissing:
        pass
    except atomic.ArtifactStaleTag:
        # a foreign codec generation or builder variant: plain miss —
        # the rebuild overwrites it with the current shape
        pass
    except Exception as exc:
        _discard(path, exc)
    if payload is not None:
        try:
            _SITE_RESTORE()
            state, meta, tree_off = _decode_payload(spec, payload)
            if int(meta["n_validators"]) != n:
                raise CheckpointError("snapshot validator count mismatch")
            with _VERIFIED_LOCK:
                verified = path in _VERIFIED
            if not verified:
                _assert_byte_identical(state, payload, tree_off)
                with _VERIFIED_LOCK:
                    _VERIFIED.add(path)
            stats["coldstart_restores"] += 1
            return state
        except Exception as exc:
            # damage (or the chaos probe): quarantine and fall through
            # to the literal build — never serve a wrong state
            _discard(path, exc)
    state = build_fn()
    stats["coldstart_builds"] += 1
    write_snapshot(spec, state, n, label, cache_dir)
    return state
