"""Concurrent query-load harness: readers against the live firehose
(ISSUE 16).

``run_query_load`` is ``firehose.run_firehose`` plus N "query-reader"
threads hammering the node's ``QueryEngine`` while the apply loop
serves: each reader draws a seeded op mix (summary / balance / status /
proof+verify / vote / state-at-root), records per-op latency, and
tolerates the early window where no checkpoint artifact exists yet
(counted as unserved, not failed).  Readers stop when the firehose
drains; the returned row carries p50/p99 service latency beside the
firehose throughput numbers — the ``node_query_load`` bench row's
engine, and the concurrency story the TH01 registry declares: readers
touch the engine surface only, never the apply writer's store.
"""
from __future__ import annotations

import random
import threading
import time
from typing import List, Optional

_OPS = ("summary", "balance", "status", "proof", "vote", "state")


def query_reader(engine, n_validators: int, stop: threading.Event,
                 out: list, seed: int, op_mix=_OPS) -> None:
    """One reader thread's loop (TH01 role: ``query-reader``): seeded
    op draws against ``engine`` until ``stop`` is set; appends its
    latency/outcome record to ``out`` on exit."""
    rng = random.Random(seed)
    latencies: List[float] = []
    served = unserved = errors = 0
    while not stop.is_set():
        op = rng.choice(op_mix)
        vi = rng.randrange(max(1, n_validators))
        t0 = time.perf_counter()
        try:
            if op == "summary":
                r = engine.summary()
            elif op == "balance":
                r = engine.balance_of(vi)
            elif op == "status":
                r = engine.validator_status(vi)
            elif op == "proof":
                r = engine.proof_of_validator(vi)
            elif op == "vote":
                r = engine.vote_of(vi)
            else:
                r = engine.state_at_root()
        except Exception:
            # a query may legitimately fail mid-run (an artifact pruned
            # under the reader, a chaos probe): count it, keep reading —
            # the harness asserts on the tallies, the apply loop never
            # sees any of this
            errors += 1
            continue
        dt = time.perf_counter() - t0
        if r is None and op != "vote":
            unserved += 1
        else:
            served += 1
            latencies.append(dt)
    out.append({"served": served, "unserved": unserved, "errors": errors,
                "latencies": latencies})


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    k = int(round(q * (len(sorted_vals) - 1)))
    return sorted_vals[k]


def run_query_load(spec, anchor_state, corpus, n_query_threads: int = 2,
                   query_seed: int = 1234, op_mix=_OPS,
                   **firehose_kwargs) -> dict:
    """The firehose under concurrent query load.  Forwards everything
    else to ``run_firehose`` (``checkpoint_store=...`` is effectively
    required — without one the engine never has an artifact and every
    op counts unserved).  Returns the firehose row plus a
    ``query_load`` sub-row."""
    from consensus_specs_tpu.node import firehose

    n_validators = len(anchor_state.validators)
    stop = threading.Event()
    results: list = []
    readers: List[threading.Thread] = []

    def _on_node(node) -> None:
        engine = node.query_engine
        if engine is None:
            raise RuntimeError(
                "run_query_load needs a node with a checkpoint_store "
                "(the query engine serves off its artifacts)")
        for i in range(n_query_threads):
            t = threading.Thread(
                target=query_reader,
                args=(engine, n_validators, stop, results,
                      query_seed + i, op_mix),
                name=f"query-reader-{i}", daemon=True)
            t.start()
            readers.append(t)

    try:
        run = firehose.run_firehose(spec, anchor_state, corpus,
                                    on_node=_on_node, **firehose_kwargs)
    finally:
        stop.set()
        for t in readers:
            t.join(timeout=30.0)

    latencies = sorted(x for r in results for x in r["latencies"])
    ops = sum(r["served"] + r["unserved"] + r["errors"] for r in results)
    p50 = _percentile(latencies, 0.50)
    p99 = _percentile(latencies, 0.99)
    run["query_load"] = {
        "threads": n_query_threads,
        "ops": ops,
        "served": sum(r["served"] for r in results),
        "unserved": sum(r["unserved"] for r in results),
        "errors": sum(r["errors"] for r in results),
        "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
        "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
    }
    return run
