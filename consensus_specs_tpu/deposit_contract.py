"""Incremental deposit Merkle tree — executable mirror of the deposit
contract's accumulator algorithm (reference aux subsystem:
solidity_deposit_contract/deposit_contract.sol; the branch/size scheme of
get_deposit_root and the DepositEvent ABI data layout).

The contract keeps one 32-entry `branch` array: inserting leaf i updates
the first branch slot whose subtree became full; the root folds branch
entries against zero-subtree hashes and mixes in the little-endian count.
This mirror is differentially tested against the SSZ
List[DepositData, 2**32] hash_tree_root (tests/test_deposit_contract.py),
which is exactly the equivalence process_deposit relies on
(phase0/beacon-chain.md is_valid_merkle_branch against eth1_data.deposit_root).
"""
from __future__ import annotations

from typing import List

from consensus_specs_tpu.ssz.hashing import sha256

DEPOSIT_CONTRACT_TREE_DEPTH = 32


class DepositTree:
    """The contract's incremental accumulator."""

    def __init__(self) -> None:
        self.branch: List[bytes] = [b"\x00" * 32] * DEPOSIT_CONTRACT_TREE_DEPTH
        self.deposit_count = 0
        self._zero_hashes = [b"\x00" * 32]
        for _ in range(DEPOSIT_CONTRACT_TREE_DEPTH - 1):
            prev = self._zero_hashes[-1]
            self._zero_hashes.append(sha256(prev + prev))

    def push_leaf(self, leaf: bytes) -> None:
        assert self.deposit_count < 2**DEPOSIT_CONTRACT_TREE_DEPTH - 1, "tree full"
        self.deposit_count += 1
        size = self.deposit_count
        node = bytes(leaf)
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size % 2 == 1:
                self.branch[height] = node
                return
            node = sha256(self.branch[height] + node)
            size //= 2
        raise AssertionError("unreachable: loop always returns")

    def get_root(self) -> bytes:
        """Contract get_deposit_root: fold + mix in deposit_count."""
        node = b"\x00" * 32
        size = self.deposit_count
        for height in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            if size % 2 == 1:
                node = sha256(self.branch[height] + node)
            else:
                node = sha256(node + self._zero_hashes[height])
            size //= 2
        return sha256(node + self.deposit_count.to_bytes(8, "little") + b"\x00" * 24)


def deposit_event_data(pubkey: bytes, withdrawal_credentials: bytes,
                       amount_gwei: int, signature: bytes, index: int) -> bytes:
    """The DepositEvent FIELD VALUES concatenated in contract order with
    the contract's little-endian amount/index encoding.  NOTE: this is the
    logical payload, not the ABI event encoding (which adds head offsets
    and 32-byte padding around each dynamic bytes argument)."""
    assert len(pubkey) == 48 and len(withdrawal_credentials) == 32
    assert len(signature) == 96
    return b"".join([
        pubkey,
        withdrawal_credentials,
        amount_gwei.to_bytes(8, "little"),
        signature,
        index.to_bytes(8, "little"),
    ])
