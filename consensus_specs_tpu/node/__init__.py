"""Node serving pipeline: the two engines composed into one servable
surface (ISSUE 12; ROADMAP item 1).

Layers (see docs/architecture.md, "Node serving pipeline"):

* ``service``  — the ``Node``: a fork-choice engine whose ``on_block``
  routes the state transition through the batched stf engine
  (``engine_backed_on_block``), behind a single-writer apply loop;
* ``ingest``   — bounded multi-producer FIFO work queue with
  back-pressure, feeding the apply loop;
* ``firehose`` — seeded concurrent load harness: N epochs of blocks +
  ≥100k-attestation gossip from concurrent producer threads, with
  journal-replay head/root parity vs the literal spec;
* ``admission`` — the survival layer (ISSUE 13): content-root dedup,
  bounded slot-expiring orphan pool with re-link, future-slot parking,
  malformed rejection, per-producer scoring/quarantine, and the
  dead-letter ring the apply loop's poison-pill containment feeds;
* ``adversary`` — seeded deterministic adversarial corpora
  (equivocation storms, long-range reorgs, finality stalls, junk and
  duplicate floods) and the adversarial firehose driver.
"""
from .ingest import IngestQueue
from .service import Node, engine_backed_on_block, recover_node

__all__ = ["IngestQueue", "Node", "engine_backed_on_block", "recover_node"]
