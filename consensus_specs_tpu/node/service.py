"""The node: one servable pipeline over the two engines (ISSUE 12
tentpole; ROADMAP item 1).

Eleven PRs built two fast libraries — the proto-array fork-choice engine
(``forkchoice/engine.py``) and the batched stf engine
(``stf/engine.py``) — but ``on_block`` still replayed blocks through the
literal ``spec.state_transition``.  This module composes them into ONE
pipeline:

* **engine-backed ``on_block``** — ``engine_backed_on_block`` is the
  spec handler (specs/src/phase0.py:1602-1641; bellatrix adds the
  merge-transition check) with the state transition routed through
  ``stf.apply_signed_blocks``: the block's signature batch dispatches to
  the pipeline worker, attestations apply vectorized, slot roots ride
  the resident merkle path — and the stf engine's rollback contract,
  literal-replay fallback, and circuit breaker carry over UNCHANGED
  (``apply_signed_blocks`` is semantically identical to
  ``state_transition``, same post-state, same exception at the same
  point).  A ``Node``'s fork-choice engine gets this handler installed
  at construction (the ``block_handler`` seam), so head tracking, block
  verification, and state transition are one pipeline.

* **single-writer apply loop over a bounded multi-producer queue** —
  fork choice is single-writer by contract; producers (gossip readers,
  block fetchers, the clock) enqueue into ``node/ingest.py``'s bounded
  FIFO and ``run_apply_loop`` drains it on ONE thread.  A non-blocking
  writer lock enforces the contract (a second concurrent writer raises
  instead of corrupting the store).  The ``node.apply`` fault probe
  fires before any store/proto mutation so an injected failure leaves
  both untouched (tests/chaos/test_node_chaos.py).

* **adaptive micro-batching (ISSUE 19)** — the loop drains the WHOLE
  queue in one lock acquisition (waking every blocked producer at
  once), partitions the batch into strict-order items (blocks, ticks,
  slashings — the full rollback contract, unchanged) and consecutive
  gossip **runs** that land through ONE staged-commit
  ``forkchoice.batch.ingest_attestations`` each, and flushes the
  admission gate's back-pressure aggregation buffer into the same
  drain.  The journal keeps per-item provenance (one entry per original
  gossip batch, in arrival order), so replay parity and ``recover_node``
  hold byte-identically.  A spec-rejected run BISECTS to its poison
  item (``stf/verify.py``'s ``first_invalid`` pattern at the node
  layer, fault site ``node.batch_bisect``): the clean remainder lands,
  exactly the poison producer is charged.

* **the survival layer (ISSUE 13)** — every loop item passes the
  admission gate (``node/admission.py``: content-root dedup, orphan
  pool, future parking, malformed rejection, peer quarantine) before a
  spec handler sees it, and the loop CONTAINS failure instead of
  halting: a spec rejection (``AssertionError``) is counted, charged to
  the producer, and dropped; any other failure re-queues at the head
  with exponential backoff up to ``max_item_retries`` total attempts
  (the ingest queue's per-item ``attempts`` count), then quarantines to
  the bounded dead-letter ring (``node_quarantine`` flight-recorder
  event) while serving continues.  Only a real kill
  (``BaseException``) propagates — with the item back at the head, so
  the journal stays a true history for recovery.

* **parity journal + crash recovery** — every applied item lands in
  ``node.journal`` in apply order, so a concurrent run's end state is
  exactly replayable through the literal spec handlers (the firehose's
  head/root parity leg replays the journal, making
  byte-identical-state assertions meaningful under nondeterministic
  producer interleaving) — and ``recover_node`` rebuilds a crashed
  node byte-identically from the same journal.

Observability: ``node_block``/``node_gossip`` flight-recorder events
(recorded only after the engine call settled — OB01's commit
discipline), ``node/apply`` timeline spans carrying the enqueue-time
causality link (Perfetto shows the producer → apply-loop handoff), and a
``node`` snapshot provider on the telemetry bus (queue depth,
applied/rejected counters, producer stats).
"""
from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Optional, Sequence

from consensus_specs_tpu import faults, telemetry
from consensus_specs_tpu.forkchoice import ForkChoiceEngine
from consensus_specs_tpu.forkchoice import batch as fc_batch
from consensus_specs_tpu.persist import store as persist_store
from consensus_specs_tpu.query.engine import QueryEngine
from consensus_specs_tpu.stf import apply_signed_blocks
from consensus_specs_tpu.telemetry import histogram, recorder, timeline

from . import admission, ingest

# probed at the top of every apply (direct handler or loop item), BEFORE
# the engine dispatch: an injected failure leaves store + proto-array
# exactly as they were and the dequeued item back at the queue head
_SITE_APPLY = faults.site("node.apply")
# probed BEFORE a journal replay begins: an injected recovery failure
# leaves the half-built node discarded and nothing global touched — a
# retried recovery starts clean (tests/chaos/test_node_chaos.py)
_SITE_RECOVER = faults.site("node.recover")
# probed at each bisection step of a spec-rejected gossip run (ISSUE
# 19): an injected failure abandons the bisection machinery and falls
# back to item-at-a-time apply — containment degrades, never breaks
_SITE_BISECT = faults.site("node.batch_bisect")

# total apply ATTEMPTS a poison item gets before the loop quarantines it
# to the dead-letter ring (the containment contract: the node keeps
# serving; the item keeps its evidence)
DEFAULT_MAX_ITEM_RETRIES = 3
DEFAULT_RETRY_BACKOFF_S = 0.01

stats = {
    "blocks_applied": 0,
    "ticks_applied": 0,
    "attestation_batches_applied": 0,
    "attestations_applied": 0,
    "slashings_applied": 0,
    "rejected_batches": 0,
    "rejected_attestations": 0,
    "rejected_blocks": 0,
    "rejected_slashings": 0,
    "rejected_ticks": 0,
    "retried_items": 0,
    "quarantined_items": 0,
    "requeued_items": 0,
    "recoveries": 0,
    "checkpoint_recoveries": 0,
    "checkpoints_scheduled": 0,
    "checkpoint_gather_failures": 0,
    "apply_loop_runs": 0,
    "batches_applied": 0,      # drained micro-batches (ISSUE 19)
    "runs_coalesced": 0,       # multi-item gossip runs landed as one ingest
    "batch_bisections": 0,     # spec-rejected runs bisected to the poison
}


def reset_stats() -> None:
    """Zero the node counters AND the ingest queue's and admission
    gate's (they attribute one pipeline; a firehose run must not inherit
    a previous run's counts)."""
    for k in stats:
        stats[k] = 0
    ingest.reset_stats()
    admission.reset_stats()


def _telemetry_provider() -> dict:
    return {**stats, "queue": ingest.snapshot()}


telemetry.register_provider("node", _telemetry_provider, replace=True)


def engine_backed_on_block(spec, store, signed_block) -> None:
    """``spec.on_block`` with the state transition routed through the
    batched stf engine — same store mutations, same exceptions at the
    same points (``apply_signed_blocks`` is differentially pinned to
    ``state_transition``), so this is a drop-in for the fork-choice
    engine's ``block_handler`` seam."""
    block = signed_block.message
    # Parent block must be known
    assert block.parent_root in store.block_states
    pre_state = store.block_states[block.parent_root].copy()
    # Blocks cannot be in the future
    assert spec.get_current_slot(store) >= block.slot
    # Block must be later than the finalized epoch slot, on its chain
    finalized_slot = spec.compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch)
    assert block.slot > finalized_slot
    assert spec.get_ancestor(store, block.parent_root, finalized_slot) == \
        store.finalized_checkpoint.root

    # the one substitution: the batched engine instead of the literal
    # spec.state_transition (rollback/breaker/replay semantics inside)
    state = pre_state.copy()
    apply_signed_blocks(spec, state, (signed_block,), True)

    # [New in Bellatrix] merge-transition validation, against the
    # untransitioned pre-state exactly as the spec orders it
    is_mtb = getattr(spec, "is_merge_transition_block", None)
    if is_mtb is not None and is_mtb(pre_state, block.body):
        spec.validate_merge_block(block)

    root = spec.hash_tree_root(block)
    store.blocks[root] = block
    store.block_states[root] = state

    time_into_slot = ((store.time - store.genesis_time)
                      % spec.config.SECONDS_PER_SLOT)
    is_before_attesting_interval = (
        time_into_slot
        < spec.config.SECONDS_PER_SLOT // spec.INTERVALS_PER_SLOT)
    if spec.get_current_slot(store) == block.slot \
            and is_before_attesting_interval:
        store.proposer_boost_root = root

    if state.current_justified_checkpoint.epoch > \
            store.justified_checkpoint.epoch:
        if state.current_justified_checkpoint.epoch > \
                store.best_justified_checkpoint.epoch:
            store.best_justified_checkpoint = \
                state.current_justified_checkpoint
        if spec.should_update_justified_checkpoint(
                store, state.current_justified_checkpoint):
            store.justified_checkpoint = state.current_justified_checkpoint

    if state.finalized_checkpoint.epoch > store.finalized_checkpoint.epoch:
        store.finalized_checkpoint = state.finalized_checkpoint
        store.justified_checkpoint = state.current_justified_checkpoint


def default_anchor_block(spec, anchor_state):
    """The anchor block a state implies: its ``latest_block_header`` with
    the state root filled — valid whenever the header's body root is the
    empty body's (genesis states; firehose-prepared anchors)."""
    header = anchor_state.latest_block_header
    return spec.BeaconBlock(
        slot=header.slot, proposer_index=header.proposer_index,
        parent_root=header.parent_root,
        state_root=anchor_state.hash_tree_root())


class Node:
    """A servable consensus node: fork choice + batched state transition
    behind one single-writer handler surface and one ingest queue."""

    def __init__(self, spec, anchor_state, anchor_block=None,
                 queue_cap: int = ingest.DEFAULT_CAP, journal: bool = True,
                 admission_gate: bool = True,
                 max_item_retries: int = DEFAULT_MAX_ITEM_RETRIES,
                 retry_backoff_s: float = DEFAULT_RETRY_BACKOFF_S,
                 adopt_admission: bool = True,
                 checkpoint_store=None,
                 checkpoint_interval_epochs: int = 1,
                 _warm_store=None):
        self.spec = spec
        if _warm_store is not None:
            # checkpoint recovery (ISSUE 14): a spec Store rebuilt from a
            # restored checkpoint — the engine's warm-store path seeds the
            # proto-array, votes, and checkpoint sync from it
            store = _warm_store
        else:
            if anchor_block is None:
                anchor_block = default_anchor_block(spec, anchor_state)
            store = spec.get_forkchoice_store(anchor_state, anchor_block)
        self.engine = ForkChoiceEngine(
            spec, store, block_handler=self._on_block_stf)
        self.queue = ingest.IngestQueue(cap=queue_cap)
        # apply-order journal: the literal-spec parity replay's script.
        # Owner-mutated only (analyzer-registered next to the queue).
        self._journal = [] if journal else None
        self._journal_last_block = None
        self._writer_lock = threading.Lock()
        self._clock_cond = threading.Condition()
        self._clock_slot = int(spec.get_current_slot(store))
        # the survival layer (ISSUE 13): the admission gate judges every
        # LOOP item before a spec handler sees it; direct handler calls
        # (the differential mirrors') bypass it by design — they want
        # the spec's exact accept/reject verdicts.  A fresh node adopts
        # the process-wide admission surface (pools reset);
        # ``recover_node`` opts out — recovery must PRESERVE the crashed
        # surface's dead letters, peer scores, and quarantine set (the
        # post-mortem evidence and the shed protection both outlive the
        # crash)
        self._admission = admission_gate
        self._max_item_retries = max(1, int(max_item_retries))
        self._retry_backoff_s = float(retry_backoff_s)
        # durable checkpoint cadence (ISSUE 14): the apply loop writes a
        # checkpoint whenever the store clock crosses an epoch boundary
        # (the fence) — gathering is cheap reference-taking under the
        # single writer; serialization + the atomic write happen on the
        # store's background writer thread, off the serving hot path
        self._ckpt_store = checkpoint_store
        self._ckpt_interval = max(1, int(checkpoint_interval_epochs))
        self._spe = int(spec.SLOTS_PER_EPOCH)
        self._ckpt_epoch_seen = \
            int(spec.get_current_slot(store)) // self._spe
        # the historical read path (ISSUE 16): a query engine over the
        # same store's artifacts, exposed beside the apply loop —
        # reader threads serve off verified artifact mmaps and
        # engine-owned caches, never off this node's fork-choice store
        # (the TH01 "query-reader" role wall)
        self.query_engine = (QueryEngine(spec, checkpoint_store)
                             if checkpoint_store is not None else None)
        if adopt_admission:
            admission.reset_state()

    def _on_block_stf(self, store, signed_block) -> None:
        """The ``block_handler`` installed on the fork-choice engine:
        the spec handler shape with the batched stf transition."""
        engine_backed_on_block(self.spec, store, signed_block)

    # -- single-writer contract ----------------------------------------------

    @contextlib.contextmanager
    def _single_writer(self):
        # thread-safe: a deliberately NON-blocking probe — contention
        # means a second writer, which must raise, not wait; released
        # in the finally below (the with-form cannot express try-acquire)
        if not self._writer_lock.acquire(blocking=False):
            raise RuntimeError(
                "concurrent node apply: fork choice is single-writer — "
                "producers must enqueue, only the apply loop applies")
        try:
            yield
        finally:
            self._writer_lock.release()

    def _journal_append(self, kind: str, payload) -> None:
        if self._journal is not None:
            self._journal.append((kind, payload))
            if kind == "block":
                # the checkpoint's content-bound journal anchor (root is
                # memoized — on_block already hashed the message)
                self._journal_last_block = (
                    len(self._journal) - 1,
                    bytes(payload.message.hash_tree_root()).hex())

    def _note_clock(self) -> None:
        slot = int(self.spec.get_current_slot(self.engine.store))
        if slot != self._clock_slot:
            with self._clock_cond:
                self._clock_slot = slot
                self._clock_cond.notify_all()

    def wait_for_clock(self, slot: int,
                       timeout: Optional[float] = None) -> bool:
        """Block until the store clock reaches ``slot`` (producers pace
        themselves against the apply loop with this — e.g. gossip for
        slot N enqueues only once the clock passed N, so the votes are
        mature on arrival)."""
        with self._clock_cond:
            return self._clock_cond.wait_for(
                lambda: self._clock_slot >= slot, timeout)

    # -- handlers (the single writer's API) ----------------------------------

    def on_tick(self, time) -> None:
        with self._single_writer():
            _SITE_APPLY()
            self.engine.on_tick(time)
            stats["ticks_applied"] += 1
            self._journal_append("tick", int(time))
        self._note_clock()

    def on_block(self, signed_block) -> None:
        with self._single_writer():
            _SITE_APPLY()
            self.engine.on_block(signed_block)
            stats["blocks_applied"] += 1
            self._journal_append("block", signed_block)
            if recorder.enabled():
                recorder.record("node_block",
                                slot=int(signed_block.message.slot))

    def on_attestations(self, attestations: Sequence,
                        is_from_block: bool = False) -> None:
        with self._single_writer():
            _SITE_APPLY()
            self.engine.on_attestations(list(attestations),
                                        is_from_block=is_from_block)
            stats["attestation_batches_applied"] += 1
            stats["attestations_applied"] += len(attestations)
            self._journal_append("attestations", tuple(attestations))
            if recorder.enabled():
                recorder.record("node_gossip", n=len(attestations))

    def on_attestation(self, attestation, is_from_block: bool = False) -> None:
        self.on_attestations([attestation], is_from_block=is_from_block)

    def on_attester_slashing(self, attester_slashing) -> None:
        with self._single_writer():
            _SITE_APPLY()
            self.engine.on_attester_slashing(attester_slashing)
            stats["slashings_applied"] += 1
            self._journal_append("attester_slashing", attester_slashing)

    def get_head(self):
        return self.engine.get_head()

    @property
    def store(self):
        return self.engine.store

    @property
    def journal(self) -> list:
        return list(self._journal or ())

    # -- producer surface ----------------------------------------------------

    def enqueue_tick(self, time, timeout: Optional[float] = None) -> None:
        self.queue.put("tick", int(time), timeout=timeout)

    def enqueue_block(self, signed_block,
                      timeout: Optional[float] = None) -> None:
        self.queue.put("block", signed_block, timeout=timeout)

    def enqueue_attestations(self, attestations: Sequence,
                             timeout: Optional[float] = None) -> None:
        payload = tuple(attestations)
        if self._admission:
            # back-pressure becomes aggregation work (ISSUE 19): when
            # the queue sits at cap, the batch goes to the admission
            # gate's staging buffer instead of blocking the producer;
            # the apply loop flushes the buffer into its next drain.
            # Refused stagings (buffer at cap, undecodable payload,
            # quarantined producer) fall through to the blocking put —
            # the original back-pressure contract
            if self.queue.try_put("attestations", payload):
                return
            link = timeline.next_link() if timeline.enabled() else None
            if admission.aggregate_gossip(
                    payload, threading.current_thread().name, link):
                if link is not None:
                    with timeline.span("node/enqueue", link=link,
                                       kind="attestations", aggregated=True):
                        pass
                return
        self.queue.put("attestations", payload, timeout=timeout)

    def enqueue_attester_slashing(self, attester_slashing,
                                  timeout: Optional[float] = None) -> None:
        self.queue.put("attester_slashing", attester_slashing,
                       timeout=timeout)

    # -- the apply loop ------------------------------------------------------

    def apply_item(self, item: ingest.WorkItem) -> None:
        """Dispatch one work item to its handler, raising on any failure
        (a spec rejection raises the spec's ``AssertionError``).  The
        verdict/containment policy — admission, rejection counting,
        retries, quarantine — is ``_process_item``'s job; this is the
        raw apply."""
        with timeline.span("node/apply", link=item.link, kind=item.kind):
            if item.kind == "tick":
                self.on_tick(item.payload)
            elif item.kind == "block":
                self.on_block(item.payload)
            elif item.kind == "attestations":
                self.on_attestations(item.payload)
            elif item.kind == "attester_slashing":
                self.on_attester_slashing(item.payload)
            else:
                raise ValueError(f"unknown work item kind {item.kind!r}")

    # -- containment (ISSUE 13): the loop never halts on a poison item --------

    def _count_rejected(self, item: ingest.WorkItem) -> None:
        """A spec-invalid item (``AssertionError`` out of the handler):
        production-shaped load, counted + charged + dropped."""
        if item.kind == "attestations":
            stats["rejected_batches"] += 1
            stats["rejected_attestations"] += len(item.payload)
            if recorder.enabled():
                recorder.record("node_gossip_rejected", n=len(item.payload))
        elif item.kind == "block":
            stats["rejected_blocks"] += 1
            if recorder.enabled():
                recorder.record("node_block_rejected",
                                slot=int(item.payload.message.slot))
        elif item.kind == "attester_slashing":
            stats["rejected_slashings"] += 1
        else:
            stats["rejected_ticks"] += 1
        admission.charge(item.producer, admission.CHARGE_REJECTED)
        # a rejection is a verdict on CURRENT store state (an unknown
        # root can arrive later): forget the dedup key so an honest
        # re-delivery is re-judged instead of dying as a duplicate
        admission.forget(item)

    def _contain_failure(self, item: ingest.WorkItem,
                         exc: Exception) -> None:
        """Bounded per-item retries with backoff, then quarantine to the
        dead-letter ring — the poison-pill contract: the loop keeps
        serving.  A failing quarantine (its own fault site) propagates
        un-handled: the CALLER re-queues the item ahead of any pending
        followups (exact order restored) — containment of last resort
        must fail loudly, never half-record."""
        if item.attempts + 1 >= self._max_item_retries:
            admission.dead_letter(item, exc)
            stats["quarantined_items"] += 1
        else:
            stats["retried_items"] += 1
            if self._retry_backoff_s > 0:
                time.sleep(self._retry_backoff_s * (2 ** item.attempts))
            self.queue.requeue_front(item)
            stats["requeued_items"] += 1

    def _process_item(self, item: ingest.WorkItem, readmit: bool = False,
                      tail: tuple = (), admitted: bool = False) -> bool:
        """One dequeued item through the survival layer: admission
        verdict, apply, containment, and the follow-ups a success
        unlocks (orphan re-links after a block, parked releases after a
        tick) — processed iteratively so a long re-link chain cannot
        recurse.

        ``tail`` is the drained micro-batch's unprocessed remainder
        (ISSUE 19): a containment re-queue puts it back BEHIND the
        retried item — exact pre-drain order — and the method returns
        False so the batch stops and the loop re-drains.  ``admitted``
        marks an item already past the gate this drain (a gossip-run
        member falling back to item-at-a-time apply); its verdict is
        not re-judged."""
        stop = False
        work = collections.deque([(item, readmit, admitted)])
        while work:
            it, re, adm = work.popleft()
            clock_before = self._clock_slot
            try:
                # admission runs INSIDE containment: a fault at the gate
                # is an infrastructure failure, not a verdict — the item
                # re-queues and the retry re-judges it (nothing is lost).
                # A retried item (attempts > 0) already passed the dedup
                # check once and sits in the seen-set: it re-enters as a
                # re-admission, not a duplicate.
                if self._admission and not adm:
                    verdict, it = admission.admit(
                        self.spec, self.store, it, self._clock_slot,
                        readmit=re or it.attempts > 0 or it.readmit)
                    if verdict != admission.VERDICT_ADMIT:
                        continue
                self.apply_item(it)
            except AssertionError:
                self._count_rejected(it)
            except Exception as exc:
                will_retry = it.attempts + 1 < self._max_item_retries
                if will_retry and tail:
                    # the retried item lands FIRST (inside
                    # _contain_failure below), the batch tail right
                    # behind it — exact pre-drain order
                    for rest in reversed(tail):
                        self.queue.requeue_front(rest, count_attempt=False)
                    tail = ()
                try:
                    self._contain_failure(it, exc)
                except BaseException:
                    # containment itself failed (e.g. a quarantine
                    # fault): restore the queue in EXACT order — the
                    # in-flight item first, its pending followups right
                    # behind, then any batch tail not yet returned —
                    # and propagate loudly
                    for rest in reversed(tail):
                        self.queue.requeue_front(rest, count_attempt=False)
                    tail = ()
                    for rest, _re, _adm in reversed(work):
                        self.queue.requeue_front(
                            rest._replace(readmit=True),
                            count_attempt=False)
                    work.clear()
                    self.queue.requeue_front(it)
                    stats["requeued_items"] += 1
                    raise
                if will_retry:
                    stop = True
            except BaseException:
                # a real kill (KeyboardInterrupt, SystemExit): crash
                # semantics — the item back at the head, the journal a
                # true history, recovery's replay picks up from here.
                # Pending followups were already POPPED from the
                # admission pools: re-queue them behind the in-flight
                # item (in order) or they would vanish unaccounted, and
                # the batch tail behind THEM (exact pre-drain order).
                # Neither they nor the interrupted item FAILED — the
                # kill is not a poison signal, so no attempt is charged
                for rest in reversed(tail):
                    self.queue.requeue_front(rest, count_attempt=False)
                tail = ()
                for rest, _re, _adm in reversed(work):
                    self.queue.requeue_front(rest._replace(readmit=True),
                                             count_attempt=False)
                work.clear()
                self.queue.requeue_front(it._replace(readmit=True),
                                         count_attempt=False)
                stats["requeued_items"] += 1
                raise
            else:
                if not self._admission:
                    continue
                if it.kind == "block":
                    root = bytes(it.payload.message.hash_tree_root())
                    work.extend((child, True, False)
                                for child in admission.pop_children(root))
                elif it.kind == "tick":
                    released = admission.on_clock(
                        self._clock_slot,
                        self._clock_slot - clock_before)
                    work.extend((r, True, False) for r in released)
        return not stop

    # -- the micro-batcher (ISSUE 19) ----------------------------------------

    def _drain_aggregated(self, max_items: Optional[int] = None) -> list:
        """Flush the admission gate's back-pressure aggregation buffer
        into the current drain (gate off: nothing ever staged)."""
        if not self._admission:
            return []
        return admission.drain_aggregated(max_items)

    def _process_batch(self, batch: list) -> int:
        """Partition one drained micro-batch: blocks, ticks, and
        slashings stay strict-order item-at-a-time through the full
        rollback contract; maximal consecutive gossip slices become
        runs.  Returns the number of batch items consumed before the
        batch completed or stopped (a containment re-queue returned the
        remainder to the real queue)."""
        pending = collections.deque(batch)
        consumed = 0
        while pending:
            if (pending[0].kind == "attestations" and len(pending) > 1
                    and pending[1].kind == "attestations"):
                run = []
                while pending and pending[0].kind == "attestations":
                    run.append(pending.popleft())
                consumed += len(run)
                if not self._process_gossip_run(run, tuple(pending)):
                    return consumed
            else:
                it = pending.popleft()
                consumed += 1
                if not self._process_item(it, tail=tuple(pending)):
                    return consumed
                # the epoch fence must fire PER settled item, not per
                # drained batch: one bulk drain can carry ticks crossing
                # several epoch boundaries, and each crossing owes its
                # own checkpoint (only ticks move the clock, so gossip
                # runs never need the check)
                if self._ckpt_store is not None:
                    self._maybe_checkpoint()
        return consumed

    def _process_gossip_run(self, run: list, tail: tuple) -> bool:
        """A maximal consecutive slice of gossip items from one drain:
        judge each at the gate in FIFO order, then land every admitted
        batch through ONE staged-commit fork-choice ingest
        (``_commit_run``).  Returns False when items went back to the
        real queue (the batch stops and the loop re-drains)."""
        admitted = []
        pending = collections.deque(run)
        while pending:
            it = pending.popleft()
            if not self._admission:
                admitted.append(it)
                continue
            try:
                verdict, judged = admission.admit(
                    self.spec, self.store, it, self._clock_slot,
                    readmit=it.attempts > 0 or it.readmit)
            except Exception as exc:
                # infrastructure failure at the gate mid-run: the
                # admitted prefix keeps its place (marked readmit — its
                # seen-keys are in), the failing item gets the per-item
                # containment verdict, the unjudged rest and the batch
                # tail line up behind — exact pre-drain order
                will_retry = it.attempts + 1 < self._max_item_retries
                if will_retry:
                    for rest in reversed(tail):
                        self.queue.requeue_front(rest, count_attempt=False)
                    for rest in reversed(pending):
                        self.queue.requeue_front(rest, count_attempt=False)
                try:
                    self._contain_failure(it, exc)
                except BaseException:
                    if not will_retry:
                        for rest in reversed(tail):
                            self.queue.requeue_front(rest,
                                                     count_attempt=False)
                        for rest in reversed(pending):
                            self.queue.requeue_front(rest,
                                                     count_attempt=False)
                    self.queue.requeue_front(it)
                    stats["requeued_items"] += 1
                    for rest in reversed(admitted):
                        self.queue.requeue_front(rest, count_attempt=False)
                    raise
                if will_retry:
                    for rest in reversed(admitted):
                        self.queue.requeue_front(rest, count_attempt=False)
                    return False
                continue
            if verdict == admission.VERDICT_ADMIT:
                # marked readmit: from here on the item is past the
                # gate — any later re-queue must skip the dedup check
                admitted.append(judged._replace(readmit=True))
        if not admitted:
            return True
        return self._commit_run(admitted, tail)

    def _commit_run(self, items: list, tail: tuple) -> bool:
        """Land an admitted gossip run as one combined ingest, with the
        containment ladder batching adds: a spec rejection anywhere in
        the combined batch bisects to the poison item; an infrastructure
        failure falls back to item-at-a-time apply (one retry event for
        the run); a kill restores exact order and propagates."""
        try:
            self._apply_gossip_run(items)
            return True
        except AssertionError:
            return self._bisect_run(items, tail)
        except Exception:
            stats["retried_items"] += 1
            return self._apply_items_individually(items, tail)
        except BaseException:
            for rest in reversed(tail):
                self.queue.requeue_front(rest, count_attempt=False)
            for rest in reversed(items):
                self.queue.requeue_front(rest, count_attempt=False)
            stats["requeued_items"] += 1
            raise

    def _apply_gossip_run(self, items: Sequence) -> None:
        """Land admitted gossip items as ONE staged-commit fork-choice
        ingest — ``batch.ingest_attestations`` validates the whole
        combined batch before a single vote lands, so a failure leaves
        vote state untouched — while the journal keeps per-item
        provenance: one entry per original batch, in arrival order, so
        journal-replay parity and ``recover_node`` stay
        byte-identical."""
        combined = [a for it in items for a in it.payload]
        with timeline.span("node/apply", link=items[0].link,
                           kind="attestations", run=len(items)):
            with self._single_writer():
                _SITE_APPLY()
                self.engine.on_attestations(combined)
                for it in items:
                    stats["attestation_batches_applied"] += 1
                    stats["attestations_applied"] += len(it.payload)
                    self._journal_append("attestations", tuple(it.payload))
        if recorder.enabled():
            for it in items:
                recorder.record("node_gossip", n=len(it.payload))
        if timeline.enabled():
            # the coalesced items' causality links still need an apply
            # edge each, or Perfetto shows orphaned enqueue arrows
            for it in items[1:]:
                with timeline.span("node/apply", link=it.link,
                                   kind="attestations", coalesced=True):
                    pass
        if len(items) > 1:
            stats["runs_coalesced"] += 1
        histogram.observe("gossip_run", float(len(items)))

    def _probe_run(self, items: Sequence) -> bool:
        """Validation-only probe of a candidate slice: stage through the
        batch ingest and DISCARD — ``forkchoice/batch`` validates every
        attestation before staging and commits nothing until
        ``commit_votes``, so a probe's only store touch is the
        idempotent target-checkpoint-state cache the spec handler would
        populate anyway."""
        combined = [a for it in items for a in it.payload]
        try:
            with self._single_writer():
                fc_batch.ingest_attestations(self.spec, self.engine.store,
                                             combined)
        except AssertionError:
            return False
        return True

    def _bisect_run(self, items: list, tail: tuple) -> bool:
        """The combined commit was spec-rejected: bisect to the poison
        item (``stf/verify.py``'s ``first_invalid`` pattern at the node
        layer) with validation-only probes, land every clean slice as a
        run, hand exactly the poison item to the per-item containment
        core (charged + forgotten there), and continue with the rest.
        The ``node.batch_bisect`` probe fires once per bisection step;
        any machinery failure degrades to item-at-a-time apply."""
        stats["batch_bisections"] += 1
        pending = list(items)
        known_bad = True
        while pending:
            try:
                if not known_bad:
                    if self._probe_run(pending):
                        self._apply_gossip_run(pending)
                        return True
                if len(pending) == 1:
                    lo = 0
                else:
                    # invariant: pending[:lo] verifies; a failure sits
                    # in pending[lo:hi] (the stf first_invalid loop)
                    lo, hi = 0, len(pending)
                    while hi - lo > 1:
                        _SITE_BISECT()
                        mid = (lo + hi) // 2
                        if self._probe_run(pending[lo:mid]):
                            lo = mid
                        else:
                            hi = mid
                    if lo > 0:
                        self._apply_gossip_run(pending[:lo])
            except Exception:
                # the bisection machinery itself died (an injected
                # node.batch_bisect fault, a probe infrastructure
                # error): item-at-a-time fallback keeps every
                # containment guarantee for what is left
                stats["retried_items"] += 1
                return self._apply_items_individually(pending, tail)
            except BaseException:
                for rest in reversed(tail):
                    self.queue.requeue_front(rest, count_attempt=False)
                for rest in reversed(pending):
                    self.queue.requeue_front(rest, count_attempt=False)
                stats["requeued_items"] += 1
                raise
            poison, pending = pending[lo], pending[lo + 1:]
            if not self._process_item(poison, tail=tuple(pending) + tail,
                                      admitted=True):
                return False
            known_bad = False
        return True

    def _apply_items_individually(self, items: Sequence,
                                  tail: tuple) -> bool:
        """Fallback from a failed combined commit: every run item
        through the per-item containment core.  Admission is not
        re-judged (the run already passed the gate); rejection counting,
        retry/backoff, quarantine, and crash ordering all apply
        unchanged."""
        pending = collections.deque(items)
        while pending:
            it = pending.popleft()
            if not self._process_item(it, tail=tuple(pending) + tail,
                                      admitted=True):
                return False
        return True

    # -- durable checkpoints (ISSUE 14) --------------------------------------

    def _maybe_checkpoint(self) -> None:
        """Epoch-fenced checkpoint cadence, called by the apply loop
        after every settled item.  A failure gathering or (synchronous
        store) writing is counted and contained — persistence trouble
        must never halt serving; the atomic layer guarantees it also
        never leaves a torn artifact behind."""
        # the clock the node already tracks (every tick updates
        # _clock_slot in _note_clock) — zero spec calls per settled item
        epoch = self._clock_slot // self._spe
        if epoch < self._ckpt_epoch_seen + self._ckpt_interval:
            return
        self._ckpt_epoch_seen = epoch
        if self._journal is None or not self._journal:
            return  # a journal-less node has nothing a restore can resume
        try:
            payload = self._gather_checkpoint()
            if payload is not None:
                self._ckpt_store.submit(self.spec, payload)
                stats["checkpoints_scheduled"] += 1
        except Exception:
            stats["checkpoint_gather_failures"] += 1

    def _gather_checkpoint(self):
        """Snapshot the fork-choice world under the single writer: the
        finalized anchor, every block/state descending from it (the
        since-finality window), and the store extras — all as references
        to immutable views and shallow copies of the small maps, so the
        gather costs milliseconds and the writer thread serializes from
        a frozen picture."""
        spec, store = self.spec, self.engine.store
        fin_root = bytes(store.finalized_checkpoint.root)
        if fin_root not in store.blocks:
            return None
        window = []
        descend = {fin_root}
        for root, block in sorted(store.blocks.items(),
                                  key=lambda kv: int(kv[1].slot)):
            rb = bytes(root)
            if rb == fin_root or bytes(block.parent_root) in descend:
                descend.add(rb)
                state = store.block_states[root]
                # memoize every root in the loop thread (a no-op after
                # the block's own state-root check) so the writer
                # thread's tree walk is purely read-only
                state.hash_tree_root()
                window.append((rb, block, state))
        if not window:
            return None
        return persist_store.CheckpointPayload(
            journal_pos=len(self._journal),
            trigger=_journal_token(self._journal[-1]),
            time=int(store.time),
            justified=_cp_pair(store.justified_checkpoint),
            best_justified=_cp_pair(store.best_justified_checkpoint),
            finalized=_cp_pair(store.finalized_checkpoint),
            proposer_boost_root=bytes(store.proposer_boost_root),
            latest_messages=dict(store.latest_messages),
            equivocating=frozenset(store.equivocating_indices),
            anchor_root=fin_root,
            window=tuple(window),
            head_state_root=bytes(window[-1][2].hash_tree_root()),
            last_block=self._journal_last_block)

    def run_apply_loop(self, timeout: Optional[float] = None,
                       max_items: Optional[int] = None) -> int:
        """Drain the queue until it is closed and empty (or ``timeout``
        elapses waiting for work).  Returns the number of items
        processed.  This is THE single writer: run it on one thread.
        A poison item never halts the loop — it is retried up to the
        node's cap with backoff, then quarantined to the dead-letter
        ring (``node_quarantine`` flight-recorder event) while serving
        continues.  ``max_items`` stops the loop after that many items —
        the crash-drill hook the recovery tests kill the loop with.

        The drain is an adaptive micro-batcher (ISSUE 19): one bulk
        ``drain`` pulls everything queued — waking every blocked
        producer with a single ``notify_all`` — the admission gate's
        back-pressure aggregation buffer flushes into the same batch,
        and ``_process_batch`` partitions it into strict-order items
        and coalesced gossip runs."""
        stats["apply_loop_runs"] += 1
        processed = 0
        while max_items is None or processed < max_items:
            limit = None if max_items is None else max_items - processed
            batch = self.queue.drain(timeout=timeout, max_items=limit)
            if batch is None:
                # end of stream (or timeout): whatever back-pressure
                # staged in the aggregation buffer still owes an apply
                batch = self._drain_aggregated(limit)
                if not batch:
                    return processed
            else:
                room = None if limit is None else limit - len(batch)
                if room is None or room > 0:
                    batch.extend(self._drain_aggregated(room))
            histogram.observe("drain_batch", float(len(batch)))
            stats["batches_applied"] += 1
            processed += self._process_batch(batch)
        return processed


def _journal_token(entry) -> tuple:
    """A content-bound identity token for one journal entry — what a
    checkpoint records about the entry it was written after, and what
    recovery compares before trusting that a checkpoint belongs to THIS
    journal.  Tick tokens alone would collide across any two runs on
    the same slot schedule, so attestation tokens bind content (first/
    last data roots) and the checkpoint ALSO records the newest block
    entry's (position, root) — see ``_recover_from_checkpoint``: a
    checkpoint directory from a different run must degrade to a stale
    miss, never splice a foreign suffix onto a restored store."""
    kind, payload = entry
    if kind == "block":
        return ("block", bytes(payload.message.hash_tree_root()).hex())
    if kind == "tick":
        return ("tick", int(payload))
    if kind == "attestations":
        if not payload:
            return ("attestations", 0)
        return ("attestations", len(payload),
                bytes(payload[0].hash_tree_root()).hex(),
                bytes(payload[-1].hash_tree_root()).hex())
    if kind == "attester_slashing":
        return ("attester_slashing", bytes(payload.hash_tree_root()).hex())
    return (kind, None)


def _cp_pair(checkpoint) -> tuple:
    return (int(checkpoint.epoch), bytes(checkpoint.root))


def _last_block_matches(journal, last_block, pos: int) -> bool:
    """The checkpoint's content-bound journal anchor: the newest block
    entry it recorded must sit at the same position with the same root
    in THIS journal.  Tick/gossip trigger tokens repeat across runs on
    the same slot schedule; a block root cannot — so a foreign-run
    checkpoint directory fails here and degrades to a stale miss."""
    if last_block is None:
        return True  # a pre-first-block checkpoint has no anchor to pin
    lbp, lbroot = int(last_block[0]), last_block[1]
    if not 0 <= lbp < pos or lbp >= len(journal):
        return False
    kind, payload = journal[lbp]
    return (kind == "block"
            and bytes(payload.message.hash_tree_root()).hex() == lbroot)


def _replay_journal(node: Node, journal) -> None:
    for kind, payload in journal:
        if kind == "tick":
            node.on_tick(payload)
        elif kind == "block":
            node.on_block(payload)
        elif kind == "attestations":
            node.on_attestations(payload)
        elif kind == "attester_slashing":
            node.on_attester_slashing(payload)
        else:
            raise ValueError(f"unknown journal kind {kind!r}")


def _recover_from_checkpoint(spec, journal, checkpoint_store,
                             node_kwargs) -> Optional[Node]:
    """The checkpoint fast path: walk candidates newest-first, restore
    the first one that is intact AND belongs to this journal, then
    replay only the suffix.  Every rung of the ladder — damaged
    artifact, stale tag, foreign journal — moves to the next candidate;
    None (the caller falls back to full replay) only when all are
    exhausted."""
    journal = list(journal)
    for path in checkpoint_store.candidates():
        try:
            restored = checkpoint_store.restore(spec, path)
        except persist_store.CheckpointError:
            continue  # quarantined + counted + flight-recorded inside
        pos = restored.journal_pos
        if not (1 <= pos <= len(journal)) or tuple(
                _journal_token(journal[pos - 1])) != tuple(restored.trigger):
            # an intact checkpoint from another journal/run: a stale
            # miss, not damage — the artifact survives for ITS journal
            persist_store.stats["stale_artifacts"] += 1
            continue
        if not _last_block_matches(journal,
                                   restored.meta.get("last_block"), pos):
            persist_store.stats["stale_artifacts"] += 1
            continue
        store = restored.as_store(spec)
        node = Node(spec, None, checkpoint_store=checkpoint_store,
                    _warm_store=store, **node_kwargs)
        _SITE_RECOVER()
        with timeline.span("node/recover", items=len(journal) - pos,
                           checkpoint=pos):
            # seed the journal with the covered prefix so the recovered
            # node's history is the crashed node's, then replay the
            # suffix through the engine-backed handlers (which append)
            if node._journal is not None:
                node._journal = journal[:pos]
            _replay_journal(node, journal[pos:])
        stats["checkpoint_recoveries"] += 1
        if recorder.enabled():
            recorder.record("checkpoint_restored", journal_pos=pos,
                            suffix_items=len(journal) - pos,
                            epoch=restored.meta["finalized"][0])
        return node
    return None


def recover_node(spec, anchor_state, anchor_block=None, journal=(),
                 checkpoint_store=None, **node_kwargs) -> Node:
    """Crash recovery (ISSUE 13; checkpoint fast path ISSUE 14): rebuild
    a ``Node`` whose store is byte-identical to the crashed one's.

    With a ``checkpoint_store``, recovery first tries the durable fast
    path: restore the newest valid checkpoint and replay only the
    journal suffix after its recorded position — O(since-the-last-
    epoch-fence) instead of O(history).  A truncated, bit-flipped,
    stale-tagged, or foreign-journal artifact is detected at load,
    quarantined, counted, flight-recorded (``store_corrupt``), and the
    ladder moves on; exhausting every candidate falls back to the full
    journal replay below — recovery never crashes on disk damage and
    never serves a state the journal doesn't vouch for.

    The full-replay path (PR 13) is unchanged: fresh node from the
    anchor, the whole journal through the engine-backed handlers.
    Either way the admission surface is PRESERVED (dead letters, peer
    scores, quarantine outlive the crash; only the transient seen-keys
    reset), the ``node.recover`` probe fires after construction and
    before the replay, and ``node_recovered`` is emitted only once the
    replay fully settles."""
    node_kwargs.setdefault("adopt_admission", False)
    if node_kwargs.get("adopt_admission") is False:
        # clear the TRANSIENT surface only: seen-keys for items that
        # never applied (the in-flight item at the kill, pooled
        # orphans) must not judge the mesh's re-delivery a duplicate —
        # but dead letters, scores, and quarantine survive
        admission.reset_transient()
    node = None
    if checkpoint_store is not None:
        node = _recover_from_checkpoint(spec, journal, checkpoint_store,
                                        node_kwargs)
        if node is None:
            persist_store.stats["restore_fallbacks"] += 1
    if node is None:
        node = Node(spec, anchor_state, anchor_block,
                    checkpoint_store=checkpoint_store, **node_kwargs)
        _SITE_RECOVER()
        with timeline.span("node/recover", items=len(journal)):
            _replay_journal(node, journal)
    stats["recoveries"] += 1
    if recorder.enabled():
        recorder.record("node_recovered", items=len(journal))
    return node
