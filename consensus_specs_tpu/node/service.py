"""The node: one servable pipeline over the two engines (ISSUE 12
tentpole; ROADMAP item 1).

Eleven PRs built two fast libraries — the proto-array fork-choice engine
(``forkchoice/engine.py``) and the batched stf engine
(``stf/engine.py``) — but ``on_block`` still replayed blocks through the
literal ``spec.state_transition``.  This module composes them into ONE
pipeline:

* **engine-backed ``on_block``** — ``engine_backed_on_block`` is the
  spec handler (specs/src/phase0.py:1602-1641; bellatrix adds the
  merge-transition check) with the state transition routed through
  ``stf.apply_signed_blocks``: the block's signature batch dispatches to
  the pipeline worker, attestations apply vectorized, slot roots ride
  the resident merkle path — and the stf engine's rollback contract,
  literal-replay fallback, and circuit breaker carry over UNCHANGED
  (``apply_signed_blocks`` is semantically identical to
  ``state_transition``, same post-state, same exception at the same
  point).  A ``Node``'s fork-choice engine gets this handler installed
  at construction (the ``block_handler`` seam), so head tracking, block
  verification, and state transition are one pipeline.

* **single-writer apply loop over a bounded multi-producer queue** —
  fork choice is single-writer by contract; producers (gossip readers,
  block fetchers, the clock) enqueue into ``node/ingest.py``'s bounded
  FIFO and ``run_apply_loop`` drains it on ONE thread.  A non-blocking
  writer lock enforces the contract (a second concurrent writer raises
  instead of corrupting the store).  A failed item is put back at the
  HEAD of the queue before the exception propagates — a retried loop
  resumes exactly where it stopped, and the ``node.apply`` fault probe
  fires before any store/proto mutation so an injected failure leaves
  both untouched (tests/chaos/test_node_chaos.py).  Invalid gossip is
  production-shaped load, not a crash: an attestation batch the spec
  rejects (``AssertionError``) is counted and dropped, the loop keeps
  serving.

* **parity journal** — every applied item lands in ``node.journal`` in
  apply order, so a concurrent run's end state is exactly replayable
  through the literal spec handlers (the firehose's head/root parity
  leg replays the journal, making byte-identical-state assertions
  meaningful under nondeterministic producer interleaving).

Observability: ``node_block``/``node_gossip`` flight-recorder events
(recorded only after the engine call settled — OB01's commit
discipline), ``node/apply`` timeline spans carrying the enqueue-time
causality link (Perfetto shows the producer → apply-loop handoff), and a
``node`` snapshot provider on the telemetry bus (queue depth,
applied/rejected counters, producer stats).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence

from consensus_specs_tpu import faults, telemetry
from consensus_specs_tpu.forkchoice import ForkChoiceEngine
from consensus_specs_tpu.stf import apply_signed_blocks
from consensus_specs_tpu.telemetry import recorder, timeline

from . import ingest

# probed at the top of every apply (direct handler or loop item), BEFORE
# the engine dispatch: an injected failure leaves store + proto-array
# exactly as they were and the dequeued item back at the queue head
_SITE_APPLY = faults.site("node.apply")

stats = {
    "blocks_applied": 0,
    "ticks_applied": 0,
    "attestation_batches_applied": 0,
    "attestations_applied": 0,
    "rejected_batches": 0,
    "rejected_attestations": 0,
    "requeued_items": 0,
    "apply_loop_runs": 0,
}


def reset_stats() -> None:
    """Zero the node counters AND the ingest queue's (they attribute one
    pipeline; a firehose run must not inherit a previous run's counts)."""
    for k in stats:
        stats[k] = 0
    ingest.reset_stats()


def _telemetry_provider() -> dict:
    return {**stats, "queue": ingest.snapshot()}


telemetry.register_provider("node", _telemetry_provider, replace=True)


def engine_backed_on_block(spec, store, signed_block) -> None:
    """``spec.on_block`` with the state transition routed through the
    batched stf engine — same store mutations, same exceptions at the
    same points (``apply_signed_blocks`` is differentially pinned to
    ``state_transition``), so this is a drop-in for the fork-choice
    engine's ``block_handler`` seam."""
    block = signed_block.message
    # Parent block must be known
    assert block.parent_root in store.block_states
    pre_state = store.block_states[block.parent_root].copy()
    # Blocks cannot be in the future
    assert spec.get_current_slot(store) >= block.slot
    # Block must be later than the finalized epoch slot, on its chain
    finalized_slot = spec.compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch)
    assert block.slot > finalized_slot
    assert spec.get_ancestor(store, block.parent_root, finalized_slot) == \
        store.finalized_checkpoint.root

    # the one substitution: the batched engine instead of the literal
    # spec.state_transition (rollback/breaker/replay semantics inside)
    state = pre_state.copy()
    apply_signed_blocks(spec, state, (signed_block,), True)

    # [New in Bellatrix] merge-transition validation, against the
    # untransitioned pre-state exactly as the spec orders it
    is_mtb = getattr(spec, "is_merge_transition_block", None)
    if is_mtb is not None and is_mtb(pre_state, block.body):
        spec.validate_merge_block(block)

    root = spec.hash_tree_root(block)
    store.blocks[root] = block
    store.block_states[root] = state

    time_into_slot = ((store.time - store.genesis_time)
                      % spec.config.SECONDS_PER_SLOT)
    is_before_attesting_interval = (
        time_into_slot
        < spec.config.SECONDS_PER_SLOT // spec.INTERVALS_PER_SLOT)
    if spec.get_current_slot(store) == block.slot \
            and is_before_attesting_interval:
        store.proposer_boost_root = root

    if state.current_justified_checkpoint.epoch > \
            store.justified_checkpoint.epoch:
        if state.current_justified_checkpoint.epoch > \
                store.best_justified_checkpoint.epoch:
            store.best_justified_checkpoint = \
                state.current_justified_checkpoint
        if spec.should_update_justified_checkpoint(
                store, state.current_justified_checkpoint):
            store.justified_checkpoint = state.current_justified_checkpoint

    if state.finalized_checkpoint.epoch > store.finalized_checkpoint.epoch:
        store.finalized_checkpoint = state.finalized_checkpoint
        store.justified_checkpoint = state.current_justified_checkpoint


def default_anchor_block(spec, anchor_state):
    """The anchor block a state implies: its ``latest_block_header`` with
    the state root filled — valid whenever the header's body root is the
    empty body's (genesis states; firehose-prepared anchors)."""
    header = anchor_state.latest_block_header
    return spec.BeaconBlock(
        slot=header.slot, proposer_index=header.proposer_index,
        parent_root=header.parent_root,
        state_root=anchor_state.hash_tree_root())


class Node:
    """A servable consensus node: fork choice + batched state transition
    behind one single-writer handler surface and one ingest queue."""

    def __init__(self, spec, anchor_state, anchor_block=None,
                 queue_cap: int = ingest.DEFAULT_CAP, journal: bool = True):
        self.spec = spec
        if anchor_block is None:
            anchor_block = default_anchor_block(spec, anchor_state)
        store = spec.get_forkchoice_store(anchor_state, anchor_block)
        self.engine = ForkChoiceEngine(
            spec, store, block_handler=self._on_block_stf)
        self.queue = ingest.IngestQueue(cap=queue_cap)
        # apply-order journal: the literal-spec parity replay's script.
        # Owner-mutated only (analyzer-registered next to the queue).
        self._journal = [] if journal else None
        self._writer_lock = threading.Lock()
        self._clock_cond = threading.Condition()
        self._clock_slot = int(spec.get_current_slot(store))

    def _on_block_stf(self, store, signed_block) -> None:
        """The ``block_handler`` installed on the fork-choice engine:
        the spec handler shape with the batched stf transition."""
        engine_backed_on_block(self.spec, store, signed_block)

    # -- single-writer contract ----------------------------------------------

    @contextlib.contextmanager
    def _single_writer(self):
        if not self._writer_lock.acquire(blocking=False):
            raise RuntimeError(
                "concurrent node apply: fork choice is single-writer — "
                "producers must enqueue, only the apply loop applies")
        try:
            yield
        finally:
            self._writer_lock.release()

    def _journal_append(self, kind: str, payload) -> None:
        if self._journal is not None:
            self._journal.append((kind, payload))

    def _note_clock(self) -> None:
        slot = int(self.spec.get_current_slot(self.engine.store))
        if slot != self._clock_slot:
            with self._clock_cond:
                self._clock_slot = slot
                self._clock_cond.notify_all()

    def wait_for_clock(self, slot: int,
                       timeout: Optional[float] = None) -> bool:
        """Block until the store clock reaches ``slot`` (producers pace
        themselves against the apply loop with this — e.g. gossip for
        slot N enqueues only once the clock passed N, so the votes are
        mature on arrival)."""
        with self._clock_cond:
            return self._clock_cond.wait_for(
                lambda: self._clock_slot >= slot, timeout)

    # -- handlers (the single writer's API) ----------------------------------

    def on_tick(self, time) -> None:
        with self._single_writer():
            _SITE_APPLY()
            self.engine.on_tick(time)
            stats["ticks_applied"] += 1
            self._journal_append("tick", int(time))
        self._note_clock()

    def on_block(self, signed_block) -> None:
        with self._single_writer():
            _SITE_APPLY()
            self.engine.on_block(signed_block)
            stats["blocks_applied"] += 1
            self._journal_append("block", signed_block)
            if recorder.enabled():
                recorder.record("node_block",
                                slot=int(signed_block.message.slot))

    def on_attestations(self, attestations: Sequence,
                        is_from_block: bool = False) -> None:
        with self._single_writer():
            _SITE_APPLY()
            self.engine.on_attestations(list(attestations),
                                        is_from_block=is_from_block)
            stats["attestation_batches_applied"] += 1
            stats["attestations_applied"] += len(attestations)
            self._journal_append("attestations", tuple(attestations))
            if recorder.enabled():
                recorder.record("node_gossip", n=len(attestations))

    def on_attestation(self, attestation, is_from_block: bool = False) -> None:
        self.on_attestations([attestation], is_from_block=is_from_block)

    def on_attester_slashing(self, attester_slashing) -> None:
        with self._single_writer():
            _SITE_APPLY()
            self.engine.on_attester_slashing(attester_slashing)
            self._journal_append("attester_slashing", attester_slashing)

    def get_head(self):
        return self.engine.get_head()

    @property
    def store(self):
        return self.engine.store

    @property
    def journal(self) -> list:
        return list(self._journal or ())

    # -- producer surface ----------------------------------------------------

    def enqueue_tick(self, time, timeout: Optional[float] = None) -> None:
        self.queue.put("tick", int(time), timeout=timeout)

    def enqueue_block(self, signed_block,
                      timeout: Optional[float] = None) -> None:
        self.queue.put("block", signed_block, timeout=timeout)

    def enqueue_attestations(self, attestations: Sequence,
                             timeout: Optional[float] = None) -> None:
        self.queue.put("attestations", tuple(attestations), timeout=timeout)

    # -- the apply loop ------------------------------------------------------

    def apply_item(self, item: ingest.WorkItem) -> None:
        """Apply one dequeued work item.  A rejected gossip batch (spec
        validation ``AssertionError``) is counted and dropped; ANY other
        failure re-queues the item at the head and propagates — the
        store and proto-array are untouched past the probe, so a retry
        picks up exactly where the loop stopped."""
        try:
            with timeline.span("node/apply", link=item.link, kind=item.kind):
                if item.kind == "tick":
                    self.on_tick(item.payload)
                elif item.kind == "block":
                    self.on_block(item.payload)
                elif item.kind == "attestations":
                    try:
                        self.on_attestations(item.payload)
                    except AssertionError:
                        stats["rejected_batches"] += 1
                        stats["rejected_attestations"] += len(item.payload)
                        if recorder.enabled():
                            recorder.record("node_gossip_rejected",
                                            n=len(item.payload))
                else:
                    raise ValueError(f"unknown work item kind {item.kind!r}")
        except BaseException:
            self.queue.requeue_front(item)
            stats["requeued_items"] += 1
            raise

    def run_apply_loop(self, timeout: Optional[float] = None) -> int:
        """Drain the queue until it is closed and empty (or ``timeout``
        elapses waiting for work).  Returns the number of items applied.
        This is THE single writer: run it on one thread."""
        stats["apply_loop_runs"] += 1
        applied = 0
        while True:
            item = self.queue.get(timeout=timeout)
            if item is None:
                return applied
            self.apply_item(item)
            applied += 1
