"""Seeded deterministic adversarial corpora + the adversarial firehose
(ISSUE 13 tentpole; ROADMAP item 4: run reorgs, equivocation storms, and
finality stalls THROUGH the firehose).

``firehose.py`` proves the node serves honest traffic; this module
proves it SURVIVES hostile traffic.  ``build_adversarial_corpus`` lays
four attack corpora over an honest chain, all derived from one seed:

* **finality-stall chain** — one epoch of the honest chain carries no
  block attestations, so justification stalls through that epoch and
  resumes after (the chain itself stays valid);
* **long-range reorg branch** — a valid side chain forked near the
  anchor (its first block is a PROPOSER EQUIVOCATION: same slot, same
  proposer, different content than the canonical block), delivered
  deepest-child-FIRST so every block but the last is an orphan-pool
  entry that re-links when its parent finally arrives;
* **equivocation storm** — seeded ``AttesterSlashing`` double-votes
  (distinct index sets, same target epoch, different data) that march
  through ``on_attester_slashing`` into ``store.equivocating_indices``,
  clearing those validators' fork-choice votes mid-serve;
* **junk + duplicate floods** — undecodable bytes, wrong-shaped
  objects, unknown item kinds, verbatim re-deliveries of honest gossip
  and blocks, never-linking orphan blocks (unknown parents that must
  expire), and honest blocks delivered AHEAD of their slot (the
  future-parking path) — plus a reserve of fresh gossip the flooding
  producer sends once quarantined, proving the shed path drops it.

``run_adversarial_firehose`` drives all of it concurrently through the
bounded ingest queue against the single-writer apply loop (honest chain
driver + gossip producers exactly like the honest firehose, plus an
``adv-chain`` and an ``adv-junk`` producer), and holds the survival
contract:

* **zero halts** — the apply loop runs to completion; poison/junk items
  are rejected, quarantined, or shed, never raised;
* **byte-identical head/root** — whatever the queue's interleaving, the
  node's apply journal replayed through the literal spec handlers
  reaches the same head, state root, checkpoints, and latest messages
  (``firehose.assert_parity``);
* **bounded memory** — every admission structure (orphan pool, parked
  ring, dead-letter ring, seen-set, score table, aggregation buffer)
  sits at or under its cap in the bus snapshot (``assert_bounded``).
"""
from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, NamedTuple, Tuple

from consensus_specs_tpu.testing.helpers.attestations import (
    build_attestation_data,
)

from . import admission, firehose
from .service import Node


class AdversarialCorpus(NamedTuple):
    """One seeded adversarial workload over an anchor state."""

    anchor_block: object
    chain: List[object]               # honest chain, chain order
    gossip: Dict[int, List[object]]   # slot -> honest gossip votes
    shed_gossip: Dict[int, List[object]]  # fresh votes the flooder sends
    stall_epochs: Tuple[int, ...]     # epochs with no block attestations
    fork_blocks: List[object]         # valid reorg branch, chain order
    orphan_blocks: List[object]       # unknown-parent blocks (never link)
    future_slots: Tuple[int, ...]     # honest slots pre-delivered early
    slashings: List[object]           # the equivocation storm
    junk: List[Tuple[str, object]]    # malformed/undecodable work items
    duplicate_slots: Tuple[int, ...]  # slots re-delivered verbatim


def _signed_copy(spec, signed_block):
    return spec.SignedBeaconBlock.decode_bytes(signed_block.encode_bytes())


def build_adversarial_corpus(spec, anchor_state, seed: int = 90013,
                             n_epochs: int = 3, gossip_target: int = 600,
                             fork_len: int = 5, n_orphans: int = 3,
                             n_slashings: int = 4, shed_per_slot: int = 4,
                             prebuilt=None) -> AdversarialCorpus:
    """Deterministic hostile workload: an ``n_epochs`` honest chain with
    its SECOND epoch attestation-free (the finality stall), a
    ``fork_len``-block reorg branch off the second canonical block, the
    slashing storm, junk items, and the duplicate/future/orphan
    schedules — all drawn from ``seed``.  Built BLS-off like the honest
    corpus (the firehose measures orchestration, not pairing).

    ``prebuilt`` short-circuits the expensive state walk with cached
    ``(chain, gossip, shed_gossip, fork_blocks)`` parts (bench.py's disk
    cache); the seeded schedules are re-derived identically — the rng is
    only consumed AFTER the heavy build in both paths."""
    if prebuilt is not None:
        chain, gossip, shed_gossip, fork_blocks = prebuilt
        return _assemble(spec, anchor_state, seed, n_epochs, chain, gossip,
                         shed_gossip, fork_blocks, n_orphans, n_slashings)
    from consensus_specs_tpu.crypto import bls

    anchor_block = firehose.default_anchor_block(spec, anchor_state)
    spe = int(spec.SLOTS_PER_EPOCH)
    n_slots = n_epochs * spe
    per_slot = max(1, -(-gossip_target // n_slots))
    was_active = bls.bls_active
    bls.bls_active = False
    try:
        build_st = anchor_state.copy()
        chain: List[object] = []
        gossip: Dict[int, List[object]] = {}
        shed_gossip: Dict[int, List[object]] = {}
        first_slot = int(build_st.slot) + 1
        first_epoch = first_slot // spe
        # the stall epoch: the corpus's second full epoch — late enough
        # that justification has something to stall, early enough that
        # the tail can show recovery
        stall_epochs = (first_epoch + 1,)
        # branch off the FOURTH block: strictly above the never-linking
        # orphans' slots (1-3), so the run's orphan-expiry window can be
        # tuned to expire the never-linkers by the final tick while the
        # fork branch cannot expire in-run under ANY delivery timing
        fork_base_slot = first_slot + 3
        fork_state = None
        for slot in range(first_slot, first_slot + n_slots):
            stub = build_st.copy()
            spec.process_slots(stub, slot)
            block = spec.BeaconBlock(
                slot=slot,
                proposer_index=spec.get_beacon_proposer_index(stub))
            block.body.eth1_data.deposit_count = stub.eth1_deposit_index
            header = build_st.latest_block_header.copy()
            if header.state_root == spec.Root():
                header.state_root = build_st.hash_tree_root()
            block.parent_root = header.hash_tree_root()
            att_slot = slot - 1
            in_stall = (att_slot // spe) in stall_epochs
            if att_slot >= first_slot and not in_stall:
                epoch = spec.compute_epoch_at_slot(att_slot)
                for index in range(int(
                        spec.get_committee_count_per_slot(stub, epoch))):
                    if len(block.body.attestations) >= int(
                            spec.MAX_ATTESTATIONS):
                        break
                    committee = spec.get_beacon_committee(
                        stub, att_slot, index)
                    block.body.attestations.append(spec.Attestation(
                        aggregation_bits=[True] * len(committee),
                        data=build_attestation_data(
                            spec, stub, att_slot, index)))
            spec.process_slots(build_st, slot)
            spec.process_block(build_st, block)
            block.state_root = build_st.hash_tree_root()
            chain.append(spec.SignedBeaconBlock(message=block))
            votes = firehose._gossip_for_slot(
                spec, build_st, slot, block.hash_tree_root(),
                per_slot + shed_per_slot)
            gossip[slot] = votes[:per_slot]
            shed_gossip[slot] = votes[per_slot:]
            if slot == fork_base_slot:
                fork_state = build_st.copy()

        fork_blocks = _build_fork_branch(spec, fork_state, fork_len)
        return _assemble(spec, anchor_state, seed, n_epochs, chain, gossip,
                         shed_gossip, fork_blocks, n_orphans, n_slashings)
    finally:
        bls.bls_active = was_active


def _assemble(spec, anchor_state, seed, n_epochs, chain, gossip,
              shed_gossip, fork_blocks, n_orphans,
              n_slashings) -> AdversarialCorpus:
    """The rng-driven half of the corpus: everything derivable from the
    (possibly cache-loaded) heavy parts, in one fixed draw order so cold
    and cached builds agree byte-for-byte."""
    rng = random.Random(seed)
    anchor_block = firehose.default_anchor_block(spec, anchor_state)
    spe = int(spec.SLOTS_PER_EPOCH)
    n_slots = n_epochs * spe
    first_slot = int(chain[0].message.slot)
    first_epoch = first_slot // spe
    stall_epochs = (first_epoch + 1,)
    # never-linkers come from the first three blocks only (slots 1-3):
    # strictly below the fork base, see build_adversarial_corpus
    orphan_blocks = _build_never_linking(spec, chain[:3], rng, n_orphans)
    slashings = _build_slashing_storm(
        spec, anchor_state, rng, n_slashings, first_epoch)
    junk = _build_junk(rng)
    # duplicates stay inside the first two epochs so the run has clock
    # left to process the re-deliveries; future pre-deliveries come from
    # the LAST epoch so they are guaranteed ahead of the clock at
    # enqueue (the parking path is deterministic, not a race with the
    # apply loop)
    dup_pool = sorted(gossip)[:2 * spe]
    duplicate_slots = tuple(sorted(rng.sample(
        dup_pool, min(4, len(dup_pool)))))
    last_epoch_start = first_slot + (n_epochs - 1) * spe
    future_slots = tuple(sorted(rng.sample(
        range(last_epoch_start + 1, first_slot + n_slots - 1),
        min(2, spe - 2))))
    return AdversarialCorpus(
        anchor_block, chain, gossip, shed_gossip, stall_epochs,
        fork_blocks, orphan_blocks, future_slots, slashings, junk,
        duplicate_slots)


def _build_fork_branch(spec, fork_state, fork_len: int) -> List[object]:
    """A valid empty-block side chain from ``fork_state`` (the canonical
    post-state at the fork base).  Its first block shares slot AND
    proposer with the canonical block built from the same pre-state —
    proposer equivocation by construction; graffiti disambiguates the
    content."""
    out: List[object] = []
    if fork_state is None or fork_len <= 0:
        return out
    st = fork_state.copy()
    # the branch's parent: the block whose post-state fork_state is
    header = st.latest_block_header.copy()
    if header.state_root == spec.Root():
        header.state_root = st.hash_tree_root()
    parent_root = header.hash_tree_root()
    for i in range(fork_len):
        slot = int(st.slot) + 1
        stub = st.copy()
        spec.process_slots(stub, slot)
        block = spec.BeaconBlock(
            slot=slot, proposer_index=spec.get_beacon_proposer_index(stub),
            parent_root=parent_root)
        block.body.eth1_data.deposit_count = stub.eth1_deposit_index
        block.body.graffiti = b"fork" + bytes([i]) + b"\x00" * 27
        spec.process_slots(st, slot)
        spec.process_block(st, block)
        block.state_root = st.hash_tree_root()
        out.append(spec.SignedBeaconBlock(message=block))
        parent_root = block.hash_tree_root()
    return out


def _build_never_linking(spec, chain, rng, n: int) -> List[object]:
    """Copies of early honest blocks re-parented onto roots no store
    will ever hold: orphan-pool entries whose only exit is expiry."""
    out = []
    for i in range(min(n, len(chain))):
        signed = _signed_copy(spec, chain[i])
        signed.message.parent_root = bytes(
            rng.getrandbits(8) for _ in range(32))
        out.append(signed)
    return out


def _build_slashing_storm(spec, anchor_state, rng, n: int,
                          epoch: int) -> List[object]:
    """Seeded double-vote ``AttesterSlashing`` objects: distinct sorted
    index sets, same target epoch, different vote data — exactly the
    shape ``is_slashable_attestation_data`` calls a double vote.  Valid
    BLS-off (``is_valid_indexed_attestation`` checks ordering and the
    aggregate signature; indices only need to exist in the registry)."""
    out = []
    n_validators = len(anchor_state.validators)
    root_a, root_b = b"\xaa" * 32, b"\xbb" * 32
    for i in range(n):
        k = min(4 + i, max(1, n_validators // 8))
        indices = sorted(rng.sample(range(n_validators), k))
        data_1 = spec.AttestationData(
            slot=spec.Slot(1), index=0,
            beacon_block_root=root_a,
            source=spec.Checkpoint(epoch=epoch, root=root_a),
            target=spec.Checkpoint(epoch=epoch + 1, root=root_a))
        data_2 = spec.AttestationData(
            slot=spec.Slot(1), index=0,
            beacon_block_root=root_b,
            source=spec.Checkpoint(epoch=epoch, root=root_b),
            target=spec.Checkpoint(epoch=epoch + 1, root=root_b))
        out.append(spec.AttesterSlashing(
            attestation_1=spec.IndexedAttestation(
                attesting_indices=indices, data=data_1),
            attestation_2=spec.IndexedAttestation(
                attesting_indices=indices, data=data_2)))
    return out


def _build_junk(rng) -> List[Tuple[str, object]]:
    """Malformed/undecodable work items: every admission rejection path
    gets traffic."""
    return [
        ("block", bytes(rng.getrandbits(8) for _ in range(17))),
        ("block", 42),
        ("block", object()),
        ("attestations", ("not-an-attestation",)),
        ("attestations", b"\x00\x01\x02"),
        ("attester_slashing", b"\xff" * 9),
        ("blob_sidecar", b"\x00" * 48),
        ("tick", "not-a-time"),
    ]


def assert_bounded(snap: dict = None) -> dict:
    """Every admission structure at or under its registered cap in the
    bus snapshot — the bounded-memory half of the survival contract."""
    snap = snap if snap is not None else admission.snapshot()
    for size_key, cap_key in (
            ("orphan_pool_depth", "orphan_pool_cap"),
            ("parked_depth", "parked_cap"),
            ("dead_letter_depth", "dead_letter_cap"),
            ("seen_size", "seen_cap"),
            ("scores_size", "scores_cap"),
            ("agg_depth", "agg_cap")):
        assert snap[size_key] <= snap[cap_key], (
            f"admission {size_key} {snap[size_key]} over its cap "
            f"{snap[cap_key]} — an unbounded survival structure")
    # the quarantine set is a subset of the tracked scores by invariant
    assert len(snap["quarantined_producers"]) <= snap["scores_cap"]
    return snap


def run_adversarial_firehose(spec, anchor_state, corpus: AdversarialCorpus,
                             n_gossip_producers: int = 2,
                             queue_cap: int = 64, gossip_batch: int = 256,
                             producer_timeout: float = 300.0,
                             junk_rounds: int = 2) -> dict:
    """Serve the hostile corpus through a fresh ``Node`` under
    concurrent load: the honest chain driver + gossip producers of the
    plain firehose, plus the ``adv-chain`` producer (future blocks, the
    reorg branch deepest-child-first, the slashing storm, never-linking
    orphans) and the ``adv-junk`` flood (malformed items, verbatim
    duplicates, then fresh gossip from inside quarantine).  The calling
    thread runs the apply loop; the run's survival asserts
    (zero-halt/bounded) live here, parity is the caller's leg like the
    honest harness."""
    spe = int(spec.SLOTS_PER_EPOCH)
    genesis_time = int(anchor_state.genesis_time)
    sps = int(spec.config.SECONDS_PER_SLOT)
    node = Node(spec, anchor_state, corpus.anchor_block,
                queue_cap=queue_cap)
    # orphan-expiry window derived from the corpus geometry (expiry is
    # slot-anchored): final_clock - max(never-linker slot) makes every
    # never-linking orphan expire AT OR BEFORE the final tick's
    # housekeeping (or expire-on-arrival if delivered later still),
    # while the fork branch — whose slots sit strictly higher — cannot
    # expire in-run under ANY thread-scheduling delay (restored on exit)
    final_clock = int(corpus.chain[-1].message.slot) + 1
    never_max = max((int(sb.message.slot) for sb in corpus.orphan_blocks),
                    default=int(corpus.chain[0].message.slot))
    prev_expiry = admission.set_orphan_expiry(final_clock - never_max)

    slots = sorted(corpus.gossip)
    remaining_by_epoch: Dict[int, int] = {}
    for s in slots:
        e = s // spe
        remaining_by_epoch[e] = remaining_by_epoch.get(e, 0) + 1
    fence = threading.Condition()
    abort = threading.Event()
    errors: List[BaseException] = []

    def _fail(exc: BaseException) -> None:
        errors.append(exc)
        abort.set()
        with fence:
            fence.notify_all()

    def _wait_clock(slot: int) -> bool:
        deadline = time.monotonic() + producer_timeout
        while not abort.is_set():
            if node.wait_for_clock(slot, timeout=0.5):
                return True
            if time.monotonic() > deadline:
                _fail(TimeoutError(
                    f"producer starved waiting for clock slot {slot}"))
                return False
        return False

    def gossip_producer(i: int) -> None:
        try:
            for s in slots[i::n_gossip_producers]:
                if not _wait_clock(s + 1):
                    return
                batch = corpus.gossip[s]
                for lo in range(0, len(batch), gossip_batch):
                    node.enqueue_attestations(
                        batch[lo:lo + gossip_batch],
                        timeout=producer_timeout)
                with fence:
                    remaining_by_epoch[s // spe] -= 1
                    fence.notify_all()
        except BaseException as exc:
            _fail(exc)

    def chain_driver() -> None:
        try:
            seen_epoch = None
            for signed in corpus.chain:
                s = int(signed.message.slot)
                e = s // spe
                if e != seen_epoch:
                    with fence:
                        fence.wait_for(lambda: abort.is_set() or not any(
                            n > 0 for ep, n in remaining_by_epoch.items()
                            if ep <= e - 2))
                    if abort.is_set():
                        return
                    seen_epoch = e
                node.enqueue_tick(genesis_time + s * sps,
                                  timeout=producer_timeout)
                node.enqueue_block(signed, timeout=producer_timeout)
            last = int(corpus.chain[-1].message.slot)
            node.enqueue_tick(genesis_time + (last + 1) * sps,
                              timeout=producer_timeout)
        except BaseException as exc:
            _fail(exc)

    first_slot = int(corpus.chain[0].message.slot)
    by_slot = {int(sb.message.slot): sb for sb in corpus.chain}

    def adv_chain() -> None:
        """Future pre-delivery, the reorg branch child-first, the
        slashing storm, and the never-linking orphans."""
        try:
            # future blocks land while the clock still sits near genesis
            for s in corpus.future_slots:
                if s in by_slot:
                    node.enqueue_block(by_slot[s], timeout=producer_timeout)
            # the branch forks off block 2: deliver once the clock has
            # passed the DEEPEST fork slot (none of the branch can hit
            # the future-parking path and bypass the orphan pool),
            # deepest child first — every block but the last orphans,
            # then one cascade re-links the whole branch
            deepest = max((int(sb.message.slot)
                           for sb in corpus.fork_blocks),
                          default=first_slot + 2)
            if not _wait_clock(deepest + 1):
                return
            for signed in reversed(corpus.fork_blocks):
                node.enqueue_block(signed, timeout=producer_timeout)
            for slashing in corpus.slashings:
                node.enqueue_attester_slashing(
                    slashing, timeout=producer_timeout)
            for signed in corpus.orphan_blocks:
                node.enqueue_block(signed, timeout=producer_timeout)
        except BaseException as exc:
            _fail(exc)

    def adv_junk() -> None:
        """Malformed flood (until quarantined), verbatim duplicates
        (dedup), then fresh reserve gossip (shed while quarantined)."""
        try:
            # the clock-rewind attack: a backwards tick must die at
            # admission (the spec's on_tick would rewind store.time)
            node.enqueue_tick(1, timeout=producer_timeout)
            for _ in range(junk_rounds):
                for kind, payload in corpus.junk:
                    node.queue.put(kind, payload, timeout=producer_timeout)
            # wait until the loop has judged enough junk to quarantine
            deadline = time.monotonic() + producer_timeout
            while (not admission.is_quarantined("adv-junk")
                   and not abort.is_set()):
                if time.monotonic() > deadline:
                    _fail(TimeoutError("junk flood never quarantined"))
                    return
                time.sleep(0.01)
            for s in corpus.duplicate_slots:
                if not _wait_clock(s + 1):
                    return
                # a real flooder keeps flooding: three fresh malformed
                # items guarantee re-quarantine before the reserve
                # gossip below is judged, even with ticks interleaving
                # between the puts (3 x 4.0 with up to two slots of
                # decay: 4*0.75^2 + 4*0.75 + 4 = 9.25 >= the 8.0
                # threshold; FIFO orders the charges before the shed
                # check)
                for j in (0, 1, 2):
                    node.queue.put("block", b"\xfe%d@%d" % (j, s),
                                   timeout=producer_timeout)
                if s in by_slot:  # duplicate block re-delivery
                    node.enqueue_block(by_slot[s], timeout=producer_timeout)
                batch = corpus.gossip[s]
                for lo in range(0, len(batch), gossip_batch):
                    node.enqueue_attestations(
                        batch[lo:lo + gossip_batch],
                        timeout=producer_timeout)
                # fresh reserve votes: these are NOT duplicates, so the
                # only thing standing between them and the spec is the
                # quarantine shed
                fresh = corpus.shed_gossip.get(s, ())
                if fresh:
                    node.enqueue_attestations(
                        fresh, timeout=producer_timeout)
        except BaseException as exc:
            _fail(exc)

    producers = [
        threading.Thread(target=chain_driver, name="firehose-chain",
                         daemon=True),
        threading.Thread(target=adv_chain, name="adv-chain", daemon=True),
        threading.Thread(target=adv_junk, name="adv-junk", daemon=True),
    ]
    producers += [
        threading.Thread(target=gossip_producer, args=(i,),
                         name=f"firehose-gossip-{i}", daemon=True)
        for i in range(n_gossip_producers)]

    def closer() -> None:
        for t in producers:
            t.join()
        node.queue.close()

    closer_thread = threading.Thread(target=closer, name="firehose-closer",
                                     daemon=True)
    t0 = time.perf_counter()
    for t in producers:
        t.start()
    closer_thread.start()
    try:
        # the zero-halt contract: this drain completing IS the assert —
        # every poison path below it contains instead of raising
        processed = node.run_apply_loop()
    except BaseException as exc:
        _fail(exc)
        node.queue.close()
        raise
    finally:
        closer_thread.join(timeout=producer_timeout)
        admission.set_orphan_expiry(prev_expiry)
    elapsed = time.perf_counter() - t0
    if errors:
        raise errors[0]

    from . import ingest, service

    snap = assert_bounded()
    n_blocks = len(corpus.chain)
    n_gossip = sum(len(v) for v in corpus.gossip.values())
    return {
        "node": node,
        "elapsed_s": round(elapsed, 3),
        "blocks": n_blocks,
        "gossip_attestations": n_gossip,
        "fork_blocks": len(corpus.fork_blocks),
        "slashings": len(corpus.slashings),
        "blocks_per_s": round(n_blocks / elapsed, 1),
        "atts_per_s": round(n_gossip / elapsed, 1),
        "processed_items": processed,
        "producer_threads": 3 + n_gossip_producers,
        "queue": ingest.snapshot(),
        "service": dict(service.stats),
        "admission": snap,
    }
