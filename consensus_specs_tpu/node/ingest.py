"""Bounded multi-producer ingest queue for the node serving pipeline
(ISSUE 12).

A node serving heavy traffic receives work from many sources at once —
gossip attestation batches, blocks, clock ticks — but fork choice and
the state transition are SINGLE-WRITER by contract (every store/proto
mutation happens on one apply loop; see node/service.py).  This module
is the boundary between the two worlds:

* **multi-producer** — any number of threads call ``put``; the append is
  lock-guarded and strictly FIFO across producers, so causal enqueue
  order (a block enqueued before the votes for it) is preserved as apply
  order;
* **bounded** — the queue holds at most ``cap`` items; a ``put`` into a
  full queue BLOCKS (back-pressure, the production behavior: a node
  sheds load by slowing its gossip readers, not by growing without
  bound).  Blocked puts and the seconds spent blocked are counted;
* **single-consumer** — ``get`` hands items to the apply loop one at a
  time; ``drain`` pulls EVERYTHING admissible in one lock acquisition
  and wakes every blocked producer with a single ``notify_all`` (the
  micro-batcher's entry point — ISSUE 19); ``close`` lets producers
  finish a run (drained queue + closed == end of stream).

Every item carries a timeline causality link allocated at enqueue time:
the producer's ``node/enqueue`` span and the apply loop's ``node/apply``
span share it, so a Perfetto load of the trace shows the producer →
apply-loop handoff as a cross-thread flow arrow (the same mechanism the
stf pipeline uses for host → dispatch-worker edges).

The deque itself (``_items``) is analyzer-registered (CC01 "node ingest
queue"): only this module may mutate it — with one sanctioned exception,
the apply loop's failure re-queue (``requeue_front``), which is also
owner API.  The ``node.enqueue`` fault probe fires BEFORE the append, so
an injected enqueue failure leaves the queue exactly as it was
(tests/chaos/test_node_chaos.py).

Counters are module-wide like the stf/forkchoice engines' (one process
may run several queues; the counters read as node-level activity); the
live depth gauge reads through a weakref to the most recently
constructed queue so the telemetry provider never keeps a dead queue
alive.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import NamedTuple, Optional

from consensus_specs_tpu import faults
from consensus_specs_tpu.telemetry import timeline

DEFAULT_CAP = 1024

# probed BEFORE the deque append: a dying enqueue must leave the queue
# untouched (the producer retries or drops; nothing half-lands)
_SITE_ENQUEUE = faults.site("node.enqueue")

stats = {
    "enqueued": 0,
    "dequeued": 0,
    "requeued": 0,        # items put back at the head by a failed apply
    "requeue_overflow": 0,  # re-queues that found the queue already full
    "requeue_attempts_max": 0,  # deepest per-item retry count observed
    "blocked_puts": 0,    # puts that found the queue full
    "blocked_s": 0.0,     # seconds producers spent in back-pressure waits
    "depth_max": 0,
    "closed": 0,
    "producers": {},      # thread name -> items enqueued
}

_LIVE: Optional[weakref.ref] = None  # most recent queue, for the depth gauge

# guards EVERY mutation of the module-wide stats: queues update under
# their own instance locks, so two live queues (one process may run
# several) would otherwise race the read-modify-writes, and the
# telemetry bus snapshots from arbitrary threads — a dict resize
# mid-copy would raise in the provider
_STATS_LOCK = threading.Lock()


def reset_stats() -> None:
    with _STATS_LOCK:
        for k in stats:
            if isinstance(stats[k], dict):
                stats[k] = {}
            else:
                stats[k] = 0.0 if isinstance(stats[k], float) else 0


class WorkItem(NamedTuple):
    """One unit of ingest work: ``kind`` is ``"tick"`` / ``"block"`` /
    ``"attestations"`` / ``"attester_slashing"``, ``payload`` the
    handler input, ``link`` the timeline causality id minted at enqueue
    (None with the timeline off), ``producer`` the enqueuing thread's
    name (the admission gate's peer-scoring identity — ISSUE 13), and
    ``attempts`` the number of failed applies so far (incremented by
    ``requeue_front``; the apply loop's retry cap consumes it), and
    ``readmit`` marking an item that already passed the admission dedup
    check once (a crash-path re-queue must skip it, or the item's own
    seen-key would judge the retry a duplicate)."""

    kind: str
    payload: object
    link: Optional[int]
    producer: str = ""
    attempts: int = 0
    readmit: bool = False


class IngestQueue:
    """Bounded FIFO work queue: N producers, one apply-loop consumer."""

    def __init__(self, cap: int = DEFAULT_CAP):
        if cap < 1:
            raise ValueError(f"queue cap must be >= 1, got {cap}")
        self._cap = cap
        self._items = collections.deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        global _LIVE
        _LIVE = weakref.ref(self)

    # -- producer side -------------------------------------------------------

    def put(self, kind: str, payload, timeout: Optional[float] = None) -> None:
        """Enqueue one item, blocking while the queue is full
        (back-pressure).  Raises ``RuntimeError`` on a closed queue and
        ``TimeoutError`` when ``timeout`` elapses before space frees —
        a producer must never silently drop work."""
        _SITE_ENQUEUE()
        link = timeline.next_link() if timeline.enabled() else None
        with timeline.span("node/enqueue", link=link, kind=kind):
            with self._not_full:
                if len(self._items) >= self._cap:
                    with _STATS_LOCK:
                        stats["blocked_puts"] += 1
                    t0 = time.perf_counter()
                    deadline = None if timeout is None else t0 + timeout
                    try:
                        while (len(self._items) >= self._cap
                               and not self._closed):
                            remaining = (None if deadline is None
                                         else deadline - time.perf_counter())
                            if remaining is not None and remaining <= 0:
                                raise TimeoutError(
                                    f"ingest queue full (cap {self._cap}) "
                                    f"for {timeout}s")
                            self._not_full.wait(remaining)
                    finally:
                        with _STATS_LOCK:
                            stats["blocked_s"] += time.perf_counter() - t0
                if self._closed:
                    raise RuntimeError("put into a closed ingest queue")
                name = threading.current_thread().name
                self._items.append(WorkItem(kind, payload, link, name))
                depth = len(self._items)
                with _STATS_LOCK:
                    stats["enqueued"] += 1
                    if depth > stats["depth_max"]:
                        stats["depth_max"] = depth
                    stats["producers"][name] = \
                        stats["producers"].get(name, 0) + 1
                self._not_empty.notify()

    def try_put(self, kind: str, payload) -> bool:
        """Non-blocking enqueue: True when the item landed, False when
        the queue sits at cap — the caller turns to useful work
        (admission-side aggregation, node/admission.py) instead of
        blocking, so a False does NOT count as a blocked put.  Raises
        ``RuntimeError`` on a closed queue exactly like ``put``."""
        _SITE_ENQUEUE()
        link = timeline.next_link() if timeline.enabled() else None
        name = threading.current_thread().name
        with self._lock:
            if self._closed:
                raise RuntimeError("put into a closed ingest queue")
            if len(self._items) >= self._cap:
                return False
            self._items.append(WorkItem(kind, payload, link, name))
            depth = len(self._items)
            with _STATS_LOCK:
                stats["enqueued"] += 1
                if depth > stats["depth_max"]:
                    stats["depth_max"] = depth
                stats["producers"][name] = \
                    stats["producers"].get(name, 0) + 1
            self._not_empty.notify()
        if link is not None:
            # the handoff edge for Perfetto: emitted after the lock so
            # the timeline ring is never touched under the queue lock
            with timeline.span("node/enqueue", link=link, kind=kind):
                pass
        return True

    def close(self) -> None:
        """End of stream: no further puts; ``get`` returns None once the
        backlog drains.  Blocked producers wake and see the close."""
        with self._lock:
            self._closed = True
            with _STATS_LOCK:
                stats["closed"] += 1
            self._not_empty.notify_all()
            self._not_full.notify_all()

    # -- consumer side (the single-writer apply loop) ------------------------

    def get(self, timeout: Optional[float] = None) -> Optional[WorkItem]:
        """Dequeue the oldest item, blocking while the queue is empty.
        Returns None when the queue is closed AND drained (end of
        stream), or on timeout."""
        with self._not_empty:
            deadline = (None if timeout is None
                        else time.perf_counter() + timeout)
            while not self._items:
                if self._closed:
                    return None
                remaining = (None if deadline is None
                             else deadline - time.perf_counter())
                if remaining is not None and remaining <= 0:
                    return None
                self._not_empty.wait(remaining)
            item = self._items.popleft()
            with _STATS_LOCK:
                stats["dequeued"] += 1
            self._not_full.notify()
            return item

    def drain(self, timeout: Optional[float] = None,
              max_items: Optional[int] = None):
        """Bulk dequeue: ONE lock acquisition pulls every queued item (up
        to ``max_items``), then wakes EVERY blocked producer with a
        single ``notify_all`` — a batch removal frees many slots, and the
        per-item ``notify`` of ``get`` would leave all but one producer
        sleeping on a queue with room (ISSUE 19 satellite).  Blocks like
        ``get`` while the queue is empty; ``timeout=0`` (or negative) is
        the opportunistic non-blocking probe — the timeout bounds the
        WAIT, never the work, so a zero-timeout drain of a non-empty
        queue still returns the whole backlog.  Returns None when the
        queue is closed AND drained (end of stream) or on timeout, else
        a non-empty list in FIFO order.  ``max_items <= 0`` is a request
        for nothing: ``[]`` immediately, never a wait, never a consume —
        the degenerate bound a caller's batch arithmetic can reach
        (tests/node/test_ingest.py pins it harmless)."""
        if max_items is not None and max_items <= 0:
            return []
        with self._not_empty:
            deadline = (None if timeout is None
                        else time.perf_counter() + timeout)
            while not self._items:
                if self._closed:
                    return None
                remaining = (None if deadline is None
                             else deadline - time.perf_counter())
                if remaining is not None and remaining <= 0:
                    return None
                self._not_empty.wait(remaining)
            if max_items is None or max_items >= len(self._items):
                batch = list(self._items)
                self._items.clear()
            else:
                batch = [self._items.popleft() for _ in range(max_items)]
            with _STATS_LOCK:
                stats["dequeued"] += len(batch)
            self._not_full.notify_all()
            return batch

    def requeue_front(self, item: WorkItem,
                      count_attempt: bool = True) -> WorkItem:
        """Put a failed item back at the HEAD of the queue (apply-loop
        failure contract: the item that broke stays next in line, so a
        retried loop resumes exactly where it stopped — nothing is lost,
        nothing is reordered).  Owner API: only the apply loop calls it,
        for an item it just dequeued plus that item's pending cascade
        followups on a crash — so the momentary cap overshoot is bounded
        by one in-flight item and its followups, and ``requeue_overflow``
        makes every overshoot visible instead of silent (ISSUE 13
        satellite).  With ``count_attempt`` (the failure path) the item
        comes back with ``attempts`` incremented — the count the apply
        loop's retry cap consumes; crash-path re-queues pass False, a
        kill is not a poison signal.  Returns the copy that landed."""
        retried = item._replace(
            attempts=item.attempts + (1 if count_attempt else 0))
        with self._lock:
            if len(self._items) >= self._cap:
                with _STATS_LOCK:
                    stats["requeue_overflow"] += 1
            self._items.appendleft(retried)
            with _STATS_LOCK:
                stats["requeued"] += 1
                if retried.attempts > stats["requeue_attempts_max"]:
                    stats["requeue_attempts_max"] = retried.attempts
            self._not_empty.notify()
        return retried

    # -- introspection -------------------------------------------------------

    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    @property
    def cap(self) -> int:
        return self._cap

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed


def snapshot() -> dict:
    """Queue counters + the live queue's depth gauge (telemetry bus)."""
    with _STATS_LOCK:
        out = {k: (dict(v) if isinstance(v, dict) else v)
               for k, v in stats.items()}
    live = _LIVE() if _LIVE is not None else None
    out["depth"] = live.depth() if live is not None else None
    out["cap"] = live.cap if live is not None else None
    return out
