"""Admission gate: the survival layer between ingest and apply
(ISSUE 13 tentpole; ROADMAP item 4's "adversarial traffic through the
firehose").

PR 12's apply loop assumed a well-behaved mesh: every dequeued item went
straight to a spec handler, an unknown-parent block raised out of
``on_block``, and any non-gossip failure halted the loop.  Production
gossip is not well-behaved — blocks arrive before their parents,
duplicates arrive forever, payloads arrive malformed, and one flooding
peer can starve everyone.  This module classifies every dequeued item
BEFORE the spec sees it:

* **duplicate suppression** — content-root keyed, reusing the PR 12
  dedup lesson (identity keys never fire on wire-decoded objects, so
  keys are content): blocks by ``hash_tree_root(block)`` (the same root
  ``on_block`` stores under, so the hash is computed once and cached on
  the backing node), attester slashings by their tree root, gossip
  batches by a *sketch* key — (first data root, last data root,
  first-attester bits, length).  The sketch is exact for verbatim
  re-delivery (the duplicate-flood shape) and collision-free for honest
  slot-sliced gossip (two batches from one committee differ in their
  first attester's bits); a crafted collision only sheds the crafter's
  own traffic.  Full per-attestation content roots would cost more than
  the duplicate apply they save — ``forkchoice/batch.py`` already
  content-dedups per data inside the batch.  The seen-set is a bounded
  FIFO (``SEEN_CAP``).

* **orphan pool** — an unknown-parent block parks under its parent root
  in a bounded, slot-expiring pool instead of raising out of
  ``on_block``.  When the parent arrives (``pop_children`` after every
  applied block) the orphans re-link and apply in arrival order —
  child-before-parent delivery converges to the same head/root as
  in-order delivery (tier-1 differential).  Orphans whose parent never
  arrives expire once the clock passes their slot by
  ``ORPHAN_EXPIRY_SLOTS`` (their votes would be outside the validity
  window anyway) and the producer is charged.  At ``ORPHAN_CAP`` the
  oldest-slot orphan is shed first (lowest re-link odds).

* **future-slot parking** — a block ahead of the store clock parks
  until a tick advances past its slot (``release_parked``), bounded at
  ``PARKED_CAP``.

* **malformed rejection** — undecodable bytes payloads (SSZ decode via
  the spec types), wrong-shaped objects, and unknown item kinds are
  rejected before any handler runs, charging the producer.

* **peer scoring + quarantine** — every rejection/expiry/duplicate
  charges the enqueuing producer (the thread name the ingest queue
  stamps on each item); scores decay multiplicatively per slot
  (``SCORE_DECAY``) so a peer that stops misbehaving drains back below
  the release threshold.  A producer over ``QUARANTINE_THRESHOLD`` is
  quarantined: its attestation gossip is SHED at admission (the
  cheapest place to shed) until the score decays under
  ``RELEASE_THRESHOLD``.  Blocks, ticks, and slashings are never shed —
  consensus-critical objects must survive a misbehaving relay, and a
  block's validity is its own gate.

* **back-pressure aggregation** — when the bounded ingest queue is full
  the producers used to sleep in ``put`` (37.8 s cumulative at 4
  firehose threads); now ``Node.enqueue_attestations`` routes the
  overflow here instead (ISSUE 19): ``aggregate_gossip`` files the batch
  into a bounded, content-root-grouped staging buffer (``_AGG``, keyed
  by the first attestation's data root so same-data batches sit
  adjacent), and the apply loop's micro-batcher pulls the groups back
  out with ``drain_aggregated`` as ready-to-coalesce runs.  Aggregated
  items never skipped admission — they are judged by ``admit`` like any
  dequeued item when the writer gets to them.  At ``AGG_CAP`` the
  buffer refuses and the producer falls back to the blocking ``put``,
  so back-pressure still bounds total memory.

* **dead-letter ring** — the apply loop's poison-pill containment
  (node/service.py) quarantines an item that keeps failing here: a
  bounded ring of (item kind, producer, error, attempts) records with a
  flight-recorder ``node_quarantine`` event per entry, so the node
  keeps serving and the post-mortem keeps the evidence.

All pools are module-level like the ingest counters (one admission
surface per process; a fresh ``Node`` resets them via ``reset_state``)
and analyzer-registered (CC01 "node orphan pool" / "node dead-letter
ring"): only this module mutates them, and every insert next to the
``node.admission`` / ``node.quarantine`` fault probes is wrapped in a
handler that pops the entry on failure (EF01's transactional-insert
discipline — an injected fault must not strand a half-admitted item).

The ``node.admission`` telemetry provider reports the orphan-pool depth
gauge, parked/expired/quarantined counters, per-producer scores, and
every ring's size against its cap — the soak harness and the
adversarial firehose sample them for the bounded-memory asserts.
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional, Tuple

from consensus_specs_tpu import faults, telemetry
from consensus_specs_tpu.telemetry import recorder

from .ingest import WorkItem

# bounds: every structure this module owns is capped; the caps are on
# the bus so soak/firehose flatness asserts can hold size <= cap
SEEN_CAP = 8192
ORPHAN_CAP = 256
ORPHAN_EXPIRY_SLOTS = 64        # two mainnet epochs: the vote window
PARKED_CAP = 128
DEAD_LETTER_CAP = 64
SCORE_CAP = 256                 # distinct producers tracked
AGG_CAP = 512                   # staged gossip batches during back-pressure

# peer-scoring charge schedule + decay (docs/architecture.md has the
# worked decay table): malformed junk is the strongest signal, a
# duplicate the weakest (honest meshes re-deliver occasionally)
CHARGE_MALFORMED = 4.0
CHARGE_REJECTED = 2.0
CHARGE_QUARANTINED_ITEM = 4.0
CHARGE_EXPIRED = 1.0
CHARGE_DUPLICATE = 0.25
SCORE_DECAY = 0.75              # multiplicative, per slot advanced
QUARANTINE_THRESHOLD = 8.0
RELEASE_THRESHOLD = 2.0

# probed BEFORE any pool/seen-set mutation: an injected admission fault
# leaves every structure exactly as it was and the item unjudged
_SITE_ADMISSION = faults.site("node.admission")
# probed BEFORE the dead-letter append: a dying quarantine must not
# half-record the poison item (the loop re-queues it and retries)
_SITE_QUARANTINE = faults.site("node.quarantine")

VERDICT_ADMIT = "admit"
VERDICT_DUPLICATE = "duplicate"
VERDICT_ORPHANED = "orphaned"
VERDICT_PARKED = "parked"
VERDICT_MALFORMED = "malformed"
VERDICT_STALE = "stale"
VERDICT_SHED = "shed"

_KNOWN_KINDS = ("tick", "block", "attestations", "attester_slashing")

stats = {
    "admitted": 0,
    "duplicates": 0,
    "orphaned": 0,
    "orphans_relinked": 0,
    "orphans_expired": 0,
    "orphans_shed": 0,          # pool at cap: oldest-slot orphan dropped
    "parked": 0,
    "parked_released": 0,
    "parked_shed": 0,
    "malformed": 0,
    "stale_blocks": 0,
    "stale_ticks": 0,           # backwards clock: the rewind attack
    "shed_items": 0,            # quarantined producers' gossip, dropped
    "quarantines": 0,           # producer entered quarantine
    "releases": 0,              # producer left quarantine (decay)
    "dead_lettered": 0,
    "aggregated": 0,            # gossip batches staged during back-pressure
    "agg_flushes": 0,           # drain_aggregated calls that returned work
    "agg_refusals": 0,          # buffer at cap: producer fell back to put
}

# guards stats + every pool below: admission runs on the single-writer
# apply loop, but the telemetry bus snapshots from arbitrary threads
_LOCK = threading.Lock()

_SEEN: "collections.OrderedDict[bytes, bool]" = collections.OrderedDict()
_ORPHANS: Dict[bytes, List[Tuple[int, WorkItem]]] = {}  # parent root -> [(expire_slot, item)]
_ORPHAN_COUNT = 0
_PARKED: List[Tuple[int, WorkItem]] = []                # (slot, item)
_DEAD_LETTERS: collections.deque = collections.deque(maxlen=DEAD_LETTER_CAP)
_SCORES: Dict[str, float] = {}
_QUARANTINED: set = set()
# back-pressure staging: first-data-root -> [WorkItem], insertion-ordered
# so the drain hands same-data batches back ADJACENT (maximal gossip runs
# for the micro-batcher); counted separately because groups hold lists
_AGG: "collections.OrderedDict[bytes, List[WorkItem]]" = \
    collections.OrderedDict()
_AGG_COUNT = 0


def reset_stats() -> None:
    with _LOCK:
        for k in stats:
            stats[k] = 0


def set_orphan_expiry(slots: int) -> int:
    """Re-tune the orphan validity window (owner API: the adversarial
    firehose and the chaos/differential suites shrink it to one epoch so
    expiry is an exercised path, not a theoretical one).  Returns the
    previous value so callers can restore it."""
    global ORPHAN_EXPIRY_SLOTS
    prev = ORPHAN_EXPIRY_SLOTS
    ORPHAN_EXPIRY_SLOTS = max(1, int(slots))
    return prev


def reset_transient() -> None:
    """Drop the seen-set and the orphan/parked pools but KEEP the
    dead-letter ring, peer scores, and quarantine set — the crash
    recovery shape: pooled items were never applied (the mesh
    re-delivers them, and their seen-keys must not suppress that
    re-delivery as 'duplicates'), while the post-mortem evidence and
    the shed protection outlive the crash."""
    global _ORPHAN_COUNT, _AGG_COUNT
    with _LOCK:
        _SEEN.clear()
        _ORPHANS.clear()
        _ORPHAN_COUNT = 0
        del _PARKED[:]
        _AGG.clear()
        _AGG_COUNT = 0


def reset_state() -> None:
    """Drop every pool, the seen-set, and all peer scores (a fresh
    ``Node`` adopting the process-wide admission surface)."""
    global _ORPHAN_COUNT, _AGG_COUNT
    with _LOCK:
        _SEEN.clear()
        _ORPHANS.clear()
        _ORPHAN_COUNT = 0
        del _PARKED[:]
        _DEAD_LETTERS.clear()
        _SCORES.clear()
        _QUARANTINED.clear()
        _AGG.clear()
        _AGG_COUNT = 0


# -- content keys --------------------------------------------------------------


def _block_key(signed_block) -> bytes:
    # the same root on_block stores the block under: the HTR caches on
    # the backing node, so admission pre-pays what the handler needs
    return b"B" + bytes(signed_block.message.hash_tree_root())


def _slashing_key(slashing) -> bytes:
    return b"S" + bytes(slashing.hash_tree_root())


def _gossip_key(batch) -> Optional[bytes]:
    """The batch sketch key (module docstring): exact for verbatim
    re-delivery, cheap enough for 100k-att firehose volume."""
    if not batch:
        return None
    first, last = batch[0], batch[-1]
    return (b"A" + bytes(first.data.hash_tree_root())
            + bytes(last.data.hash_tree_root())
            + bytes(first.aggregation_bits.encode_bytes())
            + len(batch).to_bytes(4, "little"))


def _content_key(item: WorkItem) -> Optional[bytes]:
    try:
        if item.kind == "block":
            return _block_key(item.payload)
        if item.kind == "attestations":
            return _gossip_key(item.payload)
        if item.kind == "attester_slashing":
            return _slashing_key(item.payload)
    except Exception:
        return None
    return None


def _forget_locked(item: WorkItem) -> None:
    key = _content_key(item)
    if key is not None:
        _SEEN.pop(key, None)


def forget(item: WorkItem) -> None:
    """Drop an item's dedup key so a later re-delivery is judged fresh.
    Called whenever admission sheds/expires a pooled item, or the loop
    rejects one on CURRENT store state (an unknown-root gossip batch, a
    not-yet-linkable block): the content may become valid later, and a
    seen-key left behind would make the honest re-delivery die as a
    duplicate — a crafted collision could even front-run honest traffic
    into permanent suppression."""
    with _LOCK:
        _forget_locked(item)


def _seen_before(key: Optional[bytes]) -> bool:
    """Probe-and-insert into the bounded FIFO seen-set.  Caller holds no
    lock; the insert is popped back out if anything below it raises (the
    EF01 discipline: a fault must not strand a half-judged key)."""
    if key is None:
        return False
    with _LOCK:
        if key in _SEEN:
            return True
        try:
            _SEEN[key] = True
            while len(_SEEN) > SEEN_CAP:
                _SEEN.popitem(last=False)
        except BaseException:
            _SEEN.pop(key, None)
            raise
        return False


# -- payload shape / decode ----------------------------------------------------


def _decode_payload(spec, kind: str, payload):
    """(ok, decoded) — bytes payloads SSZ-decode through the spec types
    (the wire shape); object payloads duck-type-check the fields the
    handlers will read.  Anything else is malformed."""
    try:
        if kind == "tick":
            return True, int(payload)
        if kind == "block":
            if isinstance(payload, (bytes, bytearray)):
                payload = spec.SignedBeaconBlock.decode_bytes(bytes(payload))
            m = payload.message
            int(m.slot), bytes(m.parent_root)  # noqa: B018 - shape probe
            # the content key IS the deep shape probe: junk that walks
            # like a block but cannot tree-hash must die HERE as
            # malformed, not raise out of the dedup check into the
            # retry/quarantine machinery (the root caches on the
            # backing node — admit's later use is free)
            _block_key(payload)
            return True, payload
        if kind == "attestations":
            if isinstance(payload, (bytes, bytearray)):
                return False, None  # gossip batches never arrive as one blob
            batch = tuple(payload)
            # the sketch key doubles as the shape probe of the batch
            # ENDS (SSZ field access materializes a child view ~10us, so
            # probing all 512 of a firehose batch would cost more than
            # the apply it guards); junk buried mid-batch still dies
            # safely at spec validation (AssertionError -> rejected)
            _gossip_key(batch)
            return True, batch
        if kind == "attester_slashing":
            if isinstance(payload, (bytes, bytearray)):
                payload = spec.AttesterSlashing.decode_bytes(bytes(payload))
            payload.attestation_1.attesting_indices  # noqa: B018
            payload.attestation_2.attesting_indices  # noqa: B018
            _slashing_key(payload)
            return True, payload
    except Exception:
        return False, None
    return False, None  # unknown kind


# -- peer scoring --------------------------------------------------------------


def _charge_locked(producer: str, points: float) -> None:
    """Charge ``producer`` (caller holds ``_LOCK``).  At ``SCORE_CAP``
    producers the lowest-scoring entry is evicted — the interesting
    peers are the misbehaving ones."""
    if not producer:
        return
    # _SCORES is a running-total accumulator, not a memo: the lookup
    # reads the prior total ON PURPOSE and the insert folds the charge
    # in — CC02's lookup-key coverage model doesn't apply
    score = _SCORES.get(producer, 0.0) + points  # noqa: CC02
    _SCORES[producer] = score
    if len(_SCORES) > SCORE_CAP:
        coldest = min(_SCORES, key=_SCORES.get)
        _SCORES.pop(coldest)
        if coldest in _QUARANTINED:
            # evicting a quarantined producer releases it: count it, or
            # quarantines/releases stop reconciling with the live set
            _QUARANTINED.discard(coldest)
            stats["releases"] += 1
    # the tracked-set membership guard keeps _QUARANTINED a subset of
    # _SCORES (bounded by SCORE_CAP): the eviction above may have just
    # removed THIS producer, and quarantining an untracked name would
    # leave a ghost no decay pass ever visits or releases
    if (score >= QUARANTINE_THRESHOLD and producer in _SCORES
            and producer not in _QUARANTINED):
        _QUARANTINED.add(producer)
        stats["quarantines"] += 1
        if recorder.enabled():
            recorder.record("node_producer_quarantined", producer=producer,
                            score=round(score, 2))


def charge(producer: str, points: float) -> None:
    with _LOCK:
        _charge_locked(producer, points)


def decay_scores(slots_advanced: int) -> None:
    """Multiplicative per-slot decay; producers under the release
    threshold leave quarantine (hysteresis: enter at 8, leave at 2)."""
    if slots_advanced <= 0:
        return
    factor = SCORE_DECAY ** slots_advanced
    with _LOCK:
        for producer in list(_SCORES):
            score = _SCORES[producer] * factor
            if score < 0.01:
                _SCORES.pop(producer)
                score = 0.0
            else:
                _SCORES[producer] = score
            if producer in _QUARANTINED and score < RELEASE_THRESHOLD:
                _QUARANTINED.discard(producer)
                stats["releases"] += 1


def is_quarantined(producer: str) -> bool:
    with _LOCK:
        return producer in _QUARANTINED


# -- the gate ------------------------------------------------------------------


def admit(spec, store, item: WorkItem, current_slot: int,
          readmit: bool = False):
    """Judge one dequeued item.  Returns ``(verdict, item)`` — the item
    comes back with a decoded payload when admission had to decode it.
    Only ``VERDICT_ADMIT`` items may reach the spec handlers; every
    other verdict was counted (and charged) here.  Pool inserts are
    transactional: a fault mid-admission leaves no half-parked entry.
    ``readmit`` marks an item coming back from the orphan pool or the
    parked ring: it is already in the seen-set, so the dedup check is
    skipped (every other check still runs — a released block whose
    parent is STILL unknown goes to the orphan pool, not the spec)."""
    _SITE_ADMISSION()
    kind = item.kind
    if kind not in _KNOWN_KINDS:
        _reject_malformed(item)
        return VERDICT_MALFORMED, item
    ok, decoded = _decode_payload(spec, kind, item.payload)
    if not ok:
        _reject_malformed(item)
        return VERDICT_MALFORMED, item
    if decoded is not item.payload:
        item = item._replace(payload=decoded)

    if kind == "tick":
        # the spec's on_tick trusts the local clock and would REWIND
        # store.time on a smaller value — a backwards tick from a hostile
        # producer must die here (equal is idempotent and allowed)
        if int(item.payload) < int(store.time):
            with _LOCK:
                stats["stale_ticks"] += 1
                _charge_locked(item.producer, CHARGE_REJECTED)
            return VERDICT_STALE, item
        with _LOCK:
            stats["admitted"] += 1
        return VERDICT_ADMIT, item

    if kind == "attestations":
        # the quarantine shed runs BEFORE the dedup insert: a shed batch
        # must not leave a seen-key behind, or an honest re-delivery of
        # the same votes after the producer's release would die as a
        # duplicate (blocks/ticks/slashings are never shed)
        if is_quarantined(item.producer):
            with _LOCK:
                stats["shed_items"] += 1
            return VERDICT_SHED, item
        if not readmit and _seen_before(_gossip_key(item.payload)):
            _count_duplicate(item)
            return VERDICT_DUPLICATE, item
        with _LOCK:
            stats["admitted"] += 1
        return VERDICT_ADMIT, item

    if kind == "attester_slashing":
        if not readmit and _seen_before(_slashing_key(item.payload)):
            _count_duplicate(item)
            return VERDICT_DUPLICATE, item
        with _LOCK:
            stats["admitted"] += 1
        return VERDICT_ADMIT, item

    # blocks: dedup, stale/finality floor, future parking, orphan pool
    block = item.payload.message
    root = bytes(block.hash_tree_root())
    if root in store.blocks or (not readmit
                                and _seen_before(_block_key(item.payload))):
        _count_duplicate(item)
        return VERDICT_DUPLICATE, item
    finalized_slot = int(spec.compute_start_slot_at_epoch(
        store.finalized_checkpoint.epoch))
    if int(block.slot) <= finalized_slot:
        with _LOCK:
            stats["stale_blocks"] += 1
            _charge_locked(item.producer, CHARGE_REJECTED)
        return VERDICT_STALE, item
    if int(block.slot) > current_slot:
        return _park(item, int(block.slot))
    if bytes(block.parent_root) not in store.block_states:
        return _pool_orphan(item, int(block.slot), bytes(block.parent_root),
                            current_slot)
    with _LOCK:
        stats["admitted"] += 1
    return VERDICT_ADMIT, item


def _reject_malformed(item: WorkItem) -> None:
    with _LOCK:
        stats["malformed"] += 1
        _charge_locked(item.producer, CHARGE_MALFORMED)
    if recorder.enabled():
        recorder.record("node_malformed", item_kind=str(item.kind)[:32],
                        producer=item.producer)


def _count_duplicate(item: WorkItem) -> None:
    with _LOCK:
        stats["duplicates"] += 1
        _charge_locked(item.producer, CHARGE_DUPLICATE)


def _park(item: WorkItem, slot: int):
    """Future-slot parking, bounded: at cap the FARTHEST-future block is
    shed (least likely to matter before shutdown) — charging THAT
    block's producer and forgetting its dedup key so a re-delivery
    nearer its slot gets judged fresh."""
    with _LOCK:
        try:
            _PARKED.append((slot, item))
            _PARKED.sort(key=lambda e: e[0])
            if len(_PARKED) > PARKED_CAP:
                _shed_slot, shed = _PARKED.pop()
                stats["parked_shed"] += 1
                _charge_locked(shed.producer, CHARGE_EXPIRED)
                _forget_locked(shed)
                if shed is item:
                    # the newcomer WAS the farthest-future entry: it
                    # never parked — telling the caller PARKED would
                    # claim a block is waiting that is already gone
                    return VERDICT_STALE, item
            stats["parked"] += 1
        except BaseException:
            _PARKED[:] = [e for e in _PARKED if e[1] is not item]
            raise
    return VERDICT_PARKED, item


def _pool_orphan(item: WorkItem, slot: int, parent: bytes,
                 current_slot: int):
    global _ORPHAN_COUNT
    # expiry is SLOT-relative, not arrival-relative: the window models
    # the vote-validity horizon of the block's own slot, so an orphan
    # that was already ancient when it arrived expires at the next
    # housekeeping tick instead of camping a fresh window
    expire_at = slot + ORPHAN_EXPIRY_SLOTS
    if expire_at <= current_slot:
        # already past its window on arrival: expire NOW instead of
        # pooling an entry no later housekeeping may ever visit (the
        # clock only advances on ticks; after the last one, a pooled
        # corpse would sit out the shutdown uncounted)
        with _LOCK:
            stats["orphans_expired"] += 1
            _charge_locked(item.producer, CHARGE_EXPIRED)
            _forget_locked(item)
        return VERDICT_STALE, item
    with _LOCK:
        try:
            _ORPHANS.setdefault(parent, []).append((expire_at, item))
            _ORPHAN_COUNT += 1
            stats["orphaned"] += 1
        except BaseException:
            # surgical rollback: only THIS item leaves; pooled siblings
            # under the same parent keep their entries and their count
            bucket = _ORPHANS.get(parent)
            if bucket is not None:
                bucket[:] = [e for e in bucket if e[1] is not item]
                if not bucket:
                    _ORPHANS.pop(parent, None)
            raise
        if _ORPHAN_COUNT > ORPHAN_CAP:
            _shed_oldest_orphan_locked()
    return VERDICT_ORPHANED, item


def _shed_oldest_orphan_locked() -> None:
    global _ORPHAN_COUNT
    oldest_parent, oldest_i, oldest_slot = None, -1, None
    for parent, entries in _ORPHANS.items():
        for i, (_expire, it) in enumerate(entries):
            s = int(it.payload.message.slot)
            if oldest_slot is None or s < oldest_slot:
                oldest_parent, oldest_i, oldest_slot = parent, i, s
    if oldest_parent is None:
        return
    entries = _ORPHANS[oldest_parent]
    _expire, shed = entries.pop(oldest_i)
    if not entries:
        _ORPHANS.pop(oldest_parent)
    _ORPHAN_COUNT -= 1
    stats["orphans_shed"] += 1
    _charge_locked(shed.producer, CHARGE_EXPIRED)
    _forget_locked(shed)  # a re-delivery after the parent links is fresh


def pop_children(parent_root: bytes) -> List[WorkItem]:
    """Orphans waiting on a just-applied block, in arrival order — the
    re-link path.  The caller (the apply loop) re-admits each."""
    global _ORPHAN_COUNT
    with _LOCK:
        entries = _ORPHANS.pop(bytes(parent_root), None)
        if not entries:
            return []
        _ORPHAN_COUNT -= len(entries)
        stats["orphans_relinked"] += len(entries)
    return [item for _expire, item in entries]


def release_parked(current_slot: int) -> List[WorkItem]:
    """Parked blocks whose slot the clock has reached, in slot order."""
    with _LOCK:
        due = [item for slot, item in _PARKED if slot <= current_slot]
        if due:
            _PARKED[:] = [e for e in _PARKED if e[0] > current_slot]
            stats["parked_released"] += len(due)
    return due


def expire_orphans(current_slot: int) -> int:
    """Drop orphans whose expiry slot has passed, charging producers.
    Returns the number expired."""
    global _ORPHAN_COUNT
    expired = 0
    with _LOCK:
        for parent in list(_ORPHANS):
            keep = []
            for expire_at, item in _ORPHANS[parent]:
                if expire_at <= current_slot:
                    expired += 1
                    _charge_locked(item.producer, CHARGE_EXPIRED)
                    # the block may still become linkable (expiry is a
                    # vote-window heuristic): a later honest re-delivery
                    # must be judged fresh, not a duplicate
                    _forget_locked(item)
                else:
                    keep.append((expire_at, item))
            if keep:
                _ORPHANS[parent] = keep
            else:
                _ORPHANS.pop(parent)
        _ORPHAN_COUNT -= expired
        stats["orphans_expired"] += expired
    return expired


def on_clock(current_slot: int, slots_advanced: int) -> List[WorkItem]:
    """The per-tick admission housekeeping bundle: decay scores, expire
    orphans, release due parked blocks (returned for re-admission)."""
    decay_scores(slots_advanced)
    expire_orphans(current_slot)
    return release_parked(current_slot)


# -- back-pressure aggregation (ISSUE 19) --------------------------------------


def aggregate_gossip(payload, producer: str,
                     link: Optional[int] = None) -> bool:
    """Stage a gossip batch a full ingest queue refused (``try_put``
    returned False): filed under the batch's first attestation-data root
    so same-data batches come back out adjacent — ready-made gossip runs
    for the micro-batcher.  Returns False (producer falls back to the
    blocking ``put``) at ``AGG_CAP`` or for a batch whose first entry
    cannot tree-hash (junk routes through normal admission so it is
    charged, never silently staged)."""
    global _AGG_COUNT
    try:
        key = bytes(payload[0].data.hash_tree_root())
    except Exception:
        return False
    item = WorkItem("attestations", payload, link, producer)
    with _LOCK:
        if producer in _QUARANTINED:
            # a quarantined peer's gossip must meet the shed check in
            # FIFO order with the charges that quarantined it — staging
            # would delay the judgment past the decay window
            return False
        if _AGG_COUNT >= AGG_CAP:
            stats["agg_refusals"] += 1
            return False
        try:
            _AGG.setdefault(key, []).append(item)
            _AGG_COUNT += 1
            stats["aggregated"] += 1
        except BaseException:
            bucket = _AGG.get(key)
            if bucket is not None:
                bucket[:] = [e for e in bucket if e is not item]
                if not bucket:
                    _AGG.pop(key, None)
            raise
    return True


def drain_aggregated(max_items: Optional[int] = None) -> List[WorkItem]:
    """Hand staged batches to the apply loop, group by group in staging
    order (items inside a group keep arrival order).  The items were
    never judged — the micro-batcher routes each through ``admit`` like
    any dequeued work.  A ``max_items`` bound may split a group; the
    remainder stays staged at the front."""
    global _AGG_COUNT
    out: List[WorkItem] = []
    with _LOCK:
        while _AGG and (max_items is None or len(out) < max_items):
            key, bucket = next(iter(_AGG.items()))
            room = None if max_items is None else max_items - len(out)
            if room is None or len(bucket) <= room:
                out.extend(bucket)
                _AGG.pop(key)
            else:
                out.extend(bucket[:room])
                bucket[:] = bucket[room:]
        # one recount beats per-branch bookkeeping under the lock
        _AGG_COUNT = sum(len(b) for b in _AGG.values())
        if out:
            stats["agg_flushes"] += 1
    return out


def aggregation_depth() -> int:
    with _LOCK:
        return _AGG_COUNT


# -- dead-letter ring ----------------------------------------------------------


def dead_letter(item: WorkItem, error: BaseException) -> dict:
    """Quarantine a poison item: the apply loop exhausted its retry cap
    and the node keeps serving.  Appends a bounded dead-letter record,
    charges the producer, and emits the ``node_quarantine`` event —
    AFTER the append settled (OB01's commit discipline)."""
    _SITE_QUARANTINE()
    record = {
        "item_kind": item.kind,
        "producer": item.producer,
        "attempts": int(item.attempts) + 1,
        "error": repr(error)[:200],
        "summary": _item_summary(item),
    }
    with _LOCK:
        try:
            _DEAD_LETTERS.append(record)
            stats["dead_lettered"] += 1
        except BaseException:
            if _DEAD_LETTERS and _DEAD_LETTERS[-1] is record:
                _DEAD_LETTERS.pop()
            raise
        _charge_locked(item.producer, CHARGE_QUARANTINED_ITEM)
    if recorder.enabled():
        try:
            recorder.record("node_quarantine", **record)
        except BaseException:
            # never half-record: a dying event emission rolls the ring
            # entry back out, or the caller's retry would dead-letter
            # the same poison item twice
            with _LOCK:
                if _DEAD_LETTERS and _DEAD_LETTERS[-1] is record:
                    _DEAD_LETTERS.pop()
                    stats["dead_lettered"] -= 1
            raise
    return record


def _item_summary(item: WorkItem) -> str:
    try:
        if item.kind == "block":
            return f"slot={int(item.payload.message.slot)}"
        if item.kind == "attestations":
            return f"n={len(item.payload)}"
        if item.kind == "tick":
            return f"time={int(item.payload)}"
    except Exception:
        pass
    return ""


def dead_letters() -> List[dict]:
    with _LOCK:
        return [dict(r) for r in _DEAD_LETTERS]


# -- telemetry -----------------------------------------------------------------


def snapshot() -> dict:
    """The ``node.admission`` bus subtree: counters, the orphan-pool
    depth gauge, per-producer scores, and size/cap for every bounded
    structure (the soak + firehose flatness sample)."""
    with _LOCK:
        return {
            **stats,
            "orphan_pool_depth": _ORPHAN_COUNT,
            "orphan_pool_cap": ORPHAN_CAP,
            "parked_depth": len(_PARKED),
            "parked_cap": PARKED_CAP,
            "dead_letter_depth": len(_DEAD_LETTERS),
            "dead_letter_cap": DEAD_LETTER_CAP,
            "seen_size": len(_SEEN),
            "seen_cap": SEEN_CAP,
            "scores_size": len(_SCORES),
            "scores_cap": SCORE_CAP,
            "agg_depth": _AGG_COUNT,
            "agg_cap": AGG_CAP,
            "producer_scores": {p: round(s, 3)
                                for p, s in sorted(_SCORES.items())},
            "quarantined_producers": sorted(_QUARANTINED),
        }


telemetry.register_provider("node.admission", snapshot, replace=True)
