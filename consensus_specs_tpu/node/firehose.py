"""Concurrent gossip firehose: production-shaped load for the node
(ISSUE 12 tentpole, part 3; ROADMAP item 1's "millions of users" leg).

The differential suites prove the node is CORRECT one handler call at a
time; this module proves it serves: N epochs of blocks interleaved with
≥100k-attestation gossip batches, enqueued by concurrent producer
threads against the bounded ingest queue while the single-writer apply
loop drains — then the node's end state is replayed item-for-item
through the literal spec handlers and head/root parity is asserted
byte-exactly.  Because the parity leg replays the node's own apply
JOURNAL, the assertion is meaningful under nondeterministic producer
interleaving: whatever order the queue settled on, the spec agrees on
the resulting head.

Shape of a run (``run_firehose``):

* **one chain driver** enqueues (tick, block) pairs in chain order.  It
  fences at epoch boundaries: the tick entering epoch E waits until all
  gossip for epochs ≤ E-2 is enqueued — FIFO then guarantees those
  votes apply before their target epochs age out of the spec's
  current/previous-epoch window, exactly the pacing a live node's
  gossip mesh exhibits;
* **K gossip producers** split the gossip corpus by slot; each waits
  for the apply loop's clock to pass its slot (``Node.wait_for_clock``
  — votes must be mature on arrival) and enqueues that slot's
  attestations in batches.  Back-pressure from the bounded queue is the
  flow control;
* **the caller's thread runs the apply loop** — it IS the single
  writer; a closer thread joins the producers and closes the queue so
  the loop's drain terminates.

The corpus builder (``build_corpus``) is seeded and deterministic: full
blocks (each carrying the previous slot's committees as aggregate
attestations, so justification/finalization advance and the fork-choice
prune path runs mid-firehose) plus per-slot single-attester gossip
votes for the block at that slot — the unaggregated shape a node
serving heavy traffic sees.  Construction runs with BLS off and the
harness measures orchestration throughput BLS-off (pairing cost is
gated by the e2e bench rows; what the firehose gates is the composition
— stf fast path engaged per block, batched fork-choice ingest, queue
discipline under concurrency).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, NamedTuple, Optional

from consensus_specs_tpu.testing.helpers.attestations import (
    build_attestation_data,
)

from .service import Node, default_anchor_block


class FirehoseCorpus(NamedTuple):
    """A prepared firehose workload: the anchor, the signed chain, and
    the per-slot gossip votes."""

    anchor_block: object
    chain: List[object]              # signed blocks, chain order
    gossip: Dict[int, List[object]]  # slot -> single-attester attestations


def prepare_anchor(spec, state) -> None:
    """Give a synthetic state a genesis-style ``latest_block_header`` (in
    place) so ``default_anchor_block`` hashes to the children's parent
    root — the same trick bench.py's fork-choice ingest inputs use."""
    state.latest_block_header = spec.BeaconBlockHeader(
        slot=state.slot,
        body_root=spec.hash_tree_root(spec.BeaconBlockBody()))


def _gossip_for_slot(spec, state, slot, block_root, quota) -> list:
    """Up to ``quota`` single-attester attestations voting ``block_root``
    at ``slot``, spread across the slot's committees.  ``state`` is the
    block's post-state (slot == state.slot), so the helper's
    slot-==-state.slot path would rebuild the head root — we already
    hold it."""
    epoch = spec.compute_epoch_at_slot(slot)
    current_start = spec.compute_start_slot_at_epoch(epoch)
    if slot == current_start:
        target_root = block_root
    else:
        target_root = spec.get_block_root(state, epoch)
    source = state.current_justified_checkpoint
    out = []
    committees = int(spec.get_committee_count_per_slot(state, epoch))
    while len(out) < quota:
        made_any = False
        for index in range(committees):
            committee = spec.get_beacon_committee(state, slot, index)
            size = len(committee)
            data = spec.AttestationData(
                slot=slot, index=index, beacon_block_root=block_root,
                source=spec.Checkpoint(epoch=source.epoch, root=source.root),
                target=spec.Checkpoint(epoch=epoch, root=target_root))
            for member in range(size):
                bits = [False] * size
                bits[member] = True
                out.append(spec.Attestation(
                    aggregation_bits=bits, data=data))
                made_any = True
                if len(out) >= quota:
                    return out
        if not made_any:  # empty committees: nothing to vote with
            return out
    return out


def build_corpus(spec, anchor_state, n_epochs: int = 2,
                 gossip_target: int = 100_000) -> FirehoseCorpus:
    """Deterministic chain + gossip over ``anchor_state``: ``n_epochs``
    of full blocks (aggregate attestations of the preceding slot's
    committees, capped at MAX_ATTESTATIONS) and ~``gossip_target``
    single-attester votes spread evenly over the slots.  Built with BLS
    off (the firehose measures orchestration, not pairing)."""
    from consensus_specs_tpu.crypto import bls

    anchor_block = default_anchor_block(spec, anchor_state)
    n_slots = n_epochs * int(spec.SLOTS_PER_EPOCH)
    per_slot = max(1, -(-gossip_target // n_slots))  # ceil division
    was_active = bls.bls_active
    bls.bls_active = False
    try:
        build_st = anchor_state.copy()
        chain, gossip = [], {}
        first_slot = int(build_st.slot) + 1
        for slot in range(first_slot, first_slot + n_slots):
            stub = build_st.copy()
            spec.process_slots(stub, slot)
            block = spec.BeaconBlock(
                slot=slot,
                proposer_index=spec.get_beacon_proposer_index(stub))
            # an honest eth1 vote (the helper-block shape): a winning
            # empty vote at the voting-period boundary would reset
            # eth1_data under the deposit-count check and underflow it
            block.body.eth1_data.deposit_count = stub.eth1_deposit_index
            header = build_st.latest_block_header.copy()
            if header.state_root == spec.Root():
                header.state_root = build_st.hash_tree_root()
            block.parent_root = header.hash_tree_root()
            att_slot = slot - 1
            if att_slot >= first_slot:
                # previous slot's committees, full participation: the
                # realistic block payload that moves justification
                epoch = spec.compute_epoch_at_slot(att_slot)
                for index in range(int(
                        spec.get_committee_count_per_slot(stub, epoch))):
                    if len(block.body.attestations) >= int(
                            spec.MAX_ATTESTATIONS):
                        break
                    committee = spec.get_beacon_committee(
                        stub, att_slot, index)
                    block.body.attestations.append(spec.Attestation(
                        aggregation_bits=[True] * len(committee),
                        data=build_attestation_data(
                            spec, stub, att_slot, index)))
            spec.process_slots(build_st, slot)
            spec.process_block(build_st, block)
            block.state_root = build_st.hash_tree_root()
            signed = spec.SignedBeaconBlock(message=block)
            chain.append(signed)
            gossip[slot] = _gossip_for_slot(
                spec, build_st, slot, block.hash_tree_root(), per_slot)
        return FirehoseCorpus(anchor_block, chain, gossip)
    finally:
        bls.bls_active = was_active


def replay_journal_literal(spec, anchor_state, anchor_block, journal):
    """The parity leg: replay a node's apply journal item-for-item
    through the literal spec handlers on a fresh store.  Returns the
    replayed store."""
    ref = spec.get_forkchoice_store(anchor_state, anchor_block)
    for kind, payload in journal:
        if kind == "tick":
            spec.on_tick(ref, payload)
        elif kind == "block":
            spec.on_block(ref, payload)
        elif kind == "attestations":
            for att in payload:
                spec.on_attestation(ref, att, is_from_block=False)
        elif kind == "attester_slashing":
            spec.on_attester_slashing(ref, payload)
        else:
            raise ValueError(f"unknown journal kind {kind!r}")
    return ref


def assert_parity(spec, node: Node, ref) -> dict:
    """Byte-exact end-state parity between the node and a literal store:
    head root, the head block's state root, checkpoints, and the full
    latest-message map.  Returns the compared roots (for bench rows)."""
    # the spec materializes the justified checkpoint state lazily;
    # materialize it its own way before the literal walk
    spec.store_target_checkpoint_state(ref, ref.justified_checkpoint)
    head_node = bytes(node.get_head())
    head_ref = bytes(spec.get_head(ref))
    assert head_node == head_ref, \
        f"node head {head_node.hex()} != literal spec {head_ref.hex()}"
    state_root_node = bytes(
        node.store.block_states[head_node].hash_tree_root())
    state_root_ref = bytes(ref.block_states[head_ref].hash_tree_root())
    assert state_root_node == state_root_ref, \
        "head state root diverged from the literal spec replay"
    assert node.store.justified_checkpoint == ref.justified_checkpoint
    assert node.store.finalized_checkpoint == ref.finalized_checkpoint
    assert dict(node.store.latest_messages) == dict(ref.latest_messages), \
        "latest messages diverged from the sequential spec fold"
    return {"head_root": "0x" + head_node.hex(),
            "head_state_root": "0x" + state_root_node.hex()}


def run_firehose(spec, anchor_state, corpus: FirehoseCorpus,
                 n_gossip_producers: int = 3, queue_cap: int = 64,
                 gossip_batch: int = 512,
                 producer_timeout: float = 300.0, on_node=None,
                 **node_kwargs) -> dict:
    """Serve ``corpus`` through a fresh ``Node`` under concurrent load:
    1 chain driver + ``n_gossip_producers`` gossip threads enqueue, the
    calling thread runs the single-writer apply loop.  Extra keyword
    arguments reach the ``Node`` constructor (``checkpoint_store=...``
    runs the firehose with durable checkpointing — the recovery bench's
    shape).  Returns the throughput/behavior row (the caller owns stats
    resets and the parity leg — see bench.py / tests/node/)."""
    spe = int(spec.SLOTS_PER_EPOCH)
    genesis_time = int(anchor_state.genesis_time)
    sps = int(spec.config.SECONDS_PER_SLOT)
    node = Node(spec, anchor_state, corpus.anchor_block,
                queue_cap=queue_cap, **node_kwargs)
    if on_node is not None:
        # observer hook, invoked before any producer starts: the
        # query-load harness attaches its reader threads here
        on_node(node)

    slots = sorted(corpus.gossip)
    remaining_by_epoch: Dict[int, int] = {}
    for s in slots:
        e = s // spe
        remaining_by_epoch[e] = remaining_by_epoch.get(e, 0) + 1
    fence = threading.Condition()
    abort = threading.Event()
    errors: List[BaseException] = []

    def _fail(exc: BaseException) -> None:
        errors.append(exc)
        abort.set()
        with fence:
            fence.notify_all()

    def _wait_clock(slot: int) -> bool:
        deadline = time.monotonic() + producer_timeout
        while not abort.is_set():
            if node.wait_for_clock(slot, timeout=0.5):
                return True
            if time.monotonic() > deadline:
                _fail(TimeoutError(
                    f"producer starved waiting for clock slot {slot}"))
                return False
        return False

    def gossip_producer(i: int) -> None:
        try:
            for s in slots[i::n_gossip_producers]:
                # votes must be mature on arrival: wait until the apply
                # loop's clock passed the attested slot
                if not _wait_clock(s + 1):
                    return
                batch = corpus.gossip[s]
                for lo in range(0, len(batch), gossip_batch):
                    node.enqueue_attestations(
                        batch[lo:lo + gossip_batch],
                        timeout=producer_timeout)
                with fence:
                    remaining_by_epoch[s // spe] -= 1
                    fence.notify_all()
        except BaseException as exc:
            _fail(exc)

    def chain_driver() -> None:
        try:
            seen_epoch: Optional[int] = None
            for signed in corpus.chain:
                s = int(signed.message.slot)
                e = s // spe
                if e != seen_epoch:
                    # entering epoch e: every older epoch's gossip must
                    # be enqueued before the clock can age its targets
                    # out of the current/previous validity window
                    with fence:
                        fence.wait_for(lambda: abort.is_set() or not any(
                            n > 0 for ep, n in remaining_by_epoch.items()
                            if ep <= e - 2))
                    if abort.is_set():
                        return
                    seen_epoch = e
                node.enqueue_tick(genesis_time + s * sps,
                                  timeout=producer_timeout)
                node.enqueue_block(signed, timeout=producer_timeout)
            # final tick: the last slot's gossip matures
            last = int(corpus.chain[-1].message.slot)
            node.enqueue_tick(genesis_time + (last + 1) * sps,
                              timeout=producer_timeout)
        except BaseException as exc:
            _fail(exc)

    producers = [threading.Thread(target=chain_driver,
                                  name="firehose-chain", daemon=True)]
    producers += [
        threading.Thread(target=gossip_producer, args=(i,),
                         name=f"firehose-gossip-{i}", daemon=True)
        for i in range(n_gossip_producers)]

    def closer() -> None:
        for t in producers:
            t.join()
        node.queue.close()

    closer_thread = threading.Thread(target=closer, name="firehose-closer",
                                     daemon=True)
    t0 = time.perf_counter()
    for t in producers:
        t.start()
    closer_thread.start()
    try:
        applied = node.run_apply_loop()
    except BaseException as exc:
        _fail(exc)
        node.queue.close()
        raise
    finally:
        closer_thread.join(timeout=producer_timeout)
    elapsed = time.perf_counter() - t0
    if errors:
        raise errors[0]

    n_blocks = len(corpus.chain)
    n_gossip = sum(len(v) for v in corpus.gossip.values())
    from . import ingest, service

    return {
        "node": node,
        "elapsed_s": round(elapsed, 3),
        "blocks": n_blocks,
        "gossip_attestations": n_gossip,
        "blocks_per_s": round(n_blocks / elapsed, 1),
        "atts_per_s": round(n_gossip / elapsed, 1),
        "applied_items": applied,
        "producer_threads": 1 + n_gossip_producers,
        "queue": ingest.snapshot(),
        "service": dict(service.stats),
    }
