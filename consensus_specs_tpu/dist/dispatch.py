"""Chunk dispatch over the fabric (ISSUE 20): deterministic assignment,
deadline/retry/hedge re-dispatch, and the degradation ladder.

``run_tasks`` drives one batch of task chunks to completion:

* **initial assignment** is deterministic round-robin over the live
  workers (chunk i -> worker i mod W) — under zero faults every run of
  the same batch lands the same chunks on the same processes;
* **re-dispatch**: a dead worker (EOF, corrupt frame, heartbeat
  timeout), an ``ok=False`` reply, or a blown per-task deadline sends the
  chunk to a surviving worker with exponential deadline backoff
  (``deadline_s * 2**(attempts-1)``) — counted in ``redispatched_chunks``;
* **hedging**: a chunk in flight past ``hedge_s`` gets ONE duplicate
  dispatch to a second worker (counted in ``hedged_tasks``, NOT in
  ``redispatched_chunks`` — a hedge is a latency bet, not a failure);
  first valid reply wins, late copies are discarded by task id
  (``duplicate_replies``);
* **give-up**: a chunk past ``max_attempts``, or zero surviving workers,
  raises ``FabricDown`` — which the ``FabricExecutor`` ladder catches.

Because every workload merges its chunks in FIXED chunk-index order
(dist/workloads.py), re-dispatch and hedging cannot change the result:
verdicts and roots are bit-identical to the in-process twin at every
failure schedule, and the chaos suite asserts exactly that.

``FabricExecutor`` is the degradation ladder, mirroring
``stf/verify.py``/``stf/engine.py``: a fabric failure falls back to the
caller's in-process twin (serving never halts), ``BREAKER_THRESHOLD``
consecutive failures open a breaker that demotes subsequent runs
straight to in-process, and every ``BREAKER_PROBE_INTERVAL``-th demoted
run probes the fabric again (respawning dead workers first) — a
successful probe closes the breaker and the fabric takes back over.
"""
from __future__ import annotations

import threading
import time
import warnings
from typing import Callable, Dict, List, NamedTuple, Optional, Set, Tuple

from consensus_specs_tpu import faults, telemetry, tracing
from consensus_specs_tpu.dist.fabric import (
    Event,
    Fabric,
    FabricUnavailable,
    WorkerHandle,
)
from consensus_specs_tpu.telemetry import recorder

# the coordinator-side dispatch seam: probed before each task send, so an
# injected error models the coordinator losing a worker's channel at the
# moment of assignment
_SITE_DISPATCH = faults.site("dist.dispatch")

# same shape as stf/engine.py's fast-path breaker: N consecutive fabric
# failures demote to in-process, every INTERVAL-th demoted run is a
# recovery probe
BREAKER_THRESHOLD = 3
BREAKER_PROBE_INTERVAL = 8

stats = {
    "tasks": 0,
    "dispatched": 0,
    "replies": 0,
    "duplicate_replies": 0,
    "redispatched_chunks": 0,
    "hedged_tasks": 0,
    "deadline_timeouts": 0,
    "heartbeat_timeouts": 0,
    "worker_losses": 0,
    "error_replies": 0,
    "fabric_runs": 0,
    "fallback_runs": 0,
    "breaker_trips": 0,
    "breaker_probes": 0,
    "recoveries": 0,
    "breaker_state": "closed",
}

# dispatch counters are read by the telemetry bus from arbitrary threads
# while the event loop mutates them
_STATS_LOCK = threading.Lock()

_RUN_SEQ = [0]  # task-id nonce: a straggler reply from a finished run
#                 must never collide with the next run's ids


def reset_stats() -> None:
    with _STATS_LOCK:
        for k, v in stats.items():
            if isinstance(v, int):
                stats[k] = 0
        stats["breaker_state"] = "closed"


def _bump(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        stats[key] += n


class FabricDown(RuntimeError):
    """The batch cannot complete on the fabric (no survivors, or a chunk
    exhausted ``max_attempts``): the executor's ladder demotes the run to
    the in-process twin."""


class TaskSpec(NamedTuple):
    """One chunk: ``kind`` routes to a worker handler, ``meta`` is small
    JSON routing state, ``body`` the bulk payload."""

    kind: str
    meta: dict
    body: bytes


class _Pending:
    """In-flight bookkeeping for one task chunk."""

    __slots__ = ("id", "index", "spec", "attempts", "sent_at", "deadline",
                 "hedged", "workers")

    def __init__(self, task_id: str, index: int, spec: TaskSpec):
        self.id = task_id
        self.index = index
        self.spec = spec
        self.attempts = 0
        self.sent_at = 0.0
        self.deadline = float("inf")
        self.hedged = False
        self.workers: Set[str] = set()  # procs holding a live copy


class _DispatchRun:
    """One ``run_tasks`` batch: the in-flight table + event loop."""

    def __init__(self, fabric: Fabric, tasks: List[TaskSpec],
                 deadline_s: float, hedge_s: Optional[float],
                 max_attempts: int, heartbeat_timeout_s: Optional[float]):
        _RUN_SEQ[0] += 1
        self.fabric = fabric
        self.deadline_s = deadline_s
        self.hedge_s = hedge_s
        self.max_attempts = max_attempts
        self.heartbeat_timeout_s = heartbeat_timeout_s
        # the in-flight task table: id -> _Pending, the structure every
        # re-dispatch decision routes through (declared in the
        # concurrency registry; single-threaded by construction — only
        # the dispatch loop touches it, reader threads communicate
        # through the fabric event queue)
        self._inflight: Dict[str, _Pending] = {
            f"r{_RUN_SEQ[0]}.t{i}": _Pending(f"r{_RUN_SEQ[0]}.t{i}", i, t)
            for i, t in enumerate(tasks)}
        self._results: Dict[int, Tuple[dict, bytes]] = {}
        self._done: Set[str] = set()
        self._n = len(tasks)
        self._rr = 0  # round-robin cursor for re-dispatch targets

    # -- worker selection ----------------------------------------------------

    def _pick_worker(self, exclude: Set[str]) -> WorkerHandle:
        alive = self.fabric.alive_workers()
        if not alive:
            raise FabricDown("no surviving workers")
        pool = [w for w in alive if w.name not in exclude] or alive
        self._rr += 1
        return pool[self._rr % len(pool)]

    # -- sending -------------------------------------------------------------

    def _send(self, pending: _Pending, worker: WorkerHandle) -> None:
        """Dispatch one copy of a chunk.  A send failure (injected or
        real) loses the WORKER, not the chunk: the loss event re-routes
        everything that worker held, this chunk included."""
        pending.attempts += 1
        pending.sent_at = time.monotonic()
        pending.deadline = (pending.sent_at
                            + self.deadline_s * 2 ** (pending.attempts - 1))
        pending.workers.add(worker.name)
        meta = dict(pending.spec.meta, id=pending.id, kind=pending.spec.kind)
        try:
            _SITE_DISPATCH()
            worker.send("task", meta, pending.spec.body)
        except (faults.InjectedFault, FabricUnavailable):
            self.fabric.mark_lost(worker, "dispatch-failure")
            return
        _bump("dispatched")

    def _redispatch(self, pending: _Pending, reason: str,
                    exclude: Set[str]) -> None:
        if pending.attempts >= self.max_attempts:
            raise FabricDown(
                f"chunk {pending.index} exhausted {self.max_attempts} "
                f"attempts (last: {reason})")
        _bump("redispatched_chunks")
        tracing.count("dist.redispatched_chunk")
        recorder.record("dist_redispatch", index=pending.index,
                        reason=reason, attempt=pending.attempts + 1)
        self._send(pending, self._pick_worker(exclude))

    # -- event handling ------------------------------------------------------

    def _on_reply(self, ev: Event) -> None:
        task_id = ev.meta.get("id")
        pending = self._inflight.get(task_id)
        if pending is None or task_id in self._done:
            # a late copy of an already-settled chunk (hedge loser, or a
            # straggler from a previous run): first valid reply won
            _bump("duplicate_replies")
            return
        if not ev.meta.get("ok"):
            _bump("error_replies")
            pending.workers.discard(ev.proc)
            if not pending.workers:
                self._redispatch(pending, "error-reply", {ev.proc})
            return
        _bump("replies")
        self._results[pending.index] = (ev.meta, ev.body)
        self._done.add(task_id)
        del self._inflight[task_id]
        w = self.fabric.worker(ev.proc)
        if w is not None:
            w.tasks_done += 1

    def _on_lost(self, ev: Event) -> None:
        """A worker died: every chunk whose ONLY live copy it held goes
        back out to a survivor."""
        w = self.fabric.worker(ev.proc)
        if w is not None and w in self.fabric.alive_workers():
            # a stale loss event from a retired incarnation (the worker
            # has since respawned): mark_lost orders alive=False before
            # the event, so alive-now proves the event predates this run
            return
        _bump("worker_losses")
        for pending in list(self._inflight.values()):
            if ev.proc in pending.workers:
                pending.workers.discard(ev.proc)
                if not pending.workers:
                    self._redispatch(pending, f"worker-lost:{ev.meta.get('reason')}",
                                     {ev.proc})

    # -- periodic health ticks -----------------------------------------------

    def _check_heartbeats(self, now: float) -> None:
        if self.heartbeat_timeout_s is None:
            return
        for w in self.fabric.alive_workers():
            with self.fabric._events_cond:
                age = now - w.last_beat
            if age > self.heartbeat_timeout_s:
                _bump("heartbeat_timeouts")
                self.fabric.mark_lost(w, "heartbeat-timeout")

    def _check_deadlines(self, now: float) -> None:
        for pending in list(self._inflight.values()):
            if pending.id in self._done:
                continue
            if now > pending.deadline:
                _bump("deadline_timeouts")
                self._redispatch(pending, "deadline", set(pending.workers))
            elif (self.hedge_s is not None and not pending.hedged
                  and now - pending.sent_at > self.hedge_s
                  and len(self.fabric.alive_workers()) > 1):
                # the straggler bet: one duplicate on a second worker,
                # whichever replies first wins — not a failure, so it
                # does NOT count as a re-dispatched chunk
                pending.hedged = True
                _bump("hedged_tasks")
                tracing.count("dist.hedged_task")
                self._send(pending, self._pick_worker(set(pending.workers)))

    # -- the loop ------------------------------------------------------------

    def run(self) -> List[Tuple[dict, bytes]]:
        _bump("tasks", self._n)
        workers = self.fabric.alive_workers()
        if not workers:
            raise FabricDown("no live workers at dispatch")
        order = sorted(self._inflight.values(), key=lambda p: p.index)
        for pending in order:
            # deterministic initial assignment: chunk i -> worker i mod W
            self._send(pending, workers[pending.index % len(workers)])
        while len(self._results) < self._n:
            ev = self.fabric.next_event(timeout=0.05)
            if ev is not None:
                if ev.kind == "reply":
                    self._on_reply(ev)
                elif ev.kind == "lost":
                    self._on_lost(ev)
                # hello frames are lifecycle noise at this layer
            now = time.monotonic()
            self._check_heartbeats(now)
            self._check_deadlines(now)
        return [self._results[i] for i in range(self._n)]


def run_tasks(fabric: Fabric, tasks: List[TaskSpec],
              deadline_s: float = 30.0, hedge_s: Optional[float] = None,
              max_attempts: int = 4,
              heartbeat_timeout_s: Optional[float] = None
              ) -> List[Tuple[dict, bytes]]:
    """Drive ``tasks`` to completion over ``fabric``; returns one
    ``(meta, body)`` per task IN TASK ORDER.  Raises ``FabricDown`` when
    the batch cannot complete (the executor ladder's cue)."""
    return _DispatchRun(fabric, tasks, deadline_s, hedge_s, max_attempts,
                        heartbeat_timeout_s).run()


# -- the degradation ladder ----------------------------------------------------

_DEGRADE_WARNED = False


class FabricExecutor:
    """Run work on the fabric with the in-process twin as the floor.

    ``run(fabric_fn, inprocess_fn)`` returns ``(value, mode)`` where mode
    is ``"fabric"`` or ``"inprocess"`` — the caller's result is the same
    either way (bit-identical twins), only the execution domain moves.
    Serving NEVER halts on a fabric failure."""

    def __init__(self, fabric: Fabric):
        self.fabric = fabric
        self._breaker = {"consecutive_errors": 0, "open": False,
                         "since_skipped": 0}

    def run(self, fabric_fn: Callable[[Fabric], object],
            inprocess_fn: Callable[[], object]) -> Tuple[object, str]:
        if self._allows_attempt():
            try:
                # recovery probes re-enter here after the breaker opened
                # on dead workers: respawn empty slots first so the probe
                # tests a repaired fabric, not the corpse that tripped it
                if not self.fabric.ensure_workers():
                    raise FabricUnavailable("no workers after respawn")
                value = fabric_fn(self.fabric)
            except Exception as exc:
                self._note_error(exc)
            else:
                self._note_success()
                _bump("fabric_runs")
                return value, "fabric"
        _bump("fallback_runs")
        tracing.count("dist.fallback_run")
        return inprocess_fn(), "inprocess"

    # breaker mechanics: stf/engine.py's shape, per-executor state,
    # module-level counters
    def _allows_attempt(self) -> bool:
        if not self._breaker["open"]:
            return True
        self._breaker["since_skipped"] += 1
        if self._breaker["since_skipped"] % BREAKER_PROBE_INTERVAL == 0:
            _bump("breaker_probes")
            tracing.count("dist.breaker_probe")
            recorder.record("dist_breaker_probe")
            return True
        return False

    def _note_success(self) -> None:
        self._breaker["consecutive_errors"] = 0
        if self._breaker["open"]:
            self._breaker["open"] = False
            self._breaker["since_skipped"] = 0
            _bump("recoveries")
            with _STATS_LOCK:
                stats["breaker_state"] = "closed"
            tracing.count("dist.breaker_closed")
            recorder.record("dist_breaker_close")

    def _note_error(self, exc: BaseException) -> None:
        global _DEGRADE_WARNED
        tracing.count("dist.fabric_error")
        recorder.record("dist_fabric_degraded",
                        error=f"{type(exc).__name__}: {exc}"[:300])
        if not _DEGRADE_WARNED:
            _DEGRADE_WARNED = True
            warnings.warn(
                "dist fabric degraded to in-process execution: "
                f"{type(exc).__name__}: {exc}", RuntimeWarning,
                stacklevel=3)
        self._breaker["consecutive_errors"] += 1
        if self._breaker["open"]:
            self._breaker["since_skipped"] = 0
            recorder.record("dist_breaker_probe_failed")
            return
        if self._breaker["consecutive_errors"] >= BREAKER_THRESHOLD:
            self._breaker["open"] = True
            self._breaker["since_skipped"] = 0
            _bump("breaker_trips")
            with _STATS_LOCK:
                stats["breaker_state"] = "open"
            tracing.count("dist.breaker_tripped")
            recorder.record(
                "dist_breaker_open",
                consecutive_errors=self._breaker["consecutive_errors"])

    @property
    def breaker_open(self) -> bool:
        return self._breaker["open"]


def snapshot() -> dict:
    """Dispatch counters (telemetry bus)."""
    with _STATS_LOCK:
        return dict(stats)


telemetry.register_provider("dist.dispatch", snapshot, replace=True)
