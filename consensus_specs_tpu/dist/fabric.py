"""Worker lifecycle for the dist fabric (ISSUE 20): spawn, channel
threads, heartbeat bookkeeping, loss detection.

A ``Fabric`` owns N worker subprocesses (``dist/worker.py``), each with
two coordinator-side daemon threads:

* a **sender** (``WorkerHandle._send_loop``) draining that worker's
  outbound queue onto its stdin — dispatch never blocks on a full pipe;
* a **reader** (``Fabric._read_loop``) pulling digest-framed messages off
  its stdout: heartbeats update the worker's liveness stamp, replies land
  on the fabric-wide event queue, and ANY channel damage (EOF, torn
  frame, digest mismatch) marks the worker lost — a detected miss the
  dispatcher re-routes around, never garbage.

Fault seams (coordinator-side, ``proc0`` under an active fabric scope):

* ``dist.spawn``     — before each worker launch (error = spawn failure:
  the fabric continues on survivors, or reports itself down);
* ``dist.reply``     — a value probe over a reply frame's raw envelope
  bytes (corrupt = wire bit-rot: the digest check catches it and the
  worker is demoted to lost);
* ``dist.heartbeat`` — before a received beat lands (error = the beat is
  dropped, so a sticky rule starves liveness past the deadline — the
  heartbeat-timeout chaos model).

The active fault plan ships to every worker via ``CSTPU_FAULTS`` in the
spawn env, and ``CSTPU_DIST_PROC`` gives each process its scope — so one
schedule string drives coordinated cross-process chaos
(``site@nth=kind@procK``, faults.py).

While a fabric is alive the coordinator wears scope ``proc0``
(``faults.set_process_scope``); ``close()`` restores None so unscoped
test plans behave identically outside fabric extents.
"""
from __future__ import annotations

import collections
import os
import subprocess
import sys
import threading
import time
from typing import List, NamedTuple, Optional

from consensus_specs_tpu import faults, telemetry
from consensus_specs_tpu.dist import codec
from consensus_specs_tpu.persist import atomic

_SITE_SPAWN = faults.site("dist.spawn")
_SITE_REPLY = faults.site("dist.reply")
_SITE_HEARTBEAT = faults.site("dist.heartbeat")

DEFAULT_HEARTBEAT_S = 0.25

stats = {
    "spawned": 0,
    "spawn_failures": 0,
    "respawns": 0,
    "frames_sent": 0,
    "frames_received": 0,
    "heartbeats": 0,
    "heartbeats_dropped": 0,
    "corrupt_replies": 0,
    "channel_losses": 0,   # EOF / torn frame / send failure
    "workers_lost": 0,
}

# module-wide counters mutated from sender/reader threads and snapshotted
# by the telemetry bus from arbitrary threads — same discipline as
# node/ingest.py's _STATS_LOCK
_STATS_LOCK = threading.Lock()


def reset_stats() -> None:
    with _STATS_LOCK:
        for k in stats:
            stats[k] = 0


def _bump(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        stats[key] += n


class Event(NamedTuple):
    """One item on the fabric event queue: ``kind`` is ``"hello"`` /
    ``"reply"`` / ``"lost"``, ``proc`` the worker's scope name."""

    kind: str
    proc: str
    meta: dict
    body: bytes


class FabricUnavailable(RuntimeError):
    """No live workers: the caller's ladder demotes to in-process."""


class WorkerHandle:
    """One worker subprocess + its coordinator-side channel state.

    ``last_beat`` and ``alive`` are written by the reader thread and read
    by the dispatch loop — every touch under the owning fabric's event
    condition (the one lock that orders loss against replies)."""

    def __init__(self, index: int, fabric: "Fabric"):
        self.index = index
        self.name = f"proc{index}"
        self._fabric = fabric
        self.popen: Optional[subprocess.Popen] = None
        self.alive = False
        self.last_beat = 0.0
        self.tasks_done = 0
        # outbound frame queue, drained by the sender thread; None is the
        # shutdown sentinel
        self._outbound: collections.deque = collections.deque()
        self._out_cond = threading.Condition()
        self._sender: Optional[threading.Thread] = None
        self._reader: Optional[threading.Thread] = None

    def send(self, kind: str, meta: dict, body: bytes = b"") -> None:
        """Queue one frame for this worker (non-blocking; the sender
        thread owns the actual pipe write).  Raises on a dead worker so
        the dispatcher re-routes immediately instead of queuing into a
        void."""
        with self._fabric._events_cond:
            ok = self.alive
        if not ok:
            raise FabricUnavailable(f"{self.name} is not alive")
        with self._out_cond:
            self._outbound.append((kind, meta, body))
            self._out_cond.notify_all()

    def _send_loop(self, popen, outbound) -> None:
        """Sender thread: outbound queue -> worker stdin.  A write
        failure is a channel loss (the worker died mid-read); the fabric
        re-routes its chunks.  ``popen``/``outbound`` are THIS
        incarnation's — a respawn replaces both, so a stale sender can
        neither steal the new incarnation's frames nor demote it."""
        while True:
            with self._out_cond:
                while not outbound:
                    self._out_cond.wait()
                item = outbound.popleft()
            if item is None:
                return
            kind, meta, body = item
            try:
                codec.write_frame(popen.stdin, kind, meta, body)
            except Exception:
                if self._fabric.mark_lost(self, "send", popen=popen):
                    _bump("channel_losses")
                return
            _bump("frames_sent")

    def _stop_sender(self) -> None:
        with self._out_cond:
            self._outbound.append(None)
            self._out_cond.notify_all()

    def _reset_outbound(self) -> None:
        """New incarnation: retire the previous sender (if any) and
        install a fresh outbound queue — undelivered frames belonged to
        a dead process, the dispatcher re-routes them."""
        if self._sender is not None and self._sender.is_alive():
            self._stop_sender()
        with self._out_cond:
            self._outbound = collections.deque()

    def _start_sender(self, popen) -> None:
        self._sender = threading.Thread(
            target=self._send_loop, args=(popen, self._outbound),
            name=f"dist-sender-{self.name}", daemon=True)
        self._sender.start()


class Fabric:
    """N supervised worker subprocesses behind one event queue."""

    def __init__(self, n_workers: int = 2,
                 heartbeat_interval: float = DEFAULT_HEARTBEAT_S,
                 env: Optional[dict] = None):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.n_workers = n_workers
        self.heartbeat_interval = heartbeat_interval
        self._env_extra = dict(env or {})
        self._workers: List[WorkerHandle] = [
            WorkerHandle(i + 1, self) for i in range(n_workers)]
        # the fabric-wide event queue: reader threads append, the
        # dispatch loop pops; worker alive/last_beat ride the same lock
        self._events: collections.deque = collections.deque()
        self._events_cond = threading.Condition()
        self._started = False
        self._outer_scope: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Fabric":
        """Spawn every worker slot.  Spawn failures leave slots dead (the
        fabric runs on survivors); ZERO survivors raises
        ``FabricUnavailable`` — the caller's ladder takes over."""
        self._outer_scope = faults.process_scope()
        faults.set_process_scope("proc0")
        self._started = True
        for w in self._workers:
            self._spawn(w)
        if not self.alive_workers():
            # leave scope armed for ensure_workers() respawn probes; the
            # caller decides whether to close() or retry
            raise FabricUnavailable(
                f"0 of {self.n_workers} workers spawned")
        return self

    def __enter__(self) -> "Fabric":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def ensure_workers(self) -> int:
        """Respawn dead slots (recovery probes re-enter here after a
        breaker trip); returns the live count."""
        for w in self._workers:
            with self._events_cond:
                ok = w.alive
            if not ok:
                if self._spawn(w):
                    _bump("respawns")
        return len(self.alive_workers())

    def _spawn(self, w: WorkerHandle) -> bool:
        try:
            _SITE_SPAWN()
            popen = subprocess.Popen(
                [sys.executable, "-m", "consensus_specs_tpu.dist.worker"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                env=self._worker_env(w))
        except (faults.InjectedFault, OSError) as exc:
            _bump("spawn_failures")
            telemetry.recorder.record(
                "dist_spawn_failed", proc=w.name,
                error=f"{type(exc).__name__}: {exc}"[:200])
            return False
        w._reset_outbound()
        with self._events_cond:
            w.popen = popen
            w.alive = True
            w.last_beat = time.monotonic()
        _bump("spawned")
        w._start_sender(popen)
        w._reader = threading.Thread(
            target=self._read_loop, args=(w, popen),
            name=f"dist-reader-{w.name}", daemon=True)
        w._reader.start()
        return True

    def _worker_env(self, w: WorkerHandle) -> dict:
        """The worker's env: process scope, the ACTIVE fault plan (scoped
        chaos crosses the boundary verbatim), CPU-pinned jax, and the
        repo on PYTHONPATH so ``-m`` resolves from any cwd."""
        env = dict(os.environ)
        env.update(self._env_extra)
        env["CSTPU_DIST_PROC"] = w.name
        env["CSTPU_DIST_HEARTBEAT_S"] = str(self.heartbeat_interval)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)  # dryrun discipline: no tunnel waits
        plan = faults.active_plan()
        if plan is not None:
            env["CSTPU_FAULTS"] = faults.plan_to_env(plan)
        else:
            env.pop("CSTPU_FAULTS", None)
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        return env

    def close(self) -> None:
        """Shut every worker down (best-effort shutdown frame, then kill)
        and restore the process scope the fabric found."""
        for w in self._workers:
            try:
                w.send("shutdown", {})
            except FabricUnavailable:
                pass
            w._stop_sender()
        # a clean shutdown is not a loss: demote every slot BEFORE the
        # workers exit, so a reader seeing the shutdown EOF finds the
        # slot already dead and mark_lost stays a no-op (otherwise every
        # close() would count phantom workers_lost/channel_losses)
        with self._events_cond:
            for w in self._workers:
                w.alive = False
        deadline = time.monotonic() + 2.0
        for w in self._workers:
            if w.popen is not None:
                try:
                    w.popen.wait(timeout=max(0.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    w.popen.kill()
                    w.popen.wait()
        if self._started:
            faults.set_process_scope(self._outer_scope)
            self._started = False

    # -- channel supervision -------------------------------------------------

    def mark_lost(self, w: WorkerHandle, reason: str,
                  popen=None) -> bool:
        """Demote a worker to lost (idempotent) and wake the dispatch
        loop with a ``lost`` event: its in-flight chunks re-dispatch to
        survivors.  The process is killed — a half-dead worker must not
        keep writing frames.  Channel threads pass the ``popen`` they
        were serving: a stale thread reporting EOF on a RETIRED
        incarnation must not demote the respawned one.  Returns True
        only on the live->lost transition (callers count channel losses
        off it, so a clean-shutdown EOF is not a phantom loss)."""
        with self._events_cond:
            if popen is not None and w.popen is not popen:
                return False  # a previous incarnation's thread winding down
            if not w.alive:
                return False
            w.alive = False
            self._events.append(Event("lost", w.name, {"reason": reason}, b""))
            self._events_cond.notify_all()
        _bump("workers_lost")
        telemetry.recorder.record("dist_worker_lost", proc=w.name,
                                  reason=reason)
        if w.popen is not None:
            try:
                w.popen.kill()
            except OSError:
                pass
        return True

    def _read_loop(self, w: WorkerHandle, popen) -> None:
        """Reader thread: worker stdout -> event queue.  EOF, torn
        frames, and digest mismatches all land in the same place: the
        worker is lost, never a source of garbage.  Bound to ONE
        incarnation (``popen``) so a retired reader's EOF cannot demote
        a respawned worker."""
        stream = popen.stdout
        while True:
            try:
                env = codec.read_envelope(stream)
            except atomic.ArtifactError:
                if self.mark_lost(w, "torn-frame", popen=popen):
                    _bump("channel_losses")
                return
            if env is None:
                if self.mark_lost(w, "eof", popen=popen):
                    _bump("channel_losses")
                return
            try:
                kind, meta, body = codec.parse_envelope(env)
                if kind == "reply" and faults.active_plan() is not None:
                    # the wire-damage probe: under an armed plan, route
                    # the raw envelope through dist.reply so a `corrupt`
                    # rule flips a byte the way bit rot would — then the
                    # digest check decides, exactly like persist.read
                    kind, meta, body = codec.parse_envelope(_SITE_REPLY(env))
            except (faults.InjectedFault, atomic.ArtifactError):
                _bump("corrupt_replies")
                self.mark_lost(w, "corrupt-reply", popen=popen)
                return
            _bump("frames_received")
            if kind == "heartbeat":
                try:
                    _SITE_HEARTBEAT()
                except faults.InjectedFault:
                    _bump("heartbeats_dropped")
                    continue
                with self._events_cond:
                    w.last_beat = time.monotonic()
                _bump("heartbeats")
                continue
            with self._events_cond:
                self._events.append(Event(kind, w.name, meta, body))
                self._events_cond.notify_all()

    # -- the dispatch loop's surface -----------------------------------------

    def alive_workers(self) -> List[WorkerHandle]:
        with self._events_cond:
            return [w for w in self._workers if w.alive]

    def worker(self, proc: str) -> Optional[WorkerHandle]:
        for w in self._workers:
            if w.name == proc:
                return w
        return None

    def next_event(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Pop the oldest event, waiting up to ``timeout``; None on
        timeout (the dispatch loop's health-check tick)."""
        with self._events_cond:
            deadline = (None if timeout is None
                        else time.monotonic() + timeout)
            while not self._events:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return None
                self._events_cond.wait(remaining)
            return self._events.popleft()


def snapshot() -> dict:
    """Fabric channel counters (telemetry bus)."""
    with _STATS_LOCK:
        return dict(stats)


telemetry.register_provider("dist.fabric", snapshot, replace=True)
