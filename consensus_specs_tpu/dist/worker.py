"""Dist worker subprocess body (``python -m consensus_specs_tpu.dist.worker``).

One worker = one process = one failure domain.  The coordinator spawns it
with ``CSTPU_DIST_PROC=procK`` (which ``faults.py`` reads at import, so a
scoped chaos plan shipped via ``CSTPU_FAULTS`` arms ONLY the faults
addressed to this process) and talks to it over stdin/stdout with the
``dist/codec.py`` digest-framed protocol:

* inbound  — ``task`` frames (execute, reply), ``shutdown`` (exit 0);
* outbound — one ``hello`` at startup, ``heartbeat`` frames from a side
  thread every ``CSTPU_DIST_HEARTBEAT_S`` seconds, and one ``reply`` per
  task.  All outbound frames serialize on ``_WRITE_LOCK`` so a beat can
  never tear a reply mid-frame.

Task handlers import their engines LAZILY per task kind: a worker that
only ever echoes (the chaos suites) never pays the jax/crypto import
bill, and a verify worker imports exactly the verification stack the
in-process path uses — which is what makes the results bit-identical.

Failure semantics at the ``dist.worker.exec`` probe:

* ``error``  (``InjectedFault``) — the task failed but the process is
  healthy: an ``ok=False`` reply goes back and the coordinator
  re-dispatches the chunk elsewhere;
* ``crash`` (``InjectedBackendCrash``) — the PROCESS dies mid-chunk
  (``os._exit``): no reply, the channel EOFs, and the coordinator's
  loss path takes over.  This is the "kill a worker mid-chunk" model
  the chaos suite drives.

``print()`` output from task code is repointed at stderr before the
first frame: stdout belongs to the frame stream alone.
"""
from __future__ import annotations

import hashlib
import os
import sys
import threading
import time

from consensus_specs_tpu import faults
from consensus_specs_tpu.dist import codec

# the worker-side execution seam: probed once per task, BEFORE the
# handler runs, so an injected death really is mid-chunk (the chunk is
# in flight, unreplied)
_SITE_EXEC = faults.site("dist.worker.exec")

PROC = os.environ.get("CSTPU_DIST_PROC", "proc?")

# the coordinator-facing frame stream (bound in serve()); every write —
# replies from the main loop, beats from the heartbeat thread — holds
# _WRITE_LOCK so frames never interleave
_OUT = None
_WRITE_LOCK = threading.Lock()


def _send(kind: str, meta: dict, body: bytes = b"") -> None:
    with _WRITE_LOCK:
        codec.write_frame(_OUT, kind, meta, body)


def _heartbeat_loop(interval: float, stop: threading.Event) -> None:
    """Liveness beacon: one ``heartbeat`` frame per interval until told to
    stop.  A write failure means the coordinator is gone — the main loop
    will see EOF on stdin and exit; the beacon just goes quiet."""
    seq = 0
    while not stop.wait(interval):
        seq += 1
        try:
            _send("heartbeat", {"proc": PROC, "seq": seq})
        except Exception:
            return


def run_task(kind: str, meta: dict, body: bytes):
    """Execute one task chunk; returns ``(meta, body)`` for the reply.
    Handlers are pure functions of the chunk body — any worker can run
    any chunk, which is what makes re-dispatch sound."""
    _SITE_EXEC()
    if kind == "echo":
        # cheap deterministic kind for fabric/chaos tests: digest + body
        return {"ok": True}, hashlib.sha256(body).digest() + body
    if kind == "sleep_echo":
        # straggler/kill-window model: hold the chunk in flight for a
        # while, then echo — gives heartbeat timeouts a surface
        time.sleep(float(meta.get("seconds", 0.5)))
        return {"ok": True}, hashlib.sha256(body).digest() + body
    if kind == "verify_chunk":
        return _run_verify_chunk(body)
    if kind == "pairing_partial":
        return _run_pairing_partial(body)
    if kind == "epoch_slice":
        return _run_epoch_slice(body)
    if kind == "merkle_subtree":
        return _run_merkle_subtree(body)
    raise ValueError(f"unknown task kind {kind!r}")


def _run_verify_chunk(body: bytes):
    """Leftmost-failure verify of one entry chunk THROUGH the same
    ``stf/verify.py`` path the in-process run uses: the chunk-local
    ``first_invalid`` index composes with the coordinator's min-merge
    into the exact global index the unchunked bisection names."""
    import pickle

    from consensus_specs_tpu.stf import verify as stf_verify

    payload = pickle.loads(body)
    first = stf_verify.first_invalid(payload["entries"],
                                     seed=payload["seed"])
    return {"ok": True}, pickle.dumps({"first": first})


def _run_pairing_partial(body: bytes):
    """One chunk's partial Miller product (conjugated), the unit
    ``parallel/bls_sharded.py`` merges in fixed chunk-index order.
    Integer limb arithmetic: exact, so the partial is bit-identical to
    the in-process chunk no matter which worker computes it."""
    import pickle

    import numpy as np

    d = pickle.loads(body)
    fn = _pairing_partial_fn()
    f = fn(d["px"], d["py"], d["qx"], d["qy"])
    return {"ok": True}, pickle.dumps(np.asarray(f))


_PAIRING_FN = None


def _pairing_partial_fn():
    global _PAIRING_FN
    if _PAIRING_FN is None:
        import jax

        from consensus_specs_tpu.ops.bls_jax import pairing

        _PAIRING_FN = jax.jit(pairing._miller_product)
    return _PAIRING_FN


def _run_epoch_slice(body: bytes):
    """One registry slice of the epoch balance update: the worker runs
    the SAME single-device kernel the dryrun cross-checks against
    (``ops/epoch_jax.attestation_deltas``) and returns its [lo, hi) rows.
    The global reductions (total balance, sqrt) arrive precomputed inside
    ``DeltaInputs`` — the data-parallel psum's replicated scalars, worn
    process-side."""
    import pickle

    import numpy as np

    from consensus_specs_tpu.ops.epoch_jax import DeltaInputs

    d = pickle.loads(body)
    from consensus_specs_tpu.ops.epoch_jax import attestation_deltas

    inp = DeltaInputs(**d["inp"])
    rewards, penalties = attestation_deltas(inp)
    new = d["balances"] + np.asarray(rewards)
    pen = np.asarray(penalties)
    new = np.where(pen > new, 0, new - pen)
    lo, hi = d["lo"], d["hi"]
    return {"ok": True}, pickle.dumps(np.asarray(new[lo:hi]))


def _run_merkle_subtree(body: bytes):
    """Subtree root of one packed-uint64 chunk — the per-shard unit of
    ``parallel/merkle_sharded.py``'s list merkleization, computed with
    the plain bottom-up sha256 reduction (bit-identical to the device
    kernel's subtree by SSZ construction)."""
    import pickle

    d = pickle.loads(body)
    lanes = d["lanes"]
    data = b"".join(int(v).to_bytes(8, "little") for v in lanes)
    nodes = [data[i:i + 32] for i in range(0, len(data), 32)]
    while len(nodes) > 1:
        nodes = [hashlib.sha256(nodes[i] + nodes[i + 1]).digest()
                 for i in range(0, len(nodes), 2)]
    return {"ok": True}, nodes[0]


def serve() -> None:
    """The worker main loop: hello, heartbeats, then task frames until
    shutdown/EOF.  A corrupt inbound frame is unrecoverable (the length
    framing has lost sync): exit nonzero, which the coordinator reads as
    a channel loss and re-dispatches around."""
    global _OUT
    stdin = sys.stdin.buffer
    _OUT = sys.stdout.buffer
    sys.stdout = sys.stderr  # task-code print() must not tear the frames

    interval = float(os.environ.get("CSTPU_DIST_HEARTBEAT_S", "0.25"))
    stop = threading.Event()
    _send("hello", {"proc": PROC, "pid": os.getpid()})
    beacon = threading.Thread(target=_heartbeat_loop, args=(interval, stop),
                              name=f"dist-heartbeat-{PROC}", daemon=True)
    beacon.start()
    try:
        while True:
            try:
                frame = codec.read_frame(stdin)
            except Exception:
                sys.exit(4)  # lost frame sync: die loudly, not garbled
            if frame is None:
                break  # coordinator closed the channel: end of stream
            kind, meta, body = frame
            if kind == "shutdown":
                break
            if kind != "task":
                continue  # unknown control frames: forward-compatible skip
            try:
                out_meta, out_body = run_task(meta["kind"], meta, body)
            except faults.InjectedBackendCrash:
                os._exit(13)  # injected process death: mid-chunk, no reply
            except BaseException as exc:
                out_meta, out_body = (
                    {"ok": False, "error": repr(exc)[:300]}, b"")
            out_meta = dict(out_meta, id=meta["id"], proc=PROC,
                            kind=meta["kind"])
            try:
                _send("reply", out_meta, out_body)
            except Exception:
                break  # coordinator gone mid-reply
    finally:
        stop.set()


if __name__ == "__main__":
    serve()
